"""Fused sparse softmax cross-entropy (Pallas TPU), fwd + custom VJP.

Replaces the reference's two-kernel softmax→xent chain
(ref: tensorflow/core/kernels/xent_op.cc, softmax_op.cc). For LM/BERT-size
vocabularies the [batch, vocab] logits tensor dominates HBM traffic; this
kernel streams each row once, vocab-block by vocab-block, maintaining the
online-softmax running (max, sumexp) plus the label logit, so VMEM holds
only a (block_rows, block_vocab) tile regardless of vocabulary size (a
full-row tile at 128×30522×f32 double-buffered is 30 MB — twice the 16 MB
scoped-VMEM budget). The backward emits (softmax - onehot) * g blockwise
from the saved logsumexp without re-reading intermediates.

logits: (rows, vocab) any float dtype; labels: (rows,) int32 (carried as
(rows, 1) tiles — Mosaic-legal shapes). Returns per-row loss, f32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import NEG_INF, cdiv, pad_dim, round_up, use_interpret

DEFAULT_BLOCK_ROWS = 256
DEFAULT_BLOCK_VOCAB = 2048


def _fwd_kernel(vocab, n_vblocks, smoothing, logits_ref, labels_ref,
                loss_ref, lse_ref, m_ref, s_ref, ll_ref, sx_ref):
    j = pl.program_id(1)
    x = logits_ref[:].astype(jnp.float32)           # (br, bv)
    labels = labels_ref[:]                          # (br, 1)
    bv = x.shape[1]
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    valid = cols < vocab
    x = jnp.where(valid, x, NEG_INF)                # mask the ragged edge

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full(m_ref.shape, NEG_INF, jnp.float32)
        s_ref[:] = jnp.zeros(s_ref.shape, jnp.float32)
        ll_ref[:] = jnp.zeros(ll_ref.shape, jnp.float32)
        if smoothing > 0.0:
            sx_ref[:] = jnp.zeros(sx_ref.shape, jnp.float32)

    m_prev = m_ref[:]
    m_blk = jnp.max(x, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_blk)
    s_ref[:] = s_ref[:] * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.exp(x - m_new), axis=-1, keepdims=True)
    m_ref[:] = m_new
    ll_ref[:] = ll_ref[:] + jnp.sum(
        jnp.where(cols == labels, x, 0.0), axis=-1, keepdims=True)
    if smoothing > 0.0:
        sx_ref[:] = sx_ref[:] + jnp.sum(jnp.where(valid, x, 0.0),
                                        axis=-1, keepdims=True)

    @pl.when(j == n_vblocks - 1)
    def _finish():
        lse = m_ref[:] + jnp.log(s_ref[:])
        if smoothing > 0.0:
            # soft targets q = low + (conf - low)*onehot with
            # conf = 1 - smoothing, low = smoothing/(V-1); since sum(q)=1:
            # loss = lse - conf*x_label - low*(sum_x - x_label)
            conf = 1.0 - smoothing
            low = smoothing / (vocab - 1)
            loss_ref[:] = (lse - conf * ll_ref[:]
                           - low * (sx_ref[:] - ll_ref[:]))
        else:
            loss_ref[:] = lse - ll_ref[:]
        lse_ref[:] = lse


def _bwd_kernel(vocab, smoothing, logits_ref, labels_ref, lse_ref, g_ref,
                dx_ref):
    j = pl.program_id(1)
    x = logits_ref[:].astype(jnp.float32)
    labels = labels_ref[:]                          # (br, 1)
    lse = lse_ref[:]                                # (br, 1)
    g = g_ref[:]                                    # (br, 1)
    p = jnp.exp(x - lse)
    bv = x.shape[1]
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == labels).astype(jnp.float32)
    if smoothing > 0.0:
        conf = 1.0 - smoothing
        low = smoothing / (vocab - 1)
        q = low + (conf - low) * onehot             # dL/dx = p - q
    else:
        q = onehot
    dx = jnp.where(cols < vocab, (p - q) * g, 0.0)
    dx_ref[:] = dx.astype(dx_ref.dtype)


def _block_sizes(vocab, block_vocab):
    # No padding: both kernels mask loads past `vocab` (cols < vocab), so a
    # ragged final block is fine and the [rows, vocab] tensor — the whole
    # reason this kernel exists — is never copied just to round its shape.
    bv = min(block_vocab, round_up(vocab, 128))
    return bv, cdiv(vocab, bv)


def _fwd(logits, labels, block_rows, block_vocab, smoothing):
    rows, vocab = logits.shape
    bv, nv = _block_sizes(vocab, block_vocab)
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, vocab, nv, smoothing),
        grid=(cdiv(rows, block_rows), nv),
        in_specs=[
            pl.BlockSpec((block_rows, bv), lambda i, j: (i, j)),
            pl.BlockSpec((block_rows, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_rows, 1), jnp.float32),
            pltpu.VMEM((block_rows, 1), jnp.float32),
            pltpu.VMEM((block_rows, 1), jnp.float32),
            pltpu.VMEM((block_rows, 1), jnp.float32),
        ],
        interpret=use_interpret(),
    )(logits, labels)
    return loss, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _xent_2d(logits, labels, block_rows, block_vocab, smoothing):
    loss, _ = _fwd(logits, labels, block_rows, block_vocab, smoothing)
    return loss


def _xent_fwd_rule(logits, labels, block_rows, block_vocab, smoothing):
    loss, lse = _fwd(logits, labels, block_rows, block_vocab, smoothing)
    return loss, (logits, labels, lse)


def _xent_bwd_rule(block_rows, block_vocab, smoothing, res, g):
    logits, labels, lse = res
    rows, vocab = logits.shape
    bv, nv = _block_sizes(vocab, block_vocab)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, vocab, smoothing),
        grid=(cdiv(rows, block_rows), nv),
        in_specs=[
            pl.BlockSpec((block_rows, bv), lambda i, j: (i, j)),
            pl.BlockSpec((block_rows, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, bv), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((rows, vocab), logits.dtype),
        interpret=use_interpret(),
    )(logits, labels, lse, g)
    return dx, None


_xent_2d.defvjp(_xent_fwd_rule, _xent_bwd_rule)


def softmax_cross_entropy(logits, labels, *, label_smoothing=0.0,
                          block_rows=DEFAULT_BLOCK_ROWS,
                          block_vocab=DEFAULT_BLOCK_VOCAB):
    """Per-example sparse softmax xent. logits: (..., vocab),
    labels: (...,) int. Returns f32 loss of shape (...).

    label_smoothing > 0 trains against soft targets
    q = smoothing/(V-1) + (1 - smoothing - smoothing/(V-1))*onehot, fused
    into the same streamed pass (the composed form materializes log_softmax
    AND a dense one-hot at [rows, vocab] — two extra vocab-sized tensors)."""
    orig = logits.shape
    vocab = orig[-1]
    rows = 1
    for s in orig[:-1]:
        rows *= s
    l2 = logits.reshape(rows, vocab)
    lab = labels.reshape(rows, 1).astype(jnp.int32)
    block_rows = min(block_rows, round_up(rows, 8))
    rp = round_up(rows, block_rows)
    l2 = pad_dim(l2, 0, rp)
    lab = pad_dim(lab, 0, rp)
    loss = _xent_2d(l2, lab, int(block_rows), int(block_vocab),
                    float(label_smoothing))
    return loss[:rows, 0].reshape(orig[:-1])


def softmax_cross_entropy_reference(logits, labels, *, label_smoothing=0.0):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(
        logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    if label_smoothing == 0.0:
        return nll
    vocab = logits.shape[-1]
    conf = 1.0 - label_smoothing
    low = label_smoothing / (vocab - 1)
    return conf * nll - low * (jnp.sum(logp, axis=-1) + nll)
