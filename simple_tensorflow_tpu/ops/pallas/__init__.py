"""Pallas TPU kernels (replaces ref CUDA kernels, core/kernels/*_gpu.cu.cc).

Each kernel is exposed three ways:
- as a jax-level function (used directly by jax-native model code),
- as a registered graph op, so stf graph programs pick up the kernel
  through the normal Session lowering path (`stf.nn.fused_*`), and
- as a (pallas, xla) implementation pair in the stf.kernels registry:
  the graph-op lowerings below consult the registry per (op, shape,
  dtype, backend) and emit either the Pallas kernel or the stock
  composed-XLA lowering (docs/PERFORMANCE.md "kernel tier"). ``off``
  mode reproduces the pre-registry behavior exactly; ``force`` pins
  Pallas (interpret mode off-TPU, so tier-1 CPU tests run the kernels).

All kernels auto-switch to interpret mode off-TPU so the CPU test mesh
exercises identical code paths.
"""

import numpy as np

from ...framework import op_registry
from ...kernels import registry as _kreg
from .decode_attention import decode_attention, decode_attention_xla
from .dropout_residual import (dropout_bias_residual,
                               dropout_bias_residual_reference)
from .flash_attention import attention_xla, flash_attention, mha_reference
from .fused_update import (adam_update, adam_update_reference,
                           momentum_update, momentum_update_reference)
from .layer_norm import layer_norm, layer_norm_reference
from .quant_matmul import (quant_matmul, quant_matmul_reference,
                           quant_matmul_ste, quant_matmul_ste_reference,
                           quantize_colwise, quantize_rowwise)
from .softmax_xent import (softmax_cross_entropy,
                           softmax_cross_entropy_reference)


def _np_of(dt):
    s = str(dt)
    try:
        return np.dtype(s)
    except TypeError:
        import ml_dtypes  # registered by jax; covers bfloat16 etc.

        return np.dtype(getattr(ml_dtypes, s))


def _is_float(dt) -> bool:
    s = str(dt)
    return s.startswith("float") or s.startswith("bfloat")


def _bytes_of(aval_entry):
    shape, dt = aval_entry
    n = 1
    for d in shape:
        n *= int(d)
    return n * _np_of(dt).itemsize


def _rand(shape, dt, seed=0):
    rng = np.random.RandomState(seed)
    d = _np_of(dt)
    if d.kind in "iu":
        return rng.randint(0, 4, size=shape).astype(d)
    return rng.randn(*shape).astype(np.float32).astype(d)


# ---------------------------------------------------------------------------
# FlashAttention (+Dropout): Pallas streamed kernel vs composed matmuls
# ---------------------------------------------------------------------------

def _flash_eligible(key):
    (qs, qd), (ks, _kd), (vs, _vd), bias = key[:4]
    statics = dict(key[4:])
    if not _is_float(qd):
        return "ineligible_dtype"
    if len(qs) != 4 or len(ks) != 4:
        return "ineligible_shape"
    if statics.get("causal") and qs[2] != ks[2]:
        return "ineligible_shape"
    if bias is not None:
        bs, _bd = bias
        # the kernel takes a key bias broadcast over heads/queries:
        # anything not squeezable to (batch, kv_seq) needs the composed
        # path (which handles arbitrary additive biases)
        if len(bs) < 2 or bs[0] != qs[0] or bs[-1] != ks[2] \
                or any(d != 1 for d in bs[1:-1]):
            return "ineligible_bias"
    return None


def _flash_gate(key, bk):
    (qs, qd), (ks, _), (vs, _), bias = key[:4]
    statics = dict(key[4:])
    b, h, sq, d = (int(x) for x in qs)
    sk = int(ks[2])
    flops = 4.0 * b * h * sq * sk * d * (0.5 if statics.get("causal") else 1)
    itm = _np_of(qd).itemsize
    qkv_bytes = (_bytes_of(key[0]) + _bytes_of(key[1]) + _bytes_of(key[2])
                 + b * h * sq * d * itm)
    # the composed path materializes the (B,H,Sq,Sk) f32 score matrix
    # roughly three times (scores, softmax, P·V read) — the exact HBM
    # traffic the streamed kernel exists to avoid
    return _kreg.roofline_gate(flops, qkv_bytes,
                               qkv_bytes + 3.0 * b * h * sq * sk * 4, bk)


def _flash_case(key):
    (qs, qd), (ks, kd), (vs, vd), bias = key[:4]
    statics = dict(key[4:])
    args = [_rand(qs, qd, 0), _rand(ks, kd, 1), _rand(vs, vd, 2)]
    kw = {"causal": bool(statics.get("causal", False))}
    if bias is not None:
        kw["bias"] = _rand(bias[0], bias[1], 3)
    if statics.get("dropout"):
        kw["dropout_rate"] = 0.1
        kw["dropout_seed"] = np.asarray([7], np.int32)
    return tuple(args), kw


_kreg.register_kernel(
    "FlashAttention",
    impls={"pallas": flash_attention, "xla": attention_xla},
    legacy="pallas",
    eligible=_flash_eligible,
    cost_gate=_flash_gate,
    make_case=_flash_case,
    graph_key=lambda op: _flash_graph_key(op),
    doc="streamed FlashAttention-2 kernel vs composed batch-matmul "
        "attention")
_kreg.register_kernel(
    "FlashAttentionDropout",
    impls={"pallas": flash_attention, "xla": attention_xla},
    legacy="pallas",
    eligible=_flash_eligible,
    cost_gate=_flash_gate,
    make_case=_flash_case,
    graph_key=lambda op: _flash_graph_key(op, dropout=True),
    doc="FlashAttention with in-kernel probability dropout (counter-"
        "based mask shared with the composed fallback)")


def _tensor_aval(t):
    sh = t.shape
    if sh.rank is None or any(d.value is None for d in sh.dims):
        return None
    return (tuple(int(d.value) for d in sh.dims), t.dtype.base_dtype.name)


def _flash_graph_key(op, dropout=False):
    avals = [_tensor_aval(t) for t in op.inputs]
    if any(a is None for a in avals[:3]) or len(avals) < 3:
        return None
    bias = avals[3] if len(avals) > 3 else None
    return _kreg.aval_key(
        *[_Aval(*a) for a in avals[:3]],
        *( [_Aval(*bias)] if bias is not None else [None]),
        causal=bool(op.attrs.get("causal", False)), dropout=bool(dropout))


class _Aval:
    """shape/dtype carrier for aval_key from graph tensors."""

    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype):
        self.shape = shape
        self.dtype = dtype


def _flash_key(q, k, v, bias, causal, dropout):
    return _kreg.aval_key(q, k, v, bias, causal=bool(causal),
                          dropout=bool(dropout))


def _lower_flash(ctx, op, input_values):
    q, k, v = input_values[:3]
    bias = input_values[3] if len(input_values) > 3 else None
    causal = op.attrs.get("causal", False)
    sm_scale = op.attrs.get("sm_scale")
    fn = _kreg.select("FlashAttention",
                      _flash_key(q, k, v, bias, causal, False))
    return [fn(q, k, v, bias=bias, causal=causal, sm_scale=sm_scale)]


def _flash_dropout_lower(ctx, op, input_values):
    """FlashAttention with probability dropout: stateful (never CSE'd —
    two dropout sites must draw different masks), seeded from the op's
    per-step RNG stream so fwd and vjp replay the same mask. The op's
    graph/op seed attrs fold into the stream exactly like nn_ops
    dropout (random_seed.fold_in_value), so ``stf.set_random_seed``
    reproduces the mask regardless of op naming — and regardless of
    which implementation the registry picks (both draw the identical
    counter-based mask from the derived seed)."""
    import jax
    import jax.numpy as jnp

    q, k, v = input_values[:3]
    bias = input_values[3] if len(input_values) > 3 else None
    key = ctx.rng_for(op)
    seed = jax.random.randint(key, (1,), 0, jnp.iinfo(jnp.int32).max,
                              dtype=jnp.int32)
    causal = op.attrs.get("causal", False)
    fn = _kreg.select("FlashAttentionDropout",
                      _flash_key(q, k, v, bias, causal, True))
    out = fn(q, k, v, bias=bias, causal=causal,
             sm_scale=op.attrs.get("sm_scale"),
             dropout_rate=float(op.attrs["dropout_rate"]), dropout_seed=seed)
    return [out]


op_registry.register("FlashAttention", lower=_lower_flash)
op_registry.register("FlashAttentionDropout", lower=_flash_dropout_lower,
                     effects=op_registry.Effects(rng=True))


# ---------------------------------------------------------------------------
# FusedLayerNorm: one-pass VMEM kernel vs composed mean/var/normalize
# ---------------------------------------------------------------------------

def _ln_eligible(key):
    (xs, xd), (gs, _), (bs, _) = key[:3]
    if not _is_float(xd):
        return "ineligible_dtype"
    if len(xs) < 1 or len(gs) != 1 or len(bs) != 1 or gs[0] != xs[-1]:
        return "ineligible_shape"
    return None


def _ln_gate(key, bk):
    xb = _bytes_of(key[0])
    n = 1
    for d in key[0][0]:
        n *= int(d)
    # composed LN re-reads x for the mean pass, the variance pass and
    # the normalize/affine pass (pre-fusion accounting); the kernel
    # streams each row block once
    return _kreg.roofline_gate(5.0 * n, 2.0 * xb, 4.0 * xb, bk)


def _ln_case(key):
    (xs, xd), (gs, gd), (bs, bd) = key[:3]
    return ((_rand(xs, xd, 0), _rand(gs, gd, 1), _rand(bs, bd, 2)), {})


_kreg.register_kernel(
    "FusedLayerNorm",
    impls={"pallas": layer_norm, "xla": layer_norm_reference},
    legacy="pallas",
    eligible=_ln_eligible,
    cost_gate=_ln_gate,
    make_case=_ln_case,
    graph_key=lambda op: _simple_graph_key(op),
    doc="one-pass fused layer norm vs composed mean/var/normalize")


def _simple_graph_key(op, **statics):
    avals = [_tensor_aval(t) for t in op.inputs]
    if any(a is None for a in avals):
        return None
    return _kreg.aval_key(*[_Aval(*a) for a in avals], **statics)


def _lower_fused_layer_norm(ctx, op, inputs):
    x, gamma, beta = inputs
    eps = float(op.attrs.get("eps", 1e-6))
    fn = _kreg.select("FusedLayerNorm", _kreg.aval_key(x, gamma, beta))
    return [fn(x, gamma, beta, eps=eps)]


op_registry.register("FusedLayerNorm", lower=_lower_fused_layer_norm)


# ---------------------------------------------------------------------------
# FusedSoftmaxXent: streamed online-softmax xent vs composed log_softmax
# ---------------------------------------------------------------------------

def _xent_eligible(key):
    (ls, ld), (labs, labd) = key[:2]
    if not _is_float(ld) or _np_of(labd).kind not in "iu":
        return "ineligible_dtype"
    if len(ls) < 1 or len(labs) != len(ls) - 1:
        return "ineligible_shape"
    return None


def _xent_gate(key, bk):
    lb = _bytes_of(key[0])
    n = 1
    for d in key[0][0]:
        n *= int(d)
    # composed materializes log_softmax at [rows, vocab] f32 (plus the
    # max/sum passes); the kernel streams each row's vocab blocks once
    return _kreg.roofline_gate(5.0 * n, 1.2 * lb, 3.0 * lb, bk)


def _xent_case(key):
    (ls, ld), (labs, labd) = key[:2]
    statics = dict(key[2:])
    logits = _rand(ls, ld, 0)
    labels = np.random.RandomState(1).randint(
        0, ls[-1], size=labs).astype(_np_of(labd))
    return ((logits, labels),
            {"label_smoothing": 0.1 if statics.get("label_smoothing")
             else 0.0})


_kreg.register_kernel(
    "FusedSoftmaxXent",
    impls={"pallas": softmax_cross_entropy,
           "xla": softmax_cross_entropy_reference},
    legacy="pallas",
    eligible=_xent_eligible,
    cost_gate=_xent_gate,
    make_case=_xent_case,
    graph_key=lambda op: _simple_graph_key(op),
    doc="streamed sparse softmax-xent vs composed log_softmax + gather")


def _lower_fused_xent(ctx, op, inputs):
    logits, labels = inputs
    sm = float(op.attrs.get("label_smoothing", 0.0))
    fn = _kreg.select(
        "FusedSoftmaxXent",
        _kreg.aval_key(logits, labels, label_smoothing=sm > 0.0))
    return [fn(logits, labels, label_smoothing=sm)]


op_registry.register("FusedSoftmaxXent", lower=_lower_fused_xent)


# ---------------------------------------------------------------------------
# QuantMatMul: native int8 MXU kernel vs int32 jnp dot
# ---------------------------------------------------------------------------

def _qmm_eligible(key):
    (xs, xd), (ws, wd), (ss, _sd) = key[:3]
    if not _is_float(xd) or str(wd) != "int8":
        return "ineligible_dtype"
    if len(xs) != 2 or len(ws) != 2 or len(ss) != 1:
        return "ineligible_shape"
    return None


def _qmm_gate(key, bk):
    if bk != "tpu":
        return ("xla", "interpret_backend")
    # the MXU multiplies int8 natively at 2x the bf16 rate; XLA lowers
    # the int32 jnp.dot off that fast path — the kernel wins whenever
    # the matmul is big enough to be MXU-bound at all
    (xs, _), (ws, _), _ = key[:3]
    m, k = int(xs[0]), int(xs[1])
    n = int(ws[1])
    if 2.0 * m * k * n >= 1e8:
        return ("pallas", "cost_model")
    return (None, "cost_model_uncertain")


def _qmm_case(key):
    (xs, xd), (ws, wd), (ss, sd) = key[:3]
    rng = np.random.RandomState(0)
    x = rng.randn(*xs).astype(_np_of(xd))
    wq = rng.randint(-127, 128, size=ws).astype(np.int8)
    scale = (rng.rand(*ss).astype(np.float32) * 0.1 + 0.01)
    return ((x, wq, scale), {})


_kreg.register_kernel(
    "QuantMatMul",
    impls={"pallas": quant_matmul_ste, "xla": quant_matmul_ste_reference},
    legacy="pallas",
    eligible=_qmm_eligible,
    cost_gate=_qmm_gate,
    make_case=_qmm_case,
    graph_key=lambda op: _simple_graph_key(op),
    doc="int8 MXU quantized matmul (straight-through vjp) vs int32 dot")


def _lower_quant_matmul(ctx, op, inputs):
    x, wq, w_scale = inputs
    fn = _kreg.select("QuantMatMul", _kreg.aval_key(x, wq, w_scale))
    return [fn(x, wq, w_scale)]


op_registry.register("QuantMatMul", lower=_lower_quant_matmul)


# ---------------------------------------------------------------------------
# FusedDropoutBiasResidual: blocked elementwise kernel vs fused XLA chain.
# XLA fuses a pure elementwise chain into one pass itself, so the static
# gate prefers the composed lowering; the kernel is there for ``force``
# (testability) and for measured wins via the autotune cache.
# ---------------------------------------------------------------------------

def _dbr_eligible(key):
    (xs, xd), (rs, _rd), bias = key[:3]
    if not _is_float(xd):
        return "ineligible_dtype"
    if tuple(xs) != tuple(rs) or len(xs) < 1:
        return "ineligible_shape"
    if bias is not None and (len(bias[0]) != 1 or bias[0][0] != xs[-1]):
        return "ineligible_shape"
    return None


def _dbr_gate(key, bk):
    if bk != "tpu":
        return ("xla", "interpret_backend")
    # elementwise: both lowerings are one HBM pass (XLA fuses the
    # composed chain); nothing for the kernel to win statically
    return ("xla", "cost_model")


def _dbr_case(key):
    (xs, xd), (rs, rd), bias = key[:3]
    statics = dict(key[3:])
    args = [_rand(xs, xd, 0), _rand(rs, rd, 1)]
    kw = {"rate": float(statics.get("rate", 0.1)),
          "seed": np.asarray([5], np.int32)}
    if bias is not None:
        kw["bias"] = _rand(bias[0], bias[1], 2)
    return tuple(args), kw


def _dbr_pallas(x, residual, bias=None, *, rate, seed):
    return dropout_bias_residual(x, residual, bias, rate=rate, seed=seed)


def _dbr_xla(x, residual, bias=None, *, rate, seed):
    return dropout_bias_residual_reference(x, residual, bias, rate=rate,
                                           seed=seed)


_kreg.register_kernel(
    "FusedDropoutBiasResidual",
    impls={"pallas": _dbr_pallas, "xla": _dbr_xla},
    legacy="xla",
    eligible=_dbr_eligible,
    cost_gate=_dbr_gate,
    make_case=_dbr_case,
    graph_key=lambda op: _dbr_graph_key(op),
    doc="fused residual + dropout(x + bias) vs composed elementwise "
        "chain (identical counter-based mask)")


def _dbr_graph_key(op):
    avals = [_tensor_aval(t) for t in op.inputs]
    if len(avals) < 2 or any(a is None for a in avals):
        return None
    bias = avals[2] if len(avals) > 2 else None
    return _kreg.aval_key(_Aval(*avals[0]), _Aval(*avals[1]),
                          _Aval(*bias) if bias is not None else None,
                          rate=float(op.attrs.get("rate", 0.0)))


def _lower_dropout_bias_residual(ctx, op, inputs):
    import jax
    import jax.numpy as jnp

    x, residual = inputs[:2]
    bias = inputs[2] if len(inputs) > 2 else None
    rate = float(op.attrs["rate"])
    key = ctx.rng_for(op)
    seed = jax.random.randint(key, (1,), 0, jnp.iinfo(jnp.int32).max,
                              dtype=jnp.int32)
    fn = _kreg.select(
        "FusedDropoutBiasResidual",
        _kreg.aval_key(x, residual, bias, rate=rate))
    return [fn(x, residual, bias, rate=rate, seed=seed)]


op_registry.register("FusedDropoutBiasResidual",
                     lower=_lower_dropout_bias_residual,
                     effects=op_registry.Effects(rng=True))


# ---------------------------------------------------------------------------
# Fused optimizer updates: the flat-group math pairs. The graph ops
# (FusedAdamUpdate / FusedMomentumUpdate) are registered by
# train/optimizers.py, which owns their variable semantics; it routes
# each flat group through these registry entries.
# ---------------------------------------------------------------------------

def _flat_gate(key, bk):
    if bk != "tpu":
        return ("xla", "interpret_backend")
    n = int(dict(key).get("n", 0))
    # one guaranteed pass over the g/m/v/p streams; below ~1M elements
    # launch overhead and XLA's own fusion make it a wash — measure
    if n >= (1 << 20):
        return ("pallas", "cost_model")
    return (None, "cost_model_uncertain")


def _adam_case(key):
    st = dict(key)
    n = int(st["n"])
    pdt, udt = st["pdt"], st["udt"]
    rng = np.random.RandomState(0)
    p = rng.randn(n).astype(_np_of(pdt))
    m = rng.randn(n).astype(_np_of(udt)) * 0.01
    v = np.abs(rng.randn(n)).astype(_np_of(udt)) * 0.01
    g = rng.randn(n).astype(_np_of(udt))
    alpha = np.asarray(0.001, _np_of(udt))
    return ((p, m, v, g, alpha),
            {"beta1": 0.9, "beta2": 0.999, "eps": 1e-8})


def _momentum_case(key):
    st = dict(key)
    n = int(st["n"])
    pdt, udt = st["pdt"], st["udt"]
    rng = np.random.RandomState(0)
    p = rng.randn(n).astype(_np_of(pdt))
    acc = rng.randn(n).astype(_np_of(udt)) * 0.01
    g = rng.randn(n).astype(_np_of(udt))
    lr = np.asarray(0.01, _np_of(udt))
    mu = np.asarray(0.9, _np_of(udt))
    return ((p, acc, g, lr, mu), {"use_nesterov": False})


_kreg.register_kernel(
    "FusedAdamUpdate",
    impls={"pallas": adam_update, "xla": adam_update_reference},
    legacy="xla",
    cost_gate=_flat_gate,
    make_case=_adam_case,
    graph_key=lambda op: _opt_graph_key(op),
    doc="one flat m/v/param Adam update per dtype group vs the fused "
        "XLA closure")
_kreg.register_kernel(
    "FusedMomentumUpdate",
    impls={"pallas": momentum_update, "xla": momentum_update_reference},
    legacy="xla",
    cost_gate=_flat_gate,
    make_case=_momentum_case,
    graph_key=lambda op: _opt_graph_key(op),
    doc="one flat accumulator/param Momentum update per dtype group vs "
        "the fused XLA closure")


def _opt_graph_key(op):
    n = 0
    for t in op.inputs:
        a = _tensor_aval(t)
        if a is None:
            return None
        sz = 1
        for d in a[0]:
            sz *= d
        n += sz
    return _kreg.aval_key(n=int(n), pdt="float32", udt="float32")


def flat_group_key(n, pdt, udt):
    """Decision key for one flattened optimizer parameter group."""
    return _kreg.aval_key(n=int(n), pdt=str(pdt), udt=str(udt))


# ---------------------------------------------------------------------------
# DecodeAttention: paged-cache decode kernel (q length 1) vs composed
# masked softmax. The graph op is registered by ops/kv_cache_ops.py,
# which owns the cache semantics; this entry owns the routing.
# ---------------------------------------------------------------------------

def _decode_attn_eligible(key):
    (qs, qd), (ks, _kd), (vs, _vd), bias = key[:4]
    if not _is_float(qd):
        return "ineligible_dtype"
    # q is (B, H, D) — or the (B, Kq, H, D) query block of the
    # speculative-verify / block-prefill plans
    if len(qs) not in (3, 4) or len(ks) != 4 or len(vs) != 4:
        return "ineligible_shape"
    if ks[0] != qs[0] or ks[2] != qs[-2] or ks[3] != qs[-1] or ks != vs:
        return "ineligible_shape"
    if bias is not None:
        bs, _bd = bias
        if len(bs) != 2 or bs[0] != qs[0] or bs[1] != ks[1]:
            return "ineligible_bias"
    return None


def _decode_attn_gate(key, bk):
    (qs, qd), (ks, _), _, _bias = key[:4]
    b, h, d = int(qs[0]), int(qs[-2]), int(qs[-1])
    kq = int(qs[1]) if len(qs) == 4 else 1
    max_len = int(ks[1])
    flops = 4.0 * b * kq * h * max_len * d
    itm = _np_of(qd).itemsize
    cache_bytes = 2.0 * b * max_len * h * d * itm
    # composed materializes the (B[, Kq], H, L) f32 score tensor ~three
    # times (scores, softmax, P·V read); the kernel streams the cache
    # once
    return _kreg.roofline_gate(
        flops, cache_bytes + b * kq * h * d * itm,
        cache_bytes + 3.0 * b * kq * h * max_len * 4, bk)


def _decode_attn_case(key):
    (qs, qd), (ks, kd), (vs, vd), bias = key[:4]
    args = [_rand(qs, qd, 0), _rand(ks, kd, 1), _rand(vs, vd, 2),
            np.full((qs[0],), ks[1] // 2 + 1, np.int32)]
    kw = {}
    if bias is not None:
        kw["bias"] = _rand(bias[0], bias[1], 3)
    return tuple(args), kw


_kreg.register_kernel(
    "DecodeAttention",
    impls={"pallas": decode_attention, "xla": decode_attention_xla},
    legacy="xla",
    eligible=_decode_attn_eligible,
    cost_gate=_decode_attn_gate,
    make_case=_decode_attn_case,
    graph_key=lambda op: _decode_attn_graph_key(op),
    doc="paged-cache decode attention (query length 1, heads on the "
        "sublane axis) vs composed masked softmax")


def _decode_attn_graph_key(op):
    avals = [_tensor_aval(t) for t in op.inputs[:3]]
    if len(avals) < 3 or any(a is None for a in avals):
        return None
    bias = _tensor_aval(op.inputs[4]) if len(op.inputs) > 4 else None
    if len(op.inputs) > 4 and bias is None:
        return None
    return _kreg.aval_key(
        *[_Aval(*a) for a in avals],
        _Aval(*bias) if bias is not None else None,
        has_bias=len(op.inputs) > 4)
