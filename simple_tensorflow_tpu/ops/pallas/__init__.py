"""Pallas TPU kernels (replaces ref CUDA kernels, core/kernels/*_gpu.cu.cc).

Each kernel is exposed two ways:
- as a jax-level function (used directly by jax-native model code), and
- as a registered graph op, so stf graph programs pick up the fused kernel
  through the normal Session lowering path (`stf.nn.fused_*`).

All kernels auto-switch to interpret mode off-TPU so the CPU test mesh
exercises identical code paths.
"""

from ...framework import op_registry
from .flash_attention import flash_attention, mha_reference
from .layer_norm import layer_norm, layer_norm_reference
from .quant_matmul import (quant_matmul, quant_matmul_reference,
                           quant_matmul_ste, quantize_colwise,
                           quantize_rowwise)
from .softmax_xent import (softmax_cross_entropy,
                           softmax_cross_entropy_reference)

op_registry.register_pure(
    "FlashAttention",
    lambda q, k, v, causal=False, sm_scale=None:
        flash_attention(q, k, v, causal=causal, sm_scale=sm_scale))
op_registry.register_pure(
    "FusedLayerNorm",
    lambda x, gamma, beta, eps=1e-6: layer_norm(x, gamma, beta, eps=eps))
op_registry.register_pure(
    "FusedSoftmaxXent",
    lambda logits, labels: softmax_cross_entropy(logits, labels))
op_registry.register_pure(
    "QuantMatMul",
    lambda x, wq, w_scale: quant_matmul_ste(x, wq, w_scale))
