"""Pallas TPU kernels (replaces ref CUDA kernels, core/kernels/*_gpu.cu.cc).

Each kernel is exposed two ways:
- as a jax-level function (used directly by jax-native model code), and
- as a registered graph op, so stf graph programs pick up the fused kernel
  through the normal Session lowering path (`stf.nn.fused_*`).

All kernels auto-switch to interpret mode off-TPU so the CPU test mesh
exercises identical code paths.
"""

from ...framework import op_registry
from .flash_attention import flash_attention, mha_reference
from .layer_norm import layer_norm, layer_norm_reference
from .quant_matmul import (quant_matmul, quant_matmul_reference,
                           quant_matmul_ste, quantize_colwise,
                           quantize_rowwise)
from .softmax_xent import (softmax_cross_entropy,
                           softmax_cross_entropy_reference)

def _flash_pure(q, k, v, bias=None, causal=False, sm_scale=None):
    return flash_attention(q, k, v, bias=bias, causal=causal,
                           sm_scale=sm_scale)


def _flash_dropout_lower(ctx, op, input_values):
    """FlashAttention with probability dropout: stateful (never CSE'd —
    two dropout sites must draw different masks), seeded from the op's
    per-step RNG stream so fwd and vjp replay the same mask."""
    import jax
    import jax.numpy as jnp

    q, k, v = input_values[:3]
    bias = input_values[3] if len(input_values) > 3 else None
    key = ctx.rng_for(op)
    seed = jax.random.randint(key, (1,), 0, jnp.iinfo(jnp.int32).max,
                              dtype=jnp.int32)
    out = flash_attention(
        q, k, v, bias=bias, causal=op.attrs.get("causal", False),
        sm_scale=op.attrs.get("sm_scale"),
        dropout_rate=float(op.attrs["dropout_rate"]), dropout_seed=seed)
    return [out]


op_registry.register_pure("FlashAttention", _flash_pure)
op_registry.register("FlashAttentionDropout", lower=_flash_dropout_lower,
                     is_stateful=True)
op_registry.register_pure(
    "FusedLayerNorm",
    lambda x, gamma, beta, eps=1e-6: layer_norm(x, gamma, beta, eps=eps))
op_registry.register_pure(
    "FusedSoftmaxXent",
    lambda logits, labels, label_smoothing=0.0: softmax_cross_entropy(
        logits, labels, label_smoothing=label_smoothing))
op_registry.register_pure(
    "QuantMatMul",
    lambda x, wq, w_scale: quant_matmul_ste(x, wq, w_scale))
