"""Flash attention for TPU (Pallas), forward + custom-VJP backward.

Replaces the reference's attention-as-composed-matmuls path (the reference
has no fused attention; BERT-style models there materialise the [B,H,S,S]
score matrix through batch_matmul + softmax kernels,
ref: tensorflow/core/kernels/{batch_matmul_op,softmax_op}.cc). On TPU the
materialised scores blow HBM bandwidth at long sequence, so we compute
attention with the FlashAttention-2 online-softmax recurrence, tiled to the
MXU.

K/V genuinely stream: the grid's innermost dimension walks K/V blocks (TPU
grids execute sequentially per core), the online-softmax state (m, l, acc)
lives in VMEM scratch across those iterations, and the output block flushes
on the last one. VMEM per program is O(block_q*d + block_k*d) independent of
sequence length. Causally-dead blocks are predicated off with pl.when.

Matmul policy: operands stay in the input dtype (bf16 runs the MXU at
native rate), accumulation is f32 via preferred_element_type, and
Precision.HIGHEST stops XLA from demoting f32 operands to bf16 passes.
The probability matrix is cast back to the input dtype for the P·V and
dS-type matmuls (standard FlashAttention practice).

Layout: (batch, heads, seq, head_dim), bf16/f32 in, f32 accumulation.
The wrapper pads seq to the block size; head_dim stays UNPADDED for the
common 64/128 sizes (Mosaic accepts a half-tile minor dim — padding d=64
to the 128-lane width in HBM doubled every attention tensor, ~11 GB/step
on BERT-base), with only odd sizes rounded up to the next half tile.
Padded keys are masked in-kernel against the true KV length (static), so
softmax stays NaN-free. Per-row stats (m, l, lse, delta) are kept as
(rows, 1) tiles — Mosaic requires sublane×lane-legal block shapes.

Backward follows FlashAttention-2: recompute P block-wise from (Q,K,lse),
dV = P^T dO, dP = dO V^T, dS = P * (dP - delta), dQ = dS K, dK = dS^T Q,
with delta = rowsum(dO * O) precomputed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import (NEG_INF, cdiv, counter_keep_mask, mix32, pad_dim,
                     round_up, use_interpret)

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_HI = jax.lax.Precision.HIGHEST


def _dot(a, b, contract):
    """dot_general with f32 accumulation. contract=((a_dims),(b_dims)).
    f32 operands get Precision.HIGHEST (stops XLA demoting them to bf16
    MXU passes); bf16 operands run the MXU natively — Mosaic rejects an
    fp32 contract precision on bf16 inputs."""
    precision = _HI if a.dtype == jnp.float32 else None
    return jax.lax.dot_general(
        a, b, dimension_numbers=(contract, ((), ())),
        preferred_element_type=jnp.float32, precision=precision)


def _score_mask(s, qi, kb, block_q, block_k, kv_true, causal):
    """Apply KV-length and causal masking to a (block_q, block_k) score
    tile for Q block qi / K block kb. Single source of truth for fwd+bwd."""
    shape = (s.shape[0], s.shape[1])
    span_q = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    span_k = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    mask = span_k < kv_true
    if causal:
        mask = mask & (span_q >= span_k)
    return jnp.where(mask, s, NEG_INF)


_mix32 = mix32  # moved to common.py (shared with the fused dropout kernel)


def _keep_mask(seed, bh, qi, kb, block_q, block_k, keep_prob):
    """Deterministic dropout keep-mask for score tile (qi, kb) of head bh.

    Counter-based on GLOBAL (row, col) score indices (common.py
    counter_keep_mask) — regenerated bit-identically in the backward
    kernels regardless of grid order AND by the composed-XLA fallback
    lowering (attention_xla), so swapping implementations through the
    kernel registry preserves seeded runs exactly. No mask tensor is
    ever materialized in HBM."""
    shape = (block_q, block_k)
    rows = (qi.astype(jnp.uint32) * jnp.uint32(block_q) +
            jax.lax.broadcasted_iota(jnp.uint32, shape, 0))
    cols = (kb.astype(jnp.uint32) * jnp.uint32(block_k) +
            jax.lax.broadcasted_iota(jnp.uint32, shape, 1))
    return counter_keep_mask(seed, bh, rows, cols, keep_prob)


# ---------------------------------------------------------------------------
# Forward kernel: grid (bh, q_blocks, k_blocks), innermost streams K/V
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, sm_scale, causal, block_q, block_k, kv_true, num_kb,
                has_bias, dropout_rate):
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    bias_ref = next(it) if has_bias else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    o_ref, lse_ref, m_scr, l_scr, acc_scr = it
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # A block contributes unless it is wholly above the causal diagonal.
    live = ((qi + 1) * block_q - 1 >= kb * block_k) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = _dot(q, k, ((1,), (1,))) * sm_scale        # (block_q, block_k)
        if has_bias:
            s = s + bias_ref[:]                        # (1, block_k) f32
        s = _score_mask(s, qi, kb, block_q, block_k, kv_true, causal)

        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)                # (block_q, 1)
        m_scr[:] = m_new
        # denominator accumulates the UN-dropped sum: dropout scales
        # normalized probs, and elementwise 0/(1/keep) commutes with the
        # final per-row division by l.
        l_scr[:] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            keep_prob = 1.0 - dropout_rate
            keep = _keep_mask(seed_ref[0], bh, qi, kb, block_q, block_k,
                              keep_prob)
            p = jnp.where(keep, p * (1.0 / keep_prob), 0.0)
        acc_scr[:] = acc_scr[:] * alpha + _dot(
            p.astype(v.dtype), v, ((1,), (0,)))

    @pl.when(kb == num_kb - 1)
    def _():
        l_safe = jnp.where(l_scr[:] == 0.0, 1.0, l_scr[:])
        o_ref[:] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[:] = m_scr[:] + jnp.log(l_safe)


def _fwd(q, k, v, bias, seed, sm_scale, causal, block_q, block_k, kv_true,
         dropout_rate, num_heads):
    bh, q_len, d = q.shape
    kv_pad_len = k.shape[1]
    num_kb = cdiv(kv_pad_len, block_k)
    has_bias = bias is not None
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               kv_true=kv_true, num_kb=num_kb,
                               has_bias=has_bias, dropout_rate=dropout_rate)
    in_specs = [
        pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    operands = [q, k, v]
    if has_bias:
        in_specs.append(pl.BlockSpec(
            (None, 1, block_k),
            lambda b, i, j, nh=num_heads: (b // nh, 0, j)))
        operands.append(bias)
    if dropout_rate > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(seed)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, cdiv(q_len, block_q), num_kb),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, q_len, d), q.dtype),
            jax.ShapeDtypeStruct((bh, q_len, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(4 * bh * q_len * kv_true * d * (0.5 if causal else 1.0)),
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=bh * q_len * kv_true),
        interpret=use_interpret(),
    )(*operands)
    return o, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------

def _bwd_dkdv_kernel(*refs, sm_scale, causal, block_q, block_k, kv_true,
                     num_qb, has_bias, dropout_rate):
    # grid (bh, k_blocks, q_blocks): one K/V block, streaming Q/dO blocks.
    it = iter(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = (
        next(it), next(it), next(it), next(it), next(it), next(it))
    bias_ref = next(it) if has_bias else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    dk_ref, dv_ref, dk_scr, dv_scr = it
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = ((qb + 1) * block_q - 1 >= ki * block_k) if causal else True

    @pl.when(live)
    def _():
        k = k_ref[:]
        v = v_ref[:]
        q = q_ref[:]
        do = do_ref[:]
        lse = lse_ref[:]                               # (bq, 1)
        delta = delta_ref[:]
        s = _dot(q, k, ((1,), (1,))) * sm_scale
        if has_bias:
            s = s + bias_ref[:]
        s = _score_mask(s, qb, ki, block_q, block_k, kv_true, causal)
        p = jnp.exp(s - lse)                           # (bq, bk) f32
        dp = _dot(do, v, ((1,), (1,)))                 # (bq, bk)
        if dropout_rate > 0.0:
            keep_prob = 1.0 - dropout_rate
            # NOTE (qb, ki) order: the mask is keyed on (q-block, k-block)
            # exactly as in the forward, though this grid iterates k outer.
            keep = _keep_mask(seed_ref[0], bh, qb, ki, block_q, block_k,
                              keep_prob)
            pc = jnp.where(keep, p * (1.0 / keep_prob), 0.0).astype(do.dtype)
            dp = jnp.where(keep, dp * (1.0 / keep_prob), 0.0)
        else:
            pc = p.astype(do.dtype)
        dv_scr[:] += _dot(pc, do, ((0,), (0,)))        # (bk, d)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk_scr[:] += _dot(ds, q, ((0,), (0,)))         # (bk, d)

    @pl.when(qb == num_qb - 1)
    def _():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(*refs, sm_scale, causal, block_q, block_k, kv_true,
                   num_kb, has_bias, dropout_rate):
    # grid (bh, q_blocks, k_blocks): one Q block, streaming K/V blocks.
    it = iter(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = (
        next(it), next(it), next(it), next(it), next(it), next(it))
    bias_ref = next(it) if has_bias else None
    seed_ref = next(it) if dropout_rate > 0.0 else None
    dq_ref, dq_scr = it
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = ((qi + 1) * block_q - 1 >= kb * block_k) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[:]
        do = do_ref[:]
        lse = lse_ref[:]
        delta = delta_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = _dot(q, k, ((1,), (1,))) * sm_scale
        if has_bias:
            s = s + bias_ref[:]
        s = _score_mask(s, qi, kb, block_q, block_k, kv_true, causal)
        p = jnp.exp(s - lse)
        dp = _dot(do, v, ((1,), (1,)))
        if dropout_rate > 0.0:
            keep_prob = 1.0 - dropout_rate
            keep = _keep_mask(seed_ref[0], bh, qi, kb, block_q, block_k,
                              keep_prob)
            dp = jnp.where(keep, dp * (1.0 / keep_prob), 0.0)
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        dq_scr[:] += _dot(ds, k, ((1,), (0,)))

    @pl.when(kb == num_kb - 1)
    def _():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, kv_true, dropout_rate,
         num_heads, res, g):
    q, k, v, bias, seed, o, lse = res
    do = g.astype(jnp.float32)
    delta = jnp.sum(do * o.astype(jnp.float32), axis=-1,
                    keepdims=True)                          # (bh, q_len, 1)
    return _bwd_with_delta(sm_scale, causal, block_q, block_k, kv_true,
                           dropout_rate, num_heads,
                           (q, k, v, bias, seed, lse), g, delta)


def _bwd_with_delta(sm_scale, causal, block_q, block_k, kv_true,
                    dropout_rate, num_heads, res, g, delta):
    """Kernel plumbing shared by the plain vjp (delta = rowsum(dO∘O)) and
    the (o, lse) vjp (delta shifted by −dlse)."""
    q, k, v, bias, seed, lse = res
    bh, q_len, d = q.shape
    kv_pad_len = k.shape[1]
    has_bias = bias is not None
    num_qb = cdiv(q_len, block_q)
    num_kb = cdiv(kv_pad_len, block_k)

    def aux(kb_index_map):
        """Optional bias/seed specs+operands; kb_index_map maps grid ids to
        the k-block index (differs between the two bwd grids)."""
        specs, ops = [], []
        if has_bias:
            specs.append(pl.BlockSpec(
                (None, 1, block_k),
                lambda b, i, j, nh=num_heads: (b // nh, 0,
                                               kb_index_map(i, j))))
            ops.append(bias)
        if dropout_rate > 0.0:
            specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
            ops.append(seed)
        return specs, ops

    dkdv = functools.partial(_bwd_dkdv_kernel, sm_scale=sm_scale,
                             causal=causal, block_q=block_q, block_k=block_k,
                             kv_true=kv_true, num_qb=num_qb,
                             has_bias=has_bias, dropout_rate=dropout_rate)
    aux_specs, aux_ops = aux(lambda i, j: i)  # grid (bh, kb, qb)
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, j, 0)),
        ] + aux_specs,
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, kv_pad_len, d), k.dtype),
            jax.ShapeDtypeStruct((bh, kv_pad_len, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=use_interpret(),
    )(q, k, v, g, lse, delta, *aux_ops)

    dqk = functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                            block_q=block_q, block_k=block_k,
                            kv_true=kv_true, num_kb=num_kb,
                            has_bias=has_bias, dropout_rate=dropout_rate)
    aux_specs, aux_ops = aux(lambda i, j: j)  # grid (bh, qb, kb)
    dq = pl.pallas_call(
        dqk,
        grid=(bh, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ] + aux_specs,
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, q_len, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=use_interpret(),
    )(q, k, v, g, lse, delta, *aux_ops)
    grads = [dq, dk, dv]
    # bias is a constant mask under differentiation (stop_gradient'd in the
    # wrapper); seed is integer-typed. Both get symbolic-zero cotangents.
    if has_bias:
        grads.append(jnp.zeros_like(bias))
    else:
        grads.append(None)
    if seed is not None:
        grads.append(np.zeros(seed.shape, dtype=jax.dtypes.float0))
    else:
        grads.append(None)
    return tuple(grads)


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash_bhsd(q, k, v, bias, seed, sm_scale, causal, block_q, block_k,
                kv_true, dropout_rate, num_heads):
    o, _ = _fwd(q, k, v, bias, seed, sm_scale, causal, block_q, block_k,
                kv_true, dropout_rate, num_heads)
    return o


def _flash_fwd_rule(q, k, v, bias, seed, sm_scale, causal, block_q, block_k,
                    kv_true, dropout_rate, num_heads):
    o, lse = _fwd(q, k, v, bias, seed, sm_scale, causal, block_q, block_k,
                  kv_true, dropout_rate, num_heads)
    return o, (q, k, v, bias, seed, o, lse)


_flash_bhsd.defvjp(_flash_fwd_rule, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash_bhsd_lse(q, k, v, bias, seed, sm_scale, causal, block_q, block_k,
                    kv_true, dropout_rate, num_heads):
    """Variant returning (o, lse) — ring attention merges per-block
    partials through the log-sum-exp."""
    return _fwd(q, k, v, bias, seed, sm_scale, causal, block_q, block_k,
                kv_true, dropout_rate, num_heads)


def _flash_lse_fwd_rule(q, k, v, bias, seed, sm_scale, causal, block_q,
                        block_k, kv_true, dropout_rate, num_heads):
    o, lse = _fwd(q, k, v, bias, seed, sm_scale, causal, block_q, block_k,
                  kv_true, dropout_rate, num_heads)
    return (o, lse), (q, k, v, bias, seed, o, lse)


def _bwd_lse(sm_scale, causal, block_q, block_k, kv_true, dropout_rate,
             num_heads, res, gs):
    """The lse cotangent folds into the existing kernels: with
    L = f(O, LSE), dS = P∘(dP − delta + dlse) since ∂LSE/∂S = P — i.e.
    run the standard backward with delta' = rowsum(dO∘O) − dlse."""
    g_o, g_lse = gs
    q, k, v, bias, seed, o, lse = res
    do = g_o.astype(jnp.float32)
    delta = jnp.sum(do * o.astype(jnp.float32), axis=-1, keepdims=True) \
        - g_lse.astype(jnp.float32)
    return _bwd_with_delta(sm_scale, causal, block_q, block_k, kv_true,
                           dropout_rate, num_heads,
                           (q, k, v, bias, seed, lse), g_o, delta)


_flash_bhsd_lse.defvjp(_flash_lse_fwd_rule, _bwd_lse)


def flash_attention(q, k, v, *, causal=False, sm_scale=None, bias=None,
                    dropout_rate=0.0, dropout_seed=None, return_lse=False,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Fused attention. q,k,v: (batch, heads, seq, head_dim) (kv seq may
    differ for cross-attention; causal requires equal lengths). Returns
    (batch, heads, q_seq, head_dim) in q.dtype.

    bias: optional additive score bias, broadcast over heads and query
    positions — shape (batch, kv_seq) or any (batch, 1, 1, kv_seq)-style
    squeezable form. This is the padding-mask shape (0 attendable / -1e9
    padded); it is treated as a CONSTANT under differentiation
    (stop_gradient) — per-head trainable biases must use the XLA
    composed-attention path.

    dropout_rate: attention-probability dropout (applied after softmax
    normalization, inverted scaling). Requires dropout_seed, an int32
    scalar/array; the mask is counter-based on (head, row, col) so the
    backward pass regenerates it exactly — nothing is materialized.

    return_lse: also return the per-row log-sum-exp (batch, heads,
    q_seq) in f32 — the merge key for composing partial attentions
    (ring attention); differentiable (the lse cotangent folds into the
    backward's delta term).
    """
    b, h, q_len, d = q.shape
    kv_len = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    if causal and q_len != kv_len:
        raise ValueError("causal flash attention needs q_len == kv_len")
    if dropout_rate < 0.0 or dropout_rate >= 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1): {dropout_rate}")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("flash attention dropout needs dropout_seed")

    align = 8 if use_interpret() else 128
    block_q = min(block_q, round_up(q_len, align))
    block_k = min(block_k, round_up(kv_len, align))
    qp_len = round_up(q_len, block_q)
    kp_len = round_up(kv_len, block_k)
    # head_dim 64 stays unpadded: Mosaic accepts a half-tile minor dim, and
    # padding to the 128-lane width in HBM doubles every attention tensor
    # (q/k/v/o and all three gradients) — measured as ~11 GB/step of pure
    # padding traffic on BERT-base. Only odd sizes pad, to the next half
    # tile.
    dp = d if use_interpret() else round_up(d, 64)

    qq = pad_dim(pad_dim(q.reshape(b * h, q_len, d), 1, qp_len), 2, dp)
    kk = pad_dim(pad_dim(k.reshape(b * h, kv_len, d), 1, kp_len), 2, dp)
    vv = pad_dim(pad_dim(v.reshape(b * h, kv_len, d), 1, kp_len), 2, dp)

    bb = None
    if bias is not None:
        bb = jnp.asarray(bias, jnp.float32)
        # squeeze broadcast dims down to (batch, kv_seq)
        while bb.ndim > 2:
            sq = next((i for i in range(1, bb.ndim - 1) if bb.shape[i] == 1),
                      None)
            if sq is None:
                raise NotImplementedError(
                    "flash attention bias must broadcast over heads and "
                    f"query positions (got shape {bias.shape}); use the "
                    "XLA composed-attention path for per-head/per-query "
                    "biases")
            bb = jnp.squeeze(bb, axis=sq)
        if bb.shape != (b, kv_len):
            raise ValueError(
                f"flash attention bias: expected (batch, kv_seq)="
                f"({b}, {kv_len}) after squeezing, got {bb.shape}")
        bb = jax.lax.stop_gradient(pad_dim(bb, 1, kp_len))
        bb = bb.reshape(b, 1, kp_len)

    ss = None
    if dropout_rate > 0.0:
        ss = jnp.asarray(dropout_seed, jnp.int32).reshape((1,))

    args = (qq, kk, vv, bb, ss, float(sm_scale), bool(causal),
            int(block_q), int(block_k), int(kv_len),
            float(dropout_rate), int(h))
    if return_lse:
        o, lse = _flash_bhsd_lse(*args)
        o = o[:, :q_len, :d].reshape(b, h, q_len, d)
        lse = lse[:, :q_len, 0].reshape(b, h, q_len)
        return o, lse
    o = _flash_bhsd(*args)
    o = o[:, :q_len, :d].reshape(b, h, q_len, d)
    return o


def attention_xla(q, k, v, *, causal=False, sm_scale=None, bias=None,
                  dropout_rate=0.0, dropout_seed=None, return_lse=False,
                  block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """The stock composed-XLA lowering of the FlashAttention op contract
    (batch_matmul → softmax → batch_matmul, the reference's attention
    path; ref core/kernels/{batch_matmul_op,softmax_op}.cc) — the
    registry's fallback when the Pallas kernel is ineligible or the
    cost model/autotune prices the fused kernel slower (tiny shapes;
    every shape off-TPU, where Pallas runs in interpret mode).

    Call-compatible with :func:`flash_attention` including in-kernel
    probability dropout: the keep mask is the same counter-based hash
    of (head, row, col) positions, so a seeded run is bit-identically
    reproducible whichever implementation the registry picks. The
    score matrix IS materialized ((B, H, Sq, Sk) f32) — that HBM
    traffic is exactly what the cost-model gate prices against the
    streamed kernel. ``bias`` additionally accepts any
    attention-broadcastable shape (per-head/per-query biases the fused
    kernel rejects)."""
    b, h, q_len, d = q.shape
    kv_len = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    if causal and q_len != kv_len:
        raise ValueError("causal attention needs q_len == kv_len")
    if dropout_rate < 0.0 or dropout_rate >= 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1): {dropout_rate}")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("attention dropout needs dropout_seed")
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32), precision=_HI) * sm_scale
    if bias is not None:
        bb = jax.lax.stop_gradient(jnp.asarray(bias, jnp.float32))
        if bb.ndim == 2:                       # (batch, kv_seq) key bias
            bb = bb[:, None, None, :]
        s = s + bb
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 1)
        s = jnp.where((rows >= cols)[None, None], s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)          # (b, h, q) f32
    p = jnp.exp(s - lse[..., None])
    if dropout_rate > 0.0:
        keep_prob = 1.0 - dropout_rate
        seed = jnp.asarray(dropout_seed, jnp.int32).reshape((-1,))[0]
        bh = jax.lax.broadcasted_iota(jnp.uint32,
                                      (b * h, q_len, kv_len), 0)
        rr = jax.lax.broadcasted_iota(jnp.uint32,
                                      (b * h, q_len, kv_len), 1)
        cc = jax.lax.broadcasted_iota(jnp.uint32,
                                      (b * h, q_len, kv_len), 2)
        keep = counter_keep_mask(seed, bh, rr, cc,
                                 keep_prob).reshape(b, h, q_len, kv_len)
        p = jnp.where(keep, p * (1.0 / keep_prob), 0.0)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                   precision=_HI).astype(q.dtype)
    if return_lse:
        return o, lse
    return o


def mha_reference(q, k, v, *, causal=False, sm_scale=None, bias=None):
    """Naive attention in jnp — the numeric reference for tests.
    bias: additive (batch, kv_seq) or (batch, 1, 1, kv_seq) score bias."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32) * sm_scale,
                   precision=_HI)
    if bias is not None:
        bias = jnp.asarray(bias, jnp.float32)
        if bias.ndim == 2:
            bias = bias[:, None, None, :]
        s = s + bias
    if causal:
        q_len, k_len = s.shape[-2:]
        mask = jnp.tril(jnp.ones((q_len, k_len), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                      precision=_HI).astype(q.dtype)
