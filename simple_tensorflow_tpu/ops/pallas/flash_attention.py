"""Flash attention for TPU (Pallas), forward + custom-VJP backward.

Replaces the reference's attention-as-composed-matmuls path (the reference
has no fused attention; BERT-style models there materialise the [B,H,S,S]
score matrix through batch_matmul + softmax kernels,
ref: tensorflow/core/kernels/{batch_matmul_op,softmax_op}.cc). On TPU the
materialised scores blow HBM bandwidth at long sequence, so we compute
attention with the FlashAttention-2 online-softmax recurrence, tiled to the
MXU.

K/V genuinely stream: the grid's innermost dimension walks K/V blocks (TPU
grids execute sequentially per core), the online-softmax state (m, l, acc)
lives in VMEM scratch across those iterations, and the output block flushes
on the last one. VMEM per program is O(block_q*d + block_k*d) independent of
sequence length. Causally-dead blocks are predicated off with pl.when.

Matmul policy: operands stay in the input dtype (bf16 runs the MXU at
native rate), accumulation is f32 via preferred_element_type, and
Precision.HIGHEST stops XLA from demoting f32 operands to bf16 passes.
The probability matrix is cast back to the input dtype for the P·V and
dS-type matmuls (standard FlashAttention practice).

Layout: (batch, heads, seq, head_dim), bf16/f32 in, f32 accumulation.
The wrapper pads seq to the block size and head_dim to the 128-lane width;
padded keys are masked in-kernel against the true KV length (static), so
softmax stays NaN-free. Per-row stats (m, l, lse, delta) are kept as
(rows, 1) tiles — Mosaic requires sublane×lane-legal block shapes.

Backward follows FlashAttention-2: recompute P block-wise from (Q,K,lse),
dV = P^T dO, dP = dO V^T, dS = P * (dP - delta), dQ = dS K, dK = dS^T Q,
with delta = rowsum(dO * O) precomputed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import NEG_INF, cdiv, pad_dim, round_up, use_interpret

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
LANE = 128
_HI = jax.lax.Precision.HIGHEST


def _dot(a, b, contract):
    """dot_general with f32 accumulation. contract=((a_dims),(b_dims)).
    f32 operands get Precision.HIGHEST (stops XLA demoting them to bf16
    MXU passes); bf16 operands run the MXU natively — Mosaic rejects an
    fp32 contract precision on bf16 inputs."""
    precision = _HI if a.dtype == jnp.float32 else None
    return jax.lax.dot_general(
        a, b, dimension_numbers=(contract, ((), ())),
        preferred_element_type=jnp.float32, precision=precision)


def _score_mask(s, qi, kb, block_q, block_k, kv_true, causal):
    """Apply KV-length and causal masking to a (block_q, block_k) score
    tile for Q block qi / K block kb. Single source of truth for fwd+bwd."""
    shape = (s.shape[0], s.shape[1])
    span_q = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    span_k = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    mask = span_k < kv_true
    if causal:
        mask = mask & (span_q >= span_k)
    return jnp.where(mask, s, NEG_INF)


# ---------------------------------------------------------------------------
# Forward kernel: grid (bh, q_blocks, k_blocks), innermost streams K/V
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, sm_scale, causal, block_q, block_k, kv_true, num_kb):
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # A block contributes unless it is wholly above the causal diagonal.
    live = ((qi + 1) * block_q - 1 >= kb * block_k) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = _dot(q, k, ((1,), (1,))) * sm_scale        # (block_q, block_k)
        s = _score_mask(s, qi, kb, block_q, block_k, kv_true, causal)

        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)                # (block_q, 1)
        m_scr[:] = m_new
        l_scr[:] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + _dot(
            p.astype(v.dtype), v, ((1,), (0,)))

    @pl.when(kb == num_kb - 1)
    def _():
        l_safe = jnp.where(l_scr[:] == 0.0, 1.0, l_scr[:])
        o_ref[:] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[:] = m_scr[:] + jnp.log(l_safe)


def _fwd(q, k, v, sm_scale, causal, block_q, block_k, kv_true):
    bh, q_len, d = q.shape
    kv_pad_len = k.shape[1]
    num_kb = cdiv(kv_pad_len, block_k)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               kv_true=kv_true, num_kb=num_kb)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, cdiv(q_len, block_q), num_kb),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, q_len, d), q.dtype),
            jax.ShapeDtypeStruct((bh, q_len, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(4 * bh * q_len * kv_true * d * (0.5 if causal else 1.0)),
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=bh * q_len * kv_true),
        interpret=use_interpret(),
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------

def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_scr, dv_scr, *,
                     sm_scale, causal, block_q, block_k, kv_true, num_qb):
    # grid (bh, k_blocks, q_blocks): one K/V block, streaming Q/dO blocks.
    ki = pl.program_id(1)
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = ((qb + 1) * block_q - 1 >= ki * block_k) if causal else True

    @pl.when(live)
    def _():
        k = k_ref[:]
        v = v_ref[:]
        q = q_ref[:]
        do = do_ref[:]
        lse = lse_ref[:]                               # (bq, 1)
        delta = delta_ref[:]
        s = _dot(q, k, ((1,), (1,))) * sm_scale
        s = _score_mask(s, qb, ki, block_q, block_k, kv_true, causal)
        p = jnp.exp(s - lse)                           # (bq, bk) f32
        pc = p.astype(do.dtype)
        dv_scr[:] += _dot(pc, do, ((0,), (0,)))        # (bk, d)
        dp = _dot(do, v, ((1,), (1,)))                 # (bq, bk)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk_scr[:] += _dot(ds, q, ((0,), (0,)))         # (bk, d)

    @pl.when(qb == num_qb - 1)
    def _():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr, *, sm_scale, causal, block_q, block_k,
                   kv_true, num_kb):
    # grid (bh, q_blocks, k_blocks): one Q block, streaming K/V blocks.
    qi = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = ((qi + 1) * block_q - 1 >= kb * block_k) if causal else True

    @pl.when(live)
    def _():
        q = q_ref[:]
        do = do_ref[:]
        lse = lse_ref[:]
        delta = delta_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = _dot(q, k, ((1,), (1,))) * sm_scale
        s = _score_mask(s, qi, kb, block_q, block_k, kv_true, causal)
        p = jnp.exp(s - lse)
        dp = _dot(do, v, ((1,), (1,)))
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        dq_scr[:] += _dot(ds, k, ((1,), (0,)))

    @pl.when(kb == num_kb - 1)
    def _():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, kv_true, res, g):
    q, k, v, o, lse = res
    bh, q_len, d = q.shape
    kv_pad_len = k.shape[1]
    do = g.astype(jnp.float32)
    delta = jnp.sum(do * o.astype(jnp.float32), axis=-1,
                    keepdims=True)                          # (bh, q_len, 1)
    num_qb = cdiv(q_len, block_q)
    num_kb = cdiv(kv_pad_len, block_k)

    dkdv = functools.partial(_bwd_dkdv_kernel, sm_scale=sm_scale,
                             causal=causal, block_q=block_q, block_k=block_k,
                             kv_true=kv_true, num_qb=num_qb)
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(bh, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, kv_pad_len, d), k.dtype),
            jax.ShapeDtypeStruct((bh, kv_pad_len, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=use_interpret(),
    )(q, k, v, g, lse, delta)

    dqk = functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                            block_q=block_q, block_k=block_k,
                            kv_true=kv_true, num_kb=num_kb)
    dq = pl.pallas_call(
        dqk,
        grid=(bh, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, q_len, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=use_interpret(),
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhsd(q, k, v, sm_scale, causal, block_q, block_k, kv_true):
    o, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k, kv_true)
    return o


def _flash_fwd_rule(q, k, v, sm_scale, causal, block_q, block_k, kv_true):
    o, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k, kv_true)
    return o, (q, k, v, o, lse)


_flash_bhsd.defvjp(_flash_fwd_rule, _bwd)


def flash_attention(q, k, v, *, causal=False, sm_scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """Fused attention. q,k,v: (batch, heads, seq, head_dim) (kv seq may
    differ for cross-attention; causal requires equal lengths). Returns
    (batch, heads, q_seq, head_dim) in q.dtype."""
    b, h, q_len, d = q.shape
    kv_len = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    if causal and q_len != kv_len:
        raise ValueError("causal flash attention needs q_len == kv_len")

    align = 8 if use_interpret() else 128
    block_q = min(block_q, round_up(q_len, align))
    block_k = min(block_k, round_up(kv_len, align))
    qp_len = round_up(q_len, block_q)
    kp_len = round_up(kv_len, block_k)
    dp = d if use_interpret() else round_up(d, LANE)

    qq = pad_dim(pad_dim(q.reshape(b * h, q_len, d), 1, qp_len), 2, dp)
    kk = pad_dim(pad_dim(k.reshape(b * h, kv_len, d), 1, kp_len), 2, dp)
    vv = pad_dim(pad_dim(v.reshape(b * h, kv_len, d), 1, kp_len), 2, dp)

    o = _flash_bhsd(qq, kk, vv, float(sm_scale), bool(causal),
                    int(block_q), int(block_k), int(kv_len))
    o = o[:, :q_len, :d].reshape(b, h, q_len, d)
    return o


def mha_reference(q, k, v, *, causal=False, sm_scale=None):
    """Naive attention in jnp — the numeric reference for tests."""
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32) * sm_scale,
                   precision=_HI)
    if causal:
        q_len, k_len = s.shape[-2:]
        mask = jnp.tril(jnp.ones((q_len, k_len), bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                      precision=_HI).astype(q.dtype)
