"""Paged-cache decode attention for TPU (Pallas): query length 1 or a
small query BLOCK (speculative verify / paged block prefill).

The generative-inference hot loop (docs/PERFORMANCE.md "decode
anatomy") attends ONE new query position per sequence against that
sequence's gathered KV-cache rows. The training-side flash kernel is
the wrong tool here: its q-block tiling amortizes over many query rows,
and a (1, d) query block wastes the whole MXU pass. This kernel keeps
the HEADS on the sublane axis instead — grid (batch, kv_blocks), one
(H, D) query tile per sequence, K/V streamed in (block_l, H, D) tiles
straight from the paged-cache layout (slots, max_len, heads, head_dim)
that :mod:`..kv_cache_ops` gathers — so no (B, H, 1, L) score tensor
ever reaches HBM and the cache rows are read exactly once.

Masking is per-sequence by LENGTH (cache positions >= lengths[b] are
dead slots/future positions) plus an optional additive key bias
(B, kv_len) — the padding-mask shape cross-attention feeds. Online
softmax (m, l, acc) lives in VMEM scratch across the kv-block walk,
exactly like flash_attention.py.

Decode is inference-only: no custom VJP (the op is registered without
a gradient; training uses the flash kernel).

Layout: q (B, H, D); k/v (B, L, H, D); lengths (B,) int32 in SMEM.
Heads pad to the f32 sublane tile (8), head_dim to a half lane tile
(64) off-interpret — dead head rows are sliced off on return.

Query-block variant (PR 16): q (B, Kq, H, D) — Kq consecutive
positions per sequence, the shape of a speculative VERIFY step (the
target re-scores the draft's K proposals in one pass) and of the
causal-LM page-block prefill. With ``causal_offset=True`` ``lengths``
is the committed prefix BEFORE the block and query j attends
positions < lengths[b] + j + 1 (the block's own K/V were appended at
lengths[b]..lengths[b]+Kq-1 just before this op); with False every
query sees positions < lengths[b] (cross-attention over a fixed
source). The kernel walks the same (batch, kv_blocks) grid with the
query block riding the sublane axis next to heads — tiles (H, Kq, D),
scores (H, Kq, block_l) — so the Kq=4-ish verify widths never touch
HBM either.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import NEG_INF, cdiv, pad_dim, round_up, use_interpret

DEFAULT_BLOCK_L = 128
_HI = jax.lax.Precision.HIGHEST


def _decode_kernel(q_ref, k_ref, v_ref, len_ref, bias_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, sm_scale, block_l, num_lb,
                   has_bias):
    b = pl.program_id(0)
    lb = pl.program_id(1)

    @pl.when(lb == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[b]
    # a block wholly past this sequence's live length contributes nothing
    live = lb * block_l < length

    @pl.when(live)
    def _():
        q = q_ref[:]                                   # (H, D)
        k = k_ref[:]                                   # (block_l, H, D)
        v = v_ref[:]
        # per-head contraction: batch dim H, contract D -> (H, block_l)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
            precision=_HI if q.dtype == jnp.float32 else None) * sm_scale
        if has_bias:
            s = s + bias_ref[:]                        # (1, block_l) f32
        span = lb * block_l + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(span < length, s, NEG_INF)

        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                         # (H, block_l)
        alpha = jnp.exp(m_prev - m_new)                # (H, 1)
        m_scr[:] = m_new
        l_scr[:] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        # P·V with batch dim H: (H, block_l) x (block_l, H, D) -> (H, D)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
            precision=_HI if v.dtype == jnp.float32 else None)

    @pl.when(lb == num_lb - 1)
    def _():
        l_safe = jnp.where(l_scr[:] == 0.0, 1.0, l_scr[:])
        o_ref[:] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_block_kernel(q_ref, k_ref, v_ref, len_ref, bias_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, sm_scale, block_l,
                         num_lb, kq, has_bias, causal_offset):
    b = pl.program_id(0)
    lb = pl.program_id(1)

    @pl.when(lb == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[b]
    # the furthest position ANY query in the block may read
    horizon = length + (kq if causal_offset else 0)
    live = lb * block_l < horizon

    @pl.when(live)
    def _():
        q = q_ref[:]                                   # (H, Kq, D)
        k = k_ref[:]                                   # (block_l, H, D)
        v = v_ref[:]
        # batch dim H, contract D -> (H, Kq, block_l)
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
            precision=_HI if q.dtype == jnp.float32 else None) * sm_scale
        if has_bias:
            s = s + bias_ref[:].reshape(1, 1, block_l)
        span = lb * block_l + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 2)
        if causal_offset:
            jrow = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            allowed = length + jrow + 1
        else:
            allowed = length
        s = jnp.where(span < allowed, s, NEG_INF)

        m_prev, l_prev = m_scr[:], l_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                         # (H, Kq, block_l)
        alpha = jnp.exp(m_prev - m_new)                # (H, Kq, 1)
        m_scr[:] = m_new
        l_scr[:] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        # P·V with batch dim H: (H, Kq, block_l) x (block_l, H, D)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v,
            dimension_numbers=(((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
            precision=_HI if v.dtype == jnp.float32 else None)

    @pl.when(lb == num_lb - 1)
    def _():
        l_safe = jnp.where(l_scr[:] == 0.0, 1.0, l_scr[:])
        o_ref[:] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _decode_attention_block(q, k_cache, v_cache, lengths, *, bias,
                            sm_scale, block_l, causal_offset):
    """Query-block path: q (B, Kq, H, D) -> (B, Kq, H, D)."""
    b, kq, h, d = q.shape
    max_len = k_cache.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    lengths = jnp.asarray(lengths, jnp.int32)

    align = 8 if use_interpret() else 128
    block_l = min(block_l, round_up(max_len, align))
    lp = round_up(max_len, block_l)
    hp = h if use_interpret() else round_up(h, 8)
    kqp = kq if use_interpret() else round_up(kq, 8)
    dp = d if use_interpret() else round_up(d, 64)

    # ride the query block on the sublane axis next to heads
    qt = jnp.transpose(q, (0, 2, 1, 3))                # (B, H, Kq, D)
    qq = pad_dim(pad_dim(pad_dim(qt, 1, hp), 2, kqp), 3, dp)
    kk = pad_dim(pad_dim(pad_dim(k_cache, 1, lp), 2, hp), 3, dp)
    vv = pad_dim(pad_dim(pad_dim(v_cache, 1, lp), 2, hp), 3, dp)
    num_lb = cdiv(lp, block_l)

    has_bias = bias is not None
    in_specs = [
        pl.BlockSpec((None, hp, kqp, dp), lambda i, j: (i, 0, 0, 0)),
        pl.BlockSpec((None, block_l, hp, dp), lambda i, j: (i, j, 0, 0)),
        pl.BlockSpec((None, block_l, hp, dp), lambda i, j: (i, j, 0, 0)),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    operands = [qq, kk, vv, lengths]
    if has_bias:
        bb = jax.lax.stop_gradient(
            jnp.asarray(bias, jnp.float32).reshape(b, max_len))
        bb = pad_dim(bb, 1, lp, value=NEG_INF).reshape(b, 1, lp)
        in_specs.append(pl.BlockSpec((None, 1, block_l),
                                     lambda i, j: (i, 0, j)))
        operands.append(bb)
    else:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(jnp.zeros((1,), jnp.float32))

    kernel = functools.partial(
        _decode_block_kernel, sm_scale=float(sm_scale), block_l=block_l,
        num_lb=num_lb, kq=kq, has_bias=has_bias,
        causal_offset=causal_offset)
    o = pl.pallas_call(
        kernel,
        grid=(b, num_lb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, hp, kqp, dp),
                               lambda i, j: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hp, kqp, dp), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((hp, kqp, 1), jnp.float32),
            pltpu.VMEM((hp, kqp, 1), jnp.float32),
            pltpu.VMEM((hp, kqp, dp), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(4 * b * kq * h * max_len * d),
            bytes_accessed=(kk.size + vv.size + qq.size) * q.dtype.itemsize,
            transcendentals=b * kq * h * max_len),
        interpret=use_interpret(),
    )(*operands)
    return jnp.transpose(o[:, :h, :kq, :d], (0, 2, 1, 3))


def decode_attention(q, k_cache, v_cache, lengths, *, bias=None,
                     sm_scale=None, block_l=DEFAULT_BLOCK_L,
                     causal_offset=False):
    """One-position attention against a gathered paged cache.

    q: (batch, heads, head_dim) — the single new query per sequence —
    or a (batch, Kq, heads, head_dim) query block (module docstring).
    k_cache/v_cache: (batch, max_len, heads, head_dim) gathered cache
    rows (the :func:`..kv_cache_ops.kv_cache` layout). lengths: (batch,)
    int32 live prefix per sequence — positions >= lengths[b] are masked.
    bias: optional additive (batch, max_len) f32 key bias (padding
    masks for cross-attention); constant under differentiation (the op
    has no gradient — decode is inference-only). Returns q's shape in
    q.dtype.
    """
    if q.ndim == 4:
        return _decode_attention_block(
            q, k_cache, v_cache, lengths, bias=bias, sm_scale=sm_scale,
            block_l=block_l, causal_offset=bool(causal_offset))
    b, h, d = q.shape
    max_len = k_cache.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    lengths = jnp.asarray(lengths, jnp.int32)

    align = 8 if use_interpret() else 128
    block_l = min(block_l, round_up(max_len, align))
    lp = round_up(max_len, block_l)
    hp = h if use_interpret() else round_up(h, 8)
    dp = d if use_interpret() else round_up(d, 64)

    qq = pad_dim(pad_dim(q, 1, hp), 2, dp)
    kk = pad_dim(pad_dim(pad_dim(k_cache, 1, lp), 2, hp), 3, dp)
    vv = pad_dim(pad_dim(pad_dim(v_cache, 1, lp), 2, hp), 3, dp)
    num_lb = cdiv(lp, block_l)

    has_bias = bias is not None
    in_specs = [
        pl.BlockSpec((None, hp, dp), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, block_l, hp, dp), lambda i, j: (i, j, 0, 0)),
        pl.BlockSpec((None, block_l, hp, dp), lambda i, j: (i, j, 0, 0)),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    operands = [qq, kk, vv, lengths]
    if has_bias:
        bb = jax.lax.stop_gradient(
            jnp.asarray(bias, jnp.float32).reshape(b, max_len))
        bb = pad_dim(bb, 1, lp, value=NEG_INF).reshape(b, 1, lp)
        in_specs.append(pl.BlockSpec((None, 1, block_l),
                                     lambda i, j: (i, 0, j)))
        operands.append(bb)
    else:
        # keep the kernel arity static: a zero-length dummy never read
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        operands.append(jnp.zeros((1,), jnp.float32))

    kernel = functools.partial(_decode_kernel, sm_scale=float(sm_scale),
                               block_l=block_l, num_lb=num_lb,
                               has_bias=has_bias)
    o = pl.pallas_call(
        kernel,
        grid=(b, num_lb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, hp, dp), lambda i, j: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hp, dp), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((hp, 1), jnp.float32),
            pltpu.VMEM((hp, 1), jnp.float32),
            pltpu.VMEM((hp, dp), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=int(4 * b * h * max_len * d),
            bytes_accessed=(kk.size + vv.size + qq.size) * q.dtype.itemsize,
            transcendentals=b * h * max_len),
        interpret=use_interpret(),
    )(*operands)
    return o[:, :h, :d]


def decode_attention_xla(q, k_cache, v_cache, lengths, *, bias=None,
                         sm_scale=None, block_l=DEFAULT_BLOCK_L,
                         causal_offset=False):
    """Composed-XLA lowering of the DecodeAttention op contract — the
    registry fallback (and the only implementation the cost gate picks
    off-TPU, where Pallas runs in interpret mode). Materializes the
    (B, H, L) f32 score tensor; numerically the same f32 logsumexp
    softmax as :func:`attention_xla`, so the cached decode step matches
    the naive re-forward search to float round-off."""
    if q.ndim == 4:
        b, kq, h, d = q.shape
        max_len = k_cache.shape[1]
        if sm_scale is None:
            sm_scale = 1.0 / (d ** 0.5)
        s = jnp.einsum("bqhd,blhd->bqhl", q.astype(jnp.float32),
                       k_cache.astype(jnp.float32),
                       precision=_HI) * sm_scale
        if bias is not None:
            bb = jax.lax.stop_gradient(
                jnp.asarray(bias, jnp.float32).reshape(b, max_len))
            s = s + bb[:, None, None, :]
        span = jax.lax.broadcasted_iota(
            jnp.int32, (b, kq, h, max_len), 3)
        allowed = jnp.asarray(lengths, jnp.int32)[:, None, None, None]
        if causal_offset:
            allowed = allowed + 1 + jax.lax.broadcasted_iota(
                jnp.int32, (b, kq, h, max_len), 1)
        s = jnp.where(span < allowed, s, NEG_INF)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        p = jnp.exp(s - lse[..., None])
        o = jnp.einsum("bqhl,blhd->bqhd", p,
                       v_cache.astype(jnp.float32), precision=_HI)
        return o.astype(q.dtype)
    b, h, d = q.shape
    max_len = k_cache.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bhd,blhd->bhl", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32), precision=_HI) * sm_scale
    if bias is not None:
        bb = jax.lax.stop_gradient(
            jnp.asarray(bias, jnp.float32).reshape(b, max_len))
        s = s + bb[:, None, :]
    span = jax.lax.broadcasted_iota(jnp.int32, (b, h, max_len), 2)
    s = jnp.where(span < jnp.asarray(lengths, jnp.int32)[:, None, None],
                  s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    o = jnp.einsum("bhl,blhd->bhd", p, v_cache.astype(jnp.float32),
                   precision=_HI)
    return o.astype(q.dtype)
