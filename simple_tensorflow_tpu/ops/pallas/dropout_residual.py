"""Fused dropout + bias + residual add (Pallas TPU), fwd + custom VJP.

The transformer block tail ``residual + dropout(x + bias)`` lowers today
as separate bias-add, RNG-mask, scale and add ops — four HBM round
trips over a (B, S, D) activation. This kernel streams the row blocks
once, generating the dropout mask from the same counter-based position
hash the flash-attention kernel uses (common.counter_keep_mask), so

- nothing is materialized for the backward pass (the vjp regenerates
  the mask from the seed), and
- the composed-XLA fallback (``dropout_bias_residual_reference``)
  produces bit-identical output from the same seed — the kernel
  registry can swap implementations without perturbing seeded runs.

x, residual: (rows, n); bias: (n,) or None; seed: int32 (1,).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import cdiv, counter_keep_mask, pad_dim, round_up, use_interpret

BLOCK_ROWS = 256
_VMEM_BLOCK_BUDGET = 4 * 1024 * 1024


def _keep(seed, row0, rows, n, keep_prob):
    """(rows, n) keep mask from GLOBAL row indices starting at row0."""
    rr = (row0.astype(jnp.uint32)
          + jax.lax.broadcasted_iota(jnp.uint32, (rows, n), 0))
    cc = jax.lax.broadcasted_iota(jnp.uint32, (rows, n), 1)
    return counter_keep_mask(seed, jnp.uint32(0), rr, cc, keep_prob)


def _kernel(*refs, rate, has_bias, block_rows):
    it = iter(refs)
    x_ref = next(it)
    res_ref = next(it)
    bias_ref = next(it) if has_bias else None
    seed_ref = next(it)
    o_ref = next(it)
    keep_prob = 1.0 - rate
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    if has_bias:
        x = x + bias_ref[:].astype(jnp.float32)
    rows, n = x.shape
    row0 = i * jnp.uint32(block_rows)
    keep = _keep(seed_ref[0], row0, rows, n, keep_prob)
    y = jnp.where(keep, x * (1.0 / keep_prob), 0.0)
    o_ref[:] = (res_ref[:].astype(jnp.float32) + y).astype(o_ref.dtype)


def _fwd(x, residual, bias, seed, rate, block_rows):
    rows, n = x.shape
    grid = (cdiv(rows, block_rows),)
    spec = pl.BlockSpec((block_rows, n), lambda i: (i, 0))
    in_specs = [spec, spec]
    operands = [x, residual]
    if bias is not None:
        in_specs.append(pl.BlockSpec((n,), lambda i: (0,)))
        operands.append(bias)
    in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    operands.append(seed)
    return pl.pallas_call(
        functools.partial(_kernel, rate=rate, has_bias=bias is not None,
                          block_rows=block_rows),
        grid=grid,
        in_specs=in_specs,
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((rows, n), x.dtype),
        cost_estimate=pl.CostEstimate(
            flops=4 * rows * n,
            bytes_accessed=3 * rows * n * x.dtype.itemsize,
            transcendentals=0),
        interpret=use_interpret(),
    )(*operands)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _dbr_2d(x, residual, bias, seed, rate, block_rows):
    return _fwd(x, residual, bias, seed, rate, block_rows)


def _dbr_fwd_rule(x, residual, bias, seed, rate, block_rows):
    out = _fwd(x, residual, bias, seed, rate, block_rows)
    # zero-size dtype carriers: custom-vjp residuals must be JAX types
    res = (jnp.zeros((0,), x.dtype), jnp.zeros((0,), residual.dtype),
           None if bias is None else jnp.zeros((0,), bias.dtype), seed)
    return out, res


def _dbr_bwd_rule(rate, block_rows, res, g):
    """d/dx = mask/keep ∘ g ; d/dbias = Σ_rows d/dx ; d/dres = g. The
    mask regenerates from (seed, positions) — nothing was saved."""
    x_c, res_c, bias_c, seed = res
    rows, n = g.shape
    keep_prob = 1.0 - rate
    gf = g.astype(jnp.float32)
    keep = _keep_full(seed, rows, n, keep_prob)
    dx_f = jnp.where(keep, gf * (1.0 / keep_prob), 0.0)
    dx = dx_f.astype(x_c.dtype)
    dres = g.astype(res_c.dtype)
    dbias = None if bias_c is None \
        else jnp.sum(dx_f, axis=0).astype(bias_c.dtype)
    import numpy as np

    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return dx, dres, dbias, dseed


def _keep_full(seed, rows, n, keep_prob):
    seed0 = jnp.asarray(seed, jnp.int32).reshape((-1,))[0]
    rr = jax.lax.broadcasted_iota(jnp.uint32, (rows, n), 0)
    cc = jax.lax.broadcasted_iota(jnp.uint32, (rows, n), 1)
    return counter_keep_mask(seed0, jnp.uint32(0), rr, cc, keep_prob)


_dbr_2d.defvjp(_dbr_fwd_rule, _dbr_bwd_rule)


def dropout_bias_residual(x, residual, bias=None, *, rate, seed,
                          block_rows=BLOCK_ROWS):
    """Fused ``residual + dropout(x + bias)``. x/residual: (..., n);
    bias (n,) or None; seed: int32 scalar/array. Returns x.dtype."""
    orig = x.shape
    n = orig[-1]
    rows = 1
    for s in orig[:-1]:
        rows *= s
    x2 = x.reshape(rows, n)
    r2 = residual.reshape(rows, n)
    # whole (block_rows, n) f32 rows live in VMEM: shrink for wide n
    fit = _VMEM_BLOCK_BUDGET // (max(int(n), 1) * 4)
    block_rows = max(8, min(block_rows, (fit // 8) * 8 or 8))
    block_rows = min(block_rows, round_up(rows, 8))
    rp = round_up(rows, block_rows)
    x2 = pad_dim(x2, 0, rp)
    r2 = pad_dim(r2, 0, rp)
    seed1 = jnp.asarray(seed, jnp.int32).reshape((-1,))[:1]
    out = _dbr_2d(x2, r2, bias, seed1, float(rate), int(block_rows))
    return out[:rows].reshape(orig)


def dropout_bias_residual_reference(x, residual, bias=None, *, rate, seed,
                                    block_rows=BLOCK_ROWS):
    """The stock composed-XLA lowering: identical math and identical
    counter-based mask — bit-exact with the kernel from the same seed
    (XLA fuses the chain into one elementwise pass; this is the CPU
    lowering and the registry fallback)."""
    orig = x.shape
    n = orig[-1]
    rows = 1
    for s in orig[:-1]:
        rows *= s
    keep_prob = 1.0 - rate
    xf = x.reshape(rows, n).astype(jnp.float32)
    if bias is not None:
        xf = xf + bias.astype(jnp.float32)
    keep = _keep_full(seed, rows, n, keep_prob)
    y = jnp.where(keep, xf * (1.0 / keep_prob), 0.0)
    out = (residual.reshape(rows, n).astype(jnp.float32) + y).astype(x.dtype)
    return out.reshape(orig)
