"""Int8 quantized matmul (Pallas TPU).

TPU-native counterpart of the reference's quantized matmul kernels
(ref: tensorflow/core/kernels/quantized_matmul_op.cc, quantize_op.cc —
gemmlowp on CPU). The MXU multiplies int8 natively at 2x bf16 rate;
we keep weights pre-quantized per output channel, quantize activations
per row on the fly (dynamic symmetric quantization), accumulate int32,
and dequantize with the outer product of the two scale vectors.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import cdiv, pad_dim, round_up, use_interpret

TILE_M = 128
TILE_N = 128


def quantize_rowwise(x):
    """Symmetric per-row int8 quantization: returns (q, scale)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale[..., 0]


def quantize_colwise(w):
    """Symmetric per-output-channel int8 quantization of a (k, n) weight."""
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=0, keepdims=True)
    scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return q, scale[0]


def _qmm_kernel(n_kb, xq_ref, wq_ref, xs_ref, ws_ref, o_ref, acc_scr):
    # Operands stay s8: Mosaic lowers s8 x s8 -> s32 onto the MXU's native
    # int8 path (2x bf16 rate); widening to i32 first produces an i32
    # matmul Mosaic rejects ("Bad lhs/rhs type: vector<...xi32>").
    # The contraction streams in TILE_K blocks (innermost grid dim) with an
    # int32 VMEM accumulator — full-k strips bust the 16 MB scoped budget
    # for large k.
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    acc_scr[:] += jax.lax.dot_general(
        xq_ref[:], wq_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)          # (tm, tn)

    @pl.when(kb == n_kb - 1)
    def _():
        scale = xs_ref[:] * ws_ref[:]              # (tm,1)*(1,tn)->(tm,tn)
        o_ref[:] = (acc_scr[:].astype(jnp.float32)
                    * scale).astype(o_ref.dtype)


TILE_K = 1024


def quant_matmul(x, wq, w_scale, *, out_dtype=None):
    """x @ dequant(wq) with int8 MXU accumulation.

    x: (m, k) float; wq: (k, n) int8; w_scale: (n,) f32.
    """
    if out_dtype is None:
        out_dtype = x.dtype
    m, k = x.shape
    n = wq.shape[1]
    xq, x_scale = quantize_rowwise(x)

    # int8 tiles are (32, 128); pad every dim (zero contraction columns are
    # exact no-ops in the int32 accumulation).
    mp, np_ = round_up(m, TILE_M), round_up(n, TILE_N)
    # k pads to a multiple of tile_k: a ragged final k-block would
    # accumulate out-of-bounds garbage (no in-kernel contraction mask)
    tile_k = min(TILE_K, round_up(k, 8 if use_interpret() else 128))
    kp = round_up(k, tile_k)
    xq = pad_dim(pad_dim(xq, 0, mp), 1, kp)
    x_scale = pad_dim(x_scale.reshape(m, 1), 0, mp)
    wq = pad_dim(pad_dim(wq, 0, kp), 1, np_)
    w_scale = pad_dim(w_scale.reshape(1, n), 1, np_)
    k = kp
    n_kb = cdiv(k, tile_k)

    out = pl.pallas_call(
        functools.partial(_qmm_kernel, n_kb),
        grid=(cdiv(mp, TILE_M), cdiv(np_, TILE_N), n_kb),
        in_specs=[
            pl.BlockSpec((TILE_M, tile_k), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((tile_k, TILE_N), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((TILE_M, 1), lambda i, j, kk: (i, 0)),
            pl.BlockSpec((1, TILE_N), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((TILE_M, TILE_N), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        scratch_shapes=[pltpu.VMEM((TILE_M, TILE_N), jnp.int32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * mp * np_ * k,
            # Grid (i, j, kk), kk innermost. A block is re-fetched when its
            # index changes between consecutive iterations: xq (i,kk) cycles
            # per j → mp*k s8 bytes × n_j; wq (kk,j) changes every step →
            # k*np_ × n_i; x_scale (i,0) only on i change → mp f32 once;
            # w_scale (0,j) on j change → np_ f32 × n_i.
            bytes_accessed=(mp * k * cdiv(np_, TILE_N)
                            + k * np_ * cdiv(mp, TILE_M)
                            + mp * 4 + np_ * 4 * cdiv(mp, TILE_M)
                            + mp * np_ * 4),
            transcendentals=0),
        interpret=use_interpret(),
    )(xq, wq, x_scale.astype(jnp.float32), w_scale.astype(jnp.float32))
    return out[:m, :n]


@jax.custom_vjp
def quant_matmul_ste(x, wq, w_scale):
    """quant_matmul with a straight-through gradient for x: the rounding in
    the activation quantizer has zero derivative almost everywhere, so
    d/dx is taken through the dequantized matmul x @ (wq * w_scale).
    This is the op the graph registers — differentiable training works."""
    return quant_matmul(x, wq, w_scale)


def _qmm_ste_fwd(x, wq, w_scale):
    return quant_matmul(x, wq, w_scale), (x, wq, w_scale)


def _qmm_ste_bwd(res, g):
    x, wq, w_scale = res
    gf = g.astype(jnp.float32)
    wd = wq.astype(jnp.float32) * w_scale[None, :].astype(jnp.float32)
    dx = (gf @ wd.T).astype(x.dtype)
    d_wq = np.zeros(wq.shape, dtype=jax.dtypes.float0)  # int8: no tangent
    # y[m,n] = acc[m,n] * x_scale[m] * w_scale[n]  (acc = xq @ wq, int32)
    # => d w_scale[n] = sum_m g[m,n] * acc[m,n] * x_scale[m]
    xq, x_scale = quantize_rowwise(x)
    acc = jnp.dot(xq.astype(jnp.int32), wq.astype(jnp.int32))
    d_scale = jnp.sum(gf * acc.astype(jnp.float32)
                      * x_scale[:, None].astype(jnp.float32), axis=0
                      ).astype(w_scale.dtype)
    return dx, d_wq, d_scale


quant_matmul_ste.defvjp(_qmm_ste_fwd, _qmm_ste_bwd)


def quant_matmul_reference(x, wq, w_scale, *, out_dtype=None):
    if out_dtype is None:
        out_dtype = x.dtype
    xq, x_scale = quantize_rowwise(x)
    acc = jnp.dot(xq.astype(jnp.int32), wq.astype(jnp.int32))
    return (acc.astype(jnp.float32)
            * x_scale[:, None] * w_scale[None, :]).astype(out_dtype)


@jax.custom_vjp
def quant_matmul_ste_reference(x, wq, w_scale):
    """The stock-XLA lowering of the QuantMatMul op contract: same
    dynamic row quantization and int32 accumulation as the Pallas
    kernel, as a plain jnp dot (XLA picks the layout), with the
    IDENTICAL straight-through vjp — the kernel registry's fallback."""
    return quant_matmul_reference(x, wq, w_scale)


def _qmm_ref_fwd(x, wq, w_scale):
    return quant_matmul_reference(x, wq, w_scale), (x, wq, w_scale)


quant_matmul_ste_reference.defvjp(_qmm_ref_fwd, _qmm_ste_bwd)
