"""Fused LayerNorm (Pallas TPU), forward + custom-VJP backward.

The reference computes layer norm from unfused mean/var/mul/add graph nodes
(there is no fused LN kernel in TF-1.0; batch-norm has one,
ref: tensorflow/core/kernels/fused_batch_norm_op.cc — this is the layer-norm
analogue done the TPU way). One VMEM-resident pass per row block computes
mean, variance, normalisation and the affine transform; backward fuses the
three reduction terms of d_x and accumulates d_gamma/d_beta into a single
VMEM-resident tile across the sequential TPU grid.

x: (..., features) — flattened to (rows, features). f32 statistics
regardless of input dtype (bf16-safe). Row stats are (rows, 1) tiles
(Mosaic-legal shapes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .common import cdiv, pad_dim, round_up, use_interpret

DEFAULT_BLOCK_ROWS = 256


def _fwd_kernel(x_ref, g_ref, b_ref, o_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)             # (br, 1)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd
    o_ref[:] = (y * g_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, do_ref,
                dx_ref, dg_ref, db_ref):
    x = x_ref[:].astype(jnp.float32)
    gamma = g_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    mean = mean_ref[:]                                     # (br, 1)
    rstd = rstd_ref[:]

    xhat = (x - mean) * rstd
    wdo = do * gamma
    c1 = jnp.mean(wdo, axis=-1, keepdims=True)
    c2 = jnp.mean(wdo * xhat, axis=-1, keepdims=True)
    dx = (wdo - c1 - xhat * c2) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)

    # d_gamma/d_beta accumulate across the sequential grid into one
    # VMEM-resident (1, n) tile (same output block for every program).
    @pl.when(pl.program_id(0) == 0)
    def _():
        dg_ref[:] = jnp.zeros_like(dg_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    dg_ref[:] += jnp.sum(do * xhat, axis=0, keepdims=True)
    db_ref[:] += jnp.sum(do, axis=0, keepdims=True)


def _fwd(x, gamma, beta, eps, block_rows):
    rows, n = x.shape
    grid = (cdiv(rows, block_rows),)
    o, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, n), lambda i: (i, 0)),
            pl.BlockSpec((n,), lambda i: (0,)),
            pl.BlockSpec((n,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, n), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, n), x.dtype),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=use_interpret(),
    )(x, gamma, beta)
    return o, mean, rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _layer_norm_2d(x, gamma, beta, eps, block_rows):
    o, _, _ = _fwd(x, gamma, beta, eps, block_rows)
    return o


def _ln_fwd_rule(x, gamma, beta, eps, block_rows):
    o, mean, rstd = _fwd(x, gamma, beta, eps, block_rows)
    return o, (x, gamma, beta, mean, rstd)


def _ln_bwd_rule(eps, block_rows, res, g):
    x, gamma, beta, mean, rstd = res
    rows, n = x.shape
    nblocks = cdiv(rows, block_rows)
    dx, dg, db = pl.pallas_call(
        _bwd_kernel,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((block_rows, n), lambda i: (i, 0)),
            pl.BlockSpec((n,), lambda i: (0,)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, n), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, n), x.dtype),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        interpret=use_interpret(),
    )(x, gamma, mean, rstd, g)
    return dx, dg[0].astype(gamma.dtype), db[0].astype(beta.dtype)


_layer_norm_2d.defvjp(_ln_fwd_rule, _ln_bwd_rule)


_VMEM_BLOCK_BUDGET = 4 * 1024 * 1024  # bytes per (block_rows, n) f32 tile


def layer_norm(x, gamma, beta, *, eps=1e-6, block_rows=DEFAULT_BLOCK_ROWS):
    """Fused layer norm over the last axis. gamma/beta: (features,)."""
    orig_shape = x.shape
    n = orig_shape[-1]
    rows = 1
    for s in orig_shape[:-1]:
        rows *= s
    x2 = x.reshape(rows, n)
    # the kernel holds whole (block_rows, n) rows in VMEM (f32 math,
    # double-buffered): shrink block_rows for very wide features so the
    # tile stays inside the ~16 MB scoped budget (n=16384 at the default
    # 256 rows would be a 16 MB tile — the same OOM class the xent kernel
    # hit at BERT vocab width)
    fit = _VMEM_BLOCK_BUDGET // (int(n) * 4)
    block_rows = max(8, min(block_rows, (fit // 8) * 8 or 8))
    block_rows = min(block_rows, round_up(rows, 8))
    rp = round_up(rows, block_rows)
    x2 = pad_dim(x2, 0, rp)
    o = _layer_norm_2d(x2, gamma, beta, float(eps), int(block_rows))
    return o[:rows].reshape(orig_shape)


def layer_norm_reference(x, gamma, beta, *, eps=1e-6):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma.astype(jnp.float32)
            + beta.astype(jnp.float32)).astype(x.dtype)
