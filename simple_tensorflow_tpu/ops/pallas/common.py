"""Shared helpers for the Pallas TPU kernels.

These kernels replace the reference's hand-written CUDA kernels
(ref: tensorflow/core/kernels/*_gpu.cu.cc) with Mosaic/Pallas programs tiled
for the MXU/VPU. On non-TPU backends (the CPU test mesh) every kernel runs
in interpret mode, so numerics tests are backend-independent.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.lru_cache(None)
def use_interpret() -> bool:
    """Pallas compiles natively only on TPU; interpret elsewhere."""
    return jax.default_backend() != "tpu"


def round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def pad_dim(x, dim: int, target: int, value=0.0):
    """Zero-pad dimension ``dim`` of x up to ``target`` (no-op if equal)."""
    cur = x.shape[dim]
    if cur == target:
        return x
    pads = [(0, 0)] * x.ndim
    pads[dim] = (0, target - cur)
    return jnp.pad(x, pads, constant_values=value)


NEG_INF = -1e30  # finite "minus infinity" — avoids NaN from (-inf) - (-inf)


def mix32(h):
    """murmur3 finalizer: avalanche a uint32 value (vectorized)."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def keep_threshold(keep_prob: float):
    """uint32 compare threshold for a counter-based keep mask."""
    return jnp.uint32(min(int(keep_prob * 4294967296.0), 4294967295))


def counter_keep_mask(seed, salt, rows, cols, keep_prob):
    """Deterministic dropout keep-mask from GLOBAL (row, col) indices.

    Counter-based: hash(seed, salt, row, col) — the mask is a pure
    function of positions, so a blocked Pallas kernel and a composed
    XLA lowering regenerate it bit-identically from the same seed (the
    kernel-registry swap contract), and backward passes replay it
    without materializing anything in HBM. Plain uint32 arithmetic (not
    pltpu.prng_*) so interpret mode runs the identical code path.

    seed/salt: uint32-castable scalars; rows/cols: broadcastable uint32
    index arrays.
    """
    # every term stays uint32 explicitly: mixing in an int32 scalar would
    # silently promote-then-clamp the whole chain back to int32 (x64 off),
    # and an int32 < uint32 compare wraps the threshold negative.
    h0 = mix32(seed.astype(jnp.uint32)
               ^ (salt.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)))
    h = mix32(h0 ^ rows.astype(jnp.uint32))
    h = mix32(h ^ cols.astype(jnp.uint32))
    return h.astype(jnp.uint32) < keep_threshold(keep_prob)
