"""Shared helpers for the Pallas TPU kernels.

These kernels replace the reference's hand-written CUDA kernels
(ref: tensorflow/core/kernels/*_gpu.cu.cc) with Mosaic/Pallas programs tiled
for the MXU/VPU. On non-TPU backends (the CPU test mesh) every kernel runs
in interpret mode, so numerics tests are backend-independent.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.lru_cache(None)
def use_interpret() -> bool:
    """Pallas compiles natively only on TPU; interpret elsewhere."""
    return jax.default_backend() != "tpu"


def round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def pad_dim(x, dim: int, target: int, value=0.0):
    """Zero-pad dimension ``dim`` of x up to ``target`` (no-op if equal)."""
    cur = x.shape[dim]
    if cur == target:
        return x
    pads = [(0, 0)] * x.ndim
    pads[dim] = (0, target - cur)
    return jnp.pad(x, pads, constant_values=value)


NEG_INF = -1e30  # finite "minus infinity" — avoids NaN from (-inf) - (-inf)
