"""Fused optimizer updates over flattened parameter groups (Pallas TPU).

The reference applies one ApplyAdam/ApplyMomentum kernel per variable
(ref: tensorflow/core/kernels/training_ops.cc) — a long tail of small
launches after every backward pass. Here the optimizer tier concatenates
every same-dtype parameter into ONE flat vector per group and updates
m/v/param in a single blocked elementwise kernel: one pass over four HBM
streams (g, m, v, p) instead of a per-variable chain of a dozen ops
each. The same math is exposed as a plain-jnp "reference" closure — the
stock XLA lowering the kernel registry falls back to (and the CPU path,
where XLA fuses the closure into a few vectorized passes: the fused win
on CPU comes from collapsing the per-variable op tail, not from Pallas).

Math is kept op-for-op identical to the per-variable _apply_dense chains
in train/optimizers.py (same constant formation, same multiply/divide
order), so fused and per-variable training trajectories are bit-exact —
pinned by tests/test_kernel_registry.py.

Inputs are 1-D flat vectors: p (param dtype), m/v/g (update dtype, f32
for low-precision params), plus the traced scalar hyperparameters. The
wrapper pads to (rows, 128) VPU lanes; padded elements compute garbage
that is sliced off.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import cdiv, pad_dim, round_up, use_interpret

LANES = 128
BLOCK_ROWS = 256


# ---------------------------------------------------------------------------
# Adam: new_m = b1*m + (1-b1)*g ; new_v = b2*v + (1-b2)*g^2 ;
#       new_p = p - (alpha*new_m/(sqrt(new_v)+eps)) cast to p.dtype
# ---------------------------------------------------------------------------

def adam_update_reference(p, m, v, g, alpha, *, beta1, beta2, eps):
    """The fused XLA closure (stock lowering): identical math to the
    per-variable chain, over the flat group."""
    ud = m.dtype
    b1 = jnp.asarray(beta1, ud)
    b2 = jnp.asarray(beta2, ud)
    e = jnp.asarray(eps, ud)
    new_m = b1 * m + (1 - b1) * g
    new_v = b2 * v + (1 - b2) * jnp.square(g)
    upd = alpha.astype(ud) * new_m / (jnp.sqrt(new_v) + e)
    new_p = p - upd.astype(p.dtype)
    return new_p, new_m, new_v


def _adam_kernel(p_ref, m_ref, v_ref, g_ref, alpha_ref,
                 np_ref, nm_ref, nv_ref, *, beta1, beta2, eps):
    ud = m_ref.dtype
    b1 = jnp.asarray(beta1, ud)
    b2 = jnp.asarray(beta2, ud)
    e = jnp.asarray(eps, ud)
    g = g_ref[:]
    new_m = b1 * m_ref[:] + (1 - b1) * g
    new_v = b2 * v_ref[:] + (1 - b2) * jnp.square(g)
    upd = alpha_ref[0].astype(ud) * new_m / (jnp.sqrt(new_v) + e)
    np_ref[:] = p_ref[:] - upd.astype(np_ref.dtype)
    nm_ref[:] = new_m
    nv_ref[:] = new_v


def _flat_2d(x, rows, cols):
    return pad_dim(x, 0, rows * cols).reshape(rows, cols)


def _grid_shapes(n):
    cols = LANES
    rows = cdiv(n, cols)
    block = min(BLOCK_ROWS, round_up(rows, 8))
    rows = round_up(rows, block)
    return rows, cols, block


def adam_update(p, m, v, g, alpha, *, beta1, beta2, eps):
    """Pallas fused Adam over a flat group; one kernel for m/v/param."""
    n = p.shape[0]
    rows, cols, block = _grid_shapes(n)
    p2 = _flat_2d(p, rows, cols)
    m2 = _flat_2d(m, rows, cols)
    v2 = _flat_2d(v, rows, cols)
    g2 = _flat_2d(g, rows, cols)
    alpha1 = jnp.asarray(alpha, m.dtype).reshape((1,))
    spec = pl.BlockSpec((block, cols), lambda i: (i, 0))
    np_, nm, nv = pl.pallas_call(
        functools.partial(_adam_kernel, beta1=float(beta1),
                          beta2=float(beta2), eps=float(eps)),
        grid=(rows // block,),
        in_specs=[spec, spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec, spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, cols), p.dtype),
            jax.ShapeDtypeStruct((rows, cols), m.dtype),
            jax.ShapeDtypeStruct((rows, cols), v.dtype),
        ],
        cost_estimate=pl.CostEstimate(
            flops=12 * n,
            bytes_accessed=(p.size * p.dtype.itemsize * 2
                            + 5 * m.size * m.dtype.itemsize),
            transcendentals=n),
        interpret=use_interpret(),
    )(p2, m2, v2, g2, alpha1)
    return (np_.reshape(-1)[:n], nm.reshape(-1)[:n], nv.reshape(-1)[:n])


# ---------------------------------------------------------------------------
# Momentum: new_acc = mu*acc + g ;
#           upd = lr*(g + mu*new_acc) (nesterov) | lr*new_acc ;
#           new_p = p - upd cast to p.dtype
# ---------------------------------------------------------------------------

def momentum_update_reference(p, acc, g, lr, mu, *, use_nesterov=False):
    ud = acc.dtype
    new_acc = mu.astype(ud) * acc + g
    if use_nesterov:
        upd = lr.astype(ud) * (g + mu.astype(ud) * new_acc)
    else:
        upd = lr.astype(ud) * new_acc
    new_p = p - upd.astype(p.dtype)
    return new_p, new_acc


def _momentum_kernel(p_ref, acc_ref, g_ref, lr_ref, mu_ref,
                     np_ref, nacc_ref, *, use_nesterov):
    ud = acc_ref.dtype
    g = g_ref[:]
    mu = mu_ref[0].astype(ud)
    new_acc = mu * acc_ref[:] + g
    if use_nesterov:
        upd = lr_ref[0].astype(ud) * (g + mu * new_acc)
    else:
        upd = lr_ref[0].astype(ud) * new_acc
    np_ref[:] = p_ref[:] - upd.astype(np_ref.dtype)
    nacc_ref[:] = new_acc


def momentum_update(p, acc, g, lr, mu, *, use_nesterov=False):
    """Pallas fused Momentum over a flat group."""
    n = p.shape[0]
    rows, cols, block = _grid_shapes(n)
    p2 = _flat_2d(p, rows, cols)
    a2 = _flat_2d(acc, rows, cols)
    g2 = _flat_2d(g, rows, cols)
    lr1 = jnp.asarray(lr, acc.dtype).reshape((1,))
    mu1 = jnp.asarray(mu, acc.dtype).reshape((1,))
    spec = pl.BlockSpec((block, cols), lambda i: (i, 0))
    np_, nacc = pl.pallas_call(
        functools.partial(_momentum_kernel,
                          use_nesterov=bool(use_nesterov)),
        grid=(rows // block,),
        in_specs=[spec, spec, spec,
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=[spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((rows, cols), p.dtype),
            jax.ShapeDtypeStruct((rows, cols), acc.dtype),
        ],
        cost_estimate=pl.CostEstimate(
            flops=6 * n,
            bytes_accessed=(p.size * p.dtype.itemsize * 2
                            + 3 * acc.size * acc.dtype.itemsize),
            transcendentals=0),
        interpret=use_interpret(),
    )(p2, a2, g2, lr1, mu1)
    return (np_.reshape(-1)[:n], nacc.reshape(-1)[:n])
