"""Session handles: keep fetched tensors DEVICE-resident across
``Session.run`` calls (ref: python/ops/session_ops.py:58
``get_session_handle``, :155 ``get_session_tensor``,
core/kernels/session_ops.cc).

On TPU this matters more than on the reference's hardware: HBM is
~819 GB/s while the host link is PCIe-class, so a fetch→feed round trip
through host numpy costs two slow transfers. A handle pins the jax.Array
in the Session's handle store; feeding it back routes through the
device-resident feed path (zero host copies — provable with the L0
transfer guard in "disallow" mode).

Staging: ``GetSessionHandle`` of a device tensor runs in the post-host
stage and receives the RAW device array (the Session skips numpy
conversion for its inputs); ``GetSessionTensor`` runs pre-host, resolves
the handle string, and its output crosses the boundary as an
already-on-device feed.
"""

from __future__ import annotations

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import errors
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod


class TensorHandle:
    """Handle to a device-resident tensor (ref: session_ops.py:35
    ``class TensorHandle``)."""

    def __init__(self, handle_str, dtype, session):
        self._handle = handle_str
        self._dtype = dtype
        self._session = session

    @property
    def handle(self):
        return self._handle

    @property
    def dtype(self):
        return self._dtype

    def __str__(self):
        return self._handle

    def __repr__(self):
        return f"<TensorHandle {self._handle}>"

    def eval(self):
        """Fetch the handle's value to host numpy (explicitly — this is
        the one deliberate host transfer)."""
        return np.asarray(self._session._handle_value(self._handle))

    def delete(self):
        self._session._delete_handle(self._handle)


def get_session_handle(data, name=None):
    """Return a tensor that, when fetched, pins ``data`` in the session's
    device-resident handle store and evaluates to a TensorHandle (ref:
    session_ops.py:58)."""
    data = ops_mod.convert_to_tensor(data)
    g = ops_mod.get_default_graph()
    op = g.create_op("GetSessionHandle", [data],
                     attrs={"dtype": data.dtype},
                     name=name or "GetSessionHandle",
                     output_specs=[(shape_mod.scalar(),
                                    dtypes_mod.string)])
    return op.outputs[0]


def get_session_tensor(handle, dtype, name=None):
    """(holder, tensor) pair: feed a handle string into ``holder`` and
    ``tensor`` evaluates to the stored device array — without a host
    round trip (ref: session_ops.py:155)."""
    from . import array_ops

    dt = dtypes_mod.as_dtype(dtype)
    holder = array_ops.placeholder(dtypes_mod.string, shape=(),
                                   name=(name or "session_tensor")
                                   + "_holder")
    g = ops_mod.get_default_graph()
    op = g.create_op("GetSessionTensor", [holder], attrs={"dtype": dt},
                     name=name or "GetSessionTensor",
                     output_specs=[(shape_mod.TensorShape(None), dt)])
    return holder, op.outputs[0]


def delete_session_tensor(handle=None, name=None):
    """(holder, deleter) pair: feed a handle string into ``holder`` and
    run ``deleter`` to free the stored array (ref: session_ops.py:237 —
    its ``handle`` argument only selects a device; accepted and unused
    here, the session owns all handles)."""
    from . import array_ops

    holder = array_ops.placeholder(dtypes_mod.string, shape=(),
                                   name=(name or "delete_session_tensor")
                                   + "_holder")
    g = ops_mod.get_default_graph()
    deleter = g.create_op("DeleteSessionTensor", [holder], attrs={},
                          name=name or "DeleteSessionTensor",
                          output_specs=[])
    return holder, deleter


def _session_of(ctx):
    sess = getattr(ctx, "session", None)
    if sess is None:
        raise errors.InternalError(
            None, None, "session handle ops require a Session context")
    return sess


def _lower_get_handle(ctx, op, inputs):
    sess = _session_of(ctx)
    val = inputs[0]
    if isinstance(val, np.ndarray) and val.dtype != object:
        # value arrived on the host (const-folded / pre-host source):
        # pin it in HBM anyway so every numeric handle is device-resident
        import jax

        val = jax.device_put(val)
    handle = sess._register_handle(val, op.attrs["dtype"])
    return [np.asarray(handle, dtype=object)]


def _lower_get_tensor(ctx, op, inputs):
    sess = _session_of(ctx)
    return [sess._handle_value(_handle_str(inputs[0]))]


def _lower_delete(ctx, op, inputs):
    _session_of(ctx)._delete_handle(_handle_str(inputs[0]))
    return []


def _handle_str(x):
    if isinstance(x, TensorHandle):
        return x.handle
    if isinstance(x, np.ndarray):
        x = x.item() if x.ndim == 0 else x.reshape(-1)[0]
    if isinstance(x, bytes):
        return x.decode()
    return str(x)


op_registry.register("GetSessionHandle", lower=_lower_get_handle,
                     is_stateful=True, runs_on_host=True, n_outputs=1)
op_registry.register("GetSessionTensor", lower=_lower_get_tensor,
                     is_stateful=True, runs_on_host=True, n_outputs=1)
op_registry.register("DeleteSessionTensor", lower=_lower_delete,
                     is_stateful=True, runs_on_host=True, n_outputs=0)


get_session_handle_v2 = get_session_handle  # ref raw-op alias


# declared effect sets (stf.analysis)
op_registry.declare_effects("GetSessionHandle", op_registry.Effects(io=True))
op_registry.declare_effects("GetSessionTensor", op_registry.Effects(io=True))
op_registry.declare_effects("DeleteSessionTensor", op_registry.Effects(io=True))
