"""Random ops (ref: tensorflow/python/ops/random_ops.py,
core/kernels/random_op.cc — Philox stateful kernels).

TPU-native: no mutable Philox state. Each op folds a stable per-op stream id
(framework/random_seed.py) into the per-step root key the Session advances —
stateful-looking API, functional keys underneath, reproducible under
set_random_seed, and safe under jax.vjp forward replay (same draw both
times, so dropout masks agree between forward and backward).
"""

from __future__ import annotations

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import random_seed as random_seed_mod
from ..framework import tensor_shape as shape_mod
from ..framework import constant_op
from .op_util import make_op


def _static_shape(shape):
    from . import array_ops

    return array_ops._static_shape_arg(shape, "random op")


def _rand_op(op_type, shape, dtype, seed, name, extra=None, inputs=()):
    g = ops_mod.get_default_graph()
    graph_seed, op_seed = random_seed_mod.get_seed(seed)
    dt = dtypes_mod.as_dtype(dtype)
    sh = _static_shape(shape)
    attrs = {"shape": sh, "dtype": dt, "seed": op_seed,
             "_graph_seed": graph_seed}
    attrs.update(extra or {})
    op = g.create_op(op_type, list(inputs), attrs=attrs, name=name or op_type,
                     output_specs=[(shape_mod.TensorShape(list(sh)), dt)])
    return op.outputs[0]


def _lower_random(sample_fn):
    def lower(ctx, op, inputs):
        key = ctx.rng_for(op)
        return [sample_fn(key, op, inputs)]

    return lower


def _ru(key, op, inputs):
    import jax

    a = op.attrs
    dt = a["dtype"].np_dtype
    if a["dtype"].is_integer:
        return jax.random.randint(key, a["shape"], a["minval"], a["maxval"],
                                  dtype=dt)
    u = jax.random.uniform(key, a["shape"], dtype=np.float32,
                           minval=a["minval"], maxval=a["maxval"])
    return u.astype(dt)


def _rn(key, op, inputs):
    import jax

    a = op.attrs
    x = jax.random.normal(key, a["shape"], dtype=np.float32)
    return (x * a["stddev"] + a["mean"]).astype(a["dtype"].np_dtype)


def _tn(key, op, inputs):
    import jax

    a = op.attrs
    x = jax.random.truncated_normal(key, -2.0, 2.0, a["shape"], np.float32)
    return (x * a["stddev"] + a["mean"]).astype(a["dtype"].np_dtype)


def _shuffle(key, op, inputs):
    import jax

    return jax.random.permutation(key, inputs[0], axis=0)


def _multinomial(key, op, inputs):
    import jax

    logits = inputs[0]
    n = op.attrs["num_samples"]
    return jax.random.categorical(key, logits, axis=-1,
                                  shape=(logits.shape[0], n)).astype(
        op.attrs["output_dtype"].np_dtype)


def _gamma(key, op, inputs):
    import jax

    a = op.attrs
    alpha = inputs[0]
    sample_shape = tuple(a["shape"]) + tuple(np.shape(alpha))
    g = jax.random.gamma(key, alpha, shape=sample_shape, dtype=np.float32)
    return (g / a.get("beta", 1.0)).astype(a["dtype"].np_dtype)


def _poisson(key, op, inputs):
    import jax

    a = op.attrs
    lam = inputs[0]
    sample_shape = tuple(a["shape"]) + tuple(np.shape(lam))
    return jax.random.poisson(key, lam, shape=sample_shape).astype(
        a["dtype"].np_dtype)


op_registry.register("RandomUniform", lower=_lower_random(_ru), is_stateful=True)
op_registry.register("RandomStandardNormal", lower=_lower_random(_rn),
                     is_stateful=True)
op_registry.register("TruncatedNormal", lower=_lower_random(_tn),
                     is_stateful=True)
op_registry.register("RandomShuffle", lower=_lower_random(_shuffle),
                     is_stateful=True)
op_registry.register("Multinomial", lower=_lower_random(_multinomial),
                     is_stateful=True)
op_registry.register("RandomGamma", lower=_lower_random(_gamma),
                     is_stateful=True)
op_registry.register("RandomPoisson", lower=_lower_random(_poisson),
                     is_stateful=True)


# -- public API --------------------------------------------------------------

def random_uniform(shape, minval=0, maxval=None, dtype=dtypes_mod.float32,
                   seed=None, name=None):
    dt = dtypes_mod.as_dtype(dtype)
    if maxval is None:
        if dt.is_integer:
            raise ValueError("Must specify maxval for integer random_uniform")
        maxval = 1.0
    return _rand_op("RandomUniform", shape, dt, seed, name,
                    extra={"minval": minval, "maxval": maxval})


def random_normal(shape, mean=0.0, stddev=1.0, dtype=dtypes_mod.float32,
                  seed=None, name=None):
    return _rand_op("RandomStandardNormal", shape, dtype, seed, name,
                    extra={"mean": float(mean), "stddev": float(stddev)})


def truncated_normal(shape, mean=0.0, stddev=1.0, dtype=dtypes_mod.float32,
                     seed=None, name=None):
    return _rand_op("TruncatedNormal", shape, dtype, seed, name,
                    extra={"mean": float(mean), "stddev": float(stddev)})


def random_shuffle(value, seed=None, name=None):
    value = ops_mod.convert_to_tensor(value)
    g = ops_mod.get_default_graph()
    graph_seed, op_seed = random_seed_mod.get_seed(seed)
    op = g.create_op("RandomShuffle", [value],
                     attrs={"seed": op_seed, "_graph_seed": graph_seed},
                     name=name or "RandomShuffle",
                     output_specs=[(value.shape, value.dtype)])
    return op.outputs[0]


def multinomial(logits, num_samples, seed=None, name=None,
                output_dtype=dtypes_mod.int64):
    logits = ops_mod.convert_to_tensor(logits)
    g = ops_mod.get_default_graph()
    graph_seed, op_seed = random_seed_mod.get_seed(seed)
    n = int(constant_op.constant_value(ops_mod.convert_to_tensor(num_samples)))
    batch = logits.shape[0].value
    op = g.create_op("Multinomial", [logits],
                     attrs={"num_samples": n, "seed": op_seed,
                            "_graph_seed": graph_seed,
                            "output_dtype": dtypes_mod.as_dtype(output_dtype)},
                     name=name or "Multinomial",
                     output_specs=[(shape_mod.TensorShape([batch, n]),
                                    dtypes_mod.as_dtype(output_dtype))])
    return op.outputs[0]


def random_gamma(shape, alpha, beta=None, dtype=dtypes_mod.float32, seed=None,
                 name=None):
    alpha_t = ops_mod.convert_to_tensor(alpha, dtype=dtype)
    g = ops_mod.get_default_graph()
    graph_seed, op_seed = random_seed_mod.get_seed(seed)
    sh = _static_shape(shape)
    out_shape = list(sh) + (alpha_t.shape.as_list() if alpha_t.shape.rank else [])
    op = g.create_op("RandomGamma", [alpha_t],
                     attrs={"shape": sh, "dtype": dtypes_mod.as_dtype(dtype),
                            "beta": float(beta) if beta is not None else 1.0,
                            "seed": op_seed, "_graph_seed": graph_seed},
                     name=name or "RandomGamma",
                     output_specs=[(shape_mod.TensorShape(out_shape),
                                    dtypes_mod.as_dtype(dtype))])
    return op.outputs[0]


def random_poisson(lam, shape, dtype=dtypes_mod.float32, seed=None, name=None):
    lam_t = ops_mod.convert_to_tensor(lam, dtype=dtypes_mod.float32)
    g = ops_mod.get_default_graph()
    graph_seed, op_seed = random_seed_mod.get_seed(seed)
    sh = _static_shape(shape)
    out_shape = list(sh) + (lam_t.shape.as_list() if lam_t.shape.rank else [])
    op = g.create_op("RandomPoisson", [lam_t],
                     attrs={"shape": sh, "dtype": dtypes_mod.as_dtype(dtype),
                            "seed": op_seed, "_graph_seed": graph_seed},
                     name=name or "RandomPoisson",
                     output_specs=[(shape_mod.TensorShape(out_shape),
                                    dtypes_mod.as_dtype(dtype))])
    return op.outputs[0]


def random_crop(value, size, seed=None, name=None):
    from . import array_ops

    value = ops_mod.convert_to_tensor(value)
    sh = value.shape.as_list()
    size = _static_shape(size)
    limits = [s - c for s, c in zip(sh, size)]
    offsets = [random_uniform([], 0, l + 1, dtype=dtypes_mod.int32, seed=seed)
               if l > 0 else constant_op.constant(0) for l in limits]
    # Static crop via dynamic_slice lowering: use gather-based strided slice.
    g = ops_mod.get_default_graph()
    op = g.create_op("DynamicSliceCrop", [value] + offsets,
                     attrs={"size": tuple(size)},
                     name=name or "random_crop",
                     output_specs=[(shape_mod.TensorShape(list(size)),
                                    value.dtype)])
    return op.outputs[0]


def _lower_dyn_crop(ctx, op, inputs):
    import jax

    x = inputs[0]
    offsets = inputs[1:]
    return [jax.lax.dynamic_slice(x, offsets, op.attrs["size"])]


op_registry.register("DynamicSliceCrop", lower=_lower_dyn_crop)


set_random_seed = random_seed_mod.set_random_seed


# declared effect sets (stf.analysis): every sampler draws from the
# per-step PRNG stream — never CSE'd/folded, flagged by lint when
# unseeded, invisible to the variable-hazard detector (no resources)
for _rng_op in ("RandomUniform", "RandomStandardNormal", "TruncatedNormal",
                "RandomShuffle", "Multinomial", "RandomGamma",
                "RandomPoisson"):
    op_registry.declare_effects(_rng_op, op_registry.Effects(rng=True))
