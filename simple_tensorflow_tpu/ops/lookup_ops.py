"""Lookup tables (ref: tensorflow/python/ops/lookup_ops-era API surface:
HashTable & friends registered in core/ops/data_flow_ops.cc:1969
``REGISTER_OP("HashTable")``, ``:1845 LookupTableFind``, kernels in
core/kernels/lookup_table_op.cc; python wrappers
contrib/lookup/lookup_ops.py in the 1.0 tree).

TPU-native split:

- Tables are HOST objects (the reference pins lookup kernels to CPU too).
  String keys/values never enter the XLA program; string→id and id→string
  lookups run in the Session's host stage on numpy object arrays.
- **Frozen-dense device fast path**: a ``StaticHashTable`` with integer
  keys and numeric values is, after initialization, a static vocab. Its
  ``lookup`` lowers to a pure device op that embeds the sorted key/value
  arrays as XLA constants and does ``searchsorted`` + ``gather`` on the
  chip — no host round-trip per step, MXU-adjacent throughput. This is a
  TPU capability the reference's CPU kernel never had.
- ``MutableHashTable`` (insert during training) always stays host-stage:
  device constants would go stale under mutation.

Host-round-trip audit (ISSUE 19). Ops that appear on a training plan's
hot path and what stage they lower to:

- ``LookupTableFindDevice`` / ``LookupTableSizeDevice`` — device
  (frozen tables: init-once HashTable vocab embeds as XLA constants;
  size is a baked scalar). The OOV id-remap combine
  (``IdTableWithHashBuckets.lookup``) uses the device size op, so the
  per-step plan has NO host dependency for the vocab-size offset.
- ``LookupTableFind`` (string keys or string values) — host by
  necessity: object arrays cannot enter an XLA program. A training
  plan that remaps string→id per step therefore carries a host stage;
  the supported pattern is to remap in the input pipeline (data/
  pipeline.py stage) and feed integer ids, which keeps the step graph
  device-pure.
- ``LookupTableInsert`` / mutable ``LookupTableFind``/``Size``/
  ``Export`` — host by design (mutation invalidates any device
  snapshot); these are diagnostic/vocab-building ops, not step-loop
  ops.

Initialization runs through ``tf.tables_initializer()`` semantics: every
initializer op is added to ``GraphKeys.TABLE_INITIALIZERS``.
"""

from __future__ import annotations

import builtins
import threading

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..platform import sync as _sync
from ..framework import errors
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod

GraphKeys = ops_mod.GraphKeys


class TextFileIndex:
    """Column selectors for TextFileInitializer (ref: contrib/lookup).

    WHOLE_LINE: use the entire line (minus newline) as the key/value.
    LINE_NUMBER: use the 0-based line number.
    """

    WHOLE_LINE = -2
    LINE_NUMBER = -1


# ---------------------------------------------------------------------------
# Initializers
# ---------------------------------------------------------------------------

class KeyValueTensorInitializer:
    """Table initializer from key/value tensors (ref: contrib/lookup
    ``KeyValueTensorInitializer``)."""

    def __init__(self, keys, values, key_dtype=None, value_dtype=None,
                 name="key_value_init"):
        self._keys = np.asarray(keys)
        self._values = np.asarray(values)
        self.key_dtype = dtypes_mod.as_dtype(
            key_dtype) if key_dtype else _np_to_stf(self._keys)
        self.value_dtype = dtypes_mod.as_dtype(
            value_dtype) if value_dtype else _np_to_stf(self._values)
        self._name = name

    def _materialize(self):
        return self._keys, self._values


class TextFileInitializer:
    """Table initializer from a vocab file (ref: contrib/lookup
    ``TextFileInitializer``; kernel core/kernels/lookup_util.cc)."""

    def __init__(self, filename, key_dtype, key_index, value_dtype,
                 value_index, vocab_size=None, delimiter="\t",
                 name="text_file_init"):
        self._filename = filename
        self.key_dtype = dtypes_mod.as_dtype(key_dtype)
        self.value_dtype = dtypes_mod.as_dtype(value_dtype)
        self._key_index = key_index
        self._value_index = value_index
        self._vocab_size = vocab_size
        self._delimiter = delimiter
        self._name = name
        g = ops_mod.get_default_graph()
        g.add_to_collection(GraphKeys.ASSET_FILEPATHS, filename)

    def _column(self, lines, index, dtype):
        if index == TextFileIndex.WHOLE_LINE:
            vals = lines
        elif index == TextFileIndex.LINE_NUMBER:
            vals = [builtins.str(i) for i in builtins.range(len(lines))]
        else:
            vals = [ln.split(self._delimiter)[index] for ln in lines]
        if dtype == dtypes_mod.string:
            return np.array(vals, dtype=object)
        return np.array([int(v) if dtype.is_integer else float(v)
                         for v in vals], dtype=dtype.np_dtype)

    def _materialize(self):
        with open(self._filename, "r") as f:
            lines = [ln.rstrip("\n") for ln in f]
        if self._vocab_size is not None:
            if len(lines) < self._vocab_size:
                raise errors.InvalidArgumentError(
                    None, None,
                    f"vocab file {self._filename} has {len(lines)} lines, "
                    f"expected at least vocab_size={self._vocab_size}")
            lines = lines[:self._vocab_size]
        keys = self._column(lines, self._key_index, self.key_dtype)
        values = self._column(lines, self._value_index, self.value_dtype)
        return keys, values


def _np_to_stf(arr):
    if arr.dtype == object or arr.dtype.kind in "US":
        return dtypes_mod.string
    return dtypes_mod.as_dtype(arr.dtype)


# ---------------------------------------------------------------------------
# Table objects
# ---------------------------------------------------------------------------

class LookupInterface:
    """Base lookup table: a named host object whose graph presence is a set
    of host (or device, see StaticHashTable) ops keyed by table name."""

    _counter = [0]

    def __init__(self, key_dtype, value_dtype, name):
        LookupInterface._counter[0] += 1
        self._name = f"{name}_{LookupInterface._counter[0]}"
        self.key_dtype = dtypes_mod.as_dtype(key_dtype)
        self.value_dtype = dtypes_mod.as_dtype(value_dtype)
        self._lock = _sync.Lock("ops/lookup_table",
                                rank=_sync.RANK_QUEUE)
        # registry lives in the graph's scoped state (like variables), so
        # tables — and their materialized vocab arrays — die with the graph
        # instead of leaking across reset_default_graph()
        g = ops_mod.get_default_graph()
        g._scoped_state.setdefault("__lookup_tables__", {})[self._name] = self

    @property
    def name(self):
        return self._name

    @property
    def table_ref(self):
        return self._name

    def size(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op("LookupTableSize", [],
                         attrs={"table_name": self._name},
                         name=name or f"{self._name}_size",
                         output_specs=[(shape_mod.scalar(),
                                        dtypes_mod.int64)])
        return op.outputs[0]

    def _check_keys(self, keys):
        if isinstance(keys, ops_mod.Tensor):
            # XLA demotes int64 to int32 on TPU, so device-produced ids
            # arrive as int32 — any integer width keys an integer table.
            if self.key_dtype.is_integer and keys.dtype.is_integer:
                return keys
        else:
            keys = ops_mod.convert_to_tensor(keys, dtype=self.key_dtype)
        if keys.dtype.base_dtype != self.key_dtype:
            raise TypeError(
                f"Table {self._name} expects {self.key_dtype} keys, "
                f"got {keys.dtype}")
        return keys


class InitializableLookupTableBase(LookupInterface):
    def __init__(self, initializer, default_value, name):
        super().__init__(initializer.key_dtype, initializer.value_dtype,
                         name)
        self._default_value = default_value
        self._initializer = initializer
        self._initialized = False
        self._host_map = None       # dict key -> value
        self._keys_np = None        # materialized arrays (device path)
        self._values_np = None
        g = ops_mod.get_default_graph()
        self._init_op = g.create_op(
            "InitializeTable", [], attrs={"table_name": self._name},
            name=f"{self._name}_init", output_specs=[])
        g.add_to_collection(GraphKeys.TABLE_INITIALIZERS, self._init_op)

    @property
    def initializer(self):
        return self._init_op

    @property
    def init(self):  # TF-1.0 alias
        return self._init_op

    @property
    def default_value(self):
        return self._default_value

    # -- host behavior -------------------------------------------------------
    def _host_initialize(self):
        with self._lock:
            if self._initialized:
                return  # ref: double tables_initializer() run is a no-op
            keys, values = self._initializer._materialize()
            if keys.shape[0] != values.shape[0]:
                raise errors.InvalidArgumentError(
                    None, None,
                    f"Table {self._name}: {keys.shape[0]} keys vs "
                    f"{values.shape[0]} values")
            self._host_map = {
                _norm_key(k): v for k, v in zip(keys.tolist(),
                                                values.tolist())}
            if self.key_dtype.is_integer and not _is_string_dtype(
                    self.value_dtype):
                order = np.argsort(keys, kind="stable")
                self._keys_np = np.ascontiguousarray(keys[order])
                self._values_np = np.ascontiguousarray(values[order])
            self._initialized = True

    def _require_init(self):
        if not self._initialized:
            raise errors.FailedPreconditionError(
                None, None,
                f"Table {self._name} is not initialized. Run "
                "stf.tables_initializer() (or table.init) first.")

    def _host_find(self, keys):
        self._require_init()
        flat = np.asarray(keys).reshape(-1)
        out = [self._host_map.get(_norm_key(k), self._default_value)
               for k in flat.tolist()]
        if _is_string_dtype(self.value_dtype):
            res = np.array(out, dtype=object)
        else:
            res = np.array(out, dtype=self.value_dtype.np_dtype)
        return res.reshape(np.asarray(keys).shape)

    def _host_size(self):
        self._require_init()
        return np.asarray(len(self._host_map), dtype=np.int64)

    # -- graph endpoint ------------------------------------------------------
    def size(self, name=None):
        """Frozen tables lower size to a DEVICE constant (the vocab is
        static after init) — consumers like the OOV id-remap offset stay
        in the compiled step instead of waiting on a host stage."""
        g = ops_mod.get_default_graph()
        op = g.create_op("LookupTableSizeDevice", [],
                         attrs={"table_name": self._name},
                         name=name or f"{self._name}_size",
                         output_specs=[(shape_mod.scalar(),
                                        dtypes_mod.int64)])
        return op.outputs[0]

    def lookup(self, keys, name=None):
        keys = self._check_keys(keys)
        g = ops_mod.get_default_graph()
        device_path = (self.key_dtype.is_integer
                       and not _is_string_dtype(self.value_dtype))
        op_type = ("LookupTableFindDevice" if device_path
                   else "LookupTableFind")
        op = g.create_op(
            op_type, [keys], attrs={"table_name": self._name},
            name=name or f"{self._name}_lookup",
            output_specs=[(keys.shape, self.value_dtype)])
        return op.outputs[0]

    find = lookup  # raw-op-style alias


class HashTable(InitializableLookupTableBase):
    """Immutable key→value table (ref: core/ops/data_flow_ops.cc:1969
    ``HashTable`` + kernels/lookup_table_op.cc). Init-once; integer-keyed
    numeric tables get the frozen-dense device fast path."""

    def __init__(self, initializer, default_value, shared_name=None,
                 name="hash_table"):
        super().__init__(initializer, default_value, shared_name or name)


StaticHashTable = HashTable  # TF-2 name, same object


class MutableHashTable(LookupInterface):
    """Mutable table (ref: core/ops/data_flow_ops.cc ``MutableHashTable``,
    LookupTableInsert). Always host-stage — mutation invalidates any
    device-embedded snapshot, so none is made."""

    def __init__(self, key_dtype, value_dtype, default_value,
                 shared_name=None, name="mutable_hash_table"):
        super().__init__(key_dtype, value_dtype, shared_name or name)
        self._default_value = default_value
        self._host_map = {}

    def insert(self, keys, values, name=None):
        keys = self._check_keys(keys)
        values = ops_mod.convert_to_tensor(values, dtype=self.value_dtype)
        g = ops_mod.get_default_graph()
        return g.create_op("LookupTableInsert", [keys, values],
                           attrs={"table_name": self._name},
                           name=name or f"{self._name}_insert",
                           output_specs=[])

    def lookup(self, keys, name=None):
        keys = self._check_keys(keys)
        g = ops_mod.get_default_graph()
        op = g.create_op("LookupTableFind", [keys],
                         attrs={"table_name": self._name},
                         name=name or f"{self._name}_lookup",
                         output_specs=[(keys.shape, self.value_dtype)])
        return op.outputs[0]

    def export(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op(
            "LookupTableExport", [], attrs={"table_name": self._name},
            name=name or f"{self._name}_export",
            output_specs=[(shape_mod.TensorShape([None]), self.key_dtype),
                          (shape_mod.TensorShape([None]),
                           self.value_dtype)])
        return op.outputs[0], op.outputs[1]

    # -- host behavior -------------------------------------------------------
    def _host_insert(self, keys, values):
        kf = np.asarray(keys).reshape(-1)
        vf = np.asarray(values).reshape(-1)
        if vf.shape[0] != kf.shape[0]:
            raise errors.InvalidArgumentError(
                None, None,
                f"Table {self._name} insert: {kf.shape[0]} keys vs "
                f"{vf.shape[0]} values")
        with self._lock:
            for k, v in zip(kf.tolist(), vf.tolist()):
                self._host_map[_norm_key(k)] = v

    def _host_find(self, keys):
        flat = np.asarray(keys).reshape(-1)
        with self._lock:
            out = [self._host_map.get(_norm_key(k), self._default_value)
                   for k in flat.tolist()]
        if _is_string_dtype(self.value_dtype):
            res = np.array(out, dtype=object)
        else:
            res = np.array(out, dtype=self.value_dtype.np_dtype)
        return res.reshape(np.asarray(keys).shape)

    def _host_size(self):
        with self._lock:
            return np.asarray(len(self._host_map), dtype=np.int64)

    def _host_export(self):
        with self._lock:
            ks = list(self._host_map.keys())
            vs = [self._host_map[k] for k in ks]
        if _is_string_dtype(self.key_dtype):
            ka = np.array(ks, dtype=object)
        else:
            ka = np.array(ks, dtype=self.key_dtype.np_dtype)
        if _is_string_dtype(self.value_dtype):
            va = np.array(vs, dtype=object)
        else:
            va = np.array(vs, dtype=self.value_dtype.np_dtype)
        return ka, va


class MutableDenseHashTable(MutableHashTable):
    """API-parity alias: the reference's open-addressing variant is a CPU
    memory-layout optimization; the host dict serves the same contract
    (ref: core/kernels/lookup_table_op.cc MutableDenseHashTable)."""

    def __init__(self, key_dtype, value_dtype, default_value, empty_key=None,
                 deleted_key=None, shared_name=None,
                 name="mutable_dense_hash_table", **_kw):
        super().__init__(key_dtype, value_dtype, default_value,
                         shared_name=shared_name, name=name)


def _is_string_dtype(dt):
    return dt == dtypes_mod.string


def _norm_key(k):
    if isinstance(k, bytes):
        return k.decode("utf-8", "replace")
    return k


def _get_table(op) -> LookupInterface:
    name = op.attrs["table_name"]
    t = op.graph._scoped_state.get("__lookup_tables__", {}).get(name)
    if t is None:
        raise errors.NotFoundError(None, None, f"Table {name} not found")
    return t


# ---------------------------------------------------------------------------
# Lowerings
# ---------------------------------------------------------------------------

def _lower_init(ctx, op, inputs):
    _get_table(op)._host_initialize()
    return []


def _lower_find(ctx, op, inputs):
    return [_get_table(op)._host_find(inputs[0])]


def _lower_insert(ctx, op, inputs):
    _get_table(op)._host_insert(inputs[0], inputs[1])
    return []


def _lower_size(ctx, op, inputs):
    return [_get_table(op)._host_size()]


def _lower_export(ctx, op, inputs):
    k, v = _get_table(op)._host_export()
    return [k, v]


for _n, _fn, _nout in [("InitializeTable", _lower_init, 0),
                       ("LookupTableFind", _lower_find, 1),
                       ("LookupTableInsert", _lower_insert, 0),
                       ("LookupTableSize", _lower_size, 1),
                       ("LookupTableExport", _lower_export, None)]:
    op_registry.register(_n, lower=_fn, is_stateful=True, runs_on_host=True,
                         n_outputs=_nout)


def _lower_find_device(ctx, op, inputs):
    """Frozen-dense device path: embed the (sorted) vocab as XLA constants,
    lookup = searchsorted + gather + miss→default select. Static shapes,
    fuses into the surrounding program; zero host round-trip per step."""
    import jax.numpy as jnp

    table = _get_table(op)
    table._require_init()
    keys_c = jnp.asarray(table._keys_np)
    vals_c = jnp.asarray(table._values_np)
    keys_in = inputs[0]
    idx = jnp.searchsorted(keys_c, keys_in)
    idx_clamped = jnp.clip(idx, 0, keys_c.shape[0] - 1)
    hit = keys_c[idx_clamped] == keys_in
    found = vals_c[idx_clamped]
    default = jnp.asarray(table._default_value, dtype=found.dtype)
    return [jnp.where(hit, found, default)]


# stateful=True: the result depends on host table state at lowering time,
# so it must not be constant-folded/CSE'd across re-initialization; but it
# does NOT run on host — it traces into the XLA program.
op_registry.register("LookupTableFindDevice", lower=_lower_find_device,
                     is_stateful=True, n_outputs=1)


def _lower_size_device(ctx, op, inputs):
    """Frozen-table size as a baked device scalar (same trust model as
    FindDevice: valid because init-once tables never change size)."""
    import jax.numpy as jnp

    table = _get_table(op)
    table._require_init()
    return [jnp.asarray(int(table._host_size()))]


op_registry.register("LookupTableSizeDevice", lower=_lower_size_device,
                     is_stateful=True, n_outputs=1)


# ---------------------------------------------------------------------------
# Convenience constructors (ref: contrib/lookup/lookup_ops.py)
# ---------------------------------------------------------------------------

class IdTableWithHashBuckets(LookupInterface):
    """Vocab table + OOV hash buckets (ref: contrib/lookup
    ``string_to_index_table_from_file`` with num_oov_buckets>0): in-vocab
    keys map to their file index, OOV keys hash into
    [vocab_size, vocab_size+num_oov_buckets)."""

    def __init__(self, table, num_oov_buckets, name="id_table_oov"):
        super().__init__(table.key_dtype, dtypes_mod.int64, name)
        self._table = table
        self._oov = num_oov_buckets

    @property
    def initializer(self):
        return self._table.initializer

    init = initializer

    def lookup(self, keys, name=None):
        from . import array_ops
        from . import math_ops
        from . import string_ops

        base = self._table.lookup(keys, name=name)
        if not self._oov:
            return base
        hashed = string_ops.string_to_hash_bucket_fast(keys, self._oov)
        vsize = self._table.size()
        # combine on device in int32 (TPU's native int width — XLA demotes
        # int64 anyway), cast back to int64 for TF API parity
        base32 = math_ops.cast(base, dtypes_mod.int32)
        oov_ids = (math_ops.cast(hashed, dtypes_mod.int32)
                   + math_ops.cast(vsize, dtypes_mod.int32))
        out = array_ops.where(
            math_ops.greater_equal(base32, 0), base32, oov_ids)
        return math_ops.cast(out, dtypes_mod.int64)

    def _host_size(self):
        return self._table._host_size() + np.int64(self._oov)


def _check_oov_args(num_oov_buckets, default_value):
    # ref contract: OOV buckets and an explicit default are mutually
    # exclusive (with buckets, misses hash into a bucket, never default) —
    # and the OOV combine uses default -1 as its miss sentinel.
    if num_oov_buckets and default_value != -1:
        raise ValueError(
            "num_oov_buckets and default_value cannot both be specified: "
            "with OOV buckets every miss maps into a bucket, so "
            "default_value would never be returned (reference "
            "lookup_ops contract).")


def index_table_from_file(vocabulary_file, num_oov_buckets=0,
                          vocab_size=None, default_value=-1,
                          key_dtype=dtypes_mod.string, delimiter="\t",
                          name="string_to_index"):
    """string → id table from a one-token-per-line vocab file (ref:
    contrib/lookup ``index_table_from_file``)."""
    _check_oov_args(num_oov_buckets, default_value)
    init = TextFileInitializer(
        vocabulary_file, key_dtype, TextFileIndex.WHOLE_LINE,
        dtypes_mod.int64, TextFileIndex.LINE_NUMBER,
        vocab_size=vocab_size, delimiter=delimiter)
    table = HashTable(init, default_value, name=name)
    if num_oov_buckets:
        return IdTableWithHashBuckets(table, num_oov_buckets,
                                      name=f"{name}_oov")
    return table


def index_table_from_tensor(mapping, num_oov_buckets=0, default_value=-1,
                            name="string_to_index"):
    _check_oov_args(num_oov_buckets, default_value)
    mapping = np.asarray(mapping)
    init = KeyValueTensorInitializer(
        mapping, np.arange(mapping.shape[0], dtype=np.int64))
    table = HashTable(init, default_value, name=name)
    if num_oov_buckets:
        return IdTableWithHashBuckets(table, num_oov_buckets,
                                      name=f"{name}_oov")
    return table


def index_to_string_table_from_file(vocabulary_file, vocab_size=None,
                                    default_value="UNK", delimiter="\t",
                                    name="index_to_string"):
    """id → string table for decoding (ref: contrib/lookup
    ``index_to_string_table_from_file``). Host-stage (string values)."""
    init = TextFileInitializer(
        vocabulary_file, dtypes_mod.int64, TextFileIndex.LINE_NUMBER,
        dtypes_mod.string, TextFileIndex.WHOLE_LINE,
        vocab_size=vocab_size, delimiter=delimiter)
    return HashTable(init, default_value, name=name)


def index_to_string_table_from_tensor(mapping, default_value="UNK",
                                      name="index_to_string"):
    mapping = np.asarray(mapping, dtype=object)
    init = KeyValueTensorInitializer(
        np.arange(mapping.shape[0], dtype=np.int64), mapping)
    return HashTable(init, default_value, name=name)


def tables_initializer(name="init_all_tables"):
    """Group of every table initializer in the graph (ref:
    python/ops/lookup-era ``tf.tables_initializer``)."""
    from . import control_flow_ops

    g = ops_mod.get_default_graph()
    inits = g.get_collection(GraphKeys.TABLE_INITIALIZERS)
    return control_flow_ops.group(*inits, name=name)


def initialize_all_tables(name="init_all_tables"):
    """Deprecated TF-1.0 alias of tables_initializer."""
    return tables_initializer(name=name)


# declared effect sets (stf.analysis): table state is a host resource
op_registry.declare_effects("InitializeTable", op_registry.Effects(writes=("table_name",)))
op_registry.declare_effects("LookupTableInsert", op_registry.Effects(writes=("table_name",)))
for _r_op in ("LookupTableFind", "LookupTableSize", "LookupTableExport",
              "LookupTableFindDevice", "LookupTableSizeDevice"):
    op_registry.declare_effects(_r_op, op_registry.Effects(reads=("table_name",)))
