"""SDCA linear solver (ref: core/ops/sdca_ops.cc:41 ``SdcaOptimizer``,
``:123 SdcaShrinkL1``, ``:139 SdcaFprint``; kernels
core/kernels/sdca_{ops,internal}.cc; python/ops/sdca_ops.py).

Stochastic Dual Coordinate Ascent for L1+L2-regularized linear models
(Shalev-Shwartz & Zhang, arXiv:1211.2717). Learning-rate free; optimizes
the dual one example at a time.

TPU-native design: the reference kernel is a multi-threaded CPU loop over
examples. Here the sequential dual sweep is a ``lax.scan`` inside ONE
jitted program (XLA-structured, MXU does the feature dot products), so
the whole ``num_inner_iterations`` pass is a single device program
instead of a Python loop. Dense feature groups only — sparse groups
should use embedding-style dense gathers on TPU (see
ops/embedding_ops.py); the op family's sparse arguments are accepted and
densified on the host stage with an explicit note.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod

_LOSSES = ("logistic_loss", "squared_loss", "hinge_loss",
           "smooth_hinge_loss")


def _dual_update(loss_type, label, wx, alpha, xnorm_over_l2n):
    """Closed-form / Newton dual coordinate maximization for one example.

    xnorm_over_l2n = ||x||^2 / (l2 * N): the step denominator from the
    prox-SDCA derivation (weights carry w = sum_i alpha_i x_i / (l2 N)).
    """
    g = jnp.maximum(xnorm_over_l2n, 1e-12)
    if loss_type == "squared_loss":
        # f(z) = (z - y)^2 / 2; exact maximizer
        delta = (label - wx - alpha) / (1.0 + g)
        return alpha + delta
    if loss_type == "hinge_loss":
        # f(z) = max(0, 1 - y z), labels in {-1, +1}; box [0, 1] on y*alpha
        a_y = alpha * label
        delta = (1.0 - label * wx) / g
        return jnp.clip(a_y + delta, 0.0, 1.0) * label
    if loss_type == "smooth_hinge_loss":
        gamma = 1.0  # ref kernel's smoothing parameter
        a_y = alpha * label
        delta = (1.0 - label * wx - gamma * a_y) / (g + gamma)
        return jnp.clip(a_y + delta, 0.0, 1.0) * label
    # logistic_loss: f(z) = log(1 + exp(-y z)), dual in (0, 1) on y*alpha;
    # no closed form — a few damped Newton steps on the dual objective
    # derivative h(a) = y*wx + g*(a - a0)*y^2... formulated on a = y*alpha
    y = label

    def newton_step(a, _):
        a = jnp.clip(a, 1e-6, 1.0 - 1e-6)
        # d/da [ -a log a - (1-a) log(1-a) - a*y*wx_without_self ... ]
        # standard SDCA logistic dual gradient:
        grad = jnp.log(a / (1.0 - a)) + y * wx + g * (a - a0)
        hess = 1.0 / (a * (1.0 - a)) + g
        return jnp.clip(a - grad / hess, 1e-6, 1.0 - 1e-6), None

    a0 = jnp.clip(alpha * y, 1e-6, 1.0 - 1e-6)
    # remove the example's own contribution: wx includes alpha*x/l2N; the
    # Newton objective uses wx held fixed plus the g*(a-a0) correction
    a_new, _ = jax.lax.scan(newton_step, a0, None, length=8)
    return a_new * y


def _sdca_optimizer_impl(dense_features, example_weights, example_labels,
                         dense_weights, example_state_data, *,
                         loss_type="logistic_loss", l1=0.0, l2=1.0,
                         num_loss_partitions=1, num_inner_iterations=1):
    """One SdcaOptimizer invocation over the mini-batch: scan example-by-
    example (the algorithm is inherently sequential — each update must see
    the previous example's weight delta), repeated num_inner_iterations
    times, all inside one XLA program."""
    n_groups = len(dense_features)
    feats = [jnp.asarray(f, jnp.float32) for f in dense_features]
    labels = jnp.asarray(example_labels, jnp.float32)
    weights_ex = jnp.asarray(example_weights, jnp.float32)
    n = labels.shape[0]
    num_loss_partitions = max(int(num_loss_partitions), 1)
    l2n = jnp.float32(max(l2, 1e-9) * n)
    state = jnp.asarray(example_state_data, jnp.float32)
    alpha0 = state[:, 0] if state.ndim == 2 else state
    w0 = [jnp.asarray(w, jnp.float32) for w in dense_weights]

    # per-example feature rows and norms, concatenated view per group
    xnorm = sum(jnp.sum(f * f, axis=1) for f in feats)

    l1_over_l2 = jnp.float32(l1 / max(l2, 1e-9))

    def shrink(w):
        # ref sdca_internal.cc: predictions use the L1-SHRUNK weights
        # (soft threshold at l1/l2) while the dual state carries the
        # unshrunk accumulator; callers apply sdca_shrink_l1 at the end
        if l1 <= 0.0:
            return w
        return jnp.sign(w) * jnp.maximum(jnp.abs(w) - l1_over_l2, 0.0)

    def example_step(carry, i):
        alphas, ws = carry
        xi = [f[i] for f in feats]
        wx = sum(jnp.dot(shrink(w), x) for w, x in zip(ws, xi))
        a_old = alphas[i]
        # num_loss_partitions scales the step denominator (ref
        # sdca_internal.cc: the CoCoA+ aggregation safeguard when the
        # global loss is split over partitions)
        a_new = _dual_update(loss_type, labels[i], wx, a_old,
                             num_loss_partitions * xnorm[i] / l2n)
        a_new = jnp.where(weights_ex[i] > 0, a_new, a_old)
        d = (a_new - a_old) * weights_ex[i]
        ws = [w + (d / l2n) * x for w, x in zip(ws, xi)]
        alphas = alphas.at[i].set(a_new)
        return (alphas, ws), None

    def sweep(carry, _):
        return jax.lax.scan(example_step, carry, jnp.arange(n))[0], None

    (alphas, ws), _ = jax.lax.scan(sweep, (alpha0, w0), None,
                                   length=int(num_inner_iterations))

    # primal/dual diagnostics in the state rows (ref keeps [a, norm, f, f*])
    wx_all = sum(f @ shrink(w) for f, w in zip(feats, ws))
    if loss_type == "squared_loss":
        primal = 0.5 * (wx_all - labels) ** 2
    elif loss_type in ("hinge_loss", "smooth_hinge_loss"):
        primal = jnp.maximum(0.0, 1.0 - labels * wx_all)
    else:
        primal = jnp.log1p(jnp.exp(-labels * wx_all))
    out_state = jnp.stack(
        [alphas, xnorm, primal, jnp.zeros_like(alphas)], axis=1)
    deltas = [w - w_init for w, w_init in zip(ws, w0)]
    return [out_state] + deltas


def _lower_sdca(ctx, op, inputs):
    nd = op.attrs["num_dense_features"]
    dense_features = inputs[:nd]
    example_weights = inputs[nd]
    example_labels = inputs[nd + 1]
    dense_weights = inputs[nd + 2: nd + 2 + nd]
    state = inputs[nd + 2 + nd]
    return _sdca_optimizer_impl(
        dense_features, example_weights, example_labels, dense_weights,
        state, loss_type=op.attrs["loss_type"], l1=op.attrs["l1"],
        l2=op.attrs["l2"],
        num_loss_partitions=op.attrs["num_loss_partitions"],
        num_inner_iterations=op.attrs["num_inner_iterations"])


op_registry.register("SdcaOptimizer", lower=_lower_sdca, is_stateful=True,
                     n_outputs=None)


def sdca_optimizer(sparse_example_indices, sparse_feature_indices,
                   sparse_feature_values, dense_features, example_weights,
                   example_labels, sparse_indices, sparse_weights,
                   dense_weights, example_state_data,
                   loss_type="logistic_loss", adaptative=False, l1=0.0,
                   l2=1.0, num_loss_partitions=1, num_inner_iterations=1,
                   name=None):
    """(ref: core/ops/sdca_ops.cc:41). Returns
    (out_example_state_data, out_delta_dense_weights list).

    TPU note: only dense feature groups run on device; pass sparse groups
    as dense gathers (ops/embedding_ops.py) — the sparse arguments exist
    for API parity and must be empty.
    """
    if loss_type not in _LOSSES:
        raise ValueError(f"loss_type must be one of {_LOSSES}, "
                         f"got {loss_type!r}")
    if adaptative:
        from ..platform import tf_logging as logging

        logging.warning(
            "sdca_optimizer(adaptative=True): adaptive example sampling "
            "is a convergence-speed heuristic in the reference kernel; "
            "this implementation sweeps examples in order (same optimum, "
            "possibly more inner iterations needed).")
    sparse_args = (sparse_example_indices, sparse_feature_indices,
                   sparse_feature_values, sparse_indices, sparse_weights)
    if any(len(a) > 0 for a in sparse_args if a is not None):
        raise NotImplementedError(
            "TPU SdcaOptimizer takes dense feature groups only: static "
            "shapes preclude ragged per-example sparse lists. Densify "
            "sparse groups via stf.nn.embedding_lookup / stf.gather "
            "(one dense group per sparse group) — mathematically "
            "identical, and the gather runs on the MXU.")
    g = ops_mod.get_default_graph()
    dense_features = [ops_mod.convert_to_tensor(f, dtype=dtypes_mod.float32)
                      for f in dense_features]
    dense_weights = [ops_mod.convert_to_tensor(w, dtype=dtypes_mod.float32)
                     for w in dense_weights]
    ew = ops_mod.convert_to_tensor(example_weights,
                                   dtype=dtypes_mod.float32)
    el = ops_mod.convert_to_tensor(example_labels,
                                   dtype=dtypes_mod.float32)
    st = ops_mod.convert_to_tensor(example_state_data,
                                   dtype=dtypes_mod.float32)
    n_ex = el.shape[0]
    specs = ([(shape_mod.TensorShape([n_ex, 4]), dtypes_mod.float32)]
             + [(w.shape, dtypes_mod.float32) for w in dense_weights])
    op = g.create_op(
        "SdcaOptimizer",
        list(dense_features) + [ew, el] + list(dense_weights) + [st],
        attrs={"loss_type": loss_type, "l1": float(l1), "l2": float(l2),
               "num_dense_features": len(dense_features),
               "num_loss_partitions": int(num_loss_partitions),
               "num_inner_iterations": int(num_inner_iterations),
               "adaptative": bool(adaptative)},
        name=name or "SdcaOptimizer", output_specs=specs)
    outs = list(op.outputs)
    return outs[0], outs[1:]


op_registry.register_pure(
    "SdcaShrinkL1",
    lambda *ws, l1=0.0, l2=1.0, num_features=0: [
        jnp.sign(w) * jnp.maximum(jnp.abs(w) - l1 / l2, 0.0) for w in ws],
    n_outputs=None)


def sdca_shrink_l1(weights, l1=0.0, l2=1.0, name=None):
    """Soft-threshold shrink step (ref: core/ops/sdca_ops.cc:123). Returns
    the shrunk weights (the ref mutates refs in place; here: assign the
    results back to your Variables)."""
    g = ops_mod.get_default_graph()
    ws = [ops_mod.convert_to_tensor(w, dtype=dtypes_mod.float32)
          for w in weights]
    op = g.create_op("SdcaShrinkL1", ws,
                     attrs={"l1": float(l1), "l2": float(l2),
                            "num_features": len(ws)},
                     name=name or "SdcaShrinkL1",
                     output_specs=[(w.shape, dtypes_mod.float32)
                                   for w in ws])
    return list(op.outputs)


def _fprint_impl(x):
    import hashlib

    def h(s):
        d = hashlib.sha256(
            s if isinstance(s, bytes) else str(s).encode()).digest()
        return int.from_bytes(d[:8], "little", signed=True)

    return np.vectorize(h, otypes=[np.int64])(x)


op_registry.register("SdcaFprint", lower=lambda ctx, op, i:
                     [_fprint_impl(i[0])],
                     is_stateful=True, runs_on_host=True, n_outputs=1)


def sdca_fprint(input, name=None):  # noqa: A002
    """Stable 64-bit fingerprints of example id strings (ref:
    core/ops/sdca_ops.cc:139). Host-stage: strings never enter XLA."""
    x = ops_mod.convert_to_tensor(input)
    g = ops_mod.get_default_graph()
    op = g.create_op("SdcaFprint", [x], attrs={},
                     name=name or "SdcaFprint",
                     output_specs=[(x.shape, dtypes_mod.int64)])
    return op.outputs[0]
