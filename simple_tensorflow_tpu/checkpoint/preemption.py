"""Preemption-safe training: SIGTERM → drain → save → clean exit.

Cloud schedulers deliver SIGTERM with a grace window before the kill
(the TPU-pod study, arXiv 1909.09756, and the MPI characterization,
arXiv 1810.11112, both treat restart/checkpoint cost as a first-order
scale limiter — losing the whole epoch to a preemption is the worst
case). The flow here:

1. ``install_preemption_handler()`` puts a chaining handler on SIGTERM
   that just sets a flag (+ dumps the telemetry flight recorder, which
   is what telemetry's own SIGTERM disposition would have done — but
   WITHOUT its terminate-the-process tail, because the whole point is a
   graceful drain). A previously installed *user* handler still runs;
   ``SIG_IGN`` processes stay TERM-shielded (the handler is then not
   installed at all).
2. The in-flight fused window finishes normally — ``run_steps`` windows
   are uninterruptible device programs, and their boundary is exactly
   the consistent-state barrier the checkpoint needs.
3. ``PreemptionHandler`` (a SessionRunHook) sees the flag at the next
   ``after_run`` barrier, saves a checkpoint (blocking — the process is
   about to exit), and requests a coordinator stop. Its fusion vote
   drops to 1 once preemption is requested so the drain adds at most
   one more step, not a whole window.
4. The training loop exits cleanly; on restart,
   ``MonitoredTrainingSession(checkpoint_dir=...)`` (or
   ``CheckpointManager.restore_or_initialize``) resumes bit-exact:
   variables + optimizer slots + global_step + RNG run counters + data
   iterator positions all come back (docs/CHECKPOINT.md walkthrough).
"""

from __future__ import annotations

import os
import time
from typing import Optional

from ..train.session_run_hook import SessionRunHook
from . import metrics as _m

# Plain attribute writes only — this state is touched from a SIGNAL
# HANDLER, which runs on the main thread at an arbitrary bytecode
# boundary. Taking any lock there (threading.Lock, the recorder's ring
# lock, a metric cell lock) can deadlock against the interrupted frame
# that already holds it; CPython attribute stores are atomic under the
# GIL, which is all the flag needs.
_requested = False
_requested_at: Optional[float] = None
_reason: Optional[str] = None
_bookkept = True  # no deferred metric/flight/dump work pending
_dump_on_flush = False
_prev_handler = None
_installed = False


def _mark_requested(reason: str, defer_bookkeeping: bool,
                    dump: bool) -> bool:
    """Async-signal-safe half of a preemption request: set the flag and
    stash what the drain path still owes (metric bump, flight event,
    recorder dump). Returns False when already requested."""
    global _requested, _requested_at, _reason, _bookkept, _dump_on_flush
    if _requested:
        return False
    _requested_at = time.time()
    _reason = reason
    _dump_on_flush = dump
    _bookkept = not defer_bookkeeping
    _requested = True  # set LAST: readers see fully-stamped state
    return True


def _do_bookkeeping(dump: bool) -> None:
    _m.preemptions.get_cell().increase_by(1)
    from ..telemetry import recorder

    rec = recorder.get_recorder()
    rec.record("checkpoint", action="preemption_signal",
               reason=_reason, pid=os.getpid())
    if dump:
        try:
            rec.dump(reason="sigterm")
        except Exception:  # noqa: BLE001 — forensics never block drain
            pass


def preemption_requested() -> bool:
    """Whether a preemption was requested — polled by the drain path
    (hook votes / after_run). Flushes any bookkeeping the signal
    handler deferred (it may only set flags): metric, flight event,
    flight-recorder dump — here, on a normal frame, locks are safe."""
    global _bookkept
    if _requested and not _bookkept:
        _bookkept = True
        try:
            _do_bookkeeping(_dump_on_flush)
        except Exception:  # noqa: BLE001
            pass
    return _requested


def request_preemption(reason: str = "manual") -> None:
    """Programmatic preemption (tests, external schedulers polling a
    metadata endpoint): same drain → save → stop flow, no signal."""
    if _mark_requested(reason, defer_bookkeeping=False, dump=False):
        _do_bookkeeping(dump=False)


def reset_preemption_state() -> None:
    """Clear the request flag (tests; a resumed in-process run)."""
    global _requested, _requested_at, _reason, _bookkept, _dump_on_flush
    _requested = False
    _requested_at = None
    _reason = None
    _bookkept = True
    _dump_on_flush = False


def install_preemption_handler() -> bool:
    """Install the chaining SIGTERM handler (main thread only;
    idempotent). Returns whether a handler is active."""
    global _prev_handler, _installed
    if _installed:
        return True
    import signal

    try:
        prev = signal.getsignal(signal.SIGTERM)
        if prev is None:
            # a C-level handler owns SIGTERM; we cannot chain it
            return False
        if prev == signal.SIG_IGN:
            # the process chose to be TERM-shielded; preemption-on-TERM
            # would change that contract
            return False

        from ..telemetry import recorder as recorder_mod

        def _on_sigterm(signum, frame):
            # ASYNC-SIGNAL-SAFE: plain flag writes only. The metric
            # bump, flight event, and recorder dump (telemetry's own
            # SIGTERM disposition — minus its terminate-the-process
            # tail, which a graceful drain must absorb) all take locks
            # the interrupted frame may hold, so they are DEFERRED to
            # the drain path's next preemption_requested() poll.
            _mark_requested("sigterm", defer_bookkeeping=True,
                            dump=True)
            if (callable(prev) and prev != signal.SIG_DFL
                    and prev is not recorder_mod._installed_handler):
                prev(signum, frame)  # a user handler keeps running

        signal.signal(signal.SIGTERM, _on_sigterm)
        _prev_handler = prev
        _installed = True
        return True
    except ValueError:
        # not the main thread
        return False


def uninstall_preemption_handler() -> None:
    global _prev_handler, _installed
    if not _installed:
        return
    import signal

    try:
        signal.signal(signal.SIGTERM, _prev_handler)
    except (ValueError, TypeError):
        pass
    _prev_handler = None
    _installed = False


class PreemptionHandler(SessionRunHook):
    """SessionRunHook half of the flow (importable standalone; also
    appended by ``MonitoredTrainingSession(checkpoint_dir=...)``).

    Saves through, in priority order: an explicit ``manager``, an
    explicit ``saver``, the scaffold's saver, else a fresh
    ``train.Saver`` — always blocking (the process is exiting) to
    ``checkpoint_dir/checkpoint_basename-<global_step>``.
    """

    def __init__(self, checkpoint_dir=None, manager=None, saver=None,
                 scaffold=None, checkpoint_basename="model.ckpt",
                 install: bool = True):
        if manager is None and checkpoint_dir is None:
            raise ValueError(
                "PreemptionHandler needs a checkpoint_dir or a "
                "CheckpointManager")
        self._checkpoint_dir = checkpoint_dir
        self._manager = manager
        self._saver = saver
        self._scaffold = scaffold
        self._basename = checkpoint_basename
        self._install = install
        self._installed_here = False
        self._saved = False
        self.last_saved_prefix: Optional[str] = None

    # -- SessionRunHook protocol ---------------------------------------------
    def begin(self):
        from ..train import training_util

        self._global_step_tensor = training_util.get_global_step()
        if self._install:
            self._installed_here = install_preemption_handler()

    def until_next_trigger(self, global_step):
        # once preemption is requested, stop fusing: drain in at most
        # one more step, then save at its barrier
        return 1 if preemption_requested() else (1 << 30)

    def after_run(self, run_context, run_values):
        if not preemption_requested() or self._saved:
            return
        self._drain_and_save(run_context.session)
        run_context.request_stop()

    def end(self, session):
        if preemption_requested() and not self._saved:
            # the loop exited (e.g. StopAtStep) before a post-signal
            # barrier was reached; still persist the final state
            self._drain_and_save(session)
        if self._installed_here:
            uninstall_preemption_handler()
            self._installed_here = False

    # -- internals ------------------------------------------------------------
    def _current_step(self, session) -> Optional[int]:
        from ..train.saver import resolve_global_step

        return resolve_global_step(session, self._global_step_tensor)

    def _drain_and_save(self, session):
        from ..platform import tf_logging as logging
        from ..telemetry import recorder

        self._saved = True
        step = self._current_step(session)
        if self._manager is not None:
            prefix = self._manager.save(session, global_step=step,
                                        blocking=True)
        else:
            saver = self._resolve_saver()
            save_path = os.path.join(self._checkpoint_dir,
                                     self._basename)
            prefix = saver.save(session, save_path, global_step=step)
            engine = getattr(saver, "_async_engine", None)
            if engine is not None:
                engine.wait_until_finished()
        self.last_saved_prefix = prefix
        recorder.get_recorder().record(
            "checkpoint", action="preemption_save", prefix=prefix,
            step=-1 if step is None else step)
        logging.info(
            "PreemptionHandler: drained and saved %s at global_step=%s; "
            "requesting stop.", prefix, step)

    def _resolve_saver(self):
        if self._saver is not None:
            return self._saver
        if self._scaffold is not None and \
                getattr(self._scaffold, "saver", None) is not None:
            return self._scaffold.saver
        from ..framework import graph as ops_mod
        from ..train.saver import Saver

        savers = ops_mod.get_default_graph().get_collection(
            ops_mod.GraphKeys.SAVERS)
        self._saver = savers[0] if savers else Saver()
        return self._saver
