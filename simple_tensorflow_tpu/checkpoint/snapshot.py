"""Training-state snapshots and the stf-bundle checkpoint format.

Two producers share one format implementation:

- ``Saver.save`` (blocking): fetches tensors to host numpy in-line and
  calls ``write_native_checkpoint`` directly.
- the async plane (``CheckpointManager`` / ``AsyncSaverEngine``):
  ``capture_training_state`` takes a *barrier snapshot* — donation-safe
  on-device copies of the variable store (``Session.
  snapshot_device_state``) plus host state (RNG run counter, data
  iterator positions) — in microseconds-to-milliseconds, then the
  ``stf_ckpt_writer`` thread materializes (D2H), serializes, and
  commits while the next fused window already runs.

Format (``docs/CHECKPOINT.md``): ``<prefix>.stfz`` (npz of all tensors,
keys '/'-flattened with '|') + ``<prefix>.index.json`` (dtypes/shapes/
shardings, content checksum of the data file, host state) + the classic
``checkpoint`` state file. Commit ordering — data, then index, then
state file, each through the atomic temp+fsync+replace protocol — means
a crash at any point leaves the previous checkpoint loadable.
"""

from __future__ import annotations

import io
import json
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..platform import monitoring
from . import atomic
from . import metrics as _m

INDEX_VERSION = 2  # v2 adds checksum/data_bytes/sharding fields


def _npz_key(key: str) -> str:
    # npz keys are '/'-flattened with '|' (train.saver
    # load_checkpoint_values is the one reader that knows this)
    return key.replace("/", "|")


def sharding_desc(arr) -> Optional[str]:
    """Best-effort human-readable sharding of a device array for the
    index (``PartitionSpec('tp', None)`` style, or None when fully
    replicated / unknown)."""
    try:
        sh = getattr(arr, "sharding", None)
        if sh is None:
            return None
        spec = getattr(sh, "spec", None)
        if spec is not None and any(p is not None for p in tuple(spec)):
            return str(spec)
        if len(getattr(sh, "device_set", ())) > 1 and spec is not None:
            return str(spec)
        return None
    except Exception:  # noqa: BLE001 — index metadata is advisory
        return None


class TrainingStateSnapshot:
    """A consistent point-in-time capture of the full training state.

    ``arrays`` holds *device-side copies* (not the live store arrays —
    those are donated to the next step's executable and would read as
    deleted buffers). ``materialize()`` moves them to host numpy; until
    then the snapshot pins one extra copy of the state in device memory
    — and accounts for it in the HBM ledger (stf.telemetry.memory,
    class ``snapshot``): an in-flight async save transiently DOUBLES
    the named variables' device memory, and the ledger makes that
    visible. ``release_device_state()`` (called by the writer job after
    the commit, and on GC as a fallback) drops the device copies and
    the ledger entry back to baseline.
    """

    __slots__ = ("arrays", "tensor_index", "host_state", "step",
                 "captured_at", "graph", "_mem_token", "__weakref__")

    def __init__(self, arrays, tensor_index, host_state, step=None,
                 graph=None):
        self.arrays: Dict[str, Any] = arrays
        self.tensor_index: Dict[str, Dict[str, Any]] = tensor_index
        self.host_state: Dict[str, Any] = host_state
        self.step = step
        self.captured_at = time.time()
        self.graph = graph
        from ..telemetry import memory as _memory_mod

        ledger = _memory_mod.get_ledger()
        self._mem_token = ledger.register(
            f"checkpoint_snapshot[{len(arrays)} tensors]",
            self.nbytes(), _memory_mod.CLASS_SNAPSHOT, "checkpoint",
            arrays=self)
        # GC fallback: a snapshot dropped without release (error paths)
        # must not leave a phantom ledger entry
        import weakref

        weakref.finalize(self, ledger.release, self._mem_token)

    def materialize(self) -> Dict[str, Any]:
        """D2H transfer of every snapshot array (writer-thread side).
        Arrays that are really device-sharded pass through UNGATHERED —
        ``write_native_checkpoint``'s flatten step D2H's them one shard
        at a time into flat per-shard npz entries, so a vocab-sharded
        embedding table never assembles on the host."""
        out = {}
        for key, arr in self.arrays.items():
            out[key] = arr if shard_split(arr) is not None \
                else np.asarray(arr)
        return out

    def release_device_state(self) -> None:
        """Drop the device-side copies (the host npz is durable by the
        time the writer calls this) and their ledger accounting —
        snapshot memory returns to baseline. Idempotent."""
        from ..telemetry import memory as _memory_mod

        self.arrays = {}
        _memory_mod.get_ledger().release(self._mem_token)
        self._mem_token = None

    def nbytes(self) -> int:
        return int(sum(getattr(a, "nbytes", 0)
                       for a in self.arrays.values()))


def capture_training_state(sess, vars_map) -> TrainingStateSnapshot:
    """Barrier snapshot: device copies of every variable in ``vars_map``
    ({checkpoint_key: Variable}) plus host state, taken under the
    session's device lock so it can never interleave with a step.

    Raises FailedPreconditionError when a variable is uninitialized —
    same contract as ``Saver.save``.
    """
    from ..framework import errors

    with monitoring.traceme("checkpoint_snapshot", n_vars=len(vars_map)):
        names = {}
        for key, v in vars_map.items():
            names[key] = v.var_name if hasattr(v, "var_name") else key
        store = sess._variable_store
        missing = [n for n in names.values() if n not in store.values]
        if missing:
            raise errors.FailedPreconditionError(
                None, None,
                f"Variable(s) {sorted(missing)} uninitialized; cannot "
                "checkpoint.")
        copies, host_state = sess.snapshot_device_state(
            sorted(set(names.values())))
        index = {}
        arrays = {}
        for key, store_name in names.items():
            arr = copies[store_name]
            arrays[key] = arr
            index[key] = {"dtype": str(arr.dtype),
                          "shape": list(arr.shape),
                          "store_name": store_name,
                          "sharding": sharding_desc(store.values.get(
                              store_name, arr))}
        return TrainingStateSnapshot(arrays, index, host_state,
                                     graph=sess.graph)


def shard_split(arr):
    """Per-shard views of a device array that is REALLY sharded (>1
    device, non-trivial spec): sorted list of ``(start_offsets,
    shard)`` with replicated copies deduplicated, or None when the
    array is replicated / host-side / single-device (callers then save
    it as one entry). The shards stay device-side; ``np.asarray`` on
    each is a per-shard D2H — a terabyte-class embedding table never
    materializes unsharded on one host."""
    try:
        sh = getattr(arr, "sharding", None)
        if sh is None or len(getattr(sh, "device_set", ())) <= 1:
            return None
        spec = getattr(sh, "spec", None)
        if spec is None or not any(p is not None for p in tuple(spec)):
            return None
        seen = {}
        for s in arr.addressable_shards:
            start = tuple(int(sl.start or 0) for sl in s.index)
            seen.setdefault(start, s.data)
        if len(seen) <= 1:
            return None
        return sorted(seen.items())
    except Exception:  # noqa: BLE001 — fall back to the gather path
        return None


def flatten_for_save(arrays, tensor_index):
    """(flat npz entries, index) for one checkpoint: sharded device
    arrays become ``<key>@shard<i>of<n>`` entries (one per distinct
    shard, D2H'd one at a time) and their index meta gains a
    ``sharded_layout`` describing each shard's start offsets — the
    restore/verify side reassembles from that, so the on-disk format
    needs no gather at either end. Everything else is ``np.asarray``'d
    as before. ``tensor_index`` is copied, not mutated (the async
    snapshot's index outlives one write attempt)."""
    flat: Dict[str, np.ndarray] = {}
    index = {k: dict(v) for k, v in tensor_index.items()}
    for key, arr in arrays.items():
        parts = shard_split(arr)
        if parts is None:
            flat[key] = np.asarray(arr)
            continue
        n = len(parts)
        shards_meta = []
        for i, (start, data) in enumerate(parts):
            skey = f"{key}@shard{i}of{n}"
            np_shard = np.asarray(data)
            flat[skey] = np_shard
            shards_meta.append({"key": skey, "start": list(start),
                                "shape": list(np_shard.shape)})
        index.setdefault(key, {})["sharded_layout"] = {
            "num_shards": n, "shards": shards_meta}
    return flat, index


def assemble_sharded(data, meta) -> np.ndarray:
    """Reassemble one logical tensor from its per-shard npz entries
    (inverse of :func:`flatten_for_save`; ``data`` is the open npz)."""
    lay = meta["sharded_layout"]
    full = np.empty(tuple(meta["shape"]), np.dtype(meta["dtype"]))
    for sh in lay["shards"]:
        part = data[_npz_key(sh["key"])]
        idx = tuple(slice(st, st + dim)
                    for st, dim in zip(sh["start"], part.shape))
        full[idx] = part
    return full


def encode_npz(arrays: Dict[str, np.ndarray]) -> bytes:
    """The .stfz payload as in-memory bytes (so the content checksum is
    computed over exactly what lands on disk)."""
    buf = io.BytesIO()
    np.savez(buf, **{_npz_key(k): np.asarray(v)
                     for k, v in arrays.items()})
    return buf.getvalue()


def build_index_doc(tensor_index, host_state, backend="native",
                    payload: Optional[bytes] = None) -> Dict[str, Any]:
    doc = {"tensors": tensor_index, "version": INDEX_VERSION,
           "backend": backend, "host_state": host_state,
           "time": time.time()}
    if payload is not None:
        doc["checksum"] = atomic.checksum_bytes(payload)
        doc["data_bytes"] = len(payload)
    return doc


def write_native_checkpoint(prefix: str, arrays: Dict[str, np.ndarray],
                            tensor_index, host_state) -> Dict[str, Any]:
    """Serialize + commit one native checkpoint: npz bytes → checksum →
    atomic data write → atomic index write. The ``checkpoint`` state
    file is NOT touched here — callers update it last, after every
    artifact is durable, so a crash mid-commit leaves the previous
    checkpoint as latest."""
    os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
    with monitoring.traceme("checkpoint_serialize", n_vars=len(arrays)):
        arrays, tensor_index = flatten_for_save(arrays, tensor_index)
        payload = encode_npz(arrays)
        doc = build_index_doc(tensor_index, host_state, "native",
                              payload=payload)
    index_bytes = json.dumps(doc, indent=1).encode("utf-8")
    with monitoring.traceme("checkpoint_commit",
                            data_bytes=len(payload)):
        atomic.atomic_write_bytes(prefix + ".stfz", payload, label="data")
        atomic.atomic_write_bytes(prefix + ".index.json", index_bytes,
                                  label="index")
    _m.bytes_written.get_cell().increase_by(len(payload)
                                            + len(index_bytes))
    return doc


def read_index(prefix: str) -> Dict[str, Any]:
    with open(prefix + ".index.json") as f:
        return json.load(f)


def verify_checkpoint(prefix: str) -> List[str]:
    """Integrity-check one checkpoint; returns a list of problem
    strings (empty = verified). Counts failures on
    /stf/checkpoint/integrity_failures by kind."""
    problems: List[str] = []

    def _fail(kind: str, msg: str):
        _m.integrity_failures.get_cell(kind).increase_by(1)
        problems.append(msg)

    index_path = prefix + ".index.json"
    if not os.path.exists(index_path):
        _fail("missing_file", f"{index_path}: missing index file")
        return problems
    try:
        doc = read_index(prefix)
        tensors = doc["tensors"]
    except (json.JSONDecodeError, KeyError, OSError) as e:
        _fail("bad_index", f"{index_path}: unreadable index ({e})")
        return problems
    if doc.get("backend") == "orbax" or os.path.isdir(prefix + ".orbax"):
        if not os.path.isdir(prefix + ".orbax"):
            _fail("missing_file", f"{prefix}.orbax: missing orbax dir")
        return problems  # orbax manages its own integrity metadata
    data_path = prefix + ".stfz"
    if not os.path.exists(data_path):
        _fail("missing_file", f"{data_path}: missing tensor data file")
        return problems
    expected = doc.get("checksum")
    if expected is not None:
        nbytes = os.path.getsize(data_path)
        if doc.get("data_bytes") is not None and \
                nbytes != doc["data_bytes"]:
            _fail("checksum_mismatch",
                  f"{data_path}: size {nbytes} != recorded "
                  f"{doc['data_bytes']}")
            return problems
        actual = atomic.checksum_file(data_path)
        if actual != expected:
            _fail("checksum_mismatch",
                  f"{data_path}: checksum {actual} != recorded "
                  f"{expected}")
            return problems
    # tensor-level check: every indexed tensor present with the recorded
    # shape/dtype (also catches a truncated-but-valid-zip npz)
    try:
        with np.load(data_path, allow_pickle=False) as data:
            files = set(data.files)
            for key, meta in tensors.items():
                lay = meta.get("sharded_layout")
                if lay:
                    # flat per-shard save: every shard entry present
                    # with its recorded shape, dtype matching the
                    # logical tensor's
                    for sh in lay.get("shards", []):
                        nk = _npz_key(sh["key"])
                        if nk not in files:
                            _fail("tensor_mismatch",
                                  f"{prefix}: shard {sh['key']!r} of "
                                  f"{key!r} in index but not in data "
                                  "file")
                            continue
                        arr = data[nk]
                        if list(arr.shape) != list(sh.get("shape", [])) \
                                or str(arr.dtype) != meta.get("dtype"):
                            _fail("tensor_mismatch",
                                  f"{prefix}: shard {sh['key']!r} is "
                                  f"{arr.dtype}{list(arr.shape)}, index "
                                  f"says {meta.get('dtype')}"
                                  f"{sh.get('shape')}")
                    continue
                nk = _npz_key(key)
                if nk not in files:
                    _fail("tensor_mismatch",
                          f"{prefix}: tensor {key!r} in index but not "
                          "in data file")
                    continue
                arr = data[nk]
                if list(arr.shape) != list(meta.get("shape", [])) or \
                        str(arr.dtype) != meta.get("dtype"):
                    _fail("tensor_mismatch",
                          f"{prefix}: tensor {key!r} is "
                          f"{arr.dtype}{list(arr.shape)}, index says "
                          f"{meta.get('dtype')}{meta.get('shape')}")
    except Exception as e:  # noqa: BLE001 — any load failure = corrupt
        _fail("tensor_mismatch", f"{data_path}: unreadable npz ({e})")
    return problems
