"""Atomic file commit protocol for checkpoints.

Every durable checkpoint artifact (tensor bundle, index, ``checkpoint``
state file) goes through ONE code path: write to a temp file in the
same directory, flush + fsync, ``os.replace`` over the destination,
then best-effort fsync of the directory entry. ``os.replace`` is atomic
on POSIX, so a reader (or a crash at ANY point) sees either the old
complete file or the new complete file — never a partial write (the
tensor_bundle writer in the reference makes the same guarantee via its
temp-then-rename commit, core/util/tensor_bundle/tensor_bundle.cc).

Fault injection: tests register a hook (``set_fault_hook``) that is
called at every named commit point (``"<label>:<point>"``) and may
raise or ``os._exit`` to simulate a crash mid-commit — the
crash-injection suite in tests/test_checkpoint.py drives every point
and asserts ``latest_checkpoint()`` always restores a checksum-valid
state.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Callable, Optional

# ordered commit points per file write; fault hooks receive
# "<label>:<point>" so a test can target e.g. "index:synced_tmp"
COMMIT_POINTS = ("open_tmp", "wrote_tmp", "synced_tmp", "replaced",
                 "dir_synced")

_fault_hook: Optional[Callable[[str], None]] = None


def set_fault_hook(hook: Optional[Callable[[str], None]]):
    """Install (or clear, with None) the crash-injection hook. Returns
    the previous hook so tests can restore it."""
    global _fault_hook
    prev, _fault_hook = _fault_hook, hook
    return prev


def _fault(point: str) -> None:
    if _fault_hook is not None:
        _fault_hook(point)


def checksum_bytes(data: bytes) -> str:
    """Content checksum in the ``sha256:<hex>`` form recorded in
    checkpoint indexes."""
    return "sha256:" + hashlib.sha256(data).hexdigest()


def checksum_file(path: str, chunk_size: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_size)
            if not chunk:
                break
            h.update(chunk)
    return "sha256:" + h.hexdigest()


def atomic_write_bytes(path: str, data: bytes, fsync: bool = True,
                       label: Optional[str] = None) -> None:
    """Commit ``data`` to ``path`` atomically (see module docstring).

    ``fsync=False`` skips the durability syncs (still atomic against
    concurrent readers, not against power loss) — used only by paths
    that explicitly opt out, never by checkpoint commits.
    """
    label = label if label is not None else os.path.basename(path)
    d = os.path.dirname(path) or "."
    # dotfile temp name: directory listings / GC / ckpt_inspect ignore it
    tmp = os.path.join(
        d, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        _fault(f"{label}:open_tmp")
        try:
            with os.fdopen(fd, "wb", closefd=False) as f:
                f.write(data)
                _fault(f"{label}:wrote_tmp")
                f.flush()
                if fsync:
                    os.fsync(fd)
        finally:
            os.close(fd)
        _fault(f"{label}:synced_tmp")
        os.replace(tmp, path)
        _fault(f"{label}:replaced")
        if fsync:
            # fsync the directory so the rename itself is durable;
            # best-effort — not every filesystem supports dir fds
            try:
                dfd = os.open(d, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass
        _fault(f"{label}:dir_synced")
    except BaseException:
        # an aborted commit must not litter half-written temp files
        # (a crash-kill still can; they are dotfiles readers ignore)
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj, fsync: bool = True,
                      label: Optional[str] = None) -> None:
    atomic_write_bytes(path, json.dumps(obj, indent=1).encode("utf-8"),
                       fsync=fsync, label=label)
