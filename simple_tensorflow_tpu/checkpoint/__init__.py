"""stf.checkpoint: async checkpointing and preemption-safe training
(docs/CHECKPOINT.md).

The checkpoint plane over the stf-bundle format ``train.Saver`` writes:

- **Async saves** — ``CheckpointManager.save`` (or
  ``train.Saver(backend="async")`` / the default
  ``CheckpointSaverHook``) takes a donation-safe *barrier snapshot* of
  the device-resident training state (variables + optimizer slots +
  global_step + RNG run counters + data iterator positions) at a fused-
  window boundary, then serializes and commits on the background
  ``stf_ckpt_writer`` thread so the next ``run_steps`` window overlaps
  the I/O.
- **Atomic commit protocol** — every artifact goes through temp + fsync
  + ``os.replace`` with a content checksum in the index, data → index →
  state-file ordering: a crash at ANY point leaves the previous
  checkpoint loadable (crash-injection tested).
- **CheckpointManager** — retention, garbage collection, integrity
  verification on restore, ``restore_or_initialize`` resuming mid-epoch.
- **Preemption handling** — SIGTERM (chained onto telemetry's
  dispositions) → drain the in-flight fused window → save → clean exit;
  ``MonitoredTrainingSession`` resumes bit-exact.

Inspect/verify on-disk checkpoints with
``python -m simple_tensorflow_tpu.tools.ckpt_inspect <dir>``.
"""

from . import metrics  # registers the /stf/checkpoint/* families
from .atomic import (COMMIT_POINTS, atomic_write_bytes, atomic_write_json,
                     checksum_bytes, checksum_file, set_fault_hook)
from .snapshot import (TrainingStateSnapshot, capture_training_state,
                       verify_checkpoint, write_native_checkpoint)
from .writer import (CheckpointWriter, PendingCheckpoint, get_writer,
                     shutdown_writer, wait_until_finished)
from .manager import AsyncSaverEngine, CheckpointManager
from .preemption import (PreemptionHandler, install_preemption_handler,
                         preemption_requested, request_preemption,
                         reset_preemption_state,
                         uninstall_preemption_handler)

__all__ = [
    "COMMIT_POINTS", "atomic_write_bytes", "atomic_write_json",
    "checksum_bytes", "checksum_file", "set_fault_hook",
    "TrainingStateSnapshot", "capture_training_state",
    "verify_checkpoint", "write_native_checkpoint",
    "CheckpointWriter", "PendingCheckpoint", "get_writer",
    "shutdown_writer", "wait_until_finished",
    "AsyncSaverEngine", "CheckpointManager",
    "PreemptionHandler", "install_preemption_handler",
    "preemption_requested", "request_preemption",
    "reset_preemption_state", "uninstall_preemption_handler",
    "metrics",
]
