"""CheckpointManager and the async save engine.

``AsyncSaverEngine`` gives any ``train.Saver`` an async save path: the
step loop pays only the barrier snapshot (donation-safe device copies +
host state, ``Session.snapshot_device_state``) and one queue put; the
``stf_ckpt_writer`` thread materializes, serializes, commits (atomic
data → index → ``checkpoint`` state-file ordering), applies retention,
and surfaces any failure on the caller's next ``save()`` /
``wait_until_finished()``.

``CheckpointManager`` (ref: the role of tf.train.CheckpointManager)
owns a directory: retention (max_to_keep / keep_checkpoint_every_n_
hours), garbage collection, integrity verification on restore, and
``restore_or_initialize`` that reconstructs the FULL training state —
variables, optimizer slots, global_step, RNG run counters, data
iterator positions — mid-epoch.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..framework import errors
from ..platform import monitoring
from ..platform import sync as _sync
from . import metrics as _m
from . import snapshot as snapshot_mod
from . import writer as writer_mod


def _flight():
    from ..telemetry import recorder

    return recorder.get_recorder()


class AsyncSaverEngine:
    """Async save path over an existing ``train.Saver``'s variable set
    and retention bookkeeping (native backend only — orbax ships its
    own async machinery)."""

    def __init__(self, saver):
        if getattr(saver, "_backend", "native") not in ("native",
                                                        "async"):
            raise ValueError(
                "AsyncSaverEngine writes the native stf-bundle format; "
                f"got a backend={saver._backend!r} Saver")
        self._saver = saver
        self._lock = _sync.Lock("checkpoint/manager",
                                rank=_sync.RANK_ENGINE)
        self._pending: List[writer_mod.PendingCheckpoint] = []
        self._unraised_error: Optional[BaseException] = None

    # -- error surfacing ------------------------------------------------------
    def _collect_errors(self):
        with self._lock:
            done = [p for p in self._pending if p.done]
            self._pending = [p for p in self._pending if not p.done]
            for p in done:
                if p.error is not None and self._unraised_error is None:
                    self._unraised_error = p.error

    def check_error(self):
        """Raise (once) the first failure of any previously enqueued
        write — an async save must never fail silently."""
        self._collect_errors()
        with self._lock:
            err, self._unraised_error = self._unraised_error, None
        if err is not None:
            raise err

    # -- save -----------------------------------------------------------------
    def save(self, sess, save_path, global_step=None, latest_filename=None,
             write_meta_graph=True, write_state=True) -> str:
        from ..train import saver as saver_mod

        self.check_error()
        saver = self._saver
        step_val = saver_mod.resolve_global_step(sess, global_step)
        prefix = f"{save_path}-{step_val}" if step_val is not None \
            else save_path
        t0 = time.perf_counter()
        snap = snapshot_mod.capture_training_state(sess, saver._vars())
        snap.step = step_val
        graph = sess.graph

        def job():
            arrays = snap.materialize()
            snapshot_mod.write_native_checkpoint(
                prefix, arrays, snap.tensor_index, snap.host_state)
            # device copies served their purpose the moment the host
            # npz is durable: drop them (and their ledger accounting —
            # class "snapshot" returns to baseline; ISSUE 13)
            snap.release_device_state()
            if write_meta_graph:
                try:
                    from ..framework import graph_io

                    graph_io.export_meta_graph(prefix + ".meta",
                                               graph=graph)
                except Exception as e:  # noqa: BLE001 — advisory artifact
                    from ..platform import tf_logging as logging

                    logging.warning(
                        "async checkpoint: meta-graph export to %s.meta "
                        "failed (%s); checkpoint tensors were saved.",
                        prefix, e)
            # state file LAST: only a fully durable checkpoint may
            # become `latest_checkpoint`
            saver._manage_old(prefix)
            if write_state:
                saver_mod.update_checkpoint_state(
                    os.path.dirname(prefix) or ".", prefix,
                    [p for p, _ in saver._last_checkpoints],
                    latest_filename)
            _m.saves.get_cell("async").increase_by(1)
            _flight().record("checkpoint", action="save", mode="async",
                             prefix=prefix,
                             step=-1 if step_val is None else step_val)
            return prefix

        pending = writer_mod.get_writer().submit(job, description=prefix)
        with self._lock:
            self._pending.append(pending)
        _m.save_stall_seconds.get_cell("async").add(
            time.perf_counter() - t0)
        return prefix

    def wait_until_finished(self, timeout: Optional[float] = None):
        with self._lock:
            pendings = list(self._pending)
        for p in pendings:
            if not p._done.wait(timeout):
                raise TimeoutError(
                    f"checkpoint write {p.description!r} still pending")
        self.check_error()


class CheckpointManager:
    """Directory-owning checkpoint plane (docs/CHECKPOINT.md)."""

    def __init__(self, directory, max_to_keep=5,
                 keep_checkpoint_every_n_hours=10000.0,
                 checkpoint_basename="model.ckpt", saver=None,
                 var_list=None, async_save=True, write_meta_graph=False,
                 latest_filename=None):
        from ..train import saver as saver_mod

        self._directory = str(directory)
        self._latest_filename = latest_filename
        self._write_meta_graph = write_meta_graph
        os.makedirs(self._directory, exist_ok=True)
        if saver is None:
            saver = saver_mod.Saver(
                var_list=var_list, max_to_keep=max_to_keep,
                keep_checkpoint_every_n_hours=keep_checkpoint_every_n_hours)
        self._saver = saver
        # adopt pre-existing checkpoints so retention counts them
        st = saver_mod.get_checkpoint_state(self._directory,
                                            latest_filename)
        if st is not None and st.all_model_checkpoint_paths:
            self._saver.recover_last_checkpoints(
                st.all_model_checkpoint_paths)
        self._async = bool(async_save) and \
            getattr(saver, "_backend", "native") in ("native", "async")
        self._engine = AsyncSaverEngine(saver) if self._async else None
        self._save_path = os.path.join(self._directory,
                                       checkpoint_basename)

    # -- properties -----------------------------------------------------------
    @property
    def directory(self) -> str:
        return self._directory

    @property
    def saver(self):
        return self._saver

    @property
    def latest_checkpoint(self) -> Optional[str]:
        from ..train import saver as saver_mod

        return saver_mod.latest_checkpoint(self._directory,
                                           self._latest_filename)

    @property
    def checkpoints(self) -> List[str]:
        """All registered checkpoint prefixes, oldest first."""
        from ..train import saver as saver_mod

        st = saver_mod.get_checkpoint_state(self._directory,
                                            self._latest_filename)
        return list(st.all_model_checkpoint_paths) if st else []

    # -- save/restore ---------------------------------------------------------
    def save(self, sess, global_step=None, blocking: Optional[bool] = None
             ) -> str:
        """Checkpoint the session's full training state. Async by
        default (construction-time ``async_save``): returns as soon as
        the barrier snapshot is captured; ``blocking=True`` (or a
        non-async manager) additionally waits for the commit."""
        if self._engine is not None:
            prefix = self._engine.save(
                sess, self._save_path, global_step=global_step,
                latest_filename=self._latest_filename,
                write_meta_graph=self._write_meta_graph)
            if blocking:
                self._engine.wait_until_finished()
            return prefix
        return self._saver.save(
            sess, self._save_path, global_step=global_step,
            latest_filename=self._latest_filename,
            write_meta_graph=self._write_meta_graph)

    def verify(self, checkpoint_path: Optional[str] = None) -> List[str]:
        """Integrity problems of one checkpoint (default: latest);
        empty list = verified."""
        path = checkpoint_path or self.latest_checkpoint
        if path is None:
            return [f"{self._directory}: no checkpoint found"]
        return snapshot_mod.verify_checkpoint(path)

    def restore(self, sess, checkpoint_path: Optional[str] = None,
                verify: bool = True) -> str:
        """Restore the full training state from ``checkpoint_path``
        (default: latest), verifying integrity first."""
        from ..train import saver as saver_mod

        path = checkpoint_path or self.latest_checkpoint
        if path is None or not saver_mod.checkpoint_exists(path):
            _m.restores.get_cell("not_found").increase_by(1)
            raise errors.NotFoundError(
                None, None,
                f"No checkpoint found at "
                f"{path or self._directory}")
        t0 = time.perf_counter()
        with monitoring.traceme("checkpoint_restore", prefix=path):
            if verify:
                problems = snapshot_mod.verify_checkpoint(path)
                if problems:
                    _m.restores.get_cell("verify_failed").increase_by(1)
                    raise errors.DataLossError(
                        None, None,
                        f"Checkpoint {path} failed verification:\n  "
                        + "\n  ".join(problems))
            # checksum either just verified above or explicitly opted
            # out of (verify=False skips ALL integrity checking, incl.
            # restore_or_initialize re-entering after its own verify
            # pass) — don't re-read + re-hash the bundle inside restore
            self._saver.restore(sess, path, verify_checksum=False)
        _m.restores.get_cell("ok").increase_by(1)
        _m.restore_seconds.get_cell().add(time.perf_counter() - t0)
        _flight().record("checkpoint", action="restore", prefix=path)
        return path

    def restore_or_initialize(self, sess, init_op=None,
                              init_feed_dict=None, init_fn=None,
                              verify: bool = True) -> Optional[str]:
        """Restore the newest checkpoint that passes verification
        (falling back to older ones on corruption), else run the
        provided initializer(s). Returns the restored prefix, or None
        when the session was initialized fresh."""
        from ..platform import tf_logging as logging

        seen = set()
        candidates = []
        latest = self.latest_checkpoint
        if latest:
            candidates.append(latest)
            seen.add(latest)
        for p in reversed(self.checkpoints):
            if p not in seen:
                candidates.append(p)
                seen.add(p)
        for path in candidates:
            problems = snapshot_mod.verify_checkpoint(path) if verify \
                else []
            if problems:
                _m.restores.get_cell("verify_failed").increase_by(1)
                logging.warning(
                    "CheckpointManager: %s failed verification (%s); "
                    "trying an older checkpoint.", path,
                    "; ".join(problems))
                continue
            try:
                self.restore(sess, path, verify=False)
                return path
            except errors.OpError as e:
                _m.restores.get_cell("error").increase_by(1)
                logging.warning(
                    "CheckpointManager: restore of %s failed (%s); "
                    "trying an older checkpoint.", path, e)
        if init_op is not None:
            sess.run(init_op, feed_dict=init_feed_dict)
        if init_fn is not None:
            init_fn(sess)
        return None

    # -- lifecycle ------------------------------------------------------------
    def wait_until_finished(self, timeout: Optional[float] = None):
        """Block until every async save enqueued by this manager has
        committed; re-raises the first failure."""
        if self._engine is not None:
            self._engine.wait_until_finished(timeout)

    def close(self):
        self.wait_until_finished()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # don't mask an in-flight exception with a deferred write error
        try:
            self.close()
        except Exception:  # noqa: BLE001
            if exc and exc[0] is None:
                raise
        return False
