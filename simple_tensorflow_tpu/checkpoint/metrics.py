"""/stf/checkpoint/* metric families (docs/OBSERVABILITY.md catalog).

One module so importing ``stf.checkpoint`` registers the whole family —
the metric-catalog drift gate (tests/test_metrics_catalog.py) compares
the registry against the docs table at import time.
"""

from __future__ import annotations

from ..platform import monitoring

saves = monitoring.Counter(
    "/stf/checkpoint/saves",
    "Completed checkpoint saves, by mode (async = barrier snapshot + "
    "background write, blocking = in-line Saver.save)", "mode")
save_stall_seconds = monitoring.Sampler(
    "/stf/checkpoint/save_stall_seconds",
    monitoring.ExponentialBuckets(1e-5, 2.0, 24),
    "Seconds the step loop was blocked per save (async: device-copy "
    "snapshot + enqueue; blocking: full serialize + fsync)", "mode")
write_seconds = monitoring.Sampler(
    "/stf/checkpoint/write_seconds",
    monitoring.ExponentialBuckets(1e-4, 2.0, 24),
    "Background serialize+commit seconds per checkpoint on the "
    "stf_ckpt_writer thread")
bytes_written = monitoring.Counter(
    "/stf/checkpoint/bytes_written",
    "Checkpoint payload bytes committed (tensor data + index)")
pending_writes = monitoring.IntGauge(
    "/stf/checkpoint/pending_writes",
    "Queued + in-flight async checkpoint writes")
write_errors = monitoring.Counter(
    "/stf/checkpoint/write_errors",
    "Background checkpoint writes that failed (the error re-raises on "
    "the next save()/wait_until_finished())")
restores = monitoring.Counter(
    "/stf/checkpoint/restores",
    "Checkpoint restore attempts, by outcome", "outcome")
restore_seconds = monitoring.Sampler(
    "/stf/checkpoint/restore_seconds",
    monitoring.ExponentialBuckets(1e-4, 2.0, 24),
    "Seconds per restore (verify + tensor load + host-state rebuild)")
integrity_failures = monitoring.Counter(
    "/stf/checkpoint/integrity_failures",
    "Checkpoint verification failures, by kind", "kind")
gc_deleted = monitoring.Counter(
    "/stf/checkpoint/gc_deleted",
    "Old checkpoints deleted by retention (max_to_keep / "
    "keep_checkpoint_every_n_hours)")
preemptions = monitoring.Counter(
    "/stf/checkpoint/preemptions",
    "Preemption signals observed (SIGTERM -> drain window -> save -> "
    "clean stop)")
