"""The ``stf_ckpt_writer`` background thread.

One process-global daemon thread drains a FIFO of checkpoint-commit
jobs, so (a) async saves from any session serialize in submission
order — the ``checkpoint`` state file only ever advances monotonically
— and (b) the step loop's only cost per save is the barrier snapshot +
one queue put. Job failures are recorded (``/stf/checkpoint/
write_errors``, flight-recorder ``checkpoint`` event) and re-raised to
the caller on its next ``save()`` / ``wait_until_finished()`` — an
async save must never fail silently.

Lifecycle mirrors the telemetry watchdog: lazy start on first submit,
``shutdown_writer()`` stops it (tests/conftest.py leak fixture does so
after every module), next submit restarts it.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Optional

from ..platform import monitoring
from ..platform import sync as _sync
from . import metrics as _m

_THREAD_NAME = "stf_ckpt_writer"


class PendingCheckpoint:
    """Handle for one queued async checkpoint write."""

    __slots__ = ("description", "_done", "error", "result")

    def __init__(self, description: str = ""):
        self.description = description
        self._done = threading.Event()
        self.error: Optional[BaseException] = None
        self.result: Any = None

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> Any:
        """Block until the write committed; re-raises its failure."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"checkpoint write {self.description!r} still pending "
                f"after {timeout}s")
        if self.error is not None:
            raise self.error
        return self.result


class CheckpointWriter:
    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()
        self._lock = _sync.Lock("checkpoint/writer_queue",
                                rank=_sync.RANK_QUEUE)
        # serializes submit() against a concurrent stop(): without it a
        # submit landing between stop's sentinel-put and the worker's
        # exit would queue a job BEHIND the sentinel on a thread that
        # is about to return — stranding the write with no error
        # blocking_ok: stop() joins the worker under this lock by
        # design (see stop()); runtime_lint honours the flag
        self._lifecycle = _sync.Lock("checkpoint/writer_lifecycle",
                                     rank=_sync.RANK_LIFECYCLE,
                                     blocking_ok=True)
        self._thread: Optional[threading.Thread] = None
        self._idle = threading.Event()
        self._idle.set()

    # -- submission -----------------------------------------------------------
    def submit(self, job: Callable[[], Any],
               description: str = "") -> PendingCheckpoint:
        pending = PendingCheckpoint(description)
        with self._lifecycle:
            with self._lock:
                self._ensure_thread()
                self._idle.clear()
                self._q.put((job, pending))
                _m.pending_writes.get_cell().set(self._q.qsize())
        return pending

    def _ensure_thread(self):
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(target=self._run,
                                        name=_THREAD_NAME, daemon=True)
        self._thread.start()

    # -- draining -------------------------------------------------------------
    def _run(self):
        from ..telemetry import recorder as _flight

        while True:
            item = self._q.get()
            if item is None:
                # belt-and-braces: fail (never strand) anything that
                # slipped in behind the sentinel — waiters must always
                # complete, with the error surfaced
                while True:
                    try:
                        leftover = self._q.get_nowait()
                    except queue.Empty:
                        break
                    if leftover is not None:
                        _, p = leftover
                        p.error = RuntimeError(
                            "checkpoint writer stopped before this "
                            f"write committed: {p.description!r}")
                        _m.write_errors.get_cell().increase_by(1)
                        p._done.set()
                    self._q.task_done()
                self._q.task_done()
                _m.pending_writes.get_cell().set(0)
                if self._q.unfinished_tasks == 0:
                    self._idle.set()
                return
            job, pending = item
            t0 = time.perf_counter()
            try:
                with monitoring.traceme("checkpoint_write",
                                        what=pending.description):
                    pending.result = job()
            except BaseException as e:  # noqa: BLE001 — surfaced to caller
                pending.error = e
                _m.write_errors.get_cell().increase_by(1)
                _flight.get_recorder().on_error(
                    e, where="checkpoint_write",
                    description=pending.description)
            finally:
                _m.write_seconds.get_cell().add(
                    time.perf_counter() - t0)
                pending._done.set()
                self._q.task_done()
                with self._lock:
                    _m.pending_writes.get_cell().set(
                        max(0, self._q.qsize()))
                    if self._q.unfinished_tasks == 0:
                        self._idle.set()
                # drop the job closure NOW: holding it until the next
                # queue item would pin its snapshot's device copies
                # (and their HBM-ledger "snapshot" bytes) across the
                # writer's idle stretches
                del job, pending, item

    def wait_until_finished(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted job has completed (success OR
        failure — per-job errors surface through their pending
        handles). Returns False on timeout."""
        return self._idle.wait(timeout)

    def stop(self, timeout: float = 5.0) -> bool:
        """Drain remaining jobs, then stop the thread. Idempotent; the
        next submit() lazily restarts it. Holds the lifecycle lock
        through the join so no submit can interleave with the shutdown
        sentinel."""
        from ..telemetry import recorder as _flight

        with self._lifecycle:
            with self._lock:
                t = self._thread
                if t is None or not t.is_alive():
                    self._thread = None
                    return True
                self._q.put(None)
            # checked: a write job wedged past the deadline emits a
            # flight `wedge` event with the worker's stack (and fails
            # the test-suite leak fixture via the False return)
            alive = not _flight.checked_join(
                t, timeout, "CheckpointWriter.stop")
            with self._lock:
                if self._thread is t and not alive:
                    self._thread = None
            return not alive

    @property
    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()


_WRITER = CheckpointWriter()


def get_writer() -> CheckpointWriter:
    return _WRITER


def wait_until_finished(timeout: Optional[float] = None) -> bool:
    """Module-level convenience: drain ALL pending async checkpoint
    writes in the process."""
    return _WRITER.wait_until_finished(timeout)


def shutdown_writer(timeout: float = 5.0) -> bool:
    return _WRITER.stop(timeout)
