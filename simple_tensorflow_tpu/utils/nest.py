"""stf.nest: structure flatten/pack utilities
(ref: tensorflow/python/util/nest.py — the public structure helpers TF
programs use everywhere; VERDICT missing #5).

Reference semantics, pinned exactly (where ``jax.tree_util`` — the
machinery the lowering itself uses — differs, the structural walk here
is done directly rather than delegated):

- ``None`` is an ATOM (a leaf), not an empty structure (jax's default
  treats None as an empty subtree),
- EVERY mapping flattens in ``sorted(keys)`` order — including
  OrderedDict and other dict subclasses, which jax flattens in
  insertion order (silently mispairing map_structure otherwise),
- namedtuples are structures and their type is preserved on packing;
  packing a mapping preserves its type and original key order,
- strings are atoms.

Conformance against the reference's documented behavior is pinned in
tests/test_nest.py.
"""

from __future__ import annotations

from typing import Any, List

__all__ = ["assert_same_structure", "flatten", "is_nested", "is_sequence",
           "map_structure", "pack_sequence_as"]


def _is_namedtuple(x) -> bool:
    return isinstance(x, tuple) and hasattr(x, "_fields")


def is_sequence(structure) -> bool:
    """True for list/tuple/dict/namedtuple — NOT for strings, numpy
    arrays, or Tensors (ref: nest.py ``is_sequence``)."""
    return isinstance(structure, (list, tuple, dict)) \
        and not isinstance(structure, str)


def is_nested(structure) -> bool:
    return is_sequence(structure)


def flatten(structure) -> List[Any]:
    """Flatten a (possibly nested) structure into a flat list of its
    atoms, mappings in sorted-key order; an atom flattens to ``[atom]``
    (ref: nest.py ``flatten``)."""
    out: List[Any] = []

    def rec(s):
        if not is_sequence(s):
            out.append(s)
        elif isinstance(s, dict):
            for k in sorted(s):
                rec(s[k])
        else:
            for x in s:
                rec(x)

    rec(structure)
    return out


def _sequence_like(instance, values):
    """Rebuild a structure of ``instance``'s type from child values
    (ref: nest.py ``_sequence_like``). For mappings, ``values`` arrive
    in sorted-key order and the result keeps the ORIGINAL key order."""
    if isinstance(instance, dict):
        by_key = dict(zip(sorted(instance), values))
        try:
            return type(instance)((k, by_key[k]) for k in instance)
        except TypeError:
            # dict subclass with a non-standard constructor
            # (e.g. defaultdict takes a factory first): plain dict
            return {k: by_key[k] for k in instance}
    if _is_namedtuple(instance):
        return type(instance)(*values)
    return type(instance)(values)


def pack_sequence_as(structure, flat_sequence):
    """Pack ``flat_sequence`` into the shape of ``structure``
    (ref: nest.py ``pack_sequence_as``). Raises ValueError when the
    lengths disagree."""
    flat = list(flat_sequence)
    if not is_sequence(structure):
        if len(flat) != 1:
            raise ValueError(
                f"Structure is a scalar but len(flat_sequence)="
                f"{len(flat)} > 1")
        return flat[0]
    it = iter(flat)

    def rec(s):
        if not is_sequence(s):
            try:
                return next(it)
            except StopIteration:
                raise ValueError(
                    f"Could not pack sequence: structure has more atoms "
                    f"than flat_sequence ({len(flat)}). "
                    f"Structure: {structure!r}.")
        if isinstance(s, dict):
            vals = [rec(s[k]) for k in sorted(s)]
        else:
            vals = [rec(x) for x in s]
        return _sequence_like(s, vals)

    packed = rec(structure)
    leftovers = sum(1 for _ in it)
    if leftovers:
        raise ValueError(
            f"Could not pack sequence: flat_sequence has {leftovers} "
            f"more atoms than the structure. Structure: {structure!r}.")
    return packed


def assert_same_structure(nest1, nest2, check_types: bool = True) -> None:
    """Raise ValueError when the two structures differ in shape, or
    TypeError when ``check_types`` and a substructure differs in type
    (list vs tuple, tuple vs namedtuple...) — reference nest.py
    semantics."""

    def rec(a, b):
        a_seq, b_seq = is_sequence(a), is_sequence(b)
        if a_seq != b_seq:
            raise ValueError(
                "The two structures don't have the same nested "
                f"structure: {nest1!r} vs {nest2!r}.")
        if not a_seq:
            return
        if check_types and type(a) is not type(b):
            # dict subclasses with equal keys pass (the reference only
            # enforces strict types on sequences/namedtuples)
            if not (isinstance(a, dict) and isinstance(b, dict)
                    and sorted(a) == sorted(b)):
                raise TypeError(
                    "The two structures don't have the same sequence "
                    f"type: {type(a).__name__} vs {type(b).__name__}.")
        if isinstance(a, dict):
            if sorted(a) != sorted(b):
                raise ValueError(
                    f"The two dictionaries don't have the same set of "
                    f"keys: {sorted(a)} vs {sorted(b)}.")
            for k in sorted(a):
                rec(a[k], b[k])
            return
        if len(a) != len(b):
            raise ValueError(
                "The two structures don't have the same number of "
                f"elements: {len(a)} vs {len(b)}.")
        for x, y in zip(a, b):
            rec(x, y)

    rec(nest1, nest2)


def map_structure(func: Callable, *structures, **kwargs):
    """Apply ``func`` atom-wise across structurally identical nests,
    returning a nest shaped like the first (ref: nest.py
    ``map_structure``)."""
    check_types = kwargs.pop("check_types", True)
    if kwargs:
        raise ValueError(f"Unknown keyword arguments: {list(kwargs)}")
    if not callable(func):
        raise TypeError(f"func must be callable, got {func!r}")
    if not structures:
        raise ValueError("Must provide at least one structure")
    for other in structures[1:]:
        assert_same_structure(structures[0], other,
                              check_types=check_types)
    flats = [flatten(s) for s in structures]
    results = [func(*atoms) for atoms in zip(*flats)]
    return pack_sequence_as(structures[0], results)
