"""Performance tracing: step timing, MFU, roofline estimates
(ref role: tensorflow/core/common_runtime/step_stats_collector.cc + the
timeline tooling; TPU-native it reads XLA cost analysis + jax.profiler).

- StepTimer: wall-per-step ring buffer with percentile summary.
- mfu(): achieved FLOP/s over the chip's bf16 peak from the compiled
  executable's XLA cost analysis (flops) + measured step time.
- roofline(): bytes-accessed/flops arithmetic intensity vs the chip's
  HBM bandwidth — says whether a step is compute- or bandwidth-bound.
- trace(): context manager around jax.profiler for chrome://tracing dumps.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, List, Optional

import numpy as np

# per-chip peaks (bf16 FLOP/s, HBM bytes/s)
_CHIP_SPECS = {
    "v5e": (197e12, 819e9),
    "v5 lite": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
    "v3": (123e12, 900e9),
    "v2": (46e12, 700e9),
}
_DEFAULT_SPEC = (197e12, 819e9)

_CHIP_HBM_BYTES = {
    "v5e": 16e9,
    "v5 lite": 16e9,
    "v5p": 95e9,
    "v4": 32e9,
    "v3": 32e9,
    "v2": 16e9,
}
_DEFAULT_HBM = 16e9


def chip_spec(device=None):
    """(peak_flops, peak_hbm_bw) for the attached device."""
    import jax

    d = device or jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    for key, spec in _CHIP_SPECS.items():
        if key in kind:
            return spec
    if d.platform == "cpu":
        return (1e12, 100e9)  # nominal, for CI math
    return _DEFAULT_SPEC


def chip_hbm_bytes(device=None):
    """Per-chip HBM capacity for the attached device (memory-planning
    inputs: remat decisions, pipeline microbatch sizing)."""
    import jax

    d = device or jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    for key, size in _CHIP_HBM_BYTES.items():
        if key in kind:
            return size
    if d.platform == "cpu":
        return 4e9  # nominal, for CI math
    return _DEFAULT_HBM


class StepTimer:
    """Wall-clock per-step stats; call mark() after each synced step."""

    def __init__(self, window=200):
        self._times: List[float] = []
        self._window = window
        self._last: Optional[float] = None

    def start(self):
        self._last = time.perf_counter()

    def mark(self) -> float:
        now = time.perf_counter()
        dt = now - (self._last if self._last is not None else now)
        self._last = now
        self._times.append(dt)
        if len(self._times) > self._window:
            self._times.pop(0)
        return dt

    @property
    def steps(self) -> int:
        return len(self._times)

    def summary(self) -> Dict[str, float]:
        if not self._times:
            return {}
        a = np.asarray(self._times)
        return {"mean_s": float(a.mean()),
                "p50_s": float(np.percentile(a, 50)),
                "p90_s": float(np.percentile(a, 90)),
                "steps_per_sec": float(1.0 / a.mean())}


def cost_of(compiled) -> Dict[str, float]:
    """Normalize jax cost analysis across versions: {flops, bytes}."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not ca:
        return {}
    return {"flops": float(ca.get("flops", 0.0)),
            "bytes": float(ca.get("bytes accessed", 0.0))}


def _aval_bytes(avals) -> int:
    """Sum of abstract-shape byte sizes over a (nested) aval pytree."""
    total = 0
    stack = [avals]
    while stack:
        a = stack.pop()
        if a is None:
            continue
        if isinstance(a, (list, tuple)):
            stack.extend(a)
            continue
        if isinstance(a, dict):
            stack.extend(a.values())
            continue
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for d in shape:
            try:
                n *= int(d)
            except (TypeError, ValueError):
                n = 0
                break
        total += n * int(np.dtype(dtype).itemsize)
    return total


def memory_of(compiled, lowered=None) -> Dict[str, int]:
    """Normalize jax ``Compiled.memory_analysis()`` across versions:
    {argument_bytes, output_bytes, temp_bytes, alias_bytes,
    generated_code_bytes, peak_bytes} (peak ≈ arguments + outputs + XLA
    temp allocation, minus aliased/donated buffers counted twice).

    Backends that expose no (or an all-zero) ``memory_analysis`` fall
    back to summing the XLA cost-analysis byte components plus
    abstract-shape sizes from the executable's avals (ISSUE 13
    satellite: tier-1 CPU runs must still produce peak/argument/output
    stats so the memory-accounting plane is testable without TPU).
    Fallback results carry ``"estimated": 1``."""
    ma = None
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        out = {
            "argument_bytes": int(getattr(ma, "argument_size_in_bytes",
                                          0)),
            "output_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
            "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
            "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
            "generated_code_bytes": int(
                getattr(ma, "generated_code_size_in_bytes", 0)),
        }
        if any(out.values()):
            # aliased (donated) buffers are counted in both argument
            # and output sizes but exist once on device — subtract
            # them from the peak
            out["peak_bytes"] = (out["argument_bytes"]
                                 + out["output_bytes"]
                                 + out["temp_bytes"]
                                 - out["alias_bytes"])
            return out
    # -- fallback: cost-analysis components + aval sizes ---------------------
    arg_bytes = 0
    out_bytes = 0
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ca = ca or {}
        out_bytes = int(ca.get("bytes accessedout{}", 0))
        arg_bytes = int(sum(
            v for k, v in ca.items()
            if k.startswith("bytes accessed") and k != "bytes accessed"
            and k != "bytes accessedout{}"))
    except Exception:
        ca = {}
    if not arg_bytes:
        for src in (compiled, lowered):
            avals = getattr(src, "in_avals", None) if src is not None \
                else None
            if avals:
                arg_bytes = _aval_bytes(avals)
                break
    if not out_bytes and lowered is not None:
        out_bytes = _aval_bytes(getattr(lowered, "out_info", None))
    if not arg_bytes and not out_bytes:
        return {}
    out = {
        "argument_bytes": arg_bytes,
        "output_bytes": out_bytes,
        "temp_bytes": 0,
        "alias_bytes": 0,
        "generated_code_bytes": 0,
        "estimated": 1,
    }
    out["peak_bytes"] = arg_bytes + out_bytes
    return out


_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

# `%name = SHAPE all-reduce(...)` (async variants emit -start/-done
# pairs; only -start carries the payload — -done's trailing "(" will not
# match the pattern, so pairs count once)
_HLO_COLLECTIVE_RE = None


def collective_bytes_of(compiled) -> Dict[str, float]:
    """Per-kind payload bytes of the collective instructions in a
    compiled executable's (partitioned) HLO — the machine-checkable
    comparator for the sharding analyzer's predicted collective bytes
    (stf.analysis.sharding; the bench asserts the two within 25%).

    Sums the RESULT shape bytes of every all-reduce / all-gather /
    all-to-all / collective-permute / reduce-scatter instruction.
    Sync tuple-shaped results (variadic collectives) sum their leaves;
    an async ``-start``'s tuple is (operand, result[, u32 contexts]),
    so only the result leaf counts — summing it whole would tally the
    payload twice. Returns {} when the backend exposes no HLO text."""
    import re

    global _HLO_COLLECTIVE_RE
    if _HLO_COLLECTIVE_RE is None:
        _HLO_COLLECTIVE_RE = re.compile(
            r"=\s+((?:\([^)]*\))|(?:\S+))\s+"
            r"(all-reduce|all-gather|all-to-all|collective-permute|"
            r"reduce-scatter)(-start)?\(")
    texts = []
    try:
        mods = compiled.hlo_modules()
        texts = [m.to_string() for m in mods]
    except Exception:
        try:
            texts = [compiled.as_text()]
        except Exception:
            return {}
    shape_re = re.compile(r"([a-z]+[0-9]*[a-z0-9]*)\[([0-9,]*)\]")
    out: Dict[str, float] = {}
    for text in texts:
        for m in _HLO_COLLECTIVE_RE.finditer(text):
            shape_txt, kind, is_start = (m.group(1), m.group(2),
                                         m.group(3))
            leaves = []
            for sm in shape_re.finditer(shape_txt):
                dt = _HLO_DTYPE_BYTES.get(sm.group(1))
                if dt is None:
                    continue
                n = 1
                dims = sm.group(2)
                if dims:
                    for d in dims.split(","):
                        n *= int(d)
                leaves.append(n * dt)
            if is_start and len(leaves) >= 2:
                nbytes = float(leaves[1])
            else:
                nbytes = float(sum(leaves))
            if nbytes:
                out[kind] = out.get(kind, 0.0) + nbytes
    if out:
        out["total"] = sum(out.values())
    return out


def mfu(step_flops: float, step_seconds: float, device=None) -> float:
    """Model FLOPs Utilization: achieved/peak."""
    peak, _ = chip_spec(device)
    if step_seconds <= 0 or peak <= 0:
        return 0.0
    return step_flops / step_seconds / peak


def roofline(step_flops: float, step_bytes: float, device=None
             ) -> Dict[str, float]:
    """Arithmetic intensity vs the machine ridge point: intensity >
    ridge -> compute-bound (good: MXU busy); below -> HBM-bound (fuse
    more / recompute instead of re-reading)."""
    peak_flops, peak_bw = chip_spec(device)
    intensity = step_flops / step_bytes if step_bytes else float("inf")
    ridge = peak_flops / peak_bw
    attainable = min(peak_flops, intensity * peak_bw)
    return {"intensity_flops_per_byte": intensity,
            "ridge_point": ridge,
            "compute_bound": intensity >= ridge,
            "attainable_flops": attainable,
            "roofline_fraction_of_peak": attainable / peak_flops}


@contextlib.contextmanager
def trace(logdir: str):
    """jax.profiler trace -> TensorBoard / chrome://tracing (perfetto)."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (ref: tracing annotations)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class PerfReport:
    """Combines a compiled step's cost analysis with measured wall time."""

    def __init__(self, compiled=None, flops_per_step: Optional[float] = None,
                 device=None):
        self._cost = cost_of(compiled) if compiled is not None else {}
        if flops_per_step is not None:
            self._cost["flops"] = flops_per_step
        self._device = device
        self.timer = StepTimer()

    def step_done(self):
        return self.timer.mark()

    def report(self) -> Dict[str, Any]:
        s = self.timer.summary()
        if not s:
            return {}
        out = dict(s)
        flops = self._cost.get("flops")
        if flops:
            out["mfu"] = mfu(flops, s["mean_s"], self._device)
            out["achieved_tflops"] = flops / s["mean_s"] / 1e12
        if self._cost.get("bytes"):
            out.update(roofline(self._cost.get("flops", 0.0),
                                self._cost["bytes"], self._device))
        return out
