"""Utility subsystems: perf tracing/MFU/roofline (stf.utils.perf)."""

from . import perf  # noqa: F401
