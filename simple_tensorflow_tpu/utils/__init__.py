"""Utility subsystems: perf tracing/MFU/roofline (stf.utils.perf),
structure helpers (stf.nest re-exports stf.utils.nest)."""

from . import nest  # noqa: F401
from . import perf  # noqa: F401
