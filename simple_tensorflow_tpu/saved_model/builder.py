"""SavedModelBuilder (ref: tensorflow/python/saved_model/builder_impl.py).

Layout mirrors the reference: <dir>/saved_model.json (MetaGraphs +
signature_defs), <dir>/variables/variables.* (stf-bundle checkpoint),
<dir>/assets/.
"""

from __future__ import annotations

import json
import os
import shutil

from ..framework import graph as ops_mod
from ..framework import graph_io
from ..train.saver import Saver

SAVED_MODEL_FILENAME = "saved_model.json"
VARIABLES_DIRECTORY = "variables"
VARIABLES_FILENAME = "variables"
ASSETS_DIRECTORY = "assets"


class SavedModelBuilder:
    """(ref: builder_impl.py:40 ``class SavedModelBuilder``)."""

    def __init__(self, export_dir):
        self._export_dir = export_dir
        if os.path.exists(export_dir) and os.listdir(export_dir):
            raise AssertionError(
                f"Export directory {export_dir} already exists and is not "
                "empty.")
        os.makedirs(export_dir, exist_ok=True)
        self._meta_graphs = []
        self._has_saved_variables = False

    def add_meta_graph_and_variables(self, sess, tags, signature_def_map=None,
                                     assets_collection=None, legacy_init_op=None,
                                     clear_devices=False, main_op=None,
                                     saver=None):
        """(ref: builder_impl.py:264)."""
        var_dir = os.path.join(self._export_dir, VARIABLES_DIRECTORY)
        os.makedirs(var_dir, exist_ok=True)
        saver = saver or Saver()
        saver.save(sess, os.path.join(var_dir, VARIABLES_FILENAME),
                   write_meta_graph=False, write_state=False)
        self._has_saved_variables = True
        self._add_meta(sess.graph, tags, signature_def_map, main_op)

    def add_meta_graph(self, tags, signature_def_map=None,
                       assets_collection=None, legacy_init_op=None,
                       clear_devices=False, main_op=None):
        if not self._has_saved_variables:
            raise AssertionError(
                "Graph state including variables must be saved first: call "
                "add_meta_graph_and_variables.")
        self._add_meta(ops_mod.get_default_graph(), tags, signature_def_map,
                       main_op)

    def _add_meta(self, graph, tags, signature_def_map, main_op):
        meta = graph_io.export_meta_graph(graph=graph)
        meta["tags"] = list(tags)
        meta["signature_def"] = signature_def_map or {}
        if main_op is not None:
            meta["main_op"] = main_op.name
        if signature_def_map and "serve" in {str(t) for t in tags}:
            self._lint_for_serving(graph, signature_def_map)
        self._meta_graphs.append(meta)

    @staticmethod
    def _lint_for_serving(graph, signature_def_map):
        """Export-time serving lint: a SERVING-tagged MetaGraph whose
        signature closures contain batcher-incompatible ops (host
        stages, Print/logging io, unseeded RNG) is flagged HERE, at
        export, where the graph author can still fix it — not at
        ModelServer.load in production. Advisory: warnings only."""
        from .. import analysis
        from ..platform import tf_logging as logging

        for key, sig in signature_def_map.items():
            try:
                fetches = [graph.get_tensor_by_name(info["name"])
                           for info in (sig.get("outputs") or {}).values()]
            except (KeyError, ValueError) as e:
                logging.warning(
                    "SavedModelBuilder: signature %r names a tensor "
                    "missing from the exported graph: %s", key, e)
                continue
            if not fetches:
                continue
            for d in analysis.lint_graph(
                    graph=graph, fetches=fetches, purpose="serving",
                    rules=["lint/serving-incompatible"]):
                logging.warning("SavedModelBuilder: signature %r: %s",
                                key, d.format())

    def save(self, as_text=True):
        """(ref: builder_impl.py:420 ``save``)."""
        path = os.path.join(self._export_dir, SAVED_MODEL_FILENAME)
        with open(path, "w") as f:
            json.dump({"saved_model_schema_version": 1,
                       "meta_graphs": self._meta_graphs}, f)
        return path


def simple_save(session, export_dir, inputs, outputs, legacy_init_op=None):
    """(ref: python/saved_model/simple_save.py)."""
    from . import signature_constants, signature_def_utils, tag_constants

    b = SavedModelBuilder(export_dir)
    sig = signature_def_utils.predict_signature_def(inputs, outputs)
    b.add_meta_graph_and_variables(
        session, [tag_constants.SERVING],
        signature_def_map={
            signature_constants.DEFAULT_SERVING_SIGNATURE_DEF_KEY: sig})
    return b.save()
