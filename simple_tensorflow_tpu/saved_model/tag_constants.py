"""(ref: tensorflow/python/saved_model/tag_constants.py)."""

SERVING = "serve"
TRAINING = "train"
GPU = "gpu"
TPU = "tpu"
