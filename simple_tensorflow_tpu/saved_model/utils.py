"""(ref: tensorflow/python/saved_model/utils_impl.py)."""


def build_tensor_info(tensor):
    return {
        "name": tensor.name,
        "dtype": tensor.dtype.name,
        "tensor_shape": tensor.shape.as_list() if tensor.shape.rank is not None
        else None,
    }


def get_tensor_from_tensor_info(tensor_info, graph=None):
    from ..framework import graph as ops_mod

    g = graph or ops_mod.get_default_graph()
    return g.get_tensor_by_name(tensor_info["name"])
