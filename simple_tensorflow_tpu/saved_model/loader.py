"""SavedModel loader (ref: tensorflow/python/saved_model/loader_impl.py)."""

from __future__ import annotations

import json
import os

from ..framework import graph_io
from ..train.saver import Saver
from .builder import (SAVED_MODEL_FILENAME, VARIABLES_DIRECTORY,
                      VARIABLES_FILENAME)


def maybe_saved_model_directory(export_dir):
    return os.path.exists(os.path.join(export_dir, SAVED_MODEL_FILENAME))


def get_signature_def(meta_graph, signature_key):
    """A MetaGraph's signature_def by key, with a structured
    NotFoundError naming the available keys (the serving path's
    unknown-signature contract — ref: tensorflow_serving/servables/
    tensorflow/predict_util.cc)."""
    from ..framework import errors

    sigs = meta_graph.get("signature_def") or {}
    if signature_key not in sigs:
        raise errors.NotFoundError(
            None, None,
            f"MetaGraph has no signature_def {signature_key!r}; "
            f"available: {sorted(sigs)}")
    return sigs[signature_key]


def load(sess, tags, export_dir, **saver_kwargs):
    """(ref: loader_impl.py:149 ``load``)."""
    path = os.path.join(export_dir, SAVED_MODEL_FILENAME)
    with open(path) as f:
        saved = json.load(f)
    target = None
    for meta in saved["meta_graphs"]:
        if set(meta.get("tags", [])) == set(tags):
            target = meta
            break
    if target is None:
        raise RuntimeError(
            f"MetaGraph with tags {tags} not found in {export_dir}; "
            f"available: {[m.get('tags') for m in saved['meta_graphs']]}")
    # import_meta_graph (not bare import_graph_def): rebuilds collections +
    # Variable wrappers so the Saver below finds and restores them
    # (ref: loader_impl.py:192 restores via the MetaGraph's saver_def).
    graph_io.import_meta_graph(target)
    var_prefix = os.path.join(export_dir, VARIABLES_DIRECTORY,
                              VARIABLES_FILENAME)
    from ..train.saver import checkpoint_exists

    if checkpoint_exists(var_prefix):
        Saver(**saver_kwargs).restore(sess, var_prefix)
    return target
