"""stf.saved_model (ref: tensorflow/python/saved_model)."""

from . import builder
from . import loader
from .builder import SavedModelBuilder, simple_save
from .loader import load, maybe_saved_model_directory, get_signature_def
from . import signature_constants
from . import tag_constants
from . import signature_def_utils
from . import utils
