"""stf.saved_model (ref: tensorflow/python/saved_model)."""

from .builder import SavedModelBuilder
from .loader import load, maybe_saved_model_directory
from . import signature_constants
from . import tag_constants
from . import signature_def_utils
from . import utils
