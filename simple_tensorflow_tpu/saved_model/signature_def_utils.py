"""(ref: tensorflow/python/saved_model/signature_def_utils_impl.py)."""

from . import signature_constants
from .utils import build_tensor_info


def build_signature_def(inputs=None, outputs=None, method_name=None):
    return {"inputs": inputs or {}, "outputs": outputs or {},
            "method_name": method_name}


def predict_signature_def(inputs, outputs):
    return build_signature_def(
        {k: build_tensor_info(v) for k, v in inputs.items()},
        {k: build_tensor_info(v) for k, v in outputs.items()},
        signature_constants.PREDICT_METHOD_NAME)


def classification_signature_def(examples, classes, scores):
    out = {}
    if classes is not None:
        out[signature_constants.CLASSIFY_OUTPUT_CLASSES] = \
            build_tensor_info(classes)
    if scores is not None:
        out[signature_constants.CLASSIFY_OUTPUT_SCORES] = \
            build_tensor_info(scores)
    return build_signature_def(
        {signature_constants.CLASSIFY_INPUTS: build_tensor_info(examples)},
        out, signature_constants.CLASSIFY_METHOD_NAME)


def regression_signature_def(examples, predictions):
    return build_signature_def(
        {signature_constants.REGRESS_INPUTS: build_tensor_info(examples)},
        {signature_constants.REGRESS_OUTPUTS: build_tensor_info(predictions)},
        signature_constants.REGRESS_METHOD_NAME)


def is_valid_signature(signature_def):
    return bool(signature_def.get("method_name"))
