"""gfile shim (ref: tensorflow/python/lib/io/file_io.py)."""

from __future__ import annotations

import glob as _glob
import os
import shutil


def file_exists(filename):
    return os.path.exists(filename)


def delete_file(filename):
    os.remove(filename)


def read_file_to_string(filename, binary_mode=False):
    with open(filename, "rb" if binary_mode else "r") as f:
        return f.read()


def write_string_to_file(filename, file_content):
    mode = "wb" if isinstance(file_content, bytes) else "w"
    with open(filename, mode) as f:
        f.write(file_content)


def get_matching_files(filename):
    return sorted(_glob.glob(filename))


def create_dir(dirname):
    os.mkdir(dirname)


def recursive_create_dir(dirname):
    os.makedirs(dirname, exist_ok=True)


def copy(oldpath, newpath, overwrite=False):
    if os.path.exists(newpath) and not overwrite:
        raise OSError(f"{newpath} exists")
    shutil.copy(oldpath, newpath)


def rename(oldname, newname, overwrite=False):
    if os.path.exists(newname) and not overwrite:
        raise OSError(f"{newname} exists")
    os.replace(oldname, newname)


def is_directory(dirname):
    return os.path.isdir(dirname)


def list_directory(dirname):
    return os.listdir(dirname)


def walk(top, in_order=True):
    yield from os.walk(top)


def stat(filename):
    return os.stat(filename)


class GFile:
    """(ref: python/platform/gfile.py ``GFile``)."""

    def __init__(self, name, mode="r"):
        self._f = open(name, mode)

    def __getattr__(self, item):
        return getattr(self._f, item)

    def __enter__(self):
        return self._f

    def __exit__(self, *exc):
        self._f.close()
        return False


Open = GFile
Exists = file_exists
MakeDirs = recursive_create_dir
Glob = get_matching_files
Remove = delete_file
IsDirectory = is_directory
ListDirectory = list_directory
Rename = rename
Copy = copy
Walk = walk
Stat = stat
