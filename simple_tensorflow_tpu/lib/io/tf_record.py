"""TFRecord read/write (ref: tensorflow/core/lib/io/record_writer.cc,
record_reader.cc, python/lib/io/tf_record.py).

Format-identical to the reference: [len u64][masked crc32c(len) u32]
[data][masked crc32c(data) u32]. Python implementation here; the C++
runtime (runtime_cc/record_io.cc) accelerates bulk reads via ctypes when
built (stf.data uses it).
"""

from __future__ import annotations

import os
import struct
import zlib as _zlib
from typing import Iterator, Optional

from ...framework import errors
from ..crc32c import masked_crc32c


class TFRecordCompressionType:
    NONE = 0
    ZLIB = 1
    GZIP = 2


class TFRecordOptions:
    def __init__(self, compression_type=TFRecordCompressionType.NONE):
        self.compression_type = compression_type

    @classmethod
    def get_compression_type_string(cls, options):
        if options is None:
            return ""
        return {0: "", 1: "ZLIB", 2: "GZIP"}[options.compression_type]


def _encode_record(data: bytes) -> bytes:
    header = struct.pack("<Q", len(data))
    return (header + struct.pack("<I", masked_crc32c(header)) + data +
            struct.pack("<I", masked_crc32c(data)))


class TFRecordWriter:
    """(ref: python/lib/io/tf_record.py:94 ``TFRecordWriter``)."""

    def __init__(self, path, options: Optional[TFRecordOptions] = None):
        self._path = path
        comp = TFRecordOptions.get_compression_type_string(options)
        if comp == "GZIP":
            import gzip

            self._f = gzip.open(path, "wb")
        elif comp == "ZLIB":
            raise NotImplementedError("ZLIB container: use GZIP")
        else:
            self._f = open(path, "wb")

    def write(self, record: bytes):
        if isinstance(record, str):
            record = record.encode()
        self._f.write(_encode_record(record))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _read_records_py(path, compression="",
                     buffer_size: Optional[int] = None) -> Iterator[bytes]:
    import contextlib

    raw_buffering = int(buffer_size) if buffer_size else -1
    if compression != "GZIP":
        # sniff gzip magic so the fallback matches the native reader, whose
        # gzFile transparently decompresses regardless of options
        with open(path, "rb") as probe:
            magic = probe.read(2)
    with contextlib.ExitStack() as stack:
        # GzipFile.close() leaves a caller-supplied fileobj open — the
        # stack closes the raw fd deterministically either way
        raw = stack.enter_context(
            open(path, "rb", buffering=raw_buffering))
        if compression == "GZIP" or magic == b"\x1f\x8b":
            import gzip

            f = stack.enter_context(gzip.GzipFile(fileobj=raw))
        else:
            f = raw
        while True:
            header = f.read(12)
            if len(header) == 0:
                return
            if len(header) < 12:
                raise errors.DataLossError(None, None,
                                           f"truncated record header in {path}")
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:12])
            if masked_crc32c(header[:8]) != len_crc:
                raise errors.DataLossError(None, None,
                                           f"corrupted length crc in {path}")
            data = f.read(length)
            if len(data) < length:
                raise errors.DataLossError(None, None,
                                           f"truncated record in {path}")
            (data_crc,) = struct.unpack("<I", f.read(4))
            if masked_crc32c(data) != data_crc:
                raise errors.DataLossError(None, None,
                                           f"corrupted data crc in {path}")
            yield data


def tf_record_chunks(path, compression: str = "",
                     buffer_size: Optional[int] = None,
                     chunk_records: int = 256) -> Iterator[list]:
    """Yield LISTS of records — one list per batched C++ reader call
    (the pipeline engine's sharded readers move whole chunks through
    their ring buffers, one lock crossing per ~chunk_records records
    instead of one per record). ``buffer_size`` sizes the underlying
    read buffer (native: zlib gzbuffer; Python: io buffering). The
    native gzFile reads GZIP containers transparently, so it serves
    both compression modes. On mid-chunk corruption the good prefix is
    yielded first, then the DataLossError raises — matching the
    per-record readers."""
    use_native = False
    # only the probe is guarded: once the native reader is chosen, its
    # errors (DataLossError etc.) propagate — falling back mid-stream
    # would re-deliver records from the start of the file
    try:
        from ...runtime import native

        use_native = native.available()
    except Exception:
        use_native = False
    if use_native:
        yield from native.read_tfrecord_chunks(
            path, batch=chunk_records, buffer_size=buffer_size)
        return
    gen = _read_records_py(path, compression, buffer_size)
    while True:
        chunk: list = []
        err = None
        try:
            for rec in gen:
                chunk.append(rec)
                if len(chunk) >= chunk_records:
                    break
        except Exception as e:  # yield the good prefix, then raise
            err = e
        if chunk:
            yield chunk
        if err is not None:
            raise err
        if len(chunk) < chunk_records:
            return


def tf_record_iterator(path, options: Optional[TFRecordOptions] = None,
                       buffer_size: Optional[int] = None
                       ) -> Iterator[bytes]:
    """(ref: python/lib/io/tf_record.py:43 ``tf_record_iterator``).
    Prefers the native C++ reader when available."""
    comp = TFRecordOptions.get_compression_type_string(options)
    for chunk in tf_record_chunks(path, comp, buffer_size):
        yield from chunk
