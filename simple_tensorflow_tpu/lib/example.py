"""tf.train.Example / Features proto, wire-compatible
(ref: tensorflow/core/example/example.proto, feature.proto).

Field numbers match the reference protos, so records written here parse
with real TF and vice versa:
  Example.features = 1
  Features.feature = 1   (map<string, Feature>: key=1, value=2)
  Feature.bytes_list = 1 / float_list = 2 / int64_list = 3
  *List.value = 1 (bytes repeated / float packed / int64 packed)
"""

from __future__ import annotations

import struct
from typing import Dict, List, Union

import numpy as np

from . import proto


class BytesList:
    def __init__(self, value=()):
        self.value: List[bytes] = [
            v.encode() if isinstance(v, str) else bytes(v) for v in value]


class FloatList:
    def __init__(self, value=()):
        self.value = [float(v) for v in value]


class Int64List:
    def __init__(self, value=()):
        self.value = [int(v) for v in value]


class Feature:
    def __init__(self, bytes_list=None, float_list=None, int64_list=None):
        self.bytes_list = bytes_list
        self.float_list = float_list
        self.int64_list = int64_list

    def _writer(self) -> proto.Writer:
        w = proto.Writer()
        if self.bytes_list is not None:
            sub = proto.Writer()
            for v in self.bytes_list.value:  # empty strings included
                sub._parts.append(proto._key(1, 2))
                sub._parts.append(proto.encode_varint(len(v)))
                sub._parts.append(v)
            w.message(1, sub)
        if self.float_list is not None:
            sub = proto.Writer()
            sub.packed_floats(1, self.float_list.value)
            w.message(2, sub)
        if self.int64_list is not None:
            sub = proto.Writer()
            sub.packed_varints(1, self.int64_list.value)
            w.message(3, sub)
        return w


class Features:
    def __init__(self, feature: Dict[str, Feature] = None):
        self.feature = dict(feature or {})


class Example:
    def __init__(self, features: Features = None):
        self.features = features or Features()

    def SerializeToString(self) -> bytes:
        feats = proto.Writer()
        for name in sorted(self.features.feature):
            entry = proto.Writer()
            entry.bytes_(1, name)
            entry.message(2, self.features.feature[name]._writer())
            feats.message(1, entry)
        w = proto.Writer()
        w.message(1, feats)
        return w.tobytes()

    @staticmethod
    def FromString(data: bytes) -> "Example":
        ex = Example()
        top = proto.parse(data)
        for feats_raw in top.get(1, []):
            feats = proto.parse(feats_raw)
            for entry_raw in feats.get(1, []):
                entry = proto.parse(entry_raw)
                name = entry[1][0].decode()
                ex.features.feature[name] = _parse_feature(entry[2][0])
        return ex


def _unpack_floats(chunks) -> List[float]:
    vals: List[float] = []
    for c in chunks:
        if isinstance(c, bytes):  # packed
            vals.extend(struct.unpack(f"<{len(c) // 4}f", c))
        else:  # unpacked fixed32 already decoded as float
            vals.append(float(c))
    return vals


def _unpack_varints(chunks) -> List[int]:
    vals: List[int] = []
    for c in chunks:
        if isinstance(c, bytes):  # packed
            pos = 0
            while pos < len(c):
                v, pos = proto.decode_varint(c, pos)
                if v >= 1 << 63:
                    v -= 1 << 64
                vals.append(v)
        else:
            v = int(c)
            if v >= 1 << 63:
                v -= 1 << 64
            vals.append(v)
    return vals


def _parse_feature(raw: bytes) -> Feature:
    f = proto.parse(raw)
    if 1 in f:
        bl = proto.parse(f[1][0])
        return Feature(bytes_list=BytesList(bl.get(1, [])))
    if 2 in f:
        fl = proto.parse(f[2][0])
        return Feature(float_list=FloatList(_unpack_floats(fl.get(1, []))))
    if 3 in f:
        il = proto.parse(f[3][0])
        return Feature(int64_list=Int64List(_unpack_varints(il.get(1, []))))
    return Feature()


# -- convenience constructors (tf.train.* API) ------------------------------

def bytes_feature(values) -> Feature:
    if isinstance(values, (bytes, str)):
        values = [values]
    return Feature(bytes_list=BytesList(values))


def float_feature(values) -> Feature:
    if isinstance(values, (int, float)):
        values = [values]
    return Feature(float_list=FloatList(np.ravel(values)))


def int64_feature(values) -> Feature:
    if isinstance(values, (int, np.integer)):
        values = [values]
    return Feature(int64_list=Int64List(np.ravel(values)))


def make_example(**feature_values) -> Example:
    """make_example(label=3, weights=[0.5, 0.5], name=b"x")."""
    feats = {}
    for k, v in feature_values.items():
        arr = v if isinstance(v, (list, tuple, np.ndarray)) else [v]
        first = arr[0] if len(arr) else 0
        if isinstance(first, (bytes, str)):
            feats[k] = bytes_feature(list(arr))
        elif isinstance(first, (float, np.floating)):
            feats[k] = float_feature(list(arr))
        else:
            feats[k] = int64_feature(list(arr))
    return Example(features=Features(feature=feats))
