"""Minimal PNG encode/decode (ref: tensorflow/core/lib/png/png_io.cc).

Pure-python (zlib) — no external imaging deps in the image. Supports 8-bit
grayscale/RGB/RGBA.
"""

from __future__ import annotations

import struct
import zlib

import numpy as np

_COLOR_TYPE = {1: 0, 3: 2, 4: 6}
_CHANNELS = {0: 1, 2: 3, 4: 2, 6: 4}


def _chunk(tag: bytes, data: bytes) -> bytes:
    return (struct.pack(">I", len(data)) + tag + data +
            struct.pack(">I", zlib.crc32(tag + data) & 0xFFFFFFFF))


def encode(img: np.ndarray) -> bytes:
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    if img.dtype != np.uint8:
        img = img.astype(np.uint8)
    h, w, c = img.shape
    ihdr = struct.pack(">IIBBBBB", w, h, 8, _COLOR_TYPE[c], 0, 0, 0)
    raw = b"".join(b"\x00" + img[row].tobytes() for row in range(h))
    return (b"\x89PNG\r\n\x1a\n" + _chunk(b"IHDR", ihdr) +
            _chunk(b"IDAT", zlib.compress(raw, 6)) + _chunk(b"IEND", b""))


def decode(data: bytes) -> np.ndarray:
    if data[:8] != b"\x89PNG\r\n\x1a\n":
        raise ValueError("not a PNG")
    pos = 8
    w = h = bit_depth = color_type = None
    idat = b""
    while pos < len(data):
        (ln,) = struct.unpack(">I", data[pos:pos + 4])
        tag = data[pos + 4:pos + 8]
        body = data[pos + 8:pos + 8 + ln]
        pos += 12 + ln
        if tag == b"IHDR":
            w, h, bit_depth, color_type = struct.unpack(">IIBB", body[:10])
        elif tag == b"IDAT":
            idat += body
        elif tag == b"IEND":
            break
    if bit_depth != 8:
        raise ValueError(f"unsupported bit depth {bit_depth}")
    c = _CHANNELS[color_type]
    raw = zlib.decompress(idat)
    stride = w * c
    out = np.empty((h, w, c), np.uint8)
    prev = np.zeros(stride, np.uint16)
    pos = 0
    for row in range(h):
        ft = raw[pos]
        pos += 1
        line = np.frombuffer(raw[pos:pos + stride], np.uint8).astype(np.uint16)
        pos += stride
        if ft == 0:
            cur = line
        elif ft == 1:  # sub
            cur = line.copy()
            for i in range(c, stride):
                cur[i] = (cur[i] + cur[i - c]) & 0xFF
        elif ft == 2:  # up
            cur = (line + prev) & 0xFF
        elif ft == 3:  # average
            cur = line.copy()
            for i in range(stride):
                left = cur[i - c] if i >= c else 0
                cur[i] = (cur[i] + ((left + prev[i]) >> 1)) & 0xFF
        elif ft == 4:  # paeth
            cur = line.copy()
            for i in range(stride):
                a = int(cur[i - c]) if i >= c else 0
                b = int(prev[i])
                cc = int(prev[i - c]) if i >= c else 0
                p = a + b - cc
                pa, pb, pc = abs(p - a), abs(p - b), abs(p - cc)
                pred = a if (pa <= pb and pa <= pc) else (b if pb <= pc else cc)
                cur[i] = (cur[i] + pred) & 0xFF
        else:
            raise ValueError(f"bad filter {ft}")
        out[row] = cur.astype(np.uint8).reshape(w, c)
        prev = cur
    return out
