"""CRC32-C (Castagnoli) + TFRecord masking
(ref: tensorflow/core/lib/hash/crc32c.h). Pure-python table fallback; the
C++ runtime (runtime_cc/record_io.cc) provides the fast path via ctypes.
"""

from __future__ import annotations

_POLY = 0x82F63B78
_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _POLY if _c & 1 else _c >> 1
    _TABLE.append(_c)

_MASK_DELTA = 0xA282EAD8


def _native_crc():
    try:
        from ..runtime import native

        if native.available():
            return native
    except Exception:
        pass
    return None


def crc32c(data: bytes, crc: int = 0) -> int:
    if crc == 0 and len(data) >= 64:
        native = _native_crc()
        if native is not None:
            return native.crc32c(bytes(data))
    crc = crc ^ 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    """(ref: crc32c.h ``Mask``): rotate right 15 and add delta, so CRCs of
    CRC-bearing data don't collide."""
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xFFFFFFFF


def unmask(masked: int) -> int:
    rot = (masked - _MASK_DELTA) & 0xFFFFFFFF
    return ((rot >> 17) | (rot << 15)) & 0xFFFFFFFF
