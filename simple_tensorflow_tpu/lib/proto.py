"""Minimal protobuf wire-format codec.

The reference links full protobuf (tensorflow/core/protobuf/*.proto); here
events/summaries/examples are encoded with a hand-rolled wire codec — the
bytes are protobuf-identical so TensorBoard and TF tooling read them.
Wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Tuple, Union


def encode_varint(value: int) -> bytes:
    out = bytearray()
    value &= (1 << 64) - 1
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _key(field: int, wire: int) -> bytes:
    return encode_varint((field << 3) | wire)


class Writer:
    def __init__(self):
        self._parts: List[bytes] = []

    def varint(self, field: int, value: int) -> "Writer":
        if value:
            self._parts.append(_key(field, 0))
            self._parts.append(encode_varint(int(value)))
        return self

    def varint_always(self, field: int, value: int) -> "Writer":
        self._parts.append(_key(field, 0))
        self._parts.append(encode_varint(int(value)))
        return self

    def double(self, field: int, value: float) -> "Writer":
        if value:
            self._parts.append(_key(field, 1))
            self._parts.append(struct.pack("<d", float(value)))
        return self

    def double_always(self, field: int, value: float) -> "Writer":
        self._parts.append(_key(field, 1))
        self._parts.append(struct.pack("<d", float(value)))
        return self

    def float32(self, field: int, value: float) -> "Writer":
        if value:
            self._parts.append(_key(field, 5))
            self._parts.append(struct.pack("<f", float(value)))
        return self

    def float32_always(self, field: int, value: float) -> "Writer":
        self._parts.append(_key(field, 5))
        self._parts.append(struct.pack("<f", float(value)))
        return self

    def bytes_(self, field: int, value) -> "Writer":
        if value:
            if isinstance(value, str):
                value = value.encode()
            self._parts.append(_key(field, 2))
            self._parts.append(encode_varint(len(value)))
            self._parts.append(value)
        return self

    def message(self, field: int, sub: "Writer") -> "Writer":
        data = sub.tobytes()
        self._parts.append(_key(field, 2))
        self._parts.append(encode_varint(len(data)))
        self._parts.append(data)
        return self

    def packed_doubles(self, field: int, values) -> "Writer":
        if len(values):
            data = b"".join(struct.pack("<d", float(v)) for v in values)
            self._parts.append(_key(field, 2))
            self._parts.append(encode_varint(len(data)))
            self._parts.append(data)
        return self

    def packed_floats(self, field: int, values) -> "Writer":
        if len(values):
            data = b"".join(struct.pack("<f", float(v)) for v in values)
            self._parts.append(_key(field, 2))
            self._parts.append(encode_varint(len(data)))
            self._parts.append(data)
        return self

    def packed_varints(self, field: int, values) -> "Writer":
        if len(values):
            data = b"".join(encode_varint(int(v)) for v in values)
            self._parts.append(_key(field, 2))
            self._parts.append(encode_varint(len(data)))
            self._parts.append(data)
        return self

    def tobytes(self) -> bytes:
        return b"".join(self._parts)


def parse(data: bytes) -> Dict[int, list]:
    """Decode one message into {field: [raw values]}; length-delimited
    values stay bytes (caller re-parses nested messages)."""
    out: Dict[int, list] = {}
    pos = 0
    n = len(data)
    while pos < n:
        key, pos = decode_varint(data, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            val, pos = decode_varint(data, pos)
        elif wire == 1:
            val = struct.unpack("<d", data[pos:pos + 8])[0]
            pos += 8
        elif wire == 2:
            ln, pos = decode_varint(data, pos)
            val = data[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = struct.unpack("<f", data[pos:pos + 4])[0]
            pos += 4
        else:
            raise ValueError(f"bad wire type {wire}")
        out.setdefault(field, []).append(val)
    return out


def zigzag_encode(v: int) -> int:
    return (v << 1) ^ (v >> 63)
