"""Minimal WAV encode/decode (ref: tensorflow/core/lib/wav/wav_io.cc)."""

from __future__ import annotations

import struct

import numpy as np


def encode(samples: np.ndarray, sample_rate: int) -> bytes:
    samples = np.asarray(samples, np.float32)
    if samples.ndim == 1:
        samples = samples[:, None]
    pcm = (np.clip(samples, -1.0, 1.0) * 32767).astype("<i2")
    n_frames, n_ch = pcm.shape
    data = pcm.tobytes()
    byte_rate = sample_rate * n_ch * 2
    hdr = (b"RIFF" + struct.pack("<I", 36 + len(data)) + b"WAVE" +
           b"fmt " + struct.pack("<IHHIIHH", 16, 1, n_ch, sample_rate,
                                 byte_rate, n_ch * 2, 16) +
           b"data" + struct.pack("<I", len(data)))
    return hdr + data


def decode(data: bytes):
    if data[:4] != b"RIFF" or data[8:12] != b"WAVE":
        raise ValueError("not a WAV")
    pos = 12
    fmt = None
    pcm = None
    while pos + 8 <= len(data):
        tag = data[pos:pos + 4]
        (ln,) = struct.unpack("<I", data[pos + 4:pos + 8])
        body = data[pos + 8:pos + 8 + ln]
        pos += 8 + ln + (ln & 1)
        if tag == b"fmt ":
            fmt = struct.unpack("<HHIIHH", body[:16])
        elif tag == b"data":
            pcm = body
    _, n_ch, rate, _, _, bits = fmt
    arr = np.frombuffer(pcm, "<i2").astype(np.float32) / 32767.0
    return arr.reshape(-1, n_ch), rate
