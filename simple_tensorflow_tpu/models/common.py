"""Shared building blocks for the transformer-family models.

One implementation of the fused-LayerNorm wrapper, initializer-threading
dense, and attention head split/merge used by bert.py, transformer.py and
long_context.py, so policy changes (e.g. LN param dtype) happen once.
"""

from __future__ import annotations

import simple_tensorflow_tpu as stf


def layer_norm(x, name, eps=1e-6):
    """gamma/beta (f32) + Pallas fused layer norm over the last axis."""
    with stf.variable_scope(name):
        d = int(x.shape[-1])
        g = stf.get_variable("gamma", [d], initializer=stf.ones_initializer())
        b = stf.get_variable("beta", [d], initializer=stf.zeros_initializer())
        return stf.nn.fused_layer_norm(x, g, b, eps=eps)


def dense(x, units, initializer, name, activation=None):
    return stf.layers.dense(x, units, activation=activation,
                            kernel_initializer=initializer, name=name)


def split_heads(x, b, s, heads, head_dim):
    """(B,S,H*D) -> (B,H,S,D)."""
    return stf.transpose(stf.reshape(x, [b, s, heads, head_dim]),
                         [0, 2, 1, 3])


def merge_heads(x, b, s, hidden):
    """(B,H,S,D) -> (B,S,H*D)."""
    return stf.reshape(stf.transpose(x, [0, 2, 1, 3]), [b, s, hidden])


def maybe_recompute(layer_fn, h, i, recompute, tag):
    """Apply layer_fn(h, i), optionally under stf.recompute_grad.

    Two load-bearing details of the idiom live HERE, once:
    - the throwaway call pre-creates the layer's variables in the ROOT
      graph (variables created inside the traced FuncGraph would be lost;
      the throwaway ops are pruned because nothing fetches them);
    - loop state binds via the default arg (i=i) so each layer's lambda is
      a distinct object — the trace cache keys on the function object.
    """
    if not recompute:
        return layer_fn(h, i)
    layer_fn(h, i)
    return stf.recompute_grad(lambda hh, i=i: layer_fn(hh, i),
                              name=f"{tag}_{i}_rc")(h)
