"""Word2Vec skip-gram with NCE loss.

(ref: the reference ships the word2vec tutorial in its models.BUILD /
tensorflow/examples/tutorials/word2vec.) Embedding gradients flow as
IndexedSlices; on TPU the sparse update lowers to a dense scatter-add,
which XLA turns into an efficient one-pass update.
"""

from __future__ import annotations

import numpy as np

import simple_tensorflow_tpu as stf


def skipgram_model(vocab_size=50000, embedding_size=128, batch_size=128,
                   num_sampled=64, learning_rate=1.0):
    """The classic tutorial graph: embeddings -> NCE loss -> SGD."""
    inputs = stf.placeholder(stf.int32, [batch_size], name="train_inputs")
    labels = stf.placeholder(stf.int32, [batch_size, 1], name="train_labels")
    with stf.variable_scope("word2vec", reuse=stf.AUTO_REUSE):
        embeddings = stf.get_variable(
            "embeddings", [vocab_size, embedding_size],
            initializer=stf.random_uniform_initializer(-1.0, 1.0))
        nce_w = stf.get_variable(
            "nce_weights", [vocab_size, embedding_size],
            initializer=stf.truncated_normal_initializer(
                stddev=1.0 / np.sqrt(embedding_size)))
        nce_b = stf.get_variable("nce_biases", [vocab_size],
                                 initializer=stf.zeros_initializer())
    embed = stf.nn.embedding_lookup(embeddings, inputs)
    loss = stf.reduce_mean(stf.nn.nce_loss(
        weights=nce_w, biases=nce_b, labels=labels, inputs=embed,
        num_sampled=num_sampled, num_classes=vocab_size))
    train_op = stf.train.GradientDescentOptimizer(learning_rate).minimize(
        loss)
    # cosine-similarity graph for nearest-neighbour eval
    norm = stf.sqrt(stf.reduce_sum(stf.square(embeddings), 1, keepdims=True))
    normalized = embeddings / norm
    return {"train_inputs": inputs, "train_labels": labels, "loss": loss,
            "train_op": train_op, "embeddings": embeddings,
            "normalized_embeddings": normalized}


def similarity(normalized_embeddings, valid_ids):
    """(V,D) x ids -> (len(ids), V) cosine similarity."""
    valid = stf.constant(np.asarray(valid_ids, np.int32))
    valid_emb = stf.nn.embedding_lookup(normalized_embeddings, valid)
    return stf.matmul(valid_emb, normalized_embeddings, transpose_b=True)


def synthetic_skipgram_batch(batch_size, vocab_size=50000, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randint(0, vocab_size, batch_size).astype(np.int32),
            rng.randint(0, vocab_size, (batch_size, 1)).astype(np.int32))
