"""PTB-style LSTM language model (the reference ships this tutorial
family in models.BUILD — the TF-1.0 `ptb_word_lm` recipe: embedding →
stacked LSTM via dynamic_rnn → tied-timestep softmax, truncated BPTT
with state carried ACROSS session.run calls, gradient clipping by global
norm, SGD with epoch-wise lr decay).

TPU-first notes:
- dynamic_rnn lowers to ONE `lax.scan` — the whole unrolled sequence is
  a single XLA program (the reference builds T graph nodes per layer).
- The carried LSTM state crosses steps as session handles-compatible
  feeds: `state_in` placeholders + fetched `state_out` tensors (the
  TF-1 idiom), so truncated BPTT works exactly like the tutorial.
- f32 throughout by default (the tutorial recipe); ``compute_dtype``
  plumbs the activation dtype through the embedding lookup and RNN,
  with logits/xent always f32 — note rnn_cell._linear creates LSTM
  kernels in the input dtype, so bf16 here means bf16 weights (no f32
  master copy), acceptable for inference, not the training default.
"""

from __future__ import annotations

import numpy as np

import simple_tensorflow_tpu as stf


class PTBConfig:
    def __init__(self, vocab_size=10000, hidden=650, layers=2,
                 seq_len=35, keep_prob=0.5, max_grad_norm=5.0,
                 learning_rate=1.0):
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.layers = layers
        self.seq_len = seq_len
        self.keep_prob = keep_prob
        self.max_grad_norm = max_grad_norm
        self.learning_rate = learning_rate

    @staticmethod
    def medium():
        return PTBConfig()

    @staticmethod
    def tiny():
        return PTBConfig(vocab_size=200, hidden=32, layers=2, seq_len=8,
                         keep_prob=1.0)


def ptb_lm_model(batch_size, cfg: PTBConfig | None = None, training=True,
                 compute_dtype=stf.float32):
    """Build the training graph. Returns dict with input_ids/target_ids
    placeholders, state_in placeholders, state_out fetches, loss
    (per-word xent), train_op, and lr update handles.

    (ref recipe: tutorials/rnn/ptb/ptb_word_lm.py of the TF-1.0 era —
    reimplemented from the published architecture, not the file.)
    """
    from simple_tensorflow_tpu.ops import rnn, rnn_cell

    cfg = cfg or PTBConfig.medium()
    B, T, H, V = batch_size, cfg.seq_len, cfg.hidden, cfg.vocab_size

    input_ids = stf.placeholder(stf.int32, [B, T], name="input_ids")
    target_ids = stf.placeholder(stf.int32, [B, T], name="target_ids")

    emb = stf.get_variable(
        "embedding", shape=(V, H),
        initializer=stf.random_uniform_initializer(-0.1, 0.1, seed=1))
    x = stf.nn.embedding_lookup(emb, input_ids,
                                compute_dtype=compute_dtype)
    if training and cfg.keep_prob < 1.0:
        x = stf.nn.dropout(x, keep_prob=cfg.keep_prob, seed=11)

    def make_cell(i):
        cell = rnn_cell.BasicLSTMCell(H, forget_bias=0.0)
        if training and cfg.keep_prob < 1.0:
            cell = rnn_cell.DropoutWrapper(
                cell, output_keep_prob=cfg.keep_prob, seed=100 + i)
        return cell

    cell = rnn_cell.MultiRNNCell([make_cell(i)
                                  for i in range(cfg.layers)])

    # truncated-BPTT state: placeholders in, fetch tensors out
    state_in = []
    for li in range(cfg.layers):
        c = stf.placeholder(compute_dtype, [B, H], name=f"state_c{li}")
        h = stf.placeholder(compute_dtype, [B, H], name=f"state_h{li}")
        state_in.append(rnn_cell.LSTMStateTuple(c, h))
    outputs, state_out = rnn.dynamic_rnn(
        cell, x, initial_state=tuple(state_in), dtype=compute_dtype,
        scope="ptb_rnn")

    softmax_w = stf.get_variable(
        "softmax_w", shape=(H, V),
        initializer=stf.random_uniform_initializer(-0.1, 0.1, seed=2))
    softmax_b = stf.get_variable(
        "softmax_b", shape=(V,), initializer=stf.zeros_initializer())
    flat = stf.reshape(outputs, [B * T, H])
    logits = stf.cast(stf.matmul(flat, stf.cast(softmax_w, compute_dtype))
                      + stf.cast(softmax_b, compute_dtype), stf.float32)
    loss = stf.reduce_mean(
        stf.nn.sparse_softmax_cross_entropy_with_logits(
            labels=stf.reshape(target_ids, [B * T]), logits=logits))

    model = {"input_ids": input_ids, "target_ids": target_ids,
             "state_in": state_in, "state_out": state_out,
             "loss": loss, "logits": logits}

    if training:
        # PTB recipe: clip by GLOBAL norm, plain SGD, assignable lr
        lr = stf.get_variable("lr", shape=(),
                              initializer=stf.constant_initializer(
                                  cfg.learning_rate), trainable=False)
        new_lr = stf.placeholder(stf.float32, [], name="new_lr")
        model["lr"] = lr
        model["new_lr"] = new_lr
        model["lr_update"] = lr.assign(new_lr)
        tvars = stf.trainable_variables()
        grads = stf.gradients(loss, tvars)
        clipped, _ = stf.clip_by_global_norm(grads, cfg.max_grad_norm)
        opt = stf.train.GradientDescentOptimizer(lr.value())
        gs = stf.train.get_or_create_global_step()
        model["train_op"] = opt.apply_gradients(
            list(zip(clipped, tvars)), global_step=gs)
        model["global_step"] = gs
    return model


def zero_state(batch_size, cfg: PTBConfig, dtype=np.float32):
    return [(np.zeros((batch_size, cfg.hidden), dtype),
             np.zeros((batch_size, cfg.hidden), dtype))
            for _ in range(cfg.layers)]


def state_feed(model, state_np):
    feed = {}
    for (c_ph, h_ph), (c, h) in zip(model["state_in"], state_np):
        feed[c_ph] = c
        feed[h_ph] = h
    return feed


def synthetic_ptb_batch(batch_size, seq_len, vocab_size, seed=0):
    rng = np.random.RandomState(seed)
    # a learnable synthetic language: next id = (id * 3 + 7) % V with noise
    start = rng.randint(0, vocab_size, size=(batch_size, 1))
    seqs = [start]
    for _ in range(seq_len):
        seqs.append((seqs[-1] * 3 + 7) % vocab_size)
    full = np.concatenate(seqs, axis=1)
    return full[:, :-1].astype(np.int32), full[:, 1:].astype(np.int32)
