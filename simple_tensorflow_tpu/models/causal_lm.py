"""Decoder-only causal LM: the shared-prefix serving flagship.

(ref: the reference's seq2seq decoder stack minus the encoder — GPT-style
next-token LM over one token stream.)

Two halves:

- :func:`causal_lm_logits` / :func:`causal_lm_train_model`: the training
  graph. Layer-for-layer this is the transformer DECODER with the
  cross-attention sublayer removed — the sublayer/LN naming (``ln1``
  after self-attention, ``ln3`` after the FFN, no ``ln2``) deliberately
  matches what ``transformer._incremental_decode`` builds when
  ``cross_kv=None``, so ONE checkpoint serves both the train graph and
  the incremental serving programs below.

- :func:`build_causal_lm_program` / :class:`CausalLMGenerativeModel`:
  the PAGED serving programs. Where the seq2seq serving model keys
  caches by (slot, position) with one row per live sequence, the causal
  LM keys them by PAGE: each cache is ``(num_pages + 1, page_len, H,
  hd)`` with ``paged=True``, a sequence's KV state is the ordered page
  list in its page table, and attention reads through the page-table
  gather (``slots (B, n_blocks)`` → the concatenated logical view).
  That indirection is what the shared-prefix prompt cache
  (serving/prefix_cache.py) needs: two sequences whose prompts share a
  prefix point their leading page-table entries at the SAME physical
  pages (refcounted), prefill runs once, and divergence copies a page
  (``KVCachePageCopy``) before private appends — copy-on-write.
"""

from __future__ import annotations

import contextlib

import numpy as np

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.models import common
from simple_tensorflow_tpu.models.transformer import (
    TransformerConfig, _attention, _block_decode, _dense, _embed, _ffn,
    _incremental_decode, _ln, _residual, _tp_gather,
    build_int8_logits_weights, decode_tp_collective_bytes,
    decode_tp_partition_rules, generative_cache_bytes, resolve_decode_tp,
    smoothed_xent)

# the causal LM reuses TransformerConfig (decoder-side fields only:
# d_model/num_heads/d_ff/num_layers/dropout/vocab/max_len)
CausalLMConfig = TransformerConfig


def causal_lm_logits(ids, cfg: TransformerConfig, training=True,
                     compute_dtype=stf.bfloat16, scope="causal_lm",
                     recompute=False):
    """Next-token logits (B, S, vocab) for token ids (B, S).

    Decoder-only stack: causal flash self-attention + FFN per layer,
    tied-embedding softmax. Position ``j``'s logits predict token
    ``j+1``.
    """
    with stf.variable_scope(scope, reuse=stf.AUTO_REUSE):
        h, emb = _embed(ids, cfg, compute_dtype, training)
        with stf.variable_scope("decoder"):
            def lm_layer(hh, i):
                with stf.variable_scope(f"layer_{i}"):
                    a = _attention(hh, hh, None, cfg, training,
                                   compute_dtype, "self_attn",
                                   causal=True)
                    hh = _ln(_residual(a, hh, cfg, training), cfg, "ln1")
                    f = _ffn(hh, cfg, training, "ffn")
                    # ln3, not ln2: the serving step (cross-skipped
                    # _incremental_decode) reuses these variables by name
                    return _ln(hh + f, cfg, "ln3")

            for i in range(cfg.num_layers):
                h = common.maybe_recompute(lm_layer, h, i, recompute,
                                           "lm")
        b, s = int(ids.shape[0]), int(ids.shape[1])
        flat = stf.reshape(h, [b * s, cfg.d_model])
        logits = stf.matmul(flat, stf.cast(emb, h.dtype.base_dtype),
                            transpose_b=True)
        return stf.reshape(logits, [b, s, cfg.vocab_size])


def causal_lm_train_model(batch_size=8, seq_len=32,
                          cfg: TransformerConfig | None = None,
                          learning_rate=1.0, warmup_steps=4000,
                          compute_dtype=stf.bfloat16, recompute=False):
    """Training graph: tok_in/tok_out -> label-smoothed LM loss -> Adam
    with the noam schedule (same recipe as the seq2seq transformer)."""
    cfg = cfg or TransformerConfig.base()
    tok_in = stf.placeholder(stf.int32, [batch_size, seq_len], "tok_in")
    tok_out = stf.placeholder(stf.int32, [batch_size, seq_len], "tok_out")
    logits = causal_lm_logits(tok_in, cfg, training=True,
                              compute_dtype=compute_dtype,
                              recompute=recompute)
    weights = stf.cast(stf.not_equal(tok_out, cfg.pad_id), stf.float32)
    loss = smoothed_xent(logits, tok_out, weights, cfg)
    gs = stf.train.get_or_create_global_step()
    step = stf.cast(gs, stf.float32) + 1.0
    lr = (learning_rate * cfg.d_model ** -0.5 *
          stf.minimum(stf.pow(step, -0.5), step * warmup_steps ** -1.5))
    opt = stf.train.AdamOptimizer(lr, beta1=0.9, beta2=0.997,
                                  epsilon=1e-9)
    train_op = opt.minimize(loss, global_step=gs)
    return {"tok_in": tok_in, "tok_out": tok_out, "loss": loss,
            "train_op": train_op, "learning_rate": lr, "global_step": gs}


# ---------------------------------------------------------------------------
# Paged serving programs
# ---------------------------------------------------------------------------

class _PagedCaches:
    """Cache accessor for the paged decode/prefill programs, the
    page-table counterpart of ``transformer._SlotCaches``.

    Appends land at ``(dst_pages[b], offsets[b] + j)`` — ONE physical
    page per sequence per step/block — while the gather reads the
    LOGICAL view through ``page_tables (B, n_blocks)``, so attention
    sees the sequence's full history across however many (possibly
    shared) pages it spans. The RAW between a layer's append and its
    gather is ordered by an explicit control dependency (the appended
    page is always present in the table)."""

    def __init__(self, caches, page_tables, dst_pages, offsets, base):
        self._caches = caches        # [(KVCache k, KVCache v)] per layer
        self._tables = page_tables   # (B, n_blocks) int32
        self._dst = dst_pages        # (B,) int32 physical page written
        self._off = offsets          # (B,) int32 in-page start offset
        self._base = base            # (B,) int32 committed length BEFORE

    def _one(self, cache, new):
        appended = cache.append(new, self._dst, self._off)
        with stf.control_dependencies([appended.op]):
            return cache.gather(self._tables)

    def append_and_gather(self, layer, k_new, v_new):
        kc, vc = self._caches[layer]
        return (self._one(kc, k_new), self._one(vc, v_new),
                self._base + 1)

    def append_and_gather_block(self, layer, k_new, v_new):
        kc, vc = self._caches[layer]
        return self._one(kc, k_new), self._one(vc, v_new), self._base


def build_causal_lm_program(cfg: TransformerConfig, *, page_len,
                            pages_per_seq, num_pages,
                            decode_bucket_sizes=None,
                            prefill_bucket_sizes=None,
                            compute_dtype=stf.float32, int8=False,
                            sampling=None, scope="causal_lm",
                            cache_sharding=None, tp_axis=None):
    """Build the paged-cache causal-LM serving programs.

    Emits, in the CURRENT default graph:

    - per-layer K/V caches ``(num_pages + 1, page_len, H, hd)`` with
      ``paged=True`` (row ``num_pages`` is the scratch page bucket
      padding writes into) + ``alloc_op``;
    - one PREFILL program per prefill bucket pb: a page-aligned BLOCK
      of ``page_len`` prompt tokens through ``_block_decode``
      (query-block DecodeAttention, ``causal_offset=True``), appended
      into each row's ``dst_pages`` physical page (feeds: tok
      (pb, page_len), base (pb,) absolute start, page_tables
      (pb, n_blocks), dst_pages (pb,); fetches: the append group — no
      logits: the engine feeds the last prompt token through the first
      DECODE step instead, so a partial final chunk just pads);
    - one DECODE program per decode bucket sb: one position through
      ``_incremental_decode`` (feeds: tok (sb,), pos (sb,) absolute,
      page_tables (sb, n_blocks), dst_pages (sb,), offsets (sb,);
      fetches next_tok/logp (sb,)) — greedy, or seeded sampling when
      ``sampling`` is set;
    - ``cow``: the copy-on-write program — ``KVCachePageCopy`` over
      EVERY layer cache (feeds dst (1,), src (1,)): a sequence
      diverging inside a shared page copies it before private appends.

    Page tables are host-side state (the prefix-cache trie owns them);
    the device only ever sees the resolved (page_tables, dst, offset)
    integers, so admission/eviction never retraces a program.
    """
    from ..serving.policy import _pow2_buckets
    from ..ops import kv_cache_ops as kvc

    if tp_axis and cache_sharding is None:
        cache_sharding = f"{tp_axis}{kvc.HEAD_SHARD_SUFFIX}"

    def _feed(t):
        """Annotate a placeholder replicated-on-mesh under TP (same
        contract as the seq2seq builder: fed numpy must commit onto the
        mesh's device set next to the head-sharded paged caches)."""
        if tp_axis:
            from simple_tensorflow_tpu import parallel

            parallel.shard_feed(t)
        return t

    page_len = int(page_len)
    pages_per_seq = int(pages_per_seq)
    num_pages = int(num_pages)
    max_seq_len = page_len * pages_per_seq
    if max_seq_len > cfg.max_len:
        raise ValueError(
            f"page_len*pages_per_seq={max_seq_len} exceeds "
            f"cfg.max_len={cfg.max_len} (position-encoding table)")
    heads = cfg.num_heads
    hd = cfg.d_model // heads
    total_pages = num_pages + 1          # + scratch page
    scratch_page = num_pages
    decode_buckets = sorted(set(int(x) for x in (
        decode_bucket_sizes or _pow2_buckets(8))))
    prefill_buckets = sorted(set(int(x) for x in (
        prefill_bucket_sizes or (1,))))

    caches = []
    for i in range(cfg.num_layers):
        caches.append((
            kvc.kv_cache(f"{scope}_pg/l{i}_k", total_pages, page_len,
                         (heads, hd), compute_dtype,
                         sharding=cache_sharding, paged=True),
            kvc.kv_cache(f"{scope}_pg/l{i}_v", total_pages, page_len,
                         (heads, hd), compute_dtype,
                         sharding=cache_sharding, paged=True)))
    flat_caches = [c for pair in caches for c in pair]
    alloc_op = stf.group(*[c.alloc() for c in flat_caches],
                         name="pg_alloc")

    if sampling is not None:
        sampling = dict(sampling)
        unknown = set(sampling) - {"temperature", "top_k", "top_p",
                                   "seed"}
        if unknown:
            raise ValueError(f"unknown sampling knobs: {sorted(unknown)}")
    state = {"int8_init": None, "wq": None, "w_scale": None}

    def _logits_head(h_flat, emb):
        if int8:
            if state["int8_init"] is None:
                state["wq"], state["w_scale"], state["int8_init"] = \
                    build_int8_logits_weights(emb, cfg, scope=scope)
            logits = stf.nn.quantized_matmul(h_flat, state["wq"],
                                             state["w_scale"])
        else:
            logits = stf.matmul(h_flat,
                                stf.cast(emb, h_flat.dtype.base_dtype),
                                transpose_b=True)
        return _tp_gather(stf.cast(logits, stf.float32), tp_axis)

    def _emit(logits):
        if sampling is not None:
            from ..ops import sampling_ops

            return sampling_ops.sample_token(logits, **sampling)
        logp_all = stf.nn.log_softmax(logits, axis=-1)
        tok = stf.cast(stf.argmax(logits, -1, output_type=stf.int32),
                       stf.int32)
        logp = stf.reduce_sum(
            logp_all * stf.one_hot(tok, cfg.vocab_size,
                                   dtype=stf.float32), axis=-1)
        return tok, logp

    # -- prefill: one page-aligned chunk ------------------------------------
    prefill = {}
    for pb in prefill_buckets:
        tok = _feed(stf.placeholder(stf.int32, [pb, page_len],
                                    f"lm_prefill{pb}_tok"))
        base = _feed(stf.placeholder(stf.int32, [pb],
                                     f"lm_prefill{pb}_base"))
        tables = _feed(stf.placeholder(stf.int32, [pb, pages_per_seq],
                                       f"lm_prefill{pb}_tables"))
        dst = _feed(stf.placeholder(stf.int32, [pb],
                                    f"lm_prefill{pb}_dst"))
        cache = _PagedCaches(caches, tables, dst, stf.fill([pb], 0),
                             base)
        h, _ = _block_decode(tok, base, cache, None, None, None, cfg,
                             compute_dtype, scope, tp_axis=tp_axis)
        # fetch the hidden state to anchor the whole block (appends are
        # its data deps); pad rows of a partial final chunk write
        # garbage K/V past the real length — dead rows: attention masks
        # by committed length and the next append overwrites in place
        prefill[pb] = {"tok": tok, "base": base, "tables": tables,
                       "dst": dst,
                       "op": stf.group(h, name=f"lm_prefill{pb}")}

    # -- decode: one position -----------------------------------------------
    decode_progs = {}
    for sb in decode_buckets:
        tok = _feed(stf.placeholder(stf.int32, [sb], f"lm_decode{sb}_tok"))
        pos = _feed(stf.placeholder(stf.int32, [sb], f"lm_decode{sb}_pos"))
        tables = _feed(stf.placeholder(stf.int32, [sb, pages_per_seq],
                                       f"lm_decode{sb}_tables"))
        dst = _feed(stf.placeholder(stf.int32, [sb],
                                    f"lm_decode{sb}_dst"))
        off = _feed(stf.placeholder(stf.int32, [sb],
                                    f"lm_decode{sb}_off"))
        cache = _PagedCaches(caches, tables, dst, off, pos)
        h, emb = _incremental_decode(tok, pos, cache, None, None, None,
                                     cfg, compute_dtype, scope,
                                     tp_axis=tp_axis)
        next_tok, logp = _emit(_logits_head(h, emb))
        decode_progs[sb] = {"tok": tok, "pos": pos, "tables": tables,
                            "dst": dst, "off": off,
                            "next_tok": next_tok, "logp": logp}

    # -- copy-on-write ------------------------------------------------------
    cow_dst = _feed(stf.placeholder(stf.int32, [1], "lm_cow_dst"))
    cow_src = _feed(stf.placeholder(stf.int32, [1], "lm_cow_src"))
    cow_op = stf.group(*[c.copy_pages(cow_dst, cow_src)
                         for c in flat_caches], name="lm_cow")

    return {
        "alloc_op": alloc_op,
        "int8_init": state["int8_init"],
        "prefill": prefill,
        "decode": decode_progs,
        "cow": {"dst": cow_dst, "src": cow_src, "op": cow_op},
        "decode_buckets": decode_buckets,
        "prefill_buckets": prefill_buckets,
        "scratch_page": scratch_page,
        "caches": caches,
        "cache_sharding": cache_sharding,
        "tp_axis": tp_axis,
    }


class CausalLMGenerativeModel:
    """Session-owning paged causal-LM decode programs for the serving
    engine's prefix-cache path.

    The engine (serving/generative.py) owns the page-table bookkeeping
    through :class:`~..serving.prefix_cache.PrefixCache`; this model
    exposes the device half: ``prefill_chunk`` (one page-aligned block
    per live row), ``decode`` (one position; physical page/offset
    resolved from the page table HERE, host-side), ``copy_page`` (CoW),
    and the ``page_len / num_pages / pages_per_seq / scratch_page``
    geometry the pool is sized against.
    """

    def __init__(self, cfg: TransformerConfig, *, page_len=8,
                 pages_per_seq=4, num_pages=32, max_live=8,
                 decode_bucket_sizes=None, prefill_bucket_sizes=None,
                 compute_dtype=stf.float32, int8=False, sampling=None,
                 checkpoint=None, init_fresh=False, config=None,
                 scope="causal_lm", aot_warmup=True, seed=0,
                 mesh=None, tp=None):
        if checkpoint is None and not init_fresh:
            raise ValueError("pass checkpoint=... or init_fresh=True")
        self.cfg = cfg
        self.page_len = int(page_len)
        self.pages_per_seq = int(pages_per_seq)
        self.num_pages = int(num_pages)
        self.max_seq_len = self.page_len * self.pages_per_seq
        # engine-facing decode geometry (slot == live sequence)
        self.num_slots = int(max_live)
        self.max_decode_len = self.max_seq_len
        self.src_len = 0                     # decoder-only: no encoder
        self.eos_id = cfg.eos_id
        self.pad_id = cfg.pad_id
        self.int8 = bool(int8)
        self.sampling = dict(sampling) if sampling else None
        self._compute_dtype = compute_dtype
        # paged cache set == generative_cache_bytes with slots=num_pages,
        # decode_len=page_len, no cross caches (decoder-only; all of it
        # head-dim shardable)
        self._cache_bytes_total, self._cache_bytes_unsharded = \
            generative_cache_bytes(cfg, 0, self.num_pages, self.page_len,
                                   compute_dtype, cross=False)
        self.tp_choice = None
        if tp == "auto":
            from ..analysis import autoshard as _autoshard

            budget = int(getattr(config, "device_memory_budget_bytes",
                                 0) or 0) or None
            self.tp_choice = _autoshard.choose_decode_tp(
                num_heads=cfg.num_heads,
                cache_bytes=self._cache_bytes_total,
                unsharded_bytes=self._cache_bytes_unsharded,
                collective_bytes_fn=lambda t: decode_tp_collective_bytes(
                    cfg, t, compute_dtype, cross=False),
                budget_bytes=budget, mesh=mesh)
            tp = self.tp_choice.degree
        self._mesh, self.tp_axis, self.tp_degree = resolve_decode_tp(
            mesh, tp, cfg.num_heads)
        self.graph = stf.Graph()
        with contextlib.ExitStack() as _scope_stack:
            _scope_stack.enter_context(self.graph.as_default())
            if self._mesh is not None:
                _scope_stack.enter_context(self._mesh)
            if seed is not None:
                stf.set_random_seed(seed)
            self.session = stf.Session(graph=self.graph, config=config)
            prog = build_causal_lm_program(
                cfg, page_len=page_len, pages_per_seq=pages_per_seq,
                num_pages=num_pages,
                decode_bucket_sizes=(decode_bucket_sizes
                                     or tuple(sorted({1, max_live}))),
                prefill_bucket_sizes=prefill_bucket_sizes,
                compute_dtype=compute_dtype, int8=int8,
                sampling=sampling, scope=scope, tp_axis=self.tp_axis)
            self._prog = prog
            self.scratch_page = prog["scratch_page"]
            if self.tp_axis:
                # commit the TP weight layout BEFORE restore/init so
                # the Session places (checkpoint-restored or fresh)
                # state sharded at first commit
                from simple_tensorflow_tpu import parallel

                parallel.match_partition_rules(
                    decode_tp_partition_rules(self.tp_axis), apply=True)
            if checkpoint is not None:
                saver = stf.train.Saver()
                saver.restore(self.session, checkpoint)
            else:
                self.session.run(stf.global_variables_initializer())
            init_fetches = [prog["alloc_op"]]
            if prog["int8_init"] is not None:
                init_fetches.append(prog["int8_init"])
            for f in init_fetches:
                self.session.run(f)
            self._decode_plans = {}
            for sb, p in prog["decode"].items():
                plan = self.session.plan(
                    {"next_tok": p["next_tok"], "logp": p["logp"]},
                    feeds=[p["tok"], p["pos"], p["tables"], p["dst"],
                           p["off"]])
                self._decode_plans[sb] = (plan, p)
                if aot_warmup:
                    plan.compile()
            self._prefill_plans = {}
            for pb, p in prog["prefill"].items():
                plan = self.session.plan(
                    {"done": p["op"]},
                    feeds=[p["tok"], p["base"], p["tables"], p["dst"]])
                self._prefill_plans[pb] = (plan, p)
                if aot_warmup:
                    plan.compile()
            cw = prog["cow"]
            self._cow_plan = (self.session.plan(
                {"done": cw["op"]}, feeds=[cw["dst"], cw["src"]]), cw)
            if aot_warmup:
                self._cow_plan[0].compile()
        self._decode_buckets = sorted(self._decode_plans)
        self._prefill_buckets = sorted(self._prefill_plans)

    @property
    def decode_buckets(self):
        return list(self._decode_buckets)

    @property
    def prefill_buckets(self):
        return list(self._prefill_buckets)

    def _bucket(self, buckets, n):
        for b in buckets:
            if b >= n:
                return b
        raise ValueError(f"{n} rows exceed the largest bucket "
                         f"{buckets[-1]}")

    def _run(self, plan, feed):
        """Execute under the model's mesh scope (thread-local; the
        engine's scheduler thread is not inside the construction-time
        ``with mesh:``)."""
        if self._mesh is None:
            return plan.execute(feed)
        with self._mesh:
            return plan.execute(feed)

    def tp_info(self):
        """Decode-TP facts for telemetry (/stf/serving/tp_*)."""
        t = max(int(self.tp_degree or 1), 1)
        sharded = self._cache_bytes_total - self._cache_bytes_unsharded
        per_device = self._cache_bytes_unsharded + sharded // t
        return {
            "tp_degree": t,
            "tp_axis": self.tp_axis,
            "cache_bytes_replicated": int(self._cache_bytes_total),
            "cache_bytes_per_device": int(per_device),
            "per_token_collective_bytes": int(decode_tp_collective_bytes(
                self.cfg, t, self._compute_dtype, cross=False)),
        }

    def _scratch_tables(self, n):
        return np.full((n, self.pages_per_seq), self.scratch_page,
                       np.int32)

    def prefill_chunk(self, tok_chunks, bases, page_tables, dst_pages):
        """Run ONE page-aligned prompt chunk for n rows: ``tok_chunks
        (n, page_len)`` (pad-padded past the real tail), ``bases (n,)``
        absolute chunk start (multiple of page_len), ``page_tables
        (n, pages_per_seq)``, ``dst_pages (n,)`` the physical page each
        row's chunk fills."""
        tok_chunks = np.asarray(tok_chunks, np.int32).reshape(
            -1, self.page_len)
        bases = np.asarray(bases, np.int32)
        page_tables = np.asarray(page_tables, np.int32).reshape(
            -1, self.pages_per_seq)
        dst_pages = np.asarray(dst_pages, np.int32)
        n = len(dst_pages)
        done = 0
        while done < n:
            take = min(n - done, self._prefill_buckets[-1])
            pb = self._bucket(self._prefill_buckets, take)
            plan, p = self._prefill_plans[pb]
            tok = np.full((pb, self.page_len), self.pad_id, np.int32)
            base = np.zeros((pb,), np.int32)
            tbl = self._scratch_tables(pb)
            dst = np.full((pb,), self.scratch_page, np.int32)
            sl = slice(done, done + take)
            tok[:take] = tok_chunks[sl]
            base[:take] = bases[sl]
            tbl[:take] = page_tables[sl]
            dst[:take] = dst_pages[sl]
            self._run(plan, {p["tok"]: tok, p["base"]: base,
                             p["tables"]: tbl, p["dst"]: dst})
            done += take

    def decode(self, tokens, positions, page_tables):
        """One decode position for n live sequences; the physical write
        target is resolved host-side from each row's page table:
        ``dst = page_tables[i, pos // page_len]``, ``off = pos %
        page_len``. Returns (next_tok (n,), logp (n,), bucket)."""
        tokens = np.asarray(tokens, np.int32)
        positions = np.asarray(positions, np.int32)
        page_tables = np.asarray(page_tables, np.int32).reshape(
            -1, self.pages_per_seq)
        n = len(tokens)
        sb = self._bucket(self._decode_buckets, n)
        plan, p = self._decode_plans[sb]
        tok = np.full((sb,), self.pad_id, np.int32)
        pos = np.zeros((sb,), np.int32)
        tbl = self._scratch_tables(sb)
        tok[:n], pos[:n], tbl[:n] = tokens, positions, page_tables
        dst = tbl[np.arange(sb), pos // self.page_len]
        off = pos % self.page_len
        out = self._run(plan, {p["tok"]: tok, p["pos"]: pos,
                               p["tables"]: tbl, p["dst"]: dst,
                               p["off"]: off.astype(np.int32)})
        return (np.asarray(out["next_tok"])[:n],
                np.asarray(out["logp"])[:n], sb)

    def copy_page(self, dst, src):
        """Copy-on-write: duplicate physical page ``src`` into ``dst``
        across every layer cache (one plan execution)."""
        plan, cw = self._cow_plan
        self._run(plan, {cw["dst"]: np.asarray([dst], np.int32),
                         cw["src"]: np.asarray([src], np.int32)})

    def close(self):
        self.session.close()

    def statusz_info(self):
        info = {"decode_buckets": self._decode_buckets,
                "prefill_buckets": self._prefill_buckets,
                "page_len": self.page_len, "num_pages": self.num_pages,
                "pages_per_seq": self.pages_per_seq,
                "num_slots": self.num_slots, "int8": self.int8,
                "sampling": self.sampling}
        if self.tp_degree > 1:
            info["tp"] = self.tp_info()
        return info
