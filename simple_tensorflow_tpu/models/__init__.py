"""Model zoo: the reference's baseline configs rebuilt TPU-first.

- mnist: softmax + convnet tutorials (BASELINE config 1)
- resnet: ResNet-50 v1.5 bf16/NHWC (configs 2-3)
- bert: BERT-base MLM+NSP pretraining, flash attention (config 4)
- transformer: Transformer-big WMT en-de seq2seq + beam search (config 5)
- causal_lm: decoder-only LM + paged-cache serving (shared-prefix path)
- word2vec: skip-gram NCE tutorial (ref models.BUILD)
- long_context: ring-attention long-sequence LM (sequence parallel flagship)
- dlrm: DLRM ranking — vocab-sharded embedding bags + pairwise interaction
"""

from . import mnist
from . import resnet
from . import bert
from . import transformer
from . import causal_lm
from . import word2vec
from . import long_context
from . import dlrm
