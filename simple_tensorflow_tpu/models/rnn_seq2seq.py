"""Attention seq2seq: LSTM encoder/decoder with Luong attention — the
reference's translate-tutorial recipe (its models.BUILD ships the
tutorial family; the TF-1 `translate` model is an embedding RNN
encoder-decoder trained with teacher forcing and decoded greedily).

TPU-first design:
- Encoder is `dynamic_rnn` (ONE `lax.scan` per layer, not T graph nodes)
  with sequence-length select-masking.
- The decoder is a second `lax.scan` whose step fuses the LSTM cell,
  dot-product attention over the encoder memory (a [B,H] x [B,Ts,H]
  batched matmul — MXU work, masked softmax over source padding), and
  the input feed; the output projection is applied OUTSIDE the scan to
  the stacked [T,B,H] outputs so XLA sees one [T*B,H] @ [H,V] matmul
  instead of T small ones.
- Greedy decoding runs the same scan with the argmax fed back through
  the embedding table (a traced gather) — decode length is static, the
  XLA requirement.
- Static [B, Ts]/[B,Tt] shapes throughout: pair with
  `Dataset.padded_batch(padded_shapes=...)` so the whole training run
  is one compile.
"""

from __future__ import annotations

import numpy as np

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.ops import rnn, rnn_cell


class Seq2SeqConfig:
    def __init__(self, src_vocab=120, tgt_vocab=120, hidden=64,
                 src_len=12, tgt_len=12, learning_rate=0.01,
                 max_grad_norm=5.0):
        self.src_vocab = src_vocab
        self.tgt_vocab = tgt_vocab
        self.hidden = hidden
        self.src_len = src_len
        self.tgt_len = tgt_len
        self.learning_rate = learning_rate
        self.max_grad_norm = max_grad_norm

    @staticmethod
    def tiny():
        return Seq2SeqConfig(src_vocab=24, tgt_vocab=24, hidden=32,
                             src_len=7, tgt_len=7, learning_rate=0.05)


GO_ID = 1  # decoder start symbol; 0 is padding


def _attention(query, memory, src_mask):
    """Luong dot attention. query [B,H], memory [B,Ts,H], src_mask
    [B,Ts] (1 = real token) -> context [B,H]."""
    # [B,Ts] scores via batched matvec on the MXU
    scores = stf.squeeze(stf.matmul(memory, stf.expand_dims(query, -1)),
                         axis=[-1])
    neg = stf.constant(np.float32(-1e9))
    scores = stf.where(stf.cast(src_mask, stf.bool), scores,
                       stf.ones_like(scores) * neg)
    probs = stf.nn.softmax(scores)
    return stf.squeeze(stf.matmul(stf.expand_dims(probs, 1), memory),
                       axis=[1])


def seq2seq_model(batch_size, config=None, training=True):
    """Build graph; returns the tensor dict (src, src_len, tgt_in,
    tgt_out, tgt_mask placeholders; loss, train_op, logits, decoded)."""
    cfg = config or Seq2SeqConfig()
    B, H = batch_size, cfg.hidden

    src = stf.placeholder(stf.int32, [B, cfg.src_len], name="src")
    src_len = stf.placeholder(stf.int32, [B], name="src_len")
    # teacher-forced decoder input (GO + shifted target) and target out
    tgt_in = stf.placeholder(stf.int32, [B, cfg.tgt_len], name="tgt_in")
    tgt_out = stf.placeholder(stf.int32, [B, cfg.tgt_len], name="tgt_out")

    with stf.variable_scope("seq2seq", reuse=stf.AUTO_REUSE):
        init = stf.random_uniform_initializer(-0.08, 0.08, seed=7)
        src_emb = stf.get_variable("src_emb", [cfg.src_vocab, H],
                                   initializer=init)
        tgt_emb = stf.get_variable("tgt_emb", [cfg.tgt_vocab, H],
                                   initializer=init)

        # ---- encoder ----------------------------------------------------
        enc_in = stf.nn.embedding_lookup(src_emb, src)
        with stf.variable_scope("encoder"):
            enc_cell = rnn_cell.BasicLSTMCell(H)
            memory, enc_state = rnn.dynamic_rnn(
                enc_cell, enc_in, sequence_length=src_len,
                dtype=stf.float32)
        src_mask = stf.sequence_mask(src_len, cfg.src_len,
                                     dtype=stf.float32)

        # ---- decoder scan (shared by train + greedy decode) -------------
        dec_cell = rnn_cell.BasicLSTMCell(H)

        # reference-scan semantics: fn returns the new ACCUMULATOR and
        # scan stacks every component per step — so the per-step outputs
        # (att_h, predicted id) ride in the carry alongside the state
        def make_step(feed_previous):
            def step(carry, elem):
                state, prev_ctx, prev_id, _prev_att = carry
                x_t = elem
                if feed_previous:
                    inp = stf.nn.embedding_lookup(tgt_emb, prev_id)
                else:
                    inp = x_t
                with stf.variable_scope("decoder", reuse=stf.AUTO_REUSE):
                    cell_in = stf.concat([inp, prev_ctx], 1)
                    h, new_state = dec_cell(cell_in, state)
                    ctx = _attention(h, memory, src_mask)
                    # Luong: attentional hidden = tanh(Wc [h; ctx])
                    att_h = stf.tanh(rnn_cell._linear(
                        [h, ctx], H, bias=False, scope_name="attn_mix"))
                    if feed_previous:
                        # only greedy decode needs the per-step vocab
                        # projection; the teacher-forced body carries the
                        # id through untouched so training pays the
                        # [T*B,H]@[H,V] matmul exactly once, outside the
                        # scan
                        logit = rnn_cell._linear([att_h], cfg.tgt_vocab,
                                                 bias=True,
                                                 scope_name="proj")
                        nxt = stf.argmax(logit, axis=-1,
                                         output_type=stf.int32)
                    else:
                        nxt = prev_id
                return (new_state, ctx, nxt, att_h)
            return step

        zero_ctx = stf.zeros([B, H])
        zero_att = stf.zeros([B, H])
        go_ids = stf.fill([B], stf.constant(np.int32(GO_ID)))

        # variables must exist in the ROOT graph before the scan body is
        # traced (FuncGraph-created variables would be lost); run one
        # throwaway feed_previous step (the variant that touches EVERY
        # variable incl. proj) — nothing fetches it, so it prunes away
        make_step(True)((enc_state, zero_ctx, go_ids, zero_att),
                        stf.nn.embedding_lookup(tgt_emb, go_ids))

        dec_in = stf.transpose(
            stf.nn.embedding_lookup(tgt_emb, tgt_in), [1, 0, 2])
        from simple_tensorflow_tpu.ops import functional_ops

        init = (enc_state, zero_ctx, go_ids, zero_att)
        _, _, _, att_seq = functional_ops.scan(
            make_step(False), dec_in, initializer=init, name="dec_train")
        # one [T*B,H] @ [H,V] projection — re-run proj on stacked outputs
        with stf.variable_scope("decoder", reuse=True):
            flat = stf.reshape(att_seq, [cfg.tgt_len * B, H])
            logits_flat = rnn_cell._linear([flat], cfg.tgt_vocab,
                                           bias=True, scope_name="proj")
        logits = stf.transpose(
            stf.reshape(logits_flat, [cfg.tgt_len, B, cfg.tgt_vocab]),
            [1, 0, 2])

        # greedy decode path (feed_previous=True), same variables; the
        # elems tensor only supplies the trip count (the body feeds back
        # prev_id), so thread the smallest possible buffer
        dummy = stf.zeros([cfg.tgt_len, 1])
        _, _, ids_seq, _ = functional_ops.scan(
            make_step(True), dummy, initializer=init, name="dec_greedy")
        decoded = stf.transpose(ids_seq, [1, 0])

        # ---- loss: length-masked teacher-forced xent --------------------
        tgt_mask = stf.cast(stf.not_equal(tgt_out, 0), stf.float32)
        xent = stf.nn.sparse_softmax_cross_entropy_with_logits(
            labels=tgt_out, logits=logits)
        loss = stf.reduce_sum(xent * tgt_mask) / \
            stf.maximum(stf.reduce_sum(tgt_mask), 1.0)

    out = {"src": src, "src_len": src_len, "tgt_in": tgt_in,
           "tgt_out": tgt_out, "loss": loss, "logits": logits,
           "decoded": decoded}
    if training:
        tvars = stf.trainable_variables()
        grads = stf.gradients(loss, tvars)
        clipped, _ = stf.clip_by_global_norm(grads, cfg.max_grad_norm)
        opt = stf.train.AdamOptimizer(cfg.learning_rate)
        out["train_op"] = opt.apply_gradients(zip(clipped, tvars))
    return out


def synthetic_copy_batch(batch_size, cfg, seed=0):
    """The classic seq2seq sanity task: copy a random token sequence.
    Returns feeds for (src, src_len, tgt_in, tgt_out)."""
    rng = np.random.RandomState(seed)
    L = cfg.src_len
    lens = rng.randint(2, L + 1, size=batch_size).astype(np.int32)
    src = np.zeros((batch_size, L), np.int32)
    for i, n in enumerate(lens):
        src[i, :n] = rng.randint(2, cfg.src_vocab, size=n)
    tgt_out = np.zeros((batch_size, cfg.tgt_len), np.int32)
    tgt_out[:, :L] = src[:, :cfg.tgt_len]
    tgt_in = np.zeros_like(tgt_out)
    tgt_in[:, 0] = GO_ID
    tgt_in[:, 1:] = tgt_out[:, :-1]
    return src, lens, tgt_in, tgt_out
