"""Transformer-big WMT en-de seq2seq with beam search (BASELINE config 5).

(ref: the reference targets "Transformer-big WMT en-de (seq2seq, staged
across TPU slice sub-meshes)".)

TPU-first choices:
- Every attention (encoder self, cross, causal decoder self) runs the
  Pallas flash-attention kernel; padding masks ride the kernel's additive
  key-bias input and attention dropout is generated in-kernel. All shapes
  static (fixed src/tgt lengths) for MXU tiling.
- bf16 activations, f32 parameters, fused Pallas LayerNorm, label-smoothed
  xent in f32.
- Beam search re-scores the full prefix each step — O(L^2) FLOPs but every
  iteration is the same static XLA program (no growing shapes, no host
  sync), which on TPU beats an incrementally-cached decoder that would
  retrace per length. Written entirely with stf graph ops lowering to one
  lax.while_loop.
- Pipeline-parallel staging lives in stf.parallel.pipeline ("staged across
  TPU slice sub-meshes"); data/tensor parallel via stf.parallel.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.models import common


@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 1024
    num_heads: int = 16
    d_ff: int = 4096
    num_layers: int = 6
    dropout: float = 0.1
    label_smoothing: float = 0.1
    max_len: int = 256
    layer_norm_eps: float = 1e-6
    pad_id: int = 0
    eos_id: int = 1

    @staticmethod
    def big():
        return TransformerConfig()

    @staticmethod
    def base():
        return TransformerConfig(d_model=512, num_heads=8, d_ff=2048)

    @staticmethod
    def tiny():
        return TransformerConfig(vocab_size=64, d_model=32, num_heads=2,
                                 d_ff=64, num_layers=2, dropout=0.0,
                                 max_len=32)


def _init(cfg):
    return stf.variance_scaling_initializer(1.0, "fan_avg", "uniform")


def _ln(x, cfg, name):
    return common.layer_norm(x, name, eps=cfg.layer_norm_eps)


def _dense(x, units, cfg, name, activation=None):
    return common.dense(x, units, _init(cfg), name, activation=activation)


def sinusoidal_position_encoding(max_len, d_model):
    """Classic sin/cos table as a numpy constant (host-computed once)."""
    pos = np.arange(max_len)[:, None].astype(np.float64)
    dim = np.arange(d_model // 2)[None, :].astype(np.float64)
    angle = pos / np.power(10000.0, 2.0 * dim / d_model)
    enc = np.zeros((max_len, d_model), np.float32)
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)
    return enc


def _residual(sub_out, h, cfg, training):
    """Sublayer tail ``h + dropout(sub_out)`` through the fused
    dropout+bias+residual op (ops/fused_ops.py; the registry routes the
    Pallas kernel vs the composed-XLA chain — same counter-based mask
    either way). rate 0 (eval / dropout-free configs) builds a plain
    add, keeping those graphs identical to the pre-fusion form."""
    rate = cfg.dropout if training else 0.0
    return stf.nn.fused_bias_dropout_residual(sub_out, h, rate=rate)


def _attention(q_in, kv_in, bias, cfg, training, compute_dtype, name,
               causal=False):
    """q_in (B,Sq,D) attends over kv_in (B,Sk,D). bias additive or None.

    Always the Pallas flash-attention kernel: padding bias rides the
    kernel's additive key-bias input, causal masking and attention-prob
    dropout happen in-kernel (counter-based mask replayed in the vjp).
    The output-projection dropout moved into the fused
    dropout+residual tail (_residual) applied at the block level.
    """
    b = int(q_in.shape[0])
    sq, sk = int(q_in.shape[1]), int(kv_in.shape[1])
    d, heads = cfg.d_model, cfg.num_heads
    hd = d // heads
    with stf.variable_scope(name):
        q = _dense(q_in, d, cfg, "q")
        k = _dense(kv_in, d, cfg, "k")
        v = _dense(kv_in, d, cfg, "v")
        q = common.split_heads(q, b, sq, heads, hd)
        k = common.split_heads(k, b, sk, heads, hd)
        v = common.split_heads(v, b, sk, heads, hd)
        key_bias = stf.reshape(bias, [b, sk]) if bias is not None else None
        ctx = stf.nn.fused_attention(
            q, k, v, bias=key_bias, causal=causal,
            dropout_rate=cfg.dropout if training else 0.0)
        out = _dense(common.merge_heads(ctx, b, sq, d), d, cfg, "out")
    return out


def _ffn(x, cfg, training, name):
    with stf.variable_scope(name):
        h = _dense(x, cfg.d_ff, cfg, "in", activation=stf.nn.relu)
        if training and cfg.dropout > 0:
            h = stf.nn.dropout(h, keep_prob=1.0 - cfg.dropout)
        return _dense(h, cfg.d_model, cfg, "out")


def _embed(ids, cfg, compute_dtype, training):
    """Shared embedding table, scaled, plus sinusoidal positions."""
    emb = stf.get_variable(
        "shared_embedding", [cfg.vocab_size, cfg.d_model],
        initializer=stf.random_normal_initializer(
            stddev=cfg.d_model ** -0.5))
    s = int(ids.shape[1])
    # mixed-precision lookup: [B,S,D] activations move in compute dtype,
    # gradient scatter-add still accumulates into the table in f32
    h = stf.nn.embedding_lookup(emb, ids, compute_dtype=compute_dtype) \
        * stf.cast(stf.constant(cfg.d_model ** 0.5), compute_dtype)
    pos = sinusoidal_position_encoding(cfg.max_len, cfg.d_model)[:s]
    h = h + stf.cast(stf.constant(pos[None, :, :]), compute_dtype)
    if training and cfg.dropout > 0:
        h = stf.nn.dropout(h, keep_prob=1.0 - cfg.dropout)
    return h, emb


def _pad_bias(ids, cfg):
    """(B,S) ids -> additive bias (B,1,1,S): -1e9 on pad positions."""
    b, s = int(ids.shape[0]), int(ids.shape[1])
    is_pad = stf.cast(stf.equal(ids, cfg.pad_id), stf.float32)
    return stf.reshape(is_pad, [b, 1, 1, s]) * -1e9


def encode(src_ids, cfg, training=True, compute_dtype=stf.bfloat16,
           scope="transformer", recompute=False):
    with stf.variable_scope(scope, reuse=stf.AUTO_REUSE):
        h, _ = _embed(src_ids, cfg, compute_dtype, training)
        bias = _pad_bias(src_ids, cfg)
        with stf.variable_scope("encoder"):
            def enc_layer(hh, i):
                with stf.variable_scope(f"layer_{i}"):
                    a = _attention(hh, hh, bias, cfg, training,
                                   compute_dtype, "self_attn")
                    hh = _ln(_residual(a, hh, cfg, training), cfg, "ln1")
                    f = _ffn(hh, cfg, training, "ffn")
                    return _ln(hh + f, cfg, "ln2")

            for i in range(cfg.num_layers):
                h = common.maybe_recompute(enc_layer, h, i, recompute, "enc")
    return h, bias


def decode(tgt_ids, enc_out, enc_bias, cfg, training=True,
           compute_dtype=stf.bfloat16, scope="transformer",
           recompute=False):
    """Returns logits (B, St, vocab); causal self-attention over tgt_ids."""
    with stf.variable_scope(scope, reuse=stf.AUTO_REUSE):
        h, emb = _embed(tgt_ids, cfg, compute_dtype, training)
        with stf.variable_scope("decoder"):
            def dec_layer(hh, i):
                with stf.variable_scope(f"layer_{i}"):
                    a = _attention(hh, hh, None, cfg, training,
                                   compute_dtype, "self_attn", causal=True)
                    hh = _ln(_residual(a, hh, cfg, training), cfg, "ln1")
                    c = _attention(hh, enc_out, enc_bias, cfg, training,
                                   compute_dtype, "cross_attn")
                    hh = _ln(_residual(c, hh, cfg, training), cfg, "ln2")
                    f = _ffn(hh, cfg, training, "ffn")
                    return _ln(hh + f, cfg, "ln3")

            for i in range(cfg.num_layers):
                h = common.maybe_recompute(dec_layer, h, i, recompute, "dec")
        # tied softmax weights, computed in compute dtype: the
        # [B*S, vocab] logits are the largest tensor in the model, and the
        # fused xent kernel does its softmax math in f32 blockwise anyway
        b, s = int(tgt_ids.shape[0]), int(tgt_ids.shape[1])
        flat = stf.reshape(h, [b * s, cfg.d_model])
        logits = stf.matmul(flat, stf.cast(emb, h.dtype.base_dtype),
                            transpose_b=True)
        return stf.reshape(logits, [b, s, cfg.vocab_size])


def smoothed_xent(logits, labels, weights, cfg):
    """Label-smoothed cross entropy, weight-masked mean (f32 loss math).

    The smoothing is fused into the streamed softmax-xent kernel — the
    composed form materialized log_softmax AND a dense one-hot at
    [B*S, vocab], three vocab-sized f32 tensors the kernel never builds."""
    vocab = cfg.vocab_size
    conf = 1.0 - cfg.label_smoothing
    low = cfg.label_smoothing / (vocab - 1)
    per_tok = stf.nn.fused_softmax_cross_entropy(
        logits, labels, label_smoothing=cfg.label_smoothing)
    # subtract the entropy of the smoothed target => 0 loss at perfection
    norm = -(conf * math.log(conf) +
             (vocab - 1) * low * math.log(low + 1e-20))
    per_tok = per_tok - norm
    w = stf.cast(weights, stf.float32)
    return stf.reduce_sum(per_tok * w) / (stf.reduce_sum(w) + 1e-9)


def transformer_train_model(batch_size=64, src_len=64, tgt_len=64,
                            cfg: TransformerConfig | None = None,
                            learning_rate=1.0, warmup_steps=4000,
                            compute_dtype=stf.bfloat16, data_parallel=False,
                            recompute=False):
    """Training graph: src/tgt -> label-smoothed loss -> Adam + noam decay.
    recompute="auto" resolves against the attached chip's HBM via the
    static cost model (framework/cost_model.py resolve_recompute)."""
    cfg = cfg or TransformerConfig.big()
    from ..framework import cost_model as _cm

    # encoder layers see src_len, decoder layers tgt_len (cross-attn
    # keys add a little on top; the heuristic ignores it); per-chip
    # under a dp mesh
    _shards = _cm.mesh_shard_factor(["dp"] if data_parallel else [])
    _act = (_cm.transformer_activation_bytes(
                batch_size, src_len, cfg.d_model, cfg.num_layers,
                dtype_bytes=compute_dtype.size)
            + _cm.transformer_activation_bytes(
                batch_size, tgt_len, cfg.d_model, cfg.num_layers,
                dtype_bytes=compute_dtype.size))
    _flops = (_cm.transformer_forward_flops(
                  batch_size, src_len, cfg.d_model, cfg.num_layers,
                  d_ff=cfg.d_ff)
              + _cm.transformer_forward_flops(
                  batch_size, tgt_len, cfg.d_model, cfg.num_layers,
                  d_ff=cfg.d_ff))
    recompute = _cm.resolve_recompute(recompute, _act / _shards,
                                      forward_flops=_flops / _shards)
    src = stf.placeholder(stf.int32, [batch_size, src_len], "src_ids")
    tgt_in = stf.placeholder(stf.int32, [batch_size, tgt_len], "tgt_in")
    tgt_out = stf.placeholder(stf.int32, [batch_size, tgt_len], "tgt_out")
    if data_parallel:
        from simple_tensorflow_tpu import parallel
        mesh = parallel.current_mesh()
        if mesh is not None and "dp" in mesh.axis_names:
            for t in (src, tgt_in, tgt_out):
                parallel.shard_feed(t, "dp")

    enc_out, enc_bias = encode(src, cfg, training=True,
                               compute_dtype=compute_dtype,
                               recompute=recompute)
    logits = decode(tgt_in, enc_out, enc_bias, cfg, training=True,
                    compute_dtype=compute_dtype, recompute=recompute)
    weights = stf.cast(stf.not_equal(tgt_out, cfg.pad_id), stf.float32)
    loss = smoothed_xent(logits, tgt_out, weights, cfg)

    gs = stf.train.get_or_create_global_step()
    # noam schedule: d^-0.5 * min(step^-0.5, step*warmup^-1.5)
    step = stf.cast(gs, stf.float32) + 1.0
    lr = (learning_rate * cfg.d_model ** -0.5 *
          stf.minimum(stf.pow(step, -0.5), step * warmup_steps ** -1.5))
    opt = stf.train.AdamOptimizer(lr, beta1=0.9, beta2=0.997, epsilon=1e-9)
    train_op = opt.minimize(loss, global_step=gs)
    acc = stf.reduce_sum(stf.cast(stf.equal(
        stf.cast(stf.argmax(logits, -1, output_type=stf.int32), stf.int32),
        tgt_out), stf.float32) * weights) / (stf.reduce_sum(weights) + 1e-9)
    return {"src_ids": src, "tgt_in": tgt_in, "tgt_out": tgt_out,
            "loss": loss, "train_op": train_op, "accuracy": acc,
            "learning_rate": lr, "global_step": gs}


def beam_search_decode(src, cfg: TransformerConfig | None = None,
                       beam_size=4, decode_len=None, alpha=0.6,
                       compute_dtype=stf.bfloat16, scope="transformer"):
    """Beam search over the decoder; returns (ids (B,beam,L), scores (B,beam)).

    Fixed decode_len iterations of one static XLA program via stf.while_loop;
    prefix re-scored each step (see module docstring). Finished beams (EOS
    emitted) are extended only by EOS at zero cost, so scores freeze.
    """
    cfg = cfg or TransformerConfig.big()
    b = int(src.shape[0])
    L = decode_len or cfg.max_len
    k = beam_size
    vocab = cfg.vocab_size
    neg_inf = -1e9

    enc_out, enc_bias = encode(src, cfg, training=False,
                               compute_dtype=compute_dtype, scope=scope)
    # tile encoder outputs over beams: (B,S,D) -> (B*k,S,D)
    s_src, d = int(enc_out.shape[1]), int(enc_out.shape[2])
    enc_tiled = stf.reshape(
        stf.tile(stf.expand_dims(enc_out, 1), [1, k, 1, 1]),
        [b * k, s_src, d])
    bias_tiled = stf.reshape(
        stf.tile(stf.expand_dims(enc_bias, 1), [1, k, 1, 1, 1]),
        [b * k, 1, 1, s_src])

    # state: i, seq (B,k,L) started with EOS column 0, logp (B,k)
    seq0 = stf.concat([
        stf.fill([b, k, 1], cfg.eos_id),
        stf.fill([b, k, L - 1], cfg.pad_id)], axis=2)
    # only beam 0 alive initially so the k first expansions differ
    logp0 = stf.constant(
        np.tile(np.array([[0.0] + [neg_inf] * (k - 1)], np.float32), (b, 1)))
    i0 = stf.constant(0)

    def cond(i, seq, logp):
        return stf.less(i, L - 1)

    def body(i, seq, logp):
        flat = stf.reshape(seq, [b * k, L])
        # decode() emits logits in compute dtype; beam-score math is f32
        logits = stf.cast(
            decode(flat, enc_tiled, bias_tiled, cfg, training=False,
                   compute_dtype=compute_dtype, scope=scope), stf.float32)
        # logits at position i predict token i+1: one_hot-select (static L)
        sel = stf.one_hot(i, L, dtype=stf.float32)  # (L,)
        step_logits = stf.reduce_sum(
            logits * stf.reshape(sel, [1, L, 1]), axis=1)  # (B*k, vocab)
        logprobs = stf.nn.log_softmax(step_logits, axis=-1)
        logprobs = stf.reshape(logprobs, [b, k, vocab])

        # finished beams (already emitted EOS after t=0) may only extend
        # with EOS at zero cost
        emitted = stf.reduce_sum(stf.cast(stf.equal(
            stf.slice(seq, [0, 0, 1], [b, k, L - 1]), cfg.eos_id),
            stf.float32), axis=2)
        finished = stf.greater(emitted, 0.0)  # (B,k)
        eos_row = stf.constant(
            np.array([0.0 if t == cfg.eos_id else neg_inf
                      for t in range(vocab)], np.float32).reshape(1, 1, vocab))
        fin_f = stf.reshape(stf.cast(finished, stf.float32), [b, k, 1])
        logprobs = logprobs * (1.0 - fin_f) + eos_row * fin_f

        total = stf.reshape(logp, [b, k, 1]) + logprobs  # (B,k,vocab)
        flat_total = stf.reshape(total, [b, k * vocab])
        new_logp, flat_idx = stf.nn.top_k(flat_total, k=k)  # (B,k)
        beam_idx = stf.cast(flat_idx // vocab, stf.int32)  # (B,k)
        tok = stf.cast(flat_idx % vocab, stf.int32)  # (B,k)

        # gather parent rows: batch offsets into (B*k, L)
        offs = stf.reshape(stf.constant(
            np.arange(b, dtype=np.int32) * k), [b, 1])
        parent = stf.reshape(beam_idx + offs, [-1])
        new_seq = stf.gather(stf.reshape(seq, [b * k, L]), parent)
        # write token at column i+1 via one_hot mask (static shapes)
        col = stf.one_hot(i + 1, L, dtype=stf.int32)  # (L,)
        new_seq = (new_seq * (1 - stf.reshape(col, [1, L])) +
                   stf.reshape(tok, [-1, 1]) * stf.reshape(col, [1, L]))
        return i + 1, stf.reshape(new_seq, [b, k, L]), new_logp

    _, seq, logp = stf.while_loop(cond, body, [i0, seq0, logp0])
    # GNMT length penalty, then re-sort: penalties vary with beam length,
    # so raw-logp order need not equal penalized order
    lengths = stf.reduce_sum(stf.cast(stf.logical_and(
        stf.not_equal(seq, cfg.pad_id), stf.not_equal(seq, cfg.eos_id)),
        stf.float32), axis=2) + 1.0
    penalty = stf.pow((5.0 + lengths) / 6.0, alpha)
    scores = logp / penalty
    scores, order = stf.nn.top_k(scores, k=k)  # (B,k) descending
    offs = stf.reshape(stf.constant(np.arange(b, dtype=np.int32) * k),
                       [b, 1])
    flat_order = stf.reshape(stf.cast(order, stf.int32) + offs, [-1])
    seq = stf.reshape(stf.gather(stf.reshape(seq, [b * k, L]), flat_order),
                      [b, k, L])
    return seq, scores


def synthetic_wmt_batch(batch_size, src_len, tgt_len, vocab_size=32768,
                        seed=0):
    rng = np.random.RandomState(seed)
    src = rng.randint(2, vocab_size, (batch_size, src_len)).astype(np.int32)
    tgt = rng.randint(2, vocab_size, (batch_size, tgt_len)).astype(np.int32)
    tgt_in = np.concatenate(
        [np.full((batch_size, 1), 1, np.int32), tgt[:, :-1]], axis=1)
    return {"src_ids": src, "tgt_in": tgt_in, "tgt_out": tgt}


def transformer_flops_per_token(cfg: TransformerConfig, src_len, tgt_len):
    d, ffn, L = cfg.d_model, cfg.d_ff, cfg.num_layers
    enc = L * 2 * (4 * d * d + 2 * d * ffn + 2 * src_len * d)
    dec = L * 2 * (8 * d * d + 2 * d * ffn + 2 * (src_len + tgt_len) * d)
    emb = 2 * d * cfg.vocab_size
    return (enc + dec) / 2 + emb  # rough per-token average
