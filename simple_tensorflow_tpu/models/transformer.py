"""Transformer-big WMT en-de seq2seq with beam search (BASELINE config 5).

(ref: the reference targets "Transformer-big WMT en-de (seq2seq, staged
across TPU slice sub-meshes)".)

TPU-first choices:
- Every attention (encoder self, cross, causal decoder self) runs the
  Pallas flash-attention kernel; padding masks ride the kernel's additive
  key-bias input and attention dropout is generated in-kernel. All shapes
  static (fixed src/tgt lengths) for MXU tiling.
- bf16 activations, f32 parameters, fused Pallas LayerNorm, label-smoothed
  xent in f32.
- Beam search re-scores the full prefix each step — O(L^2) FLOPs but every
  iteration is the same static XLA program (no growing shapes, no host
  sync), which on TPU beats an incrementally-cached decoder that would
  retrace per length. Written entirely with stf graph ops lowering to one
  lax.while_loop.
- Pipeline-parallel staging lives in stf.parallel.pipeline ("staged across
  TPU slice sub-meshes"); data/tensor parallel via stf.parallel.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math

import numpy as np

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.models import common


@dataclasses.dataclass
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 1024
    num_heads: int = 16
    d_ff: int = 4096
    num_layers: int = 6
    dropout: float = 0.1
    label_smoothing: float = 0.1
    max_len: int = 256
    layer_norm_eps: float = 1e-6
    pad_id: int = 0
    eos_id: int = 1

    @staticmethod
    def big():
        return TransformerConfig()

    @staticmethod
    def base():
        return TransformerConfig(d_model=512, num_heads=8, d_ff=2048)

    @staticmethod
    def tiny():
        return TransformerConfig(vocab_size=64, d_model=32, num_heads=2,
                                 d_ff=64, num_layers=2, dropout=0.0,
                                 max_len=32)


def _init(cfg):
    return stf.variance_scaling_initializer(1.0, "fan_avg", "uniform")


def _ln(x, cfg, name):
    return common.layer_norm(x, name, eps=cfg.layer_norm_eps)


def _dense(x, units, cfg, name, activation=None):
    return common.dense(x, units, _init(cfg), name, activation=activation)


def _tp_gather(x, tp_axis):
    """All-gather a tp-sharded activation back to replicated.

    The ONE collective shape of the bit-exact decode-TP layout: heads
    (and the logits' vocab columns) are computed column-parallel — each
    device owns a full contraction for its slice, so every element is
    arithmetically identical to the single-device value — and this
    replicated constraint concatenates the slices (an XLA all-gather;
    no partial-sum all-reduce anywhere, so token streams stay
    bit-exact). The sharding-analysis rule prices the same all-gather,
    which is what keeps predicted vs harvested collective bytes in
    agreement. The input is first PINNED to its column-sharded layout
    (last dim on ``tp_axis``): without the pin the SPMD partitioner is
    free to replicate an operand upstream instead — for the tied
    logits head it would all-gather the whole vocab-sharded embedding
    table (d_model*vocab bytes) rather than the (n, vocab) logits row,
    turning the ONE cheap per-token collective into a weight-sized
    one. No-op when ``tp_axis`` is None (single-device build) or no
    mesh is active at lowering time."""
    if not tp_axis:
        return x
    from simple_tensorflow_tpu import parallel

    rank = x.shape.rank
    x = parallel.with_sharding_constraint(
        x, *([None] * (rank - 1) + [tp_axis]))
    return parallel.with_sharding_constraint(x, *([None] * rank))


def decode_tp_partition_rules(tp_axis="tp"):
    """Partition rules for the decode-tensor-parallel weight layout
    (apply via ``stf.parallel.match_partition_rules(..., apply=True)``
    after building the generative program, before restore/init).

    Decoder Q/K/V projections go column-parallel — output columns split
    over ``tp_axis``, matching the head-sharded KV cache layout
    (``"<axis>:heads"``) — and the tied softmax table vocab-shards so
    the logits matmul (and its int8 QuantMatMul twin) is
    column-parallel over vocab. Everything else (encoder, out/FFN/LN
    weights) is explicitly P(): replicated ON the mesh, so every
    decode-path array lives on the same device set. Encoder weights
    stay replicated on purpose — prefill numerics are untouched, and
    only the decode inner loop pays resharding."""
    from simple_tensorflow_tpu.parallel import P

    return [
        (r"decoder/.*/(self_attn|cross_attn)/(q|k|v)/kernel$",
         P(None, tp_axis)),
        (r"decoder/.*/(self_attn|cross_attn)/(q|k|v)/bias$", P(tp_axis)),
        (r"shared_embedding$", P(tp_axis, None)),
        (r"_int8_decode/emb_q$", P(None, tp_axis)),
        (r"_int8_decode/emb_scale$", P(tp_axis)),
        (r".*", P()),
    ]


def sinusoidal_position_encoding(max_len, d_model):
    """Classic sin/cos table as a numpy constant (host-computed once)."""
    pos = np.arange(max_len)[:, None].astype(np.float64)
    dim = np.arange(d_model // 2)[None, :].astype(np.float64)
    angle = pos / np.power(10000.0, 2.0 * dim / d_model)
    enc = np.zeros((max_len, d_model), np.float32)
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)
    return enc


def _residual(sub_out, h, cfg, training):
    """Sublayer tail ``h + dropout(sub_out)`` through the fused
    dropout+bias+residual op (ops/fused_ops.py; the registry routes the
    Pallas kernel vs the composed-XLA chain — same counter-based mask
    either way). rate 0 (eval / dropout-free configs) builds a plain
    add, keeping those graphs identical to the pre-fusion form."""
    rate = cfg.dropout if training else 0.0
    return stf.nn.fused_bias_dropout_residual(sub_out, h, rate=rate)


def _attention(q_in, kv_in, bias, cfg, training, compute_dtype, name,
               causal=False):
    """q_in (B,Sq,D) attends over kv_in (B,Sk,D). bias additive or None.

    Always the Pallas flash-attention kernel: padding bias rides the
    kernel's additive key-bias input, causal masking and attention-prob
    dropout happen in-kernel (counter-based mask replayed in the vjp).
    The output-projection dropout moved into the fused
    dropout+residual tail (_residual) applied at the block level.
    """
    b = int(q_in.shape[0])
    sq, sk = int(q_in.shape[1]), int(kv_in.shape[1])
    d, heads = cfg.d_model, cfg.num_heads
    hd = d // heads
    with stf.variable_scope(name):
        q = _dense(q_in, d, cfg, "q")
        k = _dense(kv_in, d, cfg, "k")
        v = _dense(kv_in, d, cfg, "v")
        q = common.split_heads(q, b, sq, heads, hd)
        k = common.split_heads(k, b, sk, heads, hd)
        v = common.split_heads(v, b, sk, heads, hd)
        key_bias = stf.reshape(bias, [b, sk]) if bias is not None else None
        ctx = stf.nn.fused_attention(
            q, k, v, bias=key_bias, causal=causal,
            dropout_rate=cfg.dropout if training else 0.0)
        out = _dense(common.merge_heads(ctx, b, sq, d), d, cfg, "out")
    return out


def _ffn(x, cfg, training, name):
    with stf.variable_scope(name):
        h = _dense(x, cfg.d_ff, cfg, "in", activation=stf.nn.relu)
        if training and cfg.dropout > 0:
            h = stf.nn.dropout(h, keep_prob=1.0 - cfg.dropout)
        return _dense(h, cfg.d_model, cfg, "out")


def _embed(ids, cfg, compute_dtype, training):
    """Shared embedding table, scaled, plus sinusoidal positions."""
    emb = stf.get_variable(
        "shared_embedding", [cfg.vocab_size, cfg.d_model],
        initializer=stf.random_normal_initializer(
            stddev=cfg.d_model ** -0.5))
    s = int(ids.shape[1])
    # mixed-precision lookup: [B,S,D] activations move in compute dtype,
    # gradient scatter-add still accumulates into the table in f32
    h = stf.nn.embedding_lookup(emb, ids, compute_dtype=compute_dtype) \
        * stf.cast(stf.constant(cfg.d_model ** 0.5), compute_dtype)
    pos = sinusoidal_position_encoding(cfg.max_len, cfg.d_model)[:s]
    h = h + stf.cast(stf.constant(pos[None, :, :]), compute_dtype)
    if training and cfg.dropout > 0:
        h = stf.nn.dropout(h, keep_prob=1.0 - cfg.dropout)
    return h, emb


def _pad_bias(ids, cfg):
    """(B,S) ids -> additive bias (B,1,1,S): -1e9 on pad positions."""
    b, s = int(ids.shape[0]), int(ids.shape[1])
    is_pad = stf.cast(stf.equal(ids, cfg.pad_id), stf.float32)
    return stf.reshape(is_pad, [b, 1, 1, s]) * -1e9


def encode(src_ids, cfg, training=True, compute_dtype=stf.bfloat16,
           scope="transformer", recompute=False):
    with stf.variable_scope(scope, reuse=stf.AUTO_REUSE):
        h, _ = _embed(src_ids, cfg, compute_dtype, training)
        bias = _pad_bias(src_ids, cfg)
        with stf.variable_scope("encoder"):
            def enc_layer(hh, i):
                with stf.variable_scope(f"layer_{i}"):
                    a = _attention(hh, hh, bias, cfg, training,
                                   compute_dtype, "self_attn")
                    hh = _ln(_residual(a, hh, cfg, training), cfg, "ln1")
                    f = _ffn(hh, cfg, training, "ffn")
                    return _ln(hh + f, cfg, "ln2")

            for i in range(cfg.num_layers):
                h = common.maybe_recompute(enc_layer, h, i, recompute, "enc")
    return h, bias


def decode(tgt_ids, enc_out, enc_bias, cfg, training=True,
           compute_dtype=stf.bfloat16, scope="transformer",
           recompute=False):
    """Returns logits (B, St, vocab); causal self-attention over tgt_ids."""
    with stf.variable_scope(scope, reuse=stf.AUTO_REUSE):
        h, emb = _embed(tgt_ids, cfg, compute_dtype, training)
        with stf.variable_scope("decoder"):
            def dec_layer(hh, i):
                with stf.variable_scope(f"layer_{i}"):
                    a = _attention(hh, hh, None, cfg, training,
                                   compute_dtype, "self_attn", causal=True)
                    hh = _ln(_residual(a, hh, cfg, training), cfg, "ln1")
                    c = _attention(hh, enc_out, enc_bias, cfg, training,
                                   compute_dtype, "cross_attn")
                    hh = _ln(_residual(c, hh, cfg, training), cfg, "ln2")
                    f = _ffn(hh, cfg, training, "ffn")
                    return _ln(hh + f, cfg, "ln3")

            for i in range(cfg.num_layers):
                h = common.maybe_recompute(dec_layer, h, i, recompute, "dec")
        # tied softmax weights, computed in compute dtype: the
        # [B*S, vocab] logits are the largest tensor in the model, and the
        # fused xent kernel does its softmax math in f32 blockwise anyway
        b, s = int(tgt_ids.shape[0]), int(tgt_ids.shape[1])
        flat = stf.reshape(h, [b * s, cfg.d_model])
        logits = stf.matmul(flat, stf.cast(emb, h.dtype.base_dtype),
                            transpose_b=True)
        return stf.reshape(logits, [b, s, cfg.vocab_size])


def smoothed_xent(logits, labels, weights, cfg):
    """Label-smoothed cross entropy, weight-masked mean (f32 loss math).

    The smoothing is fused into the streamed softmax-xent kernel — the
    composed form materialized log_softmax AND a dense one-hot at
    [B*S, vocab], three vocab-sized f32 tensors the kernel never builds."""
    vocab = cfg.vocab_size
    conf = 1.0 - cfg.label_smoothing
    low = cfg.label_smoothing / (vocab - 1)
    per_tok = stf.nn.fused_softmax_cross_entropy(
        logits, labels, label_smoothing=cfg.label_smoothing)
    # subtract the entropy of the smoothed target => 0 loss at perfection
    norm = -(conf * math.log(conf) +
             (vocab - 1) * low * math.log(low + 1e-20))
    per_tok = per_tok - norm
    w = stf.cast(weights, stf.float32)
    return stf.reduce_sum(per_tok * w) / (stf.reduce_sum(w) + 1e-9)


def transformer_train_model(batch_size=64, src_len=64, tgt_len=64,
                            cfg: TransformerConfig | None = None,
                            learning_rate=1.0, warmup_steps=4000,
                            compute_dtype=stf.bfloat16, data_parallel=False,
                            recompute=False):
    """Training graph: src/tgt -> label-smoothed loss -> Adam + noam decay.
    recompute="auto" resolves against the attached chip's HBM via the
    static cost model (framework/cost_model.py resolve_recompute)."""
    cfg = cfg or TransformerConfig.big()
    from ..framework import cost_model as _cm

    # encoder layers see src_len, decoder layers tgt_len (cross-attn
    # keys add a little on top; the heuristic ignores it); per-chip
    # under a dp mesh
    _shards = _cm.mesh_shard_factor(["dp"] if data_parallel else [])
    _act = (_cm.transformer_activation_bytes(
                batch_size, src_len, cfg.d_model, cfg.num_layers,
                dtype_bytes=compute_dtype.size)
            + _cm.transformer_activation_bytes(
                batch_size, tgt_len, cfg.d_model, cfg.num_layers,
                dtype_bytes=compute_dtype.size))
    _flops = (_cm.transformer_forward_flops(
                  batch_size, src_len, cfg.d_model, cfg.num_layers,
                  d_ff=cfg.d_ff)
              + _cm.transformer_forward_flops(
                  batch_size, tgt_len, cfg.d_model, cfg.num_layers,
                  d_ff=cfg.d_ff))
    recompute = _cm.resolve_recompute(recompute, _act / _shards,
                                      forward_flops=_flops / _shards)
    src = stf.placeholder(stf.int32, [batch_size, src_len], "src_ids")
    tgt_in = stf.placeholder(stf.int32, [batch_size, tgt_len], "tgt_in")
    tgt_out = stf.placeholder(stf.int32, [batch_size, tgt_len], "tgt_out")
    if data_parallel:
        from simple_tensorflow_tpu import parallel
        mesh = parallel.current_mesh()
        if mesh is not None and "dp" in mesh.axis_names:
            for t in (src, tgt_in, tgt_out):
                parallel.shard_feed(t, "dp")

    enc_out, enc_bias = encode(src, cfg, training=True,
                               compute_dtype=compute_dtype,
                               recompute=recompute)
    logits = decode(tgt_in, enc_out, enc_bias, cfg, training=True,
                    compute_dtype=compute_dtype, recompute=recompute)
    weights = stf.cast(stf.not_equal(tgt_out, cfg.pad_id), stf.float32)
    loss = smoothed_xent(logits, tgt_out, weights, cfg)

    gs = stf.train.get_or_create_global_step()
    # noam schedule: d^-0.5 * min(step^-0.5, step*warmup^-1.5)
    step = stf.cast(gs, stf.float32) + 1.0
    lr = (learning_rate * cfg.d_model ** -0.5 *
          stf.minimum(stf.pow(step, -0.5), step * warmup_steps ** -1.5))
    opt = stf.train.AdamOptimizer(lr, beta1=0.9, beta2=0.997, epsilon=1e-9)
    train_op = opt.minimize(loss, global_step=gs)
    acc = stf.reduce_sum(stf.cast(stf.equal(
        stf.cast(stf.argmax(logits, -1, output_type=stf.int32), stf.int32),
        tgt_out), stf.float32) * weights) / (stf.reduce_sum(weights) + 1e-9)
    return {"src_ids": src, "tgt_in": tgt_in, "tgt_out": tgt_out,
            "loss": loss, "train_op": train_op, "accuracy": acc,
            "learning_rate": lr, "global_step": gs}


# ---------------------------------------------------------------------------
# Incremental (KV-cached) decode
# ---------------------------------------------------------------------------

class _BeamCaches:
    """Loop-carried functional caches for the cached beam search: one
    (k, v) pair per decoder layer, each (B, L, H, hd), updated in-place
    functionally via a one-hot position mask (static shapes — the whole
    search stays ONE XLA program)."""

    def __init__(self, flat_arrays, i, b, max_len):
        self._arrays = list(flat_arrays)
        self._i = i
        self._b = b
        self._L = max_len
        self.updated = list(flat_arrays)

    def append_and_gather(self, layer, k_new, v_new):
        mask = stf.cast(stf.reshape(
            stf.one_hot(self._i, self._L, dtype=stf.float32),
            [1, self._L, 1, 1]), k_new.dtype.base_dtype)
        k_all = self._arrays[2 * layer] * (1.0 - mask) + k_new * mask
        v_all = self._arrays[2 * layer + 1] * (1.0 - mask) + v_new * mask
        self.updated[2 * layer] = k_all
        self.updated[2 * layer + 1] = v_all
        lengths = stf.fill([self._b], self._i + 1)
        return k_all, v_all, lengths


class _SlotCaches:
    """Variable-backed paged caches for the serving decode step: each
    layer's k/v live device-resident in the VariableStore
    (ops/kv_cache_ops.py); appends scatter at (slot, position) and the
    gather rides a control dependency so the RAW is graph-ordered.

    ``verify_plan=True`` (the speculative VERIFY program) stamps every
    append with the ``_verify_plan``/``_refcount_guarded`` attr pair —
    the lint/serving-decode-cache contract that verify-plan cache
    writes commit only through the engine's accepted-prefix refcount
    bookkeeping."""

    def __init__(self, caches, slots, positions, verify_plan=False):
        self._caches = caches          # [(KVCache k, KVCache v)] per layer
        self._slots = slots
        self._pos = positions
        self._verify = bool(verify_plan)

    def append_and_gather(self, layer, k_new, v_new):
        kc, vc = self._caches[layer]
        k_all = kc.append_and_gather(k_new, self._slots, self._pos,
                                     verify_plan=self._verify,
                                     refcount_guarded=self._verify)
        v_all = vc.append_and_gather(v_new, self._slots, self._pos,
                                     verify_plan=self._verify,
                                     refcount_guarded=self._verify)
        return k_all, v_all, self._pos + 1

    def append_and_gather_block(self, layer, k_new, v_new):
        """Block variant: ``k_new/v_new (B, Kq, H, hd)`` append at
        positions ``pos..pos+Kq-1``; returns the gathered caches plus
        the BASE length (committed prefix before the block) —
        DecodeAttention's ``causal_offset=True`` contract."""
        kc, vc = self._caches[layer]
        k_all = kc.append_and_gather(k_new, self._slots, self._pos,
                                     verify_plan=self._verify,
                                     refcount_guarded=self._verify)
        v_all = vc.append_and_gather(v_new, self._slots, self._pos,
                                     verify_plan=self._verify,
                                     refcount_guarded=self._verify)
        return k_all, v_all, self._pos


def _decode_cross_kv(enc_out, cfg, compute_dtype, scope):
    """Per-layer cross-attention K/V projections of the encoder output,
    computed ONCE per sequence (the naive re-forward path recomputes
    them every emitted token). Returns [(ck, cv)] each
    (B, S_src, H, hd) — the DecodeAttention cache layout."""
    b, s = int(enc_out.shape[0]), int(enc_out.shape[1])
    d, heads = cfg.d_model, cfg.num_heads
    hd = d // heads
    out = []
    with stf.variable_scope(scope, reuse=stf.AUTO_REUSE):
        with stf.variable_scope("decoder"):
            for i in range(cfg.num_layers):
                with stf.variable_scope(f"layer_{i}"):
                    with stf.variable_scope("cross_attn"):
                        ck = stf.reshape(_dense(enc_out, d, cfg, "k"),
                                         [b, s, heads, hd])
                        cv = stf.reshape(_dense(enc_out, d, cfg, "v"),
                                         [b, s, heads, hd])
                out.append((ck, cv))
    return out


def _incremental_decode(tok, pos, caches, cross_kv, cross_bias, cross_len,
                        cfg, compute_dtype, scope, tp_axis=None):
    """ONE decoder position for B sequences against cached state.

    tok: (B,) int32 input tokens; pos: scalar or (B,) int32 position(s);
    caches: a :class:`_BeamCaches` / :class:`_SlotCaches` accessor;
    cross_kv: [(ck, cv)] per layer (B, S_src, H, hd); cross_bias:
    (B, S_src) additive f32; cross_len: (B,) int32. Returns
    (h (B, d_model) in compute dtype, emb) — the caller owns the logits
    matmul (f32/bf16 tied softmax, or the int8 QuantMatMul route).

    Token-for-token equivalent to selecting position ``pos`` of the
    full re-forward :func:`decode` at eval time: every sublayer here is
    position-independent (LN, FFN, residual) or reads exactly the
    positions the causal mask admits (self-attention over the cache,
    cross-attention over the full source).

    ``cross_kv=None`` builds the decoder-only (causal LM) step: the
    cross-attention sublayer — and its ``ln2`` — is skipped entirely,
    matching the sublayer/LN naming of
    :func:`~.causal_lm.causal_lm_logits`.

    ``tp_axis``: decode tensor parallelism — Q/K/V run column-parallel
    (heads split over the axis, see :func:`decode_tp_partition_rules`),
    attention runs per-shard against the head-sharded cache with zero
    collectives, and the context all-gathers back to replicated
    (:func:`_tp_gather`) right before each output projection.
    """
    b = int(tok.shape[0])
    d, heads = cfg.d_model, cfg.num_heads
    hd = d // heads
    with stf.variable_scope(scope, reuse=stf.AUTO_REUSE):
        emb = stf.get_variable(
            "shared_embedding", [cfg.vocab_size, cfg.d_model],
            initializer=stf.random_normal_initializer(
                stddev=cfg.d_model ** -0.5))
        h = stf.nn.embedding_lookup(emb, tok, compute_dtype=compute_dtype) \
            * stf.cast(stf.constant(cfg.d_model ** 0.5), compute_dtype)
        pos_table = stf.constant(
            sinusoidal_position_encoding(cfg.max_len, cfg.d_model))
        h = h + stf.cast(stf.gather(pos_table, pos), compute_dtype)
        with stf.variable_scope("decoder"):
            for i in range(cfg.num_layers):
                with stf.variable_scope(f"layer_{i}"):
                    with stf.variable_scope("self_attn"):
                        q = stf.reshape(_dense(h, d, cfg, "q"),
                                        [b, heads, hd])
                        k_new = stf.reshape(_dense(h, d, cfg, "k"),
                                            [b, 1, heads, hd])
                        v_new = stf.reshape(_dense(h, d, cfg, "v"),
                                            [b, 1, heads, hd])
                        k_all, v_all, lengths = caches.append_and_gather(
                            i, k_new, v_new)
                        a = stf.nn.decode_attention(q, k_all, v_all,
                                                    lengths)
                        a = _tp_gather(stf.reshape(a, [b, d]), tp_axis)
                        a = _dense(a, d, cfg, "out")
                    h = _ln(_residual(a, h, cfg, False), cfg, "ln1")
                    if cross_kv is not None:
                        with stf.variable_scope("cross_attn"):
                            qc = stf.reshape(_dense(h, d, cfg, "q"),
                                             [b, heads, hd])
                            ck, cv = cross_kv[i]
                            c = stf.nn.decode_attention(
                                qc, ck, cv, cross_len, bias=cross_bias)
                            c = _tp_gather(stf.reshape(c, [b, d]),
                                           tp_axis)
                            c = _dense(c, d, cfg, "out")
                        h = _ln(_residual(c, h, cfg, False), cfg, "ln2")
                    f = _ffn(h, cfg, False, "ffn")
                    h = _ln(h + f, cfg, "ln3")
    return h, emb


def _block_decode(tok_block, pos, caches, cross_kv, cross_bias, cross_len,
                  cfg, compute_dtype, scope, tp_axis=None):
    """A BLOCK of Kq consecutive decoder positions for B sequences.

    tok_block: (B, Kq) int32 input tokens at positions
    ``pos[b]..pos[b]+Kq-1``; pos: (B,) int32 committed prefix per
    sequence BEFORE the block; caches: an accessor with
    ``append_and_gather_block`` (:class:`_SlotCaches`, or the paged
    variant in models/causal_lm.py); cross args as in
    :func:`_incremental_decode` (``cross_kv=None`` for decoder-only).
    Returns (h (B, Kq, d_model), emb).

    This is the speculative VERIFY shape — the target model re-scores
    the draft's K proposals in ONE pass, self-attention running the
    query-block DecodeAttention kernel with ``causal_offset=True``
    (query j sees the committed prefix plus block positions <= j) — and
    also the causal-LM page-block prefill shape. Per-position it is
    arithmetic-identical to Kq chained :func:`_incremental_decode`
    steps: every sublayer is position-local, and the block attention
    admits exactly the positions the chained steps would have seen.
    """
    b, kq = int(tok_block.shape[0]), int(tok_block.shape[1])
    d, heads = cfg.d_model, cfg.num_heads
    hd = d // heads
    with stf.variable_scope(scope, reuse=stf.AUTO_REUSE):
        emb = stf.get_variable(
            "shared_embedding", [cfg.vocab_size, cfg.d_model],
            initializer=stf.random_normal_initializer(
                stddev=cfg.d_model ** -0.5))
        h = stf.nn.embedding_lookup(emb, tok_block,
                                    compute_dtype=compute_dtype) \
            * stf.cast(stf.constant(cfg.d_model ** 0.5), compute_dtype)
        pos_table = stf.constant(
            sinusoidal_position_encoding(cfg.max_len, cfg.d_model))
        pos_idx = stf.reshape(pos, [b, 1]) + stf.constant(
            np.arange(kq, dtype=np.int32).reshape(1, kq))
        h = h + stf.cast(stf.gather(pos_table, pos_idx), compute_dtype)
        with stf.variable_scope("decoder"):
            for i in range(cfg.num_layers):
                with stf.variable_scope(f"layer_{i}"):
                    with stf.variable_scope("self_attn"):
                        q = stf.reshape(_dense(h, d, cfg, "q"),
                                        [b, kq, heads, hd])
                        k_new = stf.reshape(_dense(h, d, cfg, "k"),
                                            [b, kq, heads, hd])
                        v_new = stf.reshape(_dense(h, d, cfg, "v"),
                                            [b, kq, heads, hd])
                        k_all, v_all, base = \
                            caches.append_and_gather_block(i, k_new,
                                                           v_new)
                        a = stf.nn.decode_attention(
                            q, k_all, v_all, base, causal_offset=True)
                        a = _tp_gather(stf.reshape(a, [b, kq, d]),
                                       tp_axis)
                        a = _dense(a, d, cfg, "out")
                    h = _ln(_residual(a, h, cfg, False), cfg, "ln1")
                    if cross_kv is not None:
                        with stf.variable_scope("cross_attn"):
                            qc = stf.reshape(_dense(h, d, cfg, "q"),
                                             [b, kq, heads, hd])
                            ck, cv = cross_kv[i]
                            c = stf.nn.decode_attention(
                                qc, ck, cv, cross_len, bias=cross_bias)
                            c = _tp_gather(stf.reshape(c, [b, kq, d]),
                                           tp_axis)
                            c = _dense(c, d, cfg, "out")
                        h = _ln(_residual(c, h, cfg, False), cfg, "ln2")
                    f = _ffn(h, cfg, False, "ffn")
                    h = _ln(h + f, cfg, "ln3")
    return h, emb


def beam_search_decode(src, cfg: TransformerConfig | None = None,
                       beam_size=4, decode_len=None, alpha=0.6,
                       compute_dtype=stf.bfloat16, scope="transformer",
                       use_cache=False):
    """Beam search over the decoder; returns (ids (B,beam,L), scores (B,beam)).

    Fixed decode_len iterations of one static XLA program via stf.while_loop;
    Finished beams (EOS emitted) are extended only by EOS at zero cost, so
    scores freeze.

    use_cache=False re-scores the full prefix each step (O(L^2) FLOPs,
    see the module docstring); use_cache=True carries per-layer KV
    caches through the loop and decodes ONE position per step through
    the DecodeAttention kernel (O(L) FLOPs) — token-for-token the same
    search (int-exact ids; scores to float round-off), bench.py's
    ``generative`` row pins the speedup.
    """
    cfg = cfg or TransformerConfig.big()
    b = int(src.shape[0])
    L = decode_len or cfg.max_len
    if L > cfg.max_len:
        # the position-encoding table has cfg.max_len rows; a longer
        # decode would silently clamp the gather (wrong tokens, no
        # error) on the cached path
        raise ValueError(
            f"decode_len={L} exceeds cfg.max_len={cfg.max_len}")
    k = beam_size
    vocab = cfg.vocab_size
    neg_inf = -1e9
    heads = cfg.num_heads
    hd = cfg.d_model // heads

    enc_out, enc_bias = encode(src, cfg, training=False,
                               compute_dtype=compute_dtype, scope=scope)
    # tile encoder outputs over beams: (B,S,D) -> (B*k,S,D)
    s_src, d = int(enc_out.shape[1]), int(enc_out.shape[2])
    enc_tiled = stf.reshape(
        stf.tile(stf.expand_dims(enc_out, 1), [1, k, 1, 1]),
        [b * k, s_src, d])
    bias_tiled = stf.reshape(
        stf.tile(stf.expand_dims(enc_bias, 1), [1, k, 1, 1, 1]),
        [b * k, 1, 1, s_src])

    # state: i, seq (B,k,L) started with EOS column 0, logp (B,k)
    seq0 = stf.concat([
        stf.fill([b, k, 1], cfg.eos_id),
        stf.fill([b, k, L - 1], cfg.pad_id)], axis=2)
    # only beam 0 alive initially so the k first expansions differ
    logp0 = stf.constant(
        np.tile(np.array([[0.0] + [neg_inf] * (k - 1)], np.float32), (b, 1)))
    i0 = stf.constant(0)

    eos_row = stf.constant(
        np.array([0.0 if t == cfg.eos_id else neg_inf
                  for t in range(vocab)], np.float32).reshape(1, 1, vocab))
    offs = stf.reshape(stf.constant(
        np.arange(b, dtype=np.int32) * k), [b, 1])

    def select(i, seq, logp, step_logits):
        """Beam expansion shared by both paths: score position ``i``'s
        logits, pick the top-k continuations, write the token at column
        i+1. Returns (new_seq, new_logp, parent (B*k,) row indices)."""
        logprobs = stf.nn.log_softmax(step_logits, axis=-1)
        logprobs = stf.reshape(logprobs, [b, k, vocab])

        # finished beams (already emitted EOS after t=0) may only extend
        # with EOS at zero cost
        emitted = stf.reduce_sum(stf.cast(stf.equal(
            stf.slice(seq, [0, 0, 1], [b, k, L - 1]), cfg.eos_id),
            stf.float32), axis=2)
        finished = stf.greater(emitted, 0.0)  # (B,k)
        fin_f = stf.reshape(stf.cast(finished, stf.float32), [b, k, 1])
        logprobs = logprobs * (1.0 - fin_f) + eos_row * fin_f

        total = stf.reshape(logp, [b, k, 1]) + logprobs  # (B,k,vocab)
        flat_total = stf.reshape(total, [b, k * vocab])
        new_logp, flat_idx = stf.nn.top_k(flat_total, k=k)  # (B,k)
        beam_idx = stf.cast(flat_idx // vocab, stf.int32)  # (B,k)
        tok = stf.cast(flat_idx % vocab, stf.int32)  # (B,k)

        # gather parent rows: batch offsets into (B*k, L)
        parent = stf.reshape(beam_idx + offs, [-1])
        new_seq = stf.gather(stf.reshape(seq, [b * k, L]), parent)
        # write token at column i+1 via one_hot mask (static shapes)
        col = stf.one_hot(i + 1, L, dtype=stf.int32)  # (L,)
        new_seq = (new_seq * (1 - stf.reshape(col, [1, L])) +
                   stf.reshape(tok, [-1, 1]) * stf.reshape(col, [1, L]))
        return stf.reshape(new_seq, [b, k, L]), new_logp, parent

    def cond(i, seq, logp, *caches):
        return stf.less(i, L - 1)

    def body_naive(i, seq, logp):
        flat = stf.reshape(seq, [b * k, L])
        # decode() emits logits in compute dtype; beam-score math is f32
        logits = stf.cast(
            decode(flat, enc_tiled, bias_tiled, cfg, training=False,
                   compute_dtype=compute_dtype, scope=scope), stf.float32)
        # logits at position i predict token i+1: one_hot-select (static L)
        sel = stf.one_hot(i, L, dtype=stf.float32)  # (L,)
        step_logits = stf.reduce_sum(
            logits * stf.reshape(sel, [1, L, 1]), axis=1)  # (B*k, vocab)
        new_seq, new_logp, _ = select(i, seq, logp, step_logits)
        return i + 1, new_seq, new_logp

    if use_cache:
        cross_kv = _decode_cross_kv(enc_tiled, cfg, compute_dtype, scope)
        cross_bias = stf.reshape(bias_tiled, [b * k, s_src])
        cross_len = stf.fill([b * k], s_src)
        caches0 = []
        for _ in range(cfg.num_layers):
            caches0.append(stf.zeros([b * k, L, heads, hd],
                                     dtype=compute_dtype))
            caches0.append(stf.zeros([b * k, L, heads, hd],
                                     dtype=compute_dtype))

        def body_cached(i, seq, logp, *flat_caches):
            # current input token = column i of every beam row
            coli = stf.one_hot(i, L, dtype=stf.int32)
            tok = stf.reduce_sum(seq * stf.reshape(coli, [1, 1, L]),
                                 axis=2)  # (B,k)
            flat_tok = stf.reshape(tok, [b * k])
            cache = _BeamCaches(flat_caches, i, b * k, L)
            h, emb = _incremental_decode(
                flat_tok, i, cache, cross_kv, cross_bias, cross_len,
                cfg, compute_dtype, scope)
            logits = stf.matmul(h, stf.cast(emb, h.dtype.base_dtype),
                                transpose_b=True)
            step_logits = stf.cast(logits, stf.float32)
            new_seq, new_logp, parent = select(i, seq, logp, step_logits)
            # beams reorder -> their caches reorder with them
            new_caches = [stf.gather(c, parent) for c in cache.updated]
            return (i + 1, new_seq, new_logp, *new_caches)

        out = stf.while_loop(cond, body_cached,
                             [i0, seq0, logp0] + caches0)
        _, seq, logp = out[0], out[1], out[2]
    else:
        _, seq, logp = stf.while_loop(cond, body_naive, [i0, seq0, logp0])
    # GNMT length penalty, then re-sort: penalties vary with beam length,
    # so raw-logp order need not equal penalized order
    lengths = stf.reduce_sum(stf.cast(stf.logical_and(
        stf.not_equal(seq, cfg.pad_id), stf.not_equal(seq, cfg.eos_id)),
        stf.float32), axis=2) + 1.0
    penalty = stf.pow((5.0 + lengths) / 6.0, alpha)
    scores = logp / penalty
    scores, order = stf.nn.top_k(scores, k=k)  # (B,k) descending
    offs = stf.reshape(stf.constant(np.arange(b, dtype=np.int32) * k),
                       [b, 1])
    flat_order = stf.reshape(stf.cast(order, stf.int32) + offs, [-1])
    seq = stf.reshape(stf.gather(stf.reshape(seq, [b * k, L]), flat_order),
                      [b, k, L])
    return seq, scores


# ---------------------------------------------------------------------------
# Serving-side generative program (stf.serving.generative)
# ---------------------------------------------------------------------------

def build_int8_logits_weights(emb, cfg, scope="transformer"):
    """Column-wise int8 quantization of the tied softmax weights for the
    decode path: ``emb (vocab, d)`` → ``wq (d, vocab) int8`` +
    ``scale (vocab,) f32`` variables, quantized ON DEVICE by the
    returned init op (run it AFTER restoring the model weights). The
    decode logits matmul then routes through the QuantMatMul kernel
    registry entry — int8 runs the MXU at 2x the bf16 rate and halves
    the vocab-sized weight read per emitted token."""
    d, vocab = cfg.d_model, cfg.vocab_size
    with stf.variable_scope(f"{scope}_int8_decode",
                            reuse=stf.AUTO_REUSE):
        wq = stf.get_variable("emb_q", [d, vocab], dtype=stf.int8,
                              initializer=stf.zeros_initializer(),
                              trainable=False,
                              collections=["stf_decode_int8"])
        scale = stf.get_variable("emb_scale", [vocab], dtype=stf.float32,
                                 initializer=stf.ones_initializer(),
                                 trainable=False,
                                 collections=["stf_decode_int8"])
        w = stf.transpose(stf.cast(emb, stf.float32), [1, 0])  # (d, vocab)
        s = stf.maximum(stf.reduce_max(stf.abs(w), axis=0), 1e-8) / 127.0
        q = stf.cast(stf.round(w / stf.reshape(s, [1, vocab])), stf.int8)
        init = stf.group(stf.assign(wq, q), stf.assign(scale, s),
                         name="int8_decode_init")
    return wq, scale, init


def build_generative_program(cfg: TransformerConfig, src_len, *,
                             num_slots, max_decode_len,
                             decode_bucket_sizes=None,
                             prefill_bucket_sizes=(1,),
                             compute_dtype=stf.float32, int8=False,
                             scope="transformer", cache_sharding=None,
                             sampling=None, speculative_k=None,
                             draft_steps=None, tp_axis=None):
    """Build the paged-cache decode graphs for token-level serving.

    Emits, in the CURRENT default graph:

    - per-layer self-attention K/V caches + per-layer cross-attention
      K/V caches + the source padding-bias cache, all device-resident
      ``KVCache`` pages with ``num_slots + 1`` rows (the extra row is
      the SCRATCH slot bucket padding writes into, so a padded decode
      row can never corrupt a live sequence's cache);
    - ``alloc_op``: zero-fills every cache (engine start);
    - one PREFILL program per ``prefill_bucket_sizes`` entry: encoder
      forward + cross-K/V projection, scattered into the slots' cache
      rows (feeds: src (pb, src_len), slots (pb,));
    - one DECODE program per ``decode_bucket_sizes`` entry: ONE
      position for sb sequences — embed, per-layer cached self-attn
      (KVCacheAppend at (slot, pos) then DecodeAttention), cached
      cross-attn, tied-softmax logits (QuantMatMul when ``int8``),
      greedy argmax (feeds: tok (sb,), pos (sb,), slots (sb,);
      fetches: next_tok (sb,), logp (sb,));
    - with ``sampling={"temperature": .., "top_k": .., "top_p": ..}``
      the decode (and verify) programs SAMPLE instead of argmax —
      seeded Gumbel-max on the per-step RNG stream
      (ops/sampling_ops.py), so the plan reports ``uses_rng`` and
      ``set_random_seed`` reproduces token streams;
    - with ``speculative_k=K``, one VERIFY program per decode bucket:
      re-score a (sb, K) token block in ONE pass through the
      query-block DecodeAttention kernel (feeds tok (sb, K), pos (sb,),
      slots (sb,); fetches next_tok/logp (sb, K)) — the target side of
      speculative decoding; its cache appends carry the
      ``_verify_plan``/``_refcount_guarded`` attr pair;
    - with ``draft_steps=Kd``, one DRAFT program per decode bucket: Kd
      chained greedy decode steps unrolled into ONE executable (feeds
      tok (sb,), pos (sb,), slots (sb,); fetches props (sb, Kd)) — the
      draft side: one dispatch proposes Kd tokens.

    With ``tp_axis`` set (decode tensor parallelism) the caches default
    to the head-sharded ``"<axis>:heads"`` layout, the decode/verify/
    draft bodies thread the axis into :func:`_incremental_decode` /
    :func:`_block_decode` (context all-gather before out-projections),
    the logits head all-gathers its column-parallel output (the ONE
    per-token vocab-sized collective), and every feed placeholder is
    annotated replicated-on-mesh so host feeds commit onto the same
    device set as the sharded state.

    Returns a dict of graph handles (see :class:`TransformerGenerativeModel`
    for the session-owning wrapper the serving engine drives).
    """
    from ..serving.policy import _pow2_buckets

    if max_decode_len > cfg.max_len:
        raise ValueError(
            f"max_decode_len={max_decode_len} exceeds "
            f"cfg.max_len={cfg.max_len} (the position-encoding table); "
            "raise cfg.max_len or shorten the cache")
    heads = cfg.num_heads
    hd = cfg.d_model // heads
    total_slots = int(num_slots) + 1      # + scratch row
    scratch = int(num_slots)
    decode_buckets = sorted(set(int(x) for x in (
        decode_bucket_sizes or _pow2_buckets(int(num_slots)))))
    prefill_buckets = sorted(set(int(x) for x in prefill_bucket_sizes))
    from ..ops import kv_cache_ops as kvc

    if tp_axis and cache_sharding is None:
        cache_sharding = f"{tp_axis}{kvc.HEAD_SHARD_SUFFIX}"

    def _feed(t):
        """Annotate a placeholder replicated-on-mesh under TP: the fed
        numpy commits onto the mesh's device set (a single-device feed
        array next to 8-device sharded caches would be an XLA
        incompatible-devices error)."""
        if tp_axis:
            from simple_tensorflow_tpu import parallel

            parallel.shard_feed(t)
        return t

    self_caches = []
    cross_caches = []
    for i in range(cfg.num_layers):
        self_caches.append((
            kvc.kv_cache(f"{scope}_kv/l{i}_k", total_slots, max_decode_len,
                         (heads, hd), compute_dtype,
                         sharding=cache_sharding),
            kvc.kv_cache(f"{scope}_kv/l{i}_v", total_slots, max_decode_len,
                         (heads, hd), compute_dtype,
                         sharding=cache_sharding)))
        cross_caches.append((
            kvc.kv_cache(f"{scope}_kv/l{i}_ck", total_slots, src_len,
                         (heads, hd), compute_dtype,
                         sharding=cache_sharding),
            kvc.kv_cache(f"{scope}_kv/l{i}_cv", total_slots, src_len,
                         (heads, hd), compute_dtype,
                         sharding=cache_sharding)))
    bias_cache = kvc.kv_cache(f"{scope}_kv/src_bias", total_slots, src_len,
                              (), stf.float32, sharding=cache_sharding)

    all_caches = [c for pair in self_caches + cross_caches for c in pair]
    all_caches.append(bias_cache)
    alloc_op = stf.group(*[c.alloc() for c in all_caches],
                         name="kv_alloc")

    # -- prefill programs ----------------------------------------------------
    prefill = {}
    for pb in prefill_buckets:
        src = _feed(stf.placeholder(stf.int32, [pb, src_len],
                                    f"prefill{pb}_src"))
        slots = _feed(stf.placeholder(stf.int32, [pb],
                                      f"prefill{pb}_slots"))
        zeros = stf.fill([pb], 0)
        enc_out, enc_bias = encode(src, cfg, training=False,
                                   compute_dtype=compute_dtype,
                                   scope=scope)
        cross_kv = _decode_cross_kv(enc_out, cfg, compute_dtype, scope)
        appends = []
        for i, (ckc, cvc) in enumerate(cross_caches):
            ck, cv = cross_kv[i]
            appends.append(ckc.append(ck, slots, zeros))
            appends.append(cvc.append(cv, slots, zeros))
        appends.append(bias_cache.append(
            stf.reshape(enc_bias, [pb, src_len]), slots, zeros))
        prefill[pb] = {
            "src": src, "slots": slots,
            "op": stf.group(*appends, name=f"prefill{pb}"),
        }

    # -- decode programs -----------------------------------------------------
    if sampling is not None:
        sampling = dict(sampling)
        unknown = set(sampling) - {"temperature", "top_k", "top_p",
                                   "seed"}
        if unknown:
            raise ValueError(f"unknown sampling knobs: {sorted(unknown)}")
    state = {"int8_init": None, "wq": None, "w_scale": None}

    def _logits_head(h_flat, emb):
        """(n, d_model) -> f32 logits (n, vocab): tied softmax, or the
        int8 QuantMatMul route (weights quantized once, shared by
        decode AND verify programs). Under TP the weights are
        vocab-sharded (column-parallel logits, every column a full
        contraction) and the output all-gathers back to replicated —
        the ONE vocab-sized collective per emitted token; emission
        (argmax/sampling) then runs on bit-exact replicated logits."""
        if int8:
            if state["int8_init"] is None:
                state["wq"], state["w_scale"], state["int8_init"] = \
                    build_int8_logits_weights(emb, cfg, scope=scope)
            logits = stf.nn.quantized_matmul(h_flat, state["wq"],
                                             state["w_scale"])
        else:
            logits = stf.matmul(h_flat,
                                stf.cast(emb, h_flat.dtype.base_dtype),
                                transpose_b=True)
        return _tp_gather(stf.cast(logits, stf.float32), tp_axis)

    def _emit(logits):
        """f32 logits (n, vocab) -> (tok (n,), logp (n,)): greedy
        argmax, or the seeded sampling chain when ``sampling`` is on."""
        if sampling is not None:
            from ..ops import sampling_ops

            return sampling_ops.sample_token(logits, **sampling)
        logp_all = stf.nn.log_softmax(logits, axis=-1)
        tok = stf.cast(stf.argmax(logits, -1, output_type=stf.int32),
                       stf.int32)
        logp = stf.reduce_sum(
            logp_all * stf.one_hot(tok, cfg.vocab_size,
                                   dtype=stf.float32), axis=-1)
        return tok, logp

    def _cross_gather(slots):
        cross_bias = bias_cache.gather(slots)            # (sb, src_len)
        cross_kv = [(ckc.gather(slots), cvc.gather(slots))
                    for ckc, cvc in cross_caches]
        return cross_kv, cross_bias

    decode_progs = {}
    for sb in decode_buckets:
        tok = _feed(stf.placeholder(stf.int32, [sb], f"decode{sb}_tok"))
        pos = _feed(stf.placeholder(stf.int32, [sb], f"decode{sb}_pos"))
        slots = _feed(stf.placeholder(stf.int32, [sb],
                                      f"decode{sb}_slots"))
        cross_len = stf.fill([sb], src_len)
        cross_kv, cross_bias = _cross_gather(slots)
        cache = _SlotCaches(self_caches, slots, pos)
        h, emb = _incremental_decode(
            tok, pos, cache, cross_kv, cross_bias, cross_len, cfg,
            compute_dtype, scope, tp_axis=tp_axis)
        next_tok, logp = _emit(_logits_head(h, emb))
        decode_progs[sb] = {"tok": tok, "pos": pos, "slots": slots,
                            "next_tok": next_tok, "logp": logp}

    # -- speculative VERIFY programs (target side) ---------------------------
    verify_progs = {}
    if speculative_k:
        kv_width = int(speculative_k)
        for sb in decode_buckets:
            tok = _feed(stf.placeholder(stf.int32, [sb, kv_width],
                                        f"verify{sb}_tok"))
            pos = _feed(stf.placeholder(stf.int32, [sb],
                                        f"verify{sb}_pos"))
            slots = _feed(stf.placeholder(stf.int32, [sb],
                                          f"verify{sb}_slots"))
            cross_len = stf.fill([sb], src_len)
            cross_kv, cross_bias = _cross_gather(slots)
            cache = _SlotCaches(self_caches, slots, pos,
                                verify_plan=True)
            h, emb = _block_decode(
                tok, pos, cache, cross_kv, cross_bias, cross_len, cfg,
                compute_dtype, scope, tp_axis=tp_axis)
            flat = stf.reshape(h, [sb * kv_width, cfg.d_model])
            t_flat, lp_flat = _emit(_logits_head(flat, emb))
            verify_progs[sb] = {
                "tok": tok, "pos": pos, "slots": slots,
                "next_tok": stf.reshape(t_flat, [sb, kv_width]),
                "logp": stf.reshape(lp_flat, [sb, kv_width])}

    # -- DRAFT programs: Kd greedy steps in one executable -------------------
    draft_progs = {}
    if draft_steps:
        kd = int(draft_steps)
        for sb in decode_buckets:
            tok = _feed(stf.placeholder(stf.int32, [sb],
                                        f"draft{sb}_tok"))
            pos = _feed(stf.placeholder(stf.int32, [sb],
                                        f"draft{sb}_pos"))
            slots = _feed(stf.placeholder(stf.int32, [sb],
                                          f"draft{sb}_slots"))
            cross_len = stf.fill([sb], src_len)
            cross_kv, cross_bias = _cross_gather(slots)
            cur, props = tok, []
            for j in range(kd):
                # step j+1's appends hang off step j's gathers through
                # the argmax data path (cur), so the per-step cache
                # RAW/WAR hazards are graph-ordered without explicit
                # control edges. Proposals are ALWAYS greedy — the
                # verify side decides acceptance (greedy: token match;
                # sampling: match against the target's sample).
                cache = _SlotCaches(self_caches, slots, pos + j)
                h, emb = _incremental_decode(
                    cur, pos + j, cache, cross_kv, cross_bias,
                    cross_len, cfg, compute_dtype, scope,
                    tp_axis=tp_axis)
                logits = _logits_head(h, emb)
                cur = stf.cast(
                    stf.argmax(logits, -1, output_type=stf.int32),
                    stf.int32)
                props.append(stf.reshape(cur, [sb, 1]))
            draft_progs[sb] = {"tok": tok, "pos": pos, "slots": slots,
                               "props": stf.concat(props, axis=1)}

    return {
        "alloc_op": alloc_op,
        "int8_init": state["int8_init"],
        "prefill": prefill,
        "decode": decode_progs,
        "verify": verify_progs,
        "draft": draft_progs,
        "decode_buckets": decode_buckets,
        "prefill_buckets": prefill_buckets,
        "scratch_slot": scratch,
        "self_caches": self_caches,
        "cross_caches": cross_caches,
        "bias_cache": bias_cache,
        "cache_sharding": cache_sharding,
        "tp_axis": tp_axis,
    }


def generative_cache_bytes(cfg, src_len, num_slots, max_decode_len,
                           compute_dtype, cross=True):
    """(total_bytes, unsharded_bytes) of the generative cache set.

    ``total`` is the replicated footprint; ``unsharded`` is the part a
    head-dim TP layout can NOT divide (the rank-2 src-bias cache). Per
    device under tp=t: ``unsharded + (total - unsharded) / t`` — the
    number the HBM ledger, the tp_* metrics, and autoshard's
    per-device budget all reason about."""
    heads = cfg.num_heads
    hd = cfg.d_model // heads
    ts = int(num_slots) + 1
    per = compute_dtype.size
    total = 2 * cfg.num_layers * ts * max_decode_len * heads * hd * per
    unsharded = 0
    if cross:
        total += 2 * cfg.num_layers * ts * src_len * heads * hd * per
        unsharded = ts * src_len * 4          # src-bias cache, rank 2
    return total + unsharded, unsharded


def decode_tp_collective_bytes(cfg, tp_degree, compute_dtype,
                               cross=True):
    """Predicted per-token (per-sequence) collective bytes of the TP
    decode step, priced like the sharding rules price them: the
    vocab-sharded embedding lookup's all-reduce, one context
    all-gather per attention sublayer (2 per layer with cross
    attention, 1 without), and the single vocab-sized logits
    all-gather (f32). Zero at tp=1."""
    if not tp_degree or int(tp_degree) <= 1:
        return 0
    csize = compute_dtype.size
    d = cfg.d_model
    n_gathers = (2 if cross else 1) * cfg.num_layers
    return (d * csize                      # embedding-lookup all-reduce
            + n_gathers * d * csize        # context all-gathers
            + cfg.vocab_size * 4)          # logits all-gather


def resolve_decode_tp(mesh, tp, num_heads):
    """Normalize the (mesh, tp) model kwargs to
    ``(mesh | None, tp_axis | None, tp_degree)``.

    - both None / tp in (0, 1): single-device decode (no mesh);
    - ``tp=N`` with no mesh: builds ``Mesh({"tp": N})`` over the first
      N local devices;
    - a mesh with a ``tp`` axis: the degree is that axis' size (a
      ``tp=N`` kwarg must agree).

    The head count must divide by the degree — head-dim sharding is
    whole heads per device (attention never splits inside a head)."""
    degree = None if tp is None else int(tp)
    if mesh is None and (degree is None or degree <= 1):
        return None, None, 1
    from simple_tensorflow_tpu import parallel

    if mesh is None:
        import jax

        avail = len(jax.devices())
        if degree > avail:
            raise ValueError(
                f"tp={degree} exceeds the {avail} available devices")
        mesh = parallel.Mesh({"tp": degree})
    else:
        axis = mesh.shape.get("tp", 1)
        if axis <= 1:
            raise ValueError(
                f"mesh {mesh.shape} has no tp axis (>1); decode tensor "
                "parallelism shards over axis 'tp'")
        if degree is None:
            degree = int(axis)
        elif degree != int(axis):
            raise ValueError(
                f"tp={degree} disagrees with the mesh's tp axis size "
                f"{axis}")
    if degree <= 1:
        return None, None, 1
    if num_heads % degree:
        raise ValueError(
            f"num_heads={num_heads} not divisible by tp={degree}: "
            "head-dim sharding places whole heads per device")
    return mesh, "tp", degree


class TransformerGenerativeModel:
    """Session-owning transformer decode program for the serving engine.

    Implements the :class:`~...serving.generative.GenerativeEngine`
    model interface: ``prefill(src_rows, slots)``, ``decode(tokens,
    positions, slots) -> (next_tok, logp)``, ``close()``, plus the
    ``eos_id / pad_id / num_slots / max_decode_len / src_len`` attrs
    the engine schedules against. Owns its own Graph + Session (the
    per-model isolation contract of ModelServer servables); weights
    restore from ``checkpoint`` or initialize fresh
    (``init_fresh=True`` — tests/benches). All decode/prefill bucket
    programs are planned at construction and optionally AOT-compiled.
    """

    def __init__(self, cfg: TransformerConfig, src_len, *, num_slots=8,
                 max_decode_len=32, decode_bucket_sizes=None,
                 prefill_bucket_sizes=(1,), compute_dtype=stf.float32,
                 int8=False, checkpoint=None, init_fresh=False,
                 config=None, scope="transformer", aot_warmup=True,
                 seed=0, sampling=None, speculative_k=None,
                 draft_steps=None, mesh=None, tp=None):
        if checkpoint is None and not init_fresh:
            raise ValueError("pass checkpoint=... or init_fresh=True")
        self.cfg = cfg
        self.src_len = int(src_len)
        self.num_slots = int(num_slots)
        self.max_decode_len = int(max_decode_len)
        self.eos_id = cfg.eos_id
        self.pad_id = cfg.pad_id
        self.int8 = bool(int8)
        self.sampling = dict(sampling) if sampling else None
        self.spec_k = int(speculative_k) if speculative_k else 0
        self.draft_steps = int(draft_steps) if draft_steps else 0
        self._compute_dtype = compute_dtype
        self._cache_bytes_total, self._cache_bytes_unsharded = \
            generative_cache_bytes(cfg, self.src_len, self.num_slots,
                                   self.max_decode_len, compute_dtype)
        self.tp_choice = None
        if tp == "auto":
            # serving/decode autoshard purpose: pick the degree from
            # the roofline objective + per-device cache budget instead
            # of a hand flag
            from ..analysis import autoshard as _autoshard

            budget = int(getattr(config, "device_memory_budget_bytes",
                                 0) or 0) or None
            self.tp_choice = _autoshard.choose_decode_tp(
                num_heads=cfg.num_heads,
                cache_bytes=self._cache_bytes_total,
                unsharded_bytes=self._cache_bytes_unsharded,
                collective_bytes_fn=lambda t: decode_tp_collective_bytes(
                    cfg, t, compute_dtype),
                budget_bytes=budget, mesh=mesh)
            tp = self.tp_choice.degree
        self._mesh, self.tp_axis, self.tp_degree = resolve_decode_tp(
            mesh, tp, cfg.num_heads)
        self.graph = stf.Graph()
        with contextlib.ExitStack() as _scope_stack:
            _scope_stack.enter_context(self.graph.as_default())
            if self._mesh is not None:
                _scope_stack.enter_context(self._mesh)
            if seed is not None:
                stf.set_random_seed(seed)
            self.session = stf.Session(graph=self.graph, config=config)
            prog = build_generative_program(
                cfg, src_len, num_slots=num_slots,
                max_decode_len=max_decode_len,
                decode_bucket_sizes=decode_bucket_sizes,
                prefill_bucket_sizes=prefill_bucket_sizes,
                compute_dtype=compute_dtype, int8=int8, scope=scope,
                sampling=sampling, speculative_k=speculative_k,
                draft_steps=draft_steps, tp_axis=self.tp_axis)
            self._prog = prog
            self._scratch = prog["scratch_slot"]
            if self.tp_axis:
                # commit the TP weight layout BEFORE restore/init so
                # the Session places (checkpoint-restored or fresh)
                # state sharded at first commit
                from simple_tensorflow_tpu import parallel

                parallel.match_partition_rules(
                    decode_tp_partition_rules(self.tp_axis), apply=True)
            if checkpoint is not None:
                saver = stf.train.Saver()
                saver.restore(self.session, checkpoint)
            else:
                self.session.run(stf.global_variables_initializer())
            init_fetches = [prog["alloc_op"]]
            if prog["int8_init"] is not None:
                # quantize AFTER the weights are live
                init_fetches.append(prog["int8_init"])
            for f in init_fetches:
                self.session.run(f)
            self._decode_plans = {}
            for sb, p in prog["decode"].items():
                plan = self.session.plan(
                    {"next_tok": p["next_tok"], "logp": p["logp"]},
                    feeds=[p["tok"], p["pos"], p["slots"]])
                self._decode_plans[sb] = (plan, p)
                if aot_warmup:
                    plan.compile()
            self._verify_plans = {}
            for sb, p in prog.get("verify", {}).items():
                plan = self.session.plan(
                    {"next_tok": p["next_tok"], "logp": p["logp"]},
                    feeds=[p["tok"], p["pos"], p["slots"]])
                self._verify_plans[sb] = (plan, p)
                if aot_warmup:
                    plan.compile()
            self._draft_plans = {}
            for sb, p in prog.get("draft", {}).items():
                plan = self.session.plan(
                    {"props": p["props"]},
                    feeds=[p["tok"], p["pos"], p["slots"]])
                self._draft_plans[sb] = (plan, p)
                if aot_warmup:
                    plan.compile()
            self._prefill_plans = {}
            for pb, p in prog["prefill"].items():
                plan = self.session.plan({"done": p["op"]},
                                         feeds=[p["src"], p["slots"]])
                self._prefill_plans[pb] = (plan, p)
                if aot_warmup:
                    plan.compile()
        self._decode_buckets = sorted(self._decode_plans)
        self._prefill_buckets = sorted(self._prefill_plans)

    # the engine drives bucketing from its DecodePolicy: these expose
    # what this model actually compiled plans for (validated at
    # GenerativeEngine construction), and the scratch row bucket
    # padding may safely write into
    @property
    def decode_buckets(self):
        return list(self._decode_buckets)

    @property
    def prefill_buckets(self):
        return list(self._prefill_buckets)

    @property
    def scratch_slot(self):
        return self._scratch

    # -- engine interface -----------------------------------------------------
    def _bucket(self, buckets, n):
        for b in buckets:
            if b >= n:
                return b
        raise ValueError(f"{n} rows exceed the largest bucket "
                         f"{buckets[-1]}")

    def _run(self, plan, feed):
        """Execute under the model's mesh scope: the mesh stack is
        thread-local and the engine's scheduler thread is not inside
        the construction-time ``with mesh:``, so every execute re-enters
        it (feed staging + any retrace must see the mesh)."""
        if self._mesh is None:
            return plan.execute(feed)
        with self._mesh:
            return plan.execute(feed)

    def tp_info(self):
        """Decode-TP facts for telemetry (/stf/serving/tp_*): degree,
        per-device cache bytes under the committed layout, and the
        predicted per-token collective bytes (0 at tp=1)."""
        t = max(int(self.tp_degree or 1), 1)
        sharded = self._cache_bytes_total - self._cache_bytes_unsharded
        per_device = self._cache_bytes_unsharded + sharded // t
        return {
            "tp_degree": t,
            "tp_axis": self.tp_axis,
            "cache_bytes_replicated": int(self._cache_bytes_total),
            "cache_bytes_per_device": int(per_device),
            "per_token_collective_bytes": int(decode_tp_collective_bytes(
                self.cfg, t, self._compute_dtype)),
        }

    def prefill(self, src_rows, slots):
        """Encode ``src_rows (n, src_len)`` into cache rows ``slots``."""
        src_rows = np.asarray(src_rows, np.int32).reshape(-1, self.src_len)
        slots = np.asarray(slots, np.int32)
        n = len(slots)
        # largest-first greedy bucket cover: one plan execution per chunk
        done = 0
        while done < n:
            take = min(n - done, self._prefill_buckets[-1])
            pb = self._bucket(self._prefill_buckets, take)
            plan, p = self._prefill_plans[pb]
            src_pad = np.full((pb, self.src_len), self.pad_id, np.int32)
            slot_pad = np.full((pb,), self._scratch, np.int32)
            src_pad[:take] = src_rows[done:done + take]
            slot_pad[:take] = slots[done:done + take]
            self._run(plan, {p["src"]: src_pad,
                             p["slots"]: slot_pad})
            done += take

    def decode(self, tokens, positions, slots):
        """One decode position for n live sequences; returns
        (next_tok (n,), logp (n,), bucket)."""
        tokens = np.asarray(tokens, np.int32)
        positions = np.asarray(positions, np.int32)
        slots = np.asarray(slots, np.int32)
        n = len(slots)
        sb = self._bucket(self._decode_buckets, n)
        plan, p = self._decode_plans[sb]
        tok = np.full((sb,), self.pad_id, np.int32)
        pos = np.zeros((sb,), np.int32)
        slt = np.full((sb,), self._scratch, np.int32)
        tok[:n], pos[:n], slt[:n] = tokens, positions, slots
        out = self._run(plan, {p["tok"]: tok, p["pos"]: pos,
                               p["slots"]: slt})
        return (np.asarray(out["next_tok"])[:n],
                np.asarray(out["logp"])[:n], sb)

    def verify(self, tok_blocks, positions, slots):
        """Score K-token blocks ``tok_blocks (n, spec_k)`` starting at
        the committed ``positions``; returns the target's next-token
        choice at each of the K positions: (toks (n, K), logps (n, K),
        bucket). Cache rows for the block positions ARE written (the
        accepted prefix is then already materialized; rejected-suffix
        rows are dead until overwritten by the next append at that
        position, and length masking keeps attention from reading
        them)."""
        if not self._verify_plans:
            raise RuntimeError("model built without speculative_k")
        tok_blocks = np.asarray(tok_blocks, np.int32)
        positions = np.asarray(positions, np.int32)
        slots = np.asarray(slots, np.int32)
        n = len(slots)
        sb = self._bucket(sorted(self._verify_plans), n)
        plan, p = self._verify_plans[sb]
        tok = np.full((sb, self.spec_k), self.pad_id, np.int32)
        pos = np.zeros((sb,), np.int32)
        slt = np.full((sb,), self._scratch, np.int32)
        tok[:n], pos[:n], slt[:n] = tok_blocks, positions, slots
        out = self._run(plan, {p["tok"]: tok, p["pos"]: pos,
                               p["slots"]: slt})
        return (np.asarray(out["next_tok"])[:n],
                np.asarray(out["logp"])[:n], sb)

    def decode_k(self, tokens, positions, slots):
        """Draft side: run ``draft_steps`` greedy decode positions in
        one plan execution; returns (props (n, draft_steps), bucket)."""
        if not self._draft_plans:
            raise RuntimeError("model built without draft_steps")
        tokens = np.asarray(tokens, np.int32)
        positions = np.asarray(positions, np.int32)
        slots = np.asarray(slots, np.int32)
        n = len(slots)
        sb = self._bucket(sorted(self._draft_plans), n)
        plan, p = self._draft_plans[sb]
        tok = np.full((sb,), self.pad_id, np.int32)
        pos = np.zeros((sb,), np.int32)
        slt = np.full((sb,), self._scratch, np.int32)
        tok[:n], pos[:n], slt[:n] = tokens, positions, slots
        out = self._run(plan, {p["tok"]: tok, p["pos"]: pos,
                               p["slots"]: slt})
        return np.asarray(out["props"])[:n], sb

    def close(self):
        self.session.close()

    def statusz_info(self):
        info = {"decode_buckets": self._decode_buckets,
                "prefill_buckets": self._prefill_buckets,
                "num_slots": self.num_slots,
                "max_decode_len": self.max_decode_len,
                "src_len": self.src_len, "int8": self.int8,
                "sampling": self.sampling, "spec_k": self.spec_k,
                "draft_steps": self.draft_steps}
        if self.tp_degree > 1:
            info["tp"] = self.tp_info()
        return info


def synthetic_wmt_batch(batch_size, src_len, tgt_len, vocab_size=32768,
                        seed=0):
    rng = np.random.RandomState(seed)
    src = rng.randint(2, vocab_size, (batch_size, src_len)).astype(np.int32)
    tgt = rng.randint(2, vocab_size, (batch_size, tgt_len)).astype(np.int32)
    tgt_in = np.concatenate(
        [np.full((batch_size, 1), 1, np.int32), tgt[:, :-1]], axis=1)
    return {"src_ids": src, "tgt_in": tgt_in, "tgt_out": tgt}


def transformer_flops_per_token(cfg: TransformerConfig, src_len, tgt_len):
    d, ffn, L = cfg.d_model, cfg.d_ff, cfg.num_layers
    enc = L * 2 * (4 * d * d + 2 * d * ffn + 2 * src_len * d)
    dec = L * 2 * (8 * d * d + 2 * d * ffn + 2 * (src_len + tgt_len) * d)
    emb = 2 * d * cfg.vocab_size
    return (enc + dec) / 2 + emb  # rough per-token average
