"""Long-context decoder-only LM — the sequence-parallel flagship.

The reference scales long sequences by partitioning the graph across
workers with Send/Recv (ref core/distributed_runtime); TPU-native the same
capability is ring attention over a mesh 'sp' axis
(stf.parallel.ring_attention): each device holds a sequence shard, K/V
blocks rotate around the ring via ppermute so attention FLOPs overlap
ICI transfers, and memory per device stays O(S/devices).

Model: pre-norm GPT-style blocks with RoPE (host-computed sin/cos
constants, rotate-half applied with stf ops — static shapes, MXU-friendly),
bf16 activations, fused Pallas LayerNorm, causal flash attention when no
mesh/'sp' axis is active.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu import parallel
from simple_tensorflow_tpu.models import common


@dataclasses.dataclass
class LongContextConfig:
    vocab_size: int = 32000
    d_model: int = 1024
    num_heads: int = 8
    d_ff: int = 4096
    num_layers: int = 8
    max_len: int = 32768
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-6

    @staticmethod
    def tiny():
        return LongContextConfig(vocab_size=64, d_model=32, num_heads=2,
                                 d_ff=64, num_layers=2, max_len=128)


def _ln(x, cfg, name):
    return common.layer_norm(x, name, eps=cfg.layer_norm_eps)


def _dense(x, units, name, activation=None):
    init = stf.variance_scaling_initializer(1.0, "fan_in", "truncated_normal")
    return common.dense(x, units, init, name, activation=activation)


def rope_tables(seq_len, head_dim, theta=10000.0):
    """Host-computed RoPE cos/sin tables, shape (seq_len, head_dim)."""
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2) / head_dim))
    t = np.arange(seq_len)[:, None] * inv[None, :]  # (S, hd/2)
    cos = np.repeat(np.cos(t), 2, axis=1).astype(np.float32)
    sin = np.repeat(np.sin(t), 2, axis=1).astype(np.float32)
    return cos, sin


def _rotate_half(x, b, h, s, hd):
    """(..., 2i, 2i+1) -> (-x[2i+1], x[2i]) via reshape/stack (static)."""
    x2 = stf.reshape(x, [b, h, s, hd // 2, 2])
    x_even = stf.slice(x2, [0, 0, 0, 0, 0], [b, h, s, hd // 2, 1])
    x_odd = stf.slice(x2, [0, 0, 0, 0, 1], [b, h, s, hd // 2, 1])
    rot = stf.concat([-x_odd, x_even], axis=4)
    return stf.reshape(rot, [b, h, s, hd])


def apply_rope(x, cos, sin):
    """x (B,H,S,hd); cos/sin constants (S,hd)."""
    b, h = int(x.shape[0]), int(x.shape[1])
    s, hd = int(x.shape[2]), int(x.shape[3])
    c = stf.cast(stf.reshape(cos, [1, 1, s, hd]), x.dtype)
    sn = stf.cast(stf.reshape(sin, [1, 1, s, hd]), x.dtype)
    return x * c + _rotate_half(x, b, h, s, hd) * sn


def block(h, cfg, cos, sin, sp_axis, name):
    b, s = int(h.shape[0]), int(h.shape[1])
    d, heads = cfg.d_model, cfg.num_heads
    hd = d // heads
    with stf.variable_scope(name):
        x = _ln(h, cfg, "ln_attn")
        qkv = _dense(x, 3 * d, "qkv")
        qkv = stf.transpose(stf.reshape(qkv, [b, s, 3, heads, hd]),
                            [2, 0, 3, 1, 4])  # (3,B,H,S,hd)
        q = stf.squeeze(stf.slice(qkv, [0, 0, 0, 0, 0],
                                  [1, b, heads, s, hd]), axis=[0])
        k = stf.squeeze(stf.slice(qkv, [1, 0, 0, 0, 0],
                                  [1, b, heads, s, hd]), axis=[0])
        v = stf.squeeze(stf.slice(qkv, [2, 0, 0, 0, 0],
                                  [1, b, heads, s, hd]), axis=[0])
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        ctx = parallel.ring_attention(q, k, v, axis=sp_axis, causal=True)
        ctx = stf.reshape(stf.transpose(ctx, [0, 2, 1, 3]), [b, s, d])
        h = h + _dense(ctx, d, "attn_out")
        x = _ln(h, cfg, "ln_mlp")
        m = _dense(x, cfg.d_ff, "mlp_in", activation=stf.nn.gelu)
        h = h + _dense(m, d, "mlp_out")
    return h


def lm_forward(ids, cfg, compute_dtype=stf.bfloat16, sp_axis="sp",
               scope="long_lm", recompute=False):
    """ids (B,S) -> logits (B,S,vocab). S may be sharded over 'sp'."""
    b, s = int(ids.shape[0]), int(ids.shape[1])
    with stf.variable_scope(scope, reuse=stf.AUTO_REUSE):
        emb = stf.get_variable(
            "embedding", [cfg.vocab_size, cfg.d_model],
            initializer=stf.random_normal_initializer(
                stddev=cfg.d_model ** -0.5))
        # mixed-precision lookup: [B,S,D] activations in compute dtype,
        # gradient scatter-add accumulates into the f32 table
        h = stf.nn.embedding_lookup(emb, ids, compute_dtype=compute_dtype)
        cos, sin = rope_tables(s, cfg.d_model // cfg.num_heads,
                               cfg.rope_theta)
        cos, sin = stf.constant(cos), stf.constant(sin)
        def lm_layer(hh, i):
            return block(hh, cfg, cos, sin, sp_axis, f"layer_{i}")

        for i in range(cfg.num_layers):
            # at long context, per-layer activations ARE the memory budget
            h = common.maybe_recompute(lm_layer, h, i, recompute, "layer")
        h = _ln(h, cfg, "ln_final")
        # tied vocab projection in compute dtype — the [B*S, vocab] logits
        # are the largest tensor at long context; the fused xent kernel
        # does its softmax math in f32 blockwise
        flat = stf.reshape(h, [b * s, cfg.d_model])
        logits = stf.matmul(flat, stf.cast(emb, h.dtype.base_dtype),
                            transpose_b=True)
    return stf.reshape(logits, [b, s, cfg.vocab_size])


def lm_train_model(batch_size=1, seq_len=32768,
                   cfg: LongContextConfig | None = None,
                   learning_rate=3e-4, compute_dtype=stf.bfloat16,
                   sp_axis="sp", recompute=False):
    """Next-token LM training graph; shard seq over 'sp', batch over 'dp'.
    recompute="auto" resolves against the attached chip's HBM via the
    static cost model (framework/cost_model.py resolve_recompute)."""
    cfg = cfg or LongContextConfig()
    from ..framework import cost_model as _cm

    # per-chip estimate: batch shards over dp, SEQUENCE over sp (ring
    # attention) — both divide the per-chip activation footprint
    _shards = _cm.mesh_shard_factor(["dp", sp_axis])
    recompute = _cm.resolve_recompute(
        recompute,
        _cm.transformer_activation_bytes(
            batch_size, seq_len, cfg.d_model, cfg.num_layers,
            dtype_bytes=compute_dtype.size) / _shards,
        forward_flops=_cm.transformer_forward_flops(
            batch_size, seq_len, cfg.d_model, cfg.num_layers,
            d_ff=cfg.d_ff) / _shards)
    ids = stf.placeholder(stf.int32, [batch_size, seq_len], "input_ids")
    targets = stf.placeholder(stf.int32, [batch_size, seq_len], "targets")
    mesh = parallel.current_mesh()
    if mesh is not None:
        spec = []
        if "dp" in mesh.axis_names:
            spec.append("dp")
        else:
            spec.append(None)
        if sp_axis in mesh.axis_names:
            spec.append(sp_axis)
        if len(spec) > 1 or spec[0] is not None:
            parallel.shard_feed(ids, *spec)
            parallel.shard_feed(targets, *spec)

    logits = lm_forward(ids, cfg, compute_dtype, sp_axis,
                        recompute=recompute)
    loss = stf.reduce_mean(stf.nn.fused_softmax_cross_entropy(
        stf.reshape(logits, [batch_size * seq_len, cfg.vocab_size]),
        stf.reshape(targets, [-1])))
    gs = stf.train.get_or_create_global_step()
    opt = stf.train.AdamOptimizer(learning_rate)
    train_op = opt.minimize(loss, global_step=gs)
    return {"input_ids": ids, "targets": targets, "loss": loss,
            "train_op": train_op, "global_step": gs}


def synthetic_lm_batch(batch_size, seq_len, vocab_size=32000, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, vocab_size, (batch_size, seq_len + 1))
    return (ids[:, :-1].astype(np.int32), ids[:, 1:].astype(np.int32))
