"""BERT-base pretraining: MLM + NSP (BASELINE.json config 4).

(ref: the reference targets "BERT-base pretraining (MonitoredTrainingSession,
grpc distributed_runtime on pod)".)

TPU-first choices:
- Every attention layer runs the Pallas flash-attention kernel: the padding
  mask rides the kernel's additive key-bias input and attention dropout is
  generated in-kernel (counter-based, replayed in the vjp). Fixed sequence
  length (the BERT pretraining setup) keeps every matmul static for the MXU.
- Fused Pallas LayerNorm, bf16 activations with f32 parameters/statistics.
- MLM gathers only the masked positions before the vocab projection, so the
  (positions, vocab) matmul is 20x smaller than a full-sequence projection.
- Data-parallel out of the box: shard the batch dim over 'dp' (see
  stf.parallel); tensor-parallel layouts live in stf.parallel.tensor_parallel.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.models import common


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def tiny():
        """For tests: 2 layers, hidden 32."""
        return BertConfig(vocab_size=99, hidden_size=32, num_layers=2,
                          num_heads=2, intermediate_size=64, max_position=64,
                          hidden_dropout=0.0, attention_dropout=0.0)


def _init(cfg):
    return stf.truncated_normal_initializer(stddev=cfg.initializer_range)


def _layer_norm(x, cfg, name):
    return common.layer_norm(x, name, eps=cfg.layer_norm_eps)


def _dense(x, units, cfg, name, activation=None):
    return common.dense(x, units, _init(cfg), name, activation=activation)


def attention_layer(h, attn_bias, cfg, training, compute_dtype, name="attention"):
    """Multi-head self-attention. attn_bias: additive (B,1,1,S) or None.

    Always runs the Pallas flash-attention kernel: the padding bias passes
    through the kernel's additive key-bias input and attention dropout is
    applied inside the kernel (counter-based mask, replayed in the vjp) —
    the pretraining config (padded batches + attention_dropout 0.1) is the
    flash path, not a fallback.
    """
    b = int(h.shape[0])
    s = int(h.shape[1])
    hidden = int(h.shape[2])
    heads = cfg.num_heads
    hd = hidden // heads
    with stf.variable_scope(name):
        q = _dense(h, hidden, cfg, "query")
        k = _dense(h, hidden, cfg, "key")
        v = _dense(h, hidden, cfg, "value")
        q = common.split_heads(q, b, s, heads, hd)
        k = common.split_heads(k, b, s, heads, hd)
        v = common.split_heads(v, b, s, heads, hd)
        key_bias = (stf.reshape(attn_bias, [b, s])
                    if attn_bias is not None else None)
        ctx = stf.nn.fused_attention(
            q, k, v, bias=key_bias, causal=False,
            dropout_rate=cfg.attention_dropout if training else 0.0)
        ctx = common.merge_heads(ctx, b, s, hidden)
        out = _dense(ctx, hidden, cfg, "output")
        if training and cfg.hidden_dropout > 0:
            out = stf.nn.dropout(out, keep_prob=1.0 - cfg.hidden_dropout)
    return out


def transformer_block(h, attn_bias, cfg, training, compute_dtype, name):
    with stf.variable_scope(name):
        attn = attention_layer(h, attn_bias, cfg, training, compute_dtype)
        h = _layer_norm(h + attn, cfg, "ln_attn")
        ffn = _dense(h, cfg.intermediate_size, cfg, "ffn_in",
                     activation=stf.nn.gelu)
        ffn = _dense(ffn, cfg.hidden_size, cfg, "ffn_out")
        if training and cfg.hidden_dropout > 0:
            ffn = stf.nn.dropout(ffn, keep_prob=1.0 - cfg.hidden_dropout)
        h = _layer_norm(h + ffn, cfg, "ln_ffn")
    return h


def bert_encoder(input_ids, token_type_ids, input_mask, cfg,
                 training=True, compute_dtype=stf.bfloat16,
                 scope="bert", recompute=False):
    """Returns (sequence_output [B,S,H], pooled_output [B,H],
    word_embeddings [V,H] — for MLM weight tying).

    recompute=True rematerializes each transformer block's activations in
    the backward pass (stf.recompute_grad / jax.checkpoint): residuals
    shrink from every per-layer intermediate to one [B,S,H] tensor per
    layer, trading ~1.33x FLOPs for the HBM that buys a bigger batch.
    recompute="auto" decides from the static activation estimate vs the
    attached chip's HBM (framework/cost_model.py resolve_recompute —
    the grappler memory-optimizer role)."""
    b = int(input_ids.shape[0])
    s = int(input_ids.shape[1])
    if recompute == "auto":
        # bert_encoder cannot know whether b is a per-chip or a global
        # batch (that's the CALLER's data_parallel decision — see
        # bert_pretrain_model, which resolves "auto" with the mesh
        # divisor before calling here), so the raw estimate treats b as
        # per-chip. No remat without a backward pass.
        from ..framework import cost_model as _cm

        recompute = training and _cm.resolve_recompute(
            "auto",
            _cm.transformer_activation_bytes(
                b, s, cfg.hidden_size, cfg.num_layers,
                dtype_bytes=compute_dtype.size),
            forward_flops=_cm.transformer_forward_flops(
                b, s, cfg.hidden_size, cfg.num_layers,
                d_ff=cfg.intermediate_size))
    with stf.variable_scope(scope, reuse=stf.AUTO_REUSE):
        with stf.variable_scope("embeddings"):
            word_emb = stf.get_variable(
                "word_embeddings", [cfg.vocab_size, cfg.hidden_size],
                initializer=_init(cfg))
            pos_emb = stf.get_variable(
                "position_embeddings", [cfg.max_position, cfg.hidden_size],
                initializer=_init(cfg))
            type_emb = stf.get_variable(
                "token_type_embeddings", [cfg.type_vocab_size, cfg.hidden_size],
                initializer=_init(cfg))
            # mixed-precision lookups: the f32 tables are cast before the
            # gather (so the [B,S,H] activations, their LayerNorm, dropout,
            # and all VJPs move in compute dtype — [24,512,768] f32 was
            # 38 MB a pass at base scale) while the gradient scatter-add
            # still accumulates into the table in f32
            h = stf.nn.embedding_lookup(word_emb, input_ids,
                                        compute_dtype=compute_dtype)
            h = h + stf.nn.embedding_lookup(type_emb, token_type_ids,
                                            compute_dtype=compute_dtype)
            h = h + stf.reshape(
                stf.cast(stf.slice(pos_emb, [0, 0], [s, cfg.hidden_size]),
                         compute_dtype),
                [1, s, cfg.hidden_size])
            h = _layer_norm(h, cfg, "ln")
            if training and cfg.hidden_dropout > 0:
                h = stf.nn.dropout(h, keep_prob=1.0 - cfg.hidden_dropout)

        if input_mask is not None:
            # additive bias: 0 where attendable, -1e9 where padded
            bias = (1.0 - stf.cast(stf.reshape(input_mask, [b, 1, 1, s]),
                                   stf.float32)) * -1e9
        else:
            bias = None
        with stf.variable_scope("encoder"):
            def enc_layer(hh, i):
                return transformer_block(hh, bias, cfg, training,
                                         compute_dtype, name=f"layer_{i}")

            for i in range(cfg.num_layers):
                h = common.maybe_recompute(enc_layer, h, i, recompute,
                                           "layer")
        # sequence_output stays in compute dtype: the MLM head reshapes and
        # gathers the full [B,S,H] tensor, and an early f32 cast here moved
        # it (plus its VJP) through HBM at double width. Heads cast their
        # own SMALL slices up to f32 where the math wants it.
        sequence_output = h
        with stf.variable_scope("pooler"):
            first = stf.cast(stf.squeeze(
                stf.slice(sequence_output, [0, 0, 0], [-1, 1, cfg.hidden_size]),
                axis=[1]), stf.float32)
            pooled = _dense(first, cfg.hidden_size, cfg, "dense",
                            activation=stf.tanh)
    return sequence_output, pooled, word_emb


def _gather_positions(seq_out, positions):
    """seq_out (B,S,H), positions (B,P) -> (B*P, H)."""
    b = int(seq_out.shape[0])
    s = int(seq_out.shape[1])
    hidden = int(seq_out.shape[2])
    flat_offsets = stf.reshape(stf.range(0, b) * s, [-1, 1])
    flat_pos = stf.reshape(positions + flat_offsets, [-1])
    flat_seq = stf.reshape(seq_out, [-1, hidden])
    return stf.gather(flat_seq, flat_pos)


def mlm_logits(seq_out, positions, word_emb, cfg, scope="cls/predictions"):
    """Masked-LM logits at ``positions``, vocab matrix tied to word_emb."""
    with stf.variable_scope(scope, reuse=stf.AUTO_REUSE):
        x = _gather_positions(seq_out, positions)
        x = _dense(x, cfg.hidden_size, cfg, "transform",
                   activation=stf.nn.gelu)
        with stf.variable_scope("transform_ln"):
            gamma = stf.get_variable("gamma", [cfg.hidden_size],
                                     initializer=stf.ones_initializer())
            beta = stf.get_variable("beta", [cfg.hidden_size],
                                    initializer=stf.zeros_initializer())
            x = stf.nn.fused_layer_norm(x, gamma, beta, eps=cfg.layer_norm_eps)
        bias = stf.get_variable("output_bias", [cfg.vocab_size],
                                initializer=stf.zeros_initializer())
        # tied vocab matmul in compute dtype (the MXU accumulates in f32
        # internally): the [B*P, vocab] logits are the largest head tensor
        # (226 MB in f32 at base scale), and the fused xent kernel does its
        # max/logsumexp math in f32 blockwise regardless
        logits = stf.matmul(x, stf.cast(word_emb, x.dtype.base_dtype),
                            transpose_b=True) \
            + stf.cast(bias, x.dtype.base_dtype)
    return logits


def bert_pretrain_model(batch_size=32, seq_len=128, max_predictions=20,
                        cfg: BertConfig | None = None, learning_rate=1e-4,
                        compute_dtype=stf.bfloat16, use_input_mask=False,
                        data_parallel=False, recompute=False):
    """Full MLM+NSP pretraining graph (ref BERT pretraining recipe).
    recompute="auto" resolves here (where data_parallel is known) from
    the PER-CHIP activation estimate — global divided by the dp mesh
    size when the batch is dp-sharded."""
    cfg = cfg or BertConfig.base()
    if recompute == "auto":
        from ..framework import cost_model as _cm

        _shards = _cm.mesh_shard_factor(["dp"] if data_parallel else [])
        recompute = _cm.resolve_recompute(
            "auto",
            _cm.transformer_activation_bytes(
                batch_size, seq_len, cfg.hidden_size, cfg.num_layers,
                dtype_bytes=compute_dtype.size) / _shards,
            forward_flops=_cm.transformer_forward_flops(
                batch_size, seq_len, cfg.hidden_size, cfg.num_layers,
                d_ff=cfg.intermediate_size) / _shards)
    input_ids = stf.placeholder(stf.int32, [batch_size, seq_len], "input_ids")
    token_type = stf.placeholder(stf.int32, [batch_size, seq_len],
                                 "token_type_ids")
    mlm_positions = stf.placeholder(stf.int32, [batch_size, max_predictions],
                                    "mlm_positions")
    mlm_ids = stf.placeholder(stf.int32, [batch_size, max_predictions],
                              "mlm_ids")
    mlm_weights = stf.placeholder(stf.float32, [batch_size, max_predictions],
                                  "mlm_weights")
    nsp_labels = stf.placeholder(stf.int32, [batch_size], "nsp_labels")
    feeds = dict(input_ids=input_ids, token_type_ids=token_type,
                 mlm_positions=mlm_positions, mlm_ids=mlm_ids,
                 mlm_weights=mlm_weights, nsp_labels=nsp_labels)
    input_mask = None
    if use_input_mask:
        input_mask = stf.placeholder(stf.int32, [batch_size, seq_len],
                                     "input_mask")
        feeds["input_mask"] = input_mask
    if data_parallel:
        from simple_tensorflow_tpu import parallel
        mesh = parallel.current_mesh()
        if mesh is not None and "dp" in mesh.axis_names:
            for t in feeds.values():
                parallel.shard_feed(t, "dp")

    seq_out, pooled, word_emb = bert_encoder(
        input_ids, token_type, input_mask, cfg, training=True,
        compute_dtype=compute_dtype, recompute=recompute)

    # MLM loss over masked positions only, weight-normalized
    logits = mlm_logits(seq_out, mlm_positions, word_emb, cfg)
    per_ex = stf.nn.fused_softmax_cross_entropy(
        logits, stf.reshape(mlm_ids, [-1]))
    w = stf.reshape(mlm_weights, [-1])
    mlm_loss = stf.reduce_sum(per_ex * w) / (stf.reduce_sum(w) + 1e-5)

    # NSP
    with stf.variable_scope("cls/seq_relationship", reuse=stf.AUTO_REUSE):
        nsp_logits = stf.layers.dense(pooled, 2, kernel_initializer=_init(cfg),
                                      name="dense")
    nsp_loss = stf.reduce_mean(stf.nn.sparse_softmax_cross_entropy_with_logits(
        labels=nsp_labels, logits=nsp_logits))

    loss = mlm_loss + nsp_loss
    gs = stf.train.get_or_create_global_step()
    opt = stf.train.AdamOptimizer(learning_rate)
    train_op = opt.minimize(loss, global_step=gs)

    mlm_acc = stf.reduce_sum(stf.cast(stf.equal(
        stf.cast(stf.argmax(logits, 1, output_type=stf.int32), stf.int32),
        stf.reshape(mlm_ids, [-1])), stf.float32) * w) / (
            stf.reduce_sum(w) + 1e-5)
    return dict(feeds, loss=loss, mlm_loss=mlm_loss, nsp_loss=nsp_loss,
                train_op=train_op, mlm_accuracy=mlm_acc, global_step=gs)


def bert_flops_per_token(cfg: BertConfig, seq_len: int) -> float:
    """Analytic fwd FLOPs/token (6*params-ish + attention)."""
    h, L, ffn = cfg.hidden_size, cfg.num_layers, cfg.intermediate_size
    per_layer = 2 * (4 * h * h + 2 * h * ffn)  # qkvo + ffn matmul MACs*2
    attn = 2 * 2 * seq_len * h  # scores + context per token
    emb = 2 * h * cfg.vocab_size / seq_len  # amortized mlm head
    return L * (per_layer + attn) + emb


def synthetic_pretrain_batch(batch_size, seq_len, max_predictions,
                             vocab_size=30522, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "input_ids": rng.randint(0, vocab_size,
                                 (batch_size, seq_len)).astype(np.int32),
        "token_type_ids": rng.randint(0, 2,
                                      (batch_size, seq_len)).astype(np.int32),
        "mlm_positions": rng.randint(0, seq_len,
                                     (batch_size, max_predictions)
                                     ).astype(np.int32),
        "mlm_ids": rng.randint(0, vocab_size,
                               (batch_size, max_predictions)).astype(np.int32),
        "mlm_weights": np.ones((batch_size, max_predictions), np.float32),
        "nsp_labels": rng.randint(0, 2, batch_size).astype(np.int32),
    }
