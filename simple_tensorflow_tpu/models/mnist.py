"""MNIST models (BASELINE.json config 1: "MNIST softmax via tf.Session").

(ref: the reference's models.BUILD mnist tutorials / tensorflow examples.)
Both the classic softmax regression and a small convnet, built with the
stf graph API exactly as a reference user would write them.
"""

from __future__ import annotations

import numpy as np

import simple_tensorflow_tpu as stf


def softmax_model(batch_size=None, image_size=784, num_classes=10,
                  learning_rate=0.5):
    """y = softmax(xW + b): the canonical tf.Session tutorial model."""
    x = stf.placeholder(stf.float32, [batch_size, image_size], name="x")
    y_ = stf.placeholder(stf.float32, [batch_size, num_classes], name="y_")
    W = stf.Variable(stf.zeros([image_size, num_classes]), name="W")
    b = stf.Variable(stf.zeros([num_classes]), name="b")
    logits = stf.matmul(x, W) + b
    cross_entropy = stf.reduce_mean(
        stf.nn.softmax_cross_entropy_with_logits(labels=y_, logits=logits))
    train_op = stf.train.GradientDescentOptimizer(learning_rate).minimize(
        cross_entropy)
    correct = stf.equal(stf.argmax(logits, 1, output_type=stf.int32),
                        stf.argmax(y_, 1, output_type=stf.int32))
    accuracy = stf.reduce_mean(stf.cast(correct, stf.float32))
    return {"x": x, "y_": y_, "logits": logits, "loss": cross_entropy,
            "train_op": train_op, "accuracy": accuracy}


def convnet_model(batch_size=None, num_classes=10, learning_rate=1e-3,
                  dtype=stf.float32):
    """LeNet-style convnet (conv-pool-conv-pool-fc-dropout-fc)."""
    x = stf.placeholder(dtype, [batch_size, 28, 28, 1], name="x")
    y_ = stf.placeholder(stf.int32, [batch_size], name="y_")
    keep_prob = stf.placeholder_with_default(stf.constant(1.0), [],
                                             name="keep_prob")
    with stf.variable_scope("convnet"):
        h = stf.layers.conv2d(x, 32, 5, padding="same",
                              activation=stf.nn.relu, name="conv1")
        h = stf.layers.max_pooling2d(h, 2, 2, name="pool1")
        h = stf.layers.conv2d(h, 64, 5, padding="same",
                              activation=stf.nn.relu, name="conv2")
        h = stf.layers.max_pooling2d(h, 2, 2, name="pool2")
        h = stf.layers.flatten(h)
        h = stf.layers.dense(h, 1024, activation=stf.nn.relu, name="fc1")
        h = stf.nn.dropout(h, keep_prob=keep_prob)
        logits = stf.layers.dense(h, num_classes, name="fc2")
    loss = stf.reduce_mean(stf.nn.sparse_softmax_cross_entropy_with_logits(
        labels=y_, logits=logits))
    gs = stf.train.get_or_create_global_step()
    train_op = stf.train.AdamOptimizer(learning_rate).minimize(
        loss, global_step=gs)
    correct = stf.equal(stf.cast(stf.argmax(logits, 1, output_type=stf.int32),
                                 stf.int32), y_)
    accuracy = stf.reduce_mean(stf.cast(correct, stf.float32))
    return {"x": x, "y_": y_, "keep_prob": keep_prob, "logits": logits,
            "loss": loss, "train_op": train_op, "accuracy": accuracy,
            "global_step": gs}


def synthetic_mnist(n=512, seed=0):
    rng = np.random.RandomState(seed)
    images = rng.rand(n, 784).astype(np.float32)
    labels = rng.randint(0, 10, size=n)
    onehot = np.zeros((n, 10), np.float32)
    onehot[np.arange(n), labels] = 1.0
    return images, labels, onehot
