"""DLRM-style ranking model over vocab-sharded embedding bags.

The canonical deep-learning recommendation shape (1906.00091): a dense
MLP "bottom" over continuous features, pooled embedding-bag lookups
over the categorical features, an explicit pairwise dot-product
interaction between all latent vectors, and a "top" MLP producing a
CTR logit trained with sigmoid cross-entropy.

The categorical path runs through :func:`stf.ops.embedding_ops.
embedding_bag` — the fused vocab-sharded lookup (dedup-before-lookup +
single all-to-all id route on the ``ep`` mesh axis) — so on a mesh the
tables live sharded across devices and autoshard's memory budget drives
the ep placement without hand specs. ``mlperf_pod_train(m["loss"],
...)`` works directly: all placement is searched, none is baked in.

Initializers are explicitly seeded so the ranking graph lints clean
(no ``lint/unseeded-rng``) and zoo runs are reproducible.
"""

from __future__ import annotations

import numpy as np

import simple_tensorflow_tpu as stf
from simple_tensorflow_tpu.ops import embedding_ops


def _mlp(x, sizes, scope, *, final_relu=True, seed=0):
    """Stacked dense layers; relu on all but optionally the last."""
    with stf.variable_scope(scope, reuse=stf.AUTO_REUSE):
        for i, width in enumerate(sizes):
            in_dim = int(x.shape.dims[-1].value)
            w = stf.get_variable(
                f"w{i}", [in_dim, width],
                initializer=stf.glorot_uniform_initializer(
                    seed=seed + 31 * i))
            b = stf.get_variable(f"b{i}", [width],
                                 initializer=stf.zeros_initializer())
            x = stf.nn.bias_add(stf.matmul(x, w), b)
            if final_relu or i + 1 < len(sizes):
                x = stf.nn.relu(x)
    return x


def dlrm_model(batch_size=32, num_dense=8,
               table_sizes=(1000, 1000, 500, 200), embedding_dim=16,
               max_ids_per_feature=8, bottom_mlp=(32, 16),
               top_mlp=(32, 16, 1), learning_rate=0.1, combiner="sum",
               axis="ep", dedup=True, optimizer=None, seed=17):
    """Build the DLRM training graph; returns the standard zoo dict.

    ``bottom_mlp[-1]`` must equal ``embedding_dim`` (the interaction
    needs every latent vector in the same space); the default shapes
    satisfy it.  Categorical feature ``i`` feeds two placeholders:
    ``cat{i}_ids`` int32 ``[batch, max_ids_per_feature]`` padded with
    ``-1`` and ``cat{i}_lengths`` int32 ``[batch]`` — the
    ``RaggedFeature`` parser contract, so a parsed Example batch plugs
    straight in.
    """
    if bottom_mlp[-1] != embedding_dim:
        raise ValueError(
            f"dlrm_model: bottom_mlp[-1] ({bottom_mlp[-1]}) must equal "
            f"embedding_dim ({embedding_dim}) for the interaction")
    dense = stf.placeholder(stf.float32, [batch_size, num_dense],
                            name="dense_features")
    labels = stf.placeholder(stf.float32, [batch_size, 1], name="labels")
    id_phs, len_phs = [], []
    for i in range(len(table_sizes)):
        id_phs.append(stf.placeholder(
            stf.int32, [batch_size, max_ids_per_feature],
            name=f"cat{i}_ids"))
        len_phs.append(stf.placeholder(stf.int32, [batch_size],
                                       name=f"cat{i}_lengths"))

    bottom = _mlp(dense, bottom_mlp, "dlrm/bottom", seed=seed)

    tables, bags = [], []
    with stf.variable_scope("dlrm/embedding", reuse=stf.AUTO_REUSE):
        for i, vocab in enumerate(table_sizes):
            t = stf.get_variable(
                f"table_{i}", [vocab, embedding_dim],
                initializer=stf.random_uniform_initializer(
                    -1.0 / np.sqrt(embedding_dim),
                    1.0 / np.sqrt(embedding_dim), seed=seed + 101 * i))
            tables.append(t)
            bags.append(embedding_ops.embedding_bag(
                t, id_phs[i], len_phs[i], combiner=combiner, axis=axis,
                dedup=dedup, name=f"bag_{i}"))

    # pairwise dot-product interaction over [bottom] + bags — the
    # feature count is small and static, so explicit pair reductions
    # beat a batched matmul + tril mask on readability and avoid any
    # rank-3 contraction in the plan
    feats = [bottom] + bags
    pairs = []
    for i in range(len(feats)):
        for j in range(i + 1, len(feats)):
            pairs.append(stf.reduce_sum(
                stf.multiply(feats[i], feats[j]), 1, keepdims=True))
    top_in = stf.concat([bottom] + bags + pairs, axis=1)

    logits = _mlp(top_in, top_mlp, "dlrm/top", final_relu=False,
                  seed=seed + 7)
    loss = stf.reduce_mean(stf.nn.sigmoid_cross_entropy_with_logits(
        labels=labels, logits=logits))
    if optimizer is None:
        optimizer = stf.train.GradientDescentOptimizer(learning_rate)
    train_op = optimizer.minimize(loss)
    prediction = stf.sigmoid(logits, name="ctr")
    return {"dense": dense, "cat_ids": id_phs, "cat_lengths": len_phs,
            "labels": labels, "loss": loss, "train_op": train_op,
            "logits": logits, "prediction": prediction,
            "tables": tables}


def synthetic_dlrm_batch(batch_size, num_dense=8,
                         table_sizes=(1000, 1000, 500, 200),
                         max_ids_per_feature=8, zipf_a=1.3, seed=0):
    """Skewed synthetic batch matching :func:`dlrm_model` placeholders.

    Ids are Zipf-distributed (real click logs are head-heavy — the
    dedup-before-lookup pass is exercised, not idle) and rows are
    ragged: per-example lengths are uniform in [0, max_ids_per_feature]
    with ``-1`` padding, the RaggedFeature contract.
    """
    rng = np.random.RandomState(seed)
    dense = rng.standard_normal((batch_size, num_dense)).astype(np.float32)
    labels = (rng.uniform(size=(batch_size, 1)) < 0.3).astype(np.float32)
    cat_ids, cat_lengths = [], []
    for vocab in table_sizes:
        lens = rng.randint(0, max_ids_per_feature + 1, batch_size)
        ids = np.full((batch_size, max_ids_per_feature), -1, np.int32)
        for b, ln in enumerate(lens):
            if ln:
                draw = rng.zipf(zipf_a, ln) - 1
                ids[b, :ln] = np.minimum(draw, vocab - 1)
        cat_ids.append(ids)
        cat_lengths.append(lens.astype(np.int32))
    return {"dense": dense, "labels": labels, "cat_ids": cat_ids,
            "cat_lengths": cat_lengths}


def feed_dict_for(model, batch):
    """Zip a synthetic (or parsed) batch onto the model placeholders."""
    fd = {model["dense"]: batch["dense"], model["labels"]: batch["labels"]}
    for ph, v in zip(model["cat_ids"], batch["cat_ids"]):
        fd[ph] = v
    for ph, v in zip(model["cat_lengths"], batch["cat_lengths"]):
        fd[ph] = v
    return fd
