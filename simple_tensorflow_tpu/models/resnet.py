"""ResNet-50 v1.5 for ImageNet (BASELINE.json configs 2-3).

(ref: the reference targets "ResNet-50 ImageNet (DirectSession, single TPU
core via tf2xla)" and data-parallel over v4-32.)

TPU-first choices:
- NHWC layout + bf16 activations/weights with f32 matmul/conv accumulation
  (MXU-native); batch-norm statistics in f32.
- v1.5 variant (stride-2 in the 3x3 of the bottleneck) — the MLPerf
  reference config.
- Data-parallel: batch feed sharded over ('dp',), params replicated; XLA
  GSPMD inserts the gradient all-reduce (see stf.parallel).
"""

from __future__ import annotations

import numpy as np

import simple_tensorflow_tpu as stf

_BLOCKS = {  # per-stage bottleneck counts
    18: (2, 2, 2, 2),
    34: (3, 4, 6, 3),
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}


def _conv(x, filters, ksize, stride, name):
    init = stf.init_ops.VarianceScaling(2.0, "fan_out", "truncated_normal")
    return stf.layers.conv2d(
        x, filters, ksize, strides=stride,
        padding="same", use_bias=False, kernel_initializer=init, name=name)


def _bn(x, training, name):
    return stf.layers.batch_normalization(
        x, momentum=0.9, epsilon=1e-5, training=training, fused=True,
        name=name)


def _bottleneck(x, filters, stride, training, projection, name):
    with stf.variable_scope(name):
        shortcut = x
        if projection:
            shortcut = _conv(x, 4 * filters, 1, stride, "proj_conv")
            shortcut = _bn(shortcut, training, "proj_bn")
        y = _conv(x, filters, 1, 1, "conv1")
        y = stf.nn.relu(_bn(y, training, "bn1"))
        y = _conv(y, filters, 3, stride, "conv2")  # v1.5: stride here
        y = stf.nn.relu(_bn(y, training, "bn2"))
        y = _conv(y, 4 * filters, 1, 1, "conv3")
        y = _bn(y, training, "bn3")
        return stf.nn.relu(y + shortcut)


def resnet_forward(x, num_classes=1000, depth=50, training=True,
                   recompute=False, conv0_space_to_depth=False):
    """Build the forward graph; x is NHWC.

    recompute=True rematerializes each residual block's activations in
    the backward pass (stf.recompute_grad / jax.checkpoint): cuts the
    dominant byte sink of the training step — saved block activations —
    at ~1.3x forward FLOPs, which ResNet can afford on v5e where the
    step is HBM-bandwidth-bound (artifacts/resnet_perf_diagnosis.md).

    conv0_space_to_depth=True reformulates the stem (the MLPerf TPU
    recipe): space_to_depth(block 2) turns the 3-channel 224px input
    into 12 channels at 112px, and conv0 becomes a 4x4 stride-1 conv —
    mathematically an 8x8/s2 conv on the original image (a superset of
    the 7x7), exactly under VALID padding and modulo border handling
    under the SAME padding used here (the SAME pads land at different
    original-pixel offsets; train-from-scratch is unaffected, but do
    not expect bit-parity when resharding a pretrained 7x7 stem).
    The 3-channel conv is the MXU's worst case (channels pad to the
    128-lane width at <3% utilization); 12 channels quadruple that and
    drop the strided access pattern.
    """
    from . import common

    blocks = _BLOCKS[depth]
    with stf.variable_scope("resnet", reuse=stf.AUTO_REUSE):
        if conv0_space_to_depth:
            hh, ww = x.shape[1].value, x.shape[2].value
            if hh is None or ww is None or hh % 2 or ww % 2:
                raise ValueError(
                    f"conv0_space_to_depth needs even static spatial "
                    f"dims, got {hh}x{ww}")
            h = stf.space_to_depth(x, 2)        # [B, H/2, W/2, 12]
            h = _conv(h, 64, 4, 1, "conv0_s2d")  # ~ 8x8/s2 on the image
        else:
            h = _conv(x, 64, 7, 2, "conv0")
        h = stf.nn.relu(_bn(h, training, "bn0"))
        h = stf.layers.max_pooling2d(h, 3, 2, padding="same", name="pool0")
        block_idx = 0
        for stage, n_blocks in enumerate(blocks):
            filters = 64 * (2 ** stage)
            for i in range(n_blocks):
                stride = 2 if (stage > 0 and i == 0) else 1

                def block_fn(hh, _bi, filters=filters, stride=stride,
                             projection=(i == 0),
                             name=f"stage{stage}_block{i}"):
                    return _bottleneck(hh, filters, stride, training,
                                       projection=projection, name=name)

                h = common.maybe_recompute(block_fn, h, block_idx,
                                           recompute, "resnet_block")
                block_idx += 1
        h = stf.reduce_mean(h, axis=[1, 2], name="global_pool")  # NHWC pool
        h = stf.cast(h, stf.float32)
        logits = stf.layers.dense(
            h, num_classes,
            kernel_initializer=stf.init_ops.RandomNormal(stddev=0.01),
            name="fc")
    return logits


def resnet50_train_model(batch_size=64, image_size=224, num_classes=1000,
                         dtype=stf.bfloat16, learning_rate=0.1,
                         momentum=0.9, weight_decay=1e-4,
                         data_parallel=False, recompute=False,
                         conv0_space_to_depth=False):
    """Full training graph: images -> loss -> momentum-SGD update.

    With ``data_parallel`` and an active Mesh, the batch shards over 'dp'.
    """
    x = stf.placeholder(dtype, [batch_size, image_size, image_size, 3],
                        name="images")
    labels = stf.placeholder(stf.int32, [batch_size], name="labels")
    from ..framework import cost_model as _cm

    # recompute="auto": static per-chip activation estimate vs the
    # attached chip (framework/cost_model.py)
    _shards = _cm.mesh_shard_factor(["dp"] if data_parallel else [])
    recompute = _cm.resolve_recompute(
        recompute,
        _cm.resnet_activation_bytes(batch_size, image_size,
                                    dtype_bytes=dtype.size) / _shards,
        forward_flops=resnet_flops_per_image(50, image_size)
        * batch_size / _shards)
    if data_parallel:
        from simple_tensorflow_tpu import parallel

        mesh = parallel.current_mesh()
        if mesh is not None and "dp" in mesh.axis_names:
            parallel.shard_feed(x, "dp")
            parallel.shard_feed(labels, "dp")

    logits = resnet_forward(x, num_classes=num_classes, training=True,
                            recompute=recompute,
                            conv0_space_to_depth=conv0_space_to_depth)
    xent = stf.reduce_mean(stf.nn.sparse_softmax_cross_entropy_with_logits(
        labels=labels, logits=logits))
    # L2 on conv/fc kernels only (reference recipe: no BN params)
    l2 = [stf.nn.l2_loss(stf.cast(v._ref, stf.float32))
          for v in stf.trainable_variables()
          if "kernel" in v.var_name]
    loss = xent + weight_decay * stf.add_n(l2)
    gs = stf.train.get_or_create_global_step()
    opt = stf.train.MomentumOptimizer(learning_rate, momentum)
    train_op = opt.minimize(loss, global_step=gs)
    acc = stf.reduce_mean(stf.cast(
        stf.equal(stf.cast(stf.argmax(logits, 1, output_type=stf.int32),
                           stf.int32), labels), stf.float32))
    return {"images": x, "labels": labels, "logits": logits, "loss": loss,
            "train_op": train_op, "accuracy": acc, "global_step": gs}


def resnet_flops_per_image(depth=50, image_size=224, num_classes=1000):
    """Analytic fwd FLOPs/image (2*MACs); train step ~= 3x fwd."""
    # Reasonable standard value for ResNet-50 @224: ~4.1 GFLOP fwd.
    table = {50: 4.089e9, 18: 1.82e9, 34: 3.67e9, 101: 7.8e9, 152: 11.5e9}
    scale = (image_size / 224.0) ** 2
    return table[depth] * scale


def synthetic_imagenet(batch_size, image_size=224, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    images = rng.rand(batch_size, image_size, image_size, 3).astype(dtype)
    labels = rng.randint(0, 1000, size=batch_size).astype(np.int32)
    return images, labels
