"""Collective ops (replaces ref: third_party/nccl.BUILD NcclAllReduce,
core/kernels/sendrecv_ops.cc Send/Recv, core/distributed_runtime rendezvous).

Two execution regimes, both XLA-native:

1. **GSPMD (default)** — the Session jits one global program over sharded
   arrays; XLA inserts the collectives. Here the graph is *global*: a
   gradient of a loss over the dp-sharded global batch is already the
   all-reduced gradient. So outside shard_map, ``all_reduce`` is the
   identity (with a sharding sanity-hint), and ``all_gather`` lowers to a
   replicate-constraint that forces the gather. This is not a cop-out — it
   is the GSPMD contract (the reference needs NcclAllReduce precisely
   because its replicas are separate programs).

2. **shard_map (explicit SPMD)** — inside stf.parallel.shard_map the body
   is per-device code with named axes; collectives lower to the XLA
   primitives lax.psum / all_gather / ppermute / all_to_all over ICI.
   Ring attention and pipeline schedules use this regime.
"""

from __future__ import annotations

import builtins

from ..framework import graph as ops_mod
from ..framework import lowering as lowering_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from .mesh import current_mesh, get_shard_map, P, PartitionSpec


def _axis_tuple(axis):
    if isinstance(axis, str):
        return (axis,)
    return builtins.tuple(axis)


def _in_shard_map(ctx):
    return getattr(ctx, "in_shard_map", False)


def _lower_all_reduce(ctx, op, inputs):
    import jax

    x = inputs[0]
    axes = op.attrs["axes"]
    reduce_op = op.attrs["op"]
    if _in_shard_map(ctx):
        if reduce_op == "sum":
            return [jax.lax.psum(x, axes)]
        if reduce_op == "mean":
            return [jax.lax.pmean(x, axes)]
        if reduce_op == "max":
            return [jax.lax.pmax(x, axes)]
        if reduce_op == "min":
            return [jax.lax.pmin(x, axes)]
        raise ValueError(f"unknown reduce op {reduce_op}")
    # GSPMD regime: the value is already global (see module docstring).
    return [x]


op_registry.register("AllReduce", lower=_lower_all_reduce)


def _lower_all_gather(ctx, op, inputs):
    import jax

    x = inputs[0]
    axes = op.attrs["axes"]
    gather_dim = op.attrs["axis_index"]
    if _in_shard_map(ctx):
        out = x
        for a in axes:
            out = jax.lax.all_gather(out, a, axis=gather_dim, tiled=True)
        return [out]
    mesh = current_mesh()
    if mesh is None:
        return [x]
    ns = jax.sharding.NamedSharding(mesh.jax_mesh,
                                    jax.sharding.PartitionSpec())
    return [jax.lax.with_sharding_constraint(x, ns)]


op_registry.register("AllGather", lower=_lower_all_gather)


def _lower_reduce_scatter(ctx, op, inputs):
    import jax

    x = inputs[0]
    axes = op.attrs["axes"]
    scatter_dim = op.attrs["axis_index"]
    if _in_shard_map(ctx):
        out = x
        for a in axes:
            out = jax.lax.psum_scatter(out, a, scatter_dimension=scatter_dim,
                                       tiled=True)
        return [out]
    mesh = current_mesh()
    if mesh is None:
        return [x]
    spec = [None] * inputs[0].ndim
    spec[scatter_dim] = axes[0] if len(axes) == 1 else builtins.tuple(axes)
    ns = jax.sharding.NamedSharding(mesh.jax_mesh,
                                    jax.sharding.PartitionSpec(*spec))
    return [jax.lax.with_sharding_constraint(x, ns)]


op_registry.register("ReduceScatter", lower=_lower_reduce_scatter)


def _lower_all_to_all(ctx, op, inputs):
    import jax

    if not _in_shard_map(ctx):
        raise ValueError(
            "all_to_all is an explicit-SPMD collective: call it inside "
            "stf.parallel.shard_map (GSPMD inserts its own all-to-alls from "
            "sharding constraints).")
    return [jax.lax.all_to_all(inputs[0], op.attrs["axes"][0],
                               split_axis=op.attrs["split_axis"],
                               concat_axis=op.attrs["concat_axis"],
                               tiled=True)]


op_registry.register("AllToAll", lower=_lower_all_to_all)


def _lower_ppermute(ctx, op, inputs):
    import jax

    if not _in_shard_map(ctx):
        raise ValueError("ppermute requires stf.parallel.shard_map")
    return [jax.lax.ppermute(inputs[0], op.attrs["axes"][0],
                             perm=op.attrs["perm"])]


op_registry.register("CollectivePermute", lower=_lower_ppermute)


def _lower_axis_index(ctx, op, inputs):
    import jax

    if not _in_shard_map(ctx):
        raise ValueError("axis_index requires stf.parallel.shard_map")
    return [jax.lax.axis_index(op.attrs["axes"][0])]


op_registry.register("AxisIndex", lower=_lower_axis_index, is_stateful=True)


def _lower_psum_scatter_like(ctx, op, inputs):
    return _lower_reduce_scatter(ctx, op, inputs)


# -- public API --------------------------------------------------------------

def all_reduce(tensor, axis, op="sum", name=None):
    """NcclAllReduce parity (ref third_party/nccl.BUILD); see module
    docstring for GSPMD semantics."""
    t = ops_mod.convert_to_tensor(tensor)
    g = ops_mod.get_default_graph()
    node = g.create_op("AllReduce", [t],
                       attrs={"axes": _axis_tuple(axis), "op": op},
                       name=name or "all_reduce",
                       output_specs=[(t.shape, t.dtype)])
    return node.outputs[0]


def all_gather(tensor, axis, gather_dim=0, name=None):
    t = ops_mod.convert_to_tensor(tensor)
    g = ops_mod.get_default_graph()
    mesh = current_mesh()
    out_shape = t.shape
    if mesh is not None and t.shape.rank is not None and \
            t.shape[gather_dim].value is not None:
        mult = 1
        for a in _axis_tuple(axis):
            mult *= mesh.axis_size(a)
        dims = t.shape.as_list()
        # inside shard_map the local dim grows; under GSPMD global shape is
        # unchanged. Report unknown to stay honest in both regimes.
        out_shape = shape_mod.TensorShape([None if i == gather_dim else d
                                           for i, d in enumerate(dims)])
    node = g.create_op("AllGather", [t],
                       attrs={"axes": _axis_tuple(axis),
                              "axis_index": int(gather_dim)},
                       name=name or "all_gather",
                       output_specs=[(out_shape, t.dtype)])
    return node.outputs[0]


def reduce_scatter(tensor, axis, scatter_dim=0, name=None):
    t = ops_mod.convert_to_tensor(tensor)
    g = ops_mod.get_default_graph()
    dims = t.shape.as_list() if t.shape.rank is not None else None
    out_shape = shape_mod.TensorShape(
        [None if i == scatter_dim else d for i, d in enumerate(dims)]
        if dims is not None else None)
    node = g.create_op("ReduceScatter", [t],
                       attrs={"axes": _axis_tuple(axis),
                              "axis_index": int(scatter_dim)},
                       name=name or "reduce_scatter",
                       output_specs=[(out_shape, t.dtype)])
    return node.outputs[0]


def all_to_all(tensor, axis, split_axis, concat_axis, name=None):
    t = ops_mod.convert_to_tensor(tensor)
    g = ops_mod.get_default_graph()
    node = g.create_op("AllToAll", [t],
                       attrs={"axes": _axis_tuple(axis),
                              "split_axis": int(split_axis),
                              "concat_axis": int(concat_axis)},
                       name=name or "all_to_all",
                       output_specs=[(shape_mod.TensorShape(None), t.dtype)])
    return node.outputs[0]


def ppermute(tensor, axis, perm, name=None):
    """Neighbor exchange over ICI (ring attention / pipeline bubble fill)."""
    t = ops_mod.convert_to_tensor(tensor)
    g = ops_mod.get_default_graph()
    node = g.create_op("CollectivePermute", [t],
                       attrs={"axes": _axis_tuple(axis),
                              "perm": builtins.tuple(
                                  builtins.tuple(p) for p in perm)},
                       name=name or "ppermute",
                       output_specs=[(t.shape, t.dtype)])
    return node.outputs[0]


def axis_index(axis, name=None):
    from ..framework import dtypes as dtypes_mod

    g = ops_mod.get_default_graph()
    node = g.create_op("AxisIndex", [], attrs={"axes": _axis_tuple(axis)},
                       name=name or "axis_index",
                       output_specs=[(shape_mod.scalar(), dtypes_mod.int32)])
    return node.outputs[0]


def broadcast(tensor, axis, root=0, name=None):
    """Broadcast from root along axis (GSPMD: replicate constraint)."""
    return all_gather(tensor, axis, name=name or "broadcast")


# -- shard_map region --------------------------------------------------------

def _lower_shard_map(ctx, op, inputs):
    import jax

    fg = op.attrs["body"]
    mesh = op.attrs["mesh"] or current_mesh()
    if mesh is None:
        raise ValueError("shard_map requires an active Mesh")
    in_specs = builtins.tuple(
        s.to_jax() if isinstance(s, PartitionSpec)
        else jax.sharding.PartitionSpec(*s) for s in op.attrs["in_specs"])
    out_specs = builtins.tuple(
        s.to_jax() if isinstance(s, PartitionSpec)
        else jax.sharding.PartitionSpec(*s) for s in op.attrs["out_specs"])
    n_args = op.attrs["n_args"]
    caps = builtins.list(inputs[n_args:])

    def body(*args):
        child_env = {}
        child = ctx.child(child_env, in_control_flow=True)
        child.in_shard_map = True
        outs = lowering_mod.lower_func_graph(child, fg, builtins.list(args),
                                             caps)
        return builtins.tuple(outs)

    _shard_map = get_shard_map()
    fn = _shard_map(body, mesh=mesh.jax_mesh, in_specs=in_specs,
                    out_specs=out_specs if len(out_specs) > 1
                    else out_specs[0], check_vma=False)
    out = fn(*inputs[:n_args])
    if not isinstance(out, builtins.tuple):
        out = (out,)
    return builtins.list(out)


op_registry.register("ShardMap", lower=_lower_shard_map, n_outputs=None)


def shard_map(fn, inputs, in_specs, out_specs, mesh=None, name=None):
    """Explicit-SPMD region: ``fn`` sees per-device shards and may call
    collectives (all_reduce/ppermute/...) with real axis names. The TPU
    counterpart of writing a custom NCCL schedule in the reference."""
    from ..framework import dtypes as dtypes_mod
    from ..ops.functional_ops import _build_fn_graph

    inputs = [ops_mod.convert_to_tensor(x) for x in inputs]
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("shard_map requires an active Mesh")
    in_specs = [P(*s) if not isinstance(s, PartitionSpec) else s
                for s in in_specs]
    out_specs_l = [P(*s) if not isinstance(s, PartitionSpec) else s
                   for s in (out_specs if isinstance(out_specs, (list,
                                                                 builtins.tuple))
                             else [out_specs])]

    def local_shape(t, spec):
        dims = t.shape.as_list()
        out = []
        for i, d in enumerate(dims):
            ax = spec[i] if i < len(spec) else None
            if ax is None or d is None:
                out.append(d)
            else:
                axes = (ax,) if isinstance(ax, str) else ax
                f = 1
                for a in axes:
                    f *= mesh.axis_size(a)
                out.append(d // f)
        return out

    arg_specs = [(local_shape(t, s), t.dtype)
                 for t, s in zip(inputs, in_specs)]
    fg, _ = _build_fn_graph(lambda *a: fn(*a), arg_specs, "shard_map_body")
    caps = [outer for outer, _ in fg.captures]
    g = ops_mod.get_default_graph()

    def global_shape(o, spec):
        dims = o.shape.as_list() if o.shape.rank is not None else None
        if dims is None:
            return shape_mod.TensorShape(None)
        out = []
        for i, d in enumerate(dims):
            ax = spec[i] if i < len(spec) else None
            if ax is None or d is None:
                out.append(d)
            else:
                axes = (ax,) if isinstance(ax, str) else ax
                f = 1
                for a in axes:
                    f *= mesh.axis_size(a)
                out.append(d * f)
        return shape_mod.TensorShape(out)

    out_spec_list = [(global_shape(o, s), o.dtype)
                     for o, s in zip(fg.outputs, out_specs_l)]
    node = g.create_op("ShardMap", inputs + caps,
                       attrs={"body": fg, "mesh": mesh,
                              "in_specs": builtins.tuple(in_specs),
                              "out_specs": builtins.tuple(out_specs_l),
                              "n_args": len(inputs)},
                       name=name or "shard_map", output_specs=out_spec_list)
    outs = builtins.list(node.outputs)
    return outs[0] if len(outs) == 1 else outs


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding; ISSUE 6): explicit
# collectives report their own traffic; under GSPMD AllReduce is the
# identity on an already-global value (see module docstring), so only
# the layout-changing ops cost anything.
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402


def _allreduce_rule(op, inp, ctx):
    return [inp[0]]


_shard.register_rules(_allreduce_rule, "AllReduce")


def _allgather_rule(op, inp, ctx):
    s = inp[0]
    if s is None:
        return [None]
    out = _shard.replicated(len(s))
    axes = tuple(a for a in _shard.spec_axes(s)
                 if ctx.mesh_axes.get(a, 1) > 1)
    if axes:
        ctx.collective("all-gather", axes,
                       _shard.tensor_bytes(op.outputs[0]),
                       tensor_name=op.outputs[0].name)
    return [out]


_shard.register_rules(_allgather_rule, "AllGather")


def _reduce_scatter_rule(op, inp, ctx):
    s = inp[0]
    if s is None:
        return [None]
    dim = int(op.attrs.get("axis_index", 0))
    axes = tuple(op.attrs.get("axes", ()))
    out = list(_shard.replicated(len(s)))
    if dim < len(out):
        out[dim] = tuple(axes)
    out_spec = _shard._dedupe_axes(tuple(out))
    live = tuple(a for a in axes if ctx.mesh_axes.get(a, 1) > 1)
    if live:
        ctx.collective("all-reduce", live,
                       _shard.tensor_bytes(op.outputs[0])
                       / ctx.shard_factor(out_spec),
                       note="reduce-scatter",
                       tensor_name=op.outputs[0].name)
    return [out_spec]


_shard.register_rules(_reduce_scatter_rule, "ReduceScatter")


def _all_to_all_rule(op, inp, ctx):
    s = inp[0]
    axes = tuple(op.attrs.get("axes", ()))
    live = tuple(a for a in axes if ctx.mesh_axes.get(a, 1) > 1)
    out_rank = _shard._out_rank(op)
    if live:
        ctx.collective("all-to-all", live,
                       _shard.tensor_bytes(op.inputs[0])
                       / max(ctx.axis_size(live), 1),
                       tensor_name=op.outputs[0].name)
    return [_shard.replicated(out_rank)]


_shard.register_rules(_all_to_all_rule, "AllToAll")


def _ppermute_rule(op, inp, ctx):
    axes = tuple(op.attrs.get("axes", ()))
    live = tuple(a for a in axes if ctx.mesh_axes.get(a, 1) > 1)
    if live:
        ctx.collective("collective-permute", live,
                       _shard.tensor_bytes(op.inputs[0])
                       / ctx.shard_factor(inp[0] or ()),
                       tensor_name=op.outputs[0].name)
    return [inp[0]]


_shard.register_rules(_ppermute_rule, "CollectivePermute")
_shard.register_rules(_shard.local_rule, "AxisIndex")


def _shard_map_rule(op, inp, ctx):
    # the op's declared in/out specs ARE the layout contract: inputs
    # reshard to in_specs, outputs emerge at out_specs; the body is
    # explicit SPMD (user-written collectives) and is not re-analyzed.
    n_args = int(op.attrs.get("n_args", len(op.inputs)))
    in_specs = op.attrs.get("in_specs", ())
    for i in range(min(n_args, len(in_specs))):
        t = op.inputs[i]
        if t.shape.rank is not None:
            ctx.require(i, _shard.normalize_spec(in_specs[i],
                                                 t.shape.rank))
    outs = []
    out_specs = op.attrs.get("out_specs", ())
    for i, t in enumerate(op.outputs):
        spec = out_specs[i] if i < len(out_specs) else None
        outs.append(_shard.normalize_spec(spec, t.shape.rank)
                    if spec is not None else _shard.replicated(
                        t.shape.rank))
    return outs


_shard_map_rule.seeds_outputs = True
_shard.register_rules(_shard_map_rule, "ShardMap")
