"""All-to-all ("Ulysses"-style) sequence parallelism.

Complement to ring attention (ring_attention.py): instead of rotating K/V
around the ICI ring, a single ``lax.all_to_all`` reshards activations from
sequence-sharded to head-sharded, full attention runs locally on each
chip's head group (flash kernel), and a second all-to-all reshards back.
Two collectives per attention instead of n ppermute hops — wins when
heads % axis_size == 0 and sequence is long but fits per-head.

The reference's only analogue is the grpc all-to-all implied by its graph
partitioning (ref: core/distributed_runtime); there is no sequence-parallel
attention in TF-1.0 — this is capability the TPU rebuild adds to hit the
long-context requirement.
"""

from __future__ import annotations

import functools

import jax

from ..framework import graph as ops_mod
from ..framework import op_registry
from ..ops.pallas.flash_attention import flash_attention, mha_reference
from .mesh import current_mesh, get_shard_map


def ulysses_attention_p(q, k, v, axis_name, *, causal=False, sm_scale=None,
                        use_flash=True):
    """Per-shard all-to-all attention, for use inside ``shard_map``.

    q, k, v: (B, H, S_local, D) with the sequence dim sharded over
    ``axis_name`` and H divisible by the axis size. Returns the local
    (B, H, S_local, D) output shard.
    """
    h = q.shape[1]
    n = jax.lax.psum(1, axis_name)
    if h % n != 0:
        raise ValueError(f"heads ({h}) must divide by axis size ({n})")

    def to_heads(x):   # (B, H, S/n, D) -> (B, H/n, S, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def to_seq(x):     # (B, H/n, S, D) -> (B, H, S/n, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    attn = flash_attention if use_flash else mha_reference
    oh = attn(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return to_seq(oh)


def _lower_ulysses(ctx, op, inputs):
    mesh = current_mesh()
    axis = op.attrs["axis"]
    causal = op.attrs["causal"]
    sm_scale = op.attrs["sm_scale"]
    q, k, v = inputs
    if ctx.in_shard_map:
        return [ulysses_attention_p(q, k, v, axis, causal=causal,
                                    sm_scale=sm_scale)]
    if mesh is None or axis not in mesh.shape or mesh.axis_size(axis) == 1:
        return [flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)]

    from jax.sharding import PartitionSpec as JP

    _shard_map = get_shard_map()
    spec = JP(None, None, axis, None)
    fn = _shard_map(
        functools.partial(ulysses_attention_p, axis_name=axis, causal=causal,
                          sm_scale=sm_scale),
        mesh=mesh.jax_mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return [fn(q, k, v)]


op_registry.register("UlyssesAttention", lower=_lower_ulysses)


def sequence_parallel_attention(q, k, v, *, axis="sp", causal=False,
                                sm_scale=None, name=None):
    """Graph op: all-to-all sequence-parallel attention over ``axis``."""
    q = ops_mod.convert_to_tensor(q)
    k = ops_mod.convert_to_tensor(k)
    v = ops_mod.convert_to_tensor(v)
    g = ops_mod.get_default_graph()
    node = g.create_op(
        "UlyssesAttention", [q, k, v],
        attrs={"axis": axis, "causal": bool(causal),
               "sm_scale": None if sm_scale is None else float(sm_scale)},
        name=name or "ulysses_attention", output_specs=[(q.shape, q.dtype)])
    return node.outputs[0]
