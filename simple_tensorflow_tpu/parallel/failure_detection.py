"""Failure detection: heartbeats + step-barrier timeout.

The reference detects worker failure through grpc channel state and session
management (ref: core/distributed_runtime/{session_mgr,worker_session}.cc,
master keeps per-worker leases); a dead worker surfaces as
``UnavailableError`` on the next Send/Recv. A TPU SPMD program has no
per-op RPCs to time out — a lost host simply hangs the next collective. So
failure detection is a *host-side* concern: a heartbeat thread stamps
progress, a watchdog raises ``UnavailableError`` / ``DeadlineExceededError``
when a step (one jitted program, collectives included) exceeds its
deadline, and a cross-host barrier with timeout verifies all processes are
alive at checkpoints/startup.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..framework.errors import DeadlineExceededError, UnavailableError


class Heartbeat:
    """Background thread stamping liveness; ``check(peer_ts, max_age)``
    classifies a peer's last-seen stamp (multi-host: exchange stamps through
    the coordination service / shared filesystem). Stamps use ``time.time()``
    — monotonic clocks have per-boot epochs and cannot be compared across
    hosts."""

    def __init__(self, interval_secs: float = 10.0):
        self.interval_secs = interval_secs
        self._last = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        def run():
            while not self._stop.wait(self.interval_secs):
                self._last = time.time()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="stf_heartbeat")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=1.0)

    @property
    def last_beat(self) -> float:
        return self._last

    def beat(self):
        self._last = time.time()

    def check(self, peer_last_beat: float, max_age_secs: float):
        age = time.time() - peer_last_beat
        if age > max_age_secs:
            raise UnavailableError(
                None, None,
                f"peer heartbeat is {age:.1f}s old (limit {max_age_secs}s) "
                "— worker presumed dead")


class StepWatchdog:
    """Raises in the main thread's stead if a training step wall-clock
    exceeds ``deadline_secs`` (hung collective = lost peer). Usage::

        wd = StepWatchdog(deadline_secs=300).start()
        for _ in range(steps):
            sess.run(train_op); wd.step_done()
        wd.stop()
    """

    def __init__(self, deadline_secs: float = 300.0,
                 on_timeout: Optional[Callable[[float], None]] = None,
                 poll_secs: float = 1.0):
        self.deadline_secs = deadline_secs
        self.poll_secs = poll_secs
        self.on_timeout = on_timeout
        self._last_step = time.monotonic()
        self._stop = threading.Event()
        self._timed_out = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        def run():
            while not self._stop.wait(self.poll_secs):
                stalled = time.monotonic() - self._last_step
                if stalled > self.deadline_secs:
                    self._timed_out.set()
                    if self.on_timeout is not None:
                        self.on_timeout(stalled)
                    return

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="stf_step_watchdog")
        self._thread.start()
        return self

    def step_done(self):
        """Call after every completed step; raises if the watchdog fired."""
        self._last_step = time.monotonic()
        if self._timed_out.is_set():
            raise DeadlineExceededError(
                None, None,
                f"training step exceeded {self.deadline_secs}s deadline — "
                "a peer host is presumed unavailable (hung collective)")

    @property
    def timed_out(self) -> bool:
        return self._timed_out.is_set()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=1.0)


def barrier(name: str = "barrier", timeout_secs: float = 600.0):
    """Cross-host barrier: all processes must arrive within the timeout.
    Single-process: no-op. Multi-host: a psum of 1 over all devices (the
    cheapest all-participant collective), bounded by a watchdog."""
    import jax

    if jax.process_count() == 1:
        return
    def run():
        import jax.numpy as jnp

        # All-participant psum: returns only once every host has joined.
        jax.device_get(
            jax.pmap(lambda x: jax.lax.psum(x, "i"), axis_name="i")(
                jnp.ones((jax.local_device_count(),))))

    t = threading.Thread(target=run, daemon=True, name=f"stf_{name}")
    t.start()
    t.join(timeout=timeout_secs)
    if t.is_alive():
        raise DeadlineExceededError(
            None, None,
            f"barrier {name!r} timed out after {timeout_secs}s — "
            "not all hosts arrived (worker presumed unavailable)")
