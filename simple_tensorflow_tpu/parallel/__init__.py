"""stf.parallel: mesh + sharding + collectives (TPU-native replacement for
the reference's distributed runtime; see SURVEY.md L5)."""

from .mesh import Mesh, PartitionSpec, P, current_mesh, make_mesh, CANONICAL_AXES
from .api import (
    shard_variables_along, shard_variable, shard_feed,
    with_sharding_constraint, match_partition_rules, num_devices,
    process_index, process_count, is_chief,
    auto_shard, emit_commit_constraint, mlperf_pod_train,
    PodTrainProgram,
)
from .collectives import (
    all_reduce, all_gather, reduce_scatter, all_to_all, ppermute,
    axis_index, broadcast, shard_map,
)
from .data_parallel import DataParallel
from .tensor_parallel import (
    TensorParallel, column_parallel_dense, row_parallel_dense,
)
from .fsdp import FSDP
from .pipeline import (pipeline, pipeline_1f1b_p, pipeline_p,
                       pipeline_train)
from .ring_attention import ring_attention, ring_attention_p
from .sequence_parallel import (
    sequence_parallel_attention, ulysses_attention_p,
)
from .failure_detection import Heartbeat, StepWatchdog, barrier
