"""Data parallelism helper (replaces ref sync_replicas + NcclAllReduce flow).

GSPMD recipe: shard the batch feeds over ('dp',) and leave params
replicated; the jitted step computes the global loss/grads and XLA inserts
the gradient reduction (a reduce-scatter + all-gather pair or all-reduce)
over ICI. ``DataParallel`` wires this onto an existing graph.
"""

from __future__ import annotations

from typing import Sequence

from ..framework import graph as ops_mod
from . import api as api_mod
from .mesh import Mesh, P, current_mesh


class DataParallel:
    """Usage:
        mesh = stf.parallel.Mesh({"dp": 8})
        with mesh:
            x = stf.placeholder(...); y = stf.placeholder(...)
            stf.parallel.DataParallel(mesh).shard_batch([x, y])
            ... build model / optimizer as usual ...
    """

    def __init__(self, mesh: Mesh = None, batch_axes: Sequence[str] = ("dp",)):
        self.mesh = mesh or current_mesh()
        if self.mesh is None:
            raise ValueError("DataParallel needs a Mesh")
        self.batch_axes = tuple(batch_axes)

    def shard_batch(self, placeholders, batch_dim=0):
        ax = self.batch_axes[0] if len(self.batch_axes) == 1 \
            else self.batch_axes
        for ph in (placeholders if isinstance(placeholders, (list, tuple))
                   else [placeholders]):
            rank = ph.shape.rank or (batch_dim + 1)
            spec = [None] * rank
            spec[batch_dim] = ax
            api_mod.shard_feed(ph, *spec)
        return placeholders

    def replicate_variables(self):
        # Replicated is the default placement; explicit call for clarity.
        return self
