"""Fully-sharded data parallelism (ZeRO-3 layout).

No reference counterpart — TF-1.0's closest is between-graph replication
with parameter servers (ref: python/training/device_setter.py shards
*whole variables* round-robin across PS tasks). FSDP instead shards every
large parameter's largest dimension across the 'fsdp' mesh axis; GSPMD
all-gathers a parameter just before use and reduce-scatters its gradient,
so peak HBM holds 1/n of params + optimizer state. Optimizer slot
variables inherit the parameter's sharding (slot_creator copies it), which
is what makes the *state* sharded too — the actual ZeRO win.
"""

from __future__ import annotations

from typing import Optional, Sequence

from . import api as api_mod
from .mesh import Mesh, current_mesh


class FSDP:
    """Usage::

        mesh = stf.parallel.Mesh({"fsdp": 8})
        with mesh, stf.parallel.FSDP(mesh).scope():
            ... build model; every large Variable is sharded ...
    """

    def __init__(self, mesh: Optional[Mesh] = None, axis: str = "fsdp",
                 min_size: int = 2 ** 14):
        self.mesh = mesh or current_mesh()
        if self.mesh is None:
            raise ValueError("FSDP needs a Mesh")
        self.axis = axis
        self.min_size = min_size

    def scope(self):
        """Context manager: Variables created inside are sharded on their
        largest divisible dim over the fsdp axis (small ones replicated)."""
        return api_mod.shard_variables_along(self.axis,
                                             min_size=self.min_size)

    def shard_batch(self, placeholders, batch_dim=0):
        """The batch is split over the same axis (fsdp is still data
        parallelism: each shard-group sees distinct examples)."""
        for ph in (placeholders if isinstance(placeholders, (list, tuple))
                   else [placeholders]):
            rank = ph.shape.rank or (batch_dim + 1)
            spec = [None] * rank
            spec[batch_dim] = self.axis
            api_mod.shard_feed(ph, *spec)
        return placeholders

    def shard_existing(self, variables: Sequence):
        """Retrofit the fsdp layout onto already-created variables."""
        for v in variables:
            api_mod.auto_shard_variable(v, self.axis,
                                        min_size=self.min_size,
                                        mesh=self.mesh)
        return self
