"""Megatron-style tensor parallelism as sharding annotations.

The reference's model parallelism is manual graph partitioning with
``tf.device`` per layer plus Send/Recv at the cut edges
(ref: core/distributed_runtime graph partitioning,
core/common_runtime/simple_placer.cc). On TPU the same layout is a pair of
sharding annotations and XLA GSPMD inserts the (reduce-scatter/all-gather)
collectives over ICI:

  column-parallel dense: W sharded (in, tp) — output hidden dim sharded;
  row-parallel dense:    W sharded (tp, out) — contracting dim sharded,
                         XLA emits the psum that Megatron calls g/f.

``column_parallel_dense`` / ``row_parallel_dense`` build the classic pair;
``TensorParallel.shard_dense_pair`` retrofits existing Variables.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..framework import graph as ops_mod
from . import api as api_mod
from .mesh import Mesh, P, current_mesh


def column_parallel_dense(x, units, *, axis="tp", activation=None,
                          use_bias=True, kernel_initializer=None, name=None):
    """y = act(x @ W + b) with W sharded (None, axis): hidden-sharded out."""
    from ..ops import init_ops, math_ops, variables as vars_mod

    in_dim = int(x.shape[-1])
    init = kernel_initializer or init_ops.glorot_uniform_initializer()
    with ops_mod.name_scope(name or "column_parallel_dense"):
        w = vars_mod.Variable(init([in_dim, units], dtype=x.dtype),
                              name="kernel")
        api_mod.shard_variable(w, None, axis)
        y = math_ops.matmul(x, w)
        if use_bias:
            b = vars_mod.Variable(init_ops.zeros_initializer()(
                [units], dtype=x.dtype), name="bias")
            api_mod.shard_variable(b, axis)
            y = y + b
        rank = y.shape.rank or 2
        y = api_mod.with_sharding_constraint(
            y, *([None] * (rank - 1) + [axis]))
        if activation is not None:
            y = activation(y)
    return y


def row_parallel_dense(x, units, *, axis="tp", activation=None,
                       use_bias=True, kernel_initializer=None, name=None):
    """y = act(x @ W + b) with W sharded (axis, None): contracting dim
    sharded — GSPMD inserts the all-reduce of partial sums."""
    from ..ops import init_ops, math_ops, variables as vars_mod

    in_dim = int(x.shape[-1])
    init = kernel_initializer or init_ops.glorot_uniform_initializer()
    with ops_mod.name_scope(name or "row_parallel_dense"):
        w = vars_mod.Variable(init([in_dim, units], dtype=x.dtype),
                              name="kernel")
        api_mod.shard_variable(w, axis, None)
        y = math_ops.matmul(x, w)
        rank = y.shape.rank or 2
        y = api_mod.with_sharding_constraint(y, *([None] * rank))
        if use_bias:
            b = vars_mod.Variable(init_ops.zeros_initializer()(
                [units], dtype=x.dtype), name="bias")
            y = y + b
        if activation is not None:
            y = activation(y)
    return y


class TensorParallel:
    """Annotation helper over an existing graph's variables.

    ``shard_dense_pair(w1, w2)`` applies the Megatron column+row layout so
    the intervening activation never needs a collective; ``shard_heads``
    shards an attention projection on the head dimension.
    """

    def __init__(self, mesh: Optional[Mesh] = None, axis: str = "tp"):
        self.mesh = mesh or current_mesh()
        if self.mesh is None:
            raise ValueError("TensorParallel needs a Mesh")
        self.axis = axis

    def shard_dense_pair(self, up_kernel, down_kernel, up_bias=None):
        api_mod.shard_variable(up_kernel, None, self.axis)
        api_mod.shard_variable(down_kernel, self.axis, None)
        if up_bias is not None:
            api_mod.shard_variable(up_bias, self.axis)
        return self

    def shard_heads(self, qkv_kernel, out_kernel):
        """(d_model, n_heads*d_head) proj sharded on heads; output proj on
        its contracting dim."""
        api_mod.shard_variable(qkv_kernel, None, self.axis)
        api_mod.shard_variable(out_kernel, self.axis, None)
        return self
