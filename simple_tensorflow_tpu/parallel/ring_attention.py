"""Ring attention: sequence/context parallelism over a mesh axis.

The reference scales long sequences only by adding workers and partitioning
the graph (ref: core/distributed_runtime graph partitioning + Send/Recv,
core/kernels/sendrecv_ops.cc); attention itself never exceeds one device's
memory. TPU-native long context shards the *sequence* dimension across a
mesh axis ('sp'): each chip keeps its Q shard resident and the K/V shards
rotate around the ICI ring via ``lax.ppermute``, one hop per step, while an
online-softmax accumulator (m, l, acc) merges each visiting block — the
FlashAttention recurrence lifted to the mesh level (Liu et al., Ring
Attention; see PAPERS.md). Memory per chip is O(S/n), compute overlaps the
ppermute because XLA schedules the collective-permute concurrently with the
local block matmuls.

Causal masking is done per (q-chunk, kv-chunk) pair from the global chunk
offsets; chunks entirely in the future contribute nothing (their rows are
masked, adding exp(-inf)=0 terms).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..framework import graph as ops_mod
from ..framework import op_registry
from ..ops.pallas.common import NEG_INF
from .mesh import current_mesh, get_shard_map


def _block_attn(q, k, v, sm_scale, mask):
    """Unnormalised attention of one KV block: returns (m, l, acc) in f32.
    q,k,v: (B, H, Sq, D)/(B, H, Sk, D); mask: (Sq, Sk) True=keep."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m, l, acc


def _merge(m1, l1, a1, m2, l2, a2):
    """Merge two online-softmax partial states."""
    m = jnp.maximum(m1, m2)
    c1 = jnp.exp(m1 - m)
    c2 = jnp.exp(m2 - m)
    l = l1 * c1 + l2 * c2
    a = a1 * c1[..., None] + a2 * c2[..., None]
    return m, l, a


def _select_block_attention(q, k, v, *, causal):
    """Registry-routed attention for one ring block (stf.kernels):
    Pallas flash kernel or the composed-XLA lowering, decided per
    (shard shape, dtype, backend) under the active mode."""
    from ..kernels import registry as _kreg

    return _kreg.select(
        "FlashAttention",
        _kreg.aval_key(q, k, v, None, causal=bool(causal), dropout=False,
                       ring_block=True))


def ring_attention_p(q, k, v, axis_name, *, causal=False, sm_scale=None,
                     use_flash=True):
    """Per-shard ring attention, for use inside ``shard_map`` where the
    sequence dim (2) of q/k/v is sharded over ``axis_name``.

    q, k, v: (B, H, S_local, D) local shards. Returns the local O shard.
    Differentiable (ppermute transposes to the reverse permute; jax.vjp of
    the scan replays the ring backwards).

    use_flash (default): each visiting KV block runs the Pallas flash
    kernel (O(block) VMEM) and partials merge through the returned
    log-sum-exp — the naive per-block path materializes an f32
    (S/n, S/n) score matrix per (b, h), which defeats ring attention's
    memory point at real context lengths. Three block cases under
    lax.switch: wholly-future (causal) blocks contribute an empty
    partial, the diagonal block runs the causal kernel, past blocks the
    full kernel.
    """
    b, h, s_local, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / (d ** 0.5)
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    if use_flash:
        # the per-block attention routes through the kernel registry
        # exactly like the single-device FlashAttention op: the Pallas
        # streamed kernel when gated in (TPU / force), the composed-XLA
        # lowering otherwise — both merge through the returned lse
        _attn_causal = _select_block_attention(q, k, v, causal=True)
        _attn_full = _select_block_attention(q, k, v, causal=False)

        def step(carry, t):
            k_t, v_t, lse_acc, o_acc = carry
            src = (idx - t) % n

            def _empty(args):
                qq, _, _ = args
                return (jnp.zeros_like(o_acc),
                        jnp.full((b, h, s_local), NEG_INF, jnp.float32))

            def _diag(args):
                qq, kk, vv = args
                o2, lse2 = _attn_causal(qq, kk, vv, causal=True,
                                        sm_scale=sm_scale,
                                        return_lse=True)
                return o2.astype(jnp.float32), lse2

            def _full(args):
                qq, kk, vv = args
                o2, lse2 = _attn_full(qq, kk, vv, causal=False,
                                      sm_scale=sm_scale,
                                      return_lse=True)
                return o2.astype(jnp.float32), lse2

            if causal:
                case = jnp.where(src > idx, 0, jnp.where(src == idx, 1, 2))
            else:
                case = jnp.full((), 2, jnp.int32)
            o2, lse2 = jax.lax.switch(case, [_empty, _diag, _full],
                                      (q, k_t, v_t))
            # merge two normalized partials through their lse
            lse_new = jnp.logaddexp(lse_acc, lse2)
            c1 = jnp.exp(lse_acc - lse_new)[..., None]
            c2 = jnp.exp(lse2 - lse_new)[..., None]
            o_acc = o_acc * c1 + o2 * c2
            k_t = jax.lax.ppermute(k_t, axis_name, perm)
            v_t = jax.lax.ppermute(v_t, axis_name, perm)
            return (k_t, v_t, lse_new, o_acc), None

        lse0 = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
        o0 = jnp.zeros((b, h, s_local, d), jnp.float32)
        (k, v, lse, o), _ = jax.lax.scan(step, (k, v, lse0, o0),
                                         jnp.arange(n))
        return o.astype(q.dtype)

    q_pos = idx * s_local + jnp.arange(s_local)

    def step(carry, t):
        k_t, v_t, m, l, acc = carry
        # After t forward rotations, this device holds the chunk that
        # originated on device (idx - t) mod n.
        src = (idx - t) % n
        k_pos = src * s_local + jnp.arange(s_local)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((s_local, s_local), bool)
        m2, l2, a2 = _block_attn(q, k_t, v_t, sm_scale, mask)
        m, l, acc = _merge(m, l, acc, m2, l2, a2)
        k_t = jax.lax.ppermute(k_t, axis_name, perm)
        v_t = jax.lax.ppermute(v_t, axis_name, perm)
        return (k_t, v_t, m, l, acc), None

    m0 = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    a0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    (k, v, m, l, acc), _ = jax.lax.scan(
        step, (k, v, m0, l0, a0), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / l[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Graph op: shard_maps the per-shard kernel over the mesh axis.
# ---------------------------------------------------------------------------

def _lower_ring_attention(ctx, op, inputs):
    mesh = current_mesh()
    axis = op.attrs["axis"]
    causal = op.attrs["causal"]
    sm_scale = op.attrs["sm_scale"]
    q, k, v = inputs
    if ctx.in_shard_map:
        return [ring_attention_p(q, k, v, axis, causal=causal,
                                 sm_scale=sm_scale)]
    if mesh is None or axis not in mesh.shape or mesh.axis_size(axis) == 1:
        # No sequence axis to ring over: plain single-device attention,
        # routed Pallas/XLA through the kernel registry like the
        # FlashAttention op itself.
        from ..kernels import registry as _kreg

        fn = _kreg.select(
            "FlashAttention",
            _kreg.aval_key(q, k, v, None, causal=bool(causal),
                           dropout=False))
        return [fn(q, k, v, causal=causal, sm_scale=sm_scale)]

    from jax.sharding import PartitionSpec as JP

    _shard_map = get_shard_map()
    spec = JP(None, None, axis, None)
    fn = _shard_map(
        functools.partial(ring_attention_p, axis_name=axis, causal=causal,
                          sm_scale=sm_scale),
        mesh=mesh.jax_mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return [fn(q, k, v)]


op_registry.register("RingAttention", lower=_lower_ring_attention)


def _register_ring_kernel():
    """Kernel-registry entry for RingAttention: the ring op's inner
    per-block attention is what routes (see _select_block_attention),
    but the offline routing report (graph_lint --kernels; the zoo force
    gate) wants a per-op verdict for the graph node itself — priced and
    gated exactly like FlashAttention on the (possibly sharded) block
    shapes."""
    from ..kernels import registry as _kreg
    from ..ops import pallas as _p
    from ..ops.pallas.flash_attention import attention_xla, flash_attention

    def _graph_key(op):
        avals = [_p._tensor_aval(t) for t in op.inputs[:3]]
        if len(avals) < 3 or any(a is None for a in avals):
            return None
        return _kreg.aval_key(
            *[_p._Aval(*a) for a in avals], None,
            causal=bool(op.attrs.get("causal", False)), dropout=False)

    _kreg.register_kernel(
        "RingAttention",
        impls={"pallas": flash_attention, "xla": attention_xla},
        legacy="pallas",
        eligible=_p._flash_eligible,
        cost_gate=_p._flash_gate,
        make_case=_p._flash_case,
        graph_key=_graph_key,
        doc="sequence-parallel ring attention; the per-block kernel "
            "routes like FlashAttention")


_register_ring_kernel()


def ring_attention(q, k, v, *, axis="sp", causal=False, sm_scale=None,
                   name=None):
    """Graph op: sequence-parallel attention over mesh axis ``axis``.
    q, k, v: (B, H, S, D) global tensors (S sharded over the axis at
    runtime). Falls back to single-device flash attention when the mesh has
    no such axis."""
    q = ops_mod.convert_to_tensor(q)
    k = ops_mod.convert_to_tensor(k)
    v = ops_mod.convert_to_tensor(v)
    g = ops_mod.get_default_graph()
    node = g.create_op(
        "RingAttention", [q, k, v],
        attrs={"axis": axis, "causal": bool(causal),
               "sm_scale": None if sm_scale is None else float(sm_scale)},
        name=name or "ring_attention", output_specs=[(q.shape, q.dtype)])
    return node.outputs[0]


# ---------------------------------------------------------------------------
# sharding propagation rule (stf.analysis.sharding; ISSUE 6): the op IS
# the sequence-parallel path — q/k/v stay S-sharded over ``axis`` and
# the kernel rings k/v shards with collective-permutes (one per ring
# step; the HLO while body materializes the instruction once, so the
# comparable payload is one shard of k plus one of v).
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402


def _ring_attention_rule(op, in_specs, ctx):
    axis = op.attrs.get("axis", "sp")
    n = ctx.axis_size(axis)
    sq = in_specs[0]
    if n > 1:
        kb = _shard.tensor_bytes(op.inputs[1]) if len(op.inputs) > 1 else 0
        vb = _shard.tensor_bytes(op.inputs[2]) if len(op.inputs) > 2 else 0
        ctx.collective("collective-permute", (axis,), (kb + vb) / n,
                       note="ring k/v shard rotation",
                       tensor_name=op.outputs[0].name)
        # q/k/v ride S-sharded over the ring axis (B, H, S, D)
        if sq is not None and len(sq) == 4:
            want = tuple(((axis,) if d == 2 else e)
                         for d, e in enumerate(sq))
            for i in range(min(3, len(in_specs))):
                if in_specs[i] is not None and in_specs[i] != want:
                    ctx.require(i, want)
            return [want]
    return [sq]


_shard.register_rules(_ring_attention_rule, "RingAttention")
