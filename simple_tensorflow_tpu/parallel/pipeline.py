"""Pipeline parallelism: GPipe and 1F1B microbatch schedules over a mesh axis.

The reference pipelines by placing layer subsets on different workers with
``tf.device`` and letting grpc Send/Recv stream activations
(ref: core/distributed_runtime partition + core/kernels/sendrecv_ops.cc);
there is no microbatch schedule, so utilisation collapses with depth. The
TPU version runs the schedule *inside one SPMD program*: every chip along
the 'pp' axis executes the same scan; at step t chip s processes microbatch
t-s (a skew of the GPipe schedule), and ``lax.ppermute`` hands activations
to the next stage over ICI. Bubble fraction is (n_stages-1)/(n_micro +
n_stages-1); XLA overlaps the permute with the next microbatch's compute.

Two schedules:
- ``pipeline_p``: GPipe forward; jax.vjp differentiates through the scan
  (activation memory O(n_micro) — fine for inference/short pipelines).
- ``pipeline_1f1b_p``: combined forward+backward 1F1B training step in ONE
  scan. The loss is computed in-pipeline at the last stage, cotangents
  ppermute backwards while later microbatches still flow forward, and the
  backward recomputes each stage from a ring buffer of saved stage INPUTS
  — activation memory O(n_stages), independent of n_micro (the reason
  1F1B exists). Returns (mean loss, per-stage param grads) directly.

Heterogeneous stages: both schedules accept a LIST of per-stage functions,
lowered to ``lax.switch`` on the stage index — each chip executes only its
own branch, so per-stage computation (and per-stage params, padded to a
common stacked shape) may differ as long as the carried activation shape
is uniform across stage boundaries.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from ..framework import lowering as lowering_mod
from .mesh import current_mesh, get_shard_map


def _as_stage_fn(fn, stage):
    """Normalize fn-or-list-of-fns to one fn dispatching on stage index.
    A list lowers to lax.switch: each chip runs only its own branch."""
    if not isinstance(fn, (list, tuple)):
        return fn
    fns = list(fn)
    return lambda p, x: jax.lax.switch(
        stage, [lambda pp, xx, f=f: f(pp, xx) for f in fns], p, x)


def pipeline_p(fn, stage_params, microbatches, axis_name):
    """Per-shard GPipe schedule, for use inside ``shard_map``.

    fn(stage_params, x) -> y with y.shape == x.shape — or a list of
    n_stages such fns for heterogeneous stages.
    stage_params: this stage's param pytree (stage dim already sliced off).
    microbatches: (n_micro, mb, ...) — replicated across the pp axis.
    Returns (n_micro, mb, ...), identical on every chip (psum broadcast of
    the last stage's outputs).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    fn = _as_stage_fn(fn, stage)
    n_micro = microbatches.shape[0]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def step(carry, t):
        state, outputs = carry
        inject = microbatches[jnp.minimum(t, n_micro - 1)]
        state = jnp.where(stage == 0, inject, state)
        y = fn(stage_params, state)
        out_idx = t - (n_stages - 1)
        is_out = (stage == n_stages - 1) & (out_idx >= 0)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(is_out, y, jax.lax.dynamic_index_in_dim(
                outputs, jnp.maximum(out_idx, 0), 0, keepdims=False)),
            jnp.maximum(out_idx, 0), 0)
        state = jax.lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    state0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros_like(microbatches)
    (state, outputs), _ = jax.lax.scan(
        step, (state0, out0), jnp.arange(n_micro + n_stages - 1))
    # Only the last stage holds real outputs; broadcast them to all chips.
    outputs = jax.lax.psum(
        jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs


def pipeline_1f1b_p(fn, loss_fn, stage_params, microbatches, targets,
                    axis_name):
    """Per-shard 1F1B training schedule, for use inside ``shard_map``.

    One scan interleaves forward and backward: at step t, stage s runs the
    forward for microbatch ``t - s`` and the backward for microbatch
    ``t - (2S-2-s)``. The last stage seeds the backward from the loss vjp
    of the microbatch it JUST forwarded (forward and backward indices
    coincide there), so cotangents start flowing after S-1 steps instead
    of after all n_micro forwards — in-flight activations are bounded by
    2(S-1-s) per stage, independent of n_micro. The backward recomputes
    the stage from its saved INPUT (rematerialization), the standard
    1F1B-with-remat memory/compute trade.

    fn(stage_params, x) -> y (or a list of per-stage fns, see
    ``_as_stage_fn``); loss_fn(y, target) -> scalar (summed over the
    microbatch — applied at the last stage only).
    Returns (loss_sum / n_micro, grad pytree like stage_params): loss
    replicated on every chip, grads local to each stage's chip.
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    fn = _as_stage_fn(fn, stage)
    n_micro = microbatches.shape[0]
    is_last = stage == n_stages - 1
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    # Ring buffer of saved stage inputs: the fwd->bwd gap for one microbatch
    # at stage s is 2(S-1-s) steps, so 2S-1 slots can never collide.
    ring = 2 * n_stages - 1

    def step(carry, t):
        fwd_state, bwd_state, x_buf, grad_acc, loss_acc = carry
        f = t - stage                      # fwd microbatch index
        b = t - (2 * n_stages - 2 - stage)  # bwd microbatch index
        fwd_valid = (f >= 0) & (f < n_micro)
        bwd_valid = (b >= 0) & (b < n_micro)

        # ---- forward: one microbatch through this stage ----
        inject = microbatches[jnp.clip(f, 0, n_micro - 1)]
        x_in = jnp.where(stage == 0, inject, fwd_state)
        y = fn(stage_params, x_in)
        slot_f = jnp.mod(jnp.clip(f, 0, n_micro - 1), ring)
        x_buf = jnp.where(
            fwd_valid,
            jax.lax.dynamic_update_index_in_dim(x_buf, x_in, slot_f, 0),
            x_buf)

        # ---- backward: recompute from the saved input, pull cotangent ----
        slot_b = jnp.mod(jnp.clip(b, 0, n_micro - 1), ring)
        x_saved = jax.lax.dynamic_index_in_dim(x_buf, slot_b, 0,
                                               keepdims=False)
        y_re, stage_vjp = jax.vjp(fn, stage_params, x_saved)
        # last stage: cotangent comes from the loss of microbatch b == f.
        # lax.cond so the S-1 non-last stages skip the loss fwd+vjp at
        # runtime instead of computing and discarding it every step.
        target_b = targets[jnp.clip(b, 0, n_micro - 1)]

        def _loss_branch(args):
            y_b, t_b = args
            loss_v, loss_vjp = jax.vjp(loss_fn, y_b, t_b)
            dy_v, _ = loss_vjp(jnp.ones_like(loss_v))
            return loss_v.astype(jnp.float32), dy_v.astype(y_b.dtype)

        def _skip_branch(args):
            y_b, _ = args
            return jnp.zeros((), jnp.float32), jnp.zeros_like(y_b)

        loss_b, dy_from_loss = jax.lax.cond(
            is_last, _loss_branch, _skip_branch, (y_re, target_b))
        dy = jnp.where(is_last, dy_from_loss, bwd_state)
        dparams, dx = stage_vjp(dy.astype(y_re.dtype))
        grad_acc = jax.tree.map(
            lambda acc, g: acc + jnp.where(bwd_valid, g, 0.0).astype(acc.dtype),
            grad_acc, dparams)
        loss_acc = loss_acc + jnp.where(
            is_last & bwd_valid, loss_b.astype(loss_acc.dtype), 0.0)

        fwd_state = jax.lax.ppermute(y, axis_name, fwd_perm)
        bwd_state = jax.lax.ppermute(dx, axis_name, bwd_perm)
        return (fwd_state, bwd_state, x_buf, grad_acc, loss_acc), None

    mb_shape = microbatches.shape[1:]
    dtype = microbatches.dtype
    carry0 = (
        jnp.zeros(mb_shape, dtype),
        jnp.zeros(mb_shape, dtype),  # cotangents carry the activation dtype
        jnp.zeros((ring,) + mb_shape, dtype),
        jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), stage_params),
        jnp.zeros((), jnp.float32),
    )
    n_steps = n_micro + 2 * n_stages - 2
    (_, _, _, grads, loss_sum), _ = jax.lax.scan(
        step, carry0, jnp.arange(n_steps))
    # only the last stage accumulated loss; broadcast it everywhere
    loss = jax.lax.psum(loss_sum, axis_name) / n_micro
    grads = jax.tree.map(lambda g: g / n_micro, grads)
    return loss, grads


# ---------------------------------------------------------------------------
# Graph op
# ---------------------------------------------------------------------------

def _lower_pipeline(ctx, op, inputs):
    mesh = current_mesh()
    axis = op.attrs["axis"]
    n_micro = op.attrs["n_microbatches"]
    fg = op.attrs["body"]
    n_params = op.attrs["n_params"]
    params = inputs[:n_params]
    x = inputs[n_params]
    caps = list(inputs[n_params + 1:])

    if mesh is None or axis not in mesh.shape:
        raise ValueError(f"pipeline requires a Mesh with axis {axis!r}")
    n_stages = mesh.axis_size(axis)

    batch = x.shape[0]
    if batch % n_micro != 0:
        raise ValueError(f"batch {batch} not divisible by "
                         f"n_microbatches {n_micro}")
    mb = batch // n_micro
    x_micro = x.reshape((n_micro, mb) + x.shape[1:])

    def body_fn(stage_params, state):
        outs = lowering_mod.lower_func_graph(
            ctx, fg, list(stage_params) + [state], caps)
        return outs[0]

    def shard_fn(*args):
        ps = [jnp.squeeze(p, 0) for p in args[:n_params]]
        return pipeline_p(lambda sp, s: body_fn(sp, s), ps, args[n_params],
                          axis)

    from jax.sharding import PartitionSpec as JP

    _shard_map = get_shard_map()
    in_specs = tuple(JP(axis) for _ in range(n_params)) + (JP(),)
    fn = _shard_map(shard_fn, mesh=mesh.jax_mesh, in_specs=in_specs,
                    out_specs=JP(), check_vma=False)
    out = fn(*params, x_micro)
    return [out.reshape((batch,) + out.shape[2:])]


op_registry.register("Pipeline", lower=_lower_pipeline)


def _lower_pipeline_train(ctx, op, inputs):
    mesh = current_mesh()
    axis = op.attrs["axis"]
    n_micro = op.attrs["n_microbatches"]
    body_fgs = op.attrs["bodies"]          # list: 1 (uniform) or n_stages
    loss_fg = op.attrs["loss_body"]
    n_params = op.attrs["n_params"]
    n_body_caps = op.attrs["n_body_caps"]  # per-fg capture counts
    params = inputs[:n_params]
    x = inputs[n_params]
    targets = inputs[n_params + 1]
    caps = list(inputs[n_params + 2:])
    body_caps, off = [], 0
    for n in n_body_caps:
        body_caps.append(caps[off:off + n])
        off += n
    loss_caps = caps[off:]

    if mesh is None or axis not in mesh.shape:
        raise ValueError(f"pipeline requires a Mesh with axis {axis!r}")
    n_stages = mesh.axis_size(axis)

    batch = x.shape[0]
    if batch % n_micro != 0:
        raise ValueError(f"batch {batch} not divisible by "
                         f"n_microbatches {n_micro}")
    mb = batch // n_micro
    x_micro = x.reshape((n_micro, mb) + x.shape[1:])
    t_micro = targets.reshape((n_micro, mb) + targets.shape[1:])

    def make_body(fg, fg_caps):
        def body_fn(stage_params, state):
            outs = lowering_mod.lower_func_graph(
                ctx, fg, list(stage_params) + [state], fg_caps)
            return outs[0]
        return body_fn

    bodies = [make_body(fg, c) for fg, c in zip(body_fgs, body_caps)]
    stage_fn = bodies[0] if len(bodies) == 1 else bodies

    def loss_fn(y, t):
        outs = lowering_mod.lower_func_graph(ctx, loss_fg, [y, t], loss_caps)
        return outs[0]

    def shard_fn(*args):
        ps = [jnp.squeeze(p, 0) for p in args[:n_params]]
        loss, grads = pipeline_1f1b_p(
            stage_fn, loss_fn, tuple(ps), args[n_params],
            args[n_params + 1], axis)
        return (loss,) + tuple(g[None] for g in grads)

    from jax.sharding import PartitionSpec as JP

    _shard_map = get_shard_map()
    in_specs = tuple(JP(axis) for _ in range(n_params)) + (JP(), JP())
    out_specs = (JP(),) + tuple(JP(axis) for _ in range(n_params))
    fn = _shard_map(shard_fn, mesh=mesh.jax_mesh, in_specs=in_specs,
                    out_specs=out_specs, check_vma=False)
    outs = fn(*params, x_micro, t_micro)
    return list(outs)


op_registry.register("PipelineTrain", lower=_lower_pipeline_train)


def _device_memory_budget(frac=0.6):
    """Usable HBM for activation stashes: memory_stats when the backend
    reports it, else the v5e's 16 GB, scaled by ``frac`` (params,
    optimizer state, and XLA scratch own the rest)."""
    import jax

    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        if limit:
            return frac * float(limit)
    except Exception:
        pass
    return frac * 16e9


def pipeline_train(stage_fn, loss_fn, params, x, targets, *,
                   n_microbatches, axis="pp", name=None):
    """Graph op: 1F1B-scheduled pipelined TRAINING step over mesh axis
    ``axis``. Returns ``(loss, grads)`` — the mean per-microbatch loss and
    one gradient tensor per stacked param, sharded like the params.

    Unlike ``pipeline`` + ``stf.gradients`` (GPipe forward, autodiff
    backward, O(n_micro) live activations), this runs the combined
    1F1B forward/backward schedule inside one scan with O(n_stages)
    activation memory; apply the returned grads with
    ``optimizer.apply_gradients(zip(grads, vars))``.

    stage_fn(*stage_params, state) -> state' builds one stage as graph ops
    — or a LIST of n_stages such fns for heterogeneous pipelines (stage
    widths may then differ internally; pad per-stage params to a common
    stacked shape and slice inside each fn). loss_fn(y, target) -> scalar
    (summed over a microbatch). ``params`` are stacked (n_stages, ...)
    tensors sharded over ``axis``; ``x``/``targets``: (batch, ...) with
    batch divisible by n_microbatches.

    ``n_microbatches="auto"`` sizes the microbatch count from the static
    cost model (framework/cost_model.py): smallest count whose 1F1B
    activation stash fits the per-device HBM budget, clamped to
    [n_stages, batch] and to a divisor of the batch.
    """
    from ..ops.functional_ops import _build_fn_graph

    mesh = current_mesh()
    if mesh is None or axis not in mesh.shape:
        raise ValueError(f"pipeline requires a Mesh with axis {axis!r}")
    n_stages = mesh.axis_size(axis)

    params = [ops_mod.convert_to_tensor(p) for p in params]
    x = ops_mod.convert_to_tensor(x)
    targets = ops_mod.convert_to_tensor(targets)
    for p in params:
        if p.shape.rank is None or p.shape[0].value != n_stages:
            raise ValueError(
                f"stacked param {p} must have leading dim == n_stages "
                f"({n_stages})")

    if n_microbatches == "auto":
        # cost-model-driven choice (ref: grappler graph_memory.cc role):
        # the inter-stage state (x-shaped, per microbatch) is the 1F1B
        # activation stash; fit it in a fraction of per-device HBM, then
        # clamp to the batch.
        from ..framework import cost_model as cost_model_mod

        state_bytes = 1
        for d in x.shape.dims:
            state_bytes *= d.value or 1
        state_bytes *= x.dtype.base_dtype.size
        budget = _device_memory_budget()
        n_microbatches = cost_model_mod.suggest_microbatches(
            float(state_bytes), n_stages, budget, schedule="1f1b")
        # more microbatches than batch rows is meaningless; also keep the
        # bubble fraction sane (>= n_stages microbatches when possible)
        batch_rows = x.shape[0].value
        n_microbatches = max(min(n_microbatches, batch_rows),
                             min(n_stages, batch_rows))
        # round UP to a divisor of the batch: fewer microbatches would
        # mean BIGGER stashes and blow the budget the count was fitted to
        # (batch_rows divides itself, so this terminates)
        while batch_rows % n_microbatches:
            n_microbatches += 1

    mb = x.shape[0].value // n_microbatches
    arg_specs = ([(p.shape.as_list()[1:], p.dtype) for p in params]
                 + [([mb] + x.shape.as_list()[1:], x.dtype)])
    stage_fns = (list(stage_fn) if isinstance(stage_fn, (list, tuple))
                 else [stage_fn])
    if len(stage_fns) not in (1, n_stages):
        raise ValueError(f"need 1 or {n_stages} stage fns, "
                         f"got {len(stage_fns)}")
    fgs, all_caps, n_body_caps = [], [], []
    for i, fn in enumerate(stage_fns):
        fg, _ = _build_fn_graph(lambda *a, f=fn: f(*a), arg_specs,
                                f"pipeline_stage_{i}")
        fgs.append(fg)
        fg_caps = [outer for outer, _ in fg.captures]
        all_caps.extend(fg_caps)
        n_body_caps.append(len(fg_caps))

    y_spec = ([mb] + x.shape.as_list()[1:], x.dtype)
    t_spec = ([mb] + targets.shape.as_list()[1:], targets.dtype)
    loss_fg, _ = _build_fn_graph(lambda y, t: loss_fn(y, t),
                                 [y_spec, t_spec], "pipeline_loss")
    loss_caps = [outer for outer, _ in loss_fg.captures]

    from ..framework import dtypes as dtypes_mod

    g = ops_mod.get_default_graph()
    out_specs = ([(shape_mod.TensorShape([]), dtypes_mod.float32)]
                 + [(p.shape, dtypes_mod.float32) for p in params])
    node = g.create_op(
        "PipelineTrain", params + [x, targets] + all_caps + loss_caps,
        attrs={"bodies": fgs, "loss_body": loss_fg, "axis": axis,
               "n_microbatches": int(n_microbatches),
               "n_params": len(params), "n_body_caps": n_body_caps},
        name=name or "pipeline_train", output_specs=out_specs)
    return node.outputs[0], list(node.outputs[1:])


def pipeline(stage_fn, params, x, *, n_microbatches, axis="pp", name=None):
    """Graph op: run ``stage_fn`` as an n_stage pipeline over mesh axis
    ``axis`` with the GPipe microbatch schedule.

    stage_fn(*stage_params, x) -> y builds the per-stage computation as
    graph ops (y.shape == x.shape). ``params`` are tensors/variables whose
    leading dim is n_stages (stacked per-stage weights, sharded over the
    axis). ``x``: (batch, ...) with batch divisible by n_microbatches.
    """
    from ..ops.functional_ops import _build_fn_graph

    mesh = current_mesh()
    if mesh is None or axis not in mesh.shape:
        raise ValueError(f"pipeline requires a Mesh with axis {axis!r}")

    params = [ops_mod.convert_to_tensor(p) for p in params]
    x = ops_mod.convert_to_tensor(x)
    for p in params:
        if p.shape.rank is None or p.shape[0].value != mesh.axis_size(axis):
            raise ValueError(
                f"stacked param {p} must have leading dim == n_stages "
                f"({mesh.axis_size(axis)})")

    arg_specs = ([(p.shape.as_list()[1:], p.dtype) for p in params]
                 + [([x.shape[0].value // n_microbatches]
                     + x.shape.as_list()[1:], x.dtype)])
    fg, _ = _build_fn_graph(lambda *a: stage_fn(*a), arg_specs,
                            "pipeline_stage")
    caps = [outer for outer, _ in fg.captures]
    g = ops_mod.get_default_graph()
    node = g.create_op(
        "Pipeline", params + [x] + caps,
        attrs={"body": fg, "axis": axis, "n_microbatches": int(n_microbatches),
               "n_params": len(params)},
        name=name or "pipeline", output_specs=[(x.shape, x.dtype)])
    return node.outputs[0]
