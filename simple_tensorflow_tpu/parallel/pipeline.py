"""Pipeline parallelism: GPipe microbatch schedule over a mesh axis.

The reference pipelines by placing layer subsets on different workers with
``tf.device`` and letting grpc Send/Recv stream activations
(ref: core/distributed_runtime partition + core/kernels/sendrecv_ops.cc);
there is no microbatch schedule, so utilisation collapses with depth. The
TPU version runs the schedule *inside one SPMD program*: every chip along
the 'pp' axis executes the same scan; at step t chip s processes microbatch
t-s (a skew of the GPipe schedule), and ``lax.ppermute`` hands activations
to the next stage over ICI. Bubble fraction is (n_stages-1)/(n_micro +
n_stages-1); XLA overlaps the permute with the next microbatch's compute.

Constraint (round 1): every stage maps activations of one shape to the same
shape (equal-width pipeline), the standard transformer-block case.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from ..framework import lowering as lowering_mod
from .mesh import current_mesh, get_shard_map


def pipeline_p(fn, stage_params, microbatches, axis_name):
    """Per-shard GPipe schedule, for use inside ``shard_map``.

    fn(stage_params, x) -> y with y.shape == x.shape.
    stage_params: this stage's param pytree (stage dim already sliced off).
    microbatches: (n_micro, mb, ...) — replicated across the pp axis.
    Returns (n_micro, mb, ...), identical on every chip (psum broadcast of
    the last stage's outputs).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def step(carry, t):
        state, outputs = carry
        inject = microbatches[jnp.minimum(t, n_micro - 1)]
        state = jnp.where(stage == 0, inject, state)
        y = fn(stage_params, state)
        out_idx = t - (n_stages - 1)
        is_out = (stage == n_stages - 1) & (out_idx >= 0)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(is_out, y, jax.lax.dynamic_index_in_dim(
                outputs, jnp.maximum(out_idx, 0), 0, keepdims=False)),
            jnp.maximum(out_idx, 0), 0)
        state = jax.lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    state0 = jnp.zeros_like(microbatches[0])
    out0 = jnp.zeros_like(microbatches)
    (state, outputs), _ = jax.lax.scan(
        step, (state0, out0), jnp.arange(n_micro + n_stages - 1))
    # Only the last stage holds real outputs; broadcast them to all chips.
    outputs = jax.lax.psum(
        jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs


# ---------------------------------------------------------------------------
# Graph op
# ---------------------------------------------------------------------------

def _lower_pipeline(ctx, op, inputs):
    mesh = current_mesh()
    axis = op.attrs["axis"]
    n_micro = op.attrs["n_microbatches"]
    fg = op.attrs["body"]
    n_params = op.attrs["n_params"]
    params = inputs[:n_params]
    x = inputs[n_params]
    caps = list(inputs[n_params + 1:])

    if mesh is None or axis not in mesh.shape:
        raise ValueError(f"pipeline requires a Mesh with axis {axis!r}")
    n_stages = mesh.axis_size(axis)

    batch = x.shape[0]
    if batch % n_micro != 0:
        raise ValueError(f"batch {batch} not divisible by "
                         f"n_microbatches {n_micro}")
    mb = batch // n_micro
    x_micro = x.reshape((n_micro, mb) + x.shape[1:])

    def body_fn(stage_params, state):
        outs = lowering_mod.lower_func_graph(
            ctx, fg, list(stage_params) + [state], caps)
        return outs[0]

    def shard_fn(*args):
        ps = [jnp.squeeze(p, 0) for p in args[:n_params]]
        return pipeline_p(lambda sp, s: body_fn(sp, s), ps, args[n_params],
                          axis)

    from jax.sharding import PartitionSpec as JP

    _shard_map = get_shard_map()
    in_specs = tuple(JP(axis) for _ in range(n_params)) + (JP(),)
    fn = _shard_map(shard_fn, mesh=mesh.jax_mesh, in_specs=in_specs,
                    out_specs=JP(), check_vma=False)
    out = fn(*params, x_micro)
    return [out.reshape((batch,) + out.shape[2:])]


op_registry.register("Pipeline", lower=_lower_pipeline)


def pipeline(stage_fn, params, x, *, n_microbatches, axis="pp", name=None):
    """Graph op: run ``stage_fn`` as an n_stage pipeline over mesh axis
    ``axis`` with the GPipe microbatch schedule.

    stage_fn(*stage_params, x) -> y builds the per-stage computation as
    graph ops (y.shape == x.shape). ``params`` are tensors/variables whose
    leading dim is n_stages (stacked per-stage weights, sharded over the
    axis). ``x``: (batch, ...) with batch divisible by n_microbatches.
    """
    from ..ops.functional_ops import _build_fn_graph

    mesh = current_mesh()
    if mesh is None or axis not in mesh.shape:
        raise ValueError(f"pipeline requires a Mesh with axis {axis!r}")

    params = [ops_mod.convert_to_tensor(p) for p in params]
    x = ops_mod.convert_to_tensor(x)
    for p in params:
        if p.shape.rank is None or p.shape[0].value != mesh.axis_size(axis):
            raise ValueError(
                f"stacked param {p} must have leading dim == n_stages "
                f"({mesh.axis_size(axis)})")

    arg_specs = ([(p.shape.as_list()[1:], p.dtype) for p in params]
                 + [([x.shape[0].value // n_microbatches]
                     + x.shape.as_list()[1:], x.dtype)])
    fg, _ = _build_fn_graph(lambda *a: stage_fn(*a), arg_specs,
                            "pipeline_stage")
    caps = [outer for outer, _ in fg.captures]
    g = ops_mod.get_default_graph()
    node = g.create_op(
        "Pipeline", params + [x] + caps,
        attrs={"body": fg, "axis": axis, "n_microbatches": int(n_microbatches),
               "n_params": len(params)},
        name=name or "pipeline", output_specs=[(x.shape, x.dtype)])
    return node.outputs[0]
