"""Device mesh (replaces ref: tensorflow/core/distributed_runtime +
third_party/nccl.BUILD NCCL rings).

The reference scales by partitioning the graph across grpc workers and
inserting Send/Recv + NcclAllReduce. TPU-native scaling is SPMD: ONE global
program, a named device mesh, shardings on arrays — XLA GSPMD inserts the
collectives over ICI/DCN. `Mesh` wraps jax.sharding.Mesh with the canonical
training axis names:

  dp    data parallel (batch split, params replicated)
  fsdp  fully-sharded data parallel (batch + params split)
  tp    tensor/model parallel (Megatron-style)
  pp    pipeline parallel (layer stages)
  sp    sequence/context parallel (ring attention)
  ep    expert parallel (MoE)

Multi-host: jax.distributed (stf.train.Server) makes jax.devices() span all
hosts; the same Mesh code then spans the pod — ICI within a slice, DCN
across slices (put dp/fsdp outermost so its collectives ride DCN).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

CANONICAL_AXES = ("dp", "fsdp", "pp", "tp", "sp", "ep")

_mesh_stack = threading.local()


def _stack() -> list:
    if not hasattr(_mesh_stack, "stack"):
        _mesh_stack.stack = []
    return _mesh_stack.stack


class Mesh:
    """Named device mesh. ``Mesh({"dp": 2, "tp": 4})`` or
    ``Mesh(axis_names=("dp","tp"), shape=(2,4))``."""

    def __init__(self, axes: Optional[Dict[str, int]] = None,
                 devices=None, axis_names: Optional[Sequence[str]] = None,
                 shape: Optional[Sequence[int]] = None):
        import jax

        if axes is not None:
            axis_names = tuple(axes.keys())
            shape = tuple(int(v) for v in axes.values())
        elif axis_names is not None:
            axis_names = tuple(axis_names)
            shape = tuple(int(s) for s in (shape or ()))
        else:
            raise ValueError("Mesh needs axes={name: size}")
        if devices is None:
            devices = jax.devices()
        n = int(np.prod(shape)) if shape else 1
        if len(devices) < n:
            raise ValueError(
                f"Mesh {dict(zip(axis_names, shape))} needs {n} devices, "
                f"have {len(devices)}")
        dev_array = np.asarray(devices[:n]).reshape(shape)
        self._jax_mesh = jax.sharding.Mesh(dev_array, axis_names)
        self.axis_names = axis_names
        self.shape = dict(zip(axis_names, shape))

    @property
    def jax_mesh(self):
        return self._jax_mesh

    @property
    def devices(self):
        return list(self._jax_mesh.devices.flat)

    @property
    def size(self) -> int:
        return int(np.prod(list(self.shape.values())))

    def axis_size(self, name: str) -> int:
        return self.shape[name]

    def named_sharding(self, *spec):
        import jax

        return jax.sharding.NamedSharding(self._jax_mesh,
                                          jax.sharding.PartitionSpec(*spec))

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False

    def __repr__(self):
        return f"stf.parallel.Mesh({self.shape})"


def current_mesh() -> Optional[Mesh]:
    st = _stack()
    return st[-1] if st else None


def get_shard_map():
    """jax.shard_map across the supported JAX versions (renamed from
    jax.experimental.shard_map; the ``check_rep`` kwarg became
    ``check_vma``). Callers use the NEW spelling (``check_vma``); on a
    jax whose shard_map still takes ``check_rep`` (e.g. the pinned
    0.4.x) the wrapper translates — and drops kwargs the resident
    version knows under neither name rather than TypeError-ing."""
    import inspect

    try:
        from jax import shard_map as sm
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map as sm
    try:
        params = inspect.signature(sm).parameters
    except (TypeError, ValueError):  # pragma: no cover
        return sm
    if "check_vma" in params:
        return sm

    def compat_shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            val = kwargs.pop("check_vma")
            if "check_rep" in params:
                kwargs["check_rep"] = val
        kwargs = {k: v for k, v in kwargs.items() if k in params}
        return sm(*args, **kwargs)

    return compat_shard_map


def make_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    return Mesh(axes, devices=devices)


class PartitionSpec(tuple):
    """Thin alias of jax.sharding.PartitionSpec semantics, constructible
    without jax imported at module scope."""

    def __new__(cls, *parts):
        return super().__new__(cls, parts)

    def to_jax(self):
        import jax

        return jax.sharding.PartitionSpec(*self)


P = PartitionSpec
