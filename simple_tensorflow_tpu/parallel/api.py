"""Sharding annotation API (replaces ref: core/common_runtime/simple_placer.cc
device placement + python/training/device_setter.py).

Placement on TPU is sharding: arrays carry NamedShardings, XLA GSPMD
partitions the one compiled step program and inserts ICI collectives. This
module annotates the three array classes:

- variables: ``shard_variables_along(axis)`` scope or ``shard_variable``;
  the Session places the state buffer with the sharding after init,
- feeds (the global batch): ``shard_feed(placeholder, spec)``; Session
  device_puts each fed array with it (host shards its slice on pods),
- activations: ``with_sharding_constraint(t, spec)`` graph op →
  lax.with_sharding_constraint inside the step.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from ..framework import graph as ops_mod
from ..framework import op_registry
from .mesh import Mesh, P, PartitionSpec, current_mesh, make_mesh

_VS_KEY = "__variable_sharding_rule__"


@contextlib.contextmanager
def shard_variables_along(axis, min_size=2 ** 14, dim=None):
    """Variables created in this scope are sharded over mesh axis ``axis``
    on their largest dimension (fsdp/ZeRO-3 layout) unless ``dim`` pins one.
    Small variables stay replicated (below ``min_size`` elements)."""
    g = ops_mod._root_graph()
    prev = g._scoped_state.get(_VS_KEY)
    g._scoped_state[_VS_KEY] = {"axis": axis, "min_size": min_size,
                                "dim": dim}
    try:
        yield
    finally:
        if prev is None:
            g._scoped_state.pop(_VS_KEY, None)
        else:
            g._scoped_state[_VS_KEY] = prev


def _auto_spec_for(shape, rule, mesh):
    if rule is None or mesh is None:
        return None
    dims = [int(d) for d in shape]
    n = 1
    for d in dims:
        n *= d
    if n < rule["min_size"] or not dims:
        return None
    axis = rule["axis"]
    size = mesh.axis_size(axis) if axis in mesh.shape else None
    if size is None:
        return None
    dim = rule["dim"]
    if dim is None:
        # largest dim divisible by the axis size
        cands = [i for i, d in enumerate(dims) if d % size == 0]
        if not cands:
            return None
        dim = max(cands, key=lambda i: dims[i])
    spec = [None] * len(dims)
    spec[dim] = axis
    return P(*spec)


def auto_shard_variable(variable, axis, min_size=2 ** 14, dim=None,
                        mesh=None):
    """Shard ``variable`` over ``axis`` on its largest divisible dim (ZeRO
    layout); no-op for small/indivisible shapes. Public entry used by
    FSDP.shard_existing and the scope rule."""
    mesh = mesh or current_mesh()
    spec = _auto_spec_for(variable.shape.as_list(),
                          {"axis": axis, "min_size": min_size, "dim": dim},
                          mesh)
    if spec is not None:
        variable.set_sharding(spec)
    return variable


def maybe_apply_variable_sharding(variable):
    """Called by Variable.__init__; applies the active scope rule."""
    g = variable.graph
    rule = g._scoped_state.get(_VS_KEY)
    mesh = current_mesh()
    if rule is not None and mesh is not None and variable.sharding is None:
        spec = _auto_spec_for(variable.shape.as_list(), rule, mesh)
        if spec is not None:
            variable.set_sharding(spec)


def shard_variable(variable, *spec):
    variable.set_sharding(P(*spec))
    return variable


def shard_feed(placeholder, *spec):
    """Annotate a placeholder so Session shards the fed batch over the mesh
    (e.g. shard_feed(x, 'dp') splits dim 0 across data-parallel devices)."""
    placeholder.op.attrs["sharding"] = P(*spec)
    return placeholder


def _lower_sharding_constraint(ctx, op, inputs):
    import jax

    mesh = current_mesh()
    spec = op.attrs["spec"]
    if mesh is None or getattr(ctx, "host", False) \
            or getattr(ctx, "in_shard_map", False):
        # no mesh / host stage / inside shard_map (manual axes): the
        # constraint is a no-op passthrough, never an error
        return [inputs[0]]
    ns = jax.sharding.NamedSharding(mesh.jax_mesh, spec.to_jax()
                                    if isinstance(spec, PartitionSpec)
                                    else jax.sharding.PartitionSpec(*spec))
    out = jax.lax.with_sharding_constraint(inputs[0], ns)
    if op.attrs.get("commit") and hasattr(ctx, "env"):
        # committing constraint (autoshard cut point): rebind the INPUT
        # tensor's traced value so every consumer lowered after this op
        # reads the constrained value — Session._plan splices commit
        # ops immediately after their producer, so that is all of them.
        # Consumers resolve inputs through the CSE alias map, so the
        # canonical tensor must rebind too.
        t = op.inputs[0]
        ctx.env[t] = out
        canon = getattr(ctx, "alias", {}).get(t)
        if canon is not None:
            ctx.env[canon] = out
    return [out]


def _infer_sharding_constraint(graph, attrs, input_tensors):
    t = input_tensors[0]
    return [(t.shape, t.dtype)]


op_registry.register("ShardingConstraint", lower=_lower_sharding_constraint,
                     infer_fn=_infer_sharding_constraint)


def with_sharding_constraint(tensor, *spec, name=None):
    """Pin an activation's layout (→ lax.with_sharding_constraint). The
    classic uses: batch axis on 'dp', hidden on 'tp' after a sharded matmul,
    sequence on 'sp'."""
    t = ops_mod.convert_to_tensor(tensor)
    g = ops_mod.get_default_graph()
    op = g.create_op("ShardingConstraint", [t], attrs={"spec": P(*spec)},
                     name=name or "sharding_constraint",
                     output_specs=[(t.shape, t.dtype)])
    return op.outputs[0]


def emit_commit_constraint(tensor, spec, name=None):
    """Create a COMMITTING ``ShardingConstraint`` op for ``tensor`` (the
    autoshard cut-point form): a first-class graph op whose lowering
    both returns the constrained value and rebinds the input tensor's
    traced value, so consumers that were built before the constraint
    existed still read the committed layout. ``Session._plan`` splices
    registered commit ops into any plan that produces their input
    (see ``Graph._scoped_state['__autoshard_constraints__']``)."""
    t = ops_mod.convert_to_tensor(tensor)
    g = t.op.graph
    op = g.create_op(
        "ShardingConstraint", [t],
        attrs={"spec": P(*spec), "commit": True},
        name=name or "autoshard_constraint",
        output_specs=[(t.shape, t.dtype)])
    return op


def num_devices() -> int:
    import jax

    return jax.device_count()


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_chief() -> bool:
    return process_index() == 0


# ---------------------------------------------------------------------------
# sharding propagation (stf.analysis.sharding; ISSUE 6)
# ---------------------------------------------------------------------------

def _sharding_constraint_rule(op, in_specs, ctx):
    from ..analysis import sharding as _shard

    t = op.outputs[0]
    spec = _shard.normalize_spec(op.attrs.get("spec"), t.shape.rank)
    if spec is None:
        return [in_specs[0]]
    ctx.require(0, spec)
    return [spec]


def _sharding_constraint_backward(op, out_specs, in_specs, ctx):
    # the constraint's spec propagates upstream through weakly-typed
    # producers, so a mid-graph constraint seeds both directions
    return [out_specs[0]]


_sharding_constraint_rule.backward = _sharding_constraint_backward
_sharding_constraint_rule.seeds_outputs = True
op_registry.register_sharding_rule("ShardingConstraint",
                                   _sharding_constraint_rule)


def match_partition_rules(rules, variable_store=None, on_missing="replicate",
                          apply=False, mesh=None, diagnostics=None):
    """Regex name-pattern -> PartitionSpec mapping over variables
    (SNIPPETS.md [2] exemplar: the fmengine/EasyLM idiom).

    ``rules``: sequence of ``(pattern, spec)`` pairs; the FIRST pattern
    to ``re.search`` a variable's store name wins. ``spec`` is a
    PartitionSpec-like (P(...), tuple, list — None entries replicate a
    dim). Scalars and single-element variables always replicate.

    ``variable_store``: where to find variables — a dict name->Variable,
    an iterable of Variables, or None for the default graph's global
    variables. ``on_missing``: "replicate" (default) maps unmatched
    variables to P(); "error" raises (the strict EasyLM contract);
    "skip" leaves them out of the result.

    Returns ``{store_name: spec}`` — exactly the ``seed_specs`` shape
    ``analysis.analyze_sharding`` takes, so a rule set can be CHECKED
    against the graph (collective bytes, lint findings) before paying a
    compile. ``apply=True`` also commits each matched spec via
    ``Variable.set_sharding`` (the Session then places state with it).

    A large non-scalar variable that falls through to the
    ``on_missing="replicate"`` default is a rule-set GAP, not a
    choice: it emits a ``sharding/unmatched-large-var`` WARNING
    (logged, and appended to ``diagnostics`` when a list is passed —
    the byte threshold is the replicated-large-tensor lint cutoff,
    ``STF_SHARDING_LARGE_BYTES``) so gaps surface before an autoshard
    search or a compile papers over them.
    """
    import re

    if variable_store is None:
        from ..ops import variables as variables_mod

        variable_store = variables_mod.global_variables()
    if isinstance(variable_store, dict):
        items = list(variable_store.items())
    else:
        items = []
        for v in variable_store:
            name = getattr(v, "var_name", None) or getattr(v, "name", "")
            items.append((name, v))
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    out = {}
    for name, var in items:
        shape = getattr(var, "shape", None)
        dims = shape.as_list() if shape is not None and \
            shape.rank is not None else None
        n = 1
        for d in (dims or []):
            n *= (d or 1)
        if dims is not None and (len(dims) == 0 or n <= 1):
            out[name] = P()
            continue
        matched = None
        for rx, spec in compiled:
            if rx.search(name) is not None:
                matched = P(*spec) if not isinstance(spec, PartitionSpec) \
                    else spec
                break
        if matched is None:
            if on_missing == "error":
                raise ValueError(
                    f"match_partition_rules: no rule matches variable "
                    f"{name!r} (add a catch-all ('.*', P()) rule or pass "
                    "on_missing='replicate')")
            if on_missing == "skip":
                continue
            matched = P()
            _warn_unmatched_large(name, var, dims, diagnostics)
        out[name] = matched
        if apply and hasattr(var, "set_sharding"):
            var.set_sharding(matched)
    return out


def _warn_unmatched_large(name, var, dims, diagnostics):
    """``sharding/unmatched-large-var``: the on_missing="replicate"
    default silently replicated a tensor above the
    replicated-large-tensor lint cutoff — a rule-set gap that must be
    loud before a search or a compile builds on it."""
    from ..analysis import diagnostics as diag_mod
    from ..analysis.sharding import LARGE_TENSOR_BYTES

    if dims is None or len(dims) == 0:
        return
    n = 1
    for d in dims:
        n *= (d or 1)
    try:
        dsize = var.dtype.base_dtype.size
    except Exception:
        dsize = 4
    nbytes = n * dsize
    if nbytes < LARGE_TENSOR_BYTES:
        return
    msg = (f"match_partition_rules: no rule matches variable {name!r} "
           f"({int(nbytes)} bytes); on_missing='replicate' copies it "
           "whole into every device's HBM — add a rule (or a "
           "deliberate catch-all ('.*', P()))")
    if diagnostics is not None:
        diag_mod.report(diagnostics, diag_mod.WARNING,
                        "sharding/unmatched-large-var", msg,
                        op=getattr(var, "op", None))
    from ..platform import tf_logging as logging

    logging.warning("sharding/unmatched-large-var: %s", msg)


# ---------------------------------------------------------------------------
# auto-sharding (stf.analysis.autoshard; ISSUE 14)
# ---------------------------------------------------------------------------

def auto_shard(variable_store=None, mesh=None, rules=None, fetches=None,
               feeds=(), graph=None, budget_bytes=None,
               emit_constraints=True, **search_kw):
    """Search PartitionSpecs for the variable store + plan inputs with
    the collective-cost analyzer as the objective and COMMIT the winner
    to the live graph: variable shardings, feed shardings, and
    committing ``ShardingConstraint`` ops at the searched cut points.
    Explicit user-placed specs are kept as fixed seeds, never
    overridden. Returns the :class:`stf.analysis.autoshard
    .AutoshardResult` (rule set, predicted bytes, cut points).

    ``variable_store`` is accepted for symmetry with
    ``match_partition_rules`` (an iterable of Variables to restrict
    the search to); None searches every variable in the plan/graph.
    """
    from ..analysis import autoshard as autoshard_mod
    from ..framework import graph as ops_graph

    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("auto_shard: no mesh — pass mesh= or enter a "
                         "stf.parallel.Mesh context")
    graph = graph or ops_graph.get_default_graph()
    ops = None
    if fetches is not None:
        from ..framework import lowering as lowering_mod

        targets = []
        for f in (fetches if isinstance(fetches, (list, tuple))
                  else [fetches]):
            targets.append(f if isinstance(f, ops_graph.Operation)
                           else f.op)
        ops = lowering_mod.prune(targets, fed_tensors=set(feeds))
    result = autoshard_mod.search_sharding(
        graph=graph, ops=ops, mesh=mesh, fetches=fetches, feeds=feeds,
        rules=rules, budget_bytes=budget_bytes, **search_kw)
    if variable_store is not None:
        keep = set()
        for v in (variable_store.values()
                  if isinstance(variable_store, dict)
                  else variable_store):
            keep.add(getattr(v, "var_name", None)
                     or getattr(v, "name", ""))
        for g in result.groups:
            if g["kind"] == "var":
                g["members"] = [m for m in g["members"] if m in keep]
    result.apply(graph=graph, emit_constraints=emit_constraints)
    return result


class PodTrainProgram:
    """What :func:`mlperf_pod_train` returns: the accumulate / apply
    ops plus a driver. ``run(sess, feeds)`` executes one GLOBAL batch —
    N gradient-accumulation micro-steps then one (mean-scaled) apply —
    and returns the last micro-step's loss. With
    ``gradient_accumulation_steps == 1`` ``train_op`` is a plain
    fused step and ``run`` is one ``sess.run``."""

    def __init__(self, train_op, accum_op, apply_op, loss, steps,
                 autoshard_result):
        self.train_op = train_op
        self.accum_op = accum_op
        self.apply_op = apply_op
        self.loss = loss
        self.steps = int(steps)
        self.autoshard = autoshard_result

    def run(self, sess, feed_fn=None, feed_dict=None):
        """One global batch. ``feed_fn(micro_step) -> feed_dict``
        supplies per-micro-batch feeds; a fixed ``feed_dict`` repeats
        the same batch (testing)."""
        out = None
        for i in range(self.steps):
            fd = feed_fn(i) if feed_fn is not None else feed_dict
            if self.steps == 1:
                out = sess.run([self.loss, self.train_op],
                               feed_dict=fd)[0]
            else:
                out = sess.run([self.loss, self.accum_op],
                               feed_dict=fd)[0]
        if self.steps > 1:
            sess.run(self.apply_op, feed_dict=fd)
        return out


def mlperf_pod_train(loss, mesh=None, optimizer=None,
                     gradient_accumulation_steps=1, fetches=None,
                     rules=None, **autoshard_kw):
    """The MLPerf-pod recipe (1909.09756) as one entry point: a dp×tp
    mesh, SEARCHED shardings (``auto_shard`` over the train plan — no
    hand-placed specs), and gradient accumulation for global-batch
    scaling. Returns a :class:`PodTrainProgram`.

    ``optimizer`` defaults to plain SGD; pass a Momentum/LARS/LAMB-
    style optimizer for the full pod recipe.
    ``gradient_accumulation_steps`` > 1 builds accumulator variables:
    the accum op adds one micro-batch's grads in place, the apply op
    feeds the MEAN accumulated gradient to the optimizer and zeroes
    the accumulators (1909.09756's batch-scaling lever)."""
    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("mlperf_pod_train: no mesh — pass mesh= or "
                         "enter a stf.parallel.Mesh context")
    if optimizer is None:
        from ..train import GradientDescentOptimizer

        optimizer = GradientDescentOptimizer(0.01)
    n = int(gradient_accumulation_steps)
    accum_op = apply_op = train_op = None
    if n <= 1:
        train_op = optimizer.minimize(loss)
        searched_fetches = fetches or [train_op, loss]
    else:
        import numpy as np

        from ..framework import graph as ops_graph
        from ..ops import math_ops, state_ops, variables as vars_mod

        grads_vars = [(g, v) for g, v in
                      optimizer.compute_gradients(loss)
                      if g is not None]
        accums = []
        with ops_graph.get_default_graph().name_scope("grad_accum"):
            for g, v in grads_vars:
                acc = vars_mod.Variable(
                    np.zeros([d or 1 for d in g.shape.as_list()],
                             dtype=g.dtype.np_dtype),
                    trainable=False,
                    name=v.op.name.rsplit("/", 1)[-1] + "_accum")
                accums.append(acc)
            accum_ops = [state_ops.assign_add(acc, g)
                         for acc, g in zip(accums,
                                           (g for g, _ in grads_vars))]
            from ..ops import control_flow_ops as cf

            accum_op = cf.group(*[op.op if hasattr(op, "op") else op
                                  for op in accum_ops],
                                name="accumulate")
            scale = 1.0 / float(n)
            mean_gv = [(math_ops.multiply(acc.value(), scale), v)
                       for acc, (_, v) in zip(accums, grads_vars)]
            step = optimizer.apply_gradients(mean_gv)
            from ..ops import array_ops

            zeros = []
            with ops_graph.get_default_graph().control_dependencies(
                    [step]):
                for acc in accums:
                    # zeros_like, NOT acc*0.0: an inf/nan accumulated
                    # gradient times 0.0 is nan — the reset must clear
                    # a poisoned accumulator, not propagate it
                    zeros.append(state_ops.assign(
                        acc, array_ops.zeros_like(acc.value())))
            apply_op = cf.group(step, *[z.op if hasattr(z, "op") else z
                                        for z in zeros], name="apply")
        searched_fetches = fetches or [accum_op, apply_op, loss]
    result = auto_shard(mesh=mesh, fetches=searched_fetches,
                        rules=rules, **autoshard_kw)
    return PodTrainProgram(train_op, accum_op, apply_op, loss, max(n, 1),
                           result)
