"""Sharding annotation API (replaces ref: core/common_runtime/simple_placer.cc
device placement + python/training/device_setter.py).

Placement on TPU is sharding: arrays carry NamedShardings, XLA GSPMD
partitions the one compiled step program and inserts ICI collectives. This
module annotates the three array classes:

- variables: ``shard_variables_along(axis)`` scope or ``shard_variable``;
  the Session places the state buffer with the sharding after init,
- feeds (the global batch): ``shard_feed(placeholder, spec)``; Session
  device_puts each fed array with it (host shards its slice on pods),
- activations: ``with_sharding_constraint(t, spec)`` graph op →
  lax.with_sharding_constraint inside the step.
"""

from __future__ import annotations

import contextlib
from typing import Optional

from ..framework import graph as ops_mod
from ..framework import op_registry
from .mesh import Mesh, P, PartitionSpec, current_mesh, make_mesh

_VS_KEY = "__variable_sharding_rule__"


@contextlib.contextmanager
def shard_variables_along(axis, min_size=2 ** 14, dim=None):
    """Variables created in this scope are sharded over mesh axis ``axis``
    on their largest dimension (fsdp/ZeRO-3 layout) unless ``dim`` pins one.
    Small variables stay replicated (below ``min_size`` elements)."""
    g = ops_mod._root_graph()
    prev = g._scoped_state.get(_VS_KEY)
    g._scoped_state[_VS_KEY] = {"axis": axis, "min_size": min_size,
                                "dim": dim}
    try:
        yield
    finally:
        if prev is None:
            g._scoped_state.pop(_VS_KEY, None)
        else:
            g._scoped_state[_VS_KEY] = prev


def _auto_spec_for(shape, rule, mesh):
    if rule is None or mesh is None:
        return None
    dims = [int(d) for d in shape]
    n = 1
    for d in dims:
        n *= d
    if n < rule["min_size"] or not dims:
        return None
    axis = rule["axis"]
    size = mesh.axis_size(axis) if axis in mesh.shape else None
    if size is None:
        return None
    dim = rule["dim"]
    if dim is None:
        # largest dim divisible by the axis size
        cands = [i for i, d in enumerate(dims) if d % size == 0]
        if not cands:
            return None
        dim = max(cands, key=lambda i: dims[i])
    spec = [None] * len(dims)
    spec[dim] = axis
    return P(*spec)


def auto_shard_variable(variable, axis, min_size=2 ** 14, dim=None,
                        mesh=None):
    """Shard ``variable`` over ``axis`` on its largest divisible dim (ZeRO
    layout); no-op for small/indivisible shapes. Public entry used by
    FSDP.shard_existing and the scope rule."""
    mesh = mesh or current_mesh()
    spec = _auto_spec_for(variable.shape.as_list(),
                          {"axis": axis, "min_size": min_size, "dim": dim},
                          mesh)
    if spec is not None:
        variable.set_sharding(spec)
    return variable


def maybe_apply_variable_sharding(variable):
    """Called by Variable.__init__; applies the active scope rule."""
    g = variable.graph
    rule = g._scoped_state.get(_VS_KEY)
    mesh = current_mesh()
    if rule is not None and mesh is not None and variable.sharding is None:
        spec = _auto_spec_for(variable.shape.as_list(), rule, mesh)
        if spec is not None:
            variable.set_sharding(spec)


def shard_variable(variable, *spec):
    variable.set_sharding(P(*spec))
    return variable


def shard_feed(placeholder, *spec):
    """Annotate a placeholder so Session shards the fed batch over the mesh
    (e.g. shard_feed(x, 'dp') splits dim 0 across data-parallel devices)."""
    placeholder.op.attrs["sharding"] = P(*spec)
    return placeholder


def _lower_sharding_constraint(ctx, op, inputs):
    import jax

    mesh = current_mesh()
    spec = op.attrs["spec"]
    if mesh is None:
        return [inputs[0]]
    ns = jax.sharding.NamedSharding(mesh.jax_mesh, spec.to_jax()
                                    if isinstance(spec, PartitionSpec)
                                    else jax.sharding.PartitionSpec(*spec))
    return [jax.lax.with_sharding_constraint(inputs[0], ns)]


op_registry.register("ShardingConstraint", lower=_lower_sharding_constraint)


def with_sharding_constraint(tensor, *spec, name=None):
    """Pin an activation's layout (→ lax.with_sharding_constraint). The
    classic uses: batch axis on 'dp', hidden on 'tp' after a sharded matmul,
    sequence on 'sp'."""
    t = ops_mod.convert_to_tensor(tensor)
    g = ops_mod.get_default_graph()
    op = g.create_op("ShardingConstraint", [t], attrs={"spec": P(*spec)},
                     name=name or "sharding_constraint",
                     output_specs=[(t.shape, t.dtype)])
    return op.outputs[0]


def num_devices() -> int:
    import jax

    return jax.device_count()


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_chief() -> bool:
    return process_index() == 0


# ---------------------------------------------------------------------------
# sharding propagation (stf.analysis.sharding; ISSUE 6)
# ---------------------------------------------------------------------------

def _sharding_constraint_rule(op, in_specs, ctx):
    from ..analysis import sharding as _shard

    t = op.outputs[0]
    spec = _shard.normalize_spec(op.attrs.get("spec"), t.shape.rank)
    if spec is None:
        return [in_specs[0]]
    ctx.require(0, spec)
    return [spec]


def _sharding_constraint_backward(op, out_specs, in_specs, ctx):
    # the constraint's spec propagates upstream through weakly-typed
    # producers, so a mid-graph constraint seeds both directions
    return [out_specs[0]]


_sharding_constraint_rule.backward = _sharding_constraint_backward
_sharding_constraint_rule.seeds_outputs = True
op_registry.register_sharding_rule("ShardingConstraint",
                                   _sharding_constraint_rule)


def match_partition_rules(rules, variable_store=None, on_missing="replicate",
                          apply=False, mesh=None):
    """Regex name-pattern -> PartitionSpec mapping over variables
    (SNIPPETS.md [2] exemplar: the fmengine/EasyLM idiom).

    ``rules``: sequence of ``(pattern, spec)`` pairs; the FIRST pattern
    to ``re.search`` a variable's store name wins. ``spec`` is a
    PartitionSpec-like (P(...), tuple, list — None entries replicate a
    dim). Scalars and single-element variables always replicate.

    ``variable_store``: where to find variables — a dict name->Variable,
    an iterable of Variables, or None for the default graph's global
    variables. ``on_missing``: "replicate" (default) maps unmatched
    variables to P(); "error" raises (the strict EasyLM contract);
    "skip" leaves them out of the result.

    Returns ``{store_name: spec}`` — exactly the ``seed_specs`` shape
    ``analysis.analyze_sharding`` takes, so a rule set can be CHECKED
    against the graph (collective bytes, lint findings) before paying a
    compile. ``apply=True`` also commits each matched spec via
    ``Variable.set_sharding`` (the Session then places state with it).
    """
    import re

    if variable_store is None:
        from ..ops import variables as variables_mod

        variable_store = variables_mod.global_variables()
    if isinstance(variable_store, dict):
        items = list(variable_store.items())
    else:
        items = []
        for v in variable_store:
            name = getattr(v, "var_name", None) or getattr(v, "name", "")
            items.append((name, v))
    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    out = {}
    for name, var in items:
        shape = getattr(var, "shape", None)
        dims = shape.as_list() if shape is not None and \
            shape.rank is not None else None
        n = 1
        for d in (dims or []):
            n *= (d or 1)
        if dims is not None and (len(dims) == 0 or n <= 1):
            out[name] = P()
            continue
        matched = None
        for rx, spec in compiled:
            if rx.search(name) is not None:
                matched = P(*spec) if not isinstance(spec, PartitionSpec) \
                    else spec
                break
        if matched is None:
            if on_missing == "error":
                raise ValueError(
                    f"match_partition_rules: no rule matches variable "
                    f"{name!r} (add a catch-all ('.*', P()) rule or pass "
                    "on_missing='replicate')")
            if on_missing == "skip":
                continue
            matched = P()
        out[name] = matched
        if apply and hasattr(var, "set_sharding"):
            var.set_sharding(matched)
    return out
