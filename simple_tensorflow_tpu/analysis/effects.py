"""Per-op effect resolution over the declared ``Effects`` sets.

The op registry declares effects per op *type* with resource selectors
(framework/op_registry.py ``Effects``); this module resolves them
against a concrete :class:`Operation`'s attrs into the
``ResolvedEffects`` the hazard detector and the debug CLI consume —
e.g. an ``Assign`` with ``attrs["var_name"] == "w"`` resolves to
``writes={"var_name=w"}``.
"""

from __future__ import annotations

from typing import Any, FrozenSet, Optional

from ..framework import op_registry

Effects = op_registry.Effects
NO_EFFECTS = op_registry.NO_EFFECTS


class ResolvedEffects:
    """Concrete effect instance of one Operation."""

    __slots__ = ("reads", "writes", "rng", "io", "update", "declared")

    def __init__(self, reads: FrozenSet[str], writes: FrozenSet[str],
                 rng: bool, io: bool, update: Optional[str],
                 declared: bool):
        self.reads = reads
        self.writes = writes
        self.rng = rng
        self.io = io
        self.update = update
        self.declared = declared

    def __bool__(self):
        return bool(self.reads or self.writes or self.rng or self.io)

    def describe(self) -> str:
        """Compact single-line rendering for CLIs/diagnostics, e.g.
        ``reads={var_name=w} writes={var_name=w}(add) rng``."""
        parts = []
        if self.reads:
            parts.append("reads={" + ",".join(sorted(self.reads)) + "}")
        if self.writes:
            w = "writes={" + ",".join(sorted(self.writes)) + "}"
            if self.update:
                w += f"({self.update})"
            parts.append(w)
        if self.rng:
            parts.append("rng")
        if self.io:
            parts.append("io")
        if not parts:
            return "pure"
        if not self.declared:
            parts.append("(synthesized)")
        return " ".join(parts)


_EMPTY = frozenset()


def op_effects(op: Any) -> ResolvedEffects:
    """Resolve the declared effect set of one Operation (unregistered op
    types resolve as pure — import-time registration is authoritative)."""
    try:
        od = op_registry.get(op.type)
    except KeyError:
        return ResolvedEffects(_EMPTY, _EMPTY, False, False, None, False)
    eff = od.effects
    if not eff:
        return ResolvedEffects(_EMPTY, _EMPTY, False, False, None,
                               od.effects_declared)
    return ResolvedEffects(
        eff.resolved_reads(op), eff.resolved_writes(op), eff.rng, eff.io,
        eff.update, od.effects_declared)


def commuting_writes(a: ResolvedEffects, b: ResolvedEffects) -> bool:
    """True when two writes to the same resource are order-independent:
    additive updates commute with each other (AssignAdd/AssignSub,
    ScatterAdd/ScatterSub), same-kind min/max updates are idempotent
    under reordering. Overwrites (update=None or "update") never
    commute with anything — the last writer wins."""
    if a.update in ("add", "sub") and b.update in ("add", "sub"):
        return True
    if a.update in ("mul", "div") and b.update in ("mul", "div"):
        return True
    if a.update in ("min", "max") and a.update == b.update:
        return True
    return False
