"""Loop-fusion safety certification for ``Session.run_steps``.

Multi-step fusion compiles N training steps into ONE XLA computation
(a ``lax.scan`` over device-staged batches, variables threaded through
the donated carry). That is only sound when the whole per-step plan
lives inside the device program: a host-stage op (queue dequeue,
iterator, py_func) would need Python between iterations, an EFFECTFUL
host sink would need per-step device->host transfers, and a
``Print``-style io op must fire once per step on the host schedule —
none of which exist inside a fused loop.

Two deliberate relaxations (the numerics-health plane, docs/DEBUG.md):

- **Pure host sinks** (``OpDef.host_sink_pure`` — summary ops) only
  OBSERVE device values, so under ``output_mode="last"`` the Session
  defers them to run ONCE on the window's final-step values instead of
  splitting the window. A device-side histogram in the train graph no
  longer costs the fusion. ``output_mode="stacked"`` still falls back
  (per-step serialization needs every step on the host).
- **CheckNumerics/Assert** ride the fused window's per-step ys and are
  inspected AFTER the window's state commit (post-commit detection,
  same contract as the numerics plane: recovery is checkpoint
  restore). The old ``numeric_check_op`` fusion blocker is retired.

This module classifies one compiled plan against those rules and
returns structured :class:`Diagnostic` objects (code
``loop_fusion/<reason>``, each naming the blocking op) so the Session
can fall back to the unfused path with an explanation instead of
miscompiling. The reasons double as the label on the
``/stf/session/loop_fusion_fallbacks`` counter (docs/PERFORMANCE.md).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Set

from . import diagnostics as diag_mod
from .effects import op_effects

# fallback reason labels (the counter's label vocabulary; the historic
# numeric_check_op reason is retired — checks now fuse, see module doc)
HOST_STAGE_OP = "host_stage_op"
HOST_SINK_OP = "host_sink_op"
HOST_EFFECTFUL_OP = "host_effectful_op"
NO_DEVICE_STAGE = "no_device_stage"
UNINITIALIZED_WRITE = "uninitialized_write"


def _written_var_names(device_ops: Sequence[Any]) -> Set[str]:
    """Variable store keys the plan assigns (from declared effects)."""
    names: Set[str] = set()
    for op in device_ops:
        for w in op_effects(op).writes:
            if w.startswith("var_name="):
                names.add(w.split("=", 1)[1])
    return names


def uninitialized_write_diag(missing: Sequence[str]) -> diag_mod.Diagnostic:
    """The store-dependent certification failure: the plan assigns
    variables with no initial device value to thread through the carry.
    Factored out so the Session can cache the plan-static certification
    and re-check only this part as the store fills."""
    return diag_mod.Diagnostic(
        diag_mod.ERROR, f"loop_fusion/{UNINITIALIZED_WRITE}",
        "the plan assigns variable(s) not yet in the session's "
        f"variable store ({', '.join(list(missing)[:5])}): the loop "
        "carry needs an initial device value for every threaded "
        "variable (run the initializer unfused first)")


def certify_plan(device_ops: Sequence[Any],
                 host_plan: Sequence[Any],
                 post_host_plan: Sequence[Any],
                 variable_store: Optional[Iterable[str]] = (),
                 ) -> List[diag_mod.Diagnostic]:
    """Certify one compiled Session plan as loop-fusable.

    Returns an empty list when the plan may be compiled into a fused
    N-step loop; otherwise one ERROR diagnostic per blocking op (code
    ``loop_fusion/<reason>``). The caller (Session.run_steps) treats a
    non-empty result as "fall back to N sequential runs".
    ``variable_store=None`` skips the store-dependent uninitialized-
    write check (callers that cache the plan-static result re-check it
    via :func:`uninitialized_write_diag`).
    """
    diags: List[diag_mod.Diagnostic] = []

    def block(reason: str, op: Any, why: str):
        diags.append(diag_mod.Diagnostic(
            diag_mod.ERROR, f"loop_fusion/{reason}",
            f"op {op.name!r} ({op.type}) prevents multi-step fusion: "
            f"{why}", op=op))

    if not device_ops:
        diags.append(diag_mod.Diagnostic(
            diag_mod.ERROR, f"loop_fusion/{NO_DEVICE_STAGE}",
            "the plan has no device stage — nothing to fuse (host-only "
            "or constant-folded fetches)"))
        return diags
    for op in host_plan:
        if op.type == "Const":
            continue  # consts staged for host consumers are pure values
        block(HOST_STAGE_OP, op,
              "it runs in the host stage (Python) before the device "
              "program, so each iteration would need a host round-trip")
    for op in post_host_plan:
        if getattr(op.op_def, "host_sink_pure", False):
            # pure observers (summary ops): deferred by the Session to
            # run once per window on last-step values — never a blocker
            continue
        block(HOST_SINK_OP, op,
              "it is an effectful host sink consuming device results "
              "(handle-style op) and would need a per-step device->host "
              "transfer")
    missing: List[str] = []
    if variable_store is not None:
        store = set(variable_store)
        missing = sorted(n for n in _written_var_names(device_ops)
                         if n not in store)
    for op in device_ops:
        eff = op_effects(op)
        if eff.io:
            block(HOST_EFFECTFUL_OP, op,
                  "it has a declared host-observable io effect that must "
                  "fire once per step")
    if missing:
        diags.append(uninitialized_write_diag(missing))
    return diags


def stacked_host_sink_diag(post_host_plan: Sequence[Any]
                           ) -> diag_mod.Diagnostic:
    """``output_mode="stacked"`` with pure host sinks still falls back:
    serializing a summary PER STEP needs every step's values on the
    host, which the once-per-window deferred stage cannot provide."""
    names = [op.name for op in post_host_plan
             if getattr(op.op_def, "host_sink_pure", False)][:5]
    return diag_mod.Diagnostic(
        diag_mod.ERROR, f"loop_fusion/{HOST_SINK_OP}",
        "output_mode='stacked' needs host sink op(s) "
        f"({', '.join(names)}) to run once per step; pure sinks defer "
        "only under output_mode='last'")


def fallback_reasons(diags: Sequence[diag_mod.Diagnostic]) -> List[str]:
    """Distinct ``<reason>`` labels from certify_plan diagnostics, in
    first-seen order (the counter labels)."""
    seen: Dict[str, None] = {}
    for d in diags:
        seen.setdefault(d.code.split("/", 1)[1], None)
    return list(seen)
