"""Auto-sharding search: flip ``stf.analysis.sharding`` from
descriptive to prescriptive (ISSUE 14 tentpole).

PR 6 built the measurement: per-edge resharding collectives,
trip-weighted and byte-sized to match the HLO XLA emits (0.998
predicted/harvested on the dp8 bench). Users still hand-placed every
PartitionSpec — exactly the placement problem TensorFlow left to users
(1605.08695 §3.2), and the MLPerf-pod study attributes most lost pod
efficiency to getting it wrong (1909.09756). This module uses the cost
model we already trust to *choose* the specs:

- **Search space** — variables grouped by name shape
  (``layer_3/kernel`` -> ``layer_\\d+/kernel``, the
  ``match_partition_rules`` idiom, SNIPPETS.md [2]) plus the plan's fed
  placeholders, each group assigned one PartitionSpec over the mesh-axis
  factorization. Axis *roles* bound the space: data axes (``dp``)
  shard feeds, model axes (``tp``/``sp``/``ep``) shard weights,
  ``fsdp`` shards both — the canonical-axis semantics of
  ``parallel.mesh.CANONICAL_AXES``; ``candidates="free"`` lifts the
  restriction.

- **Objective** — one incremental analyzer sweep per candidate
  (``sharding._Engine``: seed -> forward -> recording forward): a
  roofline-shaped predicted step time of per-device compute
  (op FLOPs / output shard factor; SymbolicGradient priced as 2x its
  forward slice at the slice's own shard factors), per-device HBM
  traffic, and trip-weighted collective bytes over the interconnect —
  plus per-shard peak HBM from ``cost_model.estimate(shard_factor_fn=)``
  with an infeasibility penalty when a device-memory budget (the PR 13
  ledger's admission budget) is given.

- **Search** — greedy per-group descent in descending group-byte order
  (two passes), then a seeded simulated-annealing refinement; every
  priced assignment is memoized, the whole search is deterministic.

- **Output** — an :class:`AutoshardResult`: a diffable JSON rule set
  (``match_partition_rules`` / ``graph_lint --rules`` format), feed
  specs, and activation *cut points* — the largest sharded
  intermediates of the winning layout, committed as first-class
  ``ShardingConstraint`` graph ops so GSPMD's propagation lands on the
  layout the search priced (SNIPPETS.md [3]).

Entry points: :func:`search_sharding` (offline: graph or op list +
abstract mesh — no devices needed), ``stf.parallel.auto_shard`` (search
+ apply to the live graph), ``ConfigProto(auto_shard=True)`` (Session
searches the first fed plan and applies the winner before compile),
``graph_lint --mesh ... --autoshard [--emit-rules]`` (offline CLI), and
the model-zoo gate's rule-set snapshots.
"""

from __future__ import annotations

import itertools
import json
import math
import os
import random
import re
import time
from dataclasses import dataclass, field
from typing import (Any, Dict, List, Optional, Sequence, Set, Tuple)

from ..framework import graph as ops_mod
from ..platform import monitoring
from . import sharding as shard_mod

Tensor = ops_mod.Tensor
Operation = ops_mod.Operation

# -- monitoring (docs/OBSERVABILITY.md "Auto-sharding") ----------------------

metric_autoshard_seconds = monitoring.Sampler(
    "/stf/analysis/autoshard_seconds",
    monitoring.ExponentialBuckets(1e-4, 4.0, 16),
    "auto-sharding search wall seconds per invocation")
metric_autoshard_candidates = monitoring.Counter(
    "/stf/analysis/autoshard_candidates",
    "assignments priced by the auto-sharding search", "phase")
metric_autoshard_bytes = monitoring.IntGauge(
    "/stf/analysis/autoshard_predicted_bytes",
    "predicted per-step collective bytes of the last search", "layout")

# Interconnect bandwidth used to weight collective bytes against
# per-device compute/HBM time in the objective. A *relative* weight —
# the search only compares candidates — defaulting to 1/8 of HBM
# bandwidth (TPU ICI links run roughly an order below HBM).
_ICI_FRACTION_OF_HBM = 8.0

# data-parallel-shaped axis names shard the fed batch; everything else
# (tp/sp/ep/pp and custom names) shards weights; fsdp shards both
# (parallel/mesh.py CANONICAL_AXES semantics)
_DATA_AXES = ("dp", "batch", "data", "b")
_BOTH_AXES = ("fsdp",)

_SKIP_SOURCE_TYPES = ("VariableV2", "ReadVariable", "Placeholder",
                      "PlaceholderWithDefault", "Const", "NoOp",
                      "ShardingConstraint")


def group_pattern(name: str) -> str:
    """Collapse digit runs so structurally identical variables share one
    rule: ``block3/conv_12/kernel`` -> ``block\\d+/conv_\\d+/kernel``."""
    return re.sub(r"\d+", r"\\d+", name)


def _anchored(pattern: str) -> str:
    return f"^{pattern}$"


@dataclass
class _Group:
    """One searchable unit: a set of same-pattern variables (or one
    placeholder pattern) assigned a single spec."""

    pattern: str
    kind: str                       # "var" | "feed"
    names: List[str] = field(default_factory=list)
    dims_list: List[List[Optional[int]]] = field(default_factory=list)
    nbytes: float = 0.0
    candidates: List[Tuple] = field(default_factory=list)  # internal specs
    chosen: int = 0                 # index into candidates


@dataclass
class AutoshardResult:
    """Winning layout + the numbers that justified it."""

    mesh_axes: Dict[str, int]
    var_specs: Dict[str, Tuple] = field(default_factory=dict)
    feed_specs: Dict[str, Tuple] = field(default_factory=dict)
    # (tensor_name, jax-style spec, nbytes); live Tensor kept separately
    cuts: List[Tuple[str, Tuple, float]] = field(default_factory=list)
    groups: List[Dict[str, Any]] = field(default_factory=list)
    predicted: Dict[str, Any] = field(default_factory=dict)
    baseline: Dict[str, Any] = field(default_factory=dict)
    search_seconds: float = 0.0
    candidates_priced: int = 0
    _cut_tensors: List[Tuple[Any, Tuple]] = field(default_factory=list)

    # -- serialization -------------------------------------------------------
    def rules(self) -> List[List[Any]]:
        """The winning variable rule set in ``match_partition_rules`` /
        ``graph_lint --rules`` format: ``[[pattern, [entries...]],
        ...]`` with a trailing catch-all replicate rule. Diffable,
        JSON-able, re-checkable before a compile."""
        out = []
        # exact-name keys (rank-collision fallbacks) first: match is
        # first-wins, so they must shadow the broader \d+ patterns
        for pat in sorted(self.var_specs,
                          key=lambda p: ("\\d+" in p, p)):
            out.append([_anchored(pat),
                        [list(e) if isinstance(e, tuple) else e
                         for e in self.var_specs[pat]]])
        out.append([".*", []])
        return out

    def seed_specs(self) -> Dict[str, Any]:
        """Per-name seeds in exactly the shape
        ``analysis.analyze_sharding(seed_specs=)`` takes."""
        seeds: Dict[str, Any] = {}
        for g in self.groups:
            spec = g["spec"]
            for name in g["members"]:
                seeds[name] = tuple(spec)
        return seeds

    def to_json(self) -> str:
        return json.dumps({
            "mesh": dict(self.mesh_axes),
            "rules": self.rules(),
            "feeds": {k: [list(e) if isinstance(e, tuple) else e
                          for e in v]
                      for k, v in sorted(self.feed_specs.items())},
            "cuts": [[n, [list(e) if isinstance(e, tuple) else e
                          for e in s], b] for n, s, b in self.cuts],
            "predicted": self.predicted,
            "baseline": self.baseline,
            "search_seconds": round(self.search_seconds, 4),
            "candidates_priced": self.candidates_priced,
        }, indent=1, sort_keys=True)

    # -- application ---------------------------------------------------------
    def apply(self, graph=None, emit_constraints: bool = True) -> int:
        """Commit the winning layout to the live graph: declared
        variable shardings (``Variable.set_sharding``), feed-placeholder
        shardings (the ``shard_feed`` attr), and — for each searched cut
        point — a first-class committing ``ShardingConstraint`` op the
        Session splices into every plan that produces the cut tensor.
        Explicit user-placed specs are never overridden. Returns the
        number of annotations applied."""
        from ..parallel.mesh import P

        graph = graph or ops_mod.get_default_graph()
        root = graph
        while getattr(root, "outer_graph", None) is not None:
            root = root.outer_graph
        registry = root._scoped_state.get("__vars_by_store_name__", {})
        seeds = self.seed_specs()  # member NAME -> jax-style spec
        applied = 0
        for name, var in registry.items():
            spec = seeds.get(name)
            if spec is None or getattr(var, "sharding", None) is not None:
                continue
            if shard_mod.is_replicated(spec):
                # explicit replication still places the buffer on the
                # mesh (one copy per device) instead of leaving it
                # committed to a single device — the difference between
                # "GSPMD broadcasts the weights every step" and "they
                # are already everywhere"
                var.set_sharding(P())
                applied += 1
                continue
            var.set_sharding(P(*spec))
            applied += 1
        for op in graph.get_operations():
            if op.type not in ("Placeholder", "PlaceholderWithDefault"):
                continue
            spec = self.feed_specs.get(op.name)
            if spec is None or op.attrs.get("sharding") is not None:
                continue
            op.attrs["sharding"] = P(*spec)
            applied += 1
        if emit_constraints:
            applied += self.emit_constraints(graph)
        return applied

    def emit_constraints(self, graph=None) -> int:
        """Create one committing ``ShardingConstraint`` op per cut point
        and register it on the graph; ``Session._plan`` splices each
        into any plan that produces its input tensor (right after the
        producer), where its lowering rebinds the traced value — every
        downstream consumer then reads the constrained value, so the
        layout the search priced is the layout GSPMD commits."""
        from ..parallel import api as api_mod

        graph = graph or ops_mod.get_default_graph()
        reg = graph._scoped_state.setdefault(
            "__autoshard_constraints__", {})
        n = 0
        for tensor, spec in self._cut_tensors:
            if tensor in reg:
                continue
            reg[tensor] = api_mod.emit_commit_constraint(tensor, spec)
            n += 1
        return n


# ---------------------------------------------------------------------------
# candidate generation
# ---------------------------------------------------------------------------

def _axis_roles(mesh_axes: Dict[str, int], mode: str
                ) -> Tuple[List[str], List[str]]:
    """(feed_axes, var_axes) allowed to shard each group kind."""
    live = [a for a, s in mesh_axes.items() if int(s) > 1]
    if mode == "free":
        return list(live), list(live)
    feed = [a for a in live if a in _DATA_AXES or a in _BOTH_AXES
            or a == "sp"]
    var = [a for a in live
           if a not in _DATA_AXES or a in _BOTH_AXES]
    return feed, var


def _spec_candidates(dims_list: Sequence[Sequence[Optional[int]]],
                     axes: Sequence[str],
                     mesh_axes: Dict[str, int],
                     cap: int = 64) -> List[Tuple]:
    """Enumerate internal specs assigning each allowed axis to one
    divisible dim (or to none). Unknown dims accept any axis (the
    uneven-shard lint polices them at runtime); multi-axis dims must
    divide by the axis-size product. Always includes replicated."""
    if not dims_list:
        return [()]
    rank = len(dims_list[0])
    per_axis: List[List[Optional[int]]] = []
    for ax in axes:
        size = int(mesh_axes.get(ax, 1))
        opts: List[Optional[int]] = [None]
        for d in range(rank):
            ok = True
            for dims in dims_list:
                v = dims[d] if d < len(dims) else None
                if v is not None and (v < size or v % size != 0):
                    ok = False
                    break
            if ok:
                opts.append(d)
        per_axis.append(opts)
    out: List[Tuple] = []
    seen: Set[Tuple] = set()
    for combo in itertools.product(*per_axis):
        entries: List[Tuple[str, ...]] = [() for _ in range(rank)]
        for ax, d in zip(axes, combo):
            if d is not None:
                entries[d] = entries[d] + (ax,)
        spec = tuple(entries)
        # multi-axis dims must divide by the product of their sizes
        ok = True
        for d, e in enumerate(spec):
            if len(e) < 2:
                continue
            f = 1
            for a in e:
                f *= int(mesh_axes.get(a, 1))
            for dims in dims_list:
                v = dims[d] if d < len(dims) else None
                if v is not None and (v < f or v % f != 0):
                    ok = False
                    break
            if not ok:
                break
        if ok and spec not in seen:
            seen.add(spec)
            out.append(spec)
        if len(out) >= cap:
            break
    if ((),) * rank not in seen:
        out.insert(0, ((),) * rank)
    return out


def _dtype_size(x, default=4) -> int:
    try:
        return int(x.dtype.base_dtype.size)
    except Exception:
        return default


# ---------------------------------------------------------------------------
# the pricer: one incremental analyzer sweep per candidate
# ---------------------------------------------------------------------------

class _Pricer:
    """Prices one spec assignment: analyzer sweep for collective edges
    and the per-tensor shard factors, then a roofline-shaped predicted
    step time. Raw per-op FLOPs/bytes are computed once and reused
    across every candidate (only the shard factors move)."""

    def __init__(self, ops: Sequence[Operation], mesh_axes: Dict[str, int],
                 fetches=None, feeds: Sequence[Any] = (),
                 budget_bytes: Optional[int] = None):
        from ..framework import cost_model
        from ..utils import perf

        self.ops = list(ops)
        self.mesh_axes = dict(mesh_axes)
        self.fetches = fetches
        self.feeds = list(feeds)
        self.budget_bytes = budget_bytes
        self._raw: Dict[Operation, Tuple[float, float]] = {}
        self._grad_paths: Dict[Operation, List[Operation]] = {}
        for op in self.ops:
            if op.type == "SymbolicGradient":
                self._grad_paths[op] = self._grad_path(op)
                continue
            try:
                self._raw[op] = (cost_model._op_flops(op),
                                 cost_model._op_bytes_dispatch(op))
            except Exception:
                self._raw[op] = (0.0, 0.0)
        peak_flops, peak_bw = perf.chip_spec()
        self.peak_flops = float(peak_flops)
        self.peak_bw = float(peak_bw)
        self.ici_bw = float(os.environ.get(
            "STF_AUTOSHARD_ICI_BW",
            self.peak_bw / _ICI_FRACTION_OF_HBM))
        self.cache: Dict[Tuple, Dict[str, Any]] = {}

    def _grad_path(self, op: Operation) -> List[Operation]:
        from ..framework import lowering as lowering_mod

        n_ys = op.attrs.get("n_ys", 1)
        n_xs = op.attrs.get("n_xs", 1)
        try:
            path_ops, _ = lowering_mod.ancestors_between(
                list(op.inputs[n_ys:n_ys + n_xs]),
                list(op.inputs[:n_ys]))
            return list(path_ops)
        except Exception:
            return []

    def price(self, seed_specs: Dict[str, Any], key: Optional[Tuple] = None,
              with_peak: Optional[bool] = None) -> Dict[str, Any]:
        if key is not None:
            hit = self.cache.get(key)
            if hit is not None:
                return hit
        from ..framework import cost_model

        engine = shard_mod._Engine(self.mesh_axes, seed_specs=seed_specs)
        engine.seed(self.ops)
        engine.forward(self.ops)
        engine.forward(self.ops, record=True)
        env = engine.env

        def factor_of(t) -> int:
            hit = env.get(t)
            if hit is None:
                return 1
            return shard_mod.shard_factor(hit[0], self.mesh_axes)

        flops_s = 0.0
        hbm_s = 0.0
        for op in self.ops:
            if op.type == "SymbolicGradient":
                fl = by = 0.0
                for p in self._grad_paths[op]:
                    rf, rb = self._raw.get(p) or (
                        cost_model._op_flops(p),
                        cost_model._op_bytes_dispatch(p))
                    f = factor_of(p.outputs[0]) if p.outputs else 1
                    fl += rf / f
                    by += rb / f
                fl *= 2.0
                by *= 2.0
            else:
                rf, rb = self._raw[op]
                f = factor_of(op.outputs[0]) if op.outputs else 1
                fl = rf / f
                by = rb / f
            flops_s += fl
            hbm_s += by
        comm = sum(e.total_bytes for e in engine.report.collective_edges())
        seconds = (flops_s / max(self.peak_flops, 1.0)
                   + hbm_s / max(self.peak_bw, 1.0)
                   + comm / max(self.ici_bw, 1.0))
        peak = None
        if with_peak is None:
            with_peak = self.budget_bytes is not None
        if with_peak and self.fetches:
            try:
                est = cost_model.estimate(
                    self.fetches, feeds=self.feeds,
                    shard_factor_fn=factor_of)
                peak = float(est.peak_bytes)
            except Exception:
                peak = None
        cost = seconds
        over_budget = bool(self.budget_bytes and peak is not None
                           and peak > self.budget_bytes)
        if over_budget:
            # infeasible layouts lose to any feasible one but still
            # order among themselves (a fully-infeasible search space
            # returns the least-bad layout + a budget failure flag)
            cost += 1e6 * (peak / float(self.budget_bytes))
        result = {
            "cost": cost, "seconds": seconds,
            "collective_bytes": comm,
            "per_shard_peak_bytes": peak,
            "over_budget": over_budget,
            "engine": engine,
        }
        if key is not None:
            self.cache[key] = result
        return result


# ---------------------------------------------------------------------------
# the search
# ---------------------------------------------------------------------------

def _collect_groups(ops: Sequence[Operation], mesh_axes: Dict[str, int],
                    rules, candidates: str, cap: int,
                    feeds: Sequence[Any] = ()
                    ) -> Tuple[List[_Group], Dict[str, Any]]:
    """Build the searchable groups (vars by collapsed-name pattern, fed
    placeholders — in-plan or on the fed boundary) and the fixed seeds
    (user-declared shardings, which the search never overrides)."""
    feed_axes, var_axes = _axis_roles(mesh_axes, candidates)
    fixed: Dict[str, Any] = {}
    var_shapes: Dict[str, Tuple[List[Optional[int]], int, Any]] = {}

    root = None
    for op in ops:
        g = op.graph
        while getattr(g, "outer_graph", None) is not None:
            g = g.outer_graph
        root = g
        break
    registry = (root._scoped_state.get("__vars_by_store_name__", {})
                if root is not None else {})
    plan_var_names = set()
    for op in ops:
        if op.type in ("VariableV2", "ReadVariable"):
            vn = op.attrs.get("var_name", op.name)
            plan_var_names.add(vn)
    for name, var in registry.items():
        if plan_var_names and name not in plan_var_names:
            continue
        try:
            shape = var.shape
            if shape.rank is None:
                continue
            dims = [d.value for d in shape.dims]
        except Exception:
            continue
        if getattr(var, "sharding", None) is not None:
            fixed[name] = var.sharding
            continue
        var_shapes[name] = (dims, _dtype_size(var), var)
    # VariableV2 ops without a python Variable wrapper (imported graphs)
    for op in ops:
        if op.type != "VariableV2" or not op.outputs:
            continue
        vn = op.attrs.get("var_name", op.name)
        if vn in var_shapes or vn in fixed:
            continue
        if op.attrs.get("sharding") is not None:
            fixed[vn] = op.attrs["sharding"]
            continue
        t = op.outputs[0]
        if t.shape.rank is None:
            continue
        var_shapes[vn] = ([d.value for d in t.shape.dims],
                          _dtype_size(t), None)

    compiled_rules = []
    for pat, spec in (rules or []):
        compiled_rules.append((re.compile(pat), spec))

    by_pattern: Dict[Tuple[str, int], _Group] = {}
    for name, (dims, dsize, _var) in sorted(var_shapes.items()):
        n = 1
        for d in dims:
            n *= (d or 1)
        if len(dims) == 0 or n <= 1:
            fixed[name] = ()
            continue
        pat = group_pattern(name)
        g = by_pattern.get((pat, len(dims)))
        if g is None:
            g = by_pattern[(pat, len(dims))] = _Group(pat, "var")
        g.names.append(name)
        g.dims_list.append(dims)
        g.nbytes += float(n * dsize)
    groups = list(by_pattern.values())

    feed_groups: Dict[Tuple[str, int], _Group] = {}
    feed_ops = [op for op in ops
                if op.type in ("Placeholder", "PlaceholderWithDefault")]
    # fed placeholders are PRUNED out of a per-run plan (the feed is
    # the boundary): pick them up from the feed set directly
    seen_feed_ops = set(feed_ops)
    for t in feeds:
        top = getattr(t, "op", None)
        if top is not None and top not in seen_feed_ops and \
                top.type in ("Placeholder", "PlaceholderWithDefault"):
            seen_feed_ops.add(top)
            feed_ops.append(top)
    for op in feed_ops:
        if op.attrs.get("sharding") is not None:
            fixed[op.name] = op.attrs["sharding"]
            continue
        if not op.outputs:
            continue
        t = op.outputs[0]
        if t.shape.rank is None or t.shape.rank == 0:
            continue
        dims = [d.value for d in t.shape.dims]
        pat = group_pattern(op.name)
        g = feed_groups.get((pat, len(dims)))
        if g is None:
            g = feed_groups[(pat, len(dims))] = _Group(pat, "feed")
        g.names.append(op.name)
        g.dims_list.append(dims)
        n = 1
        for d in dims:
            n *= (d or 1)
        g.nbytes += float(n * _dtype_size(t))
    groups.extend(feed_groups.values())

    for g in groups:
        axes = feed_axes if g.kind == "feed" else var_axes
        g.candidates = _spec_candidates(g.dims_list, axes, mesh_axes,
                                        cap=cap)
        # rule-seeded candidate + starting point (fmengine/EasyLM idiom)
        for rx, spec in compiled_rules:
            if any(rx.search(n) for n in g.names):
                cand = shard_mod.normalize_spec(spec, len(g.dims_list[0]))
                if cand is not None:
                    if cand not in g.candidates:
                        g.candidates.append(cand)
                    g.chosen = g.candidates.index(cand)
                break
    return groups, fixed


def _assignment_seeds(groups: List[_Group], fixed: Dict[str, Any]
                      ) -> Dict[str, Any]:
    seeds = dict(fixed)
    for g in groups:
        spec = g.candidates[g.chosen]
        for name in g.names:
            seeds[name] = spec
    return seeds


def search_sharding(graph=None, ops: Optional[Sequence[Operation]] = None,
                    mesh=None, fetches=None, feeds: Sequence[Any] = (),
                    rules=None, budget_bytes: Optional[int] = None,
                    candidates: str = "named",
                    anneal_steps: int = 48,
                    time_budget_s: Optional[float] = None,
                    cut_points: int = 4,
                    cut_min_bytes: Optional[int] = None,
                    candidate_cap: int = 64,
                    seed: int = 0) -> AutoshardResult:
    """Search PartitionSpec assignments for the variable store + plan
    inputs of ``ops`` (default: the whole graph) over ``mesh`` and
    return the priced winner. Deterministic for fixed inputs.

    ``rules``: optional ``match_partition_rules``-style seed rules —
    matched groups start (and stay searchable) from the matched spec.
    ``budget_bytes``: per-shard peak-HBM admission budget (the PR 13
    ledger budget); layouts over it are infeasible.
    ``candidates``: "named" (axis roles: dp shards feeds, tp/ep shard
    weights, fsdp both) or "free" (every axis everywhere).
    """
    t0 = time.perf_counter()
    if mesh is None:
        from ..parallel import mesh as mesh_mod

        mesh = mesh_mod.current_mesh()
    mesh_axes = shard_mod._as_mesh_axes(mesh)
    if graph is None and ops is None:
        graph = ops_mod.get_default_graph()
    if ops is None:
        ops = graph.get_operations()
    ops = list(ops)
    shard_mod._tls.dims_cache = {}

    groups, fixed = _collect_groups(ops, mesh_axes, rules, candidates,
                                    candidate_cap, feeds=feeds)
    pricer = _Pricer(ops, mesh_axes, fetches=fetches, feeds=feeds,
                     budget_bytes=budget_bytes)

    def assignment_key() -> Tuple:
        return tuple(g.chosen for g in groups)

    def price_current(phase: str) -> Dict[str, Any]:
        metric_autoshard_candidates.get_cell(phase).increase_by(1)
        return pricer.price(_assignment_seeds(groups, fixed),
                            key=assignment_key())

    def out_of_time() -> bool:
        return (time_budget_s is not None
                and time.perf_counter() - t0 > time_budget_s)

    # replicated baseline: every searchable group at its replicated
    # candidate (index of the all-() spec, which _spec_candidates
    # guarantees present)
    saved = [g.chosen for g in groups]
    for g in groups:
        g.chosen = g.candidates.index(((),) * len(g.dims_list[0]))
    baseline = price_current("baseline")
    for g, c in zip(groups, saved):
        g.chosen = c

    best = price_current("greedy")
    best_key = assignment_key()

    # -- greedy descent ------------------------------------------------------
    order = sorted(range(len(groups)), key=lambda i: -groups[i].nbytes)
    for _sweep in range(2):
        changed = False
        for gi in order:
            g = groups[gi]
            if out_of_time():
                break
            cur = g.chosen
            for ci in range(len(g.candidates)):
                if ci == cur:
                    continue
                g.chosen = ci
                r = price_current("greedy")
                if r["cost"] < best["cost"] - 1e-12:
                    best, best_key, cur = r, assignment_key(), ci
                    changed = True
            g.chosen = cur
        if not changed or out_of_time():
            break

    # -- simulated-annealing refinement --------------------------------------
    rng = random.Random(seed)
    searchable = [g for g in groups if len(g.candidates) > 1]
    if searchable and anneal_steps > 0:
        cur_cost = best["cost"]
        t_scale = max(abs(cur_cost), 1e-12) * 0.05
        for step in range(anneal_steps):
            if out_of_time():
                break
            temp = t_scale * (1.0 - step / float(anneal_steps)) + 1e-15
            g = rng.choice(searchable)
            old = g.chosen
            g.chosen = rng.randrange(len(g.candidates))
            if g.chosen == old:
                continue
            r = price_current("anneal")
            delta = r["cost"] - cur_cost
            if delta <= 0 or rng.random() < math.exp(-delta / temp):
                cur_cost = r["cost"]
                if r["cost"] < best["cost"] - 1e-12:
                    best, best_key = r, assignment_key()
            else:
                g.chosen = old
    for g, ci in zip(groups, best_key):
        g.chosen = ci
    # final winner price: reuse the search's memoized entry when it
    # already carries the peak (budget-aware searches price peak on
    # every candidate); otherwise one fresh sweep with peak on so the
    # reported per-shard bytes are populated
    want_peak = bool(fetches)
    winner = pricer.cache.get(best_key)
    if winner is None or (want_peak
                          and winner["per_shard_peak_bytes"] is None):
        winner = pricer.price(_assignment_seeds(groups, fixed),
                              with_peak=want_peak)

    # -- package -------------------------------------------------------------
    result = AutoshardResult(mesh_axes=dict(mesh_axes))
    # a collapsed pattern shared by groups of DIFFERENT rank (or by a
    # fixed/user-declared variable) cannot carry one rule — the regex
    # would commit a wrong-rank spec on the other members. Such groups
    # fall back to exact-name keys (rules() orders them first).
    var_pat_ranks: Dict[str, set] = {}
    for g in groups:
        if g.kind == "var":
            var_pat_ranks.setdefault(g.pattern, set()).add(
                len(g.dims_list[0]))
    fixed_pat_count: Dict[str, int] = {}
    for name in fixed:
        p = group_pattern(name)
        fixed_pat_count[p] = fixed_pat_count.get(p, 0) + 1
    for g in groups:
        spec = g.candidates[g.chosen]
        jspec = shard_mod.to_partition_spec(spec) or ()
        entry = {"pattern": g.pattern, "kind": g.kind,
                 "members": list(g.names), "bytes": g.nbytes,
                 "spec": list(jspec)}
        result.groups.append(entry)
        if g.kind == "var":
            if len(var_pat_ranks.get(g.pattern, ())) > 1 or \
                    g.pattern in fixed_pat_count:
                for name in g.names:
                    result.var_specs[re.escape(name)] = tuple(jspec)
            else:
                result.var_specs[g.pattern] = tuple(jspec)
        else:
            # feeds are few and looked up per op at apply() time: keep
            # them exact-name so same-pattern placeholders of different
            # rank can never swap specs
            for name in g.names:
                result.feed_specs[name] = tuple(jspec)
    # fixed (user-declared) specs ride along so rules() is complete;
    # fixed entries sharing a collapsed pattern with each other (their
    # specs/ranks may differ) or with a searched group go exact-name so
    # no entry can shadow another under one first-wins rule
    for name, spec in fixed.items():
        pat = group_pattern(name)
        per_name = pat in var_pat_ranks or fixed_pat_count[pat] > 1
        key = re.escape(name) if per_name else pat
        if key in result.var_specs or name in result.feed_specs:
            continue
        norm = shard_mod.normalize_spec(
            spec, len(spec) if hasattr(spec, "__len__") else None)
        result.var_specs[key] = tuple(
            shard_mod.to_partition_spec(norm) or ())

    env = winner["engine"].env
    op_set = set(ops)
    min_bytes = (shard_mod.LARGE_TENSOR_BYTES if cut_min_bytes is None
                 else int(cut_min_bytes))
    cut_cands = []
    for t, (spec, _strength) in env.items():
        if spec is None or shard_mod.is_replicated(spec):
            continue
        top = t.op
        if top not in op_set or top.type in _SKIP_SOURCE_TYPES:
            continue
        nb = shard_mod.tensor_bytes(t)
        if nb < min_bytes:
            continue
        cut_cands.append((nb, t, spec))
    cut_cands.sort(key=lambda x: (-x[0], x[1].name))
    for nb, t, spec in cut_cands[:max(int(cut_points), 0)]:
        jspec = shard_mod.to_partition_spec(spec)
        result.cuts.append((t.name, tuple(jspec), nb))
        result._cut_tensors.append((t, tuple(jspec)))

    result.predicted = {
        "collective_bytes": winner["collective_bytes"],
        "bytes_by_kind": winner["engine"].report.bytes_by_kind(),
        "per_shard_peak_bytes": winner["per_shard_peak_bytes"],
        "step_seconds": winner["seconds"],
        "over_budget": winner["over_budget"],
    }
    result.baseline = {
        "collective_bytes": baseline["collective_bytes"],
        "step_seconds": baseline["seconds"],
    }
    result.search_seconds = time.perf_counter() - t0
    result.candidates_priced = len(pricer.cache)
    metric_autoshard_seconds.get_cell().add(result.search_seconds)
    metric_autoshard_bytes.get_cell("searched").set(
        int(winner["collective_bytes"]))
    metric_autoshard_bytes.get_cell("replicated").set(
        int(baseline["collective_bytes"]))
    return result


# ---------------------------------------------------------------------------
# Serving/decode purpose: pick the decode tensor-parallel degree
# ---------------------------------------------------------------------------

@dataclass
class DecodeTpChoice:
    """Winner of :func:`choose_decode_tp`: the degree to pass to the
    generative models' ``tp=`` kwarg plus the priced candidate table
    (degree -> per-device cache bytes / per-token collective bytes /
    roofline seconds / feasibility) for statusz and tests."""

    degree: int
    seconds: float
    per_device_cache_bytes: int
    collective_bytes: int
    feasible: bool
    candidates: List[Dict[str, Any]] = field(default_factory=list)


def choose_decode_tp(*, num_heads: int, cache_bytes: int,
                     unsharded_bytes: int = 0,
                     collective_bytes_fn=None,
                     budget_bytes: Optional[int] = None,
                     mesh=None, max_degree: Optional[int] = None
                     ) -> DecodeTpChoice:
    """Serving/decode autoshard purpose: choose the decode
    tensor-parallel degree from the roofline objective + per-device
    cache-byte budget instead of a hand flag.

    The decode step is HBM-bound — every token re-reads the whole KV
    cache — so the objective per candidate degree ``t`` is the roofline
    pair the main search uses, specialized to the decode inner loop:
    per-device cache traffic ``(unsharded + sharded/t) / peak_bw`` plus
    per-token collective bytes over the interconnect
    (``collective_bytes_fn(t) / ici_bw``, the same
    ``STF_AUTOSHARD_ICI_BW``-overridable weight as :class:`_Pricer`),
    plus the same fixed infeasibility penalty when ``budget_bytes`` (the
    HBM-ledger admission budget) can't hold the per-device cache.

    Candidates are the divisors of ``num_heads`` (head-dim sharding is
    whole heads per device) capped by the device count — the mesh's
    ``tp`` axis when one is passed (that degree is then the only
    candidate: the device topology is already committed), else
    ``len(jax.devices())`` and ``max_degree``. Ties break toward the
    smallest degree (fewest devices for the same predicted time).
    """
    from ..utils import perf

    num_heads = int(num_heads)
    cache_bytes = int(cache_bytes)
    unsharded_bytes = int(unsharded_bytes)
    sharded = max(cache_bytes - unsharded_bytes, 0)
    if collective_bytes_fn is None:
        collective_bytes_fn = lambda t: 0

    if mesh is not None and getattr(mesh, "shape", {}).get("tp", 1) > 1:
        degrees = [int(mesh.shape["tp"])]
        if num_heads % degrees[0]:
            raise ValueError(
                f"mesh tp axis {degrees[0]} does not divide "
                f"num_heads={num_heads}")
    else:
        try:
            import jax

            cap = len(jax.devices())
        except Exception:
            cap = 1
        if max_degree is not None:
            cap = min(cap, int(max_degree))
        degrees = [t for t in range(1, max(cap, 1) + 1)
                   if num_heads % t == 0]

    peak_flops, peak_bw = perf.chip_spec()
    ici_bw = float(os.environ.get("STF_AUTOSHARD_ICI_BW",
                                  float(peak_bw) / _ICI_FRACTION_OF_HBM))
    rows = []
    for t in degrees:
        per_device = unsharded_bytes + sharded // t
        coll = int(collective_bytes_fn(t))
        seconds = per_device / float(peak_bw) + coll / ici_bw
        feasible = budget_bytes is None or per_device <= int(budget_bytes)
        if not feasible:
            seconds += 1e6          # same penalty as _Pricer.price
        rows.append({"degree": t, "per_device_cache_bytes": int(per_device),
                     "collective_bytes": coll, "seconds": seconds,
                     "feasible": feasible})
        metric_autoshard_candidates.get_cell("decode_tp").increase_by(1)
    best = min(rows, key=lambda r: (r["seconds"], r["degree"]))
    if not best["feasible"] and budget_bytes is not None:
        raise ValueError(
            f"no decode-tp degree fits device_memory_budget_bytes="
            f"{int(budget_bytes)}: smallest per-device cache is "
            f"{min(r['per_device_cache_bytes'] for r in rows)} bytes "
            f"(degrees tried: {degrees})")
    return DecodeTpChoice(
        degree=int(best["degree"]), seconds=float(best["seconds"]),
        per_device_cache_bytes=int(best["per_device_cache_bytes"]),
        collective_bytes=int(best["collective_bytes"]),
        feasible=bool(best["feasible"]), candidates=rows)
