"""Structured diagnostics for stf.analysis.

Every finding the static-analysis layer produces — verifier invariant
violations, variable hazards, lint smells — is a :class:`Diagnostic`:
a severity, a stable ``code`` ("verifier/dangling-input",
"hazard/raw", "lint/unseeded-rng"), a human message, and the offending
op's name/type plus the user-code ``file:line`` captured at op creation
(framework/graph.py traceback capture). The reference emits comparable
information as Status payloads from graph validation
(core/graph/validate.cc) but without source attribution; pointing at
user code is the whole point here — a bad graph must be debuggable
before a multi-second XLA compile, not after.

Emission is observable: every diagnostic constructed through
``report()`` bumps the ``/stf/analysis/diagnostics`` counter (labeled
by severity) so the monitoring layer (docs/OBSERVABILITY.md) covers the
analysis subsystem.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

from ..platform import monitoring

# -- severities --------------------------------------------------------------

NOTE = "note"
WARNING = "warning"
ERROR = "error"

_SEVERITY_ORDER = {NOTE: 0, WARNING: 1, ERROR: 2}

SEVERITIES = (NOTE, WARNING, ERROR)

# -- monitoring (ISSUE 3 satellite: stf/analysis/* counters) -----------------

metric_diagnostics = monitoring.Counter(
    "/stf/analysis/diagnostics",
    "diagnostics produced by the static-analysis layer", "severity")
metric_hazards = monitoring.Counter(
    "/stf/analysis/hazards",
    "variable hazards detected between unordered effectful ops", "kind")
metric_auto_deps = monitoring.Counter(
    "/stf/analysis/auto_control_deps",
    "hazard pairs ordered by auto_deps (program-order control edges)")
metric_check_seconds = monitoring.Sampler(
    "/stf/analysis/plan_check_seconds",
    monitoring.ExponentialBuckets(1e-6, 4.0, 16),
    "verifier+hazard seconds per Session plan analysis")


class Diagnostic:
    """One analysis finding, with op + source attribution."""

    __slots__ = ("severity", "code", "message", "op_name", "op_type",
                 "source")

    def __init__(self, severity: str, code: str, message: str,
                 op: Any = None, op_name: Optional[str] = None,
                 op_type: Optional[str] = None,
                 source: Optional[str] = None):
        if severity not in _SEVERITY_ORDER:
            raise ValueError(f"severity must be one of {SEVERITIES}, "
                             f"got {severity!r}")
        self.severity = severity
        self.code = code
        self.message = message
        if op is not None:
            op_name = op_name or getattr(op, "name", None)
            op_type = op_type or getattr(op, "type", None)
            source = source or getattr(op, "source_site", None)
        self.op_name = op_name
        self.op_type = op_type
        self.source = source

    @property
    def is_error(self) -> bool:
        return self.severity == ERROR

    def with_severity(self, severity: str) -> "Diagnostic":
        return Diagnostic(severity, self.code, self.message,
                          op_name=self.op_name, op_type=self.op_type,
                          source=self.source)

    def format(self) -> str:
        loc = ""
        if self.op_name:
            loc = f" [op {self.op_type or '?'} {self.op_name!r}"
            if self.source:
                loc += f" at {self.source}"
            loc += "]"
        elif self.source:
            loc = f" [at {self.source}]"
        return f"{self.severity.upper()} {self.code}: {self.message}{loc}"

    def to_dict(self) -> dict:
        return {"severity": self.severity, "code": self.code,
                "message": self.message, "op_name": self.op_name,
                "op_type": self.op_type, "source": self.source}

    def __repr__(self):
        return f"<Diagnostic {self.format()}>"


def report(diags: List[Diagnostic], severity: str, code: str, message: str,
           op: Any = None, **kw) -> Diagnostic:
    """Construct a Diagnostic, append it to ``diags``, count it."""
    d = Diagnostic(severity, code, message, op=op, **kw)
    diags.append(d)
    metric_diagnostics.get_cell(d.severity).increase_by(1)
    return d


def max_severity(diags: Sequence[Diagnostic]) -> Optional[str]:
    if not diags:
        return None
    return max(diags, key=lambda d: _SEVERITY_ORDER[d.severity]).severity


def errors(diags: Sequence[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity == ERROR]


def warnings(diags: Sequence[Diagnostic]) -> List[Diagnostic]:
    return [d for d in diags if d.severity == WARNING]


def format_report(diags: Sequence[Diagnostic],
                  header: Optional[str] = None) -> str:
    lines = [header] if header else []
    order = {ERROR: 0, WARNING: 1, NOTE: 2}
    for d in sorted(diags, key=lambda d: (order[d.severity],
                                          d.code, d.op_name or "")):
        lines.append("  " + d.format() if header else d.format())
    counts = {s: sum(1 for d in diags if d.severity == s)
              for s in (ERROR, WARNING, NOTE)}
    lines.append(("  " if header else "")
                 + f"{counts[ERROR]} error(s), {counts[WARNING]} "
                   f"warning(s), {counts[NOTE]} note(s)")
    return "\n".join(lines)
