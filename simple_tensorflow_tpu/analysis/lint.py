"""Extensible lint framework over the Graph IR.

A :class:`LintRule` is a named check with a default severity; rules
registered through :func:`register_lint_rule` run under
:func:`lint_graph` (whole graph or a fetch-pruned op list) and yield
:class:`~.diagnostics.Diagnostic` objects with op + user-source
attribution. Severities are per-run configurable
(``lint_graph(severities={"lint/unseeded-rng": "error"})``) so a CI
gate can promote any smell to a failure without code changes.

Built-in catalog (see docs/ANALYSIS.md for the worked examples):

  lint/int-div-float     integer division truncates, then the truncated
                         result feeds a float computation (WARNING)
  lint/narrow-64bit      a 64-bit tensor is declared while the runtime
                         narrows to 32-bit (jax_enable_x64 off): the
                         site that will silently lose precision (NOTE)
  lint/unseeded-rng      an RNG-effect op with neither graph nor op
                         seed: irreproducible across processes under
                         jit (WARNING)
  lint/const-fetch       a fetch is entirely constant-foldable — it is
                         recomputed (or at best re-fetched) every step
                         (NOTE)
  lint/transpose-pair    adjacent mutually inverse transposes survive
                         where the layout pass cannot cancel them
                         (control deps / multi-consumer boundaries)
                         (WARNING)
  lint/serving-incompatible
                         ops that make an exported inference graph
                         unservable under the stf.serving continuous
                         batcher: host-stage ops, host-observable io
                         effects (Print/logging), unseeded stateful
                         RNG. Active only for purpose="serving" runs
                         (``lint_graph(purpose="serving")`` /
                         ``graph_lint --serving``) (WARNING)
  lint/serving-decode-cache
                         generative decode-plan shape: KV-cache ops
                         missing a committed-sharding declaration, a
                         cache tensor escaping to host (fetched, or
                         feeding a host-stage op), a SHARED-page cache
                         tensor (paged prefix cache) transitively
                         REACHING a host sink, or a speculative-verify
                         cache write that is not refcount-guarded.
                         Active only for purpose="serving" runs (ERROR)
  lint/kernel-routing    per-op Pallas/XLA routing verdicts from the
                         stf.kernels registry (routed / fallback+reason
                         / autotune). Active only for purpose="kernels"
                         runs (``graph_lint --kernels``) (NOTE)
  lint/embedding-replicated-table
                         an embedding table at/over the byte budget
                         (``--budget`` or 128 MiB default) that
                         resolves REPLICATED on a >1-device mesh —
                         every device holds a full copy of a table
                         that only fits because vocab sharding divides
                         it. Active only for purpose="embeddings" runs
                         (``graph_lint --embeddings``) (ERROR)
  lint/memory-budget     the static cost model's predicted peak device
                         memory for a fetch closure exceeds the
                         configured budget (``graph_lint --memory
                         --budget BYTES``; ctx.memory_budget). Active
                         only for purpose="memory" runs (ERROR)
  lint/numeric-risk      statically visible NaN/Inf seeds, the offline
                         half of the stf.debug.numerics runtime plane:
                         unguarded domain-restricted ops (Log/Rsqrt/
                         Reciprocal on an unclamped operand, Div with
                         an unguarded denominator, Exp with no upper
                         clamp or max-subtraction) and bf16/f16
                         long-axis reductions whose low-mantissa
                         accumulator drifts. Active only for
                         purpose="numerics" runs (``graph_lint
                         --numerics``) (WARNING)
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from . import diagnostics as diag_mod
from .diagnostics import ERROR, NOTE, WARNING, Diagnostic
from .effects import op_effects


class LintContext:
    """What one lint run sees: the op list (graph order), the owning
    graph, the optional fetch set, and — when the sharding analyzer ran
    — its :class:`~.sharding.ShardingReport` (the sharding lint rules
    consult it and yield nothing without one). ``purpose`` scopes
    purpose-gated rules: "serving" activates the
    serving-incompatibility checks an exported inference graph must
    pass (a training graph legitimately fails them — dropout,
    summaries — so they never fire by default)."""

    def __init__(self, graph, ops: Sequence[Any],
                 fetches: Optional[Sequence[Any]] = None,
                 sharding_report: Optional[Any] = None,
                 purpose: Optional[str] = None,
                 memory_budget: Optional[int] = None):
        self.graph = graph
        self.ops = list(ops)
        self.fetches = list(fetches or [])
        self.sharding_report = sharding_report
        self.purpose = purpose
        # device-memory budget in bytes for the lint/memory-budget rule
        # (graph_lint --memory --budget; purpose="memory" runs)
        self.memory_budget = memory_budget
        self._x64 = None

    @property
    def x64_enabled(self) -> bool:
        if self._x64 is None:
            import jax

            self._x64 = bool(jax.config.jax_enable_x64)
        return self._x64


class LintRule:
    """One registered rule. ``check(ctx)`` yields (op, message) pairs —
    severity/code attachment and counting happen in the driver."""

    def __init__(self, code: str, default_severity: str,
                 check: Callable[[LintContext], Iterable],
                 doc: str = ""):
        if not code.startswith("lint/"):
            code = "lint/" + code
        self.code = code
        self.default_severity = default_severity
        self.check = check
        self.doc = doc or (check.__doc__ or "").strip()

    def __repr__(self):
        return f"<LintRule {self.code} ({self.default_severity})>"


_RULES: Dict[str, LintRule] = {}


def register_lint_rule(code: str, default_severity: str = WARNING,
                       doc: str = ""):
    """Decorator: register ``fn(ctx) -> iterable of (op, message)`` as a
    lint rule. Re-registration replaces (rules are module-reloadable)."""
    def deco(fn):
        rule = LintRule(code, default_severity, fn, doc)
        _RULES[rule.code] = rule
        return fn

    return deco


def registered_rules() -> List[LintRule]:
    return [_RULES[k] for k in sorted(_RULES)]


def lint_graph(graph=None, ops: Optional[Sequence[Any]] = None,
               fetches: Optional[Sequence[Any]] = None,
               severities: Optional[Dict[str, str]] = None,
               rules: Optional[Sequence[str]] = None,
               sharding_report: Optional[Any] = None,
               purpose: Optional[str] = None,
               memory_budget: Optional[int] = None) -> List[Diagnostic]:
    """Run the registered rules. ``severities`` overrides per-code
    severity ("off" disables a rule); ``rules`` restricts to a subset;
    ``sharding_report`` feeds the sharding rules (analyze_sharding
    passes its own report through here); ``purpose="serving"``
    activates the serving-compatibility rules (ModelServer.load and
    ``graph_lint --serving`` pass it); ``purpose="memory"`` +
    ``memory_budget`` activates the device-memory budget rule
    (``graph_lint --memory --budget``)."""
    if graph is None and ops is None:
        graph = ops_mod.get_default_graph()
    if ops is None:
        ops = graph.get_operations()
    ctx = LintContext(graph, ops, fetches, sharding_report=sharding_report,
                      purpose=purpose, memory_budget=memory_budget)
    severities = severities or {}
    diags: List[Diagnostic] = []
    for rule in registered_rules():
        if rules is not None and rule.code not in rules \
                and rule.code[len("lint/"):] not in rules:
            continue
        sev = severities.get(rule.code,
                             severities.get(rule.code[len("lint/"):],
                                            rule.default_severity))
        if sev == "off":
            continue
        for op, message in rule.check(ctx):
            diag_mod.report(diags, sev, rule.code, message, op=op)
    return diags


# ---------------------------------------------------------------------------
# built-in rules
# ---------------------------------------------------------------------------

_INT_DIV_TYPES = ("Div", "FloorDiv")


@register_lint_rule("int-div-float", WARNING)
def _rule_int_div_float(ctx):
    """Integer division truncates; feeding the truncated quotient into a
    float computation is almost always a missing cast on the operands
    (classic: ``mean = total / count`` with int tensors)."""
    for op in ctx.ops:
        if op.type not in _INT_DIV_TYPES or not op.outputs:
            continue
        out = op.outputs[0]
        if not out.dtype.base_dtype.is_integer:
            continue
        for consumer in out.consumers():
            floaty = False
            if consumer.type == "Cast":
                to = consumer.attrs.get("dtype")
                floaty = to is not None and \
                    dtypes_mod.as_dtype(to).is_floating
            else:
                floaty = any(
                    t is not out and t.dtype.base_dtype.is_floating
                    for t in consumer.inputs) or any(
                    t.dtype.base_dtype.is_floating
                    for t in consumer.outputs)
            if floaty:
                yield (op,
                       f"integer division {op.name!r} truncates before "
                       f"feeding float computation {consumer.name!r} "
                       f"({consumer.type}); cast the operands to float "
                       "first (or use stf.truediv)")
                break


_WIDE_DTYPES = ("int64", "uint64", "float64")
# op types whose 64-bit output is a deliberate API contract, narrowed
# once at the session boundary (see docs/MIGRATION.md): re-flagging
# every op in between would bury the signal
_NARROW_SOURCE_TYPES = ("Placeholder", "PlaceholderWithDefault",
                        "VariableV2", "Const")


@register_lint_rule("narrow-64bit", NOTE)
def _rule_narrow_64bit(ctx):
    """64-bit tensors silently narrow to 32-bit on TPU (jax x64 off).
    Flags the *source* sites (placeholders, variables, constants) where
    the narrowing enters the graph."""
    if ctx.x64_enabled:
        return
    for op in ctx.ops:
        if op.type not in _NARROW_SOURCE_TYPES:
            continue
        for out in op.outputs:
            if out.dtype.base_dtype.name in _WIDE_DTYPES:
                yield (op,
                       f"{op.type} {op.name!r} declares "
                       f"{out.dtype.base_dtype.name}, which narrows to "
                       f"{dtypes_mod.narrowed_if_no_x64(out.dtype.base_dtype).name}"
                       " on this runtime (jax_enable_x64 off); declare "
                       "the 32-bit dtype to make the precision explicit")
                break


@register_lint_rule("unseeded-rng", WARNING)
def _rule_unseeded_rng(ctx):
    """An RNG op with neither a graph seed nor an op seed draws from a
    different stream every process start — irreproducible under jit.
    Set stf.set_random_seed(...) or pass seed= at the op."""
    for op in ctx.ops:
        eff = op_effects(op)
        if not eff.rng:
            continue
        if op.attrs.get("seed") is None \
                and op.attrs.get("_graph_seed") is None \
                and (op.graph.seed is None):
            yield (op,
                   f"RNG op {op.name!r} ({op.type}) has no seed and the "
                   "graph seed is unset: draws are irreproducible "
                   "across process restarts")


@register_lint_rule("const-fetch", NOTE)
def _rule_const_fetch(ctx):
    """A fetch whose whole ancestry is constant re-evaluates (at best
    re-fetches) an invariant value every step; fold it at build time or
    fetch it once."""
    if not ctx.fetches:
        return
    cache: Dict[Any, bool] = {}

    def const_only(op) -> bool:
        if op in cache:
            return cache[op]
        cache[op] = False  # cycle guard
        try:
            od = op_registry.get(op.type)
        except KeyError:
            return False
        if op.type == "Const":
            cache[op] = True
            return True
        if od.is_stateful or od.runs_on_host or od.pure_fn is None \
                or not op.inputs:
            return False
        ok = all(const_only(t.op) for t in op.inputs) \
            and not op.control_inputs
        cache[op] = ok
        return ok

    for f in ctx.fetches:
        op = f if isinstance(f, ops_mod.Operation) else f.op
        if op.type != "Const" and const_only(op):
            yield (op,
                   f"fetch {op.name!r} is entirely constant-foldable; "
                   "its value never changes across steps")


def _perm_of(op):
    p = op.attrs.get("perm")
    return tuple(p) if p is not None else None


@register_lint_rule("transpose-pair", WARNING)
def _rule_transpose_pair(ctx):
    """Adjacent mutually inverse transposes that survive into the final
    graph (the layout pass cancels clean pairs; pairs split by control
    dependencies or consumed by name stay) — pure data-movement cost on
    every step."""
    for op in ctx.ops:
        if op.type != "Transpose" or not op.inputs:
            continue
        p1 = _perm_of(op)
        src = op.inputs[0].op
        if src.type != "Transpose" or op.inputs[0].value_index != 0 \
                or not src.inputs:
            continue
        p2 = _perm_of(src)
        if not p1 or not p2 or len(p1) != len(p2):
            continue
        if tuple(p2[i] for i in p1) == tuple(range(len(p1))):
            yield (op,
                   f"transpose pair {src.name!r} -> {op.name!r} composes "
                   "to identity but was not cancelled (control deps or "
                   "by-name fetches pin it); restructure so the layout "
                   "pass can cancel it")


# op types that are pure graph inputs/values — never serving hazards
# even though Placeholder is formally "fed on host"
_SERVING_BENIGN_TYPES = ("Placeholder", "PlaceholderWithDefault", "Const",
                         "NoOp")


@register_lint_rule("serving-incompatible", WARNING)
def _rule_serving_incompatible(ctx):
    """Ops an exported inference graph must not contain to serve under
    the stf.serving continuous batcher (active only for
    ``purpose="serving"`` runs):

    - host-stage ops (queues, readers, iterators, summaries, py_func):
      each one forces a Python host stage around every coalesced batch
      — ModelServer refuses such plans outright;
    - host-observable io effects (``Print``, logging): they fire once
      per BATCH, not per request, and serialize the device dispatch;
    - stateful RNG without an op seed: responses become dependent on
      batch composition and request arrival order (and irreproducible
      across server restarts) — seed the op, or export an inference
      graph without sampling (e.g. dropout at keep_prob=1 folded out).
    """
    if ctx.purpose != "serving":
        return
    ops = ctx.ops
    if ctx.fetches:
        from ..framework import lowering as lowering_mod

        targets = [f if isinstance(f, ops_mod.Operation) else f.op
                   for f in ctx.fetches]
        # narrow to the fetch ancestry, but never WIDEN past the op set
        # the caller scoped the run to: ModelServer passes the closure
        # already pruned at the signature-INPUT boundary, and ops
        # upstream of a fed input are not part of the serving plan
        scoped = set(ctx.ops)
        ops = [op for op in lowering_mod.prune(targets, set())
               if op in scoped]
    for op in ops:
        if op.type in _SERVING_BENIGN_TYPES:
            continue
        if op.op_def.runs_on_host:
            yield (op,
                   f"host-stage op {op.name!r} ({op.type}) in the "
                   "inference closure: every request batch would pay a "
                   "Python host stage; export a pure device inference "
                   "graph")
            continue
        eff = op_effects(op)
        if eff.io:
            yield (op,
                   f"op {op.name!r} ({op.type}) has a host-observable "
                   "io effect: under batching it fires once per batch, "
                   "not per request, and blocks async dispatch; strip "
                   "logging/Print from the exported inference graph")
        if eff.rng and op.attrs.get("seed") is None \
                and op.attrs.get("_graph_seed") is None \
                and op.graph.seed is None:
            yield (op,
                   f"unseeded stateful RNG {op.name!r} ({op.type}) in "
                   "the inference closure: responses depend on batch "
                   "composition/request order and do not reproduce "
                   "across restarts; seed it, or export without "
                   "sampling ops")


@register_lint_rule("serving-decode-cache", ERROR)
def _rule_serving_decode_cache(ctx):
    """Decode-plan shape checks for generative serving (active only for
    ``purpose="serving"`` runs — ``graph_lint --serving``). The
    KV-cache contract (ops/kv_cache_ops.py) is that cache state lives
    device-resident with a COMMITTED sharding and never leaves HBM
    between decode steps; this rule makes both halves statically
    checkable:

    - a cache op (KVCacheAlloc/Append/Gather) whose committed-sharding
      declaration is missing would commit at whatever layout the first
      write happened to produce — resharding every subsequent step;
    - a cache tensor ESCAPING TO HOST (a host-stage op consuming a
      cache op's output, or a cache op's output fetched directly) pays
      a device→host transfer of the whole cache page set per decode
      step — the exact traffic the cache exists to avoid. Slice a
      device-side view instead, or fetch derived scalars;
    - a SHARED page (paged prefix cache, ``PAGED_ATTR``) holds K/V rows
      other live sequences read through their page tables, so the
      host-sink contract tightens from "direct consumer" to
      REACHABILITY: any path from a paged cache tensor to a host sink
      leaks refcounted shared state off-device (and a host round-trip
      in the decode loop serializes every sequence sharing the page);
    - a cache write inside a speculative VERIFY plan (``VERIFY_ATTR``)
      lands K rows of which only the accepted prefix is committed; the
      write must be stamped ``refcount_guarded=True`` (``GUARD_ATTR``)
      to assert the engine masks the rejected suffix by committed
      length — an unguarded verify write could expose uncommitted
      draft rows to a sequence sharing the page;
    - decode tensor parallelism (``"<axis>:heads"`` declarations): a
      head-sharded cache whose gathered pages are immediately
      re-sharded to a head-replicated layout pays a per-token
      all-gather of the whole cache read — the traffic the TP layout
      exists to avoid (DecodeAttention runs per-shard over heads); and
      a ``KVCachePageCopy`` that declares a DIFFERENT sharding than
      the cache's committed one would re-commit the store entry at the
      new layout on the first CoW, resharding every subsequent decode
      step.
    """
    if ctx.purpose != "serving":
        return
    from ..ops import kv_cache_ops as _kvc

    # committed declarations per cache var (from the non-PageCopy ops:
    # alloc/append/gather all stamp the kv_cache handle's declaration)
    committed_decls = {}
    for op in ctx.ops:
        if _kvc.is_cache_op(op) and op.type != "KVCachePageCopy":
            vn = op.attrs.get("var_name")
            decl = op.attrs.get(_kvc.SHARDING_ATTR)
            if vn is not None and decl:
                committed_decls.setdefault(vn, set()).add(str(decl))

    fetched = set()
    for f in ctx.fetches:
        if not isinstance(f, ops_mod.Operation):
            fetched.add(f)

    def _is_host_sink(consumer):
        return consumer.op_def.runs_on_host or op_effects(consumer).io

    # transitive host-sink search for the shared-page branch; memoized
    # per consumer op so the sweep stays linear in graph size
    _reach_memo = {}

    def _reaches_host(op):
        """First host-observable op reachable downstream of ``op``
        (following data edges), or None."""
        if op in _reach_memo:
            return _reach_memo[op]
        _reach_memo[op] = None  # cycle guard (graphs are acyclic)
        found = None
        for out in op.outputs:
            for consumer in out.consumers():
                if _is_host_sink(consumer):
                    found = consumer
                    break
                found = _reaches_host(consumer)
                if found is not None:
                    break
            if found is not None:
                break
        _reach_memo[op] = found
        return found

    for op in ctx.ops:
        if not _kvc.is_cache_op(op):
            continue
        if not op.attrs.get(_kvc.SHARDING_ATTR):
            yield (op,
                   f"cache op {op.name!r} ({op.type}) on "
                   f"{op.attrs.get('var_name')!r} has no committed "
                   "sharding declaration; declare it at kv_cache(..., "
                   "sharding=...) so the store commits a stable layout")
        if op.attrs.get(_kvc.VERIFY_ATTR) \
                and not op.attrs.get(_kvc.GUARD_ATTR):
            yield (op,
                   f"verify-plan cache write {op.name!r} on "
                   f"{op.attrs.get('var_name')!r} is not refcount-"
                   "guarded: a speculative VERIFY append lands rows "
                   "the engine may reject; stamp it "
                   "refcount_guarded=True (append(..., "
                   "verify_plan=True, refcount_guarded=True)) to "
                   "assert only the accepted prefix is committed")
        decl = str(op.attrs.get(_kvc.SHARDING_ATTR) or "")
        head_sharded = decl.endswith(_kvc.HEAD_SHARD_SUFFIX)
        if op.type == "KVCachePageCopy":
            others = committed_decls.get(op.attrs.get("var_name"), set())
            head_committed = any(
                d.endswith(_kvc.HEAD_SHARD_SUFFIX) for d in others)
            if head_committed and decl not in others:
                yield (op,
                       f"page copy {op.name!r} on "
                       f"{op.attrs.get('var_name')!r} declares sharding "
                       f"{decl or None!r} but the cache committed "
                       f"{sorted(others)}: the CoW would re-commit the "
                       "store entry at the new layout and reshard every "
                       "subsequent decode step; stamp the copy with the "
                       "cache's own declaration (build it from the same "
                       "kv_cache handle)")
        if op.type == "KVCacheGather" and head_sharded:
            axis = decl[: -len(_kvc.HEAD_SHARD_SUFFIX)]
            for out in op.outputs:
                for consumer in out.consumers():
                    if consumer.type != "ShardingConstraint":
                        continue
                    spec = tuple(consumer.attrs.get("spec") or ())
                    entry = (spec[_kvc.HEAD_DIM]
                             if len(spec) > _kvc.HEAD_DIM else None)
                    axes = (tuple(entry) if isinstance(entry,
                                                       (tuple, list))
                            else (entry,) if entry else ())
                    if axis not in axes:
                        yield (op,
                               f"head-sharded cache gather {op.name!r} "
                               f"({op.attrs.get('var_name')!r}, "
                               f"sharding {decl!r}) is re-sharded to a "
                               f"head-replicated layout by "
                               f"{consumer.name!r}: the decode plan "
                               "all-gathers the full head dim of every "
                               "gathered page per token; feed the "
                               "gathered pages to DecodeAttention "
                               "per-shard instead (heads are "
                               "embarrassingly parallel)")
        paged = bool(op.attrs.get(_kvc.PAGED_ATTR))
        for out in op.outputs:
            if out in fetched:
                yield (op,
                       f"cache tensor {out.name!r} is fetched — the "
                       "whole cache page set would transfer "
                       "device->host every decode step; fetch derived "
                       "values instead")
            direct_sink = False
            for consumer in out.consumers():
                if _is_host_sink(consumer):
                    direct_sink = True
                    yield (op,
                           f"cache tensor {out.name!r} feeds host-"
                           f"observable op {consumer.name!r} "
                           f"({consumer.type}): the cache must stay "
                           "device-resident across decode steps "
                           "(host-sink on a cache tensor)")
            if paged and not direct_sink:
                sink = _reaches_host(op)
                if sink is not None:
                    yield (op,
                           f"shared-page cache tensor {out.name!r} "
                           f"(paged prefix cache) reaches host-"
                           f"observable op {sink.name!r} "
                           f"({sink.type}): shared pages are "
                           "refcounted device state read by every "
                           "sequence whose page table maps them; no "
                           "path from a paged cache tensor may leave "
                           "the device")
                    break


@register_lint_rule("memory-budget", ERROR)
def _rule_memory_budget(ctx):
    """A fetch closure whose statically predicted peak device memory
    (framework/cost_model: resident variables + transient liveness
    sweep) exceeds the configured budget (active only for
    ``purpose="memory"`` runs with ``ctx.memory_budget`` set —
    ``graph_lint --memory --budget BYTES``). The offline half of the
    ``ConfigProto(device_memory_budget_bytes=)`` admission check: a
    plan a budgeted Session would refuse at load fails CI here, before
    any deploy. Without fetches, the whole graph's terminal ops are
    the plan (one diagnostic)."""
    if ctx.purpose != "memory" or not ctx.memory_budget:
        return
    from ..framework import cost_model

    budget = int(ctx.memory_budget)
    plans = plan_fetch_groups(ctx)
    for label, fetches, anchor in plans:
        try:
            est = cost_model.estimate(fetches)
        except Exception:  # noqa: BLE001 — un-costable plan: skip
            continue
        if est.peak_bytes > budget:
            yield (anchor,
                   f"plan {label!r}: predicted peak device memory "
                   f"{int(est.peak_bytes)} B (resident "
                   f"{int(est.resident_bytes)} B + transient "
                   f"{int(est.peak_bytes - est.resident_bytes)} B) "
                   f"exceeds the budget {budget} B "
                   "(ConfigProto.device_memory_budget_bytes); a "
                   "budgeted Session refuses this plan at admission")


def plan_fetch_groups(ctx):
    """(label, fetches, anchor_op) groups the memory rules treat as
    one plan each: every explicit fetch is its own plan; with no
    fetches, the graph's terminal ops (no consumed outputs) form one
    whole-graph plan."""
    groups = []
    if ctx.fetches:
        for f in ctx.fetches:
            op = f if isinstance(f, ops_mod.Operation) else f.op
            groups.append((getattr(f, "name", op.name), [f], op))
        return groups
    consumed = set()
    for op in ctx.ops:
        for t in op.inputs:
            consumed.add(t)
    terminals = [op for op in ctx.ops
                 if op.outputs and not any(o in consumed
                                           for o in op.outputs)]
    if terminals:
        groups.append(("(whole graph)",
                       [o for op in terminals for o in op.outputs],
                       terminals[0]))
    return groups


@register_lint_rule("kernel-routing", NOTE)
def _rule_kernel_routing(ctx):
    """Per-op Pallas/XLA routing verdicts from the stf.kernels registry
    (active only for ``purpose="kernels"`` runs: ``graph_lint
    --kernels`` and the zoo routing gate). One NOTE per op whose type
    has a registered kernel pair, naming the verdict the registry would
    reach offline — ``routed`` (Pallas), ``fallback`` + reason, or
    ``autotune`` (decided by measurement on first live call). Op types
    without a kernel are summarized by the CLI, not flagged per op."""
    if ctx.purpose != "kernels":
        return
    from ..kernels import registry as kreg

    mode = kreg.current_mode()
    bk = kreg.backend()
    for op in ctx.ops:
        if not kreg.has_kernel(op.type):
            continue
        rec = kreg.routing_report([op], mode=mode)[0]
        reason = rec.get("reason")
        detail = f" ({reason})" if reason and rec["verdict"] != "routed" \
            else ""
        yield (op,
               f"kernel routing [{mode}/{bk}]: {op.type} -> "
               f"{rec['verdict']}{detail}")


# ---------------------------------------------------------------------------
# numeric-risk (purpose="numerics") — the static half of the
# stf.debug.numerics runtime health plane (docs/DEBUG.md)
# ---------------------------------------------------------------------------

# ops that constrain their operand's range: a guard anywhere on the
# plumbing path between a value and a risky consumer means the author
# already handled the edge case
_NUMERIC_GUARD_TYPES = frozenset((
    "Maximum", "Minimum", "ClipByValue", "Abs", "Square", "Exp",
    "Sigmoid", "Softmax", "Softplus", "Relu", "Relu6",
))
# Exp overflows at the TOP of the range, so its guards differ: an upper
# clamp, a negation, or the log-sum-exp ``x - max(x)`` subtraction
_NUMERIC_EXP_GUARD_TYPES = frozenset((
    "Minimum", "ClipByValue", "Neg", "Sub", "LogSoftmax", "Softplus",
    "Sigmoid", "Softmax",
))
# pure shape/dtype plumbing the guard search walks through
_NUMERIC_PASSTHROUGH_TYPES = frozenset((
    "Identity", "Reshape", "Cast", "StopGradient", "Squeeze",
    "ExpandDims", "Transpose",
))
# risky op type -> (operand index to inspect, failure mode)
_NUMERIC_RISK_OPS = {
    "Log":        (0, "log of a zero/negative value is -inf/nan"),
    "Rsqrt":      (0, "rsqrt of zero is inf, of a negative value nan"),
    "Reciprocal": (0, "1/0 is inf"),
    "Div":        (1, "a zero denominator is inf (0/0 is nan)"),
    "TrueDiv":    (1, "a zero denominator is inf (0/0 is nan)"),
    "RealDiv":    (1, "a zero denominator is inf (0/0 is nan)"),
    "Exp":        (0, "exp overflows to inf past ~88 in float32 "
                      "(~11 in float16)"),
}
_NUMERIC_RISK_GUARD_HINT = {
    "Log":        "clamp with maximum(x, eps) or use log1p",
    "Rsqrt":      "add an epsilon (rsqrt(x + eps))",
    "Reciprocal": "add an epsilon or clamp the operand",
    "Div":        "add an epsilon to the denominator or use div_no_nan",
    "TrueDiv":    "add an epsilon to the denominator or use div_no_nan",
    "RealDiv":    "add an epsilon to the denominator or use div_no_nan",
    "Exp":        "subtract the row max first (log-sum-exp) or clamp",
}
_NUMERIC_REDUCE_TYPES = ("Sum", "Mean", "Prod")
_NUMERIC_LOW_MANTISSA = ("bfloat16", "float16")
# elements folded into one low-mantissa accumulator before the lost
# bits (~log2(n) of bf16's 8) start to matter
_NUMERIC_LONG_AXIS = 1024


def _numeric_guarded(tensor, guard_types) -> bool:
    """True when ``tensor`` is visibly range-restricted: produced by a
    guard op (possibly through shape/dtype plumbing), by the
    ``x + eps`` idiom (Add with a Const operand), or a literal Const.
    A conservative single-path walk — branches in the plumbing stop the
    search, so the rule under- rather than over-silences."""
    t = tensor
    for _ in range(8):
        op = t.op
        if op.type in guard_types:
            return True
        if op.type == "Const":
            return True
        if op.type in ("Add", "AddV2") and any(
                i.op.type == "Const" for i in op.inputs):
            return True  # the x + eps idiom
        if op.type in _NUMERIC_PASSTHROUGH_TYPES and op.inputs:
            t = op.inputs[0]
            continue
        return False
    return False


def _numeric_reduced_elements(op):
    """Statically known element count folded per output element by a
    reduce op, or None when any reduced dim is unknown."""
    if not op.inputs:
        return None
    shape = op.inputs[0].shape
    if shape.rank is None:
        return None
    dims = [d.value for d in shape.dims]
    axis = op.attrs.get("axis")
    if axis is None:
        reduced = dims
    else:
        axes = axis if isinstance(axis, (tuple, list)) else (axis,)
        try:
            reduced = [dims[int(a)] for a in axes]
        except IndexError:
            return None
    n = 1
    for d in reduced:
        if d is None:
            return None
        n *= int(d)
    return n


@register_lint_rule("numeric-risk", WARNING)
def _rule_numeric_risk(ctx):
    """Statically visible NaN/Inf seeds — the offline counterpart of the
    stf.debug.numerics runtime plane (active only for
    ``purpose="numerics"`` runs: ``graph_lint --numerics``):

    - a domain-restricted op (Log/Rsqrt/Reciprocal/Div/Exp) whose
      operand shows no guard on its producer path — no clamp, no
      ``x + eps``, no max-subtraction for Exp;
    - a Sum/Mean/Prod reduction over a bfloat16/float16 input folding
      >= 1024 statically known elements into one low-mantissa
      accumulator — cast up to float32 before reducing.

    Heuristic by design: a guard hidden behind a multi-input op is not
    seen (false positive), and a clamp to a still-bad range is trusted
    (false negative). The runtime plane catches what this misses."""
    if ctx.purpose != "numerics":
        return
    for op in ctx.ops:
        risk = _NUMERIC_RISK_OPS.get(op.type)
        if risk is not None and op.outputs \
                and op.outputs[0].dtype.base_dtype.is_floating:
            idx, hazard = risk
            guards = _NUMERIC_EXP_GUARD_TYPES if op.type == "Exp" \
                else _NUMERIC_GUARD_TYPES
            if idx < len(op.inputs) and not _numeric_guarded(
                    op.inputs[idx], guards):
                operand = "denominator" if idx == 1 else "operand"
                yield (op,
                       f"unguarded {op.type} {op.name!r}: {hazard}; "
                       f"no clamp/epsilon found on the {operand} "
                       f"({op.inputs[idx].op.name!r}) — "
                       f"{_NUMERIC_RISK_GUARD_HINT[op.type]}")
            continue
        if op.type in _NUMERIC_REDUCE_TYPES and op.inputs:
            dt = op.inputs[0].dtype.base_dtype.name
            if dt not in _NUMERIC_LOW_MANTISSA:
                continue
            n = _numeric_reduced_elements(op)
            if n is not None and n >= _NUMERIC_LONG_AXIS:
                yield (op,
                       f"{op.type} {op.name!r} folds {n} {dt} elements "
                       "into one low-mantissa accumulator; precision "
                       f"drifts by ~log2({n}) of its ~8 mantissa bits "
                       "— cast to float32 before the reduction")
