"""Graph verifier: structural invariants over the live Graph IR and over
serialized GraphDef JSON dicts.

The reference validates graphs at session-creation time
(core/graph/validate.cc, core/common_runtime/graph_constructor) and
surfaces violations as Status strings; stf discovers most of the same
problems only as opaque JAX tracer errors deep inside Session.run
lowering. This verifier runs *before* lowering — standalone, at strict
Session construction, per plan, as PassManager pre/post invariant
checks, and from the ``tools.graph_lint`` CLI — and emits structured
:class:`~.diagnostics.Diagnostic` objects carrying the op's user-code
creation site.

Live-graph checks (``verify_graph`` / ``verify_ops``):

  verifier/dangling-input    input tensor's producer is not registered
                             in the graph it claims (ERROR)
  verifier/graph-order       an op consumes a tensor or control dep
                             created *after* it — impossible in the
                             append-only IR, so its presence means IR
                             corruption / a broken import (ERROR)
  verifier/cycle             data+control cycle (GraphDef level; live
                             graphs are acyclic by construction) (ERROR)
  verifier/infer-mismatch    re-running abstract shape/dtype inference
                             disagrees with the recorded output specs
                             (dtype: ERROR, shape: WARNING) — catches
                             hand-supplied output_specs that lie
  verifier/host-sink-feeds-device
                             a device op consumes the output of a host
                             op that itself depends on device results —
                             Session.run will reject the plan; reported
                             here with source attribution (WARNING)
  verifier/device-scope      an op registered runs_on_host is pinned to
                             a non-host device scope (WARNING)
  verifier/unreachable-stateful
                             with fetches given: a stateful op outside
                             the fetch closure is silently pruned (NOTE)

FuncGraph bodies (cond/while/scan/defun) are verified recursively, with
capture/input/output signature integrity checked at each level.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set

from ..framework import graph as ops_mod
from ..framework import op_registry
from . import diagnostics as diag_mod
from .diagnostics import ERROR, NOTE, WARNING, Diagnostic, report

_HOST_HINT = "cpu"
# layout-neutral source nodes: a host op consuming these has no device
# ancestor (Session folds consts / feeds placeholders)
_NEUTRAL_TYPES = ("Const", "Placeholder", "PlaceholderWithDefault",
                  "FuncArg", "CapturedInput")


def _is_host_pinned(device: str) -> bool:
    return bool(device) and _HOST_HINT in str(device).lower()


def _is_device_pinned(device: str) -> bool:
    return bool(device) and _HOST_HINT not in str(device).lower()


# ---------------------------------------------------------------------------
# live-graph verification
# ---------------------------------------------------------------------------

def verify_ops(op_list: Sequence[Any], graph=None,
               level: str = "structural",
               diags: Optional[List[Diagnostic]] = None
               ) -> List[Diagnostic]:
    """Verify one op list (a whole graph's or a pruned plan's).

    ``level``: "structural" (cheap invariants; what Session runs per
    plan) or "full" (adds the abstract-eval shape/dtype re-check)."""
    diags = diags if diags is not None else []
    host_like: Set[Any] = set()      # host-staged ops
    has_dev_anc: Set[Any] = set()    # ops downstream of device results
    for op in op_list:
        try:
            od = op_registry.get(op.type)
        except KeyError:
            report(diags, ERROR, "verifier/unregistered-op",
                   f"op type {op.type!r} is not registered", op=op)
            continue
        g = graph or op.graph
        for t in list(op.inputs):
            powner = t.op
            registered = powner.graph._ops_by_name.get(powner.name)
            if registered is not powner:
                report(diags, ERROR, "verifier/dangling-input",
                       f"input {t.name} of {op.name!r} refers to an op "
                       "that is not registered in its graph (dangling "
                       "reference after a broken import/rewrite)", op=op)
            if powner._id >= op._id and powner.graph is op.graph:
                report(diags, ERROR, "verifier/graph-order",
                       f"{op.name!r} consumes {t.name} created after it "
                       "— append-only IR ordering violated", op=op)
        for c in op.control_inputs:
            if c._id >= op._id and c.graph is op.graph:
                report(diags, ERROR, "verifier/graph-order",
                       f"{op.name!r} has control dep {c.name!r} created "
                       "after it — append-only IR ordering violated",
                       op=op)
        # device/host staging invariants (mirrors Session._plan staging)
        if od.runs_on_host and _is_device_pinned(op.device):
            report(diags, WARNING, "verifier/device-scope",
                   f"{op.name!r} ({op.type}) executes in the host stage "
                   f"but is pinned to device {op.device!r}; the pin is "
                   "ignored", op=op)
        is_host = od.runs_on_host or _is_host_pinned(op.device)
        dev_anc = False
        for t in op.inputs:
            p = t.op
            if p in has_dev_anc:
                dev_anc = True
            elif p not in host_like and p.type not in _NEUTRAL_TYPES:
                dev_anc = True  # device-stage producer
            if p in host_like and p in has_dev_anc and not is_host:
                report(diags, WARNING,
                       "verifier/host-sink-feeds-device",
                       f"device op {op.name!r} consumes {t.name} from "
                       f"host op {p.name!r}, which itself depends on "
                       "device results — Session.run will reject this "
                       "plan; use stf.py_func to re-enter the device "
                       "program", op=op)
        for c in op.control_inputs:
            if c in has_dev_anc or (c not in host_like
                                    and c.type not in _NEUTRAL_TYPES):
                dev_anc = True
        if is_host:
            host_like.add(op)
        if dev_anc:
            has_dev_anc.add(op)
        # FuncGraph bodies: recurse + signature integrity
        for k, v in op.attrs.items():
            if isinstance(v, ops_mod.FuncGraph):
                _verify_funcgraph(v, op, level, diags)
        if level == "full":
            _recheck_inference(op, od, diags)
    return diags


def _verify_funcgraph(fg: "ops_mod.FuncGraph", owner, level,
                      diags: List[Diagnostic]) -> None:
    inner_ops = fg.get_operations()
    inner_set = set(inner_ops)
    for t in fg.outputs:
        if t.op not in inner_set:
            report(diags, ERROR, "verifier/funcgraph-signature",
                   f"body {fg.func_name!r} of {owner.name!r} returns "
                   f"{t.name}, which is not an op of the body", op=owner)
    for t in fg.inputs:
        if t.op not in inner_set:
            report(diags, ERROR, "verifier/funcgraph-signature",
                   f"body {fg.func_name!r} of {owner.name!r} declares "
                   f"input {t.name} outside the body", op=owner)
    for outer, inner in fg.captures:
        if inner.op not in inner_set:
            report(diags, ERROR, "verifier/funcgraph-signature",
                   f"body {fg.func_name!r} of {owner.name!r} capture "
                   f"{inner.name} has no CapturedInput op in the body",
                   op=owner)
        if outer is not None and outer.graph is fg:
            report(diags, ERROR, "verifier/funcgraph-signature",
                   f"body {fg.func_name!r} of {owner.name!r} captures "
                   f"its own tensor {outer.name}", op=owner)
    verify_ops(inner_ops, graph=fg, level=level, diags=diags)


def _recheck_inference(op, od, diags: List[Diagnostic]) -> None:
    """Abstract-eval re-check: recorded output specs must agree with
    what the op registry's inference derives from the recorded input
    specs (ref: the reference re-runs C++ shape fns at import through
    common_runtime/shape_refiner.cc)."""
    if od.pure_fn is None or od.is_stateful:
        return
    if not op.inputs or not all(
            t.shape.is_fully_defined() for t in op.inputs):
        return
    try:
        inferred = od.infer(op.graph, op.attrs, op.inputs)
    except Exception:
        return  # probe failure: advisory only
    if len(inferred) != len(op.outputs):
        report(diags, ERROR, "verifier/infer-mismatch",
               f"{op.name!r} ({op.type}) records {len(op.outputs)} "
               f"outputs but inference derives {len(inferred)}", op=op)
        return
    from ..framework import dtypes as dtypes_mod

    for i, ((sh, dt), out) in enumerate(zip(inferred, op.outputs)):
        # compare through the x64-narrowing policy: a declared float64
        # that the runtime narrows to float32 is the lint layer's
        # business (lint/narrow-64bit), not an inference mismatch
        dt = dtypes_mod.narrowed_if_no_x64(dt.base_dtype)
        rec = dtypes_mod.narrowed_if_no_x64(out.dtype.base_dtype)
        if dt != rec:
            report(diags, ERROR, "verifier/infer-mismatch",
                   f"{op.name!r}:{i} records dtype {rec.name} but "
                   f"abstract eval derives {dt.name} — the lowering "
                   f"will produce {dt.name}", op=op)
        elif (sh.is_fully_defined()
                and out.shape.is_fully_defined()
                and sh.as_list() != out.shape.as_list()):
            report(diags, WARNING, "verifier/infer-mismatch",
                   f"{op.name!r}:{i} records shape "
                   f"{out.shape.as_list()} but abstract eval derives "
                   f"{sh.as_list()}", op=op)


def verify_graph(graph, fetches=None, level: str = "structural"
                 ) -> List[Diagnostic]:
    """Verify a whole live graph. ``fetches``: optional sequence of
    Tensors/Operations — enables the unreachable-stateful check (a
    stateful op outside the fetch closure is silently pruned)."""
    diags: List[Diagnostic] = []
    ops = graph.get_operations()
    verify_ops(ops, graph=graph, level=level, diags=diags)
    if fetches:
        _check_unreachable_stateful(graph, ops, fetches, diags)
    return diags


def _check_unreachable_stateful(graph, ops, fetches,
                                diags: List[Diagnostic]) -> None:
    targets = []
    for f in fetches:
        op = f if isinstance(f, ops_mod.Operation) else f.op
        targets.append(op)
    seen: Set[Any] = set()
    work = list(targets)
    while work:
        op = work.pop()
        if op in seen:
            continue
        seen.add(op)
        work.extend(t.op for t in op.inputs)
        work.extend(op.control_inputs)
    for op in ops:
        if op in seen:
            continue
        try:
            od = op_registry.get(op.type)
        except KeyError:
            continue
        if not od.is_stateful or op.type in ("NoOp", "Group"):
            continue
        eff = od.effects
        if not (eff and eff.writes):
            continue  # only silently-dropped *writes* are surprising
        report(diags, NOTE, "verifier/unreachable-stateful",
               f"stateful op {op.name!r} ({op.type}) is not an ancestor "
               "of any fetch — it will be silently pruned from this "
               "run (fetch it, or add it to a control dependency / "
               "stf.group)", op=op)


# ---------------------------------------------------------------------------
# GraphDef (serialized JSON dict) verification
# ---------------------------------------------------------------------------

def _tensor_ref(name: str):
    if ":" in name:
        node, idx = name.rsplit(":", 1)
        return node, int(idx)
    return name, 0


def verify_graphdef(graph_def: Dict, _path: str = "",
                    diags: Optional[List[Diagnostic]] = None
                    ) -> List[Diagnostic]:
    """Verify a GraphDef JSON dict (framework/graph_io.py wire format):
    duplicate names, unregistered op types, dangling input refs, output
    indices out of range, data+control cycles, FuncGraph body signature
    integrity — recursing into bodies. Standalone (no live Graph
    needed): this is what the ``graph_lint`` CLI and the PassManager
    pre/post invariant hooks run."""
    diags = diags if diags is not None else []
    nodes = graph_def.get("node", [])
    by_name: Dict[str, Dict] = {}
    where = f" in {_path}" if _path else ""

    def _src(n):
        s = n.get("source")
        return f"{s[0]}:{s[1]}" if s and len(s) == 3 else None

    for n in nodes:
        if n["name"] in by_name:
            report(diags, ERROR, "verifier/duplicate-name",
                   f"node name {n['name']!r} appears twice{where}",
                   op_name=n["name"], op_type=n.get("op"), source=_src(n))
        by_name[n["name"]] = n
    for n in nodes:
        if not op_registry.is_registered(n.get("op", "")):
            report(diags, ERROR, "verifier/unregistered-op",
                   f"node {n['name']!r} has unregistered op type "
                   f"{n.get('op')!r}{where}",
                   op_name=n["name"], op_type=n.get("op"), source=_src(n))
        for ref in n.get("input", []):
            src_name, idx = _tensor_ref(ref)
            producer = by_name.get(src_name)
            if producer is None:
                report(diags, ERROR, "verifier/dangling-input",
                       f"node {n['name']!r} input {ref!r} names a "
                       f"missing node{where}",
                       op_name=n["name"], op_type=n.get("op"),
                       source=_src(n))
                continue
            specs = producer.get("output_specs")
            if specs is not None and idx >= len(specs):
                report(diags, ERROR, "verifier/bad-output-index",
                       f"node {n['name']!r} input {ref!r}: producer has "
                       f"only {len(specs)} output(s){where}",
                       op_name=n["name"], op_type=n.get("op"),
                       source=_src(n))
        for c in n.get("control_input", []):
            if c not in by_name:
                report(diags, ERROR, "verifier/dangling-input",
                       f"node {n['name']!r} control input {c!r} names a "
                       f"missing node{where}",
                       op_name=n["name"], op_type=n.get("op"),
                       source=_src(n))
        # recurse into FuncGraph bodies
        for k, v in (n.get("attr") or {}).items():
            if isinstance(v, dict) and v.get("__kind__") == "funcgraph":
                body = v["v"]
                body_path = (f"{_path}/" if _path else "") \
                    + f"{n['name']}.{k}"
                verify_graphdef(body, _path=body_path, diags=diags)
                _verify_body_signature(body, n, body_path, diags)
    _check_graphdef_cycles(nodes, by_name, where, diags)
    return diags


def _verify_body_signature(body: Dict, owner: Dict, path: str,
                           diags: List[Diagnostic]) -> None:
    names = {bn["name"] for bn in body.get("node", [])}
    need = ([r for r in body.get("inputs", [])]
            + [r for r in body.get("outputs", [])]
            + [c[1] for c in body.get("captures", [])])
    for ref in need:
        if _tensor_ref(ref)[0] not in names:
            report(diags, ERROR, "verifier/funcgraph-signature",
                   f"body {path} signature ref {ref!r} resolves to no "
                   "body node", op_name=owner["name"],
                   op_type=owner.get("op"))


def _check_graphdef_cycles(nodes, by_name, where,
                           diags: List[Diagnostic]) -> None:
    state: Dict[str, int] = {}  # 0=visiting 1=done

    def deps(n):
        for ref in n.get("input", []):
            yield _tensor_ref(ref)[0]
        yield from n.get("control_input", [])

    for root in nodes:
        if state.get(root["name"]) == 1:
            continue
        stack = [(root["name"], None)]
        while stack:
            name, it = stack[-1]
            n = by_name.get(name)
            if n is None:
                stack.pop()
                continue
            if it is None:
                if state.get(name) is not None:
                    stack.pop()
                    continue
                state[name] = 0
                it = iter(list(deps(n)))
                stack[-1] = (name, it)
            advanced = False
            for d in it:
                if d not in by_name:
                    continue
                if state.get(d) is None:
                    stack.append((d, None))
                    advanced = True
                    break
                if state.get(d) == 0:  # includes d == name: a self-loop
                    cyc = " -> ".join(nm for nm, _ in stack[-5:])
                    if d == name:
                        cyc = f"{name} -> {name}"
                    report(diags, ERROR, "verifier/cycle",
                           f"data/control cycle near {cyc}{where}",
                           op_name=name, op_type=n.get("op"))
                    state[d] = 1  # break out; report once per region
            if not advanced:
                state[name] = 1
                stack.pop()
