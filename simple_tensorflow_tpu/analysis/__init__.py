"""stf.analysis: static analysis over the Graph IR (ISSUE 3 tentpole).

Three pillars, all emitting structured :class:`Diagnostic` objects that
carry the offending op's name/type and the user-code ``file:line``
captured at op creation:

- **verifier** (:mod:`.verifier`) — structural invariants: dangling
  inputs, ordering/cycle violations (including through FuncGraph
  bodies), abstract-eval dtype/shape re-checks, host/device staging
  violations, silently-pruned stateful ops.
- **variable-hazard detector** (:mod:`.hazards`) — RAW/WAR/WAW between
  effectful ops with no ordering path, over the declared per-op effect
  sets (framework/op_registry.py ``Effects``); modes
  off|warn|raise|auto_deps (auto_deps reproduces the reference's
  auto-control-dependencies by enforcing program order).
- **lint framework** (:mod:`.lint`) — registerable :class:`LintRule`
  checks with per-run severity config (numerics, RNG seeding,
  constant-foldable fetches, surviving transpose pairs).

Entry points: ``verify_graph`` / ``verify_graphdef`` / ``lint_graph``
standalone; ``analyze`` for the combined report; Session wires
``hazards.check_plan`` per run plan and ``verify_graph`` under
``ConfigProto(graph_analysis=...)``; PassManager runs ``verify_graphdef``
as pre/post pass invariants; ``python -m
simple_tensorflow_tpu.tools.graph_lint`` covers serialized graphs.
Monitoring: ``/stf/analysis/*`` counters (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence

from ..framework.graph import (set_traceback_capture,
                               traceback_capture_enabled)
from ..framework.op_registry import (Effects, declare_effects,
                                     register_sharding_rule)
from . import (autoshard, diagnostics, effects, hazards, lint, loop_safety,
               sharding, verifier)
from .autoshard import (AutoshardResult, DecodeTpChoice,
                        choose_decode_tp, search_sharding)
from .diagnostics import (ERROR, NOTE, WARNING, Diagnostic, errors,
                          format_report, max_severity, warnings)
from .effects import ResolvedEffects, op_effects
from .hazards import (MODES as HAZARD_MODES, Hazard, check_plan,
                      find_hazards, get_hazard_mode, set_hazard_mode)
from .loop_safety import certify_plan as certify_loop_safe
from .lint import (LintContext, LintRule, lint_graph, register_lint_rule,
                   registered_rules)
from .sharding import (CollectiveEdge, ShardingReport, analyze_sharding,
                       parse_mesh_arg)
from .verifier import verify_graph, verify_graphdef, verify_ops

__all__ = [
    "Diagnostic", "ERROR", "WARNING", "NOTE",
    "errors", "warnings", "max_severity", "format_report",
    "Effects", "ResolvedEffects", "op_effects", "declare_effects",
    "Hazard", "HAZARD_MODES", "find_hazards", "check_plan",
    "set_hazard_mode", "get_hazard_mode",
    "LintRule", "LintContext", "lint_graph", "register_lint_rule",
    "registered_rules",
    "verify_graph", "verify_graphdef", "verify_ops",
    "certify_loop_safe",
    "set_traceback_capture", "traceback_capture_enabled",
    "analyze",
    "analyze_sharding", "ShardingReport", "CollectiveEdge",
    "register_sharding_rule", "parse_mesh_arg",
    "search_sharding", "AutoshardResult",
]


def analyze(graph=None, fetches: Optional[Sequence[Any]] = None,
            level: str = "full",
            severities: Optional[dict] = None,
            mesh=None,
            sharding_seeds: Optional[dict] = None,
            purpose: Optional[str] = None,
            memory_budget: Optional[int] = None) -> List[Diagnostic]:
    """Run verifier + hazard detector + linter over a graph and return
    all diagnostics (the combined standalone entry point; the CLI and
    the models/examples CI gate call this). When ``mesh`` is given (a
    Mesh or abstract {axis: size} dict), the sharding analyzer runs too
    and its diagnostics are included. ``purpose="serving"`` activates
    the serving-compatibility lint over the fetch closure
    (``graph_lint --serving``)."""
    from ..framework import graph as ops_mod
    from ..framework import lowering as lowering_mod

    graph = graph or ops_mod.get_default_graph()
    diags = verify_graph(graph, fetches=fetches, level=level)
    if fetches:
        # hazards are a per-step property: analyze the fetch closure (the
        # plan Session.run would execute), not unrelated graph regions
        # that never share a step (init assigns vs. train reads)
        targets = [f if isinstance(f, ops_mod.Operation) else f.op
                   for f in fetches]
        plan = lowering_mod.prune(targets, set())
        for h in hazards.find_hazards(plan):
            diags.append(h.to_diagnostic(WARNING))
            diagnostics.metric_hazards.get_cell(h.kind).increase_by(1)
            diagnostics.metric_diagnostics.get_cell(
                WARNING).increase_by(1)
    diags.extend(lint_graph(graph, fetches=fetches, severities=severities,
                            purpose=purpose,
                            memory_budget=memory_budget))
    if mesh is not None:
        report = analyze_sharding(graph=graph, mesh=mesh,
                                  seed_specs=sharding_seeds,
                                  fetches=fetches, severities=severities,
                                  purpose=purpose,
                                  memory_budget=memory_budget)
        diags.extend(report.diagnostics)
    return diags
