"""Static partition-spec propagation + resharding/collective-cost
analysis over the Graph IR (ISSUE 6 tentpole).

GSPMD decides real placements only after a multi-second XLA compile; by
then a replicated 2 GB embedding or an all-gather inside a scan body is
a profile artifact, not a diagnostic. This pass makes sharding a
statically-analyzable property of the graph, the same way the verifier
makes structure one (1605.08695 §3-4 treats placement/communication
analysis as the precondition for scaling; 1909.09756 attributes most
lost pod efficiency to exactly the resharding/collective patterns
flagged here):

1. **Propagation** — PartitionSpecs seed from variable shardings
   (``Variable.set_sharding`` / ``shard_variables_along`` /
   ``match_partition_rules``), fed-placeholder shardings
   (``shard_feed``), and ``with_sharding_constraint`` ops, then flow
   forward AND backward through every op via per-op rules registered
   alongside abstract-eval in the op registry
   (``op_registry.register_sharding_rule``; declared across the ops/
   modules, FuncGraph bodies included). A conflict joins to replicated
   and emits ``sharding/conflict``.

2. **Resharding / collective detection** — every edge where the
   consumed spec differs from the produced spec is classified local /
   all-gather / all-to-all; rules report the collectives their op
   *implies* (contracted-sharded matmul -> all-reduce, gradient sync,
   batch-norm stats, explicit collective ops), each with estimated
   per-device payload bytes comparable to the shapes of the collective
   instructions XLA emits (utils/perf.collective_bytes_of harvests
   those for the bench comparison). Per-shard peak HBM reuses the cost
   model's liveness sweep with sharded byte accounting.

3. **Diagnostics** — everything lands in the PR 3 framework: lint rules
   ``lint/replicated-large-tensor``, ``lint/resharding-hotspot``,
   ``lint/mesh-axis-unused``, ``lint/uneven-shard`` plus the analyzer's
   own ``sharding/*`` codes, all counted on ``/stf/analysis/*``.

Entry points: :func:`analyze_sharding` (graph or op-list),
``Session._plan`` (mesh active -> per-plan report, cached with the
plan), ``tools.graph_lint --mesh/--rules`` (offline, abstract mesh — no
devices needed), and the model-zoo gate (1-device mesh, rule-gap
snapshot via ``sharding/no-rule``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, FrozenSet, List, Optional, Sequence,
                    Set, Tuple)

from ..framework import graph as ops_mod
from ..framework import op_registry
from ..platform import monitoring
from . import diagnostics as diag_mod
from .diagnostics import ERROR, NOTE, WARNING, Diagnostic

Tensor = ops_mod.Tensor
Operation = ops_mod.Operation

# -- monitoring --------------------------------------------------------------

metric_collectives = monitoring.Counter(
    "/stf/analysis/sharding_collectives",
    "collective edges detected by the sharding analyzer", "kind")
metric_collective_bytes = monitoring.Counter(
    "/stf/analysis/sharding_collective_bytes",
    "predicted collective payload bytes (trip-weighted)", "kind")
metric_sharding_seconds = monitoring.Sampler(
    "/stf/analysis/sharding_seconds",
    monitoring.ExponentialBuckets(1e-6, 4.0, 16),
    "sharding-analysis seconds per Session plan")

# -- spec algebra ------------------------------------------------------------
#
# Normalized spec: tuple with one entry per dim; entry = tuple of mesh
# axis names (() = dim unsharded). None = unknown rank (treated as
# replicated). This is jax.sharding.PartitionSpec with every entry
# canonicalized to a tuple.

REPLICATED: Tuple = ()

# provenance strengths (backward may only overwrite WEAK/BACK; forward
# recomputes WEAK/FWD; SEED never moves)
WEAK, BACK, FWD, SEED = 0, 1, 2, 3

LARGE_TENSOR_BYTES = int(os.environ.get(
    "STF_SHARDING_LARGE_BYTES", str(1 << 20)))


def replicated(rank: Optional[int]) -> Optional[Tuple]:
    if rank is None:
        return None
    return ((),) * rank


def normalize_spec(spec, rank: Optional[int]) -> Optional[Tuple]:
    """Canonicalize a PartitionSpec-like (stf P, jax PartitionSpec,
    list/tuple with None|str|sequence entries) to the internal form,
    padded/truncated to ``rank``."""
    if rank is None:
        return None
    if spec is None:
        return replicated(rank)
    entries: List[Tuple[str, ...]] = []
    for e in tuple(spec)[:rank]:
        if e is None:
            entries.append(())
        elif isinstance(e, str):
            entries.append((e,))
        else:
            entries.append(tuple(e))
    while len(entries) < rank:
        entries.append(())
    return tuple(entries)


def to_partition_spec(spec):
    """Internal spec -> jax-style entry tuple (None | axis | (axes...))
    for display and committed-sharding comparison."""
    if spec is None:
        return None
    out = []
    for e in spec:
        if not e:
            out.append(None)
        elif len(e) == 1:
            out.append(e[0])
        else:
            out.append(tuple(e))
    return tuple(out)


def spec_axes(spec) -> FrozenSet[str]:
    if not spec:
        return frozenset()
    return frozenset(a for e in spec for a in e)


def is_replicated(spec) -> bool:
    return spec is None or all(not e for e in spec)


def format_spec(spec) -> str:
    if spec is None:
        return "P(?)"
    if not spec:
        return "P()"
    return "P(" + ", ".join(
        ("None" if not e else e[0] if len(e) == 1 else str(tuple(e)))
        for e in spec) + ")"


def _dedupe_axes(spec):
    """An axis may shard at most one dim: keep the first occurrence."""
    if spec is None:
        return None
    seen: Set[str] = set()
    out = []
    for e in spec:
        keep = tuple(a for a in e if a not in seen)
        seen.update(keep)
        out.append(keep)
    return tuple(out)


def shard_factor(spec, mesh_axes: Dict[str, int]) -> int:
    """Product of the mesh-axis sizes sharding this spec (1 = fully
    replicated / unknown)."""
    n = 1
    for a in spec_axes(spec):
        n *= int(mesh_axes.get(a, 1))
    return max(n, 1)


def _nelems(shape) -> Optional[int]:
    if shape is None or shape.rank is None:
        return None
    n = 1
    for d in shape.dims:
        if d.value is None:
            return None
        n *= d.value
    return n


def tensor_bytes(t: Tensor) -> float:
    n = _nelems(t.shape)
    if n is None:
        return 0.0
    try:
        return float(n * t.dtype.base_dtype.size)
    except Exception:
        return 0.0


import threading as _threading

_tls = _threading.local()
_DIMS_MISS = object()


def _dims_of(t: Tensor) -> Optional[List[Optional[int]]]:
    """Static dims of a tensor, cached per analysis run (rules consult
    dims for most ops on every sweep; shapes never change under an
    analysis, and the cache is cleared at each analyze_sharding entry —
    thread-local because Session plans analyze on a worker thread)."""
    cache = getattr(_tls, "dims_cache", None)
    if cache is None:
        cache = _tls.dims_cache = {}
    hit = cache.get(t, _DIMS_MISS)
    if hit is not _DIMS_MISS:
        return hit
    if t.shape.rank is None:
        out = None
    else:
        out = [d.value for d in t.shape.dims]
    cache[t] = out
    return out


# -- report ------------------------------------------------------------------

@dataclass
class CollectiveEdge:
    """One materialized (or implied) collective: an edge whose consumed
    spec differs from the produced one, or a rule-reported collective
    the op's semantics force (contraction over a sharded dim, gradient
    sync). ``nbytes`` is the per-device payload of ONE occurrence;
    ``trip`` multiplies it for edges inside loop bodies."""

    op: Any
    kind: str                      # all-gather | all-reduce | all-to-all | slice | collective-permute
    axes: Tuple[str, ...]
    nbytes: float
    tensor_name: str = ""
    note: str = ""
    trip: int = 1
    in_loop: bool = False

    @property
    def total_bytes(self) -> float:
        return self.nbytes * max(self.trip, 1)

    def to_dict(self) -> dict:
        return {"op": getattr(self.op, "name", None),
                "op_type": getattr(self.op, "type", None),
                "kind": self.kind, "axes": list(self.axes),
                "bytes": self.nbytes, "trip": self.trip,
                "in_loop": self.in_loop, "tensor": self.tensor_name,
                "note": self.note}


_COMM_KINDS = ("all-gather", "all-reduce", "all-to-all",
               "collective-permute")


@dataclass
class ShardingReport:
    """Result of one sharding analysis."""

    mesh_axes: Dict[str, int] = field(default_factory=dict)
    specs: Dict[Any, Tuple] = field(default_factory=dict)   # Tensor -> spec
    edges: List[CollectiveEdge] = field(default_factory=list)
    diagnostics: List[Diagnostic] = field(default_factory=list)
    # raw lint facts (consumed by the registered lint rules)
    variables: Dict[str, Tuple[Any, float, Any]] = field(
        default_factory=dict)  # var_name -> (op, nbytes, spec)
    uneven: List[Tuple[Any, str, int, Tuple[str, ...], int]] = field(
        default_factory=list)  # (op, tensor_name, dim, axes, dim_size)
    no_rule_types: Dict[str, Any] = field(default_factory=dict)
    per_shard_peak_bytes: Optional[float] = None
    analysis_seconds: float = 0.0

    @property
    def mesh_size(self) -> int:
        n = 1
        for s in self.mesh_axes.values():
            n *= int(s)
        return n

    def spec_of(self, tensor) -> Optional[Tuple]:
        """Final spec in jax-PartitionSpec entry form (None entries for
        unsharded dims); None for unknown-rank tensors."""
        return to_partition_spec(self.specs.get(tensor))

    def collective_edges(self) -> List[CollectiveEdge]:
        return [e for e in self.edges if e.kind in _COMM_KINDS]

    def total_collective_bytes(self) -> float:
        return sum(e.total_bytes for e in self.collective_edges())

    def bytes_by_kind(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for e in self.collective_edges():
            out[e.kind] = out.get(e.kind, 0.0) + e.total_bytes
        return out

    def per_op_collectives(self) -> Dict[str, List[dict]]:
        out: Dict[str, List[dict]] = {}
        for e in self.collective_edges():
            out.setdefault(getattr(e.op, "name", "?"), []).append(
                e.to_dict())
        return out

    def summary(self) -> dict:
        return {
            "mesh": dict(self.mesh_axes),
            "total_collective_bytes": self.total_collective_bytes(),
            "bytes_by_kind": self.bytes_by_kind(),
            "n_collective_edges": len(self.collective_edges()),
            "n_diagnostics": len(self.diagnostics),
            "per_shard_peak_bytes": self.per_shard_peak_bytes,
            "analysis_seconds": round(self.analysis_seconds, 6),
        }


# -- mesh handling -----------------------------------------------------------

def _as_mesh_axes(mesh) -> Dict[str, int]:
    """Accept a parallel.Mesh, a jax Mesh, or a plain {axis: size} dict
    (the abstract form — offline analysis needs no devices)."""
    if mesh is None:
        return {}
    if isinstance(mesh, dict):
        return {str(k): int(v) for k, v in mesh.items()}
    shape = getattr(mesh, "shape", None)
    if isinstance(shape, dict):  # parallel.Mesh / jax mesh.shape mapping
        return {str(k): int(v) for k, v in dict(shape).items()}
    raise TypeError(f"cannot interpret mesh {mesh!r}; pass a "
                    "stf.parallel.Mesh or a {{axis: size}} dict")


def parse_mesh_arg(arg: str) -> Dict[str, int]:
    """CLI mesh spec: ``8`` -> {'dp': 8}; ``2x4`` -> {'dp': 2, 'tp': 4};
    ``dp=2,tp=4`` -> as named. The first two forms use the canonical
    axis-name order (mesh.CANONICAL_AXES prefix dp, tp)."""
    arg = arg.strip()
    if "=" in arg:
        out: Dict[str, int] = {}
        for part in arg.split(","):
            k, v = part.split("=", 1)
            out[k.strip()] = int(v)
        return out
    sizes = [int(p) for p in arg.lower().split("x")]
    names = ("dp", "tp", "sp", "ep")
    if len(sizes) > len(names):
        raise ValueError(f"--mesh {arg!r}: at most {len(names)} unnamed "
                         "axes; use name=size,... form")
    return {names[i]: s for i, s in enumerate(sizes)}


# -- rule context ------------------------------------------------------------

class RuleContext:
    """What one rule application sees. ``require``/``collective``/
    ``diag`` only take effect during the final record pass (quiet
    fixpoint iterations discard them)."""

    def __init__(self, engine: "_Engine", op: Operation, record: bool):
        self._engine = engine
        self._op = op
        self.record = record
        self.mesh_axes = engine.mesh_axes
        self.required: Dict[int, Tuple] = {}

    @property
    def data_axes(self) -> FrozenSet[str]:
        """Mesh axes that shard fed data (placeholder/boundary seeds):
        a contraction over the batch crosses these even when the
        contracted operand's own spec carries the axis on another dim
        (the ZeRO-layout gradient reduce-scatter)."""
        return self._engine.data_axes

    # -- helpers -------------------------------------------------------------
    def axis_size(self, axes) -> int:
        if isinstance(axes, str):
            axes = (axes,)
        n = 1
        for a in axes:
            n *= int(self.mesh_axes.get(a, 1))
        return max(n, 1)

    def shard_factor(self, spec) -> int:
        return shard_factor(spec, self.mesh_axes)

    def spec(self, tensor) -> Optional[Tuple]:
        """Propagated spec of an arbitrary in-scope tensor (replicated
        default for unvisited ones)."""
        hit = self._engine.env.get(tensor)
        if hit is not None:
            return hit[0]
        return replicated(tensor.shape.rank)

    def var_spec(self, var_name: Optional[str],
                 rank: Optional[int]) -> Optional[Tuple]:
        """Declared/seeded spec of a variable (None if unsharded)."""
        if var_name is None:
            return None
        return self._engine._var_spec(var_name, rank, self._op)

    def join(self, a, b) -> Optional[Tuple]:
        return self._engine.join(a, b, self._op, self)

    # -- effects -------------------------------------------------------------
    def require(self, idx: int, spec) -> None:
        """Declare that this op consumes input ``idx`` laid out as
        ``spec``; the engine compares with the produced spec and records
        the resharding edge."""
        self.required[idx] = spec

    def collective(self, kind: str, axes, nbytes: float,
                   note: str = "", tensor_name: str = "") -> None:
        if not self.record:
            return
        if isinstance(axes, str):
            axes = (axes,)
        axes = tuple(a for a in axes if self.mesh_axes.get(a, 1) > 1)
        if not axes:
            return
        self._engine.add_edge(CollectiveEdge(
            op=self._op, kind=kind, axes=axes, nbytes=float(nbytes),
            note=note, tensor_name=tensor_name))

    def diag(self, severity: str, code: str, message: str,
             op: Optional[Operation] = None) -> None:
        if not self.record:
            return
        self._engine.diag(severity, code, message, op or self._op)

    def analyze_body(self, fg, arg_specs: Sequence[Optional[Tuple]],
                     trip: Optional[int] = None,
                     loop: bool = False,
                     capture_outers: Optional[Sequence[Any]] = None,
                     record: Optional[bool] = None
                     ) -> List[Optional[Tuple]]:
        """Propagate through a FuncGraph body: seeds fg.inputs with
        ``arg_specs`` and captures with their outer specs, sweeps the
        body, returns the specs of fg.outputs. During the record pass,
        body edges are charged x ``trip`` (unknown trip counts once but
        keeps the in-loop flag for the hotspot rule). ``capture_outers``
        re-binds None-outer captures (imported FuncGraphs) to the outer
        tensors the op passes positionally in its input list.
        ``record=False`` forces a quiet sweep even inside the record
        pass — loop rules use it for carry-fixpoint rounds so body edges
        are recorded exactly once, by the final sweep."""
        return self._engine.analyze_body(
            fg, arg_specs, self, trip=trip, loop=loop,
            capture_outers=capture_outers,
            record=self.record if record is None else record)


# -- the engine --------------------------------------------------------------

_HOSTY_TYPES = ("Placeholder", "PlaceholderWithDefault", "Const", "NoOp")


class _Engine:
    def __init__(self, mesh_axes: Dict[str, int],
                 seed_specs: Optional[Dict[str, Any]] = None):
        self.mesh_axes = dict(mesh_axes)
        # Tensor -> (spec, strength)
        self.env: Dict[Tensor, Tuple[Optional[Tuple], int]] = {}
        self.report = ShardingReport(mesh_axes=dict(mesh_axes))
        self.seed_specs = dict(seed_specs or {})  # var/op name -> spec-like
        self._var_specs: Dict[str, Tuple[Optional[Tuple], Any]] = {}
        self._trip_stack: List[int] = []
        self._loop_depth = 0
        self._grad_path_cache: Dict[Operation, FrozenSet[str]] = {}
        self._uneven_seen: Set[str] = set()
        # axes sharding fed data (populated by seed()): consumed by the
        # SymbolicGradient rule's batch-contraction sync accounting
        self.data_axes: FrozenSet[str] = frozenset()

    # -- diagnostics/edges ---------------------------------------------------
    def diag(self, severity, code, message, op):
        diag_mod.report(self.report.diagnostics, severity, code, message,
                        op=op)

    def add_edge(self, edge: CollectiveEdge):
        if self._trip_stack:
            t = 1
            for x in self._trip_stack:
                t *= max(int(x), 1)
            edge.trip = t
            edge.in_loop = True
        self.report.edges.append(edge)

    # -- join ----------------------------------------------------------------
    def join(self, a, b, op, ctx: Optional[RuleContext] = None
             ) -> Optional[Tuple]:
        """Dim-wise join: unsharded yields to sharded; two different
        sharded entries conflict -> replicated + sharding/conflict."""
        if a is None:
            return b
        if b is None:
            return a
        if len(a) != len(b):
            return a  # rank mismatch: caller aligns before joining
        out = []
        for i, (ea, eb) in enumerate(zip(a, b)):
            if ea == eb:
                out.append(ea)
            elif not ea:
                out.append(eb)
            elif not eb:
                out.append(ea)
            else:
                if ctx is not None:
                    ctx.diag(NOTE, "sharding/conflict",
                             f"dim {i} sharded as {ea} by one operand and "
                             f"{eb} by another; joined to replicated")
                out.append(())
        return _dedupe_axes(tuple(out))

    # -- seeds ---------------------------------------------------------------
    def _variable_registry(self, ops: Sequence[Operation]) -> Dict[str, Any]:
        for op in ops:
            g = op.graph
            while getattr(g, "outer_graph", None) is not None:
                g = g.outer_graph
            reg = getattr(g, "_scoped_state", {}).get(
                "__vars_by_store_name__")
            if reg:
                return reg
        return {}

    def _var_spec(self, var_name: str, shape_rank: Optional[int],
                  op: Operation) -> Optional[Tuple]:
        hit = self._var_specs.get(var_name)
        if hit is not None:
            return hit[0]
        raw = self.seed_specs.get(var_name)
        spec = normalize_spec(raw, shape_rank) if raw is not None else None
        self._var_specs[var_name] = (spec, op)
        return spec

    def seed(self, ops: Sequence[Operation]):
        """Collect variable/feed shardings before the sweeps."""
        registry = self._variable_registry(ops)
        for name, var in registry.items():
            try:
                raw = self.seed_specs.get(name, var.sharding)
                rank = var.shape.rank
                spec = (normalize_spec(raw, rank)
                        if raw is not None else None)
                self._var_specs[name] = (spec, var.op)
            except Exception:
                continue
        for op in ops:
            if op.type in ("VariableV2",):
                vn = op.attrs.get("var_name", op.name)
                raw = self.seed_specs.get(vn, op.attrs.get("sharding"))
                if vn not in self._var_specs or raw is not None:
                    rank = op.outputs[0].shape.rank if op.outputs else None
                    self._var_specs[vn] = (
                        normalize_spec(raw, rank) if raw is not None
                        else None, op)
        # boundary tensors (fed placeholders, pre-computed host values):
        # their producers are pruned out of a per-run plan, so their
        # declared shardings must seed the env directly
        op_set = set(ops)
        for op in ops:
            for t in op.inputs:
                if t.op in op_set or t in self.env:
                    continue
                src = t.op
                raw = self.seed_specs.get(t.name,
                                          self.seed_specs.get(src.name))
                if raw is None:
                    raw = src.attrs.get("sharding")
                if raw is None and src.type in ("VariableV2",
                                                "ReadVariable"):
                    vn = src.attrs.get("var_name", src.name)
                    spec = self._var_spec(vn, t.shape.rank, src)
                    if spec is not None:
                        self.env[t] = (spec, SEED)
                    continue
                if raw is not None:
                    self.env[t] = (normalize_spec(raw, t.shape.rank),
                                   SEED)
        # data axes: what shards the fed batch (placeholder shardings +
        # non-variable boundary seeds) — the gradient rule's
        # batch-contraction sync needs them (see RuleContext.data_axes)
        data: Set[str] = set()
        for op in ops:
            if op.type in ("Placeholder", "PlaceholderWithDefault"):
                raw = self.seed_specs.get(op.name,
                                          op.attrs.get("sharding"))
                if raw is not None and op.outputs:
                    data |= spec_axes(normalize_spec(
                        raw, op.outputs[0].shape.rank))
        for t, (spec, strength) in self.env.items():
            if strength >= SEED and t.op.type not in ("VariableV2",
                                                      "ReadVariable"):
                data |= spec_axes(spec)
        self.data_axes = frozenset(
            a for a in data if self.mesh_axes.get(a, 1) > 1)

    # -- the sweeps ----------------------------------------------------------
    def _outputs_default(self, op: Operation, in_specs, ctx: RuleContext,
                         strengths: List[int]) -> List[Optional[Tuple]]:
        """Conservative fallback for op types without a rule: outputs
        replicated; a sharded input is consumed replicated (all-gather)
        and — for device ops — flags the rule gap once per op type."""
        sharded_in = [i for i, s in enumerate(in_specs)
                      if s is not None and not is_replicated(s)]
        hosty = op.op_def.runs_on_host or op.type in _HOSTY_TYPES
        for i in sharded_in:
            ctx.require(i, replicated(len(in_specs[i])))
        if sharded_in and not hosty and ctx.record \
                and op.type not in self.report.no_rule_types:
            self.report.no_rule_types[op.type] = op
            ctx.diag(NOTE, "sharding/no-rule",
                     f"op type {op.type} has no sharding propagation "
                     "rule; sharded inputs are assumed gathered and "
                     "outputs replicated (register one via "
                     "op_registry.register_sharding_rule)")
        return [replicated(t.shape.rank) for t in op.outputs]

    def _apply_op(self, op: Operation, record: bool):
        # seeds first: they are authoritative regardless of rules
        if op.type == "VariableV2":
            vn = op.attrs.get("var_name", op.name)
            spec = self._var_spec(
                vn, op.outputs[0].shape.rank if op.outputs else None, op)
            strength = SEED if spec is not None else WEAK
            for t in op.outputs:
                self._set(t, spec if spec is not None
                          else replicated(t.shape.rank), strength)
            return
        if op.type == "ReadVariable":
            vn = op.attrs.get("var_name")
            spec = self._var_spec(vn, op.outputs[0].shape.rank, op) \
                if vn is not None else None
            self._set(op.outputs[0], spec if spec is not None
                      else replicated(op.outputs[0].shape.rank),
                      SEED if spec is not None else WEAK)
            return
        if op.type in ("Placeholder", "PlaceholderWithDefault"):
            raw = self.seed_specs.get(op.name, op.attrs.get("sharding"))
            for t in op.outputs:
                if raw is not None:
                    self._set(t, normalize_spec(raw, t.shape.rank), SEED)
                else:
                    self._set(t, replicated(t.shape.rank), WEAK)
            return

        in_specs = []
        strengths = []
        for t in op.inputs:
            hit = self.env.get(t)
            if hit is None:
                hit = (replicated(t.shape.rank), WEAK)
            in_specs.append(hit[0])
            strengths.append(hit[1])

        ctx = RuleContext(self, op, record)
        rule = op_registry.sharding_rule(op.type)
        out_specs = None
        if rule is not None:
            try:
                out_specs = rule(op, in_specs, ctx)
            except Exception as e:  # a rule bug must never sink a plan
                if record:
                    self.diag(NOTE, "sharding/rule-error",
                              f"sharding rule for {op.type} failed: "
                              f"{type(e).__name__}: {e}", op)
                out_specs = None
        if out_specs is None:
            out_specs = self._outputs_default(op, in_specs, ctx, strengths)

        out_strength = FWD if any(s > WEAK for s in strengths) else WEAK
        if rule is not None and getattr(rule, "seeds_outputs", False):
            out_strength = SEED
        for t, s in zip(op.outputs, out_specs):
            if s is not None and t.shape.rank is not None \
                    and len(s) != t.shape.rank:
                s = replicated(t.shape.rank)
            self._set(t, _dedupe_axes(s), out_strength)

        if record:
            self._record_edges(op, in_specs, ctx)
            self._check_uneven(op, ctx)

    def _set(self, t: Tensor, spec, strength: int):
        cur = self.env.get(t)
        if cur is not None:
            if cur[1] >= SEED:
                return
            if cur[1] == BACK and strength <= FWD:
                # backward info survives forward recomputation
                return
        self.env[t] = (spec, strength)

    def suggest_back(self, t: Tensor, spec):
        cur = self.env.get(t)
        if cur is not None and cur[1] not in (WEAK, BACK):
            return
        if spec is None:
            return
        if t.shape.rank is not None and len(spec) != t.shape.rank:
            return
        self.env[t] = (_dedupe_axes(spec), BACK)

    def forward(self, ops: Sequence[Operation], record: bool = False):
        for op in ops:
            self._apply_op(op, record)

    def backward(self, ops: Sequence[Operation]):
        for op in reversed(ops):
            rule = op_registry.sharding_rule(op.type)
            bwd = getattr(rule, "backward", None) if rule else None
            if bwd is None:
                continue
            out_specs = [self.env.get(t, (replicated(t.shape.rank),
                                          WEAK))[0] for t in op.outputs]
            in_specs = [self.env.get(t, (replicated(t.shape.rank),
                                         WEAK))[0] for t in op.inputs]
            ctx = RuleContext(self, op, record=False)
            try:
                suggestions = bwd(op, out_specs, in_specs, ctx)
            except Exception:
                continue
            if not suggestions:
                continue
            for t, s in zip(op.inputs, suggestions):
                if s is not None:
                    self.suggest_back(t, s)

    # -- record-pass bookkeeping --------------------------------------------
    def _record_edges(self, op: Operation, in_specs, ctx: RuleContext):
        for idx, want in ctx.required.items():
            have = in_specs[idx]
            t = op.inputs[idx]
            edge = classify_reshard(have, want, t, self.mesh_axes)
            if edge is None:
                continue
            kind, axes, nbytes = edge
            self.add_edge(CollectiveEdge(
                op=op, kind=kind, axes=axes, nbytes=nbytes,
                tensor_name=t.name,
                note=f"{format_spec(have)} -> {format_spec(want)}"))

    def _check_uneven(self, op: Operation, ctx: RuleContext):
        for t in op.outputs:
            spec = self.env.get(t, (None, WEAK))[0]
            if spec is None or is_replicated(spec):
                continue
            dims = _dims_of(t)
            if dims is None:
                continue
            for i, e in enumerate(spec):
                if not e or i >= len(dims) or dims[i] is None:
                    continue
                f = ctx.axis_size(e)
                if f > 1 and dims[i] % f != 0 \
                        and t.name not in self._uneven_seen:
                    self._uneven_seen.add(t.name)
                    self.report.uneven.append(
                        (op, t.name, i, tuple(e), dims[i]))

    # -- FuncGraph bodies ----------------------------------------------------
    def analyze_body(self, fg, arg_specs, ctx: RuleContext,
                     trip: Optional[int] = None, loop: bool = False,
                     capture_outers: Optional[Sequence[Any]] = None,
                     record: Optional[bool] = None
                     ) -> List[Optional[Tuple]]:
        from ..framework import lowering as lowering_mod

        if record is None:
            record = ctx.record

        saved: Dict[Tensor, Any] = {}

        def stash_set(t, spec, strength):
            if t not in saved:
                saved[t] = self.env.get(t)
            self.env[t] = (spec, strength)

        for t, s in zip(fg.inputs, arg_specs):
            stash_set(t, normalize_spec(s, t.shape.rank)
                      if s is not None else replicated(t.shape.rank), SEED)
        for j, (outer, inner) in enumerate(fg.captures):
            # an imported FuncGraph's captures have outer=None; the loop
            # rule re-binds them from the op's positional inputs (the
            # lowerer does the same) — otherwise seed replicated
            if outer is None and capture_outers is not None \
                    and j < len(capture_outers):
                outer = capture_outers[j]
            if outer is None:
                spec = replicated(inner.shape.rank)
            else:
                hit = self.env.get(outer)
                spec = hit[0] if hit else replicated(outer.shape.rank)
            stash_set(inner, spec, SEED)
        try:
            plan = lowering_mod.prune(
                [t.op for t in fg.outputs],
                fed_tensors=set(fg.inputs)
                | {inner for _, inner in fg.captures})
        except Exception:
            return [replicated(t.shape.rank) for t in fg.outputs]
        if loop:
            self._trip_stack.append(trip if trip else 1)
        try:
            self.forward(plan, record=record)
        finally:
            if loop:
                self._trip_stack.pop()
        outs = [self.env.get(t, (replicated(t.shape.rank), WEAK))[0]
                for t in fg.outputs]
        # body-local tensors must not leak across analyses of the same
        # body with different arg specs (fixpoint iterations)
        for t, old in saved.items():
            if old is None:
                self.env.pop(t, None)
            else:
                self.env[t] = old
        return outs


def classify_reshard(have, want, tensor: Tensor, mesh_axes: Dict[str, int]
                     ) -> Optional[Tuple[str, Tuple[str, ...], float]]:
    """Classify the layout change ``have -> want`` of one edge.

    Returns (kind, axes, per-device payload bytes) or None for a free
    edge. Payload is sized like the collective instruction XLA would
    emit: the RESULT's per-device bytes (an all-gather to replicated
    moves the full tensor; an all-to-all keeps it sharded)."""
    if have is None or want is None:
        return None
    have = normalize_spec(have, len(have))
    want = normalize_spec(want, len(want))
    if have == want:
        return None
    lost: Set[str] = set()
    gained: Set[str] = set()
    for i in range(min(len(have), len(want))):
        ha, wa = set(have[i]), set(want[i])
        lost.update(a for a in ha - wa if mesh_axes.get(a, 1) > 1)
        gained.update(a for a in wa - ha if mesh_axes.get(a, 1) > 1)
    if not lost and not gained:
        return None
    gb = tensor_bytes(tensor)
    if lost and gained:
        kind = "all-to-all"
        axes = tuple(sorted(lost | gained))
    elif lost:
        kind = "all-gather"
        axes = tuple(sorted(lost))
    else:
        # replicated -> sharded is a local slice: no wire traffic
        kind = "slice"
        axes = tuple(sorted(gained))
    nbytes = gb / shard_factor(want, mesh_axes)
    return kind, axes, nbytes


# ---------------------------------------------------------------------------
# rule factories (used by the ops/ modules to declare per-op rules)
# ---------------------------------------------------------------------------

def _out_rank(op: Operation, i: int = 0) -> Optional[int]:
    if i < len(op.outputs):
        return op.outputs[i].shape.rank
    return None


def _aligned_entry(spec, dims, out_rank: int, out_dim: int,
                   out_dims=None) -> Tuple[str, ...]:
    """Entry of ``spec`` feeding output dim ``out_dim`` under numpy
    broadcasting (rank-aligned from the right; size-1 dims broadcast and
    contribute no sharding)."""
    if spec is None or dims is None:
        return ()
    r = len(spec)
    d = out_dim - (out_rank - r)
    if d < 0 or d >= r:
        return ()
    if dims[d] == 1 and (out_dims is None or out_dims[out_dim] != 1):
        return ()
    return spec[d]


def elementwise_rule(op: Operation, in_specs, ctx: RuleContext):
    """Broadcasting elementwise: the output spec is the dim-aligned join
    of the input specs; operands disagreeing with the join are consumed
    resharded."""
    out = op.outputs[0]
    out_dims = _dims_of(out)
    r = out.shape.rank
    if r is None:
        return [None for _ in op.outputs]
    # fast paths for the two dominant shapes of elementwise traffic —
    # unary (Relu/Cast/Neg/...) and same-spec n-ary — which need no
    # per-dim broadcast alignment
    s0 = in_specs[0] if in_specs else None
    if s0 is not None and len(s0) == r:
        if len(in_specs) == 1:
            if _dims_of(op.inputs[0]) == out_dims:
                return [s0 for _ in op.outputs]
        elif all(s is not None and s == s0 and
                 _dims_of(t) == out_dims
                 for t, s in zip(op.inputs, in_specs)):
            return [s0 for _ in op.outputs]
    entries = []
    for d in range(r):
        cands = []
        for t, s in zip(op.inputs, in_specs):
            e = _aligned_entry(s, _dims_of(t), r, d, out_dims)
            if e:
                cands.append(e)
        pick: Tuple[str, ...] = ()
        for e in cands:
            if not pick:
                pick = e
            elif e != pick:
                ctx.diag(NOTE, "sharding/conflict",
                         f"dim {d} sharded as {pick} and {e} by different "
                         "operands; joined to replicated")
                pick = ()
                break
        entries.append(pick)
    out_spec = _dedupe_axes(tuple(entries))
    # each operand is consumed at the out spec restricted to its dims
    for i, (t, s) in enumerate(zip(op.inputs, in_specs)):
        dims = _dims_of(t)
        if s is None or dims is None:
            continue
        want = []
        for d in range(len(dims)):
            od = d + (r - len(dims))
            want.append(out_spec[od]
                        if dims[d] != 1 and 0 <= od < r else ())
        want_t = tuple(want)
        if want_t != s:
            ctx.require(i, want_t)
    return [out_spec for _ in op.outputs]


def _elementwise_backward(op, out_specs, in_specs, ctx):
    src = out_specs[0]
    if src is None:
        return None
    r = len(src)
    outs = []
    for t, s in zip(op.inputs, in_specs):
        dims = _dims_of(t)
        if dims is None:
            outs.append(None)
            continue
        want = []
        for d in range(len(dims)):
            od = d + (r - len(dims))
            want.append(src[od] if 0 <= od < r and dims[d] != 1 else ())
        outs.append(tuple(want))
    return outs


elementwise_rule.backward = _elementwise_backward


def passthrough_rule(op: Operation, in_specs, ctx: RuleContext):
    """Output 0 mirrors input 0 (Identity/Cast-like, rank-preserving)."""
    s = in_specs[0] if in_specs else None
    return [s if i == 0 else replicated(_out_rank(op, i))
            for i in range(len(op.outputs))]


passthrough_rule.backward = lambda op, out_specs, in_specs, ctx: (
    [out_specs[0]] + [None] * (len(in_specs) - 1) if in_specs else None)


def local_rule(op: Operation, in_specs, ctx: RuleContext):
    """Outputs replicated but sharded inputs are consumed AS-IS (no
    gather): per-element/slicing ops whose result is host-small."""
    return [replicated(t.shape.rank) for t in op.outputs]


def make_reduce_rule(axis_attr: str = "axis",
                     keepdims_attr: str = "keepdims"):
    def rule(op: Operation, in_specs, ctx: RuleContext):
        x = op.inputs[0]
        s = in_specs[0]
        dims = _dims_of(x)
        if s is None or dims is None:
            return [replicated(_out_rank(op, i))
                    for i in range(len(op.outputs))]
        axis = op.attrs.get(axis_attr)
        if axis is None:
            red = list(range(len(dims)))
        elif isinstance(axis, (list, tuple)):
            red = [int(a) % len(dims) for a in axis]
        else:
            red = [int(axis) % len(dims)]
        keep = bool(op.attrs.get(keepdims_attr, False))
        red_axes = set()
        for d in red:
            red_axes.update(a for a in s[d]
                            if ctx.mesh_axes.get(a, 1) > 1)
        out_entries = []
        for d in range(len(dims)):
            if d in red:
                if keep:
                    out_entries.append(())
            else:
                out_entries.append(s[d])
        out_spec = tuple(out_entries)
        if red_axes:
            out_t = op.outputs[0]
            ctx.collective(
                "all-reduce", tuple(sorted(red_axes)),
                tensor_bytes(out_t) / ctx.shard_factor(out_spec),
                note=f"reduction over sharded dim(s) of {x.name}",
                tensor_name=out_t.name)
        return [out_spec for _ in op.outputs]

    return rule


def matmul_rule(op: Operation, in_specs, ctx: RuleContext):
    """(batch..., m, k) x (batch..., k, n): batch/m from lhs, n from rhs;
    a sharded contracted dim implies an all-reduce of the output."""
    a, b = op.inputs[0], op.inputs[1]
    sa, sb = in_specs[0], in_specs[1]
    da, db = _dims_of(a), _dims_of(b)
    r = _out_rank(op)
    if sa is None or sb is None or da is None or db is None or r is None:
        return [replicated(r)]
    ta = bool(op.attrs.get("transpose_a", op.attrs.get("adj_x", False)))
    tb = bool(op.attrs.get("transpose_b", op.attrs.get("adj_y", False)))
    am, ak = (len(da) - 1, len(da) - 2) if ta else (len(da) - 2,
                                                   len(da) - 1)
    bk, bn = (len(db) - 1, len(db) - 2) if tb else (len(db) - 2,
                                                   len(db) - 1)
    if len(da) < 2 or len(db) < 2:
        return [replicated(r)]
    # contracted dim: both operands should agree; on disagreement we
    # approximate GSPMD by resharding rhs to lhs's k sharding
    k_axes = set(sa[ak]) | set(sb[bk])
    k_axes = {x for x in k_axes if ctx.mesh_axes.get(x, 1) > 1}
    if set(sa[ak]) != set(sb[bk]):
        want_b = list(sb)
        want_b[bk] = sa[ak]
        ctx.require(1, tuple(want_b))
    out = [()] * r
    # batch dims from lhs (aligned right, before m/n)
    for d in range(r - 2):
        ad = d - (r - len(da))
        out[d] = sa[ad] if 0 <= ad < len(da) - 2 else ()
    out[r - 2] = sa[am]
    out[r - 1] = sb[bn]
    out_spec = _dedupe_axes(tuple(out))
    # axis collision: an rhs n-dim axis already sharding an earlier
    # output dim (lhs batch/m) cannot shard n too — GSPMD gathers the
    # rhs (the ZeRO layout's per-step weight all-gather; without this
    # a dp-batch x dp-cout matmul priced as free)
    dropped_n = {a for a in sb[bn]
                 if a not in out_spec[r - 1]
                 and ctx.mesh_axes.get(a, 1) > 1}
    if dropped_n:
        # compose with any k-resharding requirement already recorded
        want_b = list(ctx.required.get(1, sb))
        want_b[bn] = tuple(a for a in sb[bn] if a not in dropped_n)
        ctx.require(1, tuple(want_b))
    if set(sa[ak]) & k_axes:
        shared = tuple(sorted(set(sa[ak]) & k_axes))
        out_t = op.outputs[0]
        ctx.collective(
            "all-reduce", shared,
            tensor_bytes(out_t) / ctx.shard_factor(out_spec),
            note="contraction over sharded dim", tensor_name=out_t.name)
    return [out_spec]


def transpose_rule(op: Operation, in_specs, ctx: RuleContext):
    s = in_specs[0]
    if s is None:
        return [None]
    perm = op.attrs.get("perm")
    if perm is None:
        perm = tuple(reversed(range(len(s))))
    return [tuple(s[int(p)] for p in perm)]


def _transpose_backward(op, out_specs, in_specs, ctx):
    s = out_specs[0]
    if s is None:
        return None
    perm = op.attrs.get("perm")
    if perm is None:
        perm = tuple(reversed(range(len(s))))
    inv = [0] * len(perm)
    for i, p in enumerate(perm):
        inv[int(p)] = i
    return [tuple(s[i] for i in inv)] + [None] * (len(in_specs) - 1)


transpose_rule.backward = _transpose_backward


def reshape_rule(op: Operation, in_specs, ctx: RuleContext):
    """Keep a sharded dim that maps 1:1 (same size, same left-to-right
    position among non-unit dims... approximated by prefix products);
    anything murkier replicates with an all-gather."""
    x = op.inputs[0]
    s = in_specs[0]
    in_dims = _dims_of(x)
    out = op.outputs[0]
    out_dims = _dims_of(out)
    if s is None or in_dims is None or out_dims is None:
        return [replicated(_out_rank(op))]
    if is_replicated(s):
        return [replicated(len(out_dims))]
    # prefix products align dim boundaries between the two shapes
    def prefixes(dims):
        out, p = {}, 1
        for i, d in enumerate(dims):
            out[i] = p
            p *= (d or 1)
        return out, p

    pin, tot_in = prefixes(in_dims)
    pout, tot_out = prefixes(out_dims)
    entries = [()] * len(out_dims)
    lost: Set[str] = set()
    for i, e in enumerate(s):
        if not e:
            continue
        placed = False
        for j in range(len(out_dims)):
            if pin[i] == pout[j] and in_dims[i] == out_dims[j]:
                entries[j] = e
                placed = True
                break
            # a sharded dim split/merged as the OUTER factor keeps its
            # sharding (the shards stay contiguous)
            if pin[i] == pout[j] and out_dims[j] is not None \
                    and in_dims[i] is not None \
                    and out_dims[j] % max(ctx.axis_size(e), 1) == 0 \
                    and (in_dims[i] % out_dims[j] == 0
                         or out_dims[j] % in_dims[i] == 0):
                entries[j] = e
                placed = True
                break
        if not placed:
            lost.update(e)
    if lost:
        want = tuple(ee if not (set(ee) & lost) else
                     tuple(a for a in ee if a not in lost) for ee in s)
        ctx.require(0, want)
        ctx.diag(NOTE, "sharding/reshape-gather",
                 f"reshape {op.name!r} cannot carry axes "
                 f"{sorted(lost)} through {in_dims} -> {out_dims}; "
                 "the input is gathered")
    return [_dedupe_axes(tuple(entries))]


def _reshape_backward(op, out_specs, in_specs, ctx):
    # exact inverse only for rank-preserving same-shape reshapes
    x = op.inputs[0]
    out = op.outputs[0]
    if _dims_of(x) == _dims_of(out):
        return [out_specs[0]] + [None] * (len(in_specs) - 1)
    return None


reshape_rule.backward = _reshape_backward


def make_concat_rule(axis_attr: str = "axis"):
    def rule(op: Operation, in_specs, ctx: RuleContext):
        r = _out_rank(op)
        if r is None:
            return [None]
        axis = op.attrs.get(axis_attr, 0)
        axis = int(axis) % max(r, 1)
        joined: Optional[Tuple] = None
        for s in in_specs:
            if s is None or len(s) != r:
                continue
            joined = s if joined is None else ctx.join(joined, s)
        if joined is None:
            return [replicated(r)]
        if joined[axis]:
            # concatenating along a sharded dim forces a gather of every
            # piece (shard boundaries no longer align)
            for i, s in enumerate(in_specs):
                if s is not None and len(s) == r and s[axis]:
                    want = list(s)
                    want[axis] = ()
                    ctx.require(i, tuple(want))
            joined = tuple(() if d == axis else e
                           for d, e in enumerate(joined))
        return [joined]

    return rule


def make_gather_rule(axis_attr: str = "axis", params_idx: int = 0,
                     indices_idx: int = 1):
    """Gather/embedding-lookup: indices dims replace params' gathered
    dim. A sharded gathered dim (vocab/ep sharding) implies an
    all-reduce of the gathered output (the one-hot-matmul lowering
    GSPMD uses)."""

    def rule(op: Operation, in_specs, ctx: RuleContext):
        params = op.inputs[params_idx]
        sp = in_specs[params_idx]
        si = in_specs[indices_idx] if indices_idx < len(in_specs) else None
        pd = _dims_of(params)
        r = _out_rank(op)
        if sp is None or pd is None or r is None:
            return [replicated(r)]
        axis = int(op.attrs.get(axis_attr, 0) or 0) % max(len(pd), 1)
        ind_rank = len(si) if si is not None else \
            (op.inputs[indices_idx].shape.rank or 0) \
            if indices_idx < len(op.inputs) else 0
        entries = []
        for d in range(r):
            if d < axis:
                entries.append(sp[d])
            elif d < axis + ind_rank:
                entries.append(si[d - axis] if si is not None else ())
            else:
                entries.append(sp[d - ind_rank + 1])
        out_spec = _dedupe_axes(tuple(entries))
        gaxes = tuple(a for a in sp[axis]
                      if ctx.mesh_axes.get(a, 1) > 1)
        if gaxes:
            out_t = op.outputs[0]
            ctx.collective(
                "all-reduce", gaxes,
                tensor_bytes(out_t) / ctx.shard_factor(out_spec),
                note="gather over sharded dim (one-hot contraction)",
                tensor_name=out_t.name)
        return [out_spec for _ in op.outputs]

    return rule


def make_fused_embedding_rule(axis_attr: str = "axis"):
    """EmbeddingLookupFused (ISSUE 19): the fused route replaces the
    one-hot contraction with two tiled all-to-alls (id route + row
    return). ``axis_attr`` names the node attr holding the MESH AXIS
    NAME the table is vocab-sharded over (unlike make_gather_rule,
    whose attr is the gathered DIM index — legacy lookups keep the
    all-reduce pricing above). Priced only when the table's vocab dim
    actually carries that axis; payload uses the HLO result-shape
    convention (the (n, b) id and (n, b, D) row buffers each shard
    materializes) so the bench's predicted-vs-harvested comparison is
    apples to apples. The output is replicated over the mesh (every
    shard reassembles the full row set), so downstream specs start
    clean."""

    def rule(op: Operation, in_specs, ctx: RuleContext):
        axis = op.attrs.get(axis_attr, "ep")
        r = _out_rank(op) or 0
        sp = in_specs[0]
        n = ctx.axis_size(axis)
        if (n > 1 and sp is not None and len(sp) >= 1
                and axis in tuple(sp[0] or ())):
            ids_t = op.inputs[1]
            out_t = op.outputs[0]
            b = 1
            for d in (ids_t.shape.dims or []):
                b *= int(d.value or 1)
            dim = int(out_t.shape.dims[-1].value or 1) \
                if out_t.shape.rank else 1
            nbytes = float(n * b * ids_t.dtype.base_dtype.size
                           + n * b * dim * out_t.dtype.base_dtype.size)
            ctx.collective(
                "all-to-all", (axis,), nbytes,
                note="fused embedding gather (id route + row return)",
                tensor_name=out_t.name)
        elif (n > 1 and sp is not None
              and any(axis in tuple(e or ()) for e in sp)):
            # table sharded over `axis` on a NON-vocab dim: the fused
            # kernel's shard_map in_spec is (axis, None), so GSPMD must
            # reshard the WHOLE table every step — charge it, so the
            # search prefers the vocab layout on real cost rather than
            # by fiat
            tbl_t = op.inputs[0]
            tbytes = 1
            for d in (tbl_t.shape.dims or []):
                tbytes *= int(d.value or 1)
            ctx.collective(
                "all-to-all", (axis,),
                float(tbytes * tbl_t.dtype.base_dtype.size),
                note="fused embedding table reshard (non-vocab dim "
                     "sharded over lookup axis)",
                tensor_name=op.inputs[0].name)
        return [replicated(r) for _ in op.outputs]

    return rule


def make_fused_scatter_grad_rule(axis_attr: str = "axis"):
    """EmbeddingScatterAddGrad (ISSUE 19): the dense table gradient is
    born vocab-sharded over the table's mesh axis (each shard
    scatter-adds only the rows it owns); no collective — the incoming
    cotangents are replicated over that axis by construction of the
    fused forward."""

    def rule(op: Operation, in_specs, ctx: RuleContext):
        axis = op.attrs.get(axis_attr, "ep")
        r = _out_rank(op) or 2
        if ctx.axis_size(axis) > 1:
            return [((axis,),) + ((),) * (r - 1) for _ in op.outputs]
        return [replicated(r) for _ in op.outputs]

    return rule


def make_conv_rule(n_spatial: int = 2):
    """Convolution: batch + spatial from the data input, the filter is
    consumed replicated on its spatial/in-channel dims; out-channel may
    carry the filter's last-dim sharding (tp-style)."""

    def rule(op: Operation, in_specs, ctx: RuleContext):
        x = op.inputs[0]
        sx = in_specs[0]
        sw = in_specs[1] if len(in_specs) > 1 else None
        r = _out_rank(op)
        dx = _dims_of(x)
        if sx is None or r is None or dx is None:
            return [replicated(r) for _ in op.outputs]
        nchw = op.attrs.get("data_format") == "NCHW"
        batch_e = sx[0]
        in_chan_dim = 1 if nchw else len(sx) - 1
        chan_dim = 1 if nchw else r - 1
        # spatial sharding would need halo exchange: consume gathered
        want = list(sx)
        changed = False
        for d in range(len(sx)):
            if d == 0 or d == in_chan_dim:
                continue
            if sx[d]:
                want[d] = ()
                changed = True
        if changed:
            ctx.require(0, tuple(want))
        out = [()] * r
        out[0] = batch_e
        # contraction over a sharded in-channel dim -> all-reduce
        cin_axes = tuple(a for a in sx[in_chan_dim]
                         if ctx.mesh_axes.get(a, 1) > 1)
        if sw is not None and len(sw) >= 1 and sw[-1]:
            out[chan_dim] = sw[-1]
        out_spec = _dedupe_axes(tuple(out))
        if cin_axes:
            out_t = op.outputs[0]
            ctx.collective(
                "all-reduce", cin_axes,
                tensor_bytes(out_t) / ctx.shard_factor(out_spec),
                note="conv contraction over sharded in-channel",
                tensor_name=out_t.name)
        if sw is not None and len(sw) >= 1:
            # the filter is consumed gathered on spatial/in-channel
            # dims, and ALSO on any out-channel axis the output could
            # not keep (axis collision with the batch sharding — the
            # ZeRO layout's per-step weight all-gather)
            kept_chan = tuple(a for a in sw[-1]
                              if a in out_spec[chan_dim]
                              or ctx.mesh_axes.get(a, 1) <= 1)
            wwant = tuple([()] * (len(sw) - 1) + [kept_chan])
            if wwant != tuple(sw):
                ctx.require(1, wwant)
        return [out_spec] + [
            replicated(_out_rank(op, i))
            for i in range(1, len(op.outputs))]

    return rule


def make_pool_rule():
    """Pooling: batch and channel sharding pass through; sharded
    spatial dims would need halo exchange, so they are consumed
    gathered."""

    def rule(op: Operation, in_specs, ctx: RuleContext):
        sx = in_specs[0]
        r = _out_rank(op)
        if sx is None or r is None:
            return [replicated(_out_rank(op, i))
                    for i in range(len(op.outputs))]
        nchw = op.attrs.get("data_format") == "NCHW"
        chan = 1 if nchw else len(sx) - 1
        want = list(sx)
        out = [()] * r
        changed = False
        for d, e in enumerate(sx):
            if d == 0 or d == chan:
                if d < r:
                    out[d] = e
            elif e:
                want[d] = ()
                changed = True
        if changed:
            ctx.require(0, tuple(want))
        return [tuple(out)] + [replicated(_out_rank(op, i))
                               for i in range(1, len(op.outputs))]

    return rule


def make_softmax_rule(axis_attr: str = "axis"):
    """Softmax-family: spec-preserving; a sharded normalization dim
    costs a (small) all-reduce of the per-row statistics."""

    def rule(op: Operation, in_specs, ctx: RuleContext):
        s = in_specs[0]
        if s is None or not s:
            return [s for _ in op.outputs]
        ax = int(op.attrs.get(axis_attr, -1)) % len(s)
        red = tuple(a for a in s[ax] if ctx.mesh_axes.get(a, 1) > 1)
        if red:
            out_t = op.outputs[0]
            dims = _dims_of(out_t)
            denom = (dims[ax] or 1) if dims and ax < len(dims) else 1
            ctx.collective(
                "all-reduce", red,
                2.0 * tensor_bytes(out_t) / max(denom, 1)
                / ctx.shard_factor(s),
                note="normalization stats over sharded dim",
                tensor_name=out_t.name)
        return [s for _ in op.outputs]

    return rule


def make_last_dim_reduce_rule():
    """Per-example losses (softmax xent): the class dim reduces away;
    sharded classes imply an all-reduce of the per-example outputs."""

    def rule(op: Operation, in_specs, ctx: RuleContext):
        s = in_specs[0]
        if s is None or not s:
            return [replicated(_out_rank(op, i))
                    for i in range(len(op.outputs))]
        out_spec = tuple(s[:-1])
        red = tuple(a for a in s[-1] if ctx.mesh_axes.get(a, 1) > 1)
        if red:
            out_t = op.outputs[0]
            ctx.collective(
                "all-reduce", red,
                tensor_bytes(out_t) / ctx.shard_factor(out_spec),
                note="class-dim contraction over sharded dim",
                tensor_name=out_t.name)
        outs = []
        for i, t in enumerate(op.outputs):
            r = t.shape.rank
            outs.append(out_spec if r == len(out_spec)
                        else s if r == len(s) else replicated(r))
        return outs

    return rule


def make_axis_unsharded_rule(axis_attr: str = "axis", default: int = 0):
    """Spec-preserving ops that scan/sort along one dim: that dim is
    consumed gathered when sharded (cumsum, sort, topk-like)."""

    def rule(op: Operation, in_specs, ctx: RuleContext):
        s = in_specs[0]
        if s is None or not s:
            return [s for _ in op.outputs]
        ax = int(op.attrs.get(axis_attr, default)) % len(s)
        if s[ax]:
            want = list(s)
            want[ax] = ()
            ctx.require(0, tuple(want))
            s = tuple(want)
        outs = []
        for t in op.outputs:
            r = t.shape.rank
            outs.append(s if r == len(s) else replicated(r))
        return outs

    return rule


def einsum_rule(op: Operation, in_specs, ctx: RuleContext):
    """Parse the equation; letters join across operands, contracted
    sharded letters imply an all-reduce of the output. Ellipsis falls
    back to the conservative default."""
    eq = op.attrs.get("equation", "")
    if "..." in eq or "->" not in eq:
        return None
    lhs, out_sub = eq.replace(" ", "").split("->")
    subs = lhs.split(",")
    if len(subs) != len(op.inputs):
        return None
    letter: Dict[str, Tuple[str, ...]] = {}
    for sub, s, t in zip(subs, in_specs, op.inputs):
        if s is None or len(sub) != len(s):
            continue
        for ch, e in zip(sub, s):
            if not e:
                continue
            prev = letter.get(ch)
            if prev is None:
                letter[ch] = e
            elif prev != e:
                ctx.diag(NOTE, "sharding/conflict",
                         f"einsum index {ch!r} sharded as {prev} and "
                         f"{e}; joined to replicated")
                letter[ch] = ()
    # operands disagreeing with the joined letter map reshard
    for i, (sub, s) in enumerate(zip(subs, in_specs)):
        if s is None or len(sub) != len(s):
            continue
        want = tuple(letter.get(ch, ()) for ch in sub)
        if want != s:
            ctx.require(i, want)
    out_spec = _dedupe_axes(tuple(letter.get(ch, ()) for ch in out_sub))
    contracted = set(lhs.replace(",", "")) - set(out_sub)
    red = set()
    for ch in contracted:
        red.update(a for a in letter.get(ch, ())
                   if ctx.mesh_axes.get(a, 1) > 1)
    if red:
        out_t = op.outputs[0]
        ctx.collective("all-reduce", tuple(sorted(red)),
                       tensor_bytes(out_t) / ctx.shard_factor(out_spec),
                       note="einsum contraction over sharded index",
                       tensor_name=out_t.name)
    return [out_spec]


def make_slice_rule():
    """Slice/StridedSlice/Pad/Tile-shaped ops: dims whose size is
    unchanged keep their sharding; a changed sharded dim is consumed
    gathered (shard boundaries move)."""

    def rule(op: Operation, in_specs, ctx: RuleContext):
        s = in_specs[0]
        x = op.inputs[0]
        out = op.outputs[0]
        din, dout = _dims_of(x), _dims_of(out)
        if s is None:
            return [replicated(_out_rank(op, i))
                    for i in range(len(op.outputs))]
        if din is None or dout is None or len(din) != len(dout):
            # rank-changing slice: gather sharded dims, replicate out
            if not is_replicated(s):
                ctx.require(0, replicated(len(s)))
            return [replicated(_out_rank(op, i))
                    for i in range(len(op.outputs))]
        want = list(s)
        entries = []
        changed = False
        for d in range(len(din)):
            if din[d] == dout[d]:
                entries.append(s[d])
            else:
                entries.append(())
                if s[d]:
                    want[d] = ()
                    changed = True
        if changed:
            ctx.require(0, tuple(want))
        return [tuple(entries)] + [replicated(_out_rank(op, i))
                                   for i in range(1, len(op.outputs))]

    return rule


def make_assign_rule(value_idx: int = 0):
    """Variable writes: the committed value adopts the variable's
    declared sharding; a differently-laid-out value reshards on the
    way in."""

    def rule(op: Operation, in_specs, ctx: RuleContext):
        vn = op.attrs.get("var_name")
        rank = _out_rank(op)
        spec = ctx.var_spec(vn, rank)
        if spec is None:
            spec = replicated(rank)
        if value_idx < len(in_specs) and in_specs[value_idx] is not None \
                and spec is not None \
                and in_specs[value_idx] != spec \
                and len(in_specs[value_idx]) == len(spec):
            ctx.require(value_idx, spec)
        return [spec for _ in op.outputs]

    return rule


def batchnorm_rule(op: Operation, in_specs, ctx: RuleContext):
    """FusedBatchNorm: y keeps x's spec; the per-channel statistics are
    reduced over batch/spatial — sharded batch means an (small)
    all-reduce of the stats."""
    sx = in_specs[0]
    outs = [sx] + [replicated(_out_rank(op, i))
                   for i in range(1, len(op.outputs))]
    if sx is not None:
        nchw = op.attrs.get("data_format") == "NCHW"
        chan = 1 if nchw else len(sx) - 1
        red = set()
        for d, e in enumerate(sx):
            if d != chan:
                red.update(a for a in e if ctx.mesh_axes.get(a, 1) > 1)
        if red:
            stat_bytes = sum(tensor_bytes(t) for t in op.outputs[1:3])
            if stat_bytes <= 0 and len(op.outputs) > 1:
                stat_bytes = tensor_bytes(op.outputs[1]) * 2
            ctx.collective("all-reduce", tuple(sorted(red)),
                           stat_bytes or 0.0,
                           note="cross-shard batch statistics",
                           tensor_name=op.outputs[0].name)
    return outs


def make_stack_rule(axis_attr: str = "axis"):
    """Pack/Stack: inputs join; output gains a new leading (axis) dim."""

    def rule(op: Operation, in_specs, ctx: RuleContext):
        r = _out_rank(op)
        if r is None:
            return [None]
        axis = int(op.attrs.get(axis_attr, 0) or 0) % max(r, 1)
        joined = None
        for s in in_specs:
            if s is not None and len(s) == r - 1:
                joined = s if joined is None else ctx.join(joined, s)
        if joined is None:
            return [replicated(r)]
        out = list(joined)
        out.insert(axis, ())
        return [_dedupe_axes(tuple(out))]

    return rule


def make_unstack_rule(axis_attr: str = "axis"):
    def rule(op: Operation, in_specs, ctx: RuleContext):
        s = in_specs[0]
        if s is None:
            return [replicated(_out_rank(op, i))
                    for i in range(len(op.outputs))]
        axis = int(op.attrs.get(axis_attr, 0) or 0) % max(len(s), 1)
        if s[axis]:
            want = list(s)
            want[axis] = ()
            ctx.require(0, tuple(want))
        sub = tuple(e for d, e in enumerate(s) if d != axis)
        return [sub for _ in op.outputs]

    return rule


def expand_dims_rule(op: Operation, in_specs, ctx: RuleContext):
    s = in_specs[0]
    r = _out_rank(op)
    if s is None or r is None:
        return [replicated(r)]
    in_dims = _dims_of(op.inputs[0]) or []
    out_dims = _dims_of(op.outputs[0]) or []
    # find the inserted size-1 dim by aligning shapes
    out = []
    j = 0
    for d in range(r):
        if j < len(in_dims) and out_dims and d < len(out_dims) \
                and out_dims[d] == in_dims[j] \
                and (len(out_dims) - d) >= (len(in_dims) - j):
            out.append(s[j])
            j += 1
        else:
            out.append(())
    return [tuple(out)]


def squeeze_rule(op: Operation, in_specs, ctx: RuleContext):
    s = in_specs[0]
    if s is None:
        return [replicated(_out_rank(op))]
    in_dims = _dims_of(op.inputs[0]) or []
    out = [e for d, e in enumerate(s)
           if d >= len(in_dims) or in_dims[d] != 1]
    r = _out_rank(op)
    if r is not None and len(out) != r:
        return [replicated(r)]
    return [tuple(out)]


def make_loop_rule(kind: str):
    """Sharding rule for the structured control-flow ops; ``kind`` in
    {'while', 'scan', 'fold', 'map', 'cond', 'call'}. Bodies are
    analyzed recursively; loop carries iterate to a (2-round) fixpoint;
    edges inside loop bodies are trip-weighted."""
    from ..framework import optimizer as optimizer_mod

    def rule(op: Operation, in_specs, ctx: RuleContext):
        spec = optimizer_mod.function_op_spec(op.type)
        trip = None
        if spec is not None and spec.trip is not None:
            try:
                t = spec.trip(op.attrs, op.inputs)
                trip = int(t) if t else None
            except Exception:
                trip = None

        if kind == "cond":
            tg, fg = op.attrs.get("true_graph"), op.attrs.get(
                "false_graph")
            # inputs = [pred] + true-captures + false-captures
            ntc = int(op.attrs.get("n_true_caps",
                                   len(tg.captures) if tg else 0))
            cap_lists = (list(op.inputs[1:1 + ntc]),
                         list(op.inputs[1 + ntc:]))
            outs = None
            for bg, caps in zip((tg, fg), cap_lists):
                if bg is None:
                    continue
                o = ctx.analyze_body(bg, [], trip=None, loop=False,
                                     capture_outers=caps)
                outs = o if outs is None else [
                    ctx.join(a, b) if a is not None and b is not None
                    and len(a) == len(b) else None
                    for a, b in zip(outs, o)]
            if outs is None or len(outs) != len(op.outputs):
                return None
            return outs

        if kind == "call":
            fg = (op.attrs.get("func_graph") or op.attrs.get("fg")
                  or op.attrs.get("body"))
            if fg is None:
                return None
            n_args = int(op.attrs.get("n_args", len(fg.inputs)))
            args = list(in_specs[:len(fg.inputs)])
            outs = ctx.analyze_body(
                fg, args, trip=None, loop=False,
                capture_outers=list(op.inputs[n_args:]))
            if len(outs) != len(op.outputs):
                return None
            return outs

        if kind == "while":
            fg = op.attrs.get("body_graph")
            cg = op.attrs.get("cond_graph")
            n_vars = int(op.attrs.get("n_vars", len(op.outputs)))
            # inputs = loop-vars + cond-captures + body-captures
            ncc = int(op.attrs.get("n_cond_caps",
                                   len(cg.captures) if cg else 0))
            cond_caps = list(op.inputs[n_vars:n_vars + ncc])
            body_caps = list(op.inputs[n_vars + ncc:])
            carry = list(in_specs[:n_vars])
            # carry fixpoint rounds are QUIET — only the final sweep
            # records, so body edges are charged exactly once
            for _ in range(2):
                outs = ctx.analyze_body(fg, carry, trip=trip, loop=True,
                                        capture_outers=body_caps,
                                        record=False)
                if len(outs) != n_vars:
                    return None
                new = [ctx.join(c, o) if c is not None and o is not None
                       and len(c) == len(o) else o
                       for c, o in zip(carry, outs)]
                if new == carry:
                    break
                carry = new
            if ctx.record:
                ctx.analyze_body(fg, carry, trip=trip, loop=True,
                                 capture_outers=body_caps, record=True)
            if cg is not None:
                ctx.analyze_body(cg, carry, trip=trip, loop=True,
                                 capture_outers=cond_caps)
            return carry[:len(op.outputs)]

        # scan / fold / map: carry + sliced elems
        fg = op.attrs.get("body")
        if fg is None:
            return None
        nc = int(op.attrs.get("n_carry", 0))
        ne = int(op.attrs.get("n_elems", len(op.inputs) - nc))
        # inputs = carry + elems + captures
        body_caps = list(op.inputs[nc + ne:])
        carry = list(in_specs[:nc])

        def sliced(s):
            if s is None or not s:
                return None if s is None else s
            return tuple(s[1:])

        elems = [sliced(s) for s in in_specs[nc:nc + ne]]
        if kind == "map":
            args = elems
        else:
            args = carry + elems
        outs = None
        # carry fixpoint rounds are QUIET; one final sweep records so
        # body edges are charged exactly once
        for _ in range(2 if nc else 1):
            outs = ctx.analyze_body(fg, args, trip=trip, loop=True,
                                    capture_outers=body_caps,
                                    record=False if nc else None)
            if not nc:
                break
            if len(outs) < nc:
                return None
            new_carry = [ctx.join(c, o) if c is not None and o is not None
                         and len(c) == len(o) else o
                         for c, o in zip(carry, outs[:nc])]
            if new_carry == carry:
                break
            carry = new_carry
            args = carry + elems if kind != "map" else elems
        if outs is None:
            return None
        if nc and ctx.record:
            outs = ctx.analyze_body(fg, args, trip=trip, loop=True,
                                    capture_outers=body_caps,
                                    record=True)
        if kind == "fold":
            result = outs[:len(op.outputs)]
        else:
            # stacked outputs regain the leading (iteration) dim
            result = [tuple([()] + list(o)) if o is not None else None
                      for o in outs]
        if len(result) != len(op.outputs):
            return None
        return result

    return rule


# ---------------------------------------------------------------------------
# bulk registration helpers
# ---------------------------------------------------------------------------

def register_rules(rule, *op_types):
    for t in op_types:
        op_registry.register_sharding_rule(t, rule)


# ---------------------------------------------------------------------------
# lint rules over the report (the PR 3 framework path)
# ---------------------------------------------------------------------------

SHARDING_LINT_CODES = (
    "lint/replicated-large-tensor", "lint/resharding-hotspot",
    "lint/mesh-axis-unused", "lint/uneven-shard",
    "lint/embedding-replicated-table")

# lookup op types whose input 0 is an embedding table; and the default
# per-table byte bar for the embedding-replicated-table ERROR (a table
# this big resolving replicated on a real mesh defeats the entire point
# of vocab sharding). graph_lint --embeddings --budget overrides.
EMBEDDING_LOOKUP_TYPES = ("EmbeddingLookupFused", "EmbeddingLookupMixed",
                          "Gather", "GatherV2")
EMBEDDING_TABLE_BUDGET_BYTES = 1 << 27  # 128 MiB


def embedding_tables_of(ops, variables):
    """{table_var_name: (var_op, nbytes, spec, [consumer op types])}
    for every variable consumed as input 0 of an embedding-style
    lookup in ``ops``. ``variables`` is ``ShardingReport.variables``.
    Walks through Identity/Cast/ReadVariableOp wrappers."""
    var_by_op = {}
    for name, (vop, nbytes, spec) in variables.items():
        var_by_op[vop] = (name, nbytes, spec)
    out: Dict[str, tuple] = {}
    for op in ops:
        if op.type not in EMBEDDING_LOOKUP_TYPES or not op.inputs:
            continue
        p = op.inputs[0].op
        hops = 0
        while (p is not None and p.inputs
               and p.type in ("Identity", "Cast", "ReadVariableOp")
               and hops < 4):
            p = p.inputs[0].op
            hops += 1
        info = var_by_op.get(p)
        if info is None:
            continue
        name, nbytes, spec = info
        entry = out.setdefault(name, (p, nbytes, spec, []))
        entry[3].append(op.type)
    return out


def _report_of(ctx):
    return getattr(ctx, "sharding_report", None)


def register_sharding_lint_rules():
    from .lint import register_lint_rule

    @register_lint_rule("replicated-large-tensor", WARNING)
    def _rule_replicated_large(ctx):
        """A weight above the size threshold (STF_SHARDING_LARGE_BYTES,
        default 1 MiB) with no sharded dim is copied whole into every
        device's HBM — on an N-device mesh that is N-1 wasted copies
        and the classic cause of 'fits on one chip, OOMs on eight'."""
        rep = _report_of(ctx)
        if rep is None or rep.mesh_size <= 1:
            return
        for name, (op, nbytes, spec) in sorted(rep.variables.items()):
            if nbytes >= LARGE_TENSOR_BYTES and is_replicated(spec):
                yield (op,
                       f"variable {name!r} ({int(nbytes)} bytes) is "
                       f"replicated across the {rep.mesh_size}-device "
                       "mesh; shard it (shard_variable / "
                       "shard_variables_along / match_partition_rules)")

    @register_lint_rule("embedding-replicated-table", ERROR)
    def _rule_embedding_replicated_table(ctx):
        """A big embedding table resolving REPLICATED on a >1-device
        mesh (active only for ``purpose="embeddings"`` runs —
        ``graph_lint --embeddings``; the byte bar is ``--budget`` or
        EMBEDDING_TABLE_BUDGET_BYTES). Unlike the generic
        replicated-large-tensor WARNING this is an ERROR: a
        terabyte-class table only fits at all because vocab sharding
        divides it, so a replicated resolution is a deploy-blocking
        misconfiguration, not a smell."""
        if getattr(ctx, "purpose", None) != "embeddings":
            return
        rep = _report_of(ctx)
        if rep is None or rep.mesh_size <= 1:
            return
        budget = int(getattr(ctx, "memory_budget", None)
                     or EMBEDDING_TABLE_BUDGET_BYTES)
        tables = embedding_tables_of(ctx.ops, rep.variables)
        for name, (vop, nbytes, spec, lookups) in sorted(tables.items()):
            if nbytes >= budget and is_replicated(spec):
                yield (vop,
                       f"embedding table {name!r} ({int(nbytes)} bytes, "
                       f"looked up by {sorted(set(lookups))}) resolves "
                       f"REPLICATED on the {rep.mesh_size}-device mesh "
                       f"(>= budget {budget} bytes): every device holds "
                       "a full copy. Vocab-shard it (spec ('ep', None) "
                       "via shard_variables_along/match_partition_rules "
                       "or autoshard with a budget)")

    @register_lint_rule("resharding-hotspot", WARNING)
    def _rule_resharding_hotspot(ctx):
        """A resharding edge inside a while/scan body repeats every
        iteration: its bytes are charged x trip-count. Hoist the layout
        change out of the loop or align the body's constraint with the
        carry's sharding."""
        rep = _report_of(ctx)
        if rep is None or rep.mesh_size <= 1:
            return
        for e in rep.collective_edges():
            if not e.in_loop:
                continue
            yield (e.op,
                   f"{e.kind} of {e.tensor_name or 'tensor'} "
                   f"({int(e.nbytes)} bytes) inside a loop body "
                   + (f"repeats x{e.trip} iterations "
                      f"(~{int(e.total_bytes)} bytes/step)"
                      if e.trip > 1 else
                      "repeats every iteration")
                   + (f" [{e.note}]" if e.note else ""))

    @register_lint_rule("mesh-axis-unused", WARNING)
    def _rule_mesh_axis_unused(ctx):
        """A mesh axis that shards no tensor and feeds no collective is
        devices standing idle: the mesh is bigger than the program."""
        rep = _report_of(ctx)
        if rep is None:
            return
        used: Set[str] = set()
        for spec in rep.specs.values():
            used |= set(spec_axes(spec))
        for e in rep.edges:
            used.update(e.axes)
        for ax, size in sorted(rep.mesh_axes.items()):
            if size > 1 and ax not in used:
                yield (None,
                       f"mesh axis {ax!r} (size {size}) shards no "
                       "tensor and feeds no collective; the program "
                       f"uses 1/{size} of that axis")

    @register_lint_rule("uneven-shard", WARNING)
    def _rule_uneven_shard(ctx):
        """dim % axis-size != 0: XLA pads every shard to the ceiling,
        so each step moves and computes padding."""
        rep = _report_of(ctx)
        if rep is None or rep.mesh_size <= 1:
            return
        for (op, tname, dim, axes, size) in rep.uneven:
            f = 1
            for a in axes:
                f *= rep.mesh_axes.get(a, 1)
            waste = (f - size % f) / float(f)
            yield (op,
                   f"{tname} dim {dim} (size {size}) is sharded over "
                   f"{axes} (x{f}) but {size} % {f} != 0: ~"
                   f"{waste:.0%} of each shard is padding")


register_sharding_lint_rules()


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def analyze_sharding(graph=None, ops: Optional[Sequence[Operation]] = None,
                     mesh=None,
                     seed_specs: Optional[Dict[str, Any]] = None,
                     fetches: Optional[Sequence[Any]] = None,
                     feeds: Sequence[Any] = (),
                     with_peak: bool = False,
                     severities: Optional[Dict[str, str]] = None,
                     purpose: Optional[str] = None,
                     memory_budget: Optional[int] = None
                     ) -> ShardingReport:
    """Run the sharding analysis and the sharding lint rules.

    ``mesh``: a stf.parallel.Mesh or an abstract ``{axis: size}`` dict
    (defaults to the active mesh). ``ops`` defaults to the whole graph
    in creation (= topological) order; pass a pruned plan for per-run
    analysis. ``seed_specs`` maps variable/placeholder names to
    PartitionSpec-likes (``match_partition_rules`` output) overriding
    declared shardings. ``with_peak`` adds the per-shard peak-HBM
    estimate (needs ``fetches``)."""
    import time as _time

    t0 = _time.perf_counter()
    if mesh is None:
        from ..parallel import mesh as mesh_mod

        mesh = mesh_mod.current_mesh()
    mesh_axes = _as_mesh_axes(mesh)
    if graph is None and ops is None:
        graph = ops_mod.get_default_graph()
    if ops is None:
        ops = graph.get_operations()
    ops = list(ops)
    _tls.dims_cache = {}  # fresh static-shape cache per analysis
    engine = _Engine(mesh_axes, seed_specs=seed_specs)
    engine.seed(ops)
    # fwd -> bwd, then one recording fwd pass (which re-propagates the
    # backward suggestions while collecting edges/diagnostics)
    engine.forward(ops)
    engine.backward(ops)
    engine.forward(ops, record=True)

    rep = engine.report
    rep.specs = {t: s for t, (s, _str) in engine.env.items()}
    # variable facts for the lint rules
    for vn, (spec, op) in engine._var_specs.items():
        shp = None
        if hasattr(op, "shape"):
            shp = op.shape
        elif getattr(op, "outputs", None):
            shp = op.outputs[0].shape
        n = _nelems(shp) if shp is not None else None
        if n is None:
            continue
        try:
            dt = (op.dtype if hasattr(op, "dtype")
                  else op.outputs[0].dtype).base_dtype
            nbytes = float(n * dt.size)
        except Exception:
            nbytes = 0.0
        the_op = op.op if hasattr(op, "op") else op
        rank = shp.rank
        rep.variables[vn] = (
            the_op, nbytes,
            spec if spec is not None else replicated(rank))

    if with_peak and fetches:
        try:
            from ..framework import cost_model

            def factor(t):
                return shard_factor(engine.env.get(t, (None, 0))[0],
                                    mesh_axes)

            est = cost_model.estimate(fetches, feeds=list(feeds),
                                      shard_factor_fn=factor)
            rep.per_shard_peak_bytes = est.peak_bytes
        except Exception:
            rep.per_shard_peak_bytes = None

    # sharding lint rules through the PR 3 framework
    if mesh_axes:
        from . import lint as lint_mod

        rep.diagnostics.extend(lint_mod.lint_graph(
            graph=graph if graph is not None else None,
            ops=ops, fetches=fetches, severities=severities,
            rules=SHARDING_LINT_CODES, sharding_report=rep,
            purpose=purpose, memory_budget=memory_budget))
    # metrics
    for e in rep.collective_edges():
        metric_collectives.get_cell(e.kind).increase_by(1)
        metric_collective_bytes.get_cell(e.kind).increase_by(
            int(e.total_bytes))
    rep.analysis_seconds = _time.perf_counter() - t0
    metric_sharding_seconds.get_cell().add(rep.analysis_seconds)
    return rep
