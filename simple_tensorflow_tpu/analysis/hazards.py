"""Variable-hazard (race) detector over one Session.run plan.

SURVEY §5 / ISSUE 3 pillar 2: within one pruned step, two effectful ops
touching the same resource with NO data or control path between them
execute in an arbitrary topological tie-break order — the observed value
is nondeterministic by construction (the reference's executor runs such
nodes concurrently and calls the result "undefined",
core/common_runtime/executor.cc). Using the declared effect sets
(framework/op_registry.py ``Effects``) this module classifies every
unordered conflicting pair:

  RAW — a write precedes a read in program order but nothing orders them
  WAR — a read precedes a write in program order but nothing orders them
  WAW — two non-commuting writes to the same resource are unordered

Modes (``set_hazard_mode`` / env ``STF_HAZARD_MODE`` / per-session
``ConfigProto(variable_hazard_mode=...)``):

  off       — detector disabled
  warn      — hazards become WARNING diagnostics (logged once per plan)
  raise     — variable hazards raise InvalidArgumentError at plan time
              (the pre-existing read-your-write contract, now covering
              WAW too); non-variable resources stay warnings
  auto_deps — missing orderings are resolved by *program order* (op
              creation order), reproducing the reference's
              auto-control-dependencies (python/framework/
              auto_control_deps.py): the plan's op list is re-ordered to
              creation order, which is always a valid topological order
              of the append-only IR, so every conflicting pair executes
              in the order the user wrote it — deterministically.

Enforcement scope: only ``var_name=`` resources (device variable state,
donated HBM buffers) raise / get auto-deps. Host-side resources (queues,
staging areas, barriers, tables) execute on one thread in plan order and
commonly pipeline across runs, so their hazards are surfaced as
warnings, never errors.

Reads whose outputs feed nothing inside the step (bare fetches) are
exempt: they are observations with documented topological-position
semantics (ops/state_ops.py ReadVariable), not computation.

Cost: one forward bitmask propagation over the topologically ordered
plan — O(ops × edges) integer ops, not per-pair BFS.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from ..framework.errors import InvalidArgumentError
from . import diagnostics as diag_mod
from .effects import commuting_writes, op_effects

RAW = "raw"
WAR = "war"
WAW = "waw"

MODES = ("off", "warn", "raise", "auto_deps")

# resources in this class are enforceable (raise / auto_deps); everything
# else is advisory
_ENFORCED_PREFIX = "var_name="

_mode = os.environ.get("STF_HAZARD_MODE", "raise")
if _mode not in MODES:  # a typo'd env var must not silently disable
    _mode = "raise"


def set_hazard_mode(mode: str) -> str:
    """Set the process-default hazard mode; returns the previous one."""
    global _mode
    if mode not in MODES:
        raise ValueError(f"hazard mode must be one of {MODES}, got {mode!r}")
    prev = _mode
    _mode = mode
    return prev


def get_hazard_mode() -> str:
    return _mode


class Hazard:
    """One unordered conflicting pair. ``first``/``second`` follow
    program (creation) order — the order auto_deps enforces."""

    __slots__ = ("kind", "resource", "first", "second")

    def __init__(self, kind: str, resource: str, first: Any, second: Any):
        self.kind = kind
        self.resource = resource
        self.first = first
        self.second = second

    @property
    def enforced(self) -> bool:
        return self.resource.startswith(_ENFORCED_PREFIX)

    def describe(self) -> str:
        def at(op):
            src = op.source_site
            return f"{op.name!r} ({op.type}" + (f" at {src})" if src
                                                else ")")
        res = self.resource.split("=", 1)[-1]
        return (f"{self.kind.upper()} hazard on {res!r}: {at(self.first)} "
                f"and {at(self.second)} have no data or control-dependency "
                f"path between them, so the value observed depends on an "
                f"arbitrary execution order")

    def guidance(self) -> str:
        return ("Order them explicitly — e.g. `with stf.control_"
                "dependencies([write_op]): v.read_value()` (read-after-"
                "write) or `with stf.control_dependencies([read]): "
                "v.assign(...)` (write-after-read) — or opt into program-"
                "order auto control dependencies with hazard mode "
                "'auto_deps' (stf.analysis.set_hazard_mode or "
                "ConfigProto(variable_hazard_mode='auto_deps')).")

    def to_diagnostic(self, severity: str) -> diag_mod.Diagnostic:
        return diag_mod.Diagnostic(
            severity, f"hazard/{self.kind}", self.describe(),
            op=self.second)

    def __repr__(self):
        return (f"<Hazard {self.kind} {self.resource} "
                f"{self.first.name}~{self.second.name}>")


def find_hazards(op_list: Sequence[Any],
                 alias: Optional[Dict[Any, Any]] = None) -> List[Hazard]:
    """Detect all RAW/WAR/WAW hazards in one topologically ordered,
    ancestor-closed plan. ``alias`` is the plan-time CSE map (duplicate
    tensor → canonical) — edges through CSE-removed ops must be followed
    via their canonical, or a fully ordered graph would be misreported
    as racy."""
    alias = alias or {}
    readers: Dict[str, List[Any]] = {}
    writers: Dict[str, List[Any]] = {}
    eff_of: Dict[Any, Any] = {}
    for op in op_list:
        eff = op_effects(op)
        if not (eff.reads or eff.writes):
            continue
        eff_of[op] = eff
        for r in eff.reads:
            readers.setdefault(r, []).append(op)
        for w in eff.writes:
            writers.setdefault(w, []).append(op)

    # resources that can actually conflict: >=1 writer and >=2 accessors
    interesting = [res for res, ws in writers.items()
                   if len(ws) + len([r for r in readers.get(res, ())
                                     if r not in ws]) >= 2]
    if not interesting:
        return []

    step_set = set(op_list)

    def consumed_in_step(r) -> bool:
        for out in r.outputs:
            for c in out.consumers():
                if c in step_set:
                    return True
        return False

    tracked: List[Any] = []
    seen: Set[int] = set()
    for res in interesting:
        for op in writers.get(res, ()):
            if id(op) not in seen:
                seen.add(id(op))
                tracked.append(op)
        for op in readers.get(res, ()):
            if id(op) not in seen and consumed_in_step(op):
                seen.add(id(op))
                tracked.append(op)
    if len(tracked) < 2:
        return []
    bit = {op: 1 << i for i, op in enumerate(tracked)}

    # one forward sweep over the (topologically ordered) plan computes,
    # per op, the set of tracked ops among its ancestors
    reach: Dict[Any, int] = {}
    for op in op_list:
        m = 0
        for t in op.inputs:
            p = alias.get(t, t).op
            m |= reach.get(p, 0) | bit.get(p, 0)
        for p in op.control_inputs:
            m |= reach.get(p, 0) | bit.get(p, 0)
        reach[op] = m

    def unordered(a, b) -> bool:
        return not (reach[b] & bit[a] or reach[a] & bit[b])

    hazards: List[Hazard] = []
    emitted: Set[Tuple[int, int, str]] = set()

    def emit(kind, res, a, b):
        first, second = (a, b) if a._id <= b._id else (b, a)
        key = (id(first), id(second), res)
        if key in emitted:
            return
        emitted.add(key)
        hazards.append(Hazard(kind, res, first, second))

    for res in interesting:
        ws = writers.get(res, ())
        rs = [r for r in readers.get(res, ())
              if r in bit and r not in ws]
        for i, w1 in enumerate(ws):
            for w2 in ws[i + 1:]:
                if w2 is w1 or not unordered(w1, w2):
                    continue
                if commuting_writes(eff_of[w1], eff_of[w2]):
                    continue
                emit(WAW, res, w1, w2)
            for r in rs:
                if unordered(w1, r):
                    emit(RAW if w1._id <= r._id else WAR, res, w1, r)
    return hazards


def check_plan(op_list: Sequence[Any],
               alias: Optional[Dict[Any, Any]] = None,
               mode: Optional[str] = None,
               diags: Optional[List[diag_mod.Diagnostic]] = None
               ) -> Tuple[List[Any], List[diag_mod.Diagnostic]]:
    """Run the hazard policy over one plan. Returns the (possibly
    re-ordered, auto_deps mode) op list and the diagnostics produced.
    Raises InvalidArgumentError in "raise" mode on enforceable hazards."""
    diags = diags if diags is not None else []
    mode = mode or _mode
    if mode not in MODES:
        raise ValueError(f"hazard mode must be one of {MODES}, got {mode!r}")
    if mode == "off":
        return list(op_list), diags
    hazards = find_hazards(op_list, alias)
    if not hazards:
        return list(op_list), diags
    for h in hazards:
        diag_mod.metric_hazards.get_cell(h.kind).increase_by(1)
    enforced = [h for h in hazards if h.enforced]
    advisory = [h for h in hazards if not h.enforced]
    out_list = list(op_list)
    for h in advisory:
        d = h.to_diagnostic(diag_mod.WARNING)
        diags.append(d)
        diag_mod.metric_diagnostics.get_cell(d.severity).increase_by(1)
    if mode == "raise" and enforced:
        # raise on read/write conflicts (the pre-existing
        # read-your-write contract); WAW pairs — two writes, no read
        # observing between them — stay warnings under "raise": grouping
        # an initializer with an overwrite (Scaffold custom init_op
        # pattern) is common working code whose last-writer tie-break
        # users already rely on. auto_deps orders them too.
        raising = [h for h in enforced if h.kind != WAW]
        for h in enforced:
            if h.kind == WAW:
                d = h.to_diagnostic(diag_mod.WARNING)
                d.message += ". " + h.guidance()
                diags.append(d)
                diag_mod.metric_diagnostics.get_cell(
                    d.severity).increase_by(1)
        if raising:
            h = raising[0]
            raise InvalidArgumentError(
                None, h.second,
                h.describe() + ". " + h.guidance()
                + (f" ({len(raising) - 1} further hazard(s) in this "
                   "plan.)" if len(raising) > 1 else ""))
        return out_list, diags
    if mode == "auto_deps" and enforced:
        # program order (creation order) is always a valid topological
        # order of the append-only IR — inputs and control deps exist
        # before their consumer — so re-sorting by op id both preserves
        # every existing ordering and totally orders the hazard pairs,
        # exactly the reference's auto-control-dependencies semantics
        out_list = sorted(op_list, key=lambda op: op._id)
        diag_mod.metric_auto_deps.get_cell().increase_by(len(enforced))
        for h in enforced:
            d = h.to_diagnostic(diag_mod.NOTE)
            d.message += (" — ordered by program order "
                          f"({h.first.name!r} before {h.second.name!r}, "
                          "auto_deps)")
            diags.append(d)
            diag_mod.metric_diagnostics.get_cell(
                d.severity).increase_by(1)
    elif enforced:  # warn (and raise-mode leftovers are unreachable)
        for h in enforced:
            d = h.to_diagnostic(diag_mod.WARNING)
            d.message += ". " + h.guidance()
            diags.append(d)
            diag_mod.metric_diagnostics.get_cell(
                d.severity).increase_by(1)
    return out_list, diags
