"""stf.layers (ref: tensorflow/python/layers)."""

from .base import Layer
from .core import Dense, Dropout, Flatten, dense, dropout, flatten
from .convolutional import (
    Conv1D, Conv2D, Conv3D, Conv2DTranspose, SeparableConv2D,
    conv1d, conv2d, conv3d, conv2d_transpose, separable_conv2d,
)
from .pooling import (
    MaxPooling1D, MaxPooling2D, MaxPooling3D,
    AveragePooling1D, AveragePooling2D, AveragePooling3D,
    max_pooling1d, max_pooling2d, max_pooling3d,
    average_pooling1d, average_pooling2d, average_pooling3d,
)
from .normalization import BatchNormalization, batch_normalization
