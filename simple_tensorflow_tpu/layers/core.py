"""Core layers (ref: tensorflow/python/layers/core.py)."""

from __future__ import annotations

import numpy as np

from ..framework import graph as ops_mod
from ..ops import array_ops, init_ops, math_ops, nn_ops
from .base import Layer


class Dense(Layer):
    """(ref: core.py:48 ``class Dense``). bf16 inputs run the MXU natively
    (f32 accumulation inside the unit, bf16 activations out — see
    ops/math_ops.MatMul)."""

    def __init__(self, units, activation=None, use_bias=True,
                 kernel_initializer=None, bias_initializer=None,
                 kernel_regularizer=None, bias_regularizer=None,
                 activity_regularizer=None, kernel_constraint=None,
                 bias_constraint=None, trainable=True, name=None, **kwargs):
        super().__init__(trainable=trainable, name=name or "dense", **kwargs)
        self.units = int(units)
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer or init_ops.Zeros()
        self.kernel_regularizer = kernel_regularizer
        self.bias_regularizer = bias_regularizer
        self.kernel_constraint = kernel_constraint
        self.bias_constraint = bias_constraint

    def build(self, input_shape):
        in_dim = input_shape[-1].value
        if in_dim is None:
            raise ValueError("Dense needs known last dim")
        self.kernel = self.add_variable(
            "kernel", [in_dim, self.units],
            initializer=self.kernel_initializer,
            regularizer=self.kernel_regularizer,
            constraint=self.kernel_constraint)
        if self.use_bias:
            self.bias = self.add_variable(
                "bias", [self.units], initializer=self.bias_initializer,
                regularizer=self.bias_regularizer,
                constraint=self.bias_constraint)
        self.built = True

    def call(self, inputs):
        rank = inputs.shape.rank
        if rank is not None and rank > 2:
            flat = array_ops.reshape(
                inputs, [-1, inputs.shape[-1].value])
            out = math_ops.matmul(flat, self.kernel._ref)
            out_shape = [d.value if d.value is not None else -1
                         for d in inputs.shape[:-1]] + [self.units]
            out = array_ops.reshape(out, out_shape)
        else:
            out = math_ops.matmul(inputs, self.kernel._ref)
        if self.use_bias:
            out = nn_ops.bias_add(out, self.bias._ref)
        if self.activation is not None:
            out = self.activation(out)
        return out


def dense(inputs, units, activation=None, use_bias=True,
          kernel_initializer=None, bias_initializer=None,
          kernel_regularizer=None, bias_regularizer=None,
          activity_regularizer=None, kernel_constraint=None,
          bias_constraint=None, trainable=True, name=None, reuse=None):
    layer = Dense(units, activation, use_bias, kernel_initializer,
                  bias_initializer or init_ops.Zeros(), kernel_regularizer,
                  bias_regularizer, activity_regularizer, kernel_constraint,
                  bias_constraint, trainable, name)
    return layer(inputs)


class Dropout(Layer):
    """(ref: core.py:229 ``class Dropout``)."""

    def __init__(self, rate=0.5, noise_shape=None, seed=None, name=None,
                 **kwargs):
        super().__init__(name=name or "dropout", **kwargs)
        self.rate = rate
        self.noise_shape = noise_shape
        self.seed = seed

    def call(self, inputs, training=False):
        if not training or self.rate == 0.0:
            return array_ops.identity(inputs)
        return nn_ops.dropout(inputs, rate=self.rate, seed=self.seed)


def dropout(inputs, rate=0.5, noise_shape=None, seed=None, training=False,
            name=None):
    return Dropout(rate, noise_shape, seed, name)(inputs, training=training)


class Flatten(Layer):
    """(ref: core.py:287 ``class Flatten``)."""

    def call(self, inputs):
        dims = inputs.shape.as_list()
        n = 1
        for d in dims[1:]:
            if d is None:
                raise ValueError("Flatten needs static non-batch dims")
            n *= d
        return array_ops.reshape(inputs, [-1, n])


def flatten(inputs, name=None):
    return Flatten(name=name or "flatten")(inputs)
