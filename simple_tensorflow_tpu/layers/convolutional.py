"""Conv layers (ref: tensorflow/python/layers/convolutional.py).

NHWC is the TPU-preferred layout ("channels_last"); channels_first inputs
are accepted and transposed once at the boundary.
"""

from __future__ import annotations

from ..ops import array_ops, init_ops, nn_ops
from .base import Layer


def _norm_tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


class _Conv(Layer):
    def __init__(self, rank, filters, kernel_size, strides=1, padding="valid",
                 data_format="channels_last", dilation_rate=1, activation=None,
                 use_bias=True, kernel_initializer=None, bias_initializer=None,
                 kernel_regularizer=None, bias_regularizer=None,
                 activity_regularizer=None, trainable=True, name=None,
                 **kwargs):
        super().__init__(trainable=trainable, name=name, **kwargs)
        self.rank = rank
        self.filters = int(filters)
        self.kernel_size = _norm_tuple(kernel_size, rank)
        self.strides = _norm_tuple(strides, rank)
        self.padding = padding.upper()
        self.data_format = data_format
        self.dilation_rate = _norm_tuple(dilation_rate, rank)
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer or init_ops.Zeros()
        self.kernel_regularizer = kernel_regularizer
        self.bias_regularizer = bias_regularizer

    def build(self, input_shape):
        ch_axis = -1 if self.data_format == "channels_last" else 1
        in_ch = input_shape[ch_axis].value
        kernel_shape = list(self.kernel_size) + [in_ch, self.filters]
        self.kernel = self.add_variable("kernel", kernel_shape,
                                        initializer=self.kernel_initializer,
                                        regularizer=self.kernel_regularizer)
        if self.use_bias:
            self.bias = self.add_variable("bias", [self.filters],
                                          initializer=self.bias_initializer,
                                          regularizer=self.bias_regularizer)
        self.built = True

    def call(self, inputs):
        df = "NHWC" if self.data_format == "channels_last" else "NCHW"
        if self.rank == 2:
            out = nn_ops.conv2d(
                inputs, self.kernel._ref,
                strides=[1] + list(self.strides) + [1] if df == "NHWC"
                else [1, 1] + list(self.strides),
                padding=self.padding, data_format=df,
                dilations=[1] + list(self.dilation_rate) + [1] if df == "NHWC"
                else [1, 1] + list(self.dilation_rate))
        elif self.rank == 1:
            x = array_ops.expand_dims(inputs, 1)
            k = array_ops.expand_dims(self.kernel._ref, 0)
            out = nn_ops.conv2d(x, k,
                                strides=[1, 1, self.strides[0], 1],
                                padding=self.padding)
            out = array_ops.squeeze(out, 1)
        else:
            out = nn_ops.conv3d(inputs, self.kernel._ref,
                                strides=[1] + list(self.strides) + [1],
                                padding=self.padding)
        if self.use_bias:
            out = nn_ops.bias_add(out, self.bias._ref, data_format=df)
        if self.activation is not None:
            out = self.activation(out)
        return out


class Conv1D(_Conv):
    def __init__(self, filters, kernel_size, **kwargs):
        super().__init__(1, filters, kernel_size,
                         name=kwargs.pop("name", "conv1d"), **kwargs)


class Conv2D(_Conv):
    """(ref: convolutional.py:335 ``class Conv2D``)."""

    def __init__(self, filters, kernel_size, **kwargs):
        super().__init__(2, filters, kernel_size,
                         name=kwargs.pop("name", "conv2d"), **kwargs)


class Conv3D(_Conv):
    def __init__(self, filters, kernel_size, **kwargs):
        super().__init__(3, filters, kernel_size,
                         name=kwargs.pop("name", "conv3d"), **kwargs)


class Conv2DTranspose(Layer):
    def __init__(self, filters, kernel_size, strides=(1, 1), padding="valid",
                 data_format="channels_last", activation=None, use_bias=True,
                 kernel_initializer=None, bias_initializer=None, name=None,
                 **kwargs):
        super().__init__(name=name or "conv2d_transpose", **kwargs)
        self.filters = filters
        self.kernel_size = _norm_tuple(kernel_size, 2)
        self.strides = _norm_tuple(strides, 2)
        self.padding = padding.upper()
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer or init_ops.Zeros()

    def build(self, input_shape):
        in_ch = input_shape[-1].value
        self.kernel = self.add_variable(
            "kernel", list(self.kernel_size) + [in_ch, self.filters],
            initializer=self.kernel_initializer)
        if self.use_bias:
            self.bias = self.add_variable("bias", [self.filters],
                                          initializer=self.bias_initializer)
        self.built = True

    def call(self, inputs):
        out = nn_ops.conv2d_transpose(
            inputs, self.kernel._ref, None,
            strides=[1] + list(self.strides) + [1], padding=self.padding)
        if self.use_bias:
            out = nn_ops.bias_add(out, self.bias._ref)
        if self.activation is not None:
            out = self.activation(out)
        return out


class SeparableConv2D(Layer):
    def __init__(self, filters, kernel_size, strides=(1, 1), padding="valid",
                 depth_multiplier=1, activation=None, use_bias=True,
                 depthwise_initializer=None, pointwise_initializer=None,
                 bias_initializer=None, name=None, **kwargs):
        super().__init__(name=name or "separable_conv2d", **kwargs)
        self.filters = filters
        self.kernel_size = _norm_tuple(kernel_size, 2)
        self.strides = _norm_tuple(strides, 2)
        self.padding = padding.upper()
        self.depth_multiplier = depth_multiplier
        self.activation = activation
        self.use_bias = use_bias
        self.depthwise_initializer = depthwise_initializer
        self.pointwise_initializer = pointwise_initializer
        self.bias_initializer = bias_initializer or init_ops.Zeros()

    def build(self, input_shape):
        in_ch = input_shape[-1].value
        self.depthwise_kernel = self.add_variable(
            "depthwise_kernel",
            list(self.kernel_size) + [in_ch, self.depth_multiplier],
            initializer=self.depthwise_initializer)
        self.pointwise_kernel = self.add_variable(
            "pointwise_kernel",
            [1, 1, in_ch * self.depth_multiplier, self.filters],
            initializer=self.pointwise_initializer)
        if self.use_bias:
            self.bias = self.add_variable("bias", [self.filters],
                                          initializer=self.bias_initializer)
        self.built = True

    def call(self, inputs):
        out = nn_ops.separable_conv2d(
            inputs, self.depthwise_kernel._ref, self.pointwise_kernel._ref,
            [1] + list(self.strides) + [1], self.padding)
        if self.use_bias:
            out = nn_ops.bias_add(out, self.bias._ref)
        if self.activation is not None:
            out = self.activation(out)
        return out


def conv1d(inputs, filters, kernel_size, **kwargs):
    reuse = kwargs.pop("reuse", None)
    return Conv1D(filters, kernel_size, **kwargs)(inputs)


def conv2d(inputs, filters, kernel_size, **kwargs):
    reuse = kwargs.pop("reuse", None)
    return Conv2D(filters, kernel_size, **kwargs)(inputs)


def conv3d(inputs, filters, kernel_size, **kwargs):
    reuse = kwargs.pop("reuse", None)
    return Conv3D(filters, kernel_size, **kwargs)(inputs)


def conv2d_transpose(inputs, filters, kernel_size, **kwargs):
    reuse = kwargs.pop("reuse", None)
    return Conv2DTranspose(filters, kernel_size, **kwargs)(inputs)


def separable_conv2d(inputs, filters, kernel_size, **kwargs):
    reuse = kwargs.pop("reuse", None)
    return SeparableConv2D(filters, kernel_size, **kwargs)(inputs)
