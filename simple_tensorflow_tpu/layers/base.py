"""Layer base class (ref: tensorflow/python/layers/base.py)."""

from __future__ import annotations

from ..framework import graph as ops_mod
from ..ops import variable_scope as vs

GraphKeys = ops_mod.GraphKeys


class Layer:
    """(ref: base.py:64 ``class Layer``). Variables are created through
    get_variable under the layer's scope; calling is graph building."""

    def __init__(self, trainable=True, name=None, dtype=None, **kwargs):
        self.trainable = trainable
        self._name = name or self.__class__.__name__.lower()
        self.dtype = dtype
        self.built = False
        self._trainable_weights = []
        self._non_trainable_weights = []
        self._updates = []
        self._losses = []
        self._scope_name = None

    @property
    def name(self):
        return self._name

    @property
    def trainable_weights(self):
        return list(self._trainable_weights)

    @property
    def non_trainable_weights(self):
        return list(self._non_trainable_weights)

    @property
    def weights(self):
        return self.trainable_weights + self.non_trainable_weights

    variables = weights

    @property
    def trainable_variables(self):
        return self.trainable_weights

    @property
    def updates(self):
        return list(self._updates)

    @property
    def losses(self):
        return list(self._losses)

    def add_variable(self, name, shape, dtype=None, initializer=None,
                     regularizer=None, trainable=True, constraint=None):
        v = vs.get_variable(name, shape=shape, dtype=dtype or self.dtype,
                            initializer=initializer, regularizer=regularizer,
                            trainable=trainable and self.trainable,
                            constraint=constraint)
        if trainable and self.trainable:
            self._trainable_weights.append(v)
        else:
            self._non_trainable_weights.append(v)
        return v

    add_weight = add_variable

    def add_update(self, updates):
        if not isinstance(updates, (list, tuple)):
            updates = [updates]
        self._updates.extend(updates)
        g = ops_mod.get_default_graph()
        for u in updates:
            g.add_to_collection(GraphKeys.UPDATE_OPS, u)

    def add_loss(self, losses):
        if not isinstance(losses, (list, tuple)):
            losses = [losses]
        self._losses.extend(losses)
        g = ops_mod.get_default_graph()
        for l in losses:
            g.add_to_collection(GraphKeys.REGULARIZATION_LOSSES, l)

    def build(self, input_shape):
        self.built = True

    def call(self, inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, inputs, *args, **kwargs):
        with vs.variable_scope(self._name, reuse=vs.AUTO_REUSE) as scope:
            self._scope_name = scope.name
            if not self.built:
                t = (inputs[0] if isinstance(inputs, (list, tuple))
                     else inputs)
                if self.dtype is None:
                    self.dtype = t.dtype.base_dtype
                self.build(t.shape)
            return self.call(inputs, *args, **kwargs)

    def apply(self, inputs, *args, **kwargs):
        return self.__call__(inputs, *args, **kwargs)


class InputSpec:
    def __init__(self, dtype=None, shape=None, ndim=None, max_ndim=None,
                 min_ndim=None, axes=None):
        self.dtype = dtype
        self.shape = shape
        self.ndim = ndim
