"""BatchNormalization layer (ref: tensorflow/python/layers/normalization.py).

Uses the fused batch-norm composite (ops/nn_impl.py) — XLA fuses it into the
adjacent conv; moving stats update via UPDATE_OPS, reference-style.
"""

from __future__ import annotations

from ..framework import graph as ops_mod
from ..ops import array_ops, init_ops, math_ops, nn_impl, state_ops
from .base import Layer


class BatchNormalization(Layer):
    """(ref: normalization.py:59 ``class BatchNormalization``)."""

    def __init__(self, axis=-1, momentum=0.99, epsilon=1e-3, center=True,
                 scale=True, beta_initializer=None, gamma_initializer=None,
                 moving_mean_initializer=None, moving_variance_initializer=None,
                 beta_regularizer=None, gamma_regularizer=None, trainable=True,
                 fused=True, name=None, **kwargs):
        super().__init__(trainable=trainable,
                         name=name or "batch_normalization", **kwargs)
        self.axis = axis
        self.momentum = momentum
        self.epsilon = epsilon
        self.center = center
        self.scale = scale
        self.beta_initializer = beta_initializer or init_ops.Zeros()
        self.gamma_initializer = gamma_initializer or init_ops.Ones()
        self.moving_mean_initializer = moving_mean_initializer or init_ops.Zeros()
        self.moving_variance_initializer = (moving_variance_initializer or
                                            init_ops.Ones())
        self.fused = fused

    def build(self, input_shape):
        ch = input_shape[self.axis].value
        self.gamma = self.add_variable("gamma", [ch], dtype="float32",
                                       initializer=self.gamma_initializer,
                                       trainable=self.scale)
        self.beta = self.add_variable("beta", [ch], dtype="float32",
                                      initializer=self.beta_initializer,
                                      trainable=self.center)
        self.moving_mean = self.add_variable(
            "moving_mean", [ch], dtype="float32",
            initializer=self.moving_mean_initializer, trainable=False)
        self.moving_variance = self.add_variable(
            "moving_variance", [ch], dtype="float32",
            initializer=self.moving_variance_initializer, trainable=False)
        self.built = True

    def call(self, inputs, training=False):
        df = "NHWC" if self.axis in (-1, inputs.shape.rank - 1) else "NCHW"
        if training:
            y, batch_mean, batch_var = nn_impl.fused_batch_norm(
                inputs, self.gamma._ref, self.beta._ref,
                epsilon=self.epsilon, data_format=df, is_training=True)
            mom = ops_mod.convert_to_tensor(self.momentum, dtype="float32")
            upd_mean = state_ops.assign(
                self.moving_mean._ref,
                self.moving_mean._ref * mom + batch_mean * (1.0 - mom))
            upd_var = state_ops.assign(
                self.moving_variance._ref,
                self.moving_variance._ref * mom + batch_var * (1.0 - mom))
            self.add_update([upd_mean.op, upd_var.op])
            return y
        y, _, _ = nn_impl.fused_batch_norm(
            inputs, self.gamma._ref, self.beta._ref,
            mean=self.moving_mean._ref, variance=self.moving_variance._ref,
            epsilon=self.epsilon, data_format=df, is_training=False)
        return y


def batch_normalization(inputs, axis=-1, momentum=0.99, epsilon=1e-3,
                        center=True, scale=True, beta_initializer=None,
                        gamma_initializer=None, moving_mean_initializer=None,
                        moving_variance_initializer=None, training=False,
                        trainable=True, name=None, reuse=None, fused=True,
                        **kwargs):
    layer = BatchNormalization(
        axis=axis, momentum=momentum, epsilon=epsilon, center=center,
        scale=scale, beta_initializer=beta_initializer,
        gamma_initializer=gamma_initializer,
        moving_mean_initializer=moving_mean_initializer,
        moving_variance_initializer=moving_variance_initializer,
        trainable=trainable, fused=fused, name=name)
    return layer(inputs, training=training)
