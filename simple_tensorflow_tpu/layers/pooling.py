"""Pooling layers (ref: tensorflow/python/layers/pooling.py)."""

from __future__ import annotations

from ..ops import array_ops, nn_ops
from .base import Layer


def _norm_tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(x) for x in v)


class _Pooling2D(Layer):
    def __init__(self, pool_fn, pool_size, strides, padding="valid",
                 data_format="channels_last", name=None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.pool_fn = pool_fn
        self.pool_size = _norm_tuple(pool_size, 2)
        self.strides = _norm_tuple(strides, 2)
        self.padding = padding.upper()
        self.data_format = data_format

    def call(self, inputs):
        df = "NHWC" if self.data_format == "channels_last" else "NCHW"
        if df == "NHWC":
            ksize = [1] + list(self.pool_size) + [1]
            strides = [1] + list(self.strides) + [1]
        else:
            ksize = [1, 1] + list(self.pool_size)
            strides = [1, 1] + list(self.strides)
        return self.pool_fn(inputs, ksize, strides, self.padding,
                            data_format=df)


class MaxPooling2D(_Pooling2D):
    def __init__(self, pool_size, strides, padding="valid",
                 data_format="channels_last", name=None, **kwargs):
        super().__init__(nn_ops.max_pool, pool_size, strides, padding,
                         data_format, name or "max_pooling2d", **kwargs)


class AveragePooling2D(_Pooling2D):
    def __init__(self, pool_size, strides, padding="valid",
                 data_format="channels_last", name=None, **kwargs):
        super().__init__(nn_ops.avg_pool, pool_size, strides, padding,
                         data_format, name or "average_pooling2d", **kwargs)


class _Pooling1D(Layer):
    def __init__(self, pool_fn, pool_size, strides, padding="valid",
                 name=None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.pool_fn = pool_fn
        self.pool_size = _norm_tuple(pool_size, 1)[0]
        self.strides = _norm_tuple(strides, 1)[0]
        self.padding = padding.upper()

    def call(self, inputs):
        x = array_ops.expand_dims(inputs, 1)
        out = self.pool_fn(x, [1, 1, self.pool_size, 1],
                           [1, 1, self.strides, 1], self.padding)
        return array_ops.squeeze(out, 1)


class MaxPooling1D(_Pooling1D):
    def __init__(self, pool_size, strides, padding="valid", name=None,
                 **kwargs):
        super().__init__(nn_ops.max_pool, pool_size, strides, padding,
                         name or "max_pooling1d", **kwargs)


class AveragePooling1D(_Pooling1D):
    def __init__(self, pool_size, strides, padding="valid", name=None,
                 **kwargs):
        super().__init__(nn_ops.avg_pool, pool_size, strides, padding,
                         name or "average_pooling1d", **kwargs)


class _Pooling3D(Layer):
    def __init__(self, pool_fn, pool_size, strides, padding="valid",
                 name=None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.pool_fn = pool_fn
        self.pool_size = _norm_tuple(pool_size, 3)
        self.strides = _norm_tuple(strides, 3)
        self.padding = padding.upper()

    def call(self, inputs):
        return self.pool_fn(inputs, [1] + list(self.pool_size) + [1],
                            [1] + list(self.strides) + [1], self.padding)


class MaxPooling3D(_Pooling3D):
    def __init__(self, pool_size, strides, padding="valid", name=None,
                 **kwargs):
        super().__init__(nn_ops.max_pool3d, pool_size, strides, padding,
                         name or "max_pooling3d", **kwargs)


class AveragePooling3D(_Pooling3D):
    def __init__(self, pool_size, strides, padding="valid", name=None,
                 **kwargs):
        super().__init__(nn_ops.avg_pool3d, pool_size, strides, padding,
                         name or "average_pooling3d", **kwargs)


def max_pooling1d(inputs, pool_size, strides, padding="valid", name=None):
    return MaxPooling1D(pool_size, strides, padding, name=name)(inputs)


def max_pooling2d(inputs, pool_size, strides, padding="valid",
                  data_format="channels_last", name=None):
    return MaxPooling2D(pool_size, strides, padding, data_format,
                        name=name)(inputs)


def max_pooling3d(inputs, pool_size, strides, padding="valid", name=None):
    return MaxPooling3D(pool_size, strides, padding, name=name)(inputs)


def average_pooling1d(inputs, pool_size, strides, padding="valid", name=None):
    return AveragePooling1D(pool_size, strides, padding, name=name)(inputs)


def average_pooling2d(inputs, pool_size, strides, padding="valid",
                      data_format="channels_last", name=None):
    return AveragePooling2D(pool_size, strides, padding, data_format,
                            name=name)(inputs)


def average_pooling3d(inputs, pool_size, strides, padding="valid", name=None):
    return AveragePooling3D(pool_size, strides, padding, name=name)(inputs)
