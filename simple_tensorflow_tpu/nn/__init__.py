"""stf.nn namespace (ref: tensorflow/python/ops/nn.py)."""

from ..ops.nn_ops import (
    relu, relu6, elu, selu, gelu, leaky_relu, swish, silu, crelu,
    softplus, softsign, softmax, log_softmax, l2_loss, bias_add,
    softmax_cross_entropy_with_logits, softmax_cross_entropy_with_logits_v2,
    sparse_softmax_cross_entropy_with_logits,
    sigmoid_cross_entropy_with_logits, weighted_cross_entropy_with_logits,
    conv2d, depthwise_conv2d, depthwise_conv2d_native, separable_conv2d,
    conv3d, conv2d_transpose, conv3d_transpose, atrous_conv2d,
    dilation2d, erosion2d,
    max_pool, avg_pool, max_pool3d, avg_pool3d,
    dropout, local_response_normalization, lrn, in_top_k, top_k,
    xw_plus_b, log_poisson_loss,
    conv1d, convolution, atrous_conv2d_transpose,
    conv2d_backprop_input, conv2d_backprop_filter, max_pool_with_argmax,
    pool, with_space_to_batch, fractional_max_pool, fractional_avg_pool,
    quantized_conv2d, quantized_relu_x, quantized_max_pool,
    quantized_avg_pool, conv3d_backprop_filter_v2,
    depthwise_conv2d_native_backprop_filter,
    depthwise_conv2d_native_backprop_input,
)
from ..ops.nn_impl import (
    moments, weighted_moments, fused_batch_norm, batch_normalization,
    batch_norm_with_global_normalization, l2_normalize, zero_fraction,
    normalize_moments, sufficient_statistics, nce_loss, sampled_softmax_loss,
)
from ..ops.embedding_ops import (
    embedding_lookup, embedding_lookup_sparse, embedding_lookup_fused,
    embedding_bag,
)
from ..ops.math_ops import sigmoid, tanh
from ..ops.rnn import (
    dynamic_rnn, static_rnn, bidirectional_dynamic_rnn, raw_rnn,
)
from ..ops import rnn_cell
from ..ops.fused_ops import (
    fused_attention, fused_bias_dropout_residual, fused_layer_norm,
    fused_softmax_cross_entropy, quantized_matmul,
)
from ..ops.kv_cache_ops import decode_attention
from ..ops.candidate_sampling_ops import (
    uniform_candidate_sampler, log_uniform_candidate_sampler,
    learned_unigram_candidate_sampler, fixed_unigram_candidate_sampler,
    compute_accidental_hits, all_candidate_sampler,
)
from ..ops.ctc_ops import (ctc_loss, ctc_greedy_decoder,
                           ctc_beam_search_decoder)
