"""simple_tensorflow_tpu (``import simple_tensorflow_tpu as stf``).

A TPU-native framework with the capabilities of the reference stripped
TensorFlow-1.0 tree (DengZhuangSouthRd/simple_tensorflow): deferred graphs,
Sessions, variables, optimizers, distributed training — redesigned for
JAX/XLA/Pallas execution on TPU. See SURVEY.md for the architecture map.

The public namespace mirrors tf-1.x: stf.Session, stf.placeholder,
stf.Variable, stf.matmul, stf.train.AdamOptimizer, stf.nn.softmax, ...
"""

from .version import __version__, VERSION

# framework core
from .framework import dtypes
from .framework.dtypes import (
    DType, as_dtype,
    float16, half, bfloat16, float32, float64, double,
    float8_e4m3fn, float8_e5m2,
    int8, int16, int32, int64, uint8, uint16, uint32, uint64,
    bool_ as bool, complex64, complex128, string,
    qint8, quint8, qint32, qint16, quint16,
)
from .framework.tensor_shape import TensorShape, Dimension
from .framework import errors
from .framework.graph import (
    Graph, Operation, Tensor, GraphKeys, TensorSpec,
    get_default_graph, reset_default_graph,
    name_scope, control_dependencies, device, colocate_with, container,
    add_to_collection, add_to_collections, get_collection, get_collection_ref,
    convert_to_tensor, convert_n_to_tensor,
    register_tensor_conversion_function,
)
from .framework.constant_op import constant
from .framework.random_seed import set_random_seed
from .framework.gradients import gradients, AggregationMethod, GradientTape
from .framework.indexed_slices import IndexedSlices
from .framework.sparse_tensor import SparseTensor, SparseTensorValue
from .framework.config_pb import ConfigProto, GPUOptions, GraphOptions

# ops: import registers lowerings; re-export the tf-1.x flat namespace
from .ops import state_ops
from .ops import variables as _variables_mod
from .ops.variables import (
    Variable, PartitionedVariable, ResourceVariable, is_resource_variable,
    global_variables, all_variables, local_variables, model_variables,
    trainable_variables, moving_average_variables,
    variables_initializer, initialize_variables,
    global_variables_initializer, initialize_all_variables,
    local_variables_initializer, initialize_local_variables,
    is_variable_initialized, assert_variables_initialized,
    report_uninitialized_variables,
)
from .ops import math_ops, array_ops, control_flow_ops, random_ops, init_ops
from .ops import nn_ops, clip_ops, logging_ops, check_ops, functional_ops
from .ops import sparse_ops, linalg_ops, spectral_ops, string_ops
from .ops import variable_scope as _vs

from .ops.math_ops import (
    add, subtract, sub, multiply, mul, divide, div, truediv, realdiv,
    floordiv, mod, floormod, pow, maximum, minimum, squared_difference,
    abs, negative, neg, sign, reciprocal, square, sqrt, rsqrt, exp, expm1,
    log, log1p, sin, cos, tan, asin, acos, atan, atan2, sinh, cosh, tanh,
    asinh, acosh, atanh, sigmoid, erf, erfc, lgamma, digamma, igamma,
    igammac, zeta, polygamma, betainc, floor, ceil, rint, round,
    is_nan, is_inf, is_finite, logical_not, logical_and, logical_or,
    logical_xor, equal, not_equal, less, less_equal, greater, greater_equal,
    cast, to_float, to_double, to_int32, to_int64, to_bfloat16, saturate_cast,
    add_n, accumulate_n, matmul, batch_matmul, tensordot, einsum, cross,
    reduce_sum, reduce_mean, reduce_prod, reduce_max, reduce_min,
    reduce_all, reduce_any, reduce_logsumexp, count_nonzero,
    argmax, argmin, cumsum, cumprod,
    segment_sum, segment_mean, segment_max, segment_min, segment_prod,
    unsorted_segment_sum, unsorted_segment_max, unsorted_segment_min,
    unsorted_segment_prod, bincount, range, linspace, lin_space,
    l2_normalize, scalar_mul, trace, real, imag, conj, angle,
)
from .ops.array_ops import (
    placeholder, placeholder_with_default, identity, stop_gradient,
    check_numerics, shape, shape_n, size, rank, reshape, transpose,
    matrix_transpose, expand_dims, squeeze, zeros, ones, fill, zeros_like,
    ones_like, concat, split, stack, pack, unstack, unpack, pad, tile,
    slice, strided_slice, gather, gather_nd, scatter_nd, one_hot, where,
    select, boolean_mask, reverse, reverse_v2, reverse_sequence,
    sequence_mask, matrix_diag, matrix_diag_part, matrix_set_diag,
    matrix_band_part, diag, diag_part, eye, invert_permutation,
    broadcast_to, space_to_batch_nd, batch_to_space_nd, space_to_depth,
    depth_to_space, extract_image_patches, unique, setdiff1d, meshgrid,
    required_space_to_batch_paddings, edit_distance,
)
from .ops.control_flow_ops import (
    no_op, group, tuple, cond, case, while_loop, with_dependencies,
)
from .ops.random_ops import (
    random_uniform, random_normal, truncated_normal, random_shuffle,
    multinomial, random_gamma, random_poisson, random_crop,
)
from .ops.clip_ops import (
    clip_by_value, clip_by_norm, clip_by_global_norm, clip_by_average_norm,
    global_norm,
)
from .ops.logging_ops import Print, Assert
from .ops.init_ops import (
    zeros_initializer, ones_initializer, constant_initializer,
    random_uniform_initializer, random_normal_initializer,
    truncated_normal_initializer, uniform_unit_scaling_initializer,
    orthogonal_initializer, variance_scaling_initializer,
    glorot_uniform_initializer, glorot_normal_initializer,
)
from .ops.functional_ops import map_fn, scan, foldl, foldr
from .ops.variable_scope import (
    variable_scope, get_variable, get_variable_scope, VariableScope,
    AUTO_REUSE, no_regularizer, variable_op_scope,
)
from .ops.state_ops import (
    assign, assign_add, assign_sub, scatter_update, scatter_add, scatter_sub,
    scatter_mul, scatter_div, scatter_nd_update, count_up_to,
)
from .ops.check_ops import (
    assert_equal, assert_greater, assert_greater_equal, assert_less,
    assert_less_equal, assert_non_negative, assert_non_positive,
    assert_negative, assert_positive, assert_rank, assert_rank_at_least,
    assert_type, assert_integer, assert_scalar,
)
from .ops.template import make_template
from .ops.functional_ops import py_func
from .ops.tensor_array_ops import TensorArray
from .ops import parsing_ops
from .ops.parsing_ops import (
    FixedLenFeature, VarLenFeature, RaggedFeature, parse_example,
    parse_single_example, decode_raw,
)
from .ops import misc_ops
from .ops.misc_ops import (
    confusion_matrix, histogram_fixed_width, bitcast, lbeta,
)
from .ops.numerics import verify_tensor_all_finite, add_check_numerics_ops
from .ops import lookup_ops as lookup
from .ops.lookup_ops import tables_initializer
from .ops import sdca_ops
from .ops.sdca_ops import sdca_optimizer, sdca_shrink_l1, sdca_fprint
from .ops import quantization_ops
from .ops.quantization_ops import (
    quantize_v2, quantize, dequantize,
    fake_quant_with_min_max_args, fake_quant_with_min_max_args_gradient,
    fake_quant_with_min_max_vars, fake_quant_with_min_max_vars_gradient,
    fake_quant_with_min_max_vars_per_channel,
)
from .ops import session_ops
from .ops.session_ops import (
    TensorHandle, get_session_handle, get_session_tensor,
    delete_session_tensor,
)
from .ops import data_flow_ops
from .ops.data_flow_ops import (
    FIFOQueue, RandomShuffleQueue, PaddingFIFOQueue, PriorityQueue,
    QueueBase, StagingArea, Barrier, RecordInput, ConditionalAccumulator,
    SparseConditionalAccumulator, dynamic_partition, dynamic_stitch,
)
from .ops import io_ops
from .ops.io_ops import (
    ReaderBase, WholeFileReader, IdentityReader, TextLineReader,
    TFRecordReader, FixedLengthRecordReader, read_file, write_file,
    matching_files,
)
from .framework.function import Defun, recompute_grad
from .framework import function
from .framework import optimizer as graph_optimizer
from .ops.linalg_ops import (
    cholesky, matrix_determinant, matrix_inverse, matrix_solve,
    matrix_triangular_solve, qr, svd, self_adjoint_eig, self_adjoint_eigvals,
    norm,
)
from .ops.spectral_ops import fft, ifft, fft2d, ifft2d, fft3d, ifft3d

# client
from .client.session import (Session, InteractiveSession,
                             get_default_session, RunOptions, RunMetadata,
                             FetchFuture, ExecutionPlan)

# namespaces (tf.nn, tf.train, tf.layers, tf.summary, ...)
from . import compiler
from . import nn
from .ops import kv_cache_ops  # registers the KV-cache/decode op types
from . import train
from . import layers
from . import losses
from . import metrics
from . import summary
from . import image
from . import data
from . import parallel
from . import saved_model
from . import serving
from . import estimator
from . import debug
from . import compat
from . import sets
from . import utils
from .utils import nest  # stf.nest (ref: python/util/nest.py)
from .platform import app, flags, tf_logging as logging, resource_loader
from .platform import monitoring
from .platform import test
from .client import device_lib
from .client import timeline

# gradient checker
from .framework.gradient_checker import compute_gradient, compute_gradient_error


# round-4 reference-parity exports (@@-export sweep vs the reference's
# python/{ops,framework,client,training} public names)
from .ops.string_ops import (
    string_join, string_lower, string_upper, string_strip, string_length,
    substr, as_string, string_to_number, string_to_hash_bucket,
    string_to_hash_bucket_fast, string_to_hash_bucket_strong,
    regex_replace, encode_base64, decode_base64, string_split, reduce_join,
)
from .ops.sparse_ops import (
    sparse_to_dense, sparse_tensor_to_dense, sparse_tensor_dense_matmul,
    sparse_add, sparse_reduce_sum, sparse_retain, sparse_reorder,
    sparse_slice, sparse_concat, sparse_placeholder, sparse_mask,
    sparse_reshape, sparse_transpose, sparse_split,
    sparse_fill_empty_rows, sparse_reset_shape, sparse_to_indicator,
    sparse_merge, sparse_softmax, sparse_maximum, sparse_minimum,
    sparse_reduce_sum_sparse,
)
from .ops.array_ops import (
    broadcast_static_shape, broadcast_dynamic_shape, parallel_stack,
    space_to_batch, batch_to_space, unique_with_counts,
)
from .ops.math_ops import (
    floor_div, truncatediv, truncatemod, complex,  # noqa: A004
    sparse_segment_sum, sparse_segment_mean, sparse_segment_sqrt_n,
)
from .ops.check_ops import (
    assert_none_equal, assert_proper_iterable, is_numeric_tensor,
    is_non_decreasing, is_strictly_increasing,
)
from .ops.spectral_ops import rfft, irfft, rfft2d, irfft2d, rfft3d, irfft3d
from .ops.variable_scope import (
    get_local_variable, fixed_size_partitioner,
    variable_axis_size_partitioner, min_max_variable_partitioner,
)
from .ops.state_ops import scatter_nd_add, scatter_nd_sub
from .ops.lookup_ops import initialize_all_tables
from .ops.session_ops import get_session_handle_v2
from .ops.parsing_ops import (
    FixedLenSequenceFeature, SparseFeature, decode_csv, parse_tensor,
    serialize_tensor, decode_json_example,
)
from .ops.misc_ops import remove_squeezable_dimensions
from .ops.linalg_ops import cholesky_solve, matrix_solve_ls
from .ops.quantization_ops import (
    quantized_concat, fake_quant_with_min_max_vars_per_channel_gradient,
)
from .platform.resource_loader import (
    load_op_library, load_file_system_library,
)
from .ops.data_flow_ops import ConditionalAccumulatorBase
from .framework.graph import (
    convert_to_tensor_or_indexed_slices, convert_to_tensor_or_sparse_tensor,
    op_scope,
)
from .framework.graph_io import import_graph_def, import_meta_graph, \
    export_meta_graph, write_graph
from .framework.gradients import (
    RegisterGradient, NotDifferentiable, NoGradient, hessians,
)
from .framework.random_seed import get_seed

# static analysis: graph verifier, variable-hazard detector, lint
# framework (stf.analysis; see docs/ANALYSIS.md)
from . import analysis

# production telemetry plane: HTTP metrics/status server, request
# tracing, flight recorder + watchdog (stf.telemetry;
# docs/OBSERVABILITY.md)
from . import telemetry

# async checkpointing + preemption-safe training (stf.checkpoint;
# docs/CHECKPOINT.md)
from . import checkpoint

# Pallas/XLA kernel routing tier: per-(op, shape, dtype, backend)
# fallback registry with cost-model gating and a measured autotune
# cache (stf.kernels; docs/PERFORMANCE.md "kernel tier")
from . import kernels

newaxis = None
