"""Graph optimizer passes: constant folding, CSE, DCE
(ref: tensorflow/core/common_runtime/constant_folding.cc,
core/graph/optimizer_cse.cc, core/grappler/).

On TPU most of this work belongs to XLA — the whole pruned subgraph
compiles as one program and XLA constant-folds/CSEs/fuses HLO. These
passes run *before tracing* on the GraphDef level, where they still pay:
- smaller graphs trace faster (Session compile latency),
- exported GraphDefs / SavedModels shrink,
- AOT keys stabilize (CSE canonicalizes).
They operate on the GraphDef-JSON dict (framework/graph_io.py), returning
a new dict — the Graph IR itself is immutable-append by design.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from . import dtypes as dtypes_mod
from . import op_registry

_FOLDABLE_BLOCKLIST = {"Placeholder", "PlaceholderWithDefault", "Const",
                       "VariableV2", "VarRead", "Assign"}


def _tensor_ref(name: str) -> Tuple[str, int]:
    if ":" in name:
        node, idx = name.rsplit(":", 1)
        return node, int(idx)
    return name, 0


def _is_pure(node) -> bool:
    try:
        od = op_registry.get(node["op"])
    except KeyError:
        return False
    return od.pure_fn is not None and not od.is_stateful


def dead_code_elimination(graph_def: Dict, keep: List[str]) -> Dict:
    """Drop nodes not reachable (as dependencies) from ``keep`` node/tensor
    names (ref: core/graph/algorithm.cc PruneForReverseReachability)."""
    nodes = {n["name"]: n for n in graph_def["node"]}
    work = [_tensor_ref(k)[0] for k in keep]
    live: Set[str] = set()
    while work:
        name = work.pop()
        if name in live or name not in nodes:
            continue
        live.add(name)
        n = nodes[name]
        work.extend(_tensor_ref(i)[0] for i in n.get("input", []))
        work.extend(n.get("control_input", []))
    out = copy.deepcopy(graph_def)
    out["node"] = [n for n in graph_def["node"] if n["name"] in live]
    return out


def common_subexpression_elimination(graph_def: Dict,
                                     keep: Optional[List[str]] = None) -> Dict:
    """Merge pure nodes with identical (op, inputs, attrs)
    (ref: core/graph/optimizer_cse.cc). Nodes named in ``keep`` are never
    merged away — callers fetch them by name after import."""
    keep_names: Set[str] = {_tensor_ref(k)[0] for k in (keep or [])}
    out = copy.deepcopy(graph_def)
    replace: Dict[str, str] = {}  # old node name -> canonical node name
    seen: Dict[str, str] = {}  # signature -> canonical name
    kept = []
    for n in out["node"]:
        # rewrite inputs through earlier merges first
        n["input"] = [_rewrite(i, replace) for i in n.get("input", [])]
        n["control_input"] = [replace.get(c, c)
                              for c in n.get("control_input", [])]
        if not _is_pure(n) or n.get("control_input"):
            kept.append(n)
            continue
        sig = repr((n["op"], n["input"],
                    sorted((k, repr(v)) for k, v in
                           n.get("attr", {}).items())))
        if sig in seen and n["name"] not in keep_names:
            replace[n["name"]] = seen[sig]
        else:
            if sig not in seen:
                seen[sig] = n["name"]
            kept.append(n)
    out["node"] = kept
    return out


def _rewrite(tensor_name: str, replace: Dict[str, str]) -> str:
    node, idx = _tensor_ref(tensor_name)
    if node in replace:
        return f"{replace[node]}:{idx}"
    return tensor_name


def constant_folding(graph_def: Dict) -> Dict:
    """Evaluate pure nodes whose inputs are all Consts, replacing them with
    Const nodes (ref: core/common_runtime/constant_folding.cc). Uses each
    op's registered jax pure_fn on host numpy values — the same semantics
    the compiled program would have."""
    import jax

    from . import graph_io

    out = copy.deepcopy(graph_def)
    values: Dict[str, List[Any]] = {}  # node name -> output values
    for n in out["node"]:
        if n["op"] == "Const":
            v = graph_io._decode_attr(n.get("attr", {}).get("value"))
            if v is not None:
                values[n["name"]] = [np.asarray(v)]
    new_nodes = []
    for n in out["node"]:
        name = n["name"]
        if n["op"] == "Const" or not _is_pure(n) or n.get("control_input"):
            new_nodes.append(n)
            continue
        in_refs = [_tensor_ref(i) for i in n.get("input", [])]
        if not in_refs or not all(r[0] in values for r in in_refs):
            new_nodes.append(n)
            continue
        od = op_registry.get(n["op"])
        attrs = {k: graph_io._decode_attr(v)
                 for k, v in n.get("attr", {}).items()
                 if not k.startswith("_") and k != "dtype"}
        try:
            with jax.default_device(jax.devices("cpu")[0]):
                result = od.pure_fn(
                    *[values[r[0]][r[1]] for r in in_refs], **attrs)
        except Exception:
            new_nodes.append(n)  # fold failure leaves the node alone
            continue
        outs = (list(result) if isinstance(result, (list, tuple))
                else [result])
        outs = [np.asarray(o) for o in outs]
        values[name] = outs
        if len(outs) == 1:  # replace with a Const node
            spec = n.get("output_specs") or [[list(outs[0].shape),
                                              str(outs[0].dtype)]]
            folded = {
                "name": name, "op": "Const", "input": [],
                "control_input": [], "device": n.get("device", ""),
                "attr": {"value": graph_io._encode_attr(outs[0]),
                         "dtype": graph_io._encode_attr(
                             dtypes_mod.as_dtype(spec[0][1]))},
                "output_specs": spec,
            }
            new_nodes.append(folded)
        else:
            new_nodes.append(n)
    out["node"] = new_nodes
    return out


def optimize(graph_def: Dict, keep: Optional[List[str]] = None) -> Dict:
    """grappler-equivalent pipeline: fold -> CSE -> DCE."""
    gd = constant_folding(graph_def)
    gd = common_subexpression_elimination(gd, keep=keep)
    if keep:
        gd = dead_code_elimination(gd, keep)
    return gd


# ---------------------------------------------------------------------------
# IR-level passes (the Session's hot path)
# ---------------------------------------------------------------------------

_FOLD_MAX_BYTES = 1 << 20  # don't materialize folded constants above 1 MiB


def optimize_pruned(op_list, fed_tensors, keep_tensors):
    """Fold/CSE/DCE over a pruned, topo-ordered Operation list — the pass
    Session._plan runs before lowering (ref grappler's role ahead of the
    executor; core/common_runtime/constant_folding.cc).

    Works WITHOUT mutating the graph (the IR is immutable-append):
    returns ``(new_op_list, const_env, alias)`` where
      const_env: Tensor -> np.ndarray — outputs computed at plan time;
        the Session seeds them into the lowering env, so the ops that
        produced them never trace,
      alias: Tensor -> Tensor — CSE-duplicate output -> canonical output;
        consulted at every input lookup during lowering.

    Ops are foldable/CSE-able only via ``pure_fn`` (stateless by
    construction: RNG, variables, placeholders, host IO all register with
    ``lower=`` and/or ``is_stateful`` and are excluded)."""
    import jax

    const_env: Dict[Any, Any] = {}
    alias: Dict[Any, Any] = {}
    sigs: Dict[str, Any] = {}  # signature -> canonical op
    new_list = []
    for op in op_list:
        od = op.op_def
        if op.type == "Const":
            v = op.attrs.get("value")
            if v is not None and op.outputs:
                const_env[op.outputs[0]] = np.asarray(v)
            new_list.append(op)  # kept for host-stage consumers; DCE'd below
            continue
        pure = (od.pure_fn is not None and not od.is_stateful
                and not od.runs_on_host and not op.control_inputs
                and op.type not in _FOLDABLE_BLOCKLIST)
        resolved_ins = [alias.get(t, t) for t in op.inputs]
        if pure and resolved_ins and all(t in const_env
                                         for t in resolved_ins):
            attrs = {k: v for k, v in op.attrs.items()
                     if not k.startswith("_")}
            try:
                with jax.default_device(jax.devices("cpu")[0]):
                    out = od.pure_fn(
                        *[const_env[t] for t in resolved_ins], **attrs)
            except Exception:
                out = None  # fold failure leaves the op alone
            if out is not None:
                outs = (list(out) if isinstance(out, (list, tuple))
                        else [out])
                outs = [np.asarray(o) for o in outs]
                if (len(outs) == len(op.outputs) and
                        sum(o.nbytes for o in outs) <= _FOLD_MAX_BYTES):
                    for t, v in zip(op.outputs, outs):
                        const_env[t] = v
                    continue  # folded: op never lowers
        if pure:
            sig = repr((op.type,
                        tuple(id(t) for t in resolved_ins),
                        sorted((k, repr(v)) for k, v in op.attrs.items()
                               if not k.startswith("_"))))
            canon = sigs.get(sig)
            if canon is not None:
                for dup_out, canon_out in zip(op.outputs, canon.outputs):
                    alias[dup_out] = alias.get(canon_out, canon_out)
                continue  # CSE'd: op never lowers
            sigs[sig] = op
        new_list.append(op)

    # DCE (reverse walk): effects stay; pure ops stay only if some kept op
    # or fetch consumes an output (through aliases), and folded consumers
    # are gone already.
    needed = set()
    for t in keep_tensors:
        t = alias.get(t, t)
        if t not in const_env:
            needed.add(t)
    kept_rev = []
    for op in reversed(new_list):
        od = op.op_def
        effectful = od.is_stateful or od.runs_on_host or not op.outputs
        wanted = effectful or any(o in needed for o in op.outputs)
        if not wanted:
            continue
        kept_rev.append(op)
        for t in op.inputs:
            t = alias.get(t, t)
            if t not in const_env and t not in fed_tensors:
                needed.add(t)
        for c in op.control_inputs:
            # output-less control deps are effectful and kept by the rule
            # above; tensor-producing ones are kept via their outputs
            needed.update(c.outputs)
    return list(reversed(kept_rev)), const_env, alias
