"""Graph optimizer passes: constant folding, CSE, DCE, layout, LICM —
driven by a function-aware PassManager
(ref: tensorflow/core/common_runtime/constant_folding.cc,
core/graph/optimizer_cse.cc, core/grappler/ — grappler's
meta_optimizer.cc processes FunctionDef bodies; stf's passes recurse the
same way into the FuncGraphs that cond/while/scan/defun store in node
attrs).

On TPU most of this work belongs to XLA — the whole pruned subgraph
compiles as one program and XLA constant-folds/CSEs/fuses HLO. These
passes run *before tracing* on the GraphDef level, where they still pay:
- smaller graphs trace faster (Session compile latency),
- exported GraphDefs / SavedModels shrink,
- AOT keys stabilize (CSE canonicalizes),
- layout conversions around NCHW image ops cancel — including inside
  cond branches and while/scan bodies, where a per-op transpose is paid
  once per LOOP ITERATION if left in place.
They operate on the GraphDef-JSON dict (framework/graph_io.py), returning
a new dict — the Graph IR itself is immutable-append by design.

Function-op anatomy (who declares what): ops that embed FuncGraph bodies
register a FunctionOpSpec via ``register_function_op`` (see
ops/control_flow_ops.py Cond/While, ops/functional_ops.py
MapFn/Scan/Foldl, framework/function.py GraphFunctionCall /
RecomputeGradCall). The spec names each body attr, locates the body's
captured inputs inside the op's input list, and says whether the body
re-executes per iteration (→ loop-invariant code motion is profitable)
— the single place future rewrites (quantize_weights, fuse_convolutions)
plug into. Rewritten bodies always keep their signature: same
inputs/outputs arity and dtypes, captures only ever APPENDED (LICM), so
importers, Session executable-cache keys, and framework/lowering.py stay
valid.
"""

from __future__ import annotations

import copy
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from . import dtypes as dtypes_mod
from . import op_registry
from . import tensor_shape as shape_mod
from ..platform import monitoring

# per-pass observability (ref: grappler's meta_optimizer logs
# per-optimizer wall time and "graph rewritten" counts the same way)
_metric_pass_seconds = monitoring.Sampler(
    "/stf/graph/optimizer/pass_seconds",
    monitoring.ExponentialBuckets(1e-6, 4.0, 16),
    "wall seconds per PassManager pass invocation", "pass")
_metric_pass_runs = monitoring.Counter(
    "/stf/graph/optimizer/pass_runs",
    "PassManager pass invocations", "pass")
_metric_pass_rewrites = monitoring.Counter(
    "/stf/graph/optimizer/pass_rewrites",
    "PassManager pass invocations that changed the graph", "pass")

_FOLDABLE_BLOCKLIST = {"Placeholder", "PlaceholderWithDefault", "Const",
                       "VariableV2", "VarRead", "Assign"}


def _tensor_ref(name: str) -> Tuple[str, int]:
    if ":" in name:
        node, idx = name.rsplit(":", 1)
        return node, int(idx)
    return name, 0


def _is_pure(node) -> bool:
    try:
        od = op_registry.get(node["op"])
    except KeyError:
        return False
    return od.pure_fn is not None and not od.is_stateful


# ---------------------------------------------------------------------------
# function-aware pass infrastructure
# ---------------------------------------------------------------------------

class FunctionOpSpec:
    """How an op type embeds FuncGraph bodies (registered by the op's
    module via ``register_function_op``).

    ``bodies(attrs, n_inputs)`` returns one descriptor per body graph:
      attr:       node attr holding the FuncGraph,
      start:      index in the op's input list where this body's captured
                  inputs begin,
      count:      how many captured inputs belong to this body,
      hoist:      True when the body re-executes per iteration (while
                  cond/body, scan/map/fold fns) so hoisting
                  loop-invariant subexpressions out pays,
      count_attr: node attr counting this body's captures — bumped when
                  LICM appends one; None when the captures are the
                  trailing inputs (count is implicit).

    ``mode`` drives cost attribution (framework/cost_model.py):
      "loop"   — every body runs ``trip(attrs, inputs)`` times,
      "branch" — exactly one body runs per execution,
      "call"   — bodies run once, inline.
    """

    __slots__ = ("op_type", "bodies", "mode", "trip")

    def __init__(self, op_type, bodies, mode="call", trip=None):
        self.op_type = op_type
        self.bodies = bodies
        self.mode = mode
        self.trip = trip


_FUNCTION_OPS: Dict[str, FunctionOpSpec] = {}


def register_function_op(op_type: str, bodies: Callable, mode: str = "call",
                         trip: Optional[Callable] = None) -> FunctionOpSpec:
    spec = FunctionOpSpec(op_type, bodies, mode=mode, trip=trip)
    _FUNCTION_OPS[op_type] = spec
    return spec


def function_op_spec(op_type: str) -> Optional[FunctionOpSpec]:
    return _FUNCTION_OPS.get(op_type)


def _node_bodies(node: Dict) -> List[Tuple[Dict, Dict]]:
    """(descriptor, body_graph_dict) per FuncGraph attr of a GraphDef
    node. Body dicts are graph_io._funcgraph_to_dict shaped: the pass
    functions treat them as GraphDefs with extra inputs/outputs/captures
    keys (all preserved by the deepcopy-and-replace-"node" idiom; each
    recursion level re-deepcopies its bodies — accepted cost, since body
    dicts are small and nesting is shallow in practice)."""
    spec = _FUNCTION_OPS.get(node.get("op"))
    if spec is None:
        return []
    attrs = node.get("attr", {})
    try:
        descs = spec.bodies(attrs, len(node.get("input", [])))
    except (KeyError, TypeError):
        return []
    out = []
    for d in descs:
        enc = attrs.get(d["attr"])
        if isinstance(enc, dict) and enc.get("__kind__") == "funcgraph":
            out.append((d, enc["v"]))
    return out


def _body_keep(body: Dict) -> List[str]:
    """The body's signature: its output refs plus every FuncArg /
    CapturedInput node — lowering binds them positionally, so no pass may
    drop or rename them."""
    keep = list(body.get("outputs", []))
    keep += [n["name"] for n in body.get("node", [])
             if n.get("op") in ("FuncArg", "CapturedInput")]
    return keep


def _signature_broken(old: Dict, new: Dict) -> bool:
    """A rewritten body must keep its calling convention: identical input
    refs, same output arity, the old captures as a prefix of the new
    (LICM appends), and every signature ref still resolvable."""
    if list(old.get("inputs", [])) != list(new.get("inputs", [])):
        return True
    if len(old.get("outputs", [])) != len(new.get("outputs", [])):
        return True
    old_inner = [c[1] for c in old.get("captures", [])]
    new_inner = [c[1] for c in new.get("captures", [])]
    if new_inner[:len(old_inner)] != old_inner:
        return True
    names = {n["name"] for n in new.get("node", [])}
    need = {_tensor_ref(r)[0] for r in
            list(new.get("inputs", [])) + list(new.get("outputs", []))
            + new_inner}
    return not need <= names


def _set_body(node: Dict, desc: Dict, new_body: Dict,
              old_body: Optional[Dict] = None) -> None:
    if old_body is not None and _signature_broken(old_body, new_body):
        return  # defensive: a signature-breaking rewrite is discarded
    node["attr"][desc["attr"]] = {"__kind__": "funcgraph", "v": new_body}


def _uniq_in(used: Set[str], base: str) -> str:
    name = base
    k = 1
    while name in used:
        name = f"{base}_{k}"
        k += 1
    used.add(name)
    return name


def dead_code_elimination(graph_def: Dict, keep: List[str]) -> Dict:
    """Drop nodes not reachable (as dependencies) from ``keep`` node/tensor
    names (ref: core/graph/algorithm.cc PruneForReverseReachability).
    Recurses into FuncGraph bodies of surviving nodes, keeping each
    body's signature (inputs/captures/outputs) alive."""
    nodes = {n["name"]: n for n in graph_def["node"]}
    work = [_tensor_ref(k)[0] for k in keep]
    live: Set[str] = set()
    while work:
        name = work.pop()
        if name in live or name not in nodes:
            continue
        live.add(name)
        n = nodes[name]
        work.extend(_tensor_ref(i)[0] for i in n.get("input", []))
        work.extend(n.get("control_input", []))
    out = copy.deepcopy(graph_def)
    out["node"] = [n for n in out["node"] if n["name"] in live]
    for n in out["node"]:
        for d, b in _node_bodies(n):
            _set_body(n, d, dead_code_elimination(b, _body_keep(b)), b)
    return out


def common_subexpression_elimination(graph_def: Dict,
                                     keep: Optional[List[str]] = None) -> Dict:
    """Merge pure nodes with identical (op, inputs, attrs)
    (ref: core/graph/optimizer_cse.cc). Nodes named in ``keep`` are never
    merged away — callers fetch them by name after import. FuncGraph
    bodies are CSE'd recursively with their signature kept — duplicate
    subexpressions inside while/scan bodies cost once per ITERATION, so
    this is where CSE pays most."""
    keep_names: Set[str] = {_tensor_ref(k)[0] for k in (keep or [])}
    out = copy.deepcopy(graph_def)
    replace: Dict[str, str] = {}  # old node name -> canonical node name
    seen: Dict[str, str] = {}  # signature -> canonical name
    kept = []
    for n in out["node"]:
        for d, b in _node_bodies(n):
            _set_body(n, d, common_subexpression_elimination(
                b, keep=_body_keep(b)), b)
        # rewrite inputs through earlier merges first
        n["input"] = [_rewrite(i, replace) for i in n.get("input", [])]
        n["control_input"] = [replace.get(c, c)
                              for c in n.get("control_input", [])]
        if not _is_pure(n) or n.get("control_input"):
            kept.append(n)
            continue
        sig = repr((n["op"], n["input"],
                    sorted((k, repr(v)) for k, v in
                           n.get("attr", {}).items())))
        if sig in seen and n["name"] not in keep_names:
            replace[n["name"]] = seen[sig]
        else:
            if sig not in seen:
                seen[sig] = n["name"]
            kept.append(n)
    out["node"] = kept
    return out


def _rewrite(tensor_name: str, replace: Dict[str, str]) -> str:
    node, idx = _tensor_ref(tensor_name)
    if node in replace:
        return f"{replace[node]}:{idx}"
    return tensor_name


_SHAPE_OPS = {"Shape", "Size", "Rank"}


def constant_folding(graph_def: Dict,
                     seed_values: Optional[Dict[str, Any]] = None) -> Dict:
    """Evaluate pure nodes whose inputs are all Consts, replacing them with
    Const nodes (ref: core/common_runtime/constant_folding.cc). Uses each
    op's registered jax pure_fn on host numpy values — the same semantics
    the compiled program would have. Shape/Size/Rank of statically-shaped
    producers fold from the shape alone (grappler's
    shape-materialization), without needing a constant input value.

    Recurses into FuncGraph bodies with cross-boundary constant
    propagation: a constant captured by a cond branch / while body is
    seeded into the body's fold via ``seed_values`` (node name → value
    for that node's output 0 — captures are loop-invariant, so the seed
    holds on every iteration). Seeded CapturedInput nodes are never
    themselves replaced (the body signature must survive), only their
    consumers fold."""
    import jax

    from . import graph_io

    out = copy.deepcopy(graph_def)
    values: Dict[str, List[Any]] = {}  # node name -> output values
    for name, v in (seed_values or {}).items():
        values[name] = [np.asarray(v)]
    specs_by_name: Dict[str, Any] = {n["name"]: n.get("output_specs")
                                     for n in out["node"]}
    for n in out["node"]:
        if n["op"] == "Const":
            v = graph_io._decode_attr(n.get("attr", {}).get("value"))
            if v is not None:
                values[n["name"]] = [np.asarray(v)]
    new_nodes = []
    for n in out["node"]:
        name = n["name"]
        bodies = _node_bodies(n)
        if bodies:
            # cross-boundary propagation: captures whose outer producer
            # already has a known value seed the body's fold
            for d, b in bodies:
                inner_seeds: Dict[str, Any] = {}
                for i, cap in enumerate(b.get("captures", [])):
                    idx = d["start"] + i
                    if idx >= len(n.get("input", [])):
                        break
                    src, k = _tensor_ref(n["input"][idx])
                    if src in values and k < len(values[src]):
                        inner_seeds[_tensor_ref(cap[1])[0]] = values[src][k]
                _set_body(n, d, constant_folding(b, seed_values=inner_seeds),
                          b)
            new_nodes.append(n)
            continue
        if n["op"] == "Const" or not _is_pure(n) or n.get("control_input"):
            new_nodes.append(n)
            continue
        if n["op"] in _SHAPE_OPS and n.get("input"):
            src, idx = _tensor_ref(n["input"][0])
            specs = specs_by_name.get(src)
            sh = (specs[idx][0] if specs and idx < len(specs) else None)
            if isinstance(sh, list) and all(
                    isinstance(d, int) for d in sh):
                from . import graph_io

                ot = graph_io._decode_attr(
                    n.get("attr", {}).get("out_type"))
                # out_type through the 64-bit narrowing: a folded Shape
                # must carry the dtype the runtime path computes
                np_dt = (dtypes_mod.narrowed_if_no_x64(ot).np_dtype
                         if ot is not None else np.int32)
                if n["op"] == "Shape":
                    arr = np.asarray(sh, np_dt)
                elif n["op"] == "Size":
                    arr = np.asarray(int(np.prod(sh)) if sh else 1,
                                     np_dt)
                else:
                    arr = np.asarray(len(sh), np.int32)  # Rank: int32
                values[name] = [arr]
                new_nodes.append({
                    "name": name, "op": "Const", "input": [],
                    "control_input": [], "device": n.get("device", ""),
                    "attr": {"value": graph_io._encode_attr(arr),
                             "dtype": graph_io._encode_attr(
                                 dtypes_mod.as_dtype(str(arr.dtype)))},
                    "output_specs": [[list(arr.shape), str(arr.dtype)]],
                })
                continue
        in_refs = [_tensor_ref(i) for i in n.get("input", [])]
        if not in_refs or not all(r[0] in values for r in in_refs):
            new_nodes.append(n)
            continue
        od = op_registry.get(n["op"])
        attrs = {k: graph_io._decode_attr(v)
                 for k, v in n.get("attr", {}).items()
                 if not k.startswith("_") and k != "dtype"}
        try:
            with jax.default_device(jax.devices("cpu")[0]):
                result = od.pure_fn(
                    *[values[r[0]][r[1]] for r in in_refs], **attrs)
        except Exception:
            new_nodes.append(n)  # fold failure leaves the node alone
            continue
        outs = (list(result) if isinstance(result, (list, tuple))
                else [result])
        outs = [np.asarray(o) for o in outs]
        values[name] = outs
        if len(outs) == 1:  # replace with a Const node
            spec = n.get("output_specs") or [[list(outs[0].shape),
                                              str(outs[0].dtype)]]
            folded = {
                "name": name, "op": "Const", "input": [],
                "control_input": [], "device": n.get("device", ""),
                "attr": {"value": graph_io._encode_attr(outs[0]),
                         "dtype": graph_io._encode_attr(
                             dtypes_mod.as_dtype(spec[0][1]))},
                "output_specs": spec,
            }
            new_nodes.append(folded)
        else:
            new_nodes.append(n)
    out["node"] = new_nodes
    return out


# ---------------------------------------------------------------------------
# layout optimization (ref: core/grappler/optimizers/layout_optimizer.cc)
# ---------------------------------------------------------------------------

_NCHW_TO_NHWC = (0, 2, 3, 1)
_NHWC_TO_NCHW = (0, 3, 1, 2)

# image ops that carry a data_format attr; "vec" attrs are per-dimension
# 4-vectors (strides/ksize/dilations) permuted along with the layout
_LAYOUT_OPS = {
    "Conv2D": ("strides", "dilations"),
    "DepthwiseConv2dNative": ("strides", "dilations"),
    "MaxPool": ("strides", "ksize"),
    "AvgPool": ("strides", "ksize"),
    "FusedBatchNorm": (),
    "BiasAdd": (),
}

# rank-preserving elementwise ops a transpose can move through unchanged
_LAYOUT_AGNOSTIC = {
    "Relu", "Relu6", "Elu", "Selu", "LeakyRelu", "Tanh", "Sigmoid",
    "Softplus", "Abs", "Neg", "Square", "Sqrt", "Rsqrt", "Exp", "Log",
    "Identity", "Add", "AddV2", "Sub", "Mul", "RealDiv", "Maximum",
    "Minimum", "SquaredDifference",
}


def _compose_perm(p2, p1):
    """perm of transpose(transpose(x, p2), p1)."""
    return tuple(p2[i] for i in p1)


def layout_optimization(graph_def: Dict,
                        keep: Optional[List[str]] = None) -> Dict:
    """Rewrite NCHW image ops to NHWC globally (ref: grappler
    layout_optimizer.cc). TPU rationale: the per-op lowering honors NCHW
    by transposing around EVERY conv/pool/bn call; this pass instead
    converts the ops once and pushes the layout conversions to the
    subgraph boundary, cancelling interior transpose pairs — an NCHW
    ResNet block lowers with exactly two transposes (one in, one out).

    Three phases: (1) convert each NCHW op to NHWC with explicit
    boundary transposes; (2) push NHWC→NCHW transposes down through
    rank-preserving elementwise ops (so pairs become adjacent);
    (3) cancel adjacent inverse pairs, then DCE.
    Touched nodes drop their output_specs — the importer's shape
    inference recomputes them in the new layout.
    """
    from . import graph_io

    out = copy.deepcopy(graph_def)
    nodes: List[Dict] = out["node"]
    by_name = {n["name"]: n for n in nodes}

    def _uniq(base):
        name = base
        k = 1
        while name in by_name:
            name = f"{base}_{k}"
            k += 1
        return name

    def _attr(n, key, default=None):
        v = n.get("attr", {}).get(key)
        return default if v is None else graph_io._decode_attr(v)

    def _perm_of(n):
        p = _attr(n, "perm")
        return tuple(p) if p is not None else ()

    enc = graph_io._encode_attr

    # ---- phase 0: recurse into FuncGraph bodies (cond branches, while
    # bodies, scan/map fns, defun bodies). Signature preserved: the
    # name-swap trick keeps every body-internal AND boundary ref meaning
    # NCHW data, so loop-carried vars keep their layout — interior
    # transpose pairs cancel per iteration, and push_loop_layout (run
    # after this pass) moves the remaining boundary pair out of while
    # loops whose body provably maps NHWC→NHWC.
    for n in nodes:
        for d, b in _node_bodies(n):
            _set_body(n, d, layout_optimization(b, keep=_body_keep(b)), b)

    # ---- phase 1: per-op conversion (in topo order, so a converted
    # producer's boundary transpose is visible to later converts).
    # NAME SWAP: the converted op is renamed "<name>/nhwc" and the
    # inverse output transpose takes the ORIGINAL name, so every
    # existing reference — graph edges AND by-name fetches — still sees
    # NCHW data without any rewiring. Extra outputs (FusedBatchNorm's
    # per-channel mean/var) are layout-free and rewired to the renamed
    # node directly — but only graph-INTERNAL edges can be rewired, so a
    # multi-output op with an externally visible ":k" (k>0) ref in
    # ``keep`` is left unconverted (":0" keeps work: the shim serves
    # them — this is what lets a FusedBatchNorm that IS a cond-branch
    # output still convert).
    keep_names = {_tensor_ref(k)[0] for k in (keep or [])}
    keep_extra_out = {_tensor_ref(k)[0] for k in (keep or [])
                      if _tensor_ref(k)[1] > 0}
    new_nodes: List[Dict] = []
    rewire: Dict[str, str] = {}  # "orig:k" (k>0) -> "<orig>/nhwc:k"
    converted = []
    for n in nodes:
        if n["op"] not in _LAYOUT_OPS or _attr(n, "data_format") != "NCHW":
            new_nodes.append(n)
            continue
        if len(n.get("output_specs") or []) > 1 \
                and n["name"] in keep_extra_out:
            # a by-name fetch references output k>0, which the
            # single-output transpose shim cannot serve
            new_nodes.append(n)
            continue
        orig = n["name"]
        vec_attrs = _LAYOUT_OPS[n["op"]]
        n["attr"]["data_format"] = "NHWC"
        for va in vec_attrs:
            v = _attr(n, va)
            if isinstance(v, (list, tuple)) and len(v) == 4:
                n["attr"][va] = enc(tuple((v[0], v[2], v[3], v[1])))
        n_specs = len(n.get("output_specs") or [])
        n.pop("output_specs", None)
        del by_name[orig]
        n["name"] = _uniq(orig + "/nhwc")
        by_name[n["name"]] = n
        for k in range(1, n_specs):
            rewire[f"{orig}:{k}"] = f"{n['name']}:{k}"
        # transpose the data input (input 0 for every op here); chained
        # converted producers resolve automatically: their original name
        # now names their inverse transpose
        t_in = {
            "name": _uniq(orig + "/nchw_to_nhwc"),
            "op": "Transpose", "input": [n["input"][0]],
            "control_input": [], "device": n.get("device", ""),
            "attr": {"perm": enc(_NCHW_TO_NHWC)},
        }
        by_name[t_in["name"]] = t_in
        new_nodes.append(t_in)
        n["input"] = [t_in["name"] + ":0"] + list(n["input"][1:])
        new_nodes.append(n)
        # inverse transpose under the ORIGINAL name serves consumers
        t_out = {
            "name": orig,
            "op": "Transpose", "input": [n["name"] + ":0"],
            "control_input": [], "device": n.get("device", ""),
            "attr": {"perm": enc(_NHWC_TO_NCHW)},
        }
        by_name[orig] = t_out
        new_nodes.append(t_out)
        converted.append(orig)
    if rewire:
        conv_set = set(converted)
        for n in new_nodes:
            if n["name"] in conv_set:  # the t_out shims keep ":0" inputs
                continue
            n["input"] = [rewire.get(i, i) for i in n.get("input", [])]
    nodes = new_nodes
    by_name = {n["name"]: n for n in nodes}

    # ---- phase 2: push NHWC->NCHW transposes through elementwise ----
    def _is_inv_transpose(ref):
        node, idx = _tensor_ref(ref)
        m = by_name.get(node)
        return (m is not None and m["op"] == "Transpose" and idx == 0
                and _perm_of(m) == _NHWC_TO_NCHW)

    def _rank4_ref(ref):
        """Producer output spec says rank 4 (safe to forward-transpose)."""
        node, idx = _tensor_ref(ref)
        m = by_name.get(node)
        specs = (m or {}).get("output_specs")
        if not specs or idx >= len(specs):
            return False
        sh = specs[idx][0]
        return isinstance(sh, list) and len(sh) == 4

    changed = True
    while changed:
        changed = False
        addenda = []
        for n in nodes:
            if n["op"] not in _LAYOUT_AGNOSTIC or n.get("control_input"):
                continue
            ins = n.get("input", [])
            # every input must be pushable: already NHWC behind an inverse
            # transpose, or a rank-4 tensor we can forward-transpose here
            # (identity shortcuts: Add(bn_out, x) — the x transpose then
            # CSEs with the first conv's input transpose). Same-rank
            # inputs only: broadcasting scalars would change meaning.
            if not ins or not any(_is_inv_transpose(i) for i in ins):
                continue
            if not all(_is_inv_transpose(i) or _rank4_ref(i)
                       for i in ins):
                continue
            if any(k in n.get("attr", {}) for k in ("data_format",)):
                continue
            # consume the transposes' NHWC inputs directly; forward-
            # transpose the NCHW stragglers
            new_ins = []
            for i in ins:
                if _is_inv_transpose(i):
                    new_ins.append(by_name[_tensor_ref(i)[0]]["input"][0])
                else:
                    t_f = {
                        "name": _uniq(_tensor_ref(i)[0] +
                                      "/nchw_to_nhwc"),
                        "op": "Transpose", "input": [i],
                        "control_input": [],
                        "device": n.get("device", ""),
                        "attr": {"perm": enc(_NCHW_TO_NHWC)},
                    }
                    by_name[t_f["name"]] = t_f
                    addenda.append((_tensor_ref(i)[0], t_f))
                    new_ins.append(t_f["name"] + ":0")
            n["input"] = new_ins
            n.pop("output_specs", None)
            # name swap (as in phase 1): this op becomes "<name>/nhwc",
            # an inverse transpose under the ORIGINAL name serves every
            # existing reference unchanged
            orig = n["name"]
            del by_name[orig]
            n["name"] = _uniq(orig + "/nhwc")
            by_name[n["name"]] = n
            t_out = {
                "name": orig,
                "op": "Transpose", "input": [n["name"] + ":0"],
                "control_input": [], "device": n.get("device", ""),
                "attr": {"perm": enc(_NHWC_TO_NCHW)},
            }
            by_name[orig] = t_out
            addenda.append((n["name"], t_out))
            changed = True
        # splice each new transpose right after its producer
        for prod_name, t_out in addenda:
            idx = next(i for i, m in enumerate(nodes)
                       if m["name"] == prod_name)
            nodes.insert(idx + 1, t_out)

    # ---- phase 3: cancel adjacent inverse pairs ---------------------
    alias: Dict[str, str] = {}
    for n in nodes:
        n["input"] = [alias.get(i, i) for i in n.get("input", [])]
        if n["op"] != "Transpose":
            continue
        p1 = _perm_of(n)
        src_name, src_idx = _tensor_ref(n["input"][0])
        src = by_name.get(src_name)
        if (src is not None and src["op"] == "Transpose" and src_idx == 0):
            p2 = _perm_of(src)
            if len(p1) == len(p2) and \
                    _compose_perm(p2, p1) == tuple(range(len(p1))):
                alias[n["name"] + ":0"] = src["input"][0]
    for n in nodes:
        n["input"] = [alias.get(i, i) for i in n.get("input", [])]

    out["node"] = nodes
    if keep:
        out = dead_code_elimination(out, keep)
    return out


# ---------------------------------------------------------------------------
# loop-invariant code motion (ref: grappler/optimizers/loop_optimizer.cc
# LoopInvariantNodeMotionOptimizer)
# ---------------------------------------------------------------------------

def loop_invariant_code_motion(graph_def: Dict,
                               keep: Optional[List[str]] = None) -> Dict:
    """Hoist pure body subexpressions that depend only on captures/consts
    out of while/scan/map bodies (descriptors with hoist=True) into the
    enclosing graph. The hoisted value re-enters the body as a new
    APPENDED capture, so the body signature (inputs/outputs, existing
    captures) is untouched; the op's input list grows at the body's
    capture slot and the relevant count attr is bumped. Runs bottom-up,
    so an expression nested two bodies deep migrates one level per graph
    and reaches the outermost invariant scope in one pipeline run."""
    out = copy.deepcopy(graph_def)
    used = {n["name"] for n in out["node"]}
    result: List[Dict] = []
    for node in out["node"]:
        for d, b in _node_bodies(node):
            _set_body(node, d, loop_invariant_code_motion(b), b)
        # trailing-captures body first: its inserts don't shift the
        # earlier slices, and earlier inserts bump their count attr so
        # later recomputation stays consistent
        for d, b in sorted(_node_bodies(node),
                           key=lambda db: -db[0]["start"]):
            if d.get("hoist"):
                result.extend(_hoist_from_body(node, d, b, used))
        result.append(node)
    out["node"] = result
    return out


def _hoist_from_body(node: Dict, desc: Dict, body: Dict,
                     used: Set[str]) -> List[Dict]:
    """Hoist invariant pure ops from one body; returns the new outer
    nodes (placed before ``node``). Mutates node inputs / body nodes /
    body captures in place."""
    from . import graph_io

    nodes_b = body["node"]
    start = desc["start"]
    appended_from = len(body.get("captures", []))
    hoisted: List[Dict] = []
    const_copies: Dict[str, str] = {}
    changed = True
    while changed:
        changed = False
        by_name = {n["name"]: n for n in nodes_b}
        cap_outer = {}  # inner CapturedInput node name -> outer input ref
        for i, cap in enumerate(body.get("captures", [])):
            idx = start + i
            if idx < len(node.get("input", [])):
                cap_outer[_tensor_ref(cap[1])[0]] = node["input"][idx]
        for bn in list(nodes_b):
            if bn["op"] in ("CapturedInput", "FuncArg", "Const"):
                continue
            if not _is_pure(bn) or bn.get("control_input"):
                continue
            specs = bn.get("output_specs")
            if not specs or len(specs) != 1:
                continue  # CapturedInput replacement is single-output
            ins = bn.get("input", [])
            if not ins:
                continue
            invariant = True
            has_capture_dep = False
            for r in ins:
                p = by_name.get(_tensor_ref(r)[0])
                if p is None:
                    invariant = False
                    break
                if p["op"] == "CapturedInput":
                    if _tensor_ref(r)[0] not in cap_outer:
                        invariant = False  # LICM-orphan (imported body)
                        break
                    has_capture_dep = True
                elif p["op"] != "Const":
                    invariant = False
                    break
            if not invariant or not has_capture_dep:
                # all-const chains are constant folding's job, not LICM's
                continue
            outer_name = _uniq_in(
                used, node["name"] + "/licm/" + bn["name"].replace("/", "_"))
            new_inputs = []
            for r in ins:
                pn, pi = _tensor_ref(r)
                p = by_name[pn]
                if p["op"] == "CapturedInput":
                    new_inputs.append(cap_outer[pn])
                else:  # Const: copy into the outer graph once per body
                    cn = const_copies.get(pn)
                    if cn is None:
                        cn = _uniq_in(used, node["name"] + "/licm/"
                                      + pn.replace("/", "_"))
                        cc = copy.deepcopy(p)
                        cc["name"] = cn
                        hoisted.append(cc)
                        const_copies[pn] = cn
                    new_inputs.append(f"{cn}:{pi}")
            hn = copy.deepcopy(bn)
            hn["name"] = outer_name
            hn["input"] = new_inputs
            hn["control_input"] = []
            hoisted.append(hn)
            # the body-side residue: a CapturedInput bound to the hoisted
            # op, keeping bn's NAME so body refs need no rewriting —
            # consumers of bn become hoist candidates on the next sweep
            sh, dt = specs[0]
            nodes_b[nodes_b.index(bn)] = {
                "name": bn["name"], "op": "CapturedInput", "input": [],
                "control_input": [], "device": bn.get("device", ""),
                "attr": {"dtype": graph_io._encode_attr(
                             dtypes_mod.as_dtype(dt)),
                         "shape": graph_io._encode_attr(
                             shape_mod.TensorShape(sh))},
                "output_specs": [[sh, dt]],
            }
            body.setdefault("captures", []).append(
                [f"{outer_name}:0", f"{bn['name']}:0"])
            node["input"].insert(start + len(body["captures"]) - 1,
                                 f"{outer_name}:0")
            if desc.get("count_attr"):
                node["attr"][desc["count_attr"]] = \
                    int(node["attr"][desc["count_attr"]]) + 1
            changed = True
    # Hoisting a k-op chain leaves k-1 intermediate CapturedInput
    # residues whose only consumer hoisted out on the next sweep — dead,
    # but body-signature DCE protects every CapturedInput. These were
    # appended by THIS call (never part of the original signature), so
    # drop the unconsumed ones along with their capture entry and the
    # matching op input; the orphaned outer intermediates fall to the
    # pipeline's final DCE.
    for i in range(len(body.get("captures", [])) - 1, appended_from - 1,
                   -1):
        inner_nm = _tensor_ref(body["captures"][i][1])[0]
        consumed = any(
            _tensor_ref(r)[0] == inner_nm
            for n2 in nodes_b for r in n2.get("input", [])) or any(
            _tensor_ref(r)[0] == inner_nm for r in body.get("outputs", []))
        if consumed:
            continue
        del body["captures"][i]
        del node["input"][start + i]
        body["node"] = nodes_b = [n2 for n2 in nodes_b
                                  if n2["name"] != inner_nm]
        if desc.get("count_attr"):
            node["attr"][desc["count_attr"]] = \
                int(node["attr"][desc["count_attr"]]) - 1
    return hoisted


# ---------------------------------------------------------------------------
# loop-carried layout push (the while-specific half of layout
# optimization: ref grappler layout_optimizer + loop_optimizer interplay)
# ---------------------------------------------------------------------------

def push_loop_layout(graph_def: Dict,
                     keep: Optional[List[str]] = None) -> Dict:
    """Push the boundary layout conversions of a layout-optimized while
    body ACROSS the loop. Sound only when the layout is invariant across
    an iteration — i.e. the body maps NHWC→NHWC for that loop var —
    which is verified structurally: the var must enter the body only
    through NCHW→NHWC transposes and exit through an NHWC→NCHW
    transpose (the shims layout_optimization leaves). Such a var is
    re-carried in NHWC: zero transposes execute per iteration; one
    conversion pair runs once, outside the loop. The While op keeps its
    name, arity, and dtypes (shapes permute); external consumers are
    rewired through a restoring transpose, so a While named in ``keep``
    (fetched by name) is skipped entirely."""
    out = copy.deepcopy(graph_def)
    keep_names = {_tensor_ref(k)[0] for k in (keep or [])}
    used = {n["name"] for n in out["node"]}
    rewire: Dict[str, str] = {}
    shim_names: Set[str] = set()
    new_nodes: List[Dict] = []
    for node in out["node"]:
        if rewire and node["name"] not in shim_names:
            node["input"] = [rewire.get(r, r)
                             for r in node.get("input", [])]
        for d, b in _node_bodies(node):
            _set_body(node, d, push_loop_layout(b, keep=_body_keep(b)), b)
        if node["op"] == "While" and node["name"] not in keep_names:
            pre, post = _push_while_vars(node, used, rewire, shim_names)
            new_nodes.extend(pre)
            new_nodes.append(node)
            new_nodes.extend(post)
        else:
            new_nodes.append(node)
    out["node"] = new_nodes
    return out


def _push_while_vars(node: Dict, used: Set[str], rewire: Dict[str, str],
                     shim_names: Set[str]) -> Tuple[List[Dict], List[Dict]]:
    from . import graph_io

    enc = graph_io._encode_attr
    dec = graph_io._decode_attr

    bodies = {d["attr"]: b for d, b in _node_bodies(node)}
    body = bodies.get("body_graph")
    cond = bodies.get("cond_graph")
    if body is None or cond is None:
        return [], []
    n_vars = int(node["attr"].get("n_vars", 0))
    by_name = {n["name"]: n for n in body["node"]}

    def _perm(nd):
        p = dec(nd.get("attr", {}).get("perm"))
        return tuple(p) if p is not None else ()

    def _perm_shape(sh):
        return [sh[i] for i in _NCHW_TO_NHWC] if isinstance(sh, list) \
            and len(sh) == 4 else sh

    pre: List[Dict] = []
    post: List[Dict] = []
    for i in range(min(n_vars, len(body.get("outputs", [])),
                       len(body.get("inputs", [])))):
        onm, oi = _tensor_ref(body["outputs"][i])
        t_out = by_name.get(onm)
        if (t_out is None or t_out["op"] != "Transpose" or oi != 0
                or _perm(t_out) != _NHWC_TO_NCHW):
            continue
        arg_ref = body["inputs"][i]
        anm = _tensor_ref(arg_ref)[0]
        arg_node = by_name.get(anm)
        if arg_node is None or arg_node["op"] != "FuncArg":
            continue
        if any(_tensor_ref(r)[0] == anm for r in body["outputs"]):
            continue  # var also passed through unconverted
        consumers = [n2 for n2 in body["node"]
                     if any(r == arg_ref for r in n2.get("input", []))]
        if not consumers or any(
                n2["op"] != "Transpose" or _perm(n2) != _NCHW_TO_NHWC
                or len(n2.get("input", [])) != 1 for n2 in consumers):
            continue  # body does NOT map this var NHWC→NHWC: unsound
        spec = arg_node.get("output_specs")
        if (not spec or not isinstance(spec[0][0], list)
                or len(spec[0][0]) != 4):
            continue
        # ---- the var provably carries NHWC-invariant layout: flip it --
        dt = spec[0][1]
        nhwc_shape = _perm_shape(spec[0][0])
        arg_node["output_specs"] = [[nhwc_shape, dt]]
        arg_node.setdefault("attr", {})["shape"] = enc(
            shape_mod.TensorShape(nhwc_shape))
        # entry: consumers read the NHWC arg directly
        dead = {n2["name"] for n2 in consumers}
        for n2 in body["node"]:
            n2["input"] = [arg_ref if _tensor_ref(r)[0] in dead else r
                           for r in n2.get("input", [])]
        body["outputs"] = [arg_ref if _tensor_ref(r)[0] in dead else r
                           for r in body["outputs"]]
        body["node"] = [n2 for n2 in body["node"]
                        if n2["name"] not in dead]
        # exit: emit the NHWC value; the old shim stays only if consumed
        body["outputs"][i] = t_out["input"][0]
        # cond graph sees the var NHWC; restore NCHW for its uses
        c_ref = cond["inputs"][i]
        cnm = _tensor_ref(c_ref)[0]
        c_by_name = {n2["name"]: n2 for n2 in cond["node"]}
        c_arg = c_by_name.get(cnm)
        if c_arg is not None:
            c_spec = c_arg.get("output_specs")
            if c_spec:
                c_arg["output_specs"] = [[_perm_shape(c_spec[0][0]),
                                          c_spec[0][1]]]
            c_arg.setdefault("attr", {})["shape"] = enc(
                shape_mod.TensorShape(nhwc_shape))
            c_users = [n2 for n2 in cond["node"]
                       if any(r == c_ref for r in n2.get("input", []))]
            if c_users:
                tc_name = _uniq_in({n2["name"] for n2 in cond["node"]},
                                   cnm + "/to_nchw")
                tc = {"name": tc_name, "op": "Transpose",
                      "input": [c_ref], "control_input": [],
                      "device": c_arg.get("device", ""),
                      "attr": {"perm": enc(_NHWC_TO_NCHW)},
                      "output_specs": [[spec[0][0], dt]]}
                for n2 in c_users:
                    n2["input"] = [tc_name + ":0" if r == c_ref else r
                                   for r in n2.get("input", [])]
                cond["node"].insert(
                    cond["node"].index(c_arg) + 1, tc)
        # outer: convert the init value in, restore for consumers
        tin_name = _uniq_in(used, f"{node['name']}/v{i}_to_nhwc")
        pre.append({"name": tin_name, "op": "Transpose",
                    "input": [node["input"][i]], "control_input": [],
                    "device": node.get("device", ""),
                    "attr": {"perm": enc(_NCHW_TO_NHWC)},
                    "output_specs": [[nhwc_shape, dt]]})
        node["input"][i] = tin_name + ":0"
        old_spec_i = node["output_specs"][i]
        node["output_specs"][i] = [_perm_shape(old_spec_i[0]),
                                   old_spec_i[1]]
        tb_name = _uniq_in(used, f"{node['name']}/v{i}_to_nchw")
        post.append({"name": tb_name, "op": "Transpose",
                     "input": [f"{node['name']}:{i}"],
                     "control_input": [], "device": node.get("device", ""),
                     "attr": {"perm": enc(_NHWC_TO_NCHW)},
                     "output_specs": [old_spec_i]})
        shim_names.add(tb_name)
        rewire[f"{node['name']}:{i}"] = tb_name + ":0"
    return pre, post


# ---------------------------------------------------------------------------
# the PassManager
# ---------------------------------------------------------------------------

class GraphPass:
    """One named GraphDef rewrite. ``fn(graph_def, keep) -> graph_def``;
    every built-in pass is function-aware (recurses into FuncGraph
    bodies itself). ``signature_safe`` marks passes that never change a
    body's captures or an op's input arity — the only ones
    ``optimize_graph_functions`` may run on live graphs."""

    def __init__(self, name: str, fn: Callable, signature_safe: bool = True):
        self.name = name
        self.fn = fn
        self.signature_safe = signature_safe

    def run(self, graph_def: Dict, keep: List[str]) -> Dict:
        return self.fn(graph_def, keep)

    def __repr__(self):
        return f"<GraphPass {self.name}>"


LAYOUT_PASS = GraphPass(
    "layout", lambda gd, keep: layout_optimization(gd, keep=keep))
PUSH_LOOP_LAYOUT_PASS = GraphPass(
    "push_loop_layout", push_loop_layout, signature_safe=False)
FOLD_PASS = GraphPass("fold", lambda gd, keep: constant_folding(gd))
LICM_PASS = GraphPass("licm", loop_invariant_code_motion,
                      signature_safe=False)
CSE_PASS = GraphPass(
    "cse", lambda gd, keep: common_subexpression_elimination(gd, keep=keep))
DCE_PASS = GraphPass(
    "dce", lambda gd, keep: dead_code_elimination(gd, keep) if keep else gd)


def default_passes(layout: bool = True,
                   signature_safe_only: bool = False) -> List[GraphPass]:
    passes = []
    if layout:
        passes.append(LAYOUT_PASS)
        if not signature_safe_only:
            passes.append(PUSH_LOOP_LAYOUT_PASS)
    passes.append(FOLD_PASS)
    if not signature_safe_only:
        passes.append(LICM_PASS)
    passes += [CSE_PASS, DCE_PASS]
    return passes


class PassManager:
    """Unified driver for the GraphDef-level passes (the grappler
    meta_optimizer slot). Every registered pass is function-aware: it
    recurses into the FuncGraph bodies declared via
    ``register_function_op`` (cond branches, while cond/body, scan/map
    fns, defun bodies), preserving each body's signature so Session
    executable-cache keys and the lowering stay valid.

    ``verify``: run the stf.analysis GraphDef verifier as a pre/post
    invariant around every pass — a pass that *introduces* a structural
    error (dangling ref, broken body signature, cycle) raises
    InternalError naming the pass, instead of the error surfacing later
    as an opaque import/lowering failure. Pre-existing errors in the
    input graph are attributed to the input, not to a pass. Default
    from env ``STF_VERIFY_PASSES`` (off unless "1": verification is
    O(graph) per pass, the optimizer hot path is per-plan)."""

    def __init__(self, passes: Optional[List[GraphPass]] = None,
                 verify: Optional[bool] = None):
        self.passes = list(passes if passes is not None
                           else default_passes())
        if verify is None:
            import os

            verify = os.environ.get("STF_VERIFY_PASSES", "0") == "1"
        self.verify = bool(verify)

    @staticmethod
    def _error_keys(gd: Dict) -> set:
        from ..analysis import verifier as verifier_mod

        return {(d.code, d.op_name)
                for d in verifier_mod.verify_graphdef(gd) if d.is_error}

    def run(self, graph_def: Dict, keep: Optional[List[str]] = None) -> Dict:
        gd = graph_def
        baseline = self._error_keys(gd) if self.verify else None
        for p in self.passes:
            t0 = time.perf_counter()
            with monitoring.traceme(f"graph_pass:{p.name}",
                                    n_nodes=len(gd.get("node", ()))):
                new = p.run(gd, list(keep or []))
            _metric_pass_seconds.get_cell(p.name).add(
                time.perf_counter() - t0)
            _metric_pass_runs.get_cell(p.name).increase_by(1)
            # rewrite detection is a deep dict compare — O(graph bytes),
            # paid once per (fetches, feeds) plan; identical-object
            # returns skip it
            if new is not gd and new != gd:
                _metric_pass_rewrites.get_cell(p.name).increase_by(1)
                if baseline is not None:
                    introduced = self._error_keys(new) - baseline
                    if introduced:
                        from .errors import InternalError

                        detail = "; ".join(
                            f"{code} at {name}" for code, name
                            in sorted(introduced,
                                      key=lambda k: (k[0], str(k[1]))))
                        raise InternalError(
                            None, None,
                            f"graph pass {p.name!r} broke the graph: "
                            f"{detail} (pre/post invariant check, "
                            "stf.analysis.verify_graphdef)")
            gd = new
        return gd


def optimize(graph_def: Dict, keep: Optional[List[str]] = None,
             layout: bool = True) -> Dict:
    """grappler-equivalent pipeline:
    layout -> push_loop_layout -> fold -> licm -> CSE -> DCE,
    each pass recursing into cond/while/scan/defun bodies."""
    return PassManager(default_passes(layout=layout)).run(graph_def,
                                                          keep=keep)


def optimize_graph_functions(graph, layout: bool = True,
                             passes: Optional[List[GraphPass]] = None) -> int:
    """Rewrite the FuncGraph bodies of a LIVE graph in place.

    Runs the signature-safe pipeline (layout / fold / CSE / DCE — no
    LICM or loop push: a live op's input tuple is immutable, so captures
    must stay put) on each body, rebuilds it, and swaps it into the op's
    attr. Outputs/arity/dtypes/captures are preserved, so every existing
    by-name and positional reference stays valid. Bumps the graph's
    rewrite version so Session executable caches keyed on it invalidate
    and the next run() re-plans against the rewritten bodies. Returns
    the number of bodies rewritten."""
    from . import graph as ops_mod
    from . import graph_io

    if passes is None:
        passes = default_passes(layout=layout, signature_safe_only=True)
    if any(not p.signature_safe for p in passes):
        raise ValueError(
            "optimize_graph_functions: only signature-safe passes may "
            "rewrite live graphs (got "
            f"{[p.name for p in passes if not p.signature_safe]})")
    pm = PassManager(passes)
    changed = 0
    for op in graph.get_operations():
        spec = _FUNCTION_OPS.get(op.type)
        if spec is None:
            continue
        try:
            descs = spec.bodies(op.attrs, len(op.inputs))
        except (KeyError, TypeError):
            continue
        for desc in descs:
            fg = op.attrs.get(desc["attr"])
            if not isinstance(fg, ops_mod.FuncGraph):
                continue
            body = graph_io._funcgraph_to_dict(fg)
            opt = pm.run(body, keep=_body_keep(body))
            if opt == body:
                continue
            if (_signature_broken(body, opt)
                    or len(opt.get("captures", []))
                    != len(fg.captures)):
                continue  # defensive: never swap in a broken body
            new_fg = graph_io.rebuild_funcgraph(opt, fg.outer_graph)
            # rebind the original outer capture tensors positionally
            new_fg.captures = [
                (outer, inner2) for (outer, _), (_, inner2)
                in zip(fg.captures, new_fg.captures)]
            op.attrs[desc["attr"]] = new_fg
            changed += 1
    if changed:
        graph._rewrite_version += 1
    return changed


# ---------------------------------------------------------------------------
# IR-level passes (the Session's hot path)
# ---------------------------------------------------------------------------

_FOLD_MAX_BYTES = 1 << 20  # don't materialize folded constants above 1 MiB


def optimize_pruned(op_list, fed_tensors, keep_tensors, const_seed=None,
                    func_plans=None):
    """Fold/CSE/DCE over a pruned, topo-ordered Operation list — the pass
    Session._plan runs before lowering (ref grappler's role ahead of the
    executor; core/common_runtime/constant_folding.cc).

    Works WITHOUT mutating the graph (the IR is immutable-append):
    returns ``(new_op_list, const_env, alias)`` where
      const_env: Tensor -> np.ndarray — outputs computed at plan time;
        the Session seeds them into the lowering env, so the ops that
        produced them never trace,
      alias: Tensor -> Tensor — CSE-duplicate output -> canonical output;
        consulted at every input lookup during lowering.

    Function-aware: ops carrying FuncGraph bodies (cond/while/scan/defun)
    get each body optimized recursively at plan time — fold (seeded with
    the values of constant captures: cross-boundary constant
    propagation), CSE, and DCE run over the body's pruned op list. The
    results land in ``func_plans`` (FuncGraph -> (op_list, const_env,
    alias)), which the caller threads into the LoweringContext so
    lowering.lower_func_graph consumes them on every trace of that body.
    A duplicate subexpression inside a while/scan body therefore lowers
    ONCE per iteration instead of twice, without mutating the graph.
    Body plans belong to THIS plan, not the FuncGraph: a capture's value
    may be constant under one feed set and fed under another, so plans
    are never shared across (fetches, feeds) signatures.

    ``const_seed``: Tensor -> np value bindings known constant in this
    scope (the recursive calls pass capture constants through it).
    ``func_plans``: optional dict collecting the per-FuncGraph body
    plans (shared with recursive calls); pass it to each
    LoweringContext that will trace these ops.

    Ops are foldable/CSE-able only via ``pure_fn`` (stateless by
    construction: RNG, variables, placeholders, host IO all register with
    ``lower=`` and/or ``is_stateful`` and are excluded)."""
    import jax

    const_env: Dict[Any, Any] = dict(const_seed or {})
    alias: Dict[Any, Any] = {}
    sigs: Dict[str, Any] = {}  # signature -> canonical op
    new_list = []
    for op in op_list:
        od = op.op_def
        if op.type in _FUNCTION_OPS and func_plans is not None:
            _plan_function_bodies(op, const_env, alias, fed_tensors,
                                  func_plans)
        if op.type == "Const":
            v = op.attrs.get("value")
            if v is not None and op.outputs:
                const_env[op.outputs[0]] = np.asarray(v)
            new_list.append(op)  # kept for host-stage consumers; DCE'd below
            continue
        pure = (od.pure_fn is not None and not od.is_stateful
                and not od.runs_on_host and not op.control_inputs
                and op.type not in _FOLDABLE_BLOCKLIST)
        resolved_ins = [alias.get(t, t) for t in op.inputs]
        if (pure and op.type in _SHAPE_OPS and op.inputs
                and op.inputs[0].shape.is_fully_defined()):
            # shape materialization: static shape -> constant, no value
            # needed (grappler does the same before its folding pass);
            # out_type honored through the 64-bit narrowing so a folded
            # Shape returns the same dtype the runtime path computes
            sh = op.inputs[0].shape.as_list()
            ot = op.attrs.get("out_type")
            np_dt = (dtypes_mod.narrowed_if_no_x64(ot).np_dtype
                     if ot is not None else np.int32)
            if op.type == "Shape":
                val = np.asarray(sh, np_dt)
            elif op.type == "Size":
                val = np.asarray(int(np.prod(sh)) if sh else 1, np_dt)
            else:
                val = np.asarray(len(sh), np.int32)  # Rank: int32
            if op.outputs:
                const_env[op.outputs[0]] = val
                continue
        if pure and resolved_ins and all(t in const_env
                                         for t in resolved_ins):
            attrs = {k: v for k, v in op.attrs.items()
                     if not k.startswith("_")}
            try:
                with jax.default_device(jax.devices("cpu")[0]):
                    out = od.pure_fn(
                        *[const_env[t] for t in resolved_ins], **attrs)
            except Exception:
                out = None  # fold failure leaves the op alone
            if out is not None:
                outs = (list(out) if isinstance(out, (list, tuple))
                        else [out])
                outs = [np.asarray(o) for o in outs]
                if (len(outs) == len(op.outputs) and
                        sum(o.nbytes for o in outs) <= _FOLD_MAX_BYTES):
                    for t, v in zip(op.outputs, outs):
                        const_env[t] = v
                    continue  # folded: op never lowers
        if pure:
            sig = repr((op.type,
                        tuple(id(t) for t in resolved_ins),
                        sorted((k, repr(v)) for k, v in op.attrs.items()
                               if not k.startswith("_"))))
            canon = sigs.get(sig)
            if canon is not None:
                for dup_out, canon_out in zip(op.outputs, canon.outputs):
                    alias[dup_out] = alias.get(canon_out, canon_out)
                continue  # CSE'd: op never lowers
            sigs[sig] = op
        new_list.append(op)

    # DCE (reverse walk): effects stay; pure ops stay only if some kept op
    # or fetch consumes an output (through aliases), and folded consumers
    # are gone already.
    needed = set()
    for t in keep_tensors:
        t = alias.get(t, t)
        if t not in const_env:
            needed.add(t)
    kept_rev = []
    for op in reversed(new_list):
        od = op.op_def
        effectful = od.is_stateful or od.runs_on_host or not op.outputs
        wanted = effectful or any(o in needed for o in op.outputs)
        if not wanted:
            continue
        kept_rev.append(op)
        for t in op.inputs:
            t = alias.get(t, t)
            if t not in const_env and t not in fed_tensors:
                needed.add(t)
        for c in op.control_inputs:
            # output-less control deps are effectful and kept by the rule
            # above; tensor-producing ones are kept via their outputs
            needed.update(c.outputs)
    return list(reversed(kept_rev)), const_env, alias


def _plan_function_bodies(op, const_env, alias, fed_tensors, func_plans):
    """Optimize the FuncGraph bodies of one op at plan time, recording
    each result in ``func_plans`` as fg -> (op_list, const_env, alias)
    (consumed by lowering.lower_func_graph through the
    LoweringContext). Seeds the body fold with captures whose outer
    producer is a plan-time constant AND not fed in this plan — sound
    because captures are invariant across iterations/branches, and a
    fed tensor (even a fed Const: feeding overrides any node) must
    never be baked in. Defensive: a failure here must never break the
    session plan."""
    spec = _FUNCTION_OPS.get(op.type)
    if spec is None:
        return
    try:
        descs = spec.bodies(op.attrs, len(op.inputs))
    except (KeyError, TypeError):
        return
    from . import lowering as lowering_mod

    for d in descs:
        fg = op.attrs.get(d["attr"])
        if fg is None or not hasattr(fg, "captures"):
            continue
        if fg in func_plans:
            continue
        seeds: Dict[Any, Any] = {}
        for outer, inner in fg.captures:
            if outer is None:
                continue  # imported body: outer refs re-bound by caller
            r = alias.get(outer, outer)
            if outer in fed_tensors or r in fed_tensors:
                continue  # fed value wins over any graph constant
            if r in const_env:
                seeds[inner] = const_env[r]
            elif r.op.type == "Const":
                v = r.op.attrs.get("value")
                if v is not None:
                    seeds[inner] = np.asarray(v)
        fed = set(fg.inputs) | {inner for _, inner in fg.captures}
        try:
            plan = lowering_mod.prune([t.op for t in fg.outputs], fed)
            body_plan = optimize_pruned(plan, fed, list(fg.outputs),
                                        const_seed=seeds,
                                        func_plans=func_plans)
        except Exception:
            continue
        func_plans[fg] = body_plan
