"""Graph optimizer passes: constant folding, CSE, DCE
(ref: tensorflow/core/common_runtime/constant_folding.cc,
core/graph/optimizer_cse.cc, core/grappler/).

On TPU most of this work belongs to XLA — the whole pruned subgraph
compiles as one program and XLA constant-folds/CSEs/fuses HLO. These
passes run *before tracing* on the GraphDef level, where they still pay:
- smaller graphs trace faster (Session compile latency),
- exported GraphDefs / SavedModels shrink,
- AOT keys stabilize (CSE canonicalizes).
They operate on the GraphDef-JSON dict (framework/graph_io.py), returning
a new dict — the Graph IR itself is immutable-append by design.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from . import dtypes as dtypes_mod
from . import op_registry

_FOLDABLE_BLOCKLIST = {"Placeholder", "PlaceholderWithDefault", "Const",
                       "VariableV2", "VarRead", "Assign"}


def _tensor_ref(name: str) -> Tuple[str, int]:
    if ":" in name:
        node, idx = name.rsplit(":", 1)
        return node, int(idx)
    return name, 0


def _is_pure(node) -> bool:
    try:
        od = op_registry.get(node["op"])
    except KeyError:
        return False
    return od.pure_fn is not None and not od.is_stateful


def dead_code_elimination(graph_def: Dict, keep: List[str]) -> Dict:
    """Drop nodes not reachable (as dependencies) from ``keep`` node/tensor
    names (ref: core/graph/algorithm.cc PruneForReverseReachability)."""
    nodes = {n["name"]: n for n in graph_def["node"]}
    work = [_tensor_ref(k)[0] for k in keep]
    live: Set[str] = set()
    while work:
        name = work.pop()
        if name in live or name not in nodes:
            continue
        live.add(name)
        n = nodes[name]
        work.extend(_tensor_ref(i)[0] for i in n.get("input", []))
        work.extend(n.get("control_input", []))
    out = copy.deepcopy(graph_def)
    out["node"] = [n for n in graph_def["node"] if n["name"] in live]
    return out


def common_subexpression_elimination(graph_def: Dict,
                                     keep: Optional[List[str]] = None) -> Dict:
    """Merge pure nodes with identical (op, inputs, attrs)
    (ref: core/graph/optimizer_cse.cc). Nodes named in ``keep`` are never
    merged away — callers fetch them by name after import."""
    keep_names: Set[str] = {_tensor_ref(k)[0] for k in (keep or [])}
    out = copy.deepcopy(graph_def)
    replace: Dict[str, str] = {}  # old node name -> canonical node name
    seen: Dict[str, str] = {}  # signature -> canonical name
    kept = []
    for n in out["node"]:
        # rewrite inputs through earlier merges first
        n["input"] = [_rewrite(i, replace) for i in n.get("input", [])]
        n["control_input"] = [replace.get(c, c)
                              for c in n.get("control_input", [])]
        if not _is_pure(n) or n.get("control_input"):
            kept.append(n)
            continue
        sig = repr((n["op"], n["input"],
                    sorted((k, repr(v)) for k, v in
                           n.get("attr", {}).items())))
        if sig in seen and n["name"] not in keep_names:
            replace[n["name"]] = seen[sig]
        else:
            if sig not in seen:
                seen[sig] = n["name"]
            kept.append(n)
    out["node"] = kept
    return out


def _rewrite(tensor_name: str, replace: Dict[str, str]) -> str:
    node, idx = _tensor_ref(tensor_name)
    if node in replace:
        return f"{replace[node]}:{idx}"
    return tensor_name


_SHAPE_OPS = {"Shape", "Size", "Rank"}


def constant_folding(graph_def: Dict) -> Dict:
    """Evaluate pure nodes whose inputs are all Consts, replacing them with
    Const nodes (ref: core/common_runtime/constant_folding.cc). Uses each
    op's registered jax pure_fn on host numpy values — the same semantics
    the compiled program would have. Shape/Size/Rank of statically-shaped
    producers fold from the shape alone (grappler's
    shape-materialization), without needing a constant input value."""
    import jax

    from . import graph_io

    out = copy.deepcopy(graph_def)
    values: Dict[str, List[Any]] = {}  # node name -> output values
    specs_by_name: Dict[str, Any] = {n["name"]: n.get("output_specs")
                                     for n in out["node"]}
    for n in out["node"]:
        if n["op"] == "Const":
            v = graph_io._decode_attr(n.get("attr", {}).get("value"))
            if v is not None:
                values[n["name"]] = [np.asarray(v)]
    new_nodes = []
    for n in out["node"]:
        name = n["name"]
        if n["op"] == "Const" or not _is_pure(n) or n.get("control_input"):
            new_nodes.append(n)
            continue
        if n["op"] in _SHAPE_OPS and n.get("input"):
            src, idx = _tensor_ref(n["input"][0])
            specs = specs_by_name.get(src)
            sh = (specs[idx][0] if specs and idx < len(specs) else None)
            if isinstance(sh, list) and all(
                    isinstance(d, int) for d in sh):
                from . import graph_io

                ot = graph_io._decode_attr(
                    n.get("attr", {}).get("out_type"))
                np_dt = (dtypes_mod.as_dtype(ot).np_dtype
                         if ot is not None else np.int32)
                if n["op"] == "Shape":
                    arr = np.asarray(sh, np_dt)
                elif n["op"] == "Size":
                    arr = np.asarray(int(np.prod(sh)) if sh else 1,
                                     np_dt)
                else:
                    arr = np.asarray(len(sh), np.int32)  # Rank: int32
                values[name] = [arr]
                new_nodes.append({
                    "name": name, "op": "Const", "input": [],
                    "control_input": [], "device": n.get("device", ""),
                    "attr": {"value": graph_io._encode_attr(arr),
                             "dtype": graph_io._encode_attr(
                                 dtypes_mod.as_dtype(str(arr.dtype)))},
                    "output_specs": [[list(arr.shape), str(arr.dtype)]],
                })
                continue
        in_refs = [_tensor_ref(i) for i in n.get("input", [])]
        if not in_refs or not all(r[0] in values for r in in_refs):
            new_nodes.append(n)
            continue
        od = op_registry.get(n["op"])
        attrs = {k: graph_io._decode_attr(v)
                 for k, v in n.get("attr", {}).items()
                 if not k.startswith("_") and k != "dtype"}
        try:
            with jax.default_device(jax.devices("cpu")[0]):
                result = od.pure_fn(
                    *[values[r[0]][r[1]] for r in in_refs], **attrs)
        except Exception:
            new_nodes.append(n)  # fold failure leaves the node alone
            continue
        outs = (list(result) if isinstance(result, (list, tuple))
                else [result])
        outs = [np.asarray(o) for o in outs]
        values[name] = outs
        if len(outs) == 1:  # replace with a Const node
            spec = n.get("output_specs") or [[list(outs[0].shape),
                                              str(outs[0].dtype)]]
            folded = {
                "name": name, "op": "Const", "input": [],
                "control_input": [], "device": n.get("device", ""),
                "attr": {"value": graph_io._encode_attr(outs[0]),
                         "dtype": graph_io._encode_attr(
                             dtypes_mod.as_dtype(spec[0][1]))},
                "output_specs": spec,
            }
            new_nodes.append(folded)
        else:
            new_nodes.append(n)
    out["node"] = new_nodes
    return out


# ---------------------------------------------------------------------------
# layout optimization (ref: core/grappler/optimizers/layout_optimizer.cc)
# ---------------------------------------------------------------------------

_NCHW_TO_NHWC = (0, 2, 3, 1)
_NHWC_TO_NCHW = (0, 3, 1, 2)

# image ops that carry a data_format attr; "vec" attrs are per-dimension
# 4-vectors (strides/ksize/dilations) permuted along with the layout
_LAYOUT_OPS = {
    "Conv2D": ("strides", "dilations"),
    "DepthwiseConv2dNative": ("strides", "dilations"),
    "MaxPool": ("strides", "ksize"),
    "AvgPool": ("strides", "ksize"),
    "FusedBatchNorm": (),
    "BiasAdd": (),
}

# rank-preserving elementwise ops a transpose can move through unchanged
_LAYOUT_AGNOSTIC = {
    "Relu", "Relu6", "Elu", "Selu", "LeakyRelu", "Tanh", "Sigmoid",
    "Softplus", "Abs", "Neg", "Square", "Sqrt", "Rsqrt", "Exp", "Log",
    "Identity", "Add", "AddV2", "Sub", "Mul", "RealDiv", "Maximum",
    "Minimum", "SquaredDifference",
}


def _compose_perm(p2, p1):
    """perm of transpose(transpose(x, p2), p1)."""
    return tuple(p2[i] for i in p1)


def layout_optimization(graph_def: Dict,
                        keep: Optional[List[str]] = None) -> Dict:
    """Rewrite NCHW image ops to NHWC globally (ref: grappler
    layout_optimizer.cc). TPU rationale: the per-op lowering honors NCHW
    by transposing around EVERY conv/pool/bn call; this pass instead
    converts the ops once and pushes the layout conversions to the
    subgraph boundary, cancelling interior transpose pairs — an NCHW
    ResNet block lowers with exactly two transposes (one in, one out).

    Three phases: (1) convert each NCHW op to NHWC with explicit
    boundary transposes; (2) push NHWC→NCHW transposes down through
    rank-preserving elementwise ops (so pairs become adjacent);
    (3) cancel adjacent inverse pairs, then DCE.
    Touched nodes drop their output_specs — the importer's shape
    inference recomputes them in the new layout.
    """
    from . import graph_io

    out = copy.deepcopy(graph_def)
    nodes: List[Dict] = out["node"]
    by_name = {n["name"]: n for n in nodes}

    def _uniq(base):
        name = base
        k = 1
        while name in by_name:
            name = f"{base}_{k}"
            k += 1
        return name

    def _attr(n, key, default=None):
        v = n.get("attr", {}).get(key)
        return default if v is None else graph_io._decode_attr(v)

    def _perm_of(n):
        p = _attr(n, "perm")
        return tuple(p) if p is not None else ()

    enc = graph_io._encode_attr

    # ---- phase 1: per-op conversion (in topo order, so a converted
    # producer's boundary transpose is visible to later converts).
    # NAME SWAP: the converted op is renamed "<name>/nhwc" and the
    # inverse output transpose takes the ORIGINAL name, so every
    # existing reference — graph edges AND by-name fetches — still sees
    # NCHW data without any rewiring. Extra outputs (FusedBatchNorm's
    # per-channel mean/var) are layout-free and rewired to the renamed
    # node directly — but only graph-INTERNAL edges can be rewired, so a
    # multi-output op whose name appears in ``keep`` (externally visible
    # ":k" refs) is left unconverted.
    keep_names = {_tensor_ref(k)[0] for k in (keep or [])}
    new_nodes: List[Dict] = []
    rewire: Dict[str, str] = {}  # "orig:k" (k>0) -> "<orig>/nhwc:k"
    converted = []
    for n in nodes:
        if n["op"] not in _LAYOUT_OPS or _attr(n, "data_format") != "NCHW":
            new_nodes.append(n)
            continue
        if len(n.get("output_specs") or []) > 1 and n["name"] in keep_names:
            # a by-name fetch may reference output k>0, which the
            # single-output transpose shim cannot serve
            new_nodes.append(n)
            continue
        orig = n["name"]
        vec_attrs = _LAYOUT_OPS[n["op"]]
        n["attr"]["data_format"] = "NHWC"
        for va in vec_attrs:
            v = _attr(n, va)
            if isinstance(v, (list, tuple)) and len(v) == 4:
                n["attr"][va] = enc(tuple((v[0], v[2], v[3], v[1])))
        n_specs = len(n.get("output_specs") or [])
        n.pop("output_specs", None)
        del by_name[orig]
        n["name"] = _uniq(orig + "/nhwc")
        by_name[n["name"]] = n
        for k in range(1, n_specs):
            rewire[f"{orig}:{k}"] = f"{n['name']}:{k}"
        # transpose the data input (input 0 for every op here); chained
        # converted producers resolve automatically: their original name
        # now names their inverse transpose
        t_in = {
            "name": _uniq(orig + "/nchw_to_nhwc"),
            "op": "Transpose", "input": [n["input"][0]],
            "control_input": [], "device": n.get("device", ""),
            "attr": {"perm": enc(_NCHW_TO_NHWC)},
        }
        by_name[t_in["name"]] = t_in
        new_nodes.append(t_in)
        n["input"] = [t_in["name"] + ":0"] + list(n["input"][1:])
        new_nodes.append(n)
        # inverse transpose under the ORIGINAL name serves consumers
        t_out = {
            "name": orig,
            "op": "Transpose", "input": [n["name"] + ":0"],
            "control_input": [], "device": n.get("device", ""),
            "attr": {"perm": enc(_NHWC_TO_NCHW)},
        }
        by_name[orig] = t_out
        new_nodes.append(t_out)
        converted.append(orig)
    if rewire:
        conv_set = set(converted)
        for n in new_nodes:
            if n["name"] in conv_set:  # the t_out shims keep ":0" inputs
                continue
            n["input"] = [rewire.get(i, i) for i in n.get("input", [])]
    nodes = new_nodes
    by_name = {n["name"]: n for n in nodes}

    # ---- phase 2: push NHWC->NCHW transposes through elementwise ----
    def _is_inv_transpose(ref):
        node, idx = _tensor_ref(ref)
        m = by_name.get(node)
        return (m is not None and m["op"] == "Transpose" and idx == 0
                and _perm_of(m) == _NHWC_TO_NCHW)

    def _rank4_ref(ref):
        """Producer output spec says rank 4 (safe to forward-transpose)."""
        node, idx = _tensor_ref(ref)
        m = by_name.get(node)
        specs = (m or {}).get("output_specs")
        if not specs or idx >= len(specs):
            return False
        sh = specs[idx][0]
        return isinstance(sh, list) and len(sh) == 4

    changed = True
    while changed:
        changed = False
        addenda = []
        for n in nodes:
            if n["op"] not in _LAYOUT_AGNOSTIC or n.get("control_input"):
                continue
            ins = n.get("input", [])
            # every input must be pushable: already NHWC behind an inverse
            # transpose, or a rank-4 tensor we can forward-transpose here
            # (identity shortcuts: Add(bn_out, x) — the x transpose then
            # CSEs with the first conv's input transpose). Same-rank
            # inputs only: broadcasting scalars would change meaning.
            if not ins or not any(_is_inv_transpose(i) for i in ins):
                continue
            if not all(_is_inv_transpose(i) or _rank4_ref(i)
                       for i in ins):
                continue
            if any(k in n.get("attr", {}) for k in ("data_format",)):
                continue
            # consume the transposes' NHWC inputs directly; forward-
            # transpose the NCHW stragglers
            new_ins = []
            for i in ins:
                if _is_inv_transpose(i):
                    new_ins.append(by_name[_tensor_ref(i)[0]]["input"][0])
                else:
                    t_f = {
                        "name": _uniq(_tensor_ref(i)[0] +
                                      "/nchw_to_nhwc"),
                        "op": "Transpose", "input": [i],
                        "control_input": [],
                        "device": n.get("device", ""),
                        "attr": {"perm": enc(_NCHW_TO_NHWC)},
                    }
                    by_name[t_f["name"]] = t_f
                    addenda.append((_tensor_ref(i)[0], t_f))
                    new_ins.append(t_f["name"] + ":0")
            n["input"] = new_ins
            n.pop("output_specs", None)
            # name swap (as in phase 1): this op becomes "<name>/nhwc",
            # an inverse transpose under the ORIGINAL name serves every
            # existing reference unchanged
            orig = n["name"]
            del by_name[orig]
            n["name"] = _uniq(orig + "/nhwc")
            by_name[n["name"]] = n
            t_out = {
                "name": orig,
                "op": "Transpose", "input": [n["name"] + ":0"],
                "control_input": [], "device": n.get("device", ""),
                "attr": {"perm": enc(_NHWC_TO_NCHW)},
            }
            by_name[orig] = t_out
            addenda.append((n["name"], t_out))
            changed = True
        # splice each new transpose right after its producer
        for prod_name, t_out in addenda:
            idx = next(i for i, m in enumerate(nodes)
                       if m["name"] == prod_name)
            nodes.insert(idx + 1, t_out)

    # ---- phase 3: cancel adjacent inverse pairs ---------------------
    alias: Dict[str, str] = {}
    for n in nodes:
        n["input"] = [alias.get(i, i) for i in n.get("input", [])]
        if n["op"] != "Transpose":
            continue
        p1 = _perm_of(n)
        src_name, src_idx = _tensor_ref(n["input"][0])
        src = by_name.get(src_name)
        if (src is not None and src["op"] == "Transpose" and src_idx == 0):
            p2 = _perm_of(src)
            if len(p1) == len(p2) and \
                    _compose_perm(p2, p1) == tuple(range(len(p1))):
                alias[n["name"] + ":0"] = src["input"][0]
    for n in nodes:
        n["input"] = [alias.get(i, i) for i in n.get("input", [])]

    out["node"] = nodes
    if keep:
        out = dead_code_elimination(out, keep)
    return out


def optimize(graph_def: Dict, keep: Optional[List[str]] = None,
             layout: bool = True) -> Dict:
    """grappler-equivalent pipeline: layout -> fold -> CSE -> DCE."""
    gd = layout_optimization(graph_def, keep=keep) if layout else graph_def
    gd = constant_folding(gd)
    gd = common_subexpression_elimination(gd, keep=keep)
    if keep:
        gd = dead_code_elimination(gd, keep)
    return gd


# ---------------------------------------------------------------------------
# IR-level passes (the Session's hot path)
# ---------------------------------------------------------------------------

_FOLD_MAX_BYTES = 1 << 20  # don't materialize folded constants above 1 MiB


def optimize_pruned(op_list, fed_tensors, keep_tensors):
    """Fold/CSE/DCE over a pruned, topo-ordered Operation list — the pass
    Session._plan runs before lowering (ref grappler's role ahead of the
    executor; core/common_runtime/constant_folding.cc).

    Works WITHOUT mutating the graph (the IR is immutable-append):
    returns ``(new_op_list, const_env, alias)`` where
      const_env: Tensor -> np.ndarray — outputs computed at plan time;
        the Session seeds them into the lowering env, so the ops that
        produced them never trace,
      alias: Tensor -> Tensor — CSE-duplicate output -> canonical output;
        consulted at every input lookup during lowering.

    Ops are foldable/CSE-able only via ``pure_fn`` (stateless by
    construction: RNG, variables, placeholders, host IO all register with
    ``lower=`` and/or ``is_stateful`` and are excluded)."""
    import jax

    const_env: Dict[Any, Any] = {}
    alias: Dict[Any, Any] = {}
    sigs: Dict[str, Any] = {}  # signature -> canonical op
    new_list = []
    for op in op_list:
        od = op.op_def
        if op.type == "Const":
            v = op.attrs.get("value")
            if v is not None and op.outputs:
                const_env[op.outputs[0]] = np.asarray(v)
            new_list.append(op)  # kept for host-stage consumers; DCE'd below
            continue
        pure = (od.pure_fn is not None and not od.is_stateful
                and not od.runs_on_host and not op.control_inputs
                and op.type not in _FOLDABLE_BLOCKLIST)
        resolved_ins = [alias.get(t, t) for t in op.inputs]
        if (pure and op.type in _SHAPE_OPS and op.inputs
                and op.inputs[0].shape.is_fully_defined()):
            # shape materialization: static shape -> constant, no value
            # needed (grappler does the same before its folding pass);
            # out_type attr (int64 shapes under x64) must be honored
            sh = op.inputs[0].shape.as_list()
            ot = op.attrs.get("out_type")
            np_dt = (dtypes_mod.as_dtype(ot).np_dtype if ot is not None
                     else np.int32)
            if op.type == "Shape":
                val = np.asarray(sh, np_dt)
            elif op.type == "Size":
                val = np.asarray(int(np.prod(sh)) if sh else 1, np_dt)
            else:
                val = np.asarray(len(sh), np.int32)  # Rank: int32
            if op.outputs:
                const_env[op.outputs[0]] = val
                continue
        if pure and resolved_ins and all(t in const_env
                                         for t in resolved_ins):
            attrs = {k: v for k, v in op.attrs.items()
                     if not k.startswith("_")}
            try:
                with jax.default_device(jax.devices("cpu")[0]):
                    out = od.pure_fn(
                        *[const_env[t] for t in resolved_ins], **attrs)
            except Exception:
                out = None  # fold failure leaves the op alone
            if out is not None:
                outs = (list(out) if isinstance(out, (list, tuple))
                        else [out])
                outs = [np.asarray(o) for o in outs]
                if (len(outs) == len(op.outputs) and
                        sum(o.nbytes for o in outs) <= _FOLD_MAX_BYTES):
                    for t, v in zip(op.outputs, outs):
                        const_env[t] = v
                    continue  # folded: op never lowers
        if pure:
            sig = repr((op.type,
                        tuple(id(t) for t in resolved_ins),
                        sorted((k, repr(v)) for k, v in op.attrs.items()
                               if not k.startswith("_"))))
            canon = sigs.get(sig)
            if canon is not None:
                for dup_out, canon_out in zip(op.outputs, canon.outputs):
                    alias[dup_out] = alias.get(canon_out, canon_out)
                continue  # CSE'd: op never lowers
            sigs[sig] = op
        new_list.append(op)

    # DCE (reverse walk): effects stay; pure ops stay only if some kept op
    # or fetch consumes an output (through aliases), and folded consumers
    # are gone already.
    needed = set()
    for t in keep_tensors:
        t = alias.get(t, t)
        if t not in const_env:
            needed.add(t)
    kept_rev = []
    for op in reversed(new_list):
        od = op.op_def
        effectful = od.is_stateful or od.runs_on_host or not op.outputs
        wanted = effectful or any(o in needed for o in op.outputs)
        if not wanted:
            continue
        kept_rev.append(op)
        for t in op.inputs:
            t = alias.get(t, t)
            if t not in const_env and t not in fed_tensors:
                needed.add(t)
        for c in op.control_inputs:
            # output-less control deps are effectful and kept by the rule
            # above; tensor-producing ones are kept via their outputs
            needed.update(c.outputs)
    return list(reversed(kept_rev)), const_env, alias
