"""TensorShape: possibly-partial static shapes.

(ref: tensorflow/python/framework/tensor_shape.py). Semantics match the
reference: a shape is unknown rank, or a list of dimensions each of which may
be None. On TPU, *execution* always has static shapes (XLA requirement) — the
partial shapes only exist at graph-construction time; Session.run re-infers
concrete shapes from the actual feeds before compiling.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Union


class Dimension:
    """One dimension of a TensorShape; value may be None (unknown)."""

    __slots__ = ("_value",)

    def __init__(self, value):
        if isinstance(value, Dimension):
            self._value = value._value
        elif value is None:
            self._value = None
        else:
            self._value = int(value)
            if self._value < 0:
                raise ValueError(f"Dimension {self._value} must be >= 0")

    @property
    def value(self) -> Optional[int]:
        return self._value

    def is_compatible_with(self, other) -> bool:
        other = Dimension(other)
        return self._value is None or other._value is None or self._value == other._value

    def assert_is_compatible_with(self, other):
        if not self.is_compatible_with(other):
            raise ValueError(f"Dimensions {self} and {other} are not compatible")

    def merge_with(self, other) -> "Dimension":
        other = Dimension(other)
        self.assert_is_compatible_with(other)
        return Dimension(self._value if self._value is not None else other._value)

    def __eq__(self, other):
        try:
            other = Dimension(other)
        except (TypeError, ValueError):
            return NotImplemented
        if self._value is None or other._value is None:
            return None  # TF semantics: unknown == x is None
        return self._value == other._value

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else (None if eq is None else not eq)

    def __hash__(self):
        return hash(self._value)

    def __int__(self):
        if self._value is None:
            raise ValueError("Cannot convert unknown Dimension to int")
        return self._value

    def __index__(self):
        return self.__int__()

    def __repr__(self):
        return f"Dimension({self._value})"

    def __str__(self):
        return "?" if self._value is None else str(self._value)

    def _binop(self, other, fn):
        try:
            other = Dimension(other)
        except (TypeError, ValueError):
            return NotImplemented
        if self._value is None or other._value is None:
            return Dimension(None)
        return Dimension(fn(self._value, other._value))

    def __add__(self, other):
        return self._binop(other, lambda a, b: a + b)

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, lambda a, b: a - b)

    def __mul__(self, other):
        return self._binop(other, lambda a, b: a * b)

    __rmul__ = __mul__

    def __floordiv__(self, other):
        return self._binop(other, lambda a, b: a // b)

    def __mod__(self, other):
        return self._binop(other, lambda a, b: a % b)


class TensorShape:
    """Static shape of a symbolic Tensor. May have unknown rank or dims."""

    __slots__ = ("_dims",)

    def __init__(self, dims=None):
        if dims is None:
            self._dims: Optional[List[Dimension]] = None
        elif isinstance(dims, TensorShape):
            self._dims = None if dims._dims is None else list(dims._dims)
        elif isinstance(dims, (int, Dimension)):
            self._dims = [Dimension(dims)]
        else:
            self._dims = [Dimension(d) for d in dims]

    # -- introspection -------------------------------------------------------
    @property
    def rank(self) -> Optional[int]:
        return None if self._dims is None else len(self._dims)

    @property
    def ndims(self) -> Optional[int]:
        return self.rank

    @property
    def dims(self) -> Optional[List[Dimension]]:
        return self._dims

    def num_elements(self) -> Optional[int]:
        if not self.is_fully_defined():
            return None
        n = 1
        for d in self._dims:
            n *= d.value
        return n

    def is_fully_defined(self) -> bool:
        return self._dims is not None and all(d.value is not None for d in self._dims)

    def assert_is_fully_defined(self):
        if not self.is_fully_defined():
            raise ValueError(f"Shape {self} is not fully defined")

    def is_compatible_with(self, other) -> bool:
        other = as_shape(other)
        if self._dims is None or other._dims is None:
            return True
        if len(self._dims) != len(other._dims):
            return False
        return all(a.is_compatible_with(b) for a, b in zip(self._dims, other._dims))

    def assert_is_compatible_with(self, other):
        if not self.is_compatible_with(other):
            raise ValueError(f"Shapes {self} and {other} are incompatible")

    def assert_has_rank(self, rank):
        if self.rank is not None and self.rank != rank:
            raise ValueError(f"Shape {self} must have rank {rank}")

    def merge_with(self, other) -> "TensorShape":
        other = as_shape(other)
        if self._dims is None:
            return TensorShape(other)
        if other._dims is None:
            return TensorShape(self)
        self.assert_is_compatible_with(other)
        return TensorShape([a.merge_with(b) for a, b in zip(self._dims, other._dims)])

    def with_rank(self, rank) -> "TensorShape":
        if self._dims is None:
            return unknown_shape(rank)
        self.assert_has_rank(rank)
        return self

    def with_rank_at_least(self, rank) -> "TensorShape":
        if self.rank is not None and self.rank < rank:
            raise ValueError(f"Shape {self} must have rank at least {rank}")
        return self

    def concatenate(self, other) -> "TensorShape":
        other = as_shape(other)
        if self._dims is None or other._dims is None:
            return TensorShape(None)
        return TensorShape(self._dims + other._dims)

    # -- conversion ----------------------------------------------------------
    def as_list(self) -> List[Optional[int]]:
        if self._dims is None:
            raise ValueError("as_list() is not defined on an unknown TensorShape")
        return [d.value for d in self._dims]

    def as_tuple(self):
        return tuple(self.as_list())

    # -- dunder --------------------------------------------------------------
    def __len__(self):
        if self._dims is None:
            raise ValueError("Cannot take the length of shape with unknown rank")
        return len(self._dims)

    def __iter__(self):
        if self._dims is None:
            raise ValueError("Cannot iterate over shape with unknown rank")
        return iter(self._dims)

    def __getitem__(self, key):
        if self._dims is None:
            if isinstance(key, slice):
                return TensorShape(None)
            return Dimension(None)
        if isinstance(key, slice):
            return TensorShape(self._dims[key])
        return self._dims[key]

    def __bool__(self):
        return self._dims is not None

    def __eq__(self, other):
        try:
            other = as_shape(other)
        except TypeError:
            return NotImplemented
        if self._dims is None or other._dims is None:
            return self._dims is None and other._dims is None
        return [d.value for d in self._dims] == [d.value for d in other._dims]

    def __hash__(self):
        if self._dims is None:
            return hash(None)
        return hash(tuple(d.value for d in self._dims))

    def __add__(self, other):
        return self.concatenate(other)

    def __radd__(self, other):
        return as_shape(other).concatenate(self)

    def __repr__(self):
        if self._dims is None:
            return "TensorShape(None)"
        return f"TensorShape({[d.value for d in self._dims]})"

    def __str__(self):
        if self._dims is None:
            return "<unknown>"
        return "(" + ", ".join(str(d) for d in self._dims) + ")"


def as_shape(shape) -> TensorShape:
    if isinstance(shape, TensorShape):
        return shape
    return TensorShape(shape)


def unknown_shape(rank=None) -> TensorShape:
    if rank is None:
        return TensorShape(None)
    return TensorShape([None] * rank)


def scalar() -> TensorShape:
    return TensorShape([])


def vector(length) -> TensorShape:
    return TensorShape([length])


def matrix(rows, cols) -> TensorShape:
    return TensorShape([rows, cols])
