"""Op registry: op definitions, lowering rules, shape/dtype inference.

TPU-native replacement for the reference's OpDef/OpRegistry + kernel registry
(ref: tensorflow/core/framework/op.cc ``OpRegistry``,
tensorflow/core/framework/op_kernel.cc, tensorflow/core/ops/ops.pbtxt).

Key difference from the reference: an op does not register a *kernel* per
device — it registers a **lowering rule** that emits jax/lax (and hence XLA)
when the pruned subgraph is traced. Shape inference comes nearly for free:
for pure ops we run ``jax.eval_shape`` on the lowering itself, so inference
can never disagree with execution (the reference maintains ~800 separate C++
shape functions, core/framework/common_shape_fns.cc, which can drift).

Partial static shapes are inferred by a two-trial probe: unknown dims are
substituted with two different primes; output dims that differ between trials
are unknown. This is advisory only — Session.run re-lowers with the concrete
feed shapes, where everything is static (as XLA requires).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import dtypes as dtypes_mod
from . import tensor_shape as shape_mod

# Substitution primes for the two-trial partial-shape probe.
_PROBE_A = 13
_PROBE_B = 17


class Effects:
    """Declared effect set of an op type (the structured upgrade of the
    boolean ``is_stateful``; ref: the reference's auto-control-deps
    tracks per-resource reads/writes the same way,
    python/framework/auto_control_deps.py).

    reads / writes: resource *selectors*. A selector is either the name
    of a node attr holding the resource id at op-creation time (e.g.
    ``"var_name"``, ``"queue_name"`` — the resolved resource is
    ``"var_name=global_step"``), or a literal resource prefixed with
    ``=`` (e.g. ``"=filesystem"``) shared by every instance of the op.

    ``update``: how a write combines with the previous value — ``None``
    means overwrite (Assign); ``"add"``/``"sub"``/``"min"``/``"max"``/
    ``"update"`` mark read-modify-write ops. Used by the hazard detector
    to skip WAW hazards between commuting updates (two AssignAdds are
    order-independent; Assign vs AssignAdd is not).

    rng: draws from the per-step PRNG stream.
    io: observable host-side effect (files, stdout, summaries, handles).
    """

    __slots__ = ("reads", "writes", "rng", "io", "update")

    def __init__(self, reads=(), writes=(), rng=False, io=False,
                 update=None):
        self.reads = tuple(reads) if not isinstance(reads, str) else (reads,)
        self.writes = (tuple(writes) if not isinstance(writes, str)
                       else (writes,))
        self.rng = bool(rng)
        self.io = bool(io)
        self.update = update

    def __bool__(self):
        return bool(self.reads or self.writes or self.rng or self.io)

    @staticmethod
    def _resolve(selectors, op):
        out = set()
        for sel in selectors:
            if sel.startswith("="):
                out.add(sel[1:])
            else:
                v = op.attrs.get(sel)
                # missing attr -> a resource unique to this op: it can
                # never alias another op's resource (no false hazards)
                if v is None:
                    out.add(f"{sel}@{op.name}")
                elif isinstance(v, (list, tuple)):
                    # list-valued attr: one resource per element, named
                    # exactly like a scalar selector would name it — a
                    # fused op touching N variables (FusedAdamUpdate)
                    # aliases the same resources as N per-variable
                    # assigns, so hazards cross-detect
                    out.update(f"{sel}={x}" for x in v)
                else:
                    out.add(f"{sel}={v}")
        return frozenset(out)

    def resolved_reads(self, op) -> frozenset:
        return self._resolve(self.reads, op)

    def resolved_writes(self, op) -> frozenset:
        return self._resolve(self.writes, op)

    def __repr__(self):
        parts = []
        if self.reads:
            parts.append(f"reads={list(self.reads)}")
        if self.writes:
            parts.append(f"writes={list(self.writes)}"
                         + (f" ({self.update})" if self.update else ""))
        if self.rng:
            parts.append("rng")
        if self.io:
            parts.append("io")
        return "Effects(" + ", ".join(parts) + ")" if parts \
            else "Effects()"


NO_EFFECTS = Effects()


class OpDef:
    """Definition of one op type.

    Attributes:
      name: op type string (e.g. "MatMul").
      lower: fn(ctx, op, input_values) -> list of output jax values. For pure
        ops this is synthesized from ``pure_fn``.
      pure_fn: fn(*input_values, **attrs) -> value or tuple — stateless ops.
      infer_fn: optional fn(graph, attrs, input_tensors)
        -> [(TensorShape, DType)]; overrides generic inference.
      is_stateful: op has effects (variable read/write, RNG, IO); never CSE'd
        or constant-folded, always kept in topo order.
      effects: declared ``Effects`` set — the structured refinement of
        ``is_stateful`` (stf.analysis hazard detection + diagnostics).
        Passing a non-empty effects implies is_stateful. Stateful ops
        that predate the effect system get a synthesized conservative
        default (io for host ops, empty otherwise) and
        ``effects_declared`` False.
      runs_on_host: executes in the host (python) stage, not in the XLA
        program (queues, readers, py_func side).
      host_sink_pure: host op that only *observes* device values (writes
        summaries/files from them) and feeds nothing back into the step —
        safe to defer to after a fused window (loop_safety does not treat
        it as a fusion blocker the way it does host ops that feed state).
      n_outputs: static output count (or None -> from infer).
    """

    __slots__ = ("name", "lower", "pure_fn", "infer_fn", "is_stateful",
                 "runs_on_host", "n_outputs", "attr_keys_in_sig",
                 "effects", "effects_declared", "host_sink_pure")

    def __init__(self, name, lower=None, pure_fn=None, infer_fn=None,
                 is_stateful=False, runs_on_host=False, n_outputs=1,
                 effects=None, host_sink_pure=False):
        self.name = name
        self.pure_fn = pure_fn
        self.infer_fn = infer_fn
        self.effects_declared = effects is not None
        if effects is None:
            # legacy registration: synthesize the conservative reading of
            # the boolean (host statefulness is observable io; device
            # statefulness without a declaration stays opaque — the
            # hazard detector only orders *declared* resources)
            effects = (Effects(io=True) if is_stateful and runs_on_host
                       else NO_EFFECTS)
        self.effects = effects
        self.is_stateful = bool(is_stateful or effects)
        self.runs_on_host = runs_on_host
        self.host_sink_pure = bool(host_sink_pure)
        self.n_outputs = n_outputs
        if lower is None:
            if pure_fn is None:
                raise ValueError(f"Op {name}: need lower or pure_fn")
            lower = self._lower_from_pure
        self.lower = lower

    def _lower_from_pure(self, ctx, op, input_values):
        attrs = {k: v for k, v in op.attrs.items() if not k.startswith("_")}
        out = self.pure_fn(*input_values, **attrs)
        if isinstance(out, (list, tuple)):
            return list(out)
        return [out]

    # -- inference -----------------------------------------------------------
    def infer(self, graph, attrs, input_tensors) -> List[Tuple[Any, Any]]:
        if self.infer_fn is not None:
            return self.infer_fn(graph, attrs, input_tensors)
        if self.pure_fn is None:
            raise ValueError(
                f"Op {self.name} is stateful and must pass output_specs or infer_fn")
        return _generic_infer(self.pure_fn, attrs, input_tensors, self.name)


def _spec_with_subst(t, subst: int):
    """ShapeDtypeStruct for tensor t with unknown dims replaced by ``subst``."""
    import jax

    sh = t.shape
    if sh.rank is None:
        dims = (subst,)  # rank unknown: pretend 1-D; probe will mostly fail -> unknown
    else:
        dims = tuple(subst if d.value is None else d.value for d in sh.dims)
    return jax.ShapeDtypeStruct(dims, t.dtype.np_dtype)


def _generic_infer(pure_fn, attrs, input_tensors, op_name):
    import jax

    unknown_rank = any(t.shape.rank is None for t in input_tensors)
    fully = all(t.shape.is_fully_defined() for t in input_tensors)
    fn = functools.partial(pure_fn, **{k: v for k, v in attrs.items()
                                       if not k.startswith("_")})

    def run(subst):
        specs = [_spec_with_subst(t, subst) for t in input_tensors]
        return jax.eval_shape(fn, *specs)

    try:
        out_a = run(_PROBE_A)
        outs_a = out_a if isinstance(out_a, (list, tuple)) else [out_a]
        if fully and not unknown_rank:
            return [(shape_mod.TensorShape(list(o.shape)),
                     dtypes_mod.as_dtype(o.dtype)) for o in outs_a]
        out_b = run(_PROBE_B)
        outs_b = out_b if isinstance(out_b, (list, tuple)) else [out_b]
        specs = []
        for oa, ob in zip(outs_a, outs_b):
            if unknown_rank or len(oa.shape) != len(ob.shape):
                specs.append((shape_mod.TensorShape(None),
                              dtypes_mod.as_dtype(oa.dtype)))
            else:
                dims = [da if da == db else None
                        for da, db in zip(oa.shape, ob.shape)]
                specs.append((shape_mod.TensorShape(dims),
                              dtypes_mod.as_dtype(oa.dtype)))
        return specs
    except Exception:
        # Probe failed (shape-sensitive op with partial inputs): dtype from
        # attrs or inputs, shape unknown. Session re-infers concretely at run.
        dt = attrs.get("dtype")
        if dt is None and input_tensors:
            dt = input_tensors[0].dtype
        if dt is None:
            dt = dtypes_mod.float32
        return [(shape_mod.TensorShape(None), dtypes_mod.as_dtype(dt))]


_REGISTRY: Dict[str, OpDef] = {}


def register(name, lower=None, pure_fn=None, infer_fn=None, is_stateful=False,
             runs_on_host=False, n_outputs=1, effects=None,
             host_sink_pure=False):
    if name in _REGISTRY:
        raise ValueError(f"Op {name} already registered")
    od = OpDef(name, lower=lower, pure_fn=pure_fn, infer_fn=infer_fn,
               is_stateful=is_stateful, runs_on_host=runs_on_host,
               n_outputs=n_outputs, effects=effects,
               host_sink_pure=host_sink_pure)
    _REGISTRY[name] = od
    return od


def declare_effects(name, effects: Effects) -> None:
    """Attach a declared effect set to an already-registered op type —
    the upgrade path for op modules that register through shared loops
    (queues, readers) without re-plumbing every call site."""
    od = get(name)
    od.effects = effects
    od.effects_declared = True
    od.is_stateful = bool(od.is_stateful or effects)


def register_pure(name, pure_fn, **kw):
    """Register a stateless op whose lowering is a jax function of
    (*input_values, **attrs)."""
    return register(name, pure_fn=pure_fn, **kw)


def exists(name) -> bool:
    return name in _REGISTRY


def get(name) -> OpDef:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"Op type {name!r} is not registered "
                       f"({len(_REGISTRY)} ops known)")


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


def is_registered(name) -> bool:
    return name in _REGISTRY


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding)
#
# A rule is registered per op type ALONGSIDE the op definition — the same
# placement contract as abstract-eval (pure_fn/infer_fn) and effects: the
# module that knows an op's semantics declares how PartitionSpecs flow
# through it. Signature:
#
#     rule(op, in_specs, ctx) -> list of out specs (one per op output)
#
# where a spec is a tuple with one entry per dim, each entry a tuple of
# mesh axis names (() = unsharded, a rank-unknown tensor is None), and
# ``ctx`` is the analyzer's RuleContext (require/collective/diag/
# analyze_body — see analysis/sharding.py). Rules may carry an optional
# ``backward`` attribute fn(op, out_specs, in_specs, ctx) -> list of
# suggested in specs (or None per slot) for the reverse sweep.
# ---------------------------------------------------------------------------

_SHARDING_RULES: Dict[str, Any] = {}


def register_sharding_rule(name, rule):
    """Attach a sharding propagation rule to op type ``name``. The op
    need not be registered yet (rules and OpDefs may load from different
    modules); re-registration replaces."""
    _SHARDING_RULES[name] = rule
    return rule


def sharding_rule(name):
    """The registered sharding rule for op type ``name``, or None (the
    analyzer then applies its conservative default)."""
    return _SHARDING_RULES.get(name)
