"""IndexedSlices: sparse gradient representation for embedding lookups.

(ref: tensorflow/python/framework/ops.py ``class IndexedSlices``). On TPU,
XLA scatters are efficient and fuse into the update, so IndexedSlices is a
thin (values, indices, dense_shape) triple that optimizers can apply via
scatter-add instead of densifying — same contract as the reference.
"""

from __future__ import annotations


class IndexedSlices:
    def __init__(self, values, indices, dense_shape=None):
        self._values = values
        self._indices = indices
        self._dense_shape = dense_shape

    @property
    def values(self):
        return self._values

    @property
    def indices(self):
        return self._indices

    @property
    def dense_shape(self):
        return self._dense_shape

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def name(self):
        return self._values.name

    @property
    def op(self):
        return self._values.op

    @property
    def graph(self):
        return self._values.graph

    def __repr__(self):
        return (f"IndexedSlices(values={self._values!r}, "
                f"indices={self._indices!r})")


def convert_to_tensor_or_indexed_slices(value, dtype=None, name=None):
    from . import graph as ops_mod

    if isinstance(value, IndexedSlices):
        return value
    return ops_mod.convert_to_tensor(value, dtype=dtype, name=name)
