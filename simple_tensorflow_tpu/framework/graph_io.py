"""Graph serialization: GraphDef-equivalent JSON + MetaGraph
(ref: tensorflow/python/framework/{graph_io,importer,meta_graph}.py,
core/framework/graph.proto).

The wire format is JSON (attrs hold numpy constants base64-encoded) rather
than GraphDef protobuf — the reference's proto schema is tied to its op
registry; ours captures the same information (nodes, inputs, control deps,
attrs, collections, versions) for export/import round-trips.
"""

from __future__ import annotations

import base64
import io as _io
import json
import os

import numpy as np

from . import dtypes as dtypes_mod
from . import graph as ops_mod
from . import tensor_shape as shape_mod


def _encode_attr(v):
    if isinstance(v, np.ndarray):
        buf = _io.BytesIO()
        if v.dtype == object:
            return {"__kind__": "strlist",
                    "v": [str(s) for s in np.ravel(v)],
                    "shape": list(v.shape)}
        np.save(buf, v, allow_pickle=False)
        return {"__kind__": "ndarray",
                "v": base64.b64encode(buf.getvalue()).decode()}
    if isinstance(v, dtypes_mod.DType):
        return {"__kind__": "dtype", "v": v.name}
    if isinstance(v, shape_mod.TensorShape):
        return {"__kind__": "shape",
                "v": v.as_list() if v.rank is not None else None}
    if isinstance(v, ops_mod.FuncGraph):
        return {"__kind__": "funcgraph", "v": _funcgraph_to_dict(v)}
    if isinstance(v, tuple):
        return {"__kind__": "tuple", "v": [_encode_attr(x) for x in v]}
    if isinstance(v, (int, float, str, bool)) or v is None:
        return v
    if isinstance(v, list):
        return {"__kind__": "tuple", "v": [_encode_attr(x) for x in v]}
    return {"__kind__": "repr", "v": repr(v)}


def _decode_attr(v):
    if isinstance(v, dict) and "__kind__" in v:
        kind = v["__kind__"]
        if kind == "ndarray":
            return np.load(_io.BytesIO(base64.b64decode(v["v"])),
                           allow_pickle=False)
        if kind == "strlist":
            return np.asarray(v["v"], dtype=object).reshape(v["shape"])
        if kind == "dtype":
            return dtypes_mod.as_dtype(v["v"])
        if kind == "shape":
            return shape_mod.TensorShape(v["v"])
        if kind == "tuple":
            return tuple(_decode_attr(x) for x in v["v"])
        if kind == "funcgraph":
            return v  # rebuilt lazily by importer
        if kind == "repr":
            return v["v"]
    return v


def _node_to_dict(op: ops_mod.Operation):
    d = {
        "name": op.name,
        "op": op.type,
        "input": [t.name for t in op.inputs],
        "control_input": [c.name for c in op.control_inputs],
        "device": op.device,
        "attr": {k: _encode_attr(v) for k, v in op.attrs.items()},
        "output_specs": [
            [o.shape.as_list() if o.shape.rank is not None else None,
             o.dtype.name] for o in op.outputs],
    }
    if op.traceback:
        # innermost user frame only: enough for stf.analysis diagnostics
        # on re-imported graphs to point at the original creation site
        f, ln, fn = op.traceback[0]
        d["source"] = [f, ln, fn]
    return d


def _funcgraph_to_dict(fg: ops_mod.FuncGraph):
    return {
        "name": fg.func_name,
        "node": [_node_to_dict(op) for op in fg.get_operations()],
        "inputs": [t.name for t in fg.inputs],
        "outputs": [t.name for t in fg.outputs],
        # an imported FuncGraph has outer=None captures (re-bound by the
        # caller through the op's input list) — serialize those as None
        "captures": [[outer.name if outer is not None else None,
                      inner.name] for outer, inner in fg.captures],
    }


def graph_to_graphdef(graph: ops_mod.Graph, from_version=None):
    """(ref: Graph.as_graph_def, core/framework/graph.proto)."""
    return {
        "versions": {"producer": 1},
        "node": [_node_to_dict(op) for op in graph.get_operations()],
    }


def write_graph(graph_or_graph_def, logdir, name, as_text=True):
    """(ref: python/framework/graph_io.py:28 ``write_graph``)."""
    if isinstance(graph_or_graph_def, ops_mod.Graph):
        gd = graph_to_graphdef(graph_or_graph_def)
    else:
        gd = graph_or_graph_def
    os.makedirs(logdir, exist_ok=True)
    path = os.path.join(logdir, name)
    with open(path, "w") as f:
        json.dump(gd, f, indent=1 if as_text else None)
    return path


def _build_nodes_into(target_graph, nodes, tensor_env, scope_prefix,
                      input_map=None):
    """Rebuild GraphDef node dicts into ``target_graph`` (shared by
    import_graph_def and rebuild_funcgraph)."""
    input_map = input_map or {}
    for node in nodes:
        attrs = {k: _decode_attr(v)
                 for k, v in (node.get("attr") or {}).items()}
        # Scoped imports get their own VariableStore namespace: rewrite
        # var_name attrs so an imported 'w' cannot alias an existing
        # variable 'w' in this graph (store keys come from these attrs).
        if scope_prefix:
            if isinstance(attrs.get("var_name"), str):
                attrs["var_name"] = f"{scope_prefix}/{attrs['var_name']}"
            if isinstance(attrs.get("var_names"), tuple):
                attrs["var_names"] = tuple(
                    f"{scope_prefix}/{n}" for n in attrs["var_names"])
        # rebuild nested funcgraphs
        for k, v in list(attrs.items()):
            if isinstance(v, dict) and v.get("__kind__") == "funcgraph":
                attrs[k] = rebuild_funcgraph(v["v"], target_graph)
        inputs = []
        for ref in node["input"]:
            if ref in input_map:
                inputs.append(input_map[ref])
            else:
                inputs.append(tensor_env[ref])
        ctrl = [tensor_env["(op)" + c]
                for c in node.get("control_input", ())
                if "(op)" + c in tensor_env]
        # A producer that doesn't know output shapes (e.g. the C client
        # building math ops) omits output_specs; the op registry's
        # shape inference fills them in, mirroring the reference's
        # shape_refiner on import (ref: common_runtime/shape_refiner.cc).
        specs_raw = node.get("output_specs")
        specs = None if specs_raw is None else [
            (shape_mod.TensorShape(sh), dtypes_mod.as_dtype(dt))
            for sh, dt in specs_raw]
        new_name = f"{scope_prefix}/{node['name']}" if scope_prefix \
            else node["name"]
        op = target_graph.create_op(
            node["op"], inputs, attrs=attrs, name=new_name + "/",
            output_specs=specs, control_inputs=ctrl)
        src = node.get("source")
        if src and len(src) == 3:
            # restore the original creation site (the capture above only
            # recorded the import call) for analysis diagnostics
            op._traceback = ((str(src[0]), int(src[1]), str(src[2])),)
        tensor_env["(op)" + node["name"]] = op
        for i, out in enumerate(op.outputs):
            tensor_env[f"{node['name']}:{i}"] = out
    return tensor_env


def rebuild_funcgraph(fg_dict, outer):
    """Rebuild a serialized FuncGraph dict into a live FuncGraph of
    ``outer``. Captures keep their inner placeholders with outer refs
    None — resolving outers by name is not possible here; the caller
    (the function-op's lowering via op inputs, or
    optimizer.optimize_graph_functions) re-binds them."""
    fg = ops_mod.FuncGraph(fg_dict["name"], outer_graph=outer)
    env = {}
    with ops_mod._as_current(fg):
        _build_nodes_into(fg, fg_dict["node"], env, "")
    fg.inputs = [env[n] for n in fg_dict["inputs"]]
    fg.outputs = [env[n] for n in fg_dict["outputs"]]
    fg.captures = [(None, env[inner])
                   for _, inner in fg_dict["captures"]]
    return fg


def import_graph_def(graph_def, input_map=None, return_elements=None,
                     name=None, op_dict=None, producer_op_list=None):
    """(ref: python/framework/importer.py:156 ``import_graph_def``).

    Rebuilds nodes into the current default graph. FuncGraph attrs are
    rebuilt recursively.
    """
    if isinstance(graph_def, (str, bytes)):
        graph_def = json.loads(graph_def)
    g = ops_mod.get_default_graph()
    # TF semantics: default prefix "import"; explicit "" means no prefix
    prefix = "import" if name is None else name
    input_map = {k: v for k, v in (input_map or {}).items()}
    tensors = {}

    _build_nodes_into(g, graph_def["node"], tensors, prefix,
                      input_map=input_map)
    if return_elements:
        out = []
        for r in return_elements:
            key = f"{r}" if ":" in r else "(op)" + r
            out.append(tensors[key] if key in tensors
                       else tensors[f"{r}:0"])
        return out
    return None


def export_meta_graph(filename=None, graph=None, collection_list=None,
                      **kwargs):
    """(ref: python/framework/meta_graph.py ``export_scoped_meta_graph``)."""
    graph = graph or ops_mod.get_default_graph()
    meta = {
        "graph_def": graph_to_graphdef(graph),
        "collections": {},
        "meta_info": {"stf_version": "1.0.0-tpu"},
    }
    for key in (collection_list or graph.get_all_collection_keys()):
        items = graph.get_collection(key)
        names = []
        for it in items:
            if isinstance(it, ops_mod.Tensor):
                names.append({"tensor": it.name})
            elif isinstance(it, ops_mod.Operation):
                names.append({"op": it.name})
            elif hasattr(it, "to_proto"):
                try:
                    names.append({"proto": it.to_proto()})
                except Exception:
                    continue
        if names:
            meta["collections"][key] = names
    if filename:
        with open(filename, "w") as f:
            json.dump(meta, f)
    return meta


def import_meta_graph(meta_graph_or_file, clear_devices=False,
                      import_scope=None):
    if isinstance(meta_graph_or_file, str):
        with open(meta_graph_or_file) as f:
            meta = json.load(f)
    else:
        meta = meta_graph_or_file
    import_graph_def(meta["graph_def"], name=import_scope or "")
    _rebuild_collections(meta, import_scope)
    return meta


def _rebuild_collections(meta, import_scope=None):
    """Restore graph collections from a MetaGraph, reconstructing Variable
    wrappers from their serialized protos (ref: python/framework/
    meta_graph.py ``import_scoped_meta_graph`` — without this, Saver finds
    no variables after import and restore is a silent no-op)."""
    g = ops_mod.get_default_graph()
    rebuilt_vars = {}  # variable_name -> Variable (shared across collections)

    def _scoped(name):
        return f"{import_scope}/{name}" if import_scope else name

    for key, items in meta.get("collections", {}).items():
        for it in items:
            if "tensor" in it or "op" in it:
                ref, as_tensor = ((it["tensor"], True) if "tensor" in it
                                  else (it["op"], False))
                try:
                    g.add_to_collection(key, g.as_graph_element(
                        _scoped(ref), allow_tensor=as_tensor,
                        allow_operation=not as_tensor))
                except (KeyError, ValueError):
                    continue  # item not present in the imported subgraph
            elif "proto" in it:
                proto = it["proto"]
                if isinstance(proto, dict) and "variable_name" in proto:
                    vname = proto["variable_name"]
                    if vname not in rebuilt_vars:
                        from ..ops.variables import Variable

                        try:
                            rebuilt_vars[vname] = Variable.from_proto(
                                proto, import_scope=import_scope, graph=g)
                        except (KeyError, ValueError) as e:
                            # a dropped variable means Saver.restore would
                            # silently skip it — that must be loud
                            from ..platform import tf_logging as logging

                            logging.warning(
                                "import_meta_graph: could not rebuild "
                                "variable %s from collection %s (%s); it "
                                "will NOT be restored by Saver.", vname,
                                key, e)
                            continue
                    g.add_to_collection(key, rebuilt_vars[vname])
                # other proto kinds (e.g. SaverDef) are advisory: the
                # caller constructs a fresh Saver over the rebuilt vars
