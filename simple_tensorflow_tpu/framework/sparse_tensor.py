"""SparseTensor (ref: tensorflow/python/framework/sparse_tensor.py).

COO triple (indices, values, dense_shape). On TPU all shapes are static, so
a SparseTensor here is a fixed-capacity COO: ``nnz`` is the static leading
dim of indices/values (padding rows carry index -1 and are masked out by the
sparse ops). This is the tf2xla-compatible subset of the reference.
"""

from __future__ import annotations

import numpy as np

from . import dtypes as dtypes_mod
from . import graph as ops_mod
from . import tensor_shape as shape_mod


class SparseTensor:
    def __init__(self, indices, values, dense_shape):
        self._indices = ops_mod.convert_to_tensor(indices,
                                                  dtype=dtypes_mod.int64)
        self._values = ops_mod.convert_to_tensor(values)
        self._dense_shape = ops_mod.convert_to_tensor(dense_shape,
                                                      dtype=dtypes_mod.int64)

    @classmethod
    def from_value(cls, value):
        if isinstance(value, SparseTensor):
            return value
        if isinstance(value, SparseTensorValue):
            return cls(value.indices, value.values, value.dense_shape)
        raise TypeError(f"Cannot convert {value!r} to SparseTensor")

    @property
    def indices(self):
        return self._indices

    @property
    def values(self):
        return self._values

    @property
    def dense_shape(self):
        return self._dense_shape

    @property
    def dtype(self):
        return self._values.dtype

    @property
    def shape(self):
        from . import constant_op

        v = constant_op.constant_value(self._dense_shape)
        if v is None:
            return shape_mod.TensorShape(None)
        return shape_mod.TensorShape([int(d) for d in v])

    def get_shape(self):
        return self.shape

    @property
    def graph(self):
        return self._values.graph

    @property
    def op(self):
        return self._values.op

    def eval(self, feed_dict=None, session=None):
        from ..client.session import get_default_session

        session = session or get_default_session()
        i, v, s = session.run([self._indices, self._values, self._dense_shape],
                              feed_dict=feed_dict)
        return SparseTensorValue(i, v, s)

    def __repr__(self):
        return (f"SparseTensor(indices={self._indices!r}, "
                f"values={self._values!r}, dense_shape={self._dense_shape!r})")


class SparseTensorValue:
    """Concrete counterpart returned by Session.run."""

    __slots__ = ("indices", "values", "dense_shape")

    def __init__(self, indices, values, dense_shape):
        self.indices = np.asarray(indices)
        self.values = np.asarray(values)
        self.dense_shape = np.asarray(dense_shape)

    def __iter__(self):
        return iter((self.indices, self.values, self.dense_shape))

    def __repr__(self):
        return (f"SparseTensorValue(indices={self.indices!r}, "
                f"values={self.values!r}, dense_shape={self.dense_shape!r})")


def convert_to_tensor_or_sparse_tensor(value, dtype=None, name=None):
    if isinstance(value, (SparseTensor, SparseTensorValue)):
        return SparseTensor.from_value(value)
    return ops_mod.convert_to_tensor(value, dtype=dtype, name=name)
