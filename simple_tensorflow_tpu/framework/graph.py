"""Graph IR: Graph / Operation / Tensor.

TPU-native re-design of the reference graph layer
(ref: tensorflow/python/framework/ops.py — ``Graph``, ``Operation``,
``Tensor``; tensorflow/core/graph/graph.h). The user-facing model is the
same deferred-execution dataflow graph as TF-1.0 (name scopes, collections,
control dependencies, feeds/fetches), but the graph is *not* executed by a
per-node interpreter: Session lowers the pruned fetch subgraph into a single
pure JAX function that XLA compiles for the TPU (see
simple_tensorflow_tpu/framework/lowering.py). Consequences for the IR:

- Operations are immutable once created and the graph is append-only, so a
  compiled executable for a pruned subgraph can never be invalidated by later
  graph construction (the reference rebuilds executors on graph mutation,
  ref core/common_runtime/direct_session.cc ``GetOrCreateExecutors``).
- There are no Enter/Exit/Switch/Merge control-flow nodes; cond/while carry
  nested FuncGraphs (as TF-2 does) which lower to lax.cond/lax.while_loop —
  the XLA-friendly formulation.
- Stateful ops (variables, RNG) declare their effects; ordering between
  effectful ops is defined by data + control edges, enforced by topological
  order at lowering time.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import weakref
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..platform import sync as _sync

from . import dtypes as dtypes_mod
from . import tensor_shape as shape_mod
from .errors import InvalidArgumentError


class GraphKeys:
    """Standard collection names (ref: python/framework/ops.py ``GraphKeys``)."""

    GLOBAL_VARIABLES = "variables"
    LOCAL_VARIABLES = "local_variables"
    MODEL_VARIABLES = "model_variables"
    TRAINABLE_VARIABLES = "trainable_variables"
    SUMMARIES = "summaries"
    QUEUE_RUNNERS = "queue_runners"
    TABLE_INITIALIZERS = "table_initializer"
    ASSET_FILEPATHS = "asset_filepaths"
    MOVING_AVERAGE_VARIABLES = "moving_average_variables"
    REGULARIZATION_LOSSES = "regularization_losses"
    CONCATENATED_VARIABLES = "concatenated_variables"
    SAVERS = "savers"
    WEIGHTS = "weights"
    BIASES = "biases"
    ACTIVATIONS = "activations"
    UPDATE_OPS = "update_ops"
    LOSSES = "losses"
    SAVEABLE_OBJECTS = "saveable_objects"
    RESOURCES = "resources"
    LOCAL_RESOURCES = "local_resources"
    INIT_OP = "init_op"
    LOCAL_INIT_OP = "local_init_op"
    READY_OP = "ready_op"
    READY_FOR_LOCAL_INIT_OP = "ready_for_local_init_op"
    SUMMARY_OP = "summary_op"
    GLOBAL_STEP = "global_step"
    EVAL_STEP = "eval_step"
    TRAIN_OP = "train_op"
    COND_CONTEXT = "cond_context"
    WHILE_CONTEXT = "while_context"
    VARIABLES = GLOBAL_VARIABLES  # deprecated alias


class Tensor:
    """Symbolic handle to one output of an Operation.

    (ref: python/framework/ops.py:214 ``class Tensor``). Carries static dtype
    and (possibly partial) shape. Concrete values only exist inside the
    lowered XLA program or as Session.run results.
    """

    __slots__ = ("_op", "_value_index", "_dtype", "_shape", "__weakref__")

    def __init__(self, op: "Operation", value_index: int, dtype, shape):
        self._op = op
        self._value_index = value_index
        self._dtype = dtypes_mod.as_dtype(dtype)
        self._shape = shape_mod.as_shape(shape)

    @property
    def op(self) -> "Operation":
        return self._op

    @property
    def graph(self) -> "Graph":
        return self._op.graph

    @property
    def value_index(self) -> int:
        return self._value_index

    @property
    def dtype(self) -> dtypes_mod.DType:
        return self._dtype

    @property
    def shape(self) -> shape_mod.TensorShape:
        return self._shape

    def get_shape(self) -> shape_mod.TensorShape:
        return self._shape

    def set_shape(self, shape):
        self._shape = self._shape.merge_with(shape)

    @property
    def name(self) -> str:
        return f"{self._op.name}:{self._value_index}"

    @property
    def device(self) -> str:
        return self._op.device

    @property
    def ndim(self):
        return self._shape.rank

    def consumers(self) -> List["Operation"]:
        return self.graph._consumers(self)

    def eval(self, feed_dict=None, session=None):
        from ..client.session import get_default_session

        session = session or get_default_session()
        if session is None:
            raise ValueError(
                "Cannot evaluate tensor using `eval()`: No default session")
        return session.run(self, feed_dict=feed_dict)

    def __repr__(self):
        return (f"<stf.Tensor '{self.name}' shape={self._shape} "
                f"dtype={self._dtype.name}>")

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        # Python-level identity; elementwise equality is stf.equal().
        return self is other

    def __bool__(self):
        raise TypeError(
            "Using a symbolic stf.Tensor as a Python bool is not allowed. "
            "Use stf.cond / stf.where for data-dependent control flow — on "
            "TPU the graph is compiled once by XLA and cannot branch on "
            "tensor values in Python.")

    def __iter__(self):
        n = self._shape[0].value if self._shape.rank else None
        if self._shape.rank is None or self._shape.rank == 0:
            raise TypeError("Cannot iterate over a scalar/unknown-rank tensor")
        if n is None:
            raise TypeError("Cannot iterate over a tensor with unknown first dim")
        return iter([self[i] for i in range(n)])

    def __len__(self):
        if self._shape.rank and self._shape[0].value is not None:
            return self._shape[0].value
        raise TypeError(f"len() of tensor with unknown first dim: {self}")

    # NumPy interop: makes np.float32(tensor) etc. fail loudly.
    __array_priority__ = 100

    def __array__(self, *a, **k):
        raise NotImplementedError(
            f"Cannot convert symbolic tensor {self.name} to a numpy array: "
            "run it in a Session first.")

    # Arithmetic operators are attached by math_ops at import time
    # (mirrors the reference's _override_helper, python/framework/ops.py:1430).


# -- op-creation traceback capture (stf.analysis tentpole) -------------------
# Every Operation records where user code created it, so static-analysis
# diagnostics (verifier / hazard detector / lint) point at a file:line
# instead of a bare op name (the reference stores the same thing on
# every node, ref: python/framework/ops.py ``Operation.traceback`` /
# tf_stack.cc). Implementation is a raw sys._getframe walk — no
# traceback objects, no source-line reads — measured ~1 us per op;
# off-switchable for construction-bound workloads.

_PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) \
    + os.sep
_MAX_TB_FRAMES = 8
_capture_tracebacks = os.environ.get("STF_OP_TRACEBACK", "1") != "0"


def set_traceback_capture(enabled: bool) -> bool:
    """Toggle op-creation traceback capture; returns the previous value."""
    global _capture_tracebacks
    prev = _capture_tracebacks
    _capture_tracebacks = bool(enabled)
    return prev


def traceback_capture_enabled() -> bool:
    return _capture_tracebacks


def _capture_op_traceback():
    """(filename, lineno, function) frames, innermost first: user-code
    frames (outside the stf package), preceded by the single innermost
    in-package frame as a fallback anchor when the whole stack is
    internal (graphs built by models/ helpers called from deeper user
    code still resolve to the user frame further out)."""
    frames = []
    try:
        f = sys._getframe(2)
    except ValueError:  # shallow stack
        return ()
    innermost_internal = None
    depth = 0
    while f is not None and depth < 64 and len(frames) < _MAX_TB_FRAMES:
        code = f.f_code
        fname = code.co_filename
        if fname.startswith(_PACKAGE_DIR):
            if innermost_internal is None:
                innermost_internal = (fname, f.f_lineno, code.co_name)
        else:
            frames.append((fname, f.f_lineno, code.co_name))
        f = f.f_back
        depth += 1
    if not frames and innermost_internal is not None:
        frames.append(innermost_internal)
    return tuple(frames)


class Operation:
    """A node in the Graph. Immutable after construction.

    (ref: python/framework/ops.py:1089 ``class Operation``,
    core/framework/node_def.proto). ``attrs`` holds static (trace-time)
    attributes: python scalars, shapes, dtypes, numpy constants, nested
    FuncGraphs for control flow.
    """

    __slots__ = ("_graph", "_type", "_name", "_inputs", "_control_inputs",
                 "_attrs", "_outputs", "_device", "_id", "_traceback",
                 "__weakref__")

    def __init__(self, graph, op_type, name, inputs, control_inputs, attrs,
                 output_specs, device):
        self._graph = graph
        self._type = op_type
        self._name = name
        self._inputs: Tuple[Tensor, ...] = tuple(inputs)
        self._control_inputs: Tuple[Operation, ...] = tuple(control_inputs)
        self._attrs: Dict[str, Any] = dict(attrs)
        self._device = device
        self._id = graph._next_id()
        self._traceback = (_capture_op_traceback() if _capture_tracebacks
                           else ())
        self._outputs = tuple(
            Tensor(self, i, dt, sh) for i, (sh, dt) in enumerate(output_specs))

    @property
    def graph(self):
        return self._graph

    @property
    def type(self) -> str:
        return self._type

    @property
    def name(self) -> str:
        return self._name

    @property
    def inputs(self) -> Tuple[Tensor, ...]:
        return self._inputs

    @property
    def control_inputs(self) -> Tuple["Operation", ...]:
        return self._control_inputs

    @property
    def outputs(self) -> Tuple[Tensor, ...]:
        return self._outputs

    @property
    def device(self) -> str:
        return self._device

    @property
    def attrs(self) -> Dict[str, Any]:
        return self._attrs

    @property
    def traceback(self) -> Tuple[Tuple[str, int, str], ...]:
        """(filename, lineno, function) frames of the op's creation
        site, innermost (closest to user code) first; empty when capture
        was off (ref: ops.py ``Operation.traceback``)."""
        return self._traceback

    @property
    def source_site(self) -> Optional[str]:
        """``file:line`` of the user-code frame that created this op, or
        None when capture was disabled."""
        if not self._traceback:
            return None
        fname, lineno, _ = self._traceback[0]
        return f"{fname}:{lineno}"

    def get_attr(self, name):
        try:
            return self._attrs[name]
        except KeyError:
            raise ValueError(f"Operation {self._name!r} has no attr {name!r}")

    @property
    def node_def(self):
        return {"name": self._name, "op": self._type,
                "input": [t.name for t in self._inputs],
                "device": self._device}

    @property
    def op_def(self):
        from . import op_registry

        return op_registry.get(self._type)

    def run(self, feed_dict=None, session=None):
        from ..client.session import get_default_session

        session = session or get_default_session()
        if session is None:
            raise ValueError("No default session for Operation.run()")
        session.run(self, feed_dict=feed_dict)

    def __repr__(self):
        return f"<stf.Operation '{self._name}' type={self._type}>"


_default_graph_stack = threading.local()


class Graph:
    """A dataflow graph (ref: python/framework/ops.py:2531 ``class Graph``).

    Append-only: operations are never mutated or removed, so compiled
    executables keyed on (fetches, feeds) stay valid as the graph grows.
    """

    def __init__(self):
        self._lock = _sync.RLock("framework/graph",
                                 rank=_sync.RANK_SESSION)
        self._ops_by_name: Dict[str, Operation] = {}
        self._ops_in_order: List[Operation] = []
        self._version = 0
        # bumped by optimizer.optimize_graph_functions when a FuncGraph
        # body is rewritten in place: append-only growth never
        # invalidates a compiled step, but a body REWRITE must — Session
        # cache keys include this counter
        self._rewrite_version = 0
        self._op_counter = 0
        self._names_in_use: Dict[str, int] = {}
        self._name_stack = ""
        self._collections: Dict[str, list] = {}
        self._control_deps_stack: List[List[Operation]] = []
        self._device_stack: List[str] = []
        self._colocation_stack: List[Operation] = []
        self._seed: Optional[int] = None
        self._finalized = False
        self._consumers_map: Dict[Tensor, List[Operation]] = {}
        self._attr_scope_stack: List[Dict[str, Any]] = []
        self._container = ""
        # Used by variable_scope / sharding scopes to stash arbitrary state.
        self._scoped_state: Dict[str, Any] = {}

    # -- versioning / ids ----------------------------------------------------
    @property
    def version(self) -> int:
        return self._version

    @property
    def rewrite_version(self) -> int:
        """How many times this graph's function bodies have been
        rewritten in place (optimizer.optimize_graph_functions)."""
        return self._rewrite_version

    def _next_id(self) -> int:
        self._op_counter += 1
        return self._op_counter

    @property
    def graph_def_versions(self):
        return {"producer": 1}

    def finalize(self):
        """Make the graph read-only (ref: ops.py ``Graph.finalize``)."""
        self._finalized = True

    @property
    def finalized(self):
        return self._finalized

    # -- naming --------------------------------------------------------------
    def unique_name(self, name: str, mark_as_used=True) -> str:
        if self._name_stack:
            name = f"{self._name_stack}/{name}"
        i = self._names_in_use.get(name, 0)
        if mark_as_used:
            self._names_in_use[name] = i + 1
        if i > 0:
            base = name
            name = f"{base}_{i}"
            while name in self._names_in_use:
                i += 1
                name = f"{base}_{i}"
            if mark_as_used:
                self._names_in_use[name] = 1
        return name

    @contextlib.contextmanager
    def name_scope(self, name: Optional[str]):
        """(ref: python/framework/ops.py ``Graph.name_scope``)."""
        old = self._name_stack
        if name is None or name == "":
            self._name_stack = ""
        elif name.endswith("/"):
            self._name_stack = name[:-1]
        else:
            self._name_stack = self.unique_name(name)
        try:
            yield (self._name_stack + "/") if self._name_stack else ""
        finally:
            self._name_stack = old

    @contextlib.contextmanager
    def gradient_override_map(self, op_type_map):
        """(ref: python/framework/ops.py ``Graph.gradient_override_map``):
        within the context, ops of the mapped types differentiate through
        the @stf.RegisterGradient function of the mapped name instead of
        their normal gradient."""
        stack = self._scoped_state.setdefault("__grad_override_stack__",
                                              [])
        stack.append(dict(op_type_map))
        try:
            yield
        finally:
            stack.pop()

    # -- scopes --------------------------------------------------------------
    @contextlib.contextmanager
    def control_dependencies(self, control_inputs):
        if control_inputs is None:
            saved = self._control_deps_stack
            self._control_deps_stack = []
            try:
                yield
            finally:
                self._control_deps_stack = saved
            return
        ops = []
        for c in control_inputs:
            if isinstance(c, Tensor):
                ops.append(c.op)
            elif isinstance(c, Operation):
                ops.append(c)
            elif hasattr(c, "op"):  # Variable
                ops.append(c.op)
            else:
                raise TypeError(f"control input must be Operation/Tensor, got {c!r}")
        self._control_deps_stack.append(ops)
        try:
            yield
        finally:
            self._control_deps_stack.pop()

    def _current_control_dependencies(self) -> List[Operation]:
        out = []
        for frame in self._control_deps_stack:
            for op in frame:
                if op not in out:
                    out.append(op)
        return out

    @contextlib.contextmanager
    def device(self, device_name: Optional[str]):
        """Device scope. On TPU this is a *placement hint*: '/cpu:0' marks
        host ops (data pipeline endpoints); TPU placement within the XLA
        program is controlled by shardings, not device strings
        (ref: core/common_runtime/simple_placer.cc is replaced by
        stf/parallel sharding annotations)."""
        self._device_stack.append(device_name or "")
        try:
            yield
        finally:
            self._device_stack.pop()

    def _current_device(self) -> str:
        for d in reversed(self._device_stack):
            if d:
                return d
        return ""

    @contextlib.contextmanager
    def colocate_with(self, op, ignore_existing=False):
        if isinstance(op, Tensor):
            op = op.op
        self._colocation_stack.append(op)
        try:
            yield
        finally:
            self._colocation_stack.pop()

    @contextlib.contextmanager
    def container(self, container_name):
        old = self._container
        self._container = container_name
        try:
            yield self._container
        finally:
            self._container = old

    # -- seeds ---------------------------------------------------------------
    @property
    def seed(self):
        return self._seed

    @seed.setter
    def seed(self, value):
        self._seed = value

    # -- collections ---------------------------------------------------------
    def add_to_collection(self, name, value):
        with self._lock:
            self._collections.setdefault(name, []).append(value)

    def add_to_collections(self, names, value):
        if isinstance(names, str):
            names = [names]
        for n in names:
            self.add_to_collection(n, value)

    def get_collection(self, name, scope=None) -> list:
        with self._lock:
            items = list(self._collections.get(name, []))
        if scope is None:
            return items
        import re

        rx = re.compile(scope)
        out = []
        for item in items:
            item_name = getattr(item, "name", None)
            if item_name and rx.match(item_name):
                out.append(item)
        return out

    def get_collection_ref(self, name) -> list:
        with self._lock:
            return self._collections.setdefault(name, [])

    def clear_collection(self, name):
        with self._lock:
            self._collections.pop(name, None)

    def get_all_collection_keys(self):
        with self._lock:
            return list(self._collections.keys())

    # -- op construction -----------------------------------------------------
    def create_op(self, op_type: str, inputs: Sequence[Tensor],
                  attrs: Optional[Dict[str, Any]] = None,
                  name: Optional[str] = None,
                  output_specs=None,
                  control_inputs: Sequence[Operation] = ()) -> Operation:
        """Create and register an Operation.

        ``output_specs``: optional list of (shape, dtype); if None, the op
        registry's inference runs (ref shape_refiner,
        core/common_runtime/shape_refiner.cc).
        """
        from . import op_registry

        if self._finalized:
            raise RuntimeError("Graph is finalized and cannot be modified.")
        attrs = attrs or {}
        # gradient_override_map (ref: ops.py Graph.gradient_override_map):
        # ops created inside the context carry _gradient_op_type, which the
        # SymbolicGradient replay routes through @RegisterGradient fns
        override = self._scoped_state.get("__grad_override_stack__")
        if override:
            for m in reversed(override):
                if op_type in m:
                    attrs = dict(attrs)
                    attrs["_gradient_op_type"] = m[op_type]
                    break
        if name and name.endswith("/"):
            # TF convention: a trailing slash means "use this exact
            # (already-scoped, already-unique) name" — used by Variable and
            # variable_scope (ref: python/framework/ops.py Graph.create_op).
            name = name[:-1]
            if name in self._ops_by_name:
                raise ValueError(f"Op name {name!r} already used")
        else:
            name = self.unique_name(name or op_type)
        opdef = op_registry.get(op_type)
        checked = []
        for i, t in enumerate(inputs):
            if not isinstance(t, Tensor):
                raise TypeError(
                    f"Input {i} of op {name!r} ({op_type}) is not a Tensor: {t!r}")
            checked.append(self._maybe_capture(t, name))
        inputs = tuple(checked)
        if output_specs is None:
            output_specs = opdef.infer(self, attrs, inputs)
        ctrl = list(control_inputs) + [
            c for c in self._current_control_dependencies()
            if c not in control_inputs]
        device = self._current_device()
        if opdef.runs_on_host:
            device = device or "/cpu:0"
        op = Operation(self, op_type, name, inputs, ctrl, attrs,
                       output_specs, device)
        with self._lock:
            self._ops_by_name[name] = op
            self._ops_in_order.append(op)
            self._version += 1
            for t in inputs:
                self._consumers_map.setdefault(t, []).append(op)
        return op

    def _maybe_capture(self, t: "Tensor", for_op: str) -> "Tensor":
        """Same-graph tensors pass through; in a FuncGraph, outer-graph
        tensors are captured as implicit inputs (TF-2 FuncGraph semantics —
        the XLA-friendly replacement for the reference's Enter/Exit frame
        nodes, ref core/graph/graph.h NodeClass::ENTER)."""
        if t.graph is self:
            return t
        if isinstance(self, FuncGraph):
            og = self.outer_graph
            if t.graph is og:
                return self.capture(t)
            captured_outer = og._maybe_capture(t, for_op)
            return self.capture(captured_outer)
        raise ValueError(
            f"Input {t.name} of {for_op!r} is from a different graph.")

    # -- lookup --------------------------------------------------------------
    def get_operations(self) -> List[Operation]:
        with self._lock:
            return list(self._ops_in_order)

    def get_operation_by_name(self, name: str) -> Operation:
        with self._lock:
            if name not in self._ops_by_name:
                raise KeyError(f"Operation {name!r} not found in graph")
            return self._ops_by_name[name]

    def get_tensor_by_name(self, name: str) -> Tensor:
        if ":" not in name:
            raise ValueError(
                f"{name!r} is an operation name, not a tensor name "
                "(tensor names look like 'op:0')")
        op_name, idx = name.rsplit(":", 1)
        op = self.get_operation_by_name(op_name)
        return op.outputs[int(idx)]

    def as_graph_element(self, obj, allow_tensor=True, allow_operation=True):
        """(ref: ops.py ``Graph.as_graph_element``)."""
        if isinstance(obj, Tensor):
            if not allow_tensor:
                raise TypeError("Tensor not allowed here")
            if obj.graph is not self:
                raise ValueError(f"Tensor {obj} is not from this graph")
            return obj
        if isinstance(obj, Operation):
            if not allow_operation:
                raise TypeError("Operation not allowed here")
            if obj.graph is not self:
                raise ValueError(f"Operation {obj} is not from this graph")
            return obj
        if hasattr(obj, "_as_graph_element"):
            return self.as_graph_element(obj._as_graph_element(),
                                         allow_tensor, allow_operation)
        if isinstance(obj, str):
            if ":" in obj:
                return self.get_tensor_by_name(obj)
            return self.get_operation_by_name(obj)
        raise TypeError(f"Cannot convert {obj!r} to a graph element")

    def _consumers(self, tensor: Tensor) -> List[Operation]:
        with self._lock:
            return list(self._consumers_map.get(tensor, []))

    # -- default-graph stack -------------------------------------------------
    @contextlib.contextmanager
    def as_default(self):
        stack = _get_graph_stack()
        stack.append(self)
        try:
            yield self
        finally:
            stack.pop()

    # -- serialization (see graph_io.py) -------------------------------------
    def as_graph_def(self, from_version=None):
        from . import graph_io

        return graph_io.graph_to_graphdef(self, from_version=from_version)

    def __repr__(self):
        return f"<stf.Graph with {len(self._ops_in_order)} ops>"


class FuncGraph(Graph):
    """A nested graph with captures, used for cond/while/function bodies.

    TPU-first: the reference expresses control flow with dynamic
    Switch/Merge/Enter/Exit nodes executed by the interpreter loop
    (ref: python/ops/control_flow_ops.py); XLA wants structured control flow,
    so branch/body subgraphs are FuncGraphs that lower to lax.cond /
    lax.while_loop / lax.scan. Outer-graph tensors referenced inside are
    captured as implicit inputs (like TF-2 FuncGraph).
    """

    def __init__(self, name: str, outer_graph: Graph):
        super().__init__()
        self.func_name = name
        self.outer_graph = outer_graph
        self.captures: List[Tuple[Tensor, Tensor]] = []  # (outer, inner placeholder)
        self.inputs: List[Tensor] = []
        self.outputs: List[Tensor] = []
        self._seed = outer_graph.seed

    def capture(self, outer_tensor: Tensor) -> Tensor:
        for ext, internal in self.captures:
            if ext is outer_tensor:
                return internal
        ph_op = self.create_op(
            "CapturedInput", [],
            attrs={"dtype": outer_tensor.dtype, "shape": outer_tensor.shape},
            name=f"captured_{len(self.captures)}",
            output_specs=[(outer_tensor.shape, outer_tensor.dtype)])
        internal = ph_op.outputs[0]
        self.captures.append((outer_tensor, internal))
        return internal

    def add_input(self, dtype, shape, name="arg") -> Tensor:
        op = self.create_op("FuncArg", [],
                            attrs={"dtype": dtypes_mod.as_dtype(dtype),
                                   "shape": shape_mod.as_shape(shape),
                                   "index": len(self.inputs)},
                            name=name,
                            output_specs=[(shape, dtype)])
        t = op.outputs[0]
        self.inputs.append(t)
        return t


def _get_graph_stack() -> List[Graph]:
    if not hasattr(_default_graph_stack, "stack"):
        _default_graph_stack.stack = []
    return _default_graph_stack.stack


_global_default_graph: Optional[Graph] = None
_global_lock = _sync.Lock("framework/default_graph",
                          rank=_sync.RANK_LIFECYCLE)


def _root_graph() -> "Graph":
    """The outermost (non-FuncGraph) default graph. Variables always live
    here — a variable created while tracing a cond/while/scan body belongs to
    the main graph and is auto-captured into the body (the reference hoists
    variables out of while frames the same way, ref
    python/ops/variable_scope.py get_variable + control_flow context)."""
    g = get_default_graph()
    while isinstance(g, FuncGraph):
        g = g.outer_graph
    return g


def get_default_graph() -> Graph:
    stack = _get_graph_stack()
    if stack:
        return stack[-1]
    global _global_default_graph
    with _global_lock:
        if _global_default_graph is None:
            _global_default_graph = Graph()
        return _global_default_graph


def reset_default_graph():
    global _global_default_graph
    if _get_graph_stack():
        raise AssertionError(
            "Do not use reset_default_graph() inside a `with g.as_default()` block.")
    with _global_lock:
        _global_default_graph = Graph()


@contextlib.contextmanager
def name_scope(name, default_name=None, values=None):
    """Module-level name_scope (ref: ops.py:4164 ``name_scope``)."""
    g = get_default_graph()
    if values:
        for v in values:
            if isinstance(v, Tensor) and isinstance(v.graph, FuncGraph):
                g = v.graph
                break
    scope_name = name if name is not None else default_name
    with g.name_scope(scope_name) as scope:
        yield scope


@contextlib.contextmanager
def control_dependencies(control_inputs):
    with get_default_graph().control_dependencies(control_inputs):
        yield


@contextlib.contextmanager
def device(device_name):
    # Accept strings, context managers (replica_device_setter), and device
    # functions (legacy); non-strings are sharding-driven on TPU.
    if hasattr(device_name, "__enter__"):
        with device_name:
            yield
    elif callable(device_name) and not isinstance(device_name, str):
        yield
    else:
        with get_default_graph().device(device_name):
            yield


@contextlib.contextmanager
def colocate_with(op, ignore_existing=False):
    with get_default_graph().colocate_with(op, ignore_existing):
        yield


@contextlib.contextmanager
def container(container_name):
    with get_default_graph().container(container_name):
        yield


def add_to_collection(name, value):
    get_default_graph().add_to_collection(name, value)


def add_to_collections(names, value):
    get_default_graph().add_to_collections(names, value)


def get_collection(name, scope=None):
    return get_default_graph().get_collection(name, scope)


def get_collection_ref(name):
    return get_default_graph().get_collection_ref(name)


# -- convert_to_tensor machinery ---------------------------------------------

_tensor_conversion_funcs: List[Tuple[int, type, Callable]] = []


def register_tensor_conversion_function(base_type, conversion_func, priority=100):
    """(ref: ops.py ``register_tensor_conversion_function``)."""
    _tensor_conversion_funcs.append((priority, base_type, conversion_func))
    _tensor_conversion_funcs.sort(key=lambda x: x[0])


def convert_to_tensor(value, dtype=None, name=None, preferred_dtype=None):
    """Convert python/numpy values (and Variables etc.) to graph Tensors.

    (ref: ops.py:836 ``convert_to_tensor``). Inside a FuncGraph, outer-graph
    tensors are captured automatically.
    """
    g = get_default_graph()
    if isinstance(value, Tensor):
        if dtype is not None and not dtypes_mod.as_dtype(dtype).is_compatible_with(value.dtype):
            from ..ops import math_ops

            return math_ops.cast(value, dtype)
        if value.graph is g:
            return value
        if isinstance(g, FuncGraph):
            # Capture chain: value may be several graphs out.
            outer = value
            if g.outer_graph is not value.graph and isinstance(g.outer_graph, FuncGraph):
                with _as_current(g.outer_graph):
                    outer = convert_to_tensor(value)
            return g.capture(outer)
        raise ValueError(
            f"Tensor {value.name} belongs to a different graph.")
    for _, base_type, func in _tensor_conversion_funcs:
        if isinstance(value, base_type):
            ret = func(value, dtype=dtype, name=name)
            if ret is not NotImplemented:
                return convert_to_tensor(ret, dtype=dtype, name=name)
    from . import constant_op

    return constant_op.constant(value, dtype=dtype, name=name or "Const")


@contextlib.contextmanager
def _as_current(graph):
    stack = _get_graph_stack()
    stack.append(graph)
    try:
        yield
    finally:
        stack.pop()


def convert_n_to_tensor(values, dtype=None, name=None):
    return [convert_to_tensor(v, dtype=dtype, name=name) for v in values]


def convert_to_tensor_or_indexed_slices(value, dtype=None, name=None):
    from .indexed_slices import IndexedSlices

    if isinstance(value, IndexedSlices):
        return value
    return convert_to_tensor(value, dtype=dtype, name=name)


def is_symbolic_tensor(x) -> bool:
    return isinstance(x, Tensor)


class TensorSpec:
    """Static (shape, dtype, name) spec — used by function signatures and
    SavedModel signature_defs."""

    __slots__ = ("shape", "dtype", "name")

    def __init__(self, shape=None, dtype=dtypes_mod.float32, name=None):
        self.shape = shape_mod.as_shape(shape)
        self.dtype = dtypes_mod.as_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, t: Tensor, name=None):
        return cls(t.shape, t.dtype, name or t.name)

    def is_compatible_with(self, other):
        return (self.dtype == other.dtype and
                self.shape.is_compatible_with(other.shape))

    def __repr__(self):
        return f"TensorSpec(shape={self.shape}, dtype={self.dtype.name}, name={self.name!r})"


def convert_to_tensor_or_sparse_tensor(value, dtype=None, name=None):
    """(ref: framework/sparse_tensor.py
    ``convert_to_tensor_or_sparse_tensor``)."""
    from .sparse_tensor import SparseTensor, SparseTensorValue

    if isinstance(value, SparseTensor):
        return value
    if isinstance(value, SparseTensorValue):
        return SparseTensor.from_value(value)
    return convert_to_tensor(value, dtype=dtype, name=name)


@contextlib.contextmanager
def op_scope(values, name, default_name=None):
    """Deprecated TF-1.0 scope (ref: ops.py ``op_scope``) — name_scope
    with the legacy argument order."""
    with name_scope(name, default_name, values) as scope:
        yield scope
