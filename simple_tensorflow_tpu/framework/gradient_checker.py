"""Numeric gradient checker (ref: tensorflow/python/ops/gradient_checker.py).

compute_gradient returns (jacobian_theoretical, jacobian_numeric) like the
reference; used across op tests to verify the vjp-derived symbolic grads.
"""

from __future__ import annotations

import numpy as np

from . import graph as ops_mod
from . import gradients as gradients_mod


def _theoretical_jacobian(x, y, x_data, dy_session, feed_dict):
    from ..ops import array_ops

    x_size = int(np.prod(x_data.shape)) if x_data.shape else 1
    y_shape = [int(d) for d in y.shape.as_list()]
    y_size = int(np.prod(y_shape)) if y_shape else 1
    jac = np.zeros((x_size, y_size), dtype=np.float64)
    dy = array_ops.placeholder(y.dtype, y.shape)
    (dx,) = gradients_mod.gradients(y, [x], grad_ys=[dy])
    for col in range(y_size):
        dy_val = np.zeros(y_shape, dtype=y.dtype.np_dtype)
        dy_val.flat[col] = 1.0
        fd = dict(feed_dict or {})
        fd[dy] = dy_val
        fd[x] = x_data
        dx_val = dy_session.run(dx, feed_dict=fd)
        jac[:, col] = np.asarray(dx_val, dtype=np.float64).ravel()
    return jac


def _numeric_jacobian(x, y, x_data, session, feed_dict, delta):
    x_size = int(np.prod(x_data.shape)) if x_data.shape else 1
    y_shape = [int(d) for d in y.shape.as_list()]
    y_size = int(np.prod(y_shape)) if y_shape else 1
    jac = np.zeros((x_size, y_size), dtype=np.float64)
    for row in range(x_size):
        x_pos = x_data.copy()
        x_neg = x_data.copy()
        x_pos.flat[row] += delta
        x_neg.flat[row] -= delta
        fd = dict(feed_dict or {})
        fd[x] = x_pos
        y_pos = np.asarray(session.run(y, feed_dict=fd), dtype=np.float64)
        fd[x] = x_neg
        y_neg = np.asarray(session.run(y, feed_dict=fd), dtype=np.float64)
        jac[row, :] = ((y_pos - y_neg) / (2 * delta)).ravel()
    return jac


def compute_gradient(x, x_shape, y, y_shape, x_init_value=None, delta=1e-3,
                     init_targets=None, extra_feed_dict=None):
    """(ref: gradient_checker.py:183 ``compute_gradient``)."""
    from ..client.session import get_default_session

    sess = get_default_session()
    if sess is None:
        raise ValueError("compute_gradient requires a default session")
    if x_init_value is None:
        rng = np.random.RandomState(12345)
        x_init_value = rng.randn(*x_shape).astype(x.dtype.np_dtype)
    theo = _theoretical_jacobian(x, y, x_init_value, sess, extra_feed_dict)
    num = _numeric_jacobian(x, y, x_init_value, sess, extra_feed_dict, delta)
    return theo, num


def compute_gradient_error(x, x_shape, y, y_shape, x_init_value=None,
                           delta=1e-3, init_targets=None,
                           extra_feed_dict=None):
    theo, num = compute_gradient(x, x_shape, y, y_shape, x_init_value, delta,
                                 init_targets, extra_feed_dict)
    return float(np.max(np.abs(theo - num)))
